"""Multi-process mesh chaos soak (VERDICT r5 item 3).

Eight `mesh_node` processes form a full mesh: every node is an echo
server AND a client of every peer over (a) shared-memory ICI links and
(b) an rr load-balanced channel whose membership comes from a file://
naming service. Mid-run the soak

  * SIGKILLs one node (host failure),
  * partitions another via the deterministic fault-injection layer
    (each node's /chaos portal page, drop=1.0 scoped per-peer),
  * heals the partition and restarts the killed node.

Asserted invariants:
  * every issued RPC terminates (sync callers + outstanding==0 at stop);
  * zero lost completions (issued == ok + failed per node and plane);
  * the circuit breaker isolated the flapping peer and the health check
    revived it (rpc_circuit_breaker_isolations / rpc_health_check_revives
    in /vars);
  * nodes shut down cleanly (exit 0 — Server::Join quiesces all sockets,
    so a leaked socket or hung fiber turns into a timeout/exit failure).
"""
import json
import os
import select
import socket
import subprocess
import time
import urllib.parse
import urllib.request

NUM_NODES = 8

# Soak-tuned robustness knobs: small breaker windows + fast health checks
# so isolation->revival cycles fit the soak's seconds-scale windows.
NODE_FLAGS = [
    "circuit_breaker_short_window_size=8",
    "circuit_breaker_short_window_error_percent=20",
    "circuit_breaker_long_window_size=64",
    "circuit_breaker_min_isolation_duration_ms=100",
    "circuit_breaker_max_isolation_duration_ms=1000",
    "ns_health_check_interval_ms=300",
]


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _http_get(port, path, timeout=5.0):
    url = "http://127.0.0.1:%d%s" % (port, path)
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode()


def _var(port, name):
    """Numeric /vars value; 0 when the var does not exist (yet)."""
    try:
        text = _http_get(port, "/vars/" + name)
    except Exception:
        return 0
    try:
        return int(text.rsplit(":", 1)[-1].strip())
    except ValueError:
        return 0


class Node:
    def __init__(self, binary, port, idx, peers_file, flags=NODE_FLAGS,
                 extra_args=()):
        self.port = port
        self.idx = idx
        self.proc = subprocess.Popen(
            [str(binary), "--port", str(port), "--id", str(idx), "--peers",
             str(peers_file)]
            + list(extra_args)
            + [arg for f in flags for arg in ("--flag", f)],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
        )
        self._buf = b""

    def send(self, line):
        self.proc.stdin.write(line.encode() + b"\n")
        self.proc.stdin.flush()

    def _readline(self, deadline):
        while b"\n" not in self._buf:
            remain = deadline - time.time()
            if remain <= 0:
                return None
            r, _, _ = select.select([self.proc.stdout], [], [], remain)
            if not r:
                return None
            chunk = os.read(self.proc.stdout.fileno(), 4096)
            if not chunk:
                return None
            self._buf += chunk
        line, self._buf = self._buf.split(b"\n", 1)
        return line.decode()

    def wait_ready(self, timeout=30.0):
        deadline = time.time() + timeout
        while True:
            line = self._readline(deadline)
            if line is None:
                return False
            if line.startswith("READY"):
                return True

    def stop_and_report(self, timeout=30.0):
        try:
            self.proc.stdin.write(b"stop\n")
            self.proc.stdin.flush()
        except (BrokenPipeError, OSError):
            # The node died mid-run — exactly what the soak exists to
            # catch; surface WHO and HOW instead of an opaque pipe error.
            raise AssertionError(
                "node %d (port %d) died before drain: exit=%s"
                % (self.idx, self.port, self.proc.poll()))
        deadline = time.time() + timeout
        while True:
            line = self._readline(deadline)
            if line is None:
                return None
            if line.startswith("REPORT "):
                return json.loads(line[len("REPORT "):])

    def shutdown(self, timeout=30.0):
        try:
            self.proc.stdin.close()
        except OSError:
            pass
        return self.proc.wait(timeout=timeout)

    def kill9(self):
        self.proc.kill()
        self.proc.wait()


def _chaos(port, **params):
    q = urllib.parse.urlencode(params)
    return _http_get(port, "/chaos?" + q)


def test_mesh_chaos_soak(cpp_build, tmp_path):
    binary = cpp_build / "mesh_node"
    assert binary.exists(), "mesh_node not built"
    ports = _free_ports(NUM_NODES)
    peers_file = tmp_path / "mesh_members"
    peers_file.write_text("".join("127.0.0.1:%d\n" % p for p in ports))

    nodes = [Node(binary, ports[i], i, peers_file) for i in range(NUM_NODES)]
    try:
        for n in nodes:
            assert n.wait_ready(), "node %d never became ready" % n.idx

        time.sleep(3.0)  # healthy warm-up traffic

        # --- inject: kill node 3, partition node 5 --------------------
        kill_idx, part_idx = 3, 5
        nodes[kill_idx].kill9()

        part_ep = "127.0.0.1:%d" % ports[part_idx]
        others = ",".join(
            "127.0.0.1:%d" % p for i, p in enumerate(ports)
            if i not in (kill_idx, part_idx))
        # Bidirectional partition through per-peer scoping: node 5 drops
        # its client-side traffic to everyone; everyone drops theirs to
        # node 5. Control-plane HTTP (ephemeral remote ports) and the
        # raw health-check probes are unaffected by design — so the
        # breaker flaps isolate->revive, exactly the cycle under test.
        _chaos(ports[part_idx], enable=1, seed=1000 + part_idx,
               plan="drop=1.0", peers=others)
        for i, p in enumerate(ports):
            if i in (kill_idx, part_idx):
                continue
            _chaos(p, enable=1, seed=1000 + i, plan="drop=1.0",
                   peers=part_ep)

        # Wait (bounded) for the breaker to isolate and the health check
        # to revive somewhere in the mesh — the partitioned node's own
        # calls all time out, so its breaker trips within a few call
        # timeouts; polling beats a fixed sleep on a loaded 1-core host.
        alive = [i for i in range(NUM_NODES) if i != kill_idx]
        isolations = revives = 0
        deadline = time.time() + 25.0
        while time.time() < deadline:
            isolations = sum(
                _var(ports[i], "rpc_circuit_breaker_isolations")
                for i in alive)
            revives = sum(_var(ports[i], "rpc_health_check_revives")
                          for i in alive)
            if isolations >= 1 and revives >= 1:
                break
            time.sleep(1.0)
        assert isolations >= 1, "circuit breaker never isolated the peer"
        assert revives >= 1, "health check never revived an isolated peer"

        # --- heal: chaos off everywhere, restart the killed node ------
        for i in alive:
            _chaos(ports[i], enable=0)
        nodes[kill_idx] = Node(binary, ports[kill_idx], kill_idx, peers_file)
        assert nodes[kill_idx].wait_ready()

        time.sleep(6.0)  # mesh links re-establish; traffic recovers

        # --- drain + invariants ---------------------------------------
        reports = []
        for n in nodes:
            rep = n.stop_and_report()
            assert rep is not None, "node %d produced no report" % n.idx
            reports.append(rep)

        total_ok = 0
        for rep in reports:
            # Zero lost completions: everything issued terminated.
            assert rep["outstanding"] == 0, rep
            assert rep["lb_issued"] == rep["lb_ok"] + rep["lb_failed"], rep
            assert rep["shm_issued"] == rep["shm_ok"] + rep["shm_failed"], rep
            total_ok += rep["lb_ok"] + rep["shm_ok"]
        # The mesh kept serving through kill + partition + heal.
        assert total_ok > 100, reports
        # The restarted node rejoined and did useful work.
        restarted = reports[kill_idx]
        assert restarted["lb_ok"] + restarted["shm_ok"] > 0, restarted
        # Peers re-established at least one shm link to the restarted
        # node (its death failed their pinned sockets).
        assert sum(r["reconnects"] for r in reports) >= 1, reports

        # Clean teardown: exit 0 requires Server::Join to quiesce every
        # socket — leaks show up as a hang (timeout) or non-zero exit.
        for n in nodes:
            assert n.shutdown() == 0, "node %d unclean exit" % n.idx
    finally:
        for n in nodes:
            try:
                n.proc.kill()
            except OSError:
                pass


def test_deadline_budget_soak(cpp_build, tmp_path):
    """Delay-heavy phase: deadline propagation + retry budgets (ISSUE 2).

    Three nodes; mid-run every handler starts sleeping 50 ms while a
    stale-traffic fiber issues budget-starved calls (1 ms / 30 ms
    deadlines, both below the learned ~50 ms service time -> shed by the
    TimeoutConcurrencyLimiter at admission), a raw probe fiber sends
    handcrafted frames stamped timeout_ms=0 (the wire shape of a client
    that already gave up -> expired-on-arrival shed), and one node gets
    reset-chaos on its client side to provoke retries against the
    configured retry budget.

    Asserted:
      * expired requests are SHED, not executed (rpc_server_expired_requests
        / rpc_server_shed_requests grow; stale executions stay a minority);
      * total re-issues stay within the configured retry budget
        (burst + ratio * successes, per channel) and
        rpc_retry_budget_exhausted is observable;
      * zero lost completions on every plane, clean exit 0.
    """
    num = 3
    budget_tokens = 20
    budget_ratio = 0.1
    binary = cpp_build / "mesh_node"
    assert binary.exists(), "mesh_node not built"
    ports = _free_ports(num)
    peers_file = tmp_path / "mesh_members"
    peers_file.write_text("".join("127.0.0.1:%d\n" % p for p in ports))

    flags = NODE_FLAGS + [
        "rpc_retry_budget_tokens=%d" % budget_tokens,
        "rpc_retry_budget_ratio=%g" % budget_ratio,
        # Every stale call fails BY DESIGN (that's the point of the
        # phase); with the soak-tightened breaker windows those errors
        # would isolate healthy servers and starve the shed counters.
        # Breaker isolate/revive cycles are the kill+partition soak's
        # subject, not this one's.
        "enable_circuit_breaker=false",
    ]
    nodes = [
        Node(binary, ports[i], i, peers_file, flags=flags,
             extra_args=("--timeout_cl_ms", "800"))
        for i in range(num)
    ]
    try:
        for n in nodes:
            assert n.wait_ready(), "node %d never became ready" % n.idx

        time.sleep(2.0)  # healthy traffic; EMA learns the fast latency

        # --- delay-heavy phase -----------------------------------------
        for n in nodes:
            n.send("delay 50 30")
        # Reset-chaos on node 2's client side: connection-level failures
        # are retryable, so its channels retry until the budget is dry.
        others = ",".join(
            "127.0.0.1:%d" % p for i, p in enumerate(ports) if i != 2)
        _chaos(ports[2], enable=1, seed=4242, plan="reset=0.3",
               peers=others)

        # Shedding and budget exhaustion become observable within the
        # phase (bounded poll beats a fixed sleep on a loaded host).
        deadline = time.time() + 30.0
        expired = shed = exhausted = 0
        while time.time() < deadline:
            expired = sum(
                _var(p, "rpc_server_expired_requests") for p in ports)
            shed = sum(_var(p, "rpc_server_shed_requests") for p in ports)
            exhausted = sum(
                _var(p, "rpc_retry_budget_exhausted") for p in ports)
            if expired >= 5 and shed >= 5 and exhausted >= 1:
                break
            time.sleep(1.0)
        assert expired >= 5, "expired-on-arrival requests were not shed"
        assert shed >= 5, "budget-below-service-time requests were not shed"
        assert exhausted >= 1, "retry budget never exhausted under chaos"

        # --- heal + drain ----------------------------------------------
        _chaos(ports[2], enable=0)
        for n in nodes:
            n.send("delay 0 0")
        time.sleep(1.5)

        # Read per-process re-issue counters BEFORE stopping traffic
        # is unnecessary — the processes (and /vars) stay alive until
        # shutdown; reports first, then vars.
        reports = []
        for n in nodes:
            rep = n.stop_and_report()
            assert rep is not None, "node %d produced no report" % n.idx
            reports.append(rep)

        for i, rep in enumerate(reports):
            # Zero lost completions on every plane, stale included.
            assert rep["outstanding"] == 0, rep
            assert rep["lb_issued"] == rep["lb_ok"] + rep["lb_failed"], rep
            assert rep["shm_issued"] == rep["shm_ok"] + rep["shm_failed"], rep
            assert rep["stale_issued"] == (
                rep["stale_ok"] + rep["stale_failed"]), rep
            # The server dropped (expired/shed) most stale calls instead
            # of executing work nobody reads.
            assert rep["stale_issued"] > 20, rep
            assert rep["stale_executed"] <= rep["stale_issued"] // 2, rep
            # Re-issues bounded by the configured budget: one LB channel
            # + (num-1) shm channels per node, each reconnect is a fresh
            # channel (fresh burst), plus ratio * successes earned back.
            ok = rep["lb_ok"] + rep["shm_ok"] + rep["stale_ok"]
            channels = 1 + (num - 1) + rep["reconnects"]
            bound = channels * budget_tokens + budget_ratio * ok + 50
            reissues = (_var(ports[i], "rpc_client_retries")
                        + _var(ports[i], "rpc_client_backup_requests"))
            assert reissues <= bound, (
                "node %d re-issued %d times, budget bound %.0f (%s)"
                % (i, reissues, bound, rep))

        for n in nodes:
            assert n.shutdown() == 0, "node %d unclean exit" % n.idx
    finally:
        for n in nodes:
            try:
                n.proc.kill()
            except OSError:
                pass
