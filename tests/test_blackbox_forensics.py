"""Crash black-box forensics (ISSUE 19): chaos-crash one node of a
4-node verbs mesh and reconstruct its last moments across nodes.

Two pods of two `mesh_node` processes run --verbs_traffic with
--blackbox dump paths (the same dcn-emulated topology as
test_verbs_soak, so cross-pod verb posts traverse the emulated wire
seam and the GRANTOR records VERB_WIRE events a merge can pair with the
initiator's VERB_POST). Mid-traffic, one node gets a deterministic
`crash=1` chaos plan scoped to a bogus peer filter — only the
peer-filter-bypassing verb seams consume decisions, so the node's very
next verb post records CHAOS_INJECT and dies on a genuine SIGSEGV.

Asserted:
  * the fatal-signal path left a parseable TFRBOX1 black box (and the
    process exit status still reports SIGSEGV — the handler re-raises);
  * tools/blackbox_merge.py merges the dead node's binary dump with the
    survivors' live /blackbox?format=json rings into ONE timeline in
    which the dying node's final verb posts appear WITH a surviving
    peer's matching VERB_WIRE event (same wr id, wire after post);
  * the chaos injection that killed the node is in the timeline,
    stamped with the plan seed and the crash action kind;
  * survivors keep making verb progress and shut down cleanly.
"""
import json
import signal
import subprocess
import sys
import time
from pathlib import Path

from test_chaos_soak import Node, _chaos, _free_ports, _http_get
from test_pod_partition_soak import _report
from test_verbs_soak import VERB_FLAGS, _wait_verbs_ok

POD_SIZE = 2
NUM_NODES = 2 * POD_SIZE
MERGE_TOOL = Path(__file__).resolve().parent.parent / "tools" / \
    "blackbox_merge.py"


def test_blackbox_forensics(cpp_build, tmp_path):
    binary = cpp_build / "mesh_node"
    assert binary.exists(), "mesh_node not built"
    ports = _free_ports(NUM_NODES)
    pod_a, pod_b = ports[:POD_SIZE], ports[POD_SIZE:]

    naming = tmp_path / "naming"
    naming.write_text(
        "".join("127.0.0.1:%d zone=A\n" % p for p in pod_a)
        + "".join("127.0.0.1:%d zone=B\n" % p for p in pod_b))
    dcn_a = tmp_path / "dcn_a"
    dcn_a.write_text("".join("127.0.0.1:%d zone=B\n" % p for p in pod_b))
    dcn_b = tmp_path / "dcn_b"
    dcn_b.write_text("".join("127.0.0.1:%d zone=A\n" % p for p in pod_a))

    def _bb(i):
        return tmp_path / ("blackbox_%d.bin" % i)

    def _node(i):
        in_a = i < POD_SIZE
        return Node(binary, ports[i], i, naming, flags=VERB_FLAGS,
                    extra_args=("--zone", "A" if in_a else "B",
                                "--dcn_peers",
                                str(dcn_a if in_a else dcn_b),
                                "--verbs_traffic",
                                "--blackbox", str(_bb(i))))

    nodes = [_node(i) for i in range(NUM_NODES)]
    try:
        for n in nodes:
            assert n.wait_ready(), "node %d never became ready" % n.idx

        # Warm-up: verb traffic on both data paths, plus enough LB RPC
        # round trips for the merge tool's envelope clock normalization.
        ok0 = _wait_verbs_ok(nodes, 10)
        assert all(v >= 10 for v in ok0.values()), \
            "verb traffic never started: %s" % ok0

        # --- chaos-crash node 0 ---------------------------------------
        # Node 0 (pod A) initiates cross-pod verbs against pod B's
        # windows over the dcn wire seam, so its final posts have
        # grantor-side VERB_WIRE twins on the survivors.
        victim = 0
        try:
            _chaos(ports[victim], enable=1, seed=20260807, plan="crash=1",
                   peers="9.9.9.9:1")
        except Exception:
            pass  # the crash can beat the HTTP response off the box
        rc = nodes[victim].proc.wait(timeout=30.0)
        assert rc == -signal.SIGSEGV, \
            "victim exit %r is not the re-raised SIGSEGV" % rc

        # --- the signal path left a black box -------------------------
        dump = _bb(victim)
        assert dump.exists(), "crash handler wrote no dump"
        blob = dump.read_bytes()
        assert blob[:8] == b"TFRBOX1\0", blob[:8]
        assert len(blob) > 136, "dump is header-only"

        # Survivors: snapshot their rings live over /blackbox.
        survivors = [n for n in nodes if n.idx != victim]
        for n in survivors:
            _bb(n.idx).write_text(
                _http_get(ports[n.idx], "/blackbox?format=json",
                          timeout=10.0))
        # And the metrics families are live (lint checks 0-valued
        # exposure; here the rings demonstrably recorded).
        metrics = _http_get(ports[survivors[0].idx], "/metrics")
        for fam in ("rpc_blackbox_events", "rpc_blackbox_dropped",
                    "rpc_blackbox_ring_highwater", "rpc_flight_dump_count"):
            assert fam in metrics, "missing %s in /metrics" % fam
        line = [ln for ln in metrics.splitlines()
                if ln.startswith("rpc_blackbox_events")][0]
        assert float(line.split()[-1]) > 0, line

        # --- one merged causal timeline -------------------------------
        out = subprocess.run(
            [sys.executable, str(MERGE_TOOL), "--json"]
            + [str(_bb(i)) for i in range(NUM_NODES)],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        doc = json.loads(out.stdout)
        assert len(doc["nodes"]) == NUM_NODES, doc["nodes"]
        victim_name = "node%d:%d" % (victim, ports[victim])
        by_name = {n["name"]: n for n in doc["nodes"]}
        assert victim_name in by_name, by_name.keys()
        assert by_name[victim_name]["events"] > 0

        events = doc["events"]
        v_posts = [e for e in events
                   if e["node"] == victim_name and e["kind"] == "VERB_POST"]
        assert v_posts, "dying node's verb posts missing from timeline"
        peer_wires = {}
        for e in events:
            if e["kind"] == "VERB_WIRE" and e["node"] != victim_name:
                peer_wires.setdefault(e["a"], []).append(e)
        # The dying node's final posts must pair with a surviving peer's
        # wire event: same wr id (pid-salted, so unique across nodes),
        # wire AFTER post once clocks normalize.
        matched = None
        for post in sorted(v_posts, key=lambda e: -e["t_us"]):
            for wire in peer_wires.get(post["a"], ()):
                if wire["t_us"] > post["t_us"]:
                    matched = (post, wire)
                    break
            if matched is not None:
                break
        assert matched is not None, \
            "no (VERB_POST, peer VERB_WIRE) pair for the dying node"

        # The injection that killed it is on the record, crash-stamped
        # with the plan seed (b packs seed_lo32<<32 | op<<8 | kind).
        chaos = [e for e in events
                 if e["node"] == victim_name and e["kind"] == "CHAOS_INJECT"]
        assert chaos, "CHAOS_INJECT missing from the dying node's ring"
        last = chaos[-1]
        assert last["b"] & 0xff == 9, last     # FaultAction::kCrash
        assert last["b"] >> 32 == 20260807 & 0xFFFFFFFF, last

        # --- survivors are healthy ------------------------------------
        base = {n.idx: _report(n)["verbs_ok"] for n in survivors}
        ok1 = _wait_verbs_ok(survivors, 5, timeout=40.0, baseline=base)
        assert all(ok1[n.idx] - base[n.idx] >= 5 for n in survivors), \
            "verb progress stopped after the crash: %s" % ok1
        for n in survivors:
            rep = n.stop_and_report(timeout=60.0)
            assert rep is not None
            assert rep["outstanding"] == 0, rep
            assert n.shutdown() == 0, "node %d unclean exit" % n.idx
    finally:
        for n in nodes:
            try:
                n.proc.kill()
            except OSError:
                pass
