"""Grey-failure immunity soak (ISSUE 20 capstone).

Six `mesh_node` processes form the usual full mesh; `rpc_press` drives
them through a comma-list --server, which makes the GENERATOR the LB
client: the round-robin channel runs under the outlier-ejection wrapper
inside the press process, so detection, ejection, reinstatement probes
and the slow-start ramp all happen where the test can read them
(--json counters + --backend_csv per-interval per-backend rows).

One backend then turns GREY — `slow_node=1:80,error_rate=0.05` at the
handler seam, so connect-probe health checks still pass — and the soak
asserts the full immune response:

  phase A  baseline: all healthy -> unloaded gold p99;
  phase B1 detection + forensics: the grey node is ejected within the
           detection interval (its per-interval pick share collapses to
           probe noise while peers keep serving), and the EJECT decision
           is forensically reconstructable: a blackbox_merge timeline
           over the press dump + the grey node's live rings shows the
           OUTLIER_EJECT event with its reason code between the grey
           node's last served RPC and the press's next re-routed issue;
  phase B2 while-ejected: gold p99 recovers to <= 2x baseline, with
           ZERO lost completions and ZERO retry-budget exhaustion (the
           ejection re-route is budget-free) while the node stays
           ejected (reinstatement probes keep failing against the
           still-slow backend);
  phase C  heal mid-run: probes pass, the node is reinstated through
           the ramp, and its pick share returns to within 10% of its
           peers by the tail intervals;
  phase D  median-relative proof: ALL nodes slowed uniformly -> the
           k*MAD-vs-live-median detector ejects NOBODY.
"""
import json
import subprocess
import sys
import time
from pathlib import Path

from test_chaos_soak import NODE_FLAGS, Node, _chaos, _free_ports, _http_get

NUM_NODES = 6
MERGE_TOOL = Path(__file__).resolve().parent.parent / "tools" / \
    "blackbox_merge.py"

# gold : bronze = 1 : 3 by weight; gold rides priority 7.
TENANTS = "--tenants=gold:1:7,bronze:3:1"


def _parse_json(stdout):
    for line in reversed(stdout.splitlines()):
        if line.startswith("{"):
            return json.loads(line)
    raise AssertionError("no json line from rpc_press:\n" + stdout)


def _press(press_bin, server_list, args, timeout=120):
    out = subprocess.run(
        [str(press_bin), "--server=" + server_list, TENANTS,
         "--payload=128", "--callers=12", "--json"] + args,
        capture_output=True, timeout=timeout, text=True)
    assert out.returncode == 0, out.stderr
    return _parse_json(out.stdout)


def _backend_rows(path):
    """[(elapsed_s, backend, picks_delta, errors_delta, p99_us)]"""
    rows = []
    for line in path.read_text().splitlines()[1:]:
        c = line.split(",")
        rows.append((int(c[0]), c[1], int(c[2]), int(c[3]), int(c[4])))
    return rows


def test_grey_failure_soak(cpp_build, tmp_path):
    node_bin = cpp_build / "mesh_node"
    press_bin = cpp_build / "rpc_press"
    assert node_bin.exists(), "mesh_node not built"
    assert press_bin.exists(), "rpc_press not built"
    ports = _free_ports(NUM_NODES)
    peers_file = tmp_path / "mesh_members"
    peers_file.write_text("".join("127.0.0.1:%d\n" % p for p in ports))
    server_list = ",".join("127.0.0.1:%d" % p for p in ports)
    grey_idx = 2
    grey_port = ports[grey_idx]
    grey_ep = "127.0.0.1:%d" % grey_port

    # Big flight rings: the forensics phase snapshots the grey node's
    # live rings AFTER the 6 s detection run — its pre-ejection RPC
    # events must still be resident (4096 slots/thread wrap in ~2 s
    # under combined press + mesh background traffic, and retention is
    # per-THREAD: work-stealing can funnel most events through one hot
    # ring, so size for the worst single ring, not the average).
    nodes = [Node(node_bin, ports[i], i, peers_file,
                  flags=NODE_FLAGS + ["flight_recorder_ring=262144"])
             for i in range(NUM_NODES)]
    try:
        for n in nodes:
            assert n.wait_ready(), "node %d never became ready" % n.idx
        time.sleep(2.0)  # mesh links up, background traffic flowing

        # --- phase A: healthy baseline --------------------------------
        base = _press(press_bin, server_list,
                      ["--qps=400", "--duration_s=4"])
        base_gold_p99 = base["press_tenants"]["gold"]["p99_us"]
        assert base["press_tenants"]["gold"]["sent"] > 200, base
        assert base["press_outlier_ejections"] == 0, base
        # All six backends took picks on the healthy mesh.
        assert len(base["press_backends"]) == NUM_NODES, base

        # --- node 2 turns GREY (handler seam: health probes still pass)
        _chaos(grey_port, enable=1, seed=20260807,
               plan="slow_node=1:80,error_rate=0.05")

        # --- phase B1: detection + forensics --------------------------
        bcsv = tmp_path / "backends_b1.csv"
        press_bb = tmp_path / "press_bb.bin"
        # The enlarged flight ring keeps the t~1s EJECT event resident
        # until the end-of-run dump (default 4096 slots/thread wrap
        # under a 6 s run's RPC + scheduler events).
        b1 = _press(press_bin, server_list,
                    ["--qps=400", "--duration_s=6",
                     "--backend_csv=" + str(bcsv),
                     "--blackbox=" + str(press_bb),
                     "--flag=flight_recorder_ring=65536"])
        assert b1["press_outlier_ejections"] >= 1, b1
        assert grey_ep in b1["press_backends"], b1

        # Ejected within the detection interval: some early interval has
        # the grey backend at probe-noise picks while peers keep taking
        # real traffic — and it STAYS there for the rest of the run.
        rows = _backend_rows(bcsv)
        assert rows, "backend_csv is empty"
        ejected_at = None
        for t in sorted({r[0] for r in rows}):
            grey = sum(r[2] for r in rows if r[0] == t and r[1] == grey_ep)
            peers = [r[2] for r in rows
                     if r[0] == t and r[1] != grey_ep]
            if grey <= 2 and peers and max(peers) >= 10:
                ejected_at = t
                break
        assert ejected_at is not None and ejected_at <= 5, \
            ("never ejected within the detection interval", rows)
        late_grey = [r[2] for r in rows
                     if r[1] == grey_ep and r[0] > ejected_at]
        assert all(p <= 5 for p in late_grey), \
            ("grey node kept taking real traffic after ejection",
             late_grey)

        # Forensics: merge the press's binary dump with the grey node's
        # live rings into one causal timeline. The EJECT event names the
        # grey backend WITH a reason code, sandwiched between the grey
        # node's last served RPC and the press's next re-routed issue.
        grey_bb = tmp_path / "grey_bb.json"
        grey_bb.write_text(
            _http_get(grey_port, "/blackbox?format=json", timeout=10.0))
        grey_name = json.loads(grey_bb.read_text())["node"]
        out = subprocess.run(
            [sys.executable, str(MERGE_TOOL), "--json", str(press_bb),
             str(grey_bb)],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        events = json.loads(out.stdout)["events"]

        def _eject_ep(e):
            ip = (e["a"] >> 16) & 0xFFFFFFFF
            return "%d.%d.%d.%d:%d" % (
                (ip >> 24) & 0xFF, (ip >> 16) & 0xFF, (ip >> 8) & 0xFF,
                ip & 0xFF, e["a"] & 0xFFFF)

        # The merged timeline can hold OTHER ejections too: every node
        # runs the outlier tier on its own mesh channels, and the grey
        # node's rings (which may retain bring-up history) record ITS
        # conn-refused ejections of still-starting peers. The forensic
        # anchor is specifically the PRESS's ejection OF the grey
        # backend — select it by decoded endpoint + emitting node.
        ejects = [e for e in events
                  if e["kind"] == "OUTLIER_EJECT"
                  and e["node"] != grey_name and _eject_ep(e) == grey_ep]
        assert ejects, \
            "press OUTLIER_EJECT of the grey backend missing from the " \
            "merged timeline"
        ej = ejects[0]
        reason = ej["b"] >> 56
        assert reason in (1, 2), ej  # consecutive_errors / latency_outlier
        served_before = [
            e for e in events
            if e["node"] == grey_name and e["t_us"] < ej["t_us"]
            and e["kind"] in ("RPC_DISPATCH", "RPC_HANDLER_IN",
                              "RPC_HANDLER_OUT", "RPC_WRITE")]
        assert served_before, \
            "no grey-node RPC activity before the ejection in the timeline"
        issued_after = [
            e for e in events
            if e["node"] != grey_name and e["kind"] == "RPC_ISSUE"
            and e["t_us"] > ej["t_us"]]
        assert issued_after, \
            "no re-routed client issue after the ejection in the timeline"

        # --- phase B2: service quality WHILE ejected ------------------
        # Long enough that the final windowed percentiles (10 s) cover
        # only post-ejection traffic; the still-grey backend fails every
        # reinstatement probe, so it is STILL ejected at exit.
        b2 = _press(press_bin, server_list,
                    ["--qps=400", "--duration_s=14"])
        gold = b2["press_tenants"]["gold"]
        bronze = b2["press_tenants"]["bronze"]
        assert b2["press_outlier_ejections"] >= 1, b2
        assert b2["press_outlier_ejected_now"] == 1, b2
        # Gold p99 recovered to <= 2x its unloaded baseline (noise floor
        # for the shared CI host; the grey node's 80 ms handler delay
        # sits far above the bound, so routing THROUGH it would fail).
        bound = 2 * max(base_gold_p99, 25000)
        assert gold["p99_us"] <= bound, (gold["p99_us"], base_gold_p99)
        # Zero lost completions: the synthetic grey errors are retriable
        # and the ejection re-route is budget-free, so every issued call
        # terminated successfully.
        assert gold["failed"] == 0, b2
        assert gold["failed"] + bronze["failed"] <= 2, b2
        assert b2["press_retry_budget_exhausted"] == 0, b2

        # --- phase C: heal mid-run -> reinstatement + ramp ------------
        bcsv_c = tmp_path / "backends_c.csv"
        proc = subprocess.Popen(
            [str(press_bin), "--server=" + server_list, TENANTS,
             "--payload=128", "--callers=12", "--json", "--qps=400",
             "--duration_s=17", "--backend_csv=" + str(bcsv_c)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        # The fresh tracker re-ejects the still-grey node first (B1
        # proved detection lands well inside this window), THEN the
        # chaos heals so the next reinstatement probe passes.
        time.sleep(5.5)
        _chaos(grey_port, enable=0)
        stdout, stderr = proc.communicate(timeout=120)
        assert proc.returncode == 0, stderr
        c = _parse_json(stdout)
        assert c["press_outlier_ejections"] >= 1, c
        assert c["press_outlier_reinstatements"] >= 1, c
        assert c["press_outlier_ejected_now"] == 0, c
        # Pick share back within 10% of peers over the tail intervals
        # (past the slow-start ramp).
        rows = _backend_rows(bcsv_c)
        tail_from = max(r[0] for r in rows) - 2
        totals = {}
        for t, backend, picks, _errors, _p99 in rows:
            if t >= tail_from:
                totals[backend] = totals.get(backend, 0) + picks
        assert len(totals) == NUM_NODES, totals
        grey_picks = totals[grey_ep]
        peer_mean = (sum(totals.values()) - grey_picks) / (NUM_NODES - 1)
        assert peer_mean > 50, totals  # the tail actually carried load
        assert abs(grey_picks - peer_mean) <= 0.10 * peer_mean, \
            ("reinstated node's pick share did not recover", totals)

        # --- phase D: uniform slowness ejects NOBODY ------------------
        # Every backend slowed identically: the latency detector is
        # median-relative (k*MAD over the live set), so a uniformly slow
        # mesh has no outlier to eject.
        for p in ports:
            _chaos(p, enable=1, seed=7000 + p, plan="slow_node=1:40")
        d = _press(press_bin, server_list,
                   ["--qps=150", "--duration_s=8"], timeout=150)
        assert d["press_tenants"]["gold"]["sent"] > 100, d
        assert d["press_outlier_ejections"] == 0, d
        assert d["press_outlier_ejected_now"] == 0, d
        for p in ports:
            _chaos(p, enable=0)

        # --- drain + clean exit ---------------------------------------
        for n in nodes:
            rep = n.stop_and_report(timeout=60.0)
            assert rep is not None, "node %d produced no report" % n.idx
            assert rep["outstanding"] == 0, rep
            assert rep["lb_issued"] == rep["lb_ok"] + rep["lb_failed"], rep
            assert rep["shm_issued"] == rep["shm_ok"] + rep["shm_failed"], \
                rep
        for n in nodes:
            assert n.shutdown() == 0, "node %d unclean exit" % n.idx
    finally:
        for n in nodes:
            try:
                n.proc.kill()
            except OSError:
                pass
