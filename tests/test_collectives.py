"""Collective lowerings of the combo-channel family on a virtual 8-device
CPU mesh (conftest forces JAX_PLATFORMS=cpu + 8 host devices).

The C++ combo channels (cpp/trpc/combo_channels.h) fan calls out over
sockets; on a TPU mesh the same patterns lower to XLA collectives
(SURVEY §2.13): ParallelChannel fan-out == AllGather + ReduceScatter,
PartitionChannel sharding == sharded computation + psum merge.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest


@pytest.fixture(scope="module")
def mesh():
    # Ask for the cpu backend explicitly: the environment may pin the
    # default platform to a single real accelerator, while this suite is
    # specified against the 8-device virtual host platform.
    devices = jax.devices("cpu")
    assert len(devices) >= 8, "conftest should provide 8 virtual devices"
    return jax.sharding.Mesh(devices[:8], ("peers",))


def test_parallel_echo_roundtrip(mesh):
    from brpc_tpu.parallel.collective_echo import make_parallel_echo_step

    step = make_parallel_echo_step(mesh)
    payloads = jnp.arange(8 * 128, dtype=jnp.uint32).reshape(8, 128)
    out = step(payloads)
    # Fan-out + designated-responder + merge is an exact echo.
    np.testing.assert_array_equal(np.asarray(out), np.asarray(payloads))


def test_parallel_echo_is_exact_for_large_words(mesh):
    from brpc_tpu.parallel.collective_echo import make_parallel_echo_step

    step = make_parallel_echo_step(mesh)
    # Max-value words: a sum-based merge would overflow; the
    # designated-responder scheme must keep bits exact.
    payloads = jnp.full((8, 64), 0xFFFFFFFF, dtype=jnp.uint32)
    out = step(payloads)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(payloads))


def test_partition_echo_shards_and_checksums(mesh):
    from brpc_tpu.parallel.collective_echo import (
        _adler_frame_checksum,
        make_partition_echo_step,
    )

    step = make_partition_echo_step(mesh)
    payloads = jnp.arange(8 * 96, dtype=jnp.uint32).reshape(8, 96) * jnp.uint32(
        2654435761
    )
    check, echoed, total = step(payloads)
    np.testing.assert_array_equal(np.asarray(echoed), np.asarray(payloads))
    expected = _adler_frame_checksum(payloads)
    np.testing.assert_array_equal(np.asarray(check), np.asarray(expected))
    want_total = np.sum(np.asarray(expected), dtype=np.uint32)
    assert np.uint32(np.asarray(total)) == want_total


def test_partition_step_compiles_with_collective(mesh):
    from brpc_tpu.parallel.collective_echo import make_partition_echo_step

    step = make_partition_echo_step(mesh)
    payloads = jnp.ones((8, 32), dtype=jnp.uint32)
    compiled = step.lower(payloads).compile()
    hlo = compiled.as_text()
    # The psum merge must survive into the compiled module (the collective
    # rides ICI on hardware).
    assert "all-reduce" in hlo or "all_reduce" in hlo
