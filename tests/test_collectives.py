"""Collective lowerings of the combo-channel family on a virtual 8-device
CPU mesh (conftest forces JAX_PLATFORMS=cpu + 8 host devices).

The C++ combo channels (cpp/trpc/combo_channels.h) fan calls out over
sockets; on a TPU mesh the same patterns lower to XLA collectives
(SURVEY §2.13): ParallelChannel fan-out == AllGather + ReduceScatter,
PartitionChannel sharding == sharded computation + psum merge.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest


@pytest.fixture(scope="module")
def mesh():
    # Ask for the cpu backend explicitly: the environment may pin the
    # default platform to a single real accelerator, while this suite is
    # specified against the 8-device virtual host platform.
    devices = jax.devices("cpu")
    assert len(devices) >= 8, "conftest should provide 8 virtual devices"
    return jax.sharding.Mesh(devices[:8], ("peers",))


def test_parallel_echo_roundtrip(mesh):
    from brpc_tpu.parallel.collective_echo import make_parallel_echo_step

    step = make_parallel_echo_step(mesh)
    payloads = jnp.arange(8 * 128, dtype=jnp.uint32).reshape(8, 128)
    out = step(payloads)
    # Fan-out + designated-responder + merge is an exact echo.
    np.testing.assert_array_equal(np.asarray(out), np.asarray(payloads))


def test_parallel_echo_is_exact_for_large_words(mesh):
    from brpc_tpu.parallel.collective_echo import make_parallel_echo_step

    step = make_parallel_echo_step(mesh)
    # Max-value words: a sum-based merge would overflow; the
    # designated-responder scheme must keep bits exact.
    payloads = jnp.full((8, 64), 0xFFFFFFFF, dtype=jnp.uint32)
    out = step(payloads)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(payloads))


def test_partition_echo_shards_and_checksums(mesh):
    from brpc_tpu.parallel.collective_echo import (
        _adler_frame_checksum,
        make_partition_echo_step,
    )

    step = make_partition_echo_step(mesh)
    payloads = jnp.arange(8 * 96, dtype=jnp.uint32).reshape(8, 96) * jnp.uint32(
        2654435761
    )
    check, echoed, total = step(payloads)
    np.testing.assert_array_equal(np.asarray(echoed), np.asarray(payloads))
    expected = _adler_frame_checksum(payloads)
    np.testing.assert_array_equal(np.asarray(check), np.asarray(expected))
    want_total = np.sum(np.asarray(expected), dtype=np.uint32)
    assert np.uint32(np.asarray(total)) == want_total


def test_partition_step_compiles_with_collective(mesh):
    from brpc_tpu.parallel.collective_echo import make_partition_echo_step

    step = make_partition_echo_step(mesh)
    payloads = jnp.ones((8, 32), dtype=jnp.uint32)
    compiled = step.lower(payloads).compile()
    hlo = compiled.as_text()
    # The psum merge must survive into the compiled module (the collective
    # rides ICI on hardware).
    assert "all-reduce" in hlo or "all_reduce" in hlo


# ---------------- ISSUE 13: mesh-collective lowerings ----------------

def _fill_deterministic(seq, key, n):
    """numpy twin of CollectiveEngine::FillDeterministic (uint32 wrap):
    word(i) = 0x9E3779B1*seq + 0x85EBCA77*key + 0xC2B2AE35*i."""
    i = np.arange(n, dtype=np.uint64)
    base = (0x9E3779B1 * (seq & 0xFFFFFFFF) +
            0x85EBCA77 * (key & 0xFFFFFFFF)) & 0xFFFFFFFF
    return ((base + 0xC2B2AE35 * i) & 0xFFFFFFFF).astype(np.uint32)


def _coll_checksum(words):
    """numpy twin of CollectiveEngine::Checksum == the adler frame
    checksum of collective_echo (uint32 WRAPAROUND cumsum, mod 65521)."""
    w = np.asarray(words, dtype=np.uint32)
    lo = w & np.uint32(0xFFFF)
    hi = w >> np.uint32(16)
    halves = np.stack([lo, hi], axis=-1).reshape(-1).astype(np.uint64)
    s1 = np.cumsum(halves) & 0xFFFFFFFF
    a = int(s1[-1]) % 65521
    b = int(np.sum(s1 % 65521)) % 65521
    return (b << 16) | a


def test_coll_checksum_matches_cpp_golden():
    # Locked against Collective.ChecksumAndFillAreStable in
    # cpp/tests/tcollective_test.cc — one formula, two languages.
    assert _coll_checksum([1, 2, 3]) == 1310726
    w = _fill_deterministic(7, 9001, 2)
    assert int(w[0]) == (0x9E3779B1 * 7 + 0x85EBCA77 * 9001) % (1 << 32)
    assert int(w[1]) == (int(w[0]) + 0xC2B2AE35) % (1 << 32)


def test_allreduce_lowering_is_wraparound_sum(mesh):
    from brpc_tpu.parallel.collective_echo import make_allreduce_step

    step = make_allreduce_step(mesh)
    x = jnp.arange(8 * 64, dtype=jnp.uint32).reshape(8, 64) * jnp.uint32(
        2654435761
    )
    out = step(x)
    want = np.tile(np.asarray(x).sum(axis=0, dtype=np.uint32), (8, 1))
    np.testing.assert_array_equal(np.asarray(out), want)


def test_allgather_lowering_concatenates_rank_order(mesh):
    from brpc_tpu.parallel.collective_echo import make_allgather_step

    step = make_allgather_step(mesh)
    x = jnp.arange(8 * 32, dtype=jnp.uint32).reshape(8, 32)
    out = step(x)
    want = np.tile(np.asarray(x).reshape(-1), (8, 1))
    np.testing.assert_array_equal(np.asarray(out), want)


def test_alltoall_lowering_transposes_blocks(mesh):
    from brpc_tpu.parallel.collective_echo import make_alltoall_step

    step = make_alltoall_step(mesh)
    n, block = 8, 16
    x = jnp.arange(n * n * block, dtype=jnp.uint32).reshape(n, n * block)
    out = step(x)
    want = (
        np.arange(n * n * block, dtype=np.uint32)
        .reshape(n, n, block)
        .transpose(1, 0, 2)
        .reshape(n, n * block)
    )
    np.testing.assert_array_equal(np.asarray(out), want)


def _coll_command_round(nodes, alg, nbytes, seq, timeout=60.0):
    """Drive one collective round across every node and collect the
    per-node COLL result lines."""
    import json as _json
    import time as _time

    for n in nodes:
        n.send("coll %s %d %d" % (alg, nbytes, seq))
    results = []
    deadline = _time.time() + timeout
    for n in nodes:
        line = None
        while True:
            line = n._readline(deadline)
            assert line is not None, "node %d: no COLL line" % n.idx
            if line.startswith("COLL "):
                break
        results.append(_json.loads(line[5:]))
    return results


def test_cpp_mesh_allreduce_bitexact_vs_jax(cpp_build, tmp_path, mesh):
    """The C++ chunked-ring all-reduce over a real 4-process mesh must
    agree BIT FOR BIT with the XLA collective lowering on the same
    payloads (two implementations of one pattern)."""
    from test_chaos_soak import NODE_FLAGS, Node, _free_ports
    from brpc_tpu.parallel.collective_echo import make_allreduce_step

    binary = cpp_build / "mesh_node"
    assert binary.exists(), "mesh_node not built"
    num = 4
    ports = _free_ports(num)
    peers_file = tmp_path / "coll_members"
    peers_file.write_text("".join("127.0.0.1:%d\n" % p for p in ports))
    nodes = [
        Node(binary, ports[i], i, peers_file, flags=NODE_FLAGS,
             extra_args=("--collective",))
        for i in range(num)
    ]
    try:
        for n in nodes:
            assert n.wait_ready(), "node %d never became ready" % n.idx
        import time as _time
        _time.sleep(2.0)  # shm links establish

        seq, nbytes = 5, 64 * 1024
        nwords = nbytes // 4
        results = _coll_command_round(nodes, "allreduce", nbytes, seq)

        # Same payloads in JAX: row r = the deterministic fill of the
        # node with the r-th smallest port (the engine's rank order).
        rows = np.stack(
            [_fill_deterministic(seq, p, nwords) for p in sorted(ports)]
        )
        step = make_allreduce_step(
            jax.sharding.Mesh(jax.devices("cpu")[:num], ("peers",))
        )
        jax_out = np.asarray(step(jnp.asarray(rows)))
        # The lowering agrees with the plain numpy wraparound sum...
        want = np.tile(rows.sum(axis=0, dtype=np.uint32), (num, 1))
        np.testing.assert_array_equal(jax_out, want)
        # ...and the C++ mesh produced the identical bits: checksum +
        # leading words on every node, nodes verified it internally too.
        expect_checksum = _coll_checksum(want[0])
        expect_head = [int(v) for v in want[0][:4]]
        for rep in results:
            assert rep["ok"] == 1, rep
            assert rep["verified"] == 1, rep
            assert rep["nranks"] == num, rep
            assert rep["checksum"] == expect_checksum, rep
            assert rep["head"] == expect_head, rep

        for n in nodes:
            assert n.shutdown() == 0, "node %d unclean exit" % n.idx
    finally:
        for n in nodes:
            try:
                n.proc.kill()
            except OSError:
                pass
