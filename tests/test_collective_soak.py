"""Mesh-collective chaos soak (ISSUE 13): SIGKILL a node mid-all-reduce,
the collective RE-FORMS over the survivors and keeps completing rounds.

Five `mesh_node` processes run with --coll_traffic: every node
continuously drives the same program of chunked-pipelined collectives
(mostly all-reduce, with all-gather and all-to-all rounds mixed in) over
the shm-ICI mesh, each chunk posted as a one-sided pool descriptor and
every completed round VERIFIED bit-for-bit against the deterministic
inputs of the membership it completed over. Mid-run the soak

  * SIGKILLs one node while rounds are continuously in flight (the kill
    lands mid-all-reduce by construction),
  * asserts the survivors re-form (rpc_collective_reforms fires) and
    keep completing verified rounds as a 4-member mesh,
  * restarts the killed node and asserts it REJOINS the running
    collective (adopting the mesh's current round seq) and that rounds
    complete over all 5 members again.

Asserted invariants (the ISSUE-13 acceptance gate):
  * zero lost completions: coll_issued == coll_ok + coll_failed and
    outstanding == 0 on every node;
  * zero verification failures — a re-form may fail rounds (counted,
    retriable) but NEVER corrupt one;
  * rpc_collective_reforms >= 1 across the survivors;
  * zero leaked pins: /pools pinned drains to 0 everywhere (chunk
    descriptors ride the lease registry; the killed node's pins release
    via peer-death reclamation);
  * clean exit 0 everywhere.
"""
import time

from test_chaos_soak import Node, _free_ports, _var
from test_pool_chaos_soak import POOL_FLAGS, _pools

NUM_NODES = 5

COLL_ARGS = ("--coll_traffic",)


def _wait_ops(ports, minimum, timeout=60.0, baseline=None):
    """Wait until rpc_collective_ops grew past `minimum` over `baseline`
    on every listed node; returns the last reading."""
    baseline = baseline or {p: 0 for p in ports}
    deadline = time.time() + timeout
    ops = {}
    while time.time() < deadline:
        ops = {p: _var(p, "rpc_collective_ops") for p in ports}
        if all(ops[p] - baseline[p] >= minimum for p in ports):
            return ops
        time.sleep(0.5)
    return ops


def test_collective_soak(cpp_build, tmp_path):
    binary = cpp_build / "mesh_node"
    assert binary.exists(), "mesh_node not built"
    ports = _free_ports(NUM_NODES)
    peers_file = tmp_path / "coll_mesh_members"
    peers_file.write_text("".join("127.0.0.1:%d\n" % p for p in ports))

    nodes = [
        Node(binary, ports[i], i, peers_file, flags=POOL_FLAGS,
             extra_args=COLL_ARGS)
        for i in range(NUM_NODES)
    ]
    try:
        for n in nodes:
            assert n.wait_ready(), "node %d never became ready" % n.idx

        # Rounds are flowing on every node (and chunks really ride the
        # descriptor path: collective steps pin pool blocks).
        ops0 = _wait_ops(ports, 3)
        assert all(v >= 3 for v in ops0.values()), \
            "collective rounds never started: %s" % ops0
        assert sum(_var(p, "rpc_collective_steps") for p in ports) > 0
        assert sum(
            _var(p, "rpc_pool_descriptor_sends") for p in ports) > 0, \
            "collective chunks are not riding the descriptor path"

        # --- SIGKILL one node mid-all-reduce --------------------------
        # Traffic is continuous (a round roughly every 50ms), so the
        # kill lands with rounds in flight on every survivor.
        kill_idx = NUM_NODES - 1
        nodes[kill_idx].kill9()
        survivors = [i for i in range(NUM_NODES) if i != kill_idx]
        surv_ports = [ports[i] for i in survivors]

        # Survivors re-form over the 4-member mesh and keep completing
        # rounds (reforms is cumulative across the mesh).
        deadline = time.time() + 40.0
        reforms = 0
        while time.time() < deadline:
            reforms = sum(
                _var(p, "rpc_collective_reforms") for p in surv_ports)
            if reforms >= 1:
                break
            time.sleep(0.5)
        assert reforms >= 1, "survivors never re-formed"
        base = {p: _var(p, "rpc_collective_ops") for p in surv_ports}
        ops1 = _wait_ops(surv_ports, 3, baseline=base)
        assert all(ops1[p] - base[p] >= 3 for p in surv_ports), \
            "rounds stopped completing after the kill: %s" % ops1

        # Peer death must not strand the killed node's chunk pins on
        # the survivors (lease peer-death reclamation).
        deadline = time.time() + 20.0
        pinned = None
        while time.time() < deadline:
            pinned = [_pools(p)["pinned"] for p in surv_ports]
            if all(v <= 4 for v in pinned):
                break
            time.sleep(0.5)
        assert all(v <= 4 for v in pinned), \
            "pins stranded after peer kill: %s" % pinned

        # --- restart the killed node: it must REJOIN ------------------
        nodes[kill_idx] = Node(binary, ports[kill_idx], kill_idx,
                               peers_file, flags=POOL_FLAGS,
                               extra_args=COLL_ARGS)
        assert nodes[kill_idx].wait_ready()
        # The restarted node adopts the mesh's current round seq and
        # completes rounds WITH the others (its ops only grow when the
        # whole 5-member collective completes).
        ops2 = _wait_ops([ports[kill_idx]], 2, timeout=90.0)
        assert ops2[ports[kill_idx]] >= 2, \
            "restarted node never rejoined the collective: %s" % ops2

        # --- drain + invariants ---------------------------------------
        reports = []
        for n in nodes:
            rep = n.stop_and_report(timeout=60.0)
            assert rep is not None, "node %d produced no report" % n.idx
            reports.append(rep)

        for rep in reports:
            # Zero lost completions on the collective plane (and the
            # background planes), zero verification failures.
            assert rep["outstanding"] == 0, rep
            assert rep["coll_issued"] == (
                rep["coll_ok"] + rep["coll_failed"]), rep
            assert rep["coll_verify_failed"] == 0, rep
            assert rep["coll_ok"] > 0, rep
            assert rep["lb_issued"] == rep["lb_ok"] + rep["lb_failed"], rep
            assert rep["shm_issued"] == rep["shm_ok"] + rep["shm_failed"], \
                rep
        # The mesh re-formed at least once, and after the heal the
        # last completed rounds ran over all 5 members somewhere.
        assert sum(rep["coll_reforms"] for rep in reports) >= 1, reports
        assert any(rep["coll_nranks"] == NUM_NODES for rep in reports), \
            reports

        # Zero leaked pins after quiesce, everywhere.
        deadline = time.time() + 20.0
        pinned = None
        while time.time() < deadline:
            pinned = [_pools(p)["pinned"] for p in ports]
            if all(v == 0 for v in pinned):
                break
            time.sleep(0.5)
        assert all(v == 0 for v in pinned), \
            "pins stranded after quiesce: %s" % pinned

        for n in nodes:
            assert n.shutdown() == 0, "node %d unclean exit" % n.idx
    finally:
        for n in nodes:
            try:
                n.proc.kill()
            except OSError:
                pass
