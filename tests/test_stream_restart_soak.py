"""Resumable server-push stream restart soak (ISSUE 17 capstone).

Six `mesh_node` backends sit behind one `tpu_router`. A gold-tenant
rpc_press opens resumable 256-token server-push streams (sticky
sessions, seq contiguity + deterministic token content asserted at the
client on EVERY chunk) while a bronze-tenant press floods the plain
admission path — and EVERY backend is SIGTERM-restarted under that
load. The router terminates client streams and pumps them from the
backends, so backend death must be client-invisible: the pump re-pins
and resumes downstream, the upstream replay ring covers what the dead
backend never delivered.

Asserted invariants — the exactly-once token contract:
  * ZERO client-visible stream failures and ZERO sequencing errors:
    every delivered token arrived exactly once, in order, with the
    content regeneration determinism demands (press_stream_seq_errors
    == 0, press_failed == 0 at the gold press);
  * streams actually RESUMED: the router re-opened backend streams
    with a resume offset (stream_relay_resumes > 0) and restarted
    backends regenerated from the client floor (the backends'
    rpc_stream_resumed metric fired);
  * gold stayed responsive: TTFT p99 under chaos + bronze flood within
    2x the unloaded baseline (100ms floor absorbs tiny-baseline CI
    noise);
  * a credit-stalled slow consumer bounds server memory: the stall
    parks the writer (rpc_stream_credit_stalls > 0 at the router) and
    the replay ring high-water respects -stream_replay_ring;
  * descriptor-lease pins drain to 0 and every process exits clean.
"""
import json
import signal
import subprocess
import time

from test_chaos_soak import Node, _free_ports, _http_get, _var
from test_router_restart_soak import (BACKEND_ARGS, BACKEND_FLAGS, Router,
                                      _wait_line)

NUM_BACKENDS = 6
STREAM_TOKENS = 256
CHAOS_DURATION_S = 30
REPLAY_RING_CAP = 128  # -stream_replay_ring default
# 20ms/token => a 256-token stream runs ~5s, far past the 800ms drain
# window. That is the point: a SIGTERMed backend CANNOT finish its
# in-flight streams inside the drain, so the router pump must resume
# them on a survivor (registry miss + resume_from => regeneration).
# With the default 2ms pacing every stream slips out during the drain
# and the resume path is never exercised.
TOKEN_DELAY_US = 20000
STREAM_ARGS = BACKEND_ARGS + ("--stream_token_delay_us",
                              str(TOKEN_DELAY_US))


def _press_json(out):
    lines = [l for l in out.decode().splitlines() if l.startswith("{")]
    assert lines, "press produced no json report: %r" % out
    return json.loads(lines[-1])


def _stream_press(press_bin, router_port, tokens, qps, duration_s,
                  sessions, callers, extra=()):
    return subprocess.Popen(
        [str(press_bin),
         "--server=127.0.0.1:%d" % router_port,
         "--stream_tokens=%d" % tokens,
         "--qps=%d" % qps, "--duration_s=%d" % duration_s,
         "--callers=%d" % callers, "--sessions=%d" % sessions,
         "--tenant=gold", "--priority=7",
         "--timeout_ms=3000", "--max_retry=0", "--json"] + list(extra),
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL)


def test_stream_restart_soak(cpp_build, tmp_path):
    mesh_bin = cpp_build / "mesh_node"
    router_bin = cpp_build / "tpu_router"
    press_bin = cpp_build / "rpc_press"
    for b in (mesh_bin, router_bin, press_bin):
        assert b.exists(), "%s not built" % b

    ports = _free_ports(NUM_BACKENDS + 1)
    backend_ports, router_port = ports[:NUM_BACKENDS], ports[NUM_BACKENDS]
    backends_file = tmp_path / "stream_backends"
    backends_file.write_text(
        "".join("127.0.0.1:%d\n" % p for p in backend_ports))

    def spawn_backend(i):
        return Node(mesh_bin, backend_ports[i], i, backends_file,
                    flags=BACKEND_FLAGS, extra_args=STREAM_ARGS)

    backends = [spawn_backend(i) for i in range(NUM_BACKENDS)]
    router = None
    procs = []
    try:
        for n in backends:
            assert n.wait_ready(), "backend %d never became ready" % n.idx
        router = Router(router_bin, router_port, backends_file)
        assert router.wait_ready(), "router never became ready"
        time.sleep(0.5)  # first probe pass marks the backends live

        # --- unloaded TTFT baseline: short gold-only stream press -----
        base = _stream_press(press_bin, router_port, tokens=64, qps=6,
                             duration_s=6, sessions=4, callers=4)
        procs.append(base)
        out, _ = base.communicate(timeout=40)
        assert base.returncode == 0, "baseline press failed"
        base_rep = _press_json(out)
        assert base_rep["press_failed"] == 0, base_rep
        assert base_rep["press_stream_seq_errors"] == 0, base_rep
        assert base_rep["press_stream_tokens"] > 0, base_rep
        baseline_ttft_p99 = base_rep["press_ttft_us"]["p99"]
        assert baseline_ttft_p99 > 0, base_rep

        # --- chaos: gold streams + bronze flood + rolling restarts ----
        gold = _stream_press(press_bin, router_port, tokens=STREAM_TOKENS,
                             qps=4, duration_s=CHAOS_DURATION_S,
                             sessions=4, callers=4)
        bronze = subprocess.Popen(
            [str(press_bin),
             "--server=127.0.0.1:%d" % router_port,
             "--qps=300", "--duration_s=%d" % CHAOS_DURATION_S,
             "--payload=2048", "--callers=8",
             "--tenant=bronze", "--priority=1",
             "--timeout_ms=3000", "--json"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL)
        procs += [gold, bronze]
        time.sleep(2.5)  # streams open, sessions pin, flood warms

        for i in range(NUM_BACKENDS):
            n = backends[i]
            n.proc.send_signal(signal.SIGTERM)
            assert _wait_line(n, "DRAINING", 10.0) is not None, (
                "backend %d never announced its drain" % i)
            assert n.proc.wait(timeout=20) is not None
            assert n.proc.returncode == 0, (
                "backend %d unclean graceful exit: %d"
                % (i, n.proc.returncode))
            backends[i] = spawn_backend(i)
            assert backends[i].wait_ready(), (
                "backend %d restart failed" % i)
            time.sleep(1.0)  # streams re-pin + resume before the next kill

        out, _ = gold.communicate(timeout=CHAOS_DURATION_S + 60)
        assert gold.returncode == 0, "gold press failed"
        rep = _press_json(out)
        bout, _ = bronze.communicate(timeout=30)
        assert bronze.returncode == 0, "bronze press failed"
        bronze_rep = _press_json(bout)
        assert bronze_rep["press_qps"] > 0, bronze_rep

        # Exactly-once, in order, right content — across six restarts.
        assert rep["press_failed"] == 0, (
            "client-visible stream failures: %r" % rep)
        assert rep["press_stream_seq_errors"] == 0, (
            "lost/duplicated/corrupt tokens reached a client: %r" % rep)
        assert rep["press_qps"] > 0, "no gold stream ever completed"
        assert rep["press_stream_tokens"] >= STREAM_TOKENS, rep

        # Gold TTFT under chaos + flood stays within 2x unloaded.
        allowed = max(2 * baseline_ttft_p99, 100000)
        assert rep["press_ttft_us"]["p99"] <= allowed, (
            "gold TTFT p99 %dus vs allowed %dus (baseline %dus): %r"
            % (rep["press_ttft_us"]["p99"], allowed, baseline_ttft_p99,
               rep))
        assert rep["press_itl_us"]["p99"] > 0, rep

        # The resume machinery actually fired: the router re-opened
        # backend streams at an offset...
        state = router.state()
        assert state["stream_relays"] > 0, state
        assert state["stream_relay_resumes"] > 0, (
            "no downstream stream ever resumed across six backend "
            "restarts: %r" % state)
        # ...and restarted backends regenerated from the client floor.
        resumed = sum(_var(p, "rpc_stream_resumed")
                      for p in backend_ports)
        assert resumed > 0, (
            "no backend counted rpc_stream_resumed after the restarts")

        # --- slow consumer: credits park the writer, ring stays bounded
        # Producer paces at 20ms/token; a 100ms-per-read consumer falls
        # behind by ~40 tokens/s, exhausting the rx window well inside
        # the press — the writer must park on credits, not buffer.
        slow = _stream_press(press_bin, router_port, tokens=64, qps=1,
                             duration_s=6, sessions=1, callers=1,
                             extra=("--stream_read_delay_ms=100",))
        procs.append(slow)
        sout, _ = slow.communicate(timeout=60)
        assert slow.returncode == 0, "slow-consumer press failed"
        slow_rep = _press_json(sout)
        assert slow_rep["press_stream_seq_errors"] == 0, slow_rep
        streams = json.loads(
            _http_get(router_port, "/streams?format=json", timeout=2.0))
        assert streams["credit_stalls"] > 0, (
            "slow consumer never parked the writer: %r" % streams)
        assert 0 < streams["ring_highwater"] <= REPLAY_RING_CAP, (
            "replay ring exceeded its bound: %r" % streams)

        # --- clean drains: router REPORT, pins at 0, backends exit 0 --
        router.proc.send_signal(signal.SIGTERM)
        assert _wait_line(router, "DRAINING", 10.0) is not None, (
            "router never announced its drain")
        line = _wait_line(router, "REPORT ", 30.0)
        assert line is not None, "router produced no exit report"
        final = json.loads(line[len("REPORT "):])
        assert final["pool_pinned"] == 0, (
            "descriptor-lease pins leaked at router exit: %r" % final)
        assert router.proc.wait(timeout=30) == 0, "router unclean exit"
        for n in backends:
            assert n.shutdown() == 0, "backend %d unclean exit" % n.idx
    finally:
        for p in [router] + backends + procs:
            if p is None:
                continue
            try:
                p.proc.kill() if hasattr(p, "proc") else p.kill()
            except OSError:
                pass
