"""Validates the driver contract: entry() jits single-chip and
dryrun_multichip() compiles+runs real shardings on a virtual 8-device mesh."""
import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def test_entry_jits_and_echoes():
    import jax
    from __graft_entry__ import entry

    fn, args = entry()
    checksums, lengths, echoed = jax.jit(fn)(*args)
    np.testing.assert_array_equal(np.asarray(echoed), np.asarray(args[0]))
    assert checksums.shape == (args[0].shape[0],)
    # Checksum is order-sensitive: permuting words changes it.
    permuted = np.asarray(args[0]).copy()
    permuted[0] = permuted[0][::-1]
    c2, _, _ = jax.jit(fn)(permuted)
    assert np.asarray(c2)[0] != np.asarray(checksums)[0]


def test_dryrun_multichip_8():
    from __graft_entry__ import dryrun_multichip

    dryrun_multichip(8)


def test_parallel_echo_is_identity():
    import jax
    import jax.numpy as jnp
    from brpc_tpu.parallel.collective_echo import make_parallel_echo_step

    devices = jax.devices()[:8]
    mesh = jax.sharding.Mesh(np.array(devices), ("peers",))
    step = make_parallel_echo_step(mesh)
    x = jnp.arange(8 * 128, dtype=jnp.uint32).reshape(8, 128)
    out = step(x)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))
