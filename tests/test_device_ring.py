"""Tier-1: the pipelined DMA staging ring + one-sided descriptor path
(ISSUE 9).

Runs on the virtual CPU mesh (conftest pins JAX_PLATFORMS=cpu): the cpu
backend is explicitly tolerated — the ring must still move framed chunks
through the full C++ staging path with every integrity check live, and
the run must never be silently skipped (the record keys are asserted, a
missing device path is a failure, not a skip).
"""
import json
import subprocess
import sys

import numpy as np
import pytest


@pytest.fixture(scope="module")
def native(cpp_build):
    from brpc_tpu import native as n
    n.lib()  # loads build/libtpurpc.so produced by the cpp_build fixture
    return n


def test_ring_pipeline_correctness_and_speedup(cpp_build, native):
    """Ring correctness on the cpu backend: per-chunk crc32c verified
    after the overlapped pipeline, FIFO window respected, and the
    serial-vs-pipelined speedup recorded (>= 1 within measurement noise;
    the >= 2x bar is bench.py's, on hosts with a core to overlap on)."""
    from brpc_tpu.device_path import run

    out = run(payload_mb=4, reps=4, ring_depth=4, chunk_kb=508)
    # Never silently skipped: the run must report a real device record.
    for key in ("device_path_gbps", "device_path_serial_gbps",
                "device_path_overlap_eff", "device_path_ok",
                "device_path_device"):
        assert key in out, f"device record missing {key}"
    assert out["device_path_ok"], "per-chunk crc32c verification failed"
    assert out["device_path_gbps"] > 0
    assert out["device_path_ring_depth"] == 4
    assert out["device_path_inflight_highwater"] <= 4
    # Speedup recorded; cpu backend tolerated (throttled single-core
    # hosts can't overlap, so allow noise below 1 but require the
    # measurement itself).
    assert out["device_path_overlap_eff"] > 0
    assert out["device_path_registered_staging"], \
        "staging ring must come from registered pool memory"


def test_ring_fifo_and_recycling(native):
    ring = native.DeviceStagingRing(4, 64 << 10)
    assert ring.registered
    # FIFO order, window bounded by depth.
    slots = [ring.acquire() for _ in range(4)]
    assert slots == [0, 1, 2, 3]
    with pytest.raises(TimeoutError):
        ring.acquire(timeout_us=1000)  # window full
    # Out-of-order completes are held until predecessors finish.
    ring.complete(slots[1])
    with pytest.raises(TimeoutError):
        ring.acquire(timeout_us=1000)  # slot 0 still pins the window
    ring.complete(slots[0])
    assert ring.acquire() == 0  # both freed, FIFO resumes at 0
    assert ring.inflight_highwater == 4
    ring.close()

    # Ring slots recycle through the slab classes on close.
    live0, _ = native.slab_counters()
    r2 = native.DeviceStagingRing(2, 64 << 10)
    live_open, _ = native.slab_counters()
    assert live_open == live0 + 2
    r2.close()
    live_closed, recycled = native.slab_counters()
    assert live_closed == live0
    assert recycled >= 0


def test_frame_in_place_skips_payload_copy(native):
    """ISSUE 9 satellite: framing a payload that already resides inside
    the destination pool buffer writes header+crc only — the returned
    frame view aliases the original payload bytes (no memcpy)."""
    buf = native.PoolBuffer(1 << 20)
    payload = np.arange(4096, dtype=np.uint32)
    region = buf.array[64:64 + payload.nbytes].view(np.uint32)
    region[:] = payload
    fr = native.frame(42, region, out=buf.array)
    cid, pay, _ = native.unframe(fr)
    assert cid == 42
    # Zero-copy proof: the parsed payload view IS the staged region.
    assert pay.ctypes.data == region.view(np.uint8).ctypes.data
    # A mutation through the original region is visible in the frame.
    region[0] ^= 0xFFFFFFFF
    with pytest.raises(ValueError):
        native.unframe(fr)  # crc now mismatches: same bytes, one copy
    region[0] ^= 0xFFFFFFFF
    buf.free()


def test_descriptor_attachment_roundtrips_through_real_server(cpp_build):
    """One-sided pool descriptor through a REAL server (echo_bench
    --pool-desc --ici): the attachment crosses the seam as a (pool_id,
    offset, len, crc32c) reference, the server answers with the crc it
    computed from the in-place view, and zero inline payload bytes ride
    the frame."""
    exe = cpp_build / "echo_bench"
    proc = subprocess.run(
        [str(exe), "--json", "--ici", "--pool-desc"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr
    line = next(ln for ln in proc.stdout.splitlines()
                if ln.strip().startswith("{"))
    out = json.loads(line)
    assert out["pool_desc_zero_copy"] == 1
    assert out["pool_desc_calls"] > 0
    assert out["pool_desc_mbps"] > 0


def test_bench_compare_skips_retired_device_key(cpp_build, tmp_path):
    """The --compare gate must not flag the retired device_path_mbps
    (MB/s -> GB/s unit change) as a regression."""
    repo = cpp_build.parent
    prev = tmp_path / "prev.json"
    cur = tmp_path / "cur.json"
    prev.write_text(json.dumps({
        "metric": "echo_throughput_1MB_ici", "value": 1.0,
        "device_path_mbps": 34.0, "device_path_gbps": 0.5}) + "\n")
    cur.write_text(json.dumps({
        "metric": "echo_throughput_1MB_ici", "value": 1.0,
        "device_path_mbps": 0.001, "device_path_gbps": 1.0}) + "\n")
    proc = subprocess.run(
        [sys.executable, str(repo / "bench.py"), "--compare", str(prev),
         "--current", str(cur), "--strict"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "REGRESSION" not in proc.stdout
