"""Cross-host rpcz trace stitching (ISSUE 4 tentpole acceptance).

Three mesh_node processes with rpcz enabled. A client request fans
through 3 hops across the 3 processes (node0 client -> node1 server,
whose handler calls -> node2), all under ONE trace id; /rpcz/trace/<id>
on node0 must return a single stitched timeline containing every hop's
spans with correct parentage. A second chain under a deliberately
starved deadline (handler delay > budget) must show the shed hop's
annotation in the stitched view.
"""
import time

from test_chaos_soak import NODE_FLAGS, Node, _free_ports, _http_get


def _read_chain(node, timeout=20.0):
    """Next 'CHAIN trace=<id> err=<code>' line -> (trace, err)."""
    deadline = time.time() + timeout
    while True:
        line = node._readline(deadline)
        assert line is not None, "no CHAIN line from node %d" % node.idx
        if line.startswith("CHAIN "):
            fields = dict(kv.split("=") for kv in line.split()[1:])
            return int(fields["trace"]), int(fields["err"])


def test_stitched_trace_across_three_processes(cpp_build, tmp_path):
    binary = cpp_build / "mesh_node"
    assert binary.exists(), "mesh_node not built"
    num = 3
    ports = _free_ports(num)
    eps = ["127.0.0.1:%d" % p for p in ports]
    peers_file = tmp_path / "mesh_members"
    peers_file.write_text("".join(e + "\n" for e in eps))

    flags = NODE_FLAGS + [
        "enable_rpcz=true",
        # Full membership: the stitcher must reach nodes this process
        # never called itself (node0 has no connection to node2).
        "rpcz_peers=%s" % ",".join(eps),
    ]
    nodes = [Node(binary, ports[i], i, peers_file, flags=flags)
             for i in range(num)]
    try:
        for n in nodes:
            assert n.wait_ready(), "node %d never became ready" % n.idx
        time.sleep(1.0)  # background traffic warms connections

        # --- happy chain: 0 -> 1 -> 2 under one trace -----------------
        nodes[0].send("chain 3000 %s %s" % (eps[1], eps[2]))
        trace, err = _read_chain(nodes[0])
        assert err == 0, "chain failed with %d" % err
        assert trace != 0, "root call was not sampled (enable_rpcz?)"
        time.sleep(0.5)  # spans flow through the collector (50ms cadence)

        stitched = _http_get(ports[0], "/rpcz/trace/%d" % trace, timeout=15)
        # Every hop's host appears: client span on node0, server+client
        # on node1, server on node2.
        for e in eps:
            assert "@" + e in stitched, (e, stitched)
        assert stitched.count("SERVER") >= 2, stitched
        assert stitched.count("CLIENT") >= 2, stitched
        # Correct parentage: three nested children under the root span
        # (server@1 under client@0, client@1 under server@1, server@2
        # under client@1) — each child line carries the tree marker.
        assert stitched.count("\\_ ") >= 3, stitched
        # The deepest hop's span (server on node2) is a child, reached
        # only through stitching (node0 never talked to node2).
        assert ("SERVER benchpb.EchoService.Echo @" + eps[2]) in stitched, \
            stitched
        # Per-hop breakdown rendered for server spans.
        assert "queue=" in stitched and "process=" in stitched, stitched

        # --- starved chain: node1 sleeps past the budget --------------
        nodes[1].send("delay 60 0")
        deadline = time.time() + 10.0
        while True:
            line = nodes[1]._readline(deadline)
            assert line is not None, "no DELAY_OK from node 1"
            if line.startswith("DELAY_OK"):
                break
        nodes[0].send("chain 40 %s %s" % (eps[1], eps[2]))
        trace2, err2 = _read_chain(nodes[0])
        assert err2 != 0, "40ms budget should not survive a 60ms hop"
        assert trace2 != 0
        time.sleep(0.7)  # node1's handler finishes + collector dispatch

        stitched2 = _http_get(ports[0], "/rpcz/trace/%d" % trace2,
                              timeout=15)
        # The deliberately starved hop shows its annotation in the
        # stitched timeline (shed downstream / expired budget verdict).
        assert "failed:" in stitched2, stitched2
        nodes[1].send("delay 0 0")

        for n in nodes:
            assert n.shutdown() == 0, "node %d unclean exit" % n.idx
    finally:
        for n in nodes:
            try:
                n.proc.kill()
            except OSError:
                pass
