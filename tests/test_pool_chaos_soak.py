"""Zero-copy pool chaos soak (ISSUE 10/12): SIGKILL + stale epochs +
leaks, now SYMMETRIC.

Four `mesh_node` processes run with --desc_traffic: every node
continuously pins pool blocks under leases and posts them as one-sided
(pool_id, offset, len, crc, epoch) descriptors over the shm-ICI links —
and (ISSUE 12) every call also ASKS for a response-direction descriptor,
so each node holds server-side "rsp" pins that only its CLIENTS' acks
release. Mid-run the soak

  * SIGKILLs one node while it holds / is entitled to read in-flight
    pinned descriptors in BOTH roles — as a client mid-response-
    descriptor (its unsent acks must not strand the survivors' rsp
    pins: the socket failure observer releases them) and as a server
    holding pins for the survivors' requests,
  * injects stale-epoch faults at one survivor's resolve seam
    (chaos_pool `pool_stale`, via its /chaos portal),
  * injects leaked-pin faults at one survivor's release seam
    (chaos_pool `pool_leak`) so the lease reaper must reclaim orphans,
  * heals and restarts the killed node.

Asserted invariants (the ISSUE-10 acceptance gate):
  * slab/lease ledger returns to baseline on every surviving node —
    pinned blocks drain to ZERO after quiesce (no leaked pins from the
    kill, the leak injection, or anything else);
  * zero lost completions: desc_issued == desc_ok + desc_failed and
    outstanding == 0 on every node;
  * injected stale-epoch descriptors fail as retriable call failures
    (client desc_stale > 0, server rpc_pool_epoch_rejects > 0) while
    the fenced node KEEPS SERVING on the same connections — never a
    crash or a wedged link;
  * the reaper reclaimed the deliberately-leaked pins
    (rpc_pool_reaped > 0);
  * clean exit 0 everywhere (Join quiesces every socket).
"""
import json
import time

from test_chaos_soak import NODE_FLAGS, Node, _chaos, _free_ports, \
    _http_get, _var

NUM_NODES = 4

# Short lease grace so the leak-injection phase's orphans become
# reapable within the soak window (default grace is 2s on top of the
# 800ms call deadline). pool_lease_default_ms bounds the PRE-ARM
# lifetime the same way: a pin leaked before its call armed (setup/
# pre-issue failure under load) carries the default 30s lifetime, which
# outlives the final 20s pinned==0 poll on a slow host.
POOL_FLAGS = NODE_FLAGS + [
    "pool_lease_grace_ms=300",
    "pool_lease_default_ms=2000",
    "pool_lease_reap_ms=100",
]


def _pools(port):
    return json.loads(_http_get(port, "/pools?format=json"))


def test_pool_chaos_soak(cpp_build, tmp_path):
    binary = cpp_build / "mesh_node"
    assert binary.exists(), "mesh_node not built"
    ports = _free_ports(NUM_NODES)
    peers_file = tmp_path / "mesh_members"
    peers_file.write_text("".join("127.0.0.1:%d\n" % p for p in ports))

    nodes = [
        Node(binary, ports[i], i, peers_file, flags=POOL_FLAGS,
             extra_args=("--desc_traffic",))
        for i in range(NUM_NODES)
    ]
    try:
        for n in nodes:
            assert n.wait_ready(), "node %d never became ready" % n.idx

        # Descriptor traffic is really flowing (lease pins being taken)
        # in BOTH directions: request sends AND response-direction
        # sends/resolves (ISSUE 12).
        deadline = time.time() + 20.0
        while time.time() < deadline:
            sends = sum(
                _var(p, "rpc_pool_descriptor_sends") for p in ports)
            rsp_sends = sum(
                _var(p, "rpc_pool_desc_rsp_sends") for p in ports)
            if sends >= 20 and rsp_sends >= 10:
                break
            time.sleep(0.5)
        assert sends >= 20, "descriptor traffic never started"
        assert rsp_sends >= 10, \
            "response-direction descriptors never flowed"
        assert sum(
            _var(p, "rpc_pool_desc_rsp_resolves") for p in ports) >= 10
        assert sum(_pools(p)["pins_total"] for p in ports) >= 20
        # The /pools ledger shows rsp-direction leases with their
        # direction column while acks are in flight.
        directions = set()
        for p in ports:
            for lease in _pools(p).get("leases", []):
                directions.add(lease.get("direction"))
        assert directions <= {"req", "rsp"}, directions

        # --- kill a node holding in-flight pinned descriptors ---------
        # The victim is BOTH a client mid-response-descriptor (its
        # controllers' desc_acks die with it — the survivors' server-
        # side "rsp" pins must release through the socket failure
        # observer, rpc_pool_pinned_blocks draining to ~0) and a server
        # holding pins of its own.
        kill_idx = 3
        nodes[kill_idx].kill9()
        survivors = [i for i in range(NUM_NODES) if i != kill_idx]

        # Peer death must not strand pins on the survivors: their leases
        # to the dead node resolve via EndRPC (failed call), the
        # socket-failure ReleasePeer path (both req pins posted TOWARD
        # the dead node and rsp pins awaiting ITS acks), or the reaper;
        # steady state returns to a small in-flight transient, never a
        # growing leak.
        deadline = time.time() + 20.0
        ok = False
        while time.time() < deadline:
            pinned = [_pools(ports[i])["pinned"] for i in survivors]
            if all(p <= 4 for p in pinned):
                ok = True
                break
            time.sleep(0.5)
        assert ok, "pins stranded after peer kill: %s" % pinned

        # --- stale-epoch injection at node 0's resolve seam -----------
        _chaos(ports[0], enable=1, seed=777, plan="pool_stale=0.5")
        deadline = time.time() + 20.0
        rejects = 0
        while time.time() < deadline:
            rejects = _var(ports[0], "rpc_pool_epoch_rejects")
            if rejects >= 3:
                break
            time.sleep(0.5)
        assert rejects >= 3, "stale-epoch fence never fired"
        # The fenced node is alive and still serving its portal + RPCs.
        assert _http_get(ports[0], "/health").strip() == "OK"

        # --- leaked-pin injection at node 1's release seam ------------
        _chaos(ports[1], enable=1, seed=778, plan="pool_leak=1")
        time.sleep(2.0)  # leak a few pins
        _chaos(ports[1], enable=0)
        deadline = time.time() + 20.0
        reaped = 0
        while time.time() < deadline:
            reaped = _var(ports[1], "rpc_pool_reaped")
            if reaped >= 1:
                break
            time.sleep(0.5)
        assert reaped >= 1, "reaper never reclaimed the leaked pins"

        # --- heal + restart the killed node ---------------------------
        _chaos(ports[0], enable=0)
        nodes[kill_idx] = Node(binary, ports[kill_idx], kill_idx,
                               peers_file, flags=POOL_FLAGS,
                               extra_args=("--desc_traffic",))
        assert nodes[kill_idx].wait_ready()
        time.sleep(4.0)  # links re-establish, fresh handshakes map pools

        # --- drain + invariants ---------------------------------------
        reports = []
        for n in nodes:
            rep = n.stop_and_report()
            assert rep is not None, "node %d produced no report" % n.idx
            reports.append(rep)

        stale_total = 0
        for rep in reports:
            # Zero lost completions on the descriptor plane (and all
            # others) — the headline crash-safety invariant.
            assert rep["outstanding"] == 0, rep
            assert rep["desc_issued"] == (
                rep["desc_ok"] + rep["desc_failed"]), rep
            assert rep["lb_issued"] == rep["lb_ok"] + rep["lb_failed"], rep
            assert rep["shm_issued"] == rep["shm_ok"] + rep["shm_failed"], rep
            stale_total += rep["desc_stale"]
        # Descriptor traffic did useful work on every node (incl. the
        # restarted one) in BOTH directions, and the stale injection
        # surfaced client-side as retriable call failures, not crashes.
        for rep in reports:
            assert rep["desc_ok"] > 0, rep
            assert rep["desc_rsp_ok"] > 0, rep
            assert rep["desc_rsp_sends"] > 0, rep
            assert rep["desc_rsp_resolves"] > 0, rep
        assert stale_total >= 1, reports
        assert reports[0]["epoch_rejects"] >= 3, reports[0]
        # The deliberately-leaked pins were reaped, not stranded.
        assert reports[1]["pool_reaped"] >= 1, reports[1]

        # Lease ledger EMPTY everywhere after quiesce. Response-
        # direction pins drain asynchronously (a node's "rsp" pins
        # release on OTHER nodes' acks, which are still arriving while
        # the reports print in sequence): poll the portal, don't assert
        # the instantaneous REPORT value.
        deadline = time.time() + 20.0
        pinned = None
        while time.time() < deadline:
            pinned = [_pools(ports[i])["pinned"]
                      for i in range(NUM_NODES)]
            if all(p == 0 for p in pinned):
                break
            time.sleep(0.5)
        assert all(p == 0 for p in pinned), \
            "pins stranded after quiesce: %s" % pinned

        for n in nodes:
            assert n.shutdown() == 0, "node %d unclean exit" % n.idx
    finally:
        for n in nodes:
            try:
                n.proc.kill()
            except OSError:
                pass
