"""Zero-copy pool chaos soak (ISSUE 10): SIGKILL + stale epochs + leaks.

Four `mesh_node` processes run with --desc_traffic: every node
continuously pins pool blocks under leases and posts them as one-sided
(pool_id, offset, len, crc, epoch) descriptors over the shm-ICI links.
Mid-run the soak

  * SIGKILLs one node while it holds / is entitled to read in-flight
    pinned descriptors (the peer-death reclamation path),
  * injects stale-epoch faults at one survivor's resolve seam
    (chaos_pool `pool_stale`, via its /chaos portal),
  * injects leaked-pin faults at one survivor's release seam
    (chaos_pool `pool_leak`) so the lease reaper must reclaim orphans,
  * heals and restarts the killed node.

Asserted invariants (the ISSUE-10 acceptance gate):
  * slab/lease ledger returns to baseline on every surviving node —
    pinned blocks drain to ZERO after quiesce (no leaked pins from the
    kill, the leak injection, or anything else);
  * zero lost completions: desc_issued == desc_ok + desc_failed and
    outstanding == 0 on every node;
  * injected stale-epoch descriptors fail as retriable call failures
    (client desc_stale > 0, server rpc_pool_epoch_rejects > 0) while
    the fenced node KEEPS SERVING on the same connections — never a
    crash or a wedged link;
  * the reaper reclaimed the deliberately-leaked pins
    (rpc_pool_reaped > 0);
  * clean exit 0 everywhere (Join quiesces every socket).
"""
import json
import time

from test_chaos_soak import NODE_FLAGS, Node, _chaos, _free_ports, \
    _http_get, _var

NUM_NODES = 4

# Short lease grace so the leak-injection phase's orphans become
# reapable within the soak window (default grace is 2s on top of the
# 800ms call deadline).
POOL_FLAGS = NODE_FLAGS + [
    "pool_lease_grace_ms=300",
    "pool_lease_reap_ms=100",
]


def _pools(port):
    return json.loads(_http_get(port, "/pools?format=json"))


def test_pool_chaos_soak(cpp_build, tmp_path):
    binary = cpp_build / "mesh_node"
    assert binary.exists(), "mesh_node not built"
    ports = _free_ports(NUM_NODES)
    peers_file = tmp_path / "mesh_members"
    peers_file.write_text("".join("127.0.0.1:%d\n" % p for p in ports))

    nodes = [
        Node(binary, ports[i], i, peers_file, flags=POOL_FLAGS,
             extra_args=("--desc_traffic",))
        for i in range(NUM_NODES)
    ]
    try:
        for n in nodes:
            assert n.wait_ready(), "node %d never became ready" % n.idx

        # Descriptor traffic is really flowing (lease pins being taken).
        deadline = time.time() + 20.0
        while time.time() < deadline:
            sends = sum(
                _var(p, "rpc_pool_descriptor_sends") for p in ports)
            if sends >= 20:
                break
            time.sleep(0.5)
        assert sends >= 20, "descriptor traffic never started"
        assert sum(_pools(p)["pins_total"] for p in ports) >= 20

        # --- kill a node holding in-flight pinned descriptors ---------
        kill_idx = 3
        nodes[kill_idx].kill9()
        survivors = [i for i in range(NUM_NODES) if i != kill_idx]

        # Peer death must not strand pins on the survivors: their leases
        # to the dead node resolve via EndRPC (failed call) or the
        # socket-failure ReleasePeer path; steady state returns to a
        # small in-flight transient, never a growing leak.
        deadline = time.time() + 20.0
        ok = False
        while time.time() < deadline:
            pinned = [_pools(ports[i])["pinned"] for i in survivors]
            if all(p <= 4 for p in pinned):
                ok = True
                break
            time.sleep(0.5)
        assert ok, "pins stranded after peer kill: %s" % pinned

        # --- stale-epoch injection at node 0's resolve seam -----------
        _chaos(ports[0], enable=1, seed=777, plan="pool_stale=0.5")
        deadline = time.time() + 20.0
        rejects = 0
        while time.time() < deadline:
            rejects = _var(ports[0], "rpc_pool_epoch_rejects")
            if rejects >= 3:
                break
            time.sleep(0.5)
        assert rejects >= 3, "stale-epoch fence never fired"
        # The fenced node is alive and still serving its portal + RPCs.
        assert _http_get(ports[0], "/health").strip() == "OK"

        # --- leaked-pin injection at node 1's release seam ------------
        _chaos(ports[1], enable=1, seed=778, plan="pool_leak=1")
        time.sleep(2.0)  # leak a few pins
        _chaos(ports[1], enable=0)
        deadline = time.time() + 20.0
        reaped = 0
        while time.time() < deadline:
            reaped = _var(ports[1], "rpc_pool_reaped")
            if reaped >= 1:
                break
            time.sleep(0.5)
        assert reaped >= 1, "reaper never reclaimed the leaked pins"

        # --- heal + restart the killed node ---------------------------
        _chaos(ports[0], enable=0)
        nodes[kill_idx] = Node(binary, ports[kill_idx], kill_idx,
                               peers_file, flags=POOL_FLAGS,
                               extra_args=("--desc_traffic",))
        assert nodes[kill_idx].wait_ready()
        time.sleep(4.0)  # links re-establish, fresh handshakes map pools

        # --- drain + invariants ---------------------------------------
        reports = []
        for n in nodes:
            rep = n.stop_and_report()
            assert rep is not None, "node %d produced no report" % n.idx
            reports.append(rep)

        stale_total = 0
        for rep in reports:
            # Zero lost completions on the descriptor plane (and all
            # others), and the lease ledger is EMPTY after quiesce —
            # the headline crash-safety invariant.
            assert rep["outstanding"] == 0, rep
            assert rep["desc_issued"] == (
                rep["desc_ok"] + rep["desc_failed"]), rep
            assert rep["lb_issued"] == rep["lb_ok"] + rep["lb_failed"], rep
            assert rep["shm_issued"] == rep["shm_ok"] + rep["shm_failed"], rep
            assert rep["pool_pinned"] == 0, rep
            stale_total += rep["desc_stale"]
        # Descriptor traffic did useful work on every node (incl. the
        # restarted one), and the stale injection surfaced client-side
        # as retriable call failures, not crashes.
        for rep in reports:
            assert rep["desc_ok"] > 0, rep
        assert stale_total >= 1, reports
        assert reports[0]["epoch_rejects"] >= 3, reports[0]
        # The deliberately-leaked pins were reaped, not stranded.
        assert reports[1]["pool_reaped"] >= 1, reports[1]

        # Ledger empty via the portal too (pre-shutdown, post-quiesce).
        for i in range(NUM_NODES):
            assert _pools(ports[i])["pinned"] == 0

        for n in nodes:
            assert n.shutdown() == 0, "node %d unclean exit" % n.idx
    finally:
        for n in nodes:
            try:
                n.proc.kill()
            except OSError:
                pass
