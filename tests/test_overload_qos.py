"""Mixed-tenant overload soak (ISSUE 8 capstone).

Eight `mesh_node` processes form the usual full mesh (their background
echo traffic rides the "default" tenant class), every node running the
multi-tenant QoS tier with quotas:

    bronze: qps=250 burst=50 w=1 conc=4   (the floodable class)
    gold:   unlimited qps, w=8            (the protected class)

`rpc_press` then drives node 0 twice:

  phase 1 (baseline): gold alone at its steady 200 qps -> unloaded p99;
  phase 2 (flood):    ONE mixed-tenant press where bronze floods at ~8x
                      its qps quota (>= 4x its admitted capacity) at
                      priority 1 while gold keeps its 200 qps at
                      priority 7 — plus light chaos (drop plan scoped to
                      a mesh edge away from node 0) to keep the
                      robustness machinery engaged.

Asserted isolation invariants (the acceptance criteria):
  * gold success rate stays >= 99% THROUGH the flood;
  * gold p99 stays within 2x of its unloaded baseline (noise-floored
    for the shared 1-core CI host);
  * the shed load lands on bronze: the server's per-tenant tvars
    (/tenants?format=json) show bronze absorbing >= 95% of the sheds
    and gold essentially none;
  * shed responses are the distinct retriable TERR_OVERLOAD class (the
    press counts them separately from other failures);
  * nodes still shut down cleanly (exit 0) with the QoS tier on.
"""
import json
import subprocess
import time

from test_chaos_soak import NODE_FLAGS, Node, _chaos, _free_ports, _http_get

NUM_NODES = 8

QOS_FLAGS = NODE_FLAGS + [
    "rpc_qos_enabled=true",
    "rpc_tenant_quotas=bronze:qps=250,burst=50,w=1,conc=4;gold:w=8",
    # Small fair queue so the flood exercises queueing + eviction, not
    # just the token bucket.
    "rpc_fair_queue_highwater=256",
]


def _run_press(binary, port, args, timeout=60):
    out = subprocess.run(
        [str(binary), "--server=127.0.0.1:%d" % port, "--json"] + args,
        capture_output=True, timeout=timeout, text=True,
    )
    assert out.returncode == 0, out.stderr
    for line in reversed(out.stdout.splitlines()):
        if line.startswith("{"):
            return json.loads(line)
    raise AssertionError("no json line from rpc_press:\n" + out.stdout)


def test_overload_isolation(cpp_build, tmp_path):
    node_bin = cpp_build / "mesh_node"
    press_bin = cpp_build / "rpc_press"
    assert node_bin.exists(), "mesh_node not built"
    assert press_bin.exists(), "rpc_press not built"
    ports = _free_ports(NUM_NODES)
    peers_file = tmp_path / "mesh_members"
    peers_file.write_text("".join("127.0.0.1:%d\n" % p for p in ports))

    nodes = [
        Node(node_bin, ports[i], i, peers_file, flags=QOS_FLAGS)
        for i in range(NUM_NODES)
    ]
    try:
        for n in nodes:
            assert n.wait_ready(), "node %d never became ready" % n.idx
        time.sleep(2.0)  # mesh links up, background traffic flowing

        # The QoS tier is live and the portal lists it.
        tenants_page = _http_get(ports[0], "/tenants")
        assert "multi-tenant QoS: enabled" in tenants_page, tenants_page
        assert "/tenants" in _http_get(ports[0], "/")

        # --- phase 1: unloaded gold baseline --------------------------
        # --max_retry=0 throughout: the generator must emit its raw
        # offered load (a shed that retried-with-backoff would throttle
        # the flood below the 4x-capacity bar) and every TERR_OVERLOAD
        # surfaces as a counted final shed.
        base = _run_press(press_bin, ports[0],
                          ["--tenant=gold", "--priority=7", "--qps=200",
                           "--duration_s=4", "--callers=4",
                           "--max_retry=0", "--payload=128"])
        base_sent = base["press_tenants"]["gold"]["sent"]
        base_p99 = base["press_tenants"]["gold"]["p99_us"]
        assert base_sent > 400, base  # the baseline actually ran
        assert base["press_tenants"]["gold"]["shed"] == 0, base

        # Light chaos on a mesh edge away from the press path ("under
        # chaos flags"): node 7 drops 5% of its client bytes to node 6.
        _chaos(ports[7], enable=1, seed=7007, plan="drop=0.05",
               peers="127.0.0.1:%d" % ports[6])

        # --- phase 2: bronze floods, gold must not notice -------------
        # bronze target 2000 qps = 8x its 250 qps quota (>= 4x admitted
        # capacity); gold keeps its 200 qps. One mixed press so both
        # classes share the same generator clock.
        flood = _run_press(press_bin, ports[0],
                           ["--tenants=gold:1:7,bronze:10:1", "--qps=2200",
                            "--duration_s=6", "--callers=16",
                            "--press_threads=2", "--max_retry=0",
                            "--payload=128"],
                           timeout=120)
        gold = flood["press_tenants"]["gold"]
        bronze = flood["press_tenants"]["bronze"]

        # The flood was real: bronze pushed several times its quota and
        # got shed with the distinct TERR_OVERLOAD class.
        assert bronze["sent"] + bronze["failed"] > 4 * 250 * 6 * 0.5, flood
        assert bronze["shed"] >= 500, flood

        # Isolation invariant 1: gold success rate >= 99%.
        gold_total = gold["sent"] + gold["failed"]
        assert gold_total > 600, flood  # gold kept sending through it
        success = gold["sent"] / gold_total
        assert success >= 0.99, (success, flood)

        # Isolation invariant 2: gold p99 within 2x of its unloaded
        # baseline (floored: the shared 1-core CI host makes sub-25ms
        # baselines noise — a first-come-first-served collapse would
        # blow past this by an order of magnitude).
        bound = 2 * max(base_p99, 25000)
        assert gold["p99_us"] <= bound, (gold["p99_us"], base_p99, flood)

        # Isolation invariant 3: sheds landed on bronze, not gold —
        # asserted from the SERVER's per-tenant tvars.
        tj = json.loads(_http_get(ports[0], "/tenants?format=json"))
        srv_bronze = tj["tenants"]["bronze"]
        srv_gold = tj["tenants"]["gold"]
        assert srv_bronze["shed"] >= 500, tj
        assert srv_gold["admitted"] > 0, tj
        total_shed = sum(t["shed"] for t in tj["tenants"].values())
        assert srv_bronze["shed"] >= 0.95 * total_shed, tj
        # Gold sheds are at most noise (evictions can only hit lower
        # priorities, and gold has no rate quota).
        assert srv_gold["shed"] <= max(5, 0.01 * srv_gold["admitted"]), tj

        # The labelled families feed /metrics too (one spot check; the
        # full exposition lint lives in test_metrics_lint.py).
        metrics = _http_get(ports[0], "/metrics")
        assert 'rpc_tenant_shed{tenant="bronze"}' in metrics

        # --- heal + clean drain --------------------------------------
        _chaos(ports[7], enable=0)
        for n in nodes:
            rep = n.stop_and_report()
            assert rep is not None, "node %d produced no report" % n.idx
            assert rep["outstanding"] == 0, rep
        for n in nodes:
            assert n.shutdown() == 0, "node %d unclean exit" % n.idx
    finally:
        for n in nodes:
            try:
                n.proc.kill()
            except OSError:
                pass
