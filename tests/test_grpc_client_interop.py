"""The framework's gRPC CLIENT against a REAL grpcio server.

VERDICT item: the framework must be able to CALL gRPC servers, not just
serve grpcio clients. tools/grpc_echo_client.cc drives the client stack
(Channel protocol="grpc" -> thttp/http2_client.cc) against a grpcio
server started here. Reference parity: the client half of
src/brpc/policy/http2_rpc_protocol.cpp + example/grpc_c++/client.cpp.
"""
import subprocess
import sys
from concurrent import futures
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
BUILD = REPO / "build"

grpc = pytest.importorskip("grpc")


@pytest.fixture(scope="module")
def echo_pb(tmp_path_factory):
    out = tmp_path_factory.mktemp("pb")
    subprocess.run(
        ["protoc", f"--proto_path={REPO}/tools/proto",
         f"--python_out={out}", f"{REPO}/tools/proto/bench_echo.proto"],
        check=True,
    )
    sys.path.insert(0, str(out))
    import bench_echo_pb2  # noqa: E402
    return bench_echo_pb2


@pytest.fixture(scope="module")
def grpcio_server(echo_pb):
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))

    def echo(request_bytes, context):
        req = echo_pb.EchoRequest.FromString(request_bytes)
        res = echo_pb.EchoResponse(
            send_ts_us=req.send_ts_us, payload=req.payload)
        return res.SerializeToString()

    handler = grpc.method_handlers_generic_handler(
        "benchpb.EchoService",
        {"Echo": grpc.unary_unary_rpc_method_handler(
            echo,
            request_deserializer=lambda b: b,
            response_serializer=lambda b: b,
        )},
    )
    server.add_generic_rpc_handlers((handler,))
    port = server.add_insecure_port("127.0.0.1:0")
    server.start()
    yield port
    server.stop(grace=None)


def run_client(port, *args):
    return subprocess.run(
        [str(BUILD / "grpc_echo_client"), f"127.0.0.1:{port}",
         *[str(a) for a in args]],
        capture_output=True, text=True, timeout=60,
    )


def test_cpp_client_calls_real_grpcio_server(grpcio_server):
    proc = run_client(grpcio_server, 777)
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.strip() == "OK 777 0"


def test_cpp_client_many_sequential_calls(grpcio_server):
    proc = run_client(grpcio_server, 1000, 0, 20)
    assert proc.returncode == 0, proc.stderr
    lines = proc.stdout.strip().splitlines()
    assert len(lines) == 20
    assert lines[-1] == "OK 1019 0"


def test_cpp_client_large_payload_flow_control(grpcio_server):
    """300KB payload both directions exceeds the 65535 initial windows:
    the client must chunk DATA by the send window and replenish the
    receive window for grpcio's response frames."""
    proc = run_client(grpcio_server, 5, 300 * 1024)
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.strip() == f"OK 5 {300 * 1024}"
