"""One-sided verbs chaos soak (ISSUE 18): SIGKILL mid-verb, chaos at
every verb seam, pins drain to zero.

Two pods of two `mesh_node` processes run with --verbs_traffic: every
node continuously leases REMOTE_READ/REMOTE_WRITE windows from each
link peer and round-trips patterned scatter-gather verbs through its
doorbell completion queue. Intra-pod links are shm-ICI (one-sided
capable: posts move by direct memcpy); cross-pod links are dcn-tier
channels (one-sided INCAPABLE: the same posts degrade to the emulated
two-sided wire exchange through the ISSUE-12 seam) — so both data paths
run continuously in one mesh. Mid-run the soak

  * drops posted verbs at one node's post seam (chaos `verb_drop`):
    the initiator's pending-wr reaper must retry/terminate every post,
    never losing a completion,
  * delays doorbell delivery at another node (chaos `doorbell_delay`):
    pollers park and completions arrive late but exactly once,
  * injects stale-epoch faults at a GRANTOR's wire-verb resolve seam
    (chaos `pool_stale`): initiators see TERR_STALE_EPOCH completions,
    re-grant fresh windows, and keep going while the fenced node keeps
    serving,
  * SIGKILLs a node while verbs are in flight against its windows in
    both roles (grantor of survivors' windows + initiator holding
    leases on theirs), then restarts it.

Asserted invariants (the ISSUE-18 acceptance gate):
  * zero lost verb completions: verbs_issued == verbs_ok + verbs_failed
    and outstanding == 0 on every node, pending posts 0 after drain;
  * stale injections surface as retriable completions (client
    verbs_stale > 0, grantor rpc_verbs_stale_rejects > 0) and windows
    re-grant (verbs_regrants > 0) — never a crash or a wedged CQ;
  * SIGKILL-mid-verb strands ZERO pins: /pools pinned returns to 0 on
    every survivor (windows reclaim via peer-death + lease expiry);
  * clean exit 0 everywhere.
"""
import json
import time

from test_chaos_soak import Node, _chaos, _free_ports, _http_get, _var
from test_pool_chaos_soak import POOL_FLAGS, _pools
from test_pod_partition_soak import _report

POD_SIZE = 2
NUM_NODES = 2 * POD_SIZE

# Short verb leases so final window reclamation (grantor-side pins of
# windows whose initiators stopped without closing) fits the drain poll;
# light dcn shaping so the emulated wire path is exercised, not slow.
VERB_FLAGS = POOL_FLAGS + [
    "verbs_lease_default_ms=2500",
    "dcn_emu_latency_us=200",
    "dcn_emu_mbps=400",
]


def _wait_verbs_ok(nodes, minimum, timeout=60.0, baseline=None):
    """Wait until every node's REPORT verbs_ok grew past `minimum` over
    `baseline`; returns the last reading keyed by node idx."""
    baseline = baseline or {n.idx: 0 for n in nodes}
    deadline = time.time() + timeout
    ok = {}
    while time.time() < deadline:
        ok = {n.idx: _report(n)["verbs_ok"] for n in nodes}
        if all(ok[n.idx] - baseline[n.idx] >= minimum for n in nodes):
            return ok
        time.sleep(0.5)
    return ok


def test_verbs_soak(cpp_build, tmp_path):
    binary = cpp_build / "mesh_node"
    assert binary.exists(), "mesh_node not built"
    ports = _free_ports(NUM_NODES)
    pod_a, pod_b = ports[:POD_SIZE], ports[POD_SIZE:]

    naming = tmp_path / "naming"
    naming.write_text(
        "".join("127.0.0.1:%d zone=A\n" % p for p in pod_a)
        + "".join("127.0.0.1:%d zone=B\n" % p for p in pod_b))
    dcn_a = tmp_path / "dcn_a"  # what pod A reaches over dcn: pod B
    dcn_a.write_text("".join("127.0.0.1:%d zone=B\n" % p for p in pod_b))
    dcn_b = tmp_path / "dcn_b"
    dcn_b.write_text("".join("127.0.0.1:%d zone=A\n" % p for p in pod_a))

    def _node(i):
        in_a = i < POD_SIZE
        return Node(binary, ports[i], i, naming, flags=VERB_FLAGS,
                    extra_args=("--zone", "A" if in_a else "B",
                                "--dcn_peers",
                                str(dcn_a if in_a else dcn_b),
                                "--verbs_traffic"))

    nodes = [_node(i) for i in range(NUM_NODES)]
    try:
        for n in nodes:
            assert n.wait_ready(), "node %d never became ready" % n.idx

        # --- warm-up: verbs flow on BOTH data paths -------------------
        ok0 = _wait_verbs_ok(nodes, 10)
        assert all(v >= 10 for v in ok0.values()), \
            "verb traffic never started: %s" % ok0
        assert sum(_var(p, "rpc_verbs_posted") for p in ports) > 0
        assert sum(_var(p, "rpc_verbs_bytes") for p in ports) > 0
        # The tier registry carries the new capability bits: shm-ICI is
        # one-sided with a real SGL budget, dcn is not (its posts run
        # the emulated two-sided wire path the soak also exercises).
        tiers = {t["name"]: t
                 for t in _pools(ports[0]).get("transports", [])}
        assert tiers["ici"]["one_sided"] == 1, tiers
        assert tiers["ici"]["sgl_max"] >= 4, tiers
        assert tiers["shm_xproc"]["one_sided"] == 1, tiers
        assert tiers["dcn"]["one_sided"] == 0, tiers
        assert tiers["tcp"]["one_sided"] == 0, tiers
        # Windows are live while traffic runs (leased, pinned).
        assert any(_report(n)["verbs_windows"] > 0 for n in nodes)

        # --- chaos 1: drop posted verbs at node 0's post seam ---------
        # The pending-wr reaper must retry dropped posts (or terminate
        # them retriable after the budget); progress never stops and no
        # completion is lost (checked at drain).
        _chaos(ports[0], enable=1, seed=991, plan="verb_drop=0.4")
        base = {nodes[0].idx: _report(nodes[0])["verbs_ok"]}
        ok1 = _wait_verbs_ok([nodes[0]], 5, timeout=40.0, baseline=base)
        assert ok1[0] - base[0] >= 5, \
            "no verb progress under verb_drop: %s" % ok1
        _chaos(ports[0], enable=0)

        # --- chaos 2: delay doorbells at node 1 -----------------------
        # Completions are held back 30ms: pollers park (cq_parks grows)
        # and every delayed completion still arrives exactly once.
        parks0 = _var(ports[1], "rpc_verbs_cq_parks")
        _chaos(ports[1], enable=1, seed=992,
               plan="doorbell_delay=0.6:30000")
        base = {nodes[1].idx: _report(nodes[1])["verbs_ok"]}
        ok2 = _wait_verbs_ok([nodes[1]], 5, timeout=40.0, baseline=base)
        assert ok2[1] - base[1] >= 5, \
            "no verb progress under doorbell_delay: %s" % ok2
        assert _var(ports[1], "rpc_verbs_cq_parks") > parks0, \
            "delayed doorbells never parked a poller"
        _chaos(ports[1], enable=0)

        # --- chaos 3: stale-epoch fence at a grantor's resolve seam ---
        # Node 2 (pod B) serves wire verbs for pod A's initiators over
        # dcn; pool_stale fences its resolve seam, so those initiators
        # get TERR_STALE_EPOCH completions and must re-grant.
        _chaos(ports[2], enable=1, seed=993, plan="pool_stale=0.5")
        deadline = time.time() + 30.0
        rejects = 0
        while time.time() < deadline:
            rejects = _var(ports[2], "rpc_verbs_stale_rejects")
            if rejects >= 3:
                break
            time.sleep(0.5)
        assert rejects >= 3, "stale-epoch fence never fired on verbs"
        # The fenced node is alive and still serving.
        assert _http_get(ports[2], "/health").strip() == "OK"
        # Initiators saw the stales and re-granted fresh windows.
        deadline = time.time() + 20.0
        stales = regrants = 0
        while time.time() < deadline:
            reps = [_report(nodes[i]) for i in (0, 1)]
            stales = sum(r["verbs_stale"] for r in reps)
            regrants = sum(r["verbs_regrants"] for r in reps)
            if stales >= 1 and regrants >= 1:
                break
            time.sleep(0.5)
        assert stales >= 1, "initiators never saw a stale completion"
        assert regrants >= 1, "stale windows were never re-granted"
        _chaos(ports[2], enable=0)

        # --- SIGKILL a node mid-verb ----------------------------------
        # Traffic is continuous, so the kill lands with verbs in flight
        # against node 3's windows (it grants to node 2 over shm and to
        # pod A over dcn) and with node 3 holding leases on everyone
        # else's pools.
        kill_idx = 3
        nodes[kill_idx].kill9()
        survivors = [n for n in nodes if n.idx != kill_idx]
        surv_ports = [ports[n.idx] for n in survivors]

        # Peer death must not strand pins: windows granted TO the dead
        # node reclaim via the socket-failure ReleasePeer sweep and the
        # lease reaper backstop.
        deadline = time.time() + 25.0
        ok = False
        while time.time() < deadline:
            pinned = [_pools(p)["pinned"] for p in surv_ports]
            if all(v <= 4 for v in pinned):
                ok = True
                break
            time.sleep(0.5)
        assert ok, "pins stranded after peer kill: %s" % pinned
        # Survivors keep completing verbs on their remaining links.
        base = {n.idx: _report(n)["verbs_ok"] for n in survivors}
        ok3 = _wait_verbs_ok(survivors, 5, timeout=40.0, baseline=base)
        assert all(ok3[n.idx] - base[n.idx] >= 5 for n in survivors), \
            "verb progress stopped after the kill: %s" % ok3

        # --- restart the killed node ----------------------------------
        nodes[kill_idx] = _node(kill_idx)
        assert nodes[kill_idx].wait_ready()
        ok4 = _wait_verbs_ok([nodes[kill_idx]], 5, timeout=60.0)
        assert ok4[kill_idx] >= 5, \
            "restarted node never resumed verb traffic: %s" % ok4

        # --- drain + invariants ---------------------------------------
        reports = []
        for n in nodes:
            rep = n.stop_and_report(timeout=60.0)
            assert rep is not None, "node %d produced no report" % n.idx
            reports.append(rep)

        for rep in reports:
            # Zero lost completions on the verb plane (and the
            # background planes) — the headline crash-safety invariant.
            assert rep["outstanding"] == 0, rep
            assert rep["verbs_issued"] == (
                rep["verbs_ok"] + rep["verbs_failed"]), rep
            assert rep["verbs_ok"] > 0, rep
            assert rep["verbs_pending"] == 0, rep
            assert rep["lb_issued"] == rep["lb_ok"] + rep["lb_failed"], rep
            assert rep["shm_issued"] == rep["shm_ok"] + rep["shm_failed"], \
                rep
        # The chaos phases left their evidence.
        assert sum(rep["verbs_stale"] for rep in reports) >= 1, reports
        assert sum(rep["verbs_regrants"] for rep in reports) >= 1, reports
        assert reports[2]["verbs_stale_rejects"] >= 3, reports[2]

        # Lease ledger EMPTY everywhere after quiesce: granted windows
        # expire (2.5s lease) and the reaper returns every pinned block.
        # THE acceptance gate: SIGKILL-mid-verb strands zero pins.
        deadline = time.time() + 25.0
        pinned = None
        while time.time() < deadline:
            pinned = [_pools(p)["pinned"] for p in ports]
            if all(v == 0 for v in pinned):
                break
            time.sleep(0.5)
        assert all(v == 0 for v in pinned), \
            "pins stranded after quiesce: %s" % pinned

        for n in nodes:
            assert n.shutdown() == 0, "node %d unclean exit" % n.idx
    finally:
        for n in nodes:
            try:
                n.proc.kill()
            except OSError:
                pass
