"""Unit tests for the continuous micro-batching scheduler model
(brpc_tpu/infer_sched.py, ISSUE 17) — the same membership policy
examples/infer_server.cc runs, provable here without the RPC stack."""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from brpc_tpu.infer_sched import MicroBatchScheduler, Sequence, simulate


def test_continuous_membership():
    """Finished sequences leave and waiting ones join BETWEEN steps —
    no batch-boundary barrier."""
    sched = MicroBatchScheduler(max_batch=2)
    a = Sequence(key="a", total=1)
    b = Sequence(key="b", total=3)
    sched.admit(a)
    sched.admit(b)
    rep = sched.step()
    assert set(s.key for s in rep.batch) == {"a", "b"}
    for s in rep.batch:
        s.drained = s.granted
    # `a` finished; `c` admitted mid-flight joins the very next step.
    c = Sequence(key="c", total=2)
    sched.admit(c)
    rep = sched.step()
    assert set(s.key for s in rep.batch) == {"b", "c"}


def test_priority_and_tenant_cap():
    """Gold keeps its seat; one tenant can't own the whole batch."""
    sched = MicroBatchScheduler(max_batch=2, tenant_batch_cap=1)
    for i in range(3):
        sched.admit(Sequence(key="b%d" % i, total=8, tenant="bronze",
                             priority=1))
    sched.admit(Sequence(key="gold", total=8, tenant="gold", priority=7))
    rep = sched.step()
    keys = [s.key for s in rep.batch]
    assert keys[0] == "gold", keys          # priority first
    assert len(keys) == 2, keys             # width respected
    assert sum(1 for s in rep.batch if s.tenant == "bronze") == 1, keys


def test_stall_preemption_and_resume():
    """A consumer behind its grants loses its slot (no queue growth);
    it rejoins once drained. A resumed sequence regenerates from the
    client's floor."""
    sched = MicroBatchScheduler(max_batch=1)
    slow = Sequence(key="s", total=4)
    sched.admit(slow)
    rep = sched.step()
    assert rep.batch == [slow] and slow.granted == 1
    # Not drained: the next step preempts instead of granting more.
    rep = sched.step()
    assert rep.batch == [] and rep.preempted == 1
    assert slow.granted == 1                # memory bounded, not queued
    slow.drained = slow.granted
    rep = sched.step()
    assert rep.batch == [slow] and slow.granted == 2
    # Post-restart resume: generation restarts AT the floor.
    resumed = Sequence(key="r", total=10, resume_from=7)
    assert resumed.granted == 7 and resumed.drained == 7
    sched.admit(resumed)


def test_batched_beats_unbatched():
    """The whole point: one step serves the batch, so batched tokens/s
    approaches width x the unbatched baseline."""
    batched = simulate(n_seqs=8, tokens_each=32, max_batch=8)
    serial = simulate(n_seqs=8, tokens_each=32, max_batch=8,
                      unbatched=True)
    assert batched["tokens"] == serial["tokens"] == 8 * 32
    assert batched["steps"] == 32
    assert serial["steps"] == 8 * 32
    assert batched["tokens_per_s"] >= 7 * serial["tokens_per_s"]
