"""Work-priced admission soak (ISSUE 15 capstone).

Six `mesh_node` processes form the usual full mesh, every node running
the QoS tier with COST-unit quotas and NO hand-set concurrency limits:

    bronze: qps=400 (cost units/s) burst=100 w=1   (the heavy class)
    gold:   unlimited, w=8                         (the protected class)

The attack this soak exists for: bronze floods WITHIN its request-count
rate (350 req/s < 400) but with 64KiB bodies — each request measures at
~4-6 cost units, so its offered COST is several times its quota. A
request-counting front door (PR 7) admits all of it and gold pays; the
work-priced door must shed it.

Phases:
  1 (baseline): gold alone at 200 qps, 128-byte bodies -> unloaded p99;
  2 (cost flood): ONE mixed press — gold keeps its light 200 qps at
    priority 7 while bronze floods heavy bodies at priority 1 inside
    its request rate;
  3 (chaos repricing): a `cost_inflate` chaos plan on node 0 multiplies
    bronze's MEASURED cost 20x while bronze sends light traffic — the
    admission price must follow the injected measurement.

Asserted invariants (the acceptance criteria):
  * gold success >= 99% and gold p99 <= 2x its unloaded baseline
    THROUGH the cost flood (noise-floored for the 1-core CI host);
  * bronze absorbs >= 95% of the sheds, with nonzero COST shed
    (/tenants?format=json cost columns — the machine-readable face the
    portal satellite added);
  * bronze's learned per-method estimate (cost_ewma_milli) reflects the
    heavy bodies (>= 2 units), and the chaos phase visibly reprices it;
  * per-tenant gradient concurrency CONVERGED from measurement: gold's
    gradient_limit > 0 with gradient_updates >= 1, and no conc= was
    ever configured;
  * shed responses carry a real backoff hint (press records the max
    TERR_OVERLOAD backoff_ms it saw) and the server derives its hint
    from measured rates (drain_rate/suggested_backoff_ms in json);
  * zero lost completions (REPORT outstanding == 0 on every node) and
    pins drain to 0 (pool_pinned == 0);
  * clean exit 0 everywhere with the tier on.
"""
import json
import subprocess
import time

from test_chaos_soak import NODE_FLAGS, Node, _chaos, _free_ports, \
    _http_get, _var

NUM_NODES = 6

COST_FLAGS = NODE_FLAGS + [
    "rpc_qos_enabled=true",
    # Cost-unit quotas, NO conc= anywhere: concurrency comes from each
    # tenant's gradient limiter.
    "rpc_tenant_quotas=bronze:qps=400,burst=100,w=1;gold:w=8",
    # Queue-delay shedding tuned for a seconds-scale soak.
    "rpc_queue_delay_target_ms=20",
    "rpc_queue_delay_interval_ms=100",
]


def _run_press(binary, port, args, timeout=90):
    out = subprocess.run(
        [str(binary), "--server=127.0.0.1:%d" % port, "--json"] + args,
        capture_output=True, timeout=timeout, text=True,
    )
    assert out.returncode == 0, out.stderr
    for line in reversed(out.stdout.splitlines()):
        if line.startswith("{"):
            return json.loads(line)
    raise AssertionError("no json line from rpc_press:\n" + out.stdout)


def test_cost_admission_isolation(cpp_build, tmp_path):
    node_bin = cpp_build / "mesh_node"
    press_bin = cpp_build / "rpc_press"
    assert node_bin.exists(), "mesh_node not built"
    assert press_bin.exists(), "rpc_press not built"
    ports = _free_ports(NUM_NODES)
    peers_file = tmp_path / "mesh_members"
    peers_file.write_text("".join("127.0.0.1:%d\n" % p for p in ports))

    nodes = [
        Node(node_bin, ports[i], i, peers_file, flags=COST_FLAGS)
        for i in range(NUM_NODES)
    ]
    try:
        for n in nodes:
            assert n.wait_ready(), "node %d never became ready" % n.idx
        time.sleep(2.0)  # mesh links up, background traffic flowing

        # The tier is live, and the portal leads with the cost columns.
        tenants_page = _http_get(ports[0], "/tenants")
        assert "multi-tenant QoS: enabled" in tenants_page, tenants_page
        assert "cost_adm" in tenants_page, tenants_page
        assert "drain rate" in tenants_page, tenants_page

        # --- phase 1: unloaded gold baseline --------------------------
        base = _run_press(press_bin, ports[0],
                          ["--tenant=gold", "--priority=7", "--qps=200",
                           "--duration_s=4", "--callers=4",
                           "--max_retry=0", "--body_bytes=128"])
        base_sent = base["press_tenants"]["gold"]["sent"]
        base_p99 = base["press_tenants"]["gold"]["p99_us"]
        assert base_sent > 400, base
        assert base["press_tenants"]["gold"]["shed"] == 0, base

        # --- phase 2: bronze floods COST inside its request rate ------
        # gold 200 qps x 128B (priority 7) + bronze 350 req/s x 64KiB
        # (priority 1). Bronze's request RATE is inside its 400/s
        # quota; only its measured COST (~4-6 units/req once the model
        # has samples) exceeds it.
        flood = _run_press(
            press_bin, ports[0],
            ["--tenants=gold:4:7:128,bronze:7:1:65536", "--qps=550",
             "--duration_s=6", "--callers=16", "--press_threads=2",
             "--max_retry=0"],
            timeout=150)
        gold = flood["press_tenants"]["gold"]
        bronze = flood["press_tenants"]["bronze"]

        # The flood was real and was shed on COST: bronze emitted its
        # offered request rate but the server priced it out.
        assert bronze["sent"] + bronze["failed"] > 350 * 6 * 0.5, flood
        assert bronze["shed"] >= 200, flood
        # Its shed responses carried a real backoff hint.
        assert bronze["backoff_ms_max"] >= 1, flood

        # Isolation invariant 1: gold success rate >= 99%.
        gold_total = gold["sent"] + gold["failed"]
        assert gold_total > 600, flood
        assert gold["sent"] / gold_total >= 0.99, flood

        # Isolation invariant 2: gold p99 within 2x of unloaded
        # baseline (floored for the shared 1-core CI host).
        bound = 2 * max(base_p99, 25000)
        assert gold["p99_us"] <= bound, (gold["p99_us"], base_p99, flood)

        # Server-side cost accounting (machine-readable portal).
        tj = json.loads(_http_get(ports[0], "/tenants?format=json"))
        srv_bronze = tj["tenants"]["bronze"]
        srv_gold = tj["tenants"]["gold"]
        # Sheds landed on bronze, and they were COST sheds.
        assert srv_bronze["shed"] >= 200, tj
        assert srv_bronze["cost_shed_milli"] > 0, tj
        total_shed = sum(t["shed"] for t in tj["tenants"].values())
        assert srv_bronze["shed"] >= 0.95 * total_shed, tj
        assert srv_gold["shed"] <= max(5, 0.01 * srv_gold["admitted"]), tj
        # The model LEARNED bronze's heavy shape: >= 2 cost units.
        assert srv_bronze["cost_ewma_milli"] >= 2000, tj
        bronze_ewma_after_flood = srv_bronze["cost_ewma_milli"]
        # Gold stayed cheap.
        assert srv_gold["cost_ewma_milli"] <= 2000, tj
        # Gradient concurrency converged from measurement — no conc=
        # was ever configured, yet gold runs under a live learned limit.
        assert srv_gold["max_concurrency"] == 0, tj
        assert srv_gold["gradient_limit"] > 0, tj
        assert srv_gold["gradient_updates"] >= 1, tj
        assert srv_bronze["gradient_limit"] > 0, tj
        # Queue-delay machinery is wired: measured fields present and
        # the suggested backoff respects floor/cap.
        assert tj["queue_delay_ewma_us"] >= 0, tj
        assert tj["drain_rate_cost_per_s"] >= 0, tj
        assert 1 <= tj["suggested_backoff_ms"] <= 2000, tj

        # The labelled cost families feed /metrics too (spot check; the
        # full lint lives in test_metrics_lint.py).
        metrics = _http_get(ports[0], "/metrics")
        assert 'rpc_tenant_cost_shed{tenant="bronze"}' in metrics
        assert 'rpc_tenant_gradient_limit{tenant="gold"}' in metrics

        # --- phase 3: chaos cost_inflate reprices a method ------------
        # Bronze goes LIGHT (128B ~ 1 unit measured) but the chaos plan
        # inflates every measured sample 20x: the admission price must
        # follow the measurement seam, not the wire bytes.
        _chaos(ports[0], enable=1, seed=99, plan="cost_inflate=1:20")
        _run_press(press_bin, ports[0],
                   ["--tenant=bronze", "--priority=1", "--qps=100",
                    "--duration_s=3", "--callers=4", "--max_retry=0",
                    "--body_bytes=128"])
        assert _var(ports[0], "chaos_injected_cost_inflate") > 0
        tj2 = json.loads(_http_get(ports[0], "/tenants?format=json"))
        inflated = tj2["tenants"]["bronze"]["cost_ewma_milli"]
        assert inflated >= 6000, (inflated, tj2)
        assert inflated >= bronze_ewma_after_flood, (
            inflated, bronze_ewma_after_flood)
        _chaos(ports[0], enable=0)

        # --- zero lost completions + pins drain + clean exit ----------
        for n in nodes:
            rep = n.stop_and_report()
            assert rep is not None, "node %d produced no report" % n.idx
            assert rep["outstanding"] == 0, rep
            assert rep["pool_pinned"] == 0, rep
            if n.idx == 0:
                assert rep["cost_admitted_milli"] > 0, rep
        for n in nodes:
            assert n.shutdown() == 0, "node %d unclean exit" % n.idx
    finally:
        for n in nodes:
            try:
                n.proc.kill()
            except OSError:
                pass
