"""pytest harness: builds the C++ core once per session, then runs both the
C++ unit-test binary (tests/test_cpp.py) and the Python-level tests.

JAX tests run on a virtual 8-device CPU mesh (multi-chip sharding is
validated without hardware, per the driver's dryrun_multichip contract).
"""
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
BUILD_DIR = REPO / "build"

# Force a deterministic virtual 8-device CPU platform for all JAX tests
# BEFORE jax is imported anywhere.
# Unconditional override: the environment may point JAX at a real
# accelerator (e.g. JAX_PLATFORMS=axon with one chip), but this suite is
# specified to run on the virtual 8-device CPU mesh. The env var alone is
# NOT enough: a sitecustomize may import jax before this conftest runs,
# locking the config default — pin the config explicitly so the
# accelerator backend is never initialized (its remote tunnel can hang).
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402  (after the env setup above, by design)

jax.config.update("jax_platforms", "cpu")


def _build_cpp():
    BUILD_DIR.mkdir(exist_ok=True)
    if not (BUILD_DIR / "build.ninja").exists():
        subprocess.run(
            ["cmake", "-G", "Ninja", "-S", str(REPO), "-B", str(BUILD_DIR)],
            check=True,
        )
    subprocess.run(["ninja", "-C", str(BUILD_DIR)], check=True)


@pytest.fixture(scope="session")
def cpp_build():
    _build_cpp()
    return BUILD_DIR


@pytest.fixture(scope="session")
def cpp_tests_bin(cpp_build):
    return cpp_build / "cpp_tests"
