"""Rolling-restart mesh soak (ISSUE 5 capstone): zero-downtime lifecycle.

Eight `mesh_node` processes (LB/naming plane only) serve sustained
echo traffic to each other. Every node is then restarted IN SEQUENCE
via SIGTERM with -graceful_quit_on_sigterm: the node announces a drain
(tpu_std GOAWAY on every live connection), keeps serving through its
drain window while peers steer new calls away (budget-free,
breaker-free), completes its in-flight work (Server::GracefulStop),
reports, and exits 0. A fresh incarnation takes the port back and the
peers' health checks revive their sockets.

Asserted invariants — strictly stronger than the chaos soak's
"recovered" bar:
  * ZERO failed completions, on every incarnation of every node
    (dying incarnations report before exiting; survivors at the end);
  * ZERO retry-budget tokens spent: no retries, no backups,
    rpc_retry_budget_exhausted == 0 — drain reroutes are budget-free
    by design, so a full-mesh rolling restart costs nothing;
  * every restarted node showed "draining: 1" on /status and sent
    GOAWAYs (rpc_server_drain_goaways_sent > 0);
  * graceful exit 0 for every SIGTERMed incarnation;
  * the mesh kept doing useful work throughout (total ok calls grows).
"""
import json
import signal
import time

from test_chaos_soak import Node, _free_ports, _http_get, _var

NUM_NODES = 8

FLAGS = [
    "ns_health_check_interval_ms=200",
    "graceful_quit_on_sigterm=true",
]
# --traffic_delay_ms keeps the zero-retry invariant honest: without it,
# the first node's traffic races the last node's listen() and the
# resulting connect-refusals would spend retry tokens at t=0.
EXTRA_ARGS = ("--lb_only", "--drain_ms", "1200",
              "--traffic_delay_ms", "2000")


def _wait_line(node, prefix, timeout):
    deadline = time.time() + timeout
    while True:
        line = node._readline(deadline)
        if line is None:
            return None
        if line.startswith(prefix):
            return line


def _assert_clean(rep, who):
    assert rep["outstanding"] == 0, (who, rep)
    assert rep["lb_issued"] == rep["lb_ok"] + rep["lb_failed"], (who, rep)
    assert rep["lb_failed"] == 0, (
        "%s saw failed completions during the rolling restart: %r"
        % (who, rep))
    assert rep["reissues"] == 0, (
        "%s spent retry-budget tokens (%d re-issues): %r"
        % (who, rep["reissues"], rep))
    assert rep["budget_exhausted"] == 0, (who, rep)


def test_rolling_restart_zero_downtime(cpp_build, tmp_path):
    binary = cpp_build / "mesh_node"
    assert binary.exists(), "mesh_node not built"
    ports = _free_ports(NUM_NODES)
    peers_file = tmp_path / "mesh_members"
    peers_file.write_text("".join("127.0.0.1:%d\n" % p for p in ports))

    def spawn(i):
        return Node(binary, ports[i], i, peers_file, flags=FLAGS,
                    extra_args=EXTRA_ARGS)

    nodes = [spawn(i) for i in range(NUM_NODES)]
    dying_reports = []
    try:
        for n in nodes:
            assert n.wait_ready(), "node %d never became ready" % n.idx
        time.sleep(3.5)  # traffic-start delay + steady-state warmup

        # --- restart every node in sequence, under load ---------------
        for i in range(NUM_NODES):
            n = nodes[i]
            n.proc.send_signal(signal.SIGTERM)
            assert _wait_line(n, "DRAINING", 10.0) is not None, (
                "node %d never announced its drain" % i)

            # While the node serves through its drain window, /status
            # must show the draining state and the GOAWAY broadcast
            # must be visible in /vars.
            saw_status = False
            goaways_live = 0
            deadline = time.time() + 5.0
            while time.time() < deadline:
                try:
                    status = _http_get(ports[i], "/status", timeout=1.0)
                except Exception:
                    break  # already stopped: the REPORT assert covers it
                if "draining: 1" in status:
                    saw_status = True
                    goaways_live = _var(
                        ports[i], "rpc_server_drain_goaways_sent")
                    if goaways_live > 0:
                        break
                time.sleep(0.03)
            assert saw_status, (
                "/status never showed draining: 1 on node %d" % i)

            # The dying incarnation reports after its GracefulStop:
            # nothing lost, nothing re-issued, GOAWAYs actually sent.
            line = _wait_line(n, "REPORT ", 30.0)
            assert line is not None, "node %d produced no exit report" % i
            rep = json.loads(line[len("REPORT "):])
            _assert_clean(rep, "dying node %d" % i)
            assert rep["goaways_sent"] > 0, (
                "node %d drained without sending GOAWAYs: %r" % (i, rep))
            dying_reports.append(rep)
            assert n.proc.wait(timeout=30) == 0, (
                "node %d unclean graceful exit" % i)

            # Fresh incarnation on the same port; peers' health checks
            # revive their sockets (200ms cadence) and traffic resumes.
            nodes[i] = spawn(i)
            assert nodes[i].wait_ready(), "node %d restart failed" % i
            time.sleep(1.0)

        time.sleep(1.0)  # full mesh settles after the last restart

        # --- final drain + invariants ---------------------------------
        reports = []
        for n in nodes:
            rep = n.stop_and_report()
            assert rep is not None, "node %d produced no report" % n.idx
            reports.append(rep)

        total_ok = 0
        for i, rep in enumerate(reports):
            _assert_clean(rep, "final node %d" % i)
            total_ok += rep["lb_ok"]
        for rep in dying_reports:
            total_ok += rep["lb_ok"]
        # The mesh kept serving across all eight restarts.
        assert total_ok > 200, (dying_reports, reports)
        # The drain was actually exercised client-side: peers received
        # GOAWAY notices and rerouted around draining nodes.
        notices = sum(r["drain_notices"] for r in reports + dying_reports)
        reroutes = sum(r["drain_reroutes"] for r in reports + dying_reports)
        assert notices >= 1, (dying_reports, reports)
        assert reroutes >= 1, (dying_reports, reports)

        for n in nodes:
            assert n.shutdown() == 0, "node %d unclean exit" % n.idx
    finally:
        for n in nodes:
            try:
                n.proc.kill()
            except OSError:
                pass
