"""Runs the C++ unit-test binary (all tbase/tfiber/tvar/tnet/trpc suites)."""
import subprocess


def test_cpp_unit_tests(cpp_tests_bin):
    proc = subprocess.run(
        [str(cpp_tests_bin)], capture_output=True, text=True, timeout=600
    )
    sys_out = proc.stdout + proc.stderr
    assert proc.returncode == 0, f"C++ tests failed:\n{sys_out[-8000:]}"
