"""Two-pod DCN soak (ISSUE 14): pod-aware routing, cross-pod spill,
whole-pod partition survival, clean heal.

Two mesh groups ("pods") of 3 mesh_node processes each run under mixed
load. Intra-pod traffic rides the shm-ICI links; cross-pod traffic rides
pinned dcn-tier channels (descriptor-incapable, WAN-shaped by the
-dcn_emu_* knobs) built from --dcn_peers; the LB plane resolves ONE
naming file whose entries carry zone tags, so every node's LB is the
locality-zone two-level pick. Collective traffic mixes flat global
rounds with hierarchical all-reduce (zone ring -> leader exchange over
dcn -> zone broadcast).

Phases:
  1. warm-up — cross-pod bytes flow on the dcn tier, hierarchical
     rounds complete over all 6 ranks (busbw gauge non-zero);
  2. single-node own-pod partition — ONE chaos command
     (partition_zone=A on an A-node) cuts that node from its whole own
     pod: its LB must SPILL cross-pod (rpc_lb_zone_spills fires) and
     keep completing calls via pod B;
  3. whole-pod partition — every node partitions the OTHER pod: the
     two pods run as independent meshes (collectives re-form per pod,
     nranks drops to 3), nothing is lost;
  4. heal — links re-establish, hierarchical rounds reunite at
     nranks 6.

Final invariants: zero lost completions on every plane (issued ==
ok + failed, outstanding == 0), zero collective verification failures,
spill + partition-cut counters fired where expected, re-issues stayed
budget-bounded, descriptor pins drain to 0, clean exit 0 everywhere.
"""
import json
import re
import time

from test_chaos_soak import NODE_FLAGS, Node, _chaos, _free_ports, \
    _http_get, _var

POD_SIZE = 3
NUM_NODES = 2 * POD_SIZE

POD_FLAGS = NODE_FLAGS + [
    # Light emulated WAN: enough to exercise the shaping path without
    # slowing the soak.
    "dcn_emu_latency_us=300",
    "dcn_emu_mbps=200",
    "pool_lease_grace_ms=300",
    "pool_lease_reap_ms=100",
]


def _pools(port):
    return json.loads(_http_get(port, "/pools?format=json"))


def _report(node, timeout=20.0):
    """Mid-run REPORT snapshot via the stdin 'report' command."""
    node.send("report")
    deadline = time.time() + timeout
    while True:
        line = node._readline(deadline)
        assert line is not None, "node %d: no REPORT" % node.idx
        if line.startswith("REPORT "):
            return json.loads(line[len("REPORT "):])


def _metric_re(port, pattern):
    """True when /metrics matches the regex (labelled families are not
    addressable through /vars/<name>)."""
    try:
        return re.search(pattern, _http_get(port, "/metrics"), re.M)
    except Exception:
        return None


def test_two_pod_partition_soak(cpp_build, tmp_path):
    binary = cpp_build / "mesh_node"
    assert binary.exists(), "mesh_node not built"
    ports = _free_ports(NUM_NODES)
    pod_a, pod_b = ports[:POD_SIZE], ports[POD_SIZE:]

    # One naming file for the whole front door: every entry zone-tagged.
    naming = tmp_path / "naming"
    naming.write_text(
        "".join("127.0.0.1:%d zone=A\n" % p for p in pod_a)
        + "".join("127.0.0.1:%d zone=B\n" % p for p in pod_b))
    # Per-pod peer files (shm mesh) + cross-pod dcn files.
    peers_a = tmp_path / "peers_a"
    peers_a.write_text("".join("127.0.0.1:%d zone=A\n" % p for p in pod_a))
    peers_b = tmp_path / "peers_b"
    peers_b.write_text("".join("127.0.0.1:%d zone=B\n" % p for p in pod_b))
    dcn_a = tmp_path / "dcn_a"  # what pod A reaches over dcn: pod B
    dcn_a.write_text("".join("127.0.0.1:%d zone=B\n" % p for p in pod_b))
    dcn_b = tmp_path / "dcn_b"
    dcn_b.write_text("".join("127.0.0.1:%d zone=A\n" % p for p in pod_a))

    nodes = []
    try:
        for i, p in enumerate(ports):
            in_a = i < POD_SIZE
            # --peers carries the full zone-tagged naming set (the LB
            # plane); mesh_node links shm to same-zone entries only and
            # dcn to the --dcn_peers file.
            nodes.append(Node(
                binary, p, i, naming, flags=POD_FLAGS,
                extra_args=("--zone", "A" if in_a else "B",
                            "--dcn_peers",
                            str(dcn_a if in_a else dcn_b),
                            "--coll_traffic", "--desc_traffic",
                            "--traffic_delay_ms", "1500")))
        for n in nodes:
            assert n.wait_ready(), "node %d never became ready" % n.idx

        # --- phase 1: warm-up — cross-pod traffic + hier rounds -------
        deadline = time.time() + 60.0
        warmed = False
        while time.time() < deadline:
            reps = [_report(n) for n in nodes]
            if (all(r["dcn_out_bytes"] > 0 for r in reps)
                    and all(r["coll_ok"] >= 2 for r in reps)
                    and any(r["coll_nranks"] == NUM_NODES for r in reps)):
                warmed = True
                break
            time.sleep(1.0)
        assert warmed, "cross-pod traffic/hier rounds never warmed: %s" % [
            (r["dcn_out_bytes"], r["coll_ok"], r["coll_nranks"])
            for r in reps]
        # The hierarchical busbw gauge is live on at least one node.
        assert any(
            _metric_re(p,
                       r'^rpc_collective_busbw_mbps\{alg="hier_allreduce"\}'
                       r' [1-9]')
            for p in ports), "hier busbw gauge never recorded"
        # Healthy pods never spill.
        for r in reps:
            assert r["zone"] in ("A", "B"), r
            assert r["zone_local_picks"] > 0, r

        # --- phase 2: ONE command cuts a node from its whole own pod --
        spill_idx = 2  # an A-node (not the zone leader port ordering)
        _chaos(ports[spill_idx], partition_zone="A")
        deadline = time.time() + 30.0
        spilled = False
        while time.time() < deadline:
            rep = _report(nodes[spill_idx])
            if (rep["zone_spills"] > 0 and rep["zone_partition_cuts"] > 0
                    and rep["lb_ok"] > 0):
                spilled = True
                break
            time.sleep(1.0)
        assert spilled, "own-pod partition never spilled cross-pod: %s" % rep
        # The spilling node keeps completing LB calls via pod B.
        before = _report(nodes[spill_idx])["lb_ok"]
        time.sleep(3.0)
        assert _report(nodes[spill_idx])["lb_ok"] > before, \
            "no LB progress while spilling cross-pod"
        _chaos(ports[spill_idx], partition_zone="")  # heal

        # --- phase 3: whole-pod partition -----------------------------
        for p in pod_a:
            _chaos(p, partition_zone="B")
        for p in pod_b:
            _chaos(p, partition_zone="A")
        deadline = time.time() + 60.0
        split = False
        while time.time() < deadline:
            reps = [_report(n) for n in nodes]
            # Each pod's collectives re-formed over its own 3 ranks and
            # keep completing under the partition.
            if all(r["coll_nranks"] == POD_SIZE for r in reps) and all(
                    r["zone_partition_cuts"] > 0 for r in reps):
                split = True
                break
            time.sleep(1.0)
        assert split, "pods never re-formed as independent meshes: %s" % [
            (r["coll_nranks"], r["zone_partition_cuts"]) for r in reps]
        # Both pods still make collective progress while partitioned.
        before = [_report(n)["coll_ok"] for n in nodes]
        time.sleep(4.0)
        after = [_report(n)["coll_ok"] for n in nodes]
        assert sum(after) > sum(before), (before, after)

        # --- phase 4: heal --------------------------------------------
        for p in ports:
            _chaos(p, partition_zone="")
        deadline = time.time() + 90.0
        healed = False
        while time.time() < deadline:
            reps = [_report(n) for n in nodes]
            if all(r["coll_nranks"] == NUM_NODES for r in reps):
                healed = True
                break
            time.sleep(1.0)
        assert healed, "hier rounds never reunited after heal: %s" % [
            r["coll_nranks"] for r in reps]

        # --- drain + invariants ---------------------------------------
        reports = []
        for n in nodes:
            rep = n.stop_and_report(timeout=60.0)
            assert rep is not None, "node %d produced no report" % n.idx
            reports.append(rep)

        for rep in reports:
            # Zero lost completions on every plane — the headline
            # partition-survival invariant.
            assert rep["outstanding"] == 0, rep
            assert rep["lb_issued"] == rep["lb_ok"] + rep["lb_failed"], rep
            assert rep["shm_issued"] == rep["shm_ok"] + rep["shm_failed"], \
                rep
            assert rep["coll_issued"] == rep["coll_ok"] + rep["coll_failed"], \
                rep
            assert rep["desc_issued"] == rep["desc_ok"] + rep["desc_failed"], \
                rep
            # Every completed collective round verified bit-for-bit
            # against the membership it completed over — through both
            # partitions and the heal.
            assert rep["coll_verify_failed"] == 0, rep
            assert rep["coll_ok"] > 0, rep
            # Cross-pod bytes really rode the dcn tier.
            assert rep["dcn_out_bytes"] > 0 and rep["dcn_in_bytes"] > 0, rep
            # Re-issues stayed budget-bounded: each channel's budget is
            # a 100-token burst earned back at 0.1/success — the mesh's
            # re-issue total must sit far below the unbudgeted ceiling
            # (max_retry x every failure under two partitions).
            ok_total = rep["lb_ok"] + rep["shm_ok"] + rep["desc_ok"]
            assert rep["reissues"] <= 800 + 0.3 * ok_total, rep
        # The partitioned node spilled; everyone cut the other pod.
        assert reports[spill_idx]["zone_spills"] > 0, reports[spill_idx]
        for rep in reports:
            assert rep["zone_partition_cuts"] > 0, rep

        # Descriptor pins drain to 0 everywhere (rsp pins release on
        # other nodes' acks — poll, don't read the instantaneous value).
        deadline = time.time() + 20.0
        pinned = None
        while time.time() < deadline:
            pinned = [_pools(p)["pinned"] for p in ports]
            if all(v == 0 for v in pinned):
                break
            time.sleep(0.5)
        assert all(v == 0 for v in pinned), \
            "pins stranded after quiesce: %s" % pinned

        for n in nodes:
            assert n.shutdown(timeout=60.0) == 0, \
                "node %d unclean exit" % n.idx
    finally:
        for n in nodes:
            try:
                n.proc.kill()
            except OSError:
                pass
