"""Prometheus exposition lint (ISSUE 4 satellite): boots one server,
scrapes /metrics, and checks the text-format contract in pure Python
(promtool-style):

  * metric names match [a-zA-Z_:][a-zA-Z0-9_:]*;
  * every sample's family has a preceding # TYPE line (with _sum/_count
    resolving to their summary stem), and no family declares TYPE twice;
  * summaries are well-formed: quantile-labelled samples plus _sum and
    _count, quantile values non-decreasing within a label set.

Also asserts the /vars?series= ring endpoint returns the fixed 60-point
per-second shape (the fake-clock rollover proof lives in the C++ suite).
"""
import json
import re
import time

from test_chaos_soak import Node, _free_ports, _http_get

NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{([^}]*)\})?\s+"
    r"(-?[0-9.eE+-]+|NaN|[+-]Inf)$")
LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _lint_exposition(text):
    """Returns (families, errors): families maps name -> type."""
    families = {}
    errors = []
    samples = []
    for i, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 2 and parts[1] == "TYPE":
                if len(parts) != 4:
                    errors.append("line %d: malformed TYPE: %r" % (i, line))
                    continue
                name, mtype = parts[2], parts[3]
                if not NAME_RE.match(name):
                    errors.append("line %d: bad family name %r" % (i, name))
                if mtype not in ("gauge", "counter", "summary",
                                 "histogram", "untyped"):
                    errors.append("line %d: bad type %r" % (i, mtype))
                if name in families:
                    errors.append("line %d: duplicate TYPE for %r"
                                  % (i, name))
                families[name] = mtype
            continue
        m = SAMPLE_RE.match(line)
        if not m:
            errors.append("line %d: malformed sample: %r" % (i, line))
            continue
        name, labels = m.group(1), m.group(3) or ""
        if not NAME_RE.match(name):
            errors.append("line %d: bad metric name %r" % (i, name))
        # TYPE must precede the sample, resolving summary suffixes.
        family = name
        if family not in families:
            for suffix in ("_sum", "_count"):
                stem = name[: -len(suffix)] if name.endswith(suffix) else None
                if stem and families.get(stem) == "summary":
                    family = stem
                    break
        if family not in families:
            errors.append("line %d: sample %r has no preceding TYPE"
                          % (i, name))
        samples.append((name, dict(LABEL_RE.findall(labels)),
                        m.group(4), i))
    # Summary shape: quantiles non-decreasing per label set, _sum/_count
    # present.
    for fam, mtype in families.items():
        if mtype != "summary":
            continue
        groups = {}
        has_sum = has_count = False
        for name, labels, value, i in samples:
            if name == fam + "_sum":
                has_sum = True
            if name == fam + "_count":
                has_count = True
            if name == fam and "quantile" in labels:
                key = tuple(sorted(
                    (k, v) for k, v in labels.items() if k != "quantile"))
                groups.setdefault(key, []).append(
                    (float(labels["quantile"]), float(value), i))
        if not has_sum or not has_count:
            errors.append("summary %r missing _sum/_count" % fam)
        if not groups:
            errors.append("summary %r has no quantile samples" % fam)
        for key, qs in groups.items():
            qs.sort()
            vals = [v for _, v, _ in qs]
            if any(b < a for a, b in zip(vals, vals[1:])):
                errors.append("summary %r quantiles not monotone: %r"
                              % (fam, qs))
    return families, errors


def test_metrics_exposition_lint(cpp_build, tmp_path):
    binary = cpp_build / "mesh_node"
    assert binary.exists(), "mesh_node not built"
    (port,) = _free_ports(1)
    peers_file = tmp_path / "peers"
    peers_file.write_text("127.0.0.1:%d\n" % port)
    # QoS on (ISSUE 8): the node's self-echo traffic then populates the
    # per-tenant labelled families for the lint below.
    from test_chaos_soak import NODE_FLAGS
    node = Node(binary, port, 0, peers_file,
                flags=NODE_FLAGS + ["rpc_qos_enabled=true"])
    try:
        assert node.wait_ready(), "node never became ready"
        # Let traffic + the 1Hz series sampler produce real data.
        time.sleep(2.5)

        text = _http_get(port, "/metrics")
        families, errors = _lint_exposition(text)
        assert not errors, "exposition lint failed:\n" + "\n".join(errors)
        # The method LatencyRecorder must export a REAL summary family
        # now, not flat _field gauges parsed out of JSON.
        assert families.get("benchpb_EchoService_Echo") == "summary", \
            sorted(families)
        assert "benchpb_EchoService_Echo_p50" not in families
        # Flag->var bridge: flags are scrape-able alongside metrics.
        assert families.get("flag_enable_rpcz") == "gauge", sorted(families)
        assert re.search(r"^flag_enable_rpcz [01]$", text, re.M), text[:500]
        # ISSUE 6 attribution families: dispatcher/scheduler counters as
        # labelled gauges, distributions as labelled summaries, and the
        # socket write-batch summary — all must pass the same lint.
        assert families.get("rpc_dispatcher_epoll_waits") == "gauge", \
            sorted(families)
        assert families.get("rpc_dispatcher_events") == "gauge"
        assert families.get("rpc_dispatcher_events_per_wake") == "summary"
        assert families.get("rpc_dispatcher_wake_to_dispatch_us") == \
            "summary"
        assert families.get("rpc_scheduler_steals") == "gauge"
        assert families.get("rpc_scheduler_remote_overflows") == "gauge"
        assert families.get("rpc_scheduler_urgent_handoffs") == "gauge"
        assert families.get("rpc_scheduler_runqueue_highwater") == "gauge"
        assert families.get("rpc_socket_write_batch_bytes") == "summary"
        assert re.search(
            r'^rpc_dispatcher_epoll_waits\{loop="0"\} \d+$', text, re.M), \
            text[:500]
        assert re.search(
            r'^rpc_scheduler_steals\{pool="0"\} \d+$', text, re.M)
        # ISSUE 8 multi-tenant families: per-tenant counters as labelled
        # gauges, the served-latency distribution as a labelled summary —
        # same lint, same per-tuple series rings.
        assert families.get("rpc_tenant_admitted") == "gauge", \
            sorted(families)
        assert families.get("rpc_tenant_shed") == "gauge"
        assert families.get("rpc_tenant_queued") == "gauge"
        assert families.get("rpc_tenant_latency_us") == "summary"
        assert re.search(
            r'^rpc_tenant_admitted\{tenant="default"\} \d+$', text, re.M), \
            text[:500]
        # ISSUE 15 work-priced admission families: per-tenant estimated
        # milli-cost counters, the measured per-request cost summary,
        # the gradient concurrency-limit gauge, the process-wide cost
        # totals, and the fair-queue sojourn summary — all present on a
        # qos-enabled node from its own self-echo traffic.
        assert families.get("rpc_tenant_cost_admitted") == "gauge", \
            sorted(families)
        assert families.get("rpc_tenant_cost_shed") == "gauge"
        assert families.get("rpc_tenant_cost_units") == "summary"
        assert families.get("rpc_tenant_gradient_limit") == "gauge"
        assert families.get("rpc_server_cost_admitted") == "gauge"
        assert families.get("rpc_server_cost_shed") == "gauge"
        assert families.get("rpc_server_queue_delay_us") == "summary"
        assert re.search(
            r'^rpc_tenant_cost_admitted\{tenant="default"\} \d+$', text,
            re.M), text[:500]
        # The gradient limit is a LIVE positive limit (converging from
        # the node's own traffic), not a placeholder zero.
        m = re.search(
            r'^rpc_tenant_gradient_limit\{tenant="default"\} (\d+)$',
            text, re.M)
        assert m is not None and int(m.group(1)) > 0, m
        # ISSUE 10 zero-copy crash-safety families: the pinned-block
        # lease ledger (live gauge + reclamation counters) and the
        # epoch fence — present (0-valued) even before the first pin.
        assert families.get("rpc_pool_pinned_blocks") == "gauge", \
            sorted(families)
        assert families.get("rpc_pool_lease_expired") == "gauge"
        assert families.get("rpc_pool_reaped") == "gauge"
        assert families.get("rpc_pool_peer_released") == "gauge"
        assert families.get("rpc_pool_epoch_rejects") == "gauge"
        assert re.search(r"^rpc_pool_pinned_blocks \d+$", text, re.M), \
            text[:500]
        # ISSUE 12 response-direction descriptor families: present
        # (0-valued) from the first scrape, same lint as everything else.
        for fam in ("rpc_pool_desc_rsp_sends",
                    "rpc_pool_desc_rsp_send_bytes",
                    "rpc_pool_desc_rsp_fallbacks",
                    "rpc_pool_desc_rsp_resolves",
                    "rpc_pool_desc_rsp_resolve_bytes",
                    "rpc_pool_desc_rsp_rejects",
                    "rpc_pool_desc_rsp_acks"):
            assert families.get(fam) == "gauge", (fam, sorted(families))
        # ISSUE 13 collective families: counters present (0-valued)
        # before any round, plus the per-algorithm bus-bandwidth family
        # with one series per algorithm.
        for fam in ("rpc_collective_ops", "rpc_collective_steps",
                    "rpc_collective_retries", "rpc_collective_reforms",
                    "rpc_collective_bytes",
                    "rpc_collective_desc_fallbacks"):
            assert families.get(fam) == "gauge", (fam, sorted(families))
        assert families.get("rpc_collective_busbw_mbps") == "gauge"
        # hier_allreduce: the ISSUE 14 hierarchical (zone ring -> leader
        # exchange over dcn -> broadcast) series, 0-valued before the
        # first cross-pod round.
        for alg in ("allreduce", "allgather", "alltoall",
                    "allreduce_serial", "hier_allreduce"):
            assert re.search(
                r'^rpc_collective_busbw_mbps\{alg="%s"\} \d+$' % alg,
                text, re.M), alg
        # ISSUE 18 one-sided verb families: the verb plane's counters
        # (posted/completed verbs, bytes moved, stale-epoch rejects, CQ
        # parks) and the collective verbs-lane step/fallback counters —
        # all present (0-valued, eagerly exposed) before the first post.
        for fam in ("rpc_verbs_posted", "rpc_verbs_completed",
                    "rpc_verbs_bytes", "rpc_verbs_stale_rejects",
                    "rpc_verbs_cq_parks", "rpc_collective_verb_steps",
                    "rpc_collective_verb_fallbacks"):
            assert families.get(fam) == "gauge", (fam, sorted(families))
            assert re.search(r"^%s \d+$" % fam, text, re.M), fam
        # ISSUE 19 flight-recorder families: exposed from the first
        # scrape (the recorder is always-on, so events may already be
        # non-zero from the node's own traffic; dump_count must still be
        # 0 — nothing crashed).
        for fam in ("rpc_blackbox_events", "rpc_blackbox_dropped",
                    "rpc_blackbox_ring_highwater", "rpc_flight_dump_count"):
            assert families.get(fam) == "gauge", (fam, sorted(families))
            assert re.search(r"^%s \d+$" % fam, text, re.M), fam
        assert re.search(r"^rpc_flight_dump_count 0$", text, re.M), \
            "a dump happened on a healthy node"
        # /blackbox renders in both forms; the json is the exact document
        # tools/blackbox_merge.py consumes for live nodes.
        bb = json.loads(_http_get(port, "/blackbox?format=json"))
        for key in ("node", "pid", "wall_us", "ticks_per_us", "rings"):
            assert key in bb, (key, sorted(bb))
        assert isinstance(bb["rings"], list) and bb["rings"], bb
        assert any(r["events"] for r in bb["rings"]), \
            "always-on recorder captured nothing"
        assert "flight recorder:" in _http_get(port, "/blackbox")
        # Satellite: the contention profiler page grew a machine form
        # with the same fresh-window semantics as the text view.
        cont = json.loads(
            _http_get(port, "/hotspots/contention?format=json"))
        for key in ("total_count", "total_wait_us", "other_count",
                    "sites"):
            assert key in cont, (key, sorted(cont))
        assert isinstance(cont["sites"], list), cont
        for site in cont["sites"]:
            assert set(site) == {"site", "count", "wait_us"}, site
        assert "fiber-mutex contention" in _http_get(
            port, "/hotspots/contention")
        # ISSUE 12/14 transport-tier attribution: labelled families with
        # one series per registered endpoint type, now including the
        # cross-pod dcn tier.
        for fam in ("rpc_transport_in_bytes", "rpc_transport_out_bytes",
                    "rpc_transport_desc_in_bytes",
                    "rpc_transport_desc_out_bytes",
                    "rpc_transport_credit_stalls", "rpc_transport_ops"):
            assert families.get(fam) == "gauge", (fam, sorted(families))
        for tier in ("tcp", "ici", "shm_xproc", "device", "dcn"):
            assert re.search(
                r'^rpc_transport_out_bytes\{transport="%s"\} \d+$' % tier,
                text, re.M), tier
        # ISSUE 17 resumable push-stream families: every counter present
        # (0-valued, eagerly exposed) before the first stream, plus the
        # time-to-first-token summary — and /streams renders in both
        # forms with the counters the restart soak scrapes.
        for fam in ("rpc_stream_open", "rpc_stream_resumed",
                    "rpc_stream_replayed_chunks",
                    "rpc_stream_credit_stalls", "rpc_stream_aborts"):
            assert families.get(fam) == "gauge", (fam, sorted(families))
            assert re.search(r"^%s \d+$" % fam, text, re.M), fam
        assert families.get("rpc_stream_ttft_us") == "summary", \
            sorted(families)
        # ISSUE 18 satellite: push-stream chunks are descriptor-eligible
        # on capable links — sends/fallbacks/resolves/rejects counted,
        # present 0-valued from the first scrape.
        for fam in ("rpc_stream_desc_chunks", "rpc_stream_desc_fallbacks",
                    "rpc_stream_desc_resolves", "rpc_stream_desc_rejects"):
            assert families.get(fam) == "gauge", (fam, sorted(families))
            assert re.search(r"^%s \d+$" % fam, text, re.M), fam
        streams = json.loads(_http_get(port, "/streams?format=json"))
        for key in ("open", "resumed", "replayed_chunks",
                    "credit_stalls", "aborts", "ring_highwater"):
            assert key in streams, (key, streams)
        assert isinstance(streams.get("server_streams"), list), streams
        assert "push streams" in _http_get(port, "/streams")
        # ISSUE 14 locality-zone LB: spill accounting present (0-valued)
        # before any cross-zone member exists.
        assert families.get("rpc_lb_zone_spills") == "gauge", \
            sorted(families)
        assert families.get("rpc_lb_zone_local_picks") == "gauge"
        assert re.search(r"^rpc_lb_zone_spills \d+$", text, re.M)
        # ISSUE 20 outlier-ejection families: present (0-valued, eagerly
        # exposed) from the first scrape of a healthy node — and the live
        # ejected-now gauge must actually be zero, nothing on a healthy
        # single-node mesh qualifies for ejection.
        for fam in ("rpc_outlier_ejections", "rpc_outlier_reinstatements",
                    "rpc_outlier_probe_passes", "rpc_outlier_probe_fails",
                    "rpc_outlier_eject_vetoes", "rpc_outlier_ejected_now"):
            assert families.get(fam) == "gauge", (fam, sorted(families))
            assert re.search(r"^%s \d+$" % fam, text, re.M), fam
        assert re.search(r"^rpc_outlier_ejected_now 0$", text, re.M), \
            "a healthy mesh ejected someone"
        # /outliers renders in both forms; every mesh_node runs at least
        # the naming-service LB channel, so one tracker is always live
        # and its (self) backend reports healthy.
        outl = json.loads(_http_get(port, "/outliers?format=json"))
        for key in ("trackers", "ejections", "reinstatements",
                    "ejected_now", "probe_passes", "probe_fails",
                    "eject_vetoes"):
            assert key in outl, (key, sorted(outl))
        assert isinstance(outl["trackers"], list) and outl["trackers"], \
            outl
        tr = outl["trackers"][0]
        assert isinstance(tr.get("backends"), list) and tr["backends"], tr
        assert tr["backends"][0]["state"] == "HEALTHY", tr
        assert "tracker " in _http_get(port, "/outliers")
        # /pools json carries the lease direction column + tier table
        # (dcn: descriptor-INCAPABLE cross-process byte stream).
        pools = json.loads(_http_get(port, "/pools?format=json"))
        assert isinstance(pools.get("leases"), list), pools
        tiers = {t["name"]: t for t in pools.get("transports", [])}
        assert set(tiers) >= {"tcp", "ici", "shm_xproc", "device",
                              "dcn"}, tiers
        assert tiers["tcp"]["descriptor_capable"] == 0
        assert tiers["ici"]["descriptor_capable"] == 1
        assert tiers["shm_xproc"]["cross_process"] == 1
        assert tiers["dcn"]["descriptor_capable"] == 0
        assert tiers["dcn"]["cross_process"] == 1
        # ISSUE 18 capability bits: shm-ICI tiers take one-sided verbs
        # with a real SGL budget; byte-stream tiers do not (their posts
        # run the emulated two-sided wire path).
        assert tiers["ici"]["one_sided"] == 1, tiers
        assert tiers["ici"]["sgl_max"] >= 4, tiers
        assert tiers["shm_xproc"]["one_sided"] == 1, tiers
        assert tiers["tcp"]["one_sided"] == 0, tiers
        assert tiers["dcn"]["one_sided"] == 0, tiers

        # /vars?series= returns the fixed 60/60/24-point ring shape.
        # Poll: on a loaded host the 1Hz sampler may lag a little before
        # the ring tail shows a non-zero uptime.
        deadline = time.time() + 20.0
        while True:
            ring = json.loads(
                _http_get(port, "/vars?series=process_uptime_seconds"))
            if ring["ticks"] >= 2 and ring["second"][-1] >= 1:
                break
            assert time.time() < deadline, ring
            time.sleep(0.5)
        assert len(ring["second"]) == 60, ring
        assert len(ring["minute"]) == 60
        assert len(ring["hour"]) == 24
        # Labelled families feed per-tuple rings (ISSUE 6): the loop-0
        # dispatcher counter has its own series.
        disp_ring = json.loads(
            _http_get(port, "/vars?series=rpc_dispatcher_epoll_waits_loop_0"))
        assert len(disp_ring["second"]) == 60, disp_ring
        # Unknown series 404s with guidance instead of a silent empty.
        try:
            _http_get(port, "/vars?series=no_such_series_name")
            assert False, "expected 404"
        except Exception:
            pass

        assert node.shutdown() == 0, "unclean exit"
    finally:
        try:
            node.proc.kill()
        except OSError:
            pass


def test_router_metrics_lint(cpp_build, tmp_path):
    """ISSUE 16: a live tpu_router node passes the same exposition lint
    and publishes every rpc_router_* family 0-valued from the very
    first scrape — dashboards never see a family pop into existence."""
    import subprocess

    mesh_bin = cpp_build / "mesh_node"
    router_bin = cpp_build / "tpu_router"
    assert router_bin.exists(), "tpu_router not built"
    backend_port, router_port = _free_ports(2)
    backends_file = tmp_path / "backends"
    backends_file.write_text("127.0.0.1:%d\n" % backend_port)
    backend = Node(mesh_bin, backend_port, 0, backends_file,
                   extra_args=("--lb_only", "--traffic_delay_ms",
                               "600000"))
    router = None
    try:
        assert backend.wait_ready(), "backend never became ready"
        router = subprocess.Popen(
            [str(router_bin), "--port", str(router_port),
             "--backends", str(backends_file)],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL)
        # READY handshake (same stdout contract as mesh_node).
        deadline = time.time() + 30.0
        line = b""
        while not line.startswith(b"READY"):
            assert time.time() < deadline, "router never became ready"
            line = router.stdout.readline()

        text = _http_get(router_port, "/metrics")
        families, errors = _lint_exposition(text)
        assert not errors, "router lint failed:\n" + "\n".join(errors)
        for fam in ("rpc_router_forwards", "rpc_router_forward_failures",
                    "rpc_router_hedges", "rpc_router_hedge_wins",
                    "rpc_router_reroutes", "rpc_router_session_repins",
                    "rpc_router_edge_sheds",
                    "rpc_router_hedge_refreshes"):
            assert families.get(fam) == "gauge", (fam, sorted(families))
            assert re.search(r"^%s \d+$" % fam, text, re.M), fam
        # The backend-latency recorder exports a real summary family.
        assert families.get("rpc_router_backend_latency") == "summary", \
            sorted(families)
        # /router renders in both forms and the json has the shape the
        # restart soak polls.
        state = json.loads(
            _http_get(router_port, "/router?format=json"))
        assert isinstance(state["backends"], list) and state["backends"]
        assert "sessions" in state and "hedges" in state, state
        assert "router state" in _http_get(router_port, "/router")
    finally:
        try:
            backend.proc.kill()
        except OSError:
            pass
        if router is not None:
            try:
                router.kill()
            except OSError:
                pass
