"""TLS + ALPN interop with REAL clients and servers.

The server wraps every accepted connection in a TLS transport
(cpp/tnet/tls.{h,cc}, dlopen'd libssl) with ALPN h2/http1.1 selection;
the client stack pins a TLS connection (ChannelOptions::tls). Proven
against: grpcio secure channel, curl https, and the framework's own
gRPC-over-TLS client. Reference parity:
/root/reference/src/brpc/details/ssl_helper.cpp.
"""
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
BUILD = REPO / "build"


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    d = tmp_path_factory.mktemp("certs")
    cert, key = d / "cert.pem", d / "key.pem"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(cert), "-days", "2",
         "-subj", "/CN=localhost",
         "-addext", "subjectAltName=DNS:localhost,IP:127.0.0.1"],
        check=True, capture_output=True,
    )
    return cert, key


@pytest.fixture(scope="module")
def tls_server(certs):
    cert, key = certs
    proc = subprocess.Popen(
        [str(BUILD / "echo_bench"), "--ici-server",
         "--tls-cert", str(cert), "--tls-key", str(key)],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
    )
    port = int(proc.stdout.readline().split()[1])
    yield port, cert
    proc.stdin.close()
    proc.wait(timeout=20)


def test_grpcio_secure_channel_alpn_h2(tls_server, tmp_path):
    """A real grpcio SECURE channel: TLS handshake + ALPN h2 + gRPC
    unary echo against our TLS server."""
    grpc = pytest.importorskip("grpc")
    port, cert = tls_server
    sys.path.insert(0, str(tmp_path))
    subprocess.run(
        ["protoc", f"--proto_path={REPO}/tools/proto",
         f"--python_out={tmp_path}", f"{REPO}/tools/proto/bench_echo.proto"],
        check=True,
    )
    import bench_echo_pb2
    creds = grpc.ssl_channel_credentials(
        root_certificates=cert.read_bytes())
    ch = grpc.secure_channel(
        f"localhost:{port}", creds,
        options=[("grpc.ssl_target_name_override", "localhost")])
    stub = ch.unary_unary(
        "/benchpb.EchoService/Echo",
        request_serializer=bench_echo_pb2.EchoRequest.SerializeToString,
        response_deserializer=bench_echo_pb2.EchoResponse.FromString,
    )
    res = stub(bench_echo_pb2.EchoRequest(send_ts_us=5150), timeout=20)
    assert res.send_ts_us == 5150
    ch.close()


def test_curl_https_portal(tls_server):
    """curl over https (ALPN may pick h2 or http/1.1 — both served)."""
    port, cert = tls_server
    out = subprocess.run(
        ["curl", "-sS", "--cacert", str(cert),
         f"https://localhost:{port}/health"],
        capture_output=True, text=True, timeout=30, check=True,
    )
    assert out.stdout == "OK\n"


def test_cpp_grpc_client_over_tls(tls_server):
    """The framework's own gRPC client with ChannelOptions::tls: TLS
    handshake (client side), ALPN h2, unary echo."""
    port, _ = tls_server
    proc = subprocess.run(
        [str(BUILD / "grpc_echo_client"), f"127.0.0.1:{port}", "888",
         "0", "1", "--tls"],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.strip() == "OK 888 0"
