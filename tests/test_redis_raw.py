"""Raw RESP interop: a Python client speaking the exact bytes redis-cli
would (RESP2 arrays of bulk strings) against the framework's redis
server, including a pipelined burst on one connection.

Reference parity: src/brpc/policy/redis_protocol.cpp (server side).
"""
import socket
import subprocess
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
BUILD = REPO / "build"


@pytest.fixture(scope="module")
def server():
    proc = subprocess.Popen(
        [str(BUILD / "echo_bench"), "--ici-server"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
    )
    port = int(proc.stdout.readline().split()[1])
    yield port
    proc.stdin.close()
    proc.wait(timeout=20)


def cmd(*args):
    out = b"*%d\r\n" % len(args)
    for a in args:
        b = a.encode() if isinstance(a, str) else a
        out += b"$%d\r\n%s\r\n" % (len(b), b)
    return out


def read_reply(f):
    line = f.readline()
    tag, rest = line[:1], line[1:-2]
    if tag in (b"+", b"-"):
        return tag + rest
    if tag == b":":
        return int(rest)
    if tag == b"$":
        n = int(rest)
        if n == -1:
            return None
        data = f.read(n + 2)
        return data[:-2]
    if tag == b"*":
        return [read_reply(f) for _ in range(int(rest))]
    raise AssertionError(f"bad tag {tag!r}")


def test_resp_get_set_ping(server):
    s = socket.create_connection(("127.0.0.1", server), timeout=10)
    f = s.makefile("rb")
    s.sendall(cmd("PING"))
    assert read_reply(f) == b"+PONG"
    s.sendall(cmd("SET", "color", "green"))
    assert read_reply(f) == b"+OK"
    s.sendall(cmd("GET", "color"))
    assert read_reply(f) == b"green"
    s.sendall(cmd("GET", "absent"))
    assert read_reply(f) is None
    s.sendall(cmd("WHATISTHIS"))
    assert read_reply(f).startswith(b"-ERR")
    s.close()


def test_resp_pipelined_burst_in_order(server):
    """50 commands written back-to-back before reading anything: replies
    must come back 1:1 in order (the pipelining contract)."""
    s = socket.create_connection(("127.0.0.1", server), timeout=10)
    f = s.makefile("rb")
    burst = b""
    for i in range(50):
        burst += cmd("SET", f"k{i}", f"v{i}")
    for i in range(50):
        burst += cmd("GET", f"k{i}")
    s.sendall(burst)
    for _ in range(50):
        assert read_reply(f) == b"+OK"
    for i in range(50):
        assert read_reply(f) == b"v%d" % i
    s.close()


def test_resp_binary_safe_values(server):
    blob = bytes(range(256)) * 4
    s = socket.create_connection(("127.0.0.1", server), timeout=10)
    f = s.makefile("rb")
    s.sendall(cmd("SET", "blob", blob))
    assert read_reply(f) == b"+OK"
    s.sendall(cmd("GET", "blob"))
    assert read_reply(f) == blob
    s.close()
