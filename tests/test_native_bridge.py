"""The ctypes bridge into the C++ framework (brpc_tpu/native.py).

These tests prove the Python/JAX side and the C++ side share ONE wire
implementation: tpu_std frames built here parse in C++ and vice versa
(same library), crc32c is the framework's (RFC 3720 vectors), and
staging buffers come from the registered ICI block pool.
"""
import numpy as np
import pytest

native = pytest.importorskip("brpc_tpu.native")


def test_crc32c_rfc3720_vectors():
    assert native.crc32c(b"123456789") == 0xE3069283
    assert native.crc32c(bytes(32)) == 0x8A9136AA


def test_frame_roundtrip_and_corruption():
    payload = np.arange(4096, dtype=np.uint32)
    fr = native.frame(31337, payload)
    cid, pay, consumed = native.unframe(fr)
    assert cid == 31337
    assert consumed == len(fr)
    assert np.array_equal(pay.view(np.uint32), payload)
    # Any payload bit flip must be caught by the frame's crc32c.
    bad = fr.copy()
    bad[len(bad) // 2] ^= 0x01
    with pytest.raises(ValueError):
        native.unframe(bad)
    # Truncation reads as incomplete, not corrupt.
    with pytest.raises(ValueError, match="incomplete"):
        native.unframe(fr[: len(fr) - 1])


def test_staging_buffer_is_registered_pool_memory():
    buf = native.PoolBuffer(1 << 20)
    assert buf.registered, "staging arena must come from registered regions"
    payload = np.arange(1 << 16, dtype=np.uint32)
    fr = native.frame(7, payload, out=buf.array)
    cid, pay, _ = native.unframe(fr)
    assert cid == 7 and np.array_equal(pay.view(np.uint32), payload)
    buf.free()


def test_cpp_and_python_sides_share_checksum():
    """The frame checksum the C++ side wrote must equal the framework
    crc32c computed over the payload alone — no Python re-implementation
    anywhere in the loop."""
    payload = np.frombuffer(b"framework bytes, not a stand-in!", np.uint8)
    fr = native.frame(1, payload)
    _, pay, _ = native.unframe(fr)  # raises if embedded crc32c mismatches
    assert native.crc32c(bytes(pay)) == native.crc32c(bytes(payload))
