"""Raw-speed round smoke (ISSUE 7): drives echo load through one
mesh_node with run-to-completion dispatch enabled (--inline_echo) and
asserts the hot-path machinery actually engaged:

  * /loops shows a run-to-completion section with inline dispatches > 0
    (messages processed on the input fiber) and inline handler runs > 0
    (the echo method executed without a handler fiber);
  * write coalescing deferred at least one election into a dispatch-round
    scope (rpc_socket_coalesced_writes);
  * the new raw-speed flags are documented on /flags;
  * the node still quiesces cleanly (exit 0) with the inline path on.
"""
import time

from test_chaos_soak import Node, _free_ports, _http_get


def _rtc_fields(loops_text):
    """The 'inline_dispatches: N  inline_overflows: N ...' line of /loops
    parsed into a dict."""
    for line in loops_text.splitlines():
        if "inline_dispatches:" in line:
            parts = line.replace(":", "").split()
            return {parts[i]: int(parts[i + 1])
                    for i in range(0, len(parts) - 1, 2)}
    return {}


def test_run_to_completion_smoke(cpp_build, tmp_path):
    binary = cpp_build / "mesh_node"
    assert binary.exists(), "mesh_node not built"
    (port,) = _free_ports(1)
    peers_file = tmp_path / "peers"
    peers_file.write_text("127.0.0.1:%d\n" % port)
    node = Node(binary, port, 0, peers_file, extra_args=("--inline_echo",))
    try:
        assert node.wait_ready(), "node never became ready"
        time.sleep(3.0)  # self-echo traffic through the inline path

        loops = _http_get(port, "/loops")
        rtc = _rtc_fields(loops)
        assert rtc, "no run-to-completion section on /loops:\n" + loops
        # Small self-echo frames process ON the input fiber...
        assert rtc["inline_dispatches"] > 0, loops
        # ...including the flagged echo handler itself...
        assert rtc["inline_handlers"] > 0, loops
        # ...and their responses defer into the round's coalescing scope.
        assert rtc["coalesced_writes"] > 0, loops

        # The same counters ride /vars for the series rings.
        var = _http_get(port, "/vars/rpc_dispatcher_inline_dispatches")
        assert int(var.split(":")[-1].strip()) > 0, var

        # New raw-speed knobs are self-documenting on /flags.
        flags = _http_get(port, "/flags")
        for name in ("inline_dispatch_budget", "inline_dispatch_max_bytes",
                     "event_dispatcher_affinity"):
            assert name in flags, "missing flag %s" % name

        # Dispatcher loops still healthy (blocking waits, no idle tick):
        # waits happened because traffic did, not because of a 100ms tick.
        assert "epoll_waits" in loops
        assert node.shutdown() == 0, "unclean exit with inline path on"
    finally:
        try:
            node.proc.kill()
        except OSError:
            pass
