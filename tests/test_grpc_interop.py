"""HTTP/2 + gRPC interop against REAL clients.

The h2c server path (cpp/thttp/http2_protocol.cc) is exercised by the
clients everything else in the world uses: grpcio (unary calls, status
mapping, stream multiplexing) and curl --http2-prior-knowledge (portal +
json transcoding over h2). Reference parity row: policy/
http2_rpc_protocol.cpp + grpc.{h,cpp}.
"""
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
BUILD = REPO / "build"


@pytest.fixture(scope="module")
def server():
    proc = subprocess.Popen(
        [str(BUILD / "echo_bench"), "--ici-server"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
    )
    port = int(proc.stdout.readline().split()[1])
    yield port
    proc.stdin.close()
    proc.wait(timeout=20)


@pytest.fixture(scope="module")
def echo_pb(tmp_path_factory):
    out = tmp_path_factory.mktemp("pb")
    subprocess.run(
        ["protoc", f"--proto_path={REPO}/tools/proto",
         f"--python_out={out}", f"{REPO}/tools/proto/bench_echo.proto"],
        check=True,
    )
    sys.path.insert(0, str(out))
    import bench_echo_pb2  # noqa: E402
    return bench_echo_pb2


def test_grpcio_unary_echo(server, echo_pb):
    grpc = pytest.importorskip("grpc")
    ch = grpc.insecure_channel(f"127.0.0.1:{server}")
    stub = ch.unary_unary(
        "/benchpb.EchoService/Echo",
        request_serializer=echo_pb.EchoRequest.SerializeToString,
        response_deserializer=echo_pb.EchoResponse.FromString,
    )
    res = stub(echo_pb.EchoRequest(send_ts_us=31337), timeout=15)
    assert res.send_ts_us == 31337
    ch.close()


def test_grpcio_unknown_method_unimplemented(server, echo_pb):
    grpc = pytest.importorskip("grpc")
    ch = grpc.insecure_channel(f"127.0.0.1:{server}")
    bad = ch.unary_unary(
        "/benchpb.EchoService/Nope",
        request_serializer=echo_pb.EchoRequest.SerializeToString,
        response_deserializer=echo_pb.EchoResponse.FromString,
    )
    with pytest.raises(grpc.RpcError) as err:
        bad(echo_pb.EchoRequest(), timeout=15)
    assert err.value.code() == grpc.StatusCode.UNIMPLEMENTED
    ch.close()


def test_grpcio_many_multiplexed_calls(server, echo_pb):
    grpc = pytest.importorskip("grpc")
    ch = grpc.insecure_channel(f"127.0.0.1:{server}")
    stub = ch.unary_unary(
        "/benchpb.EchoService/Echo",
        request_serializer=echo_pb.EchoRequest.SerializeToString,
        response_deserializer=echo_pb.EchoResponse.FromString,
    )
    futures = [stub.future(echo_pb.EchoRequest(send_ts_us=i), timeout=20)
               for i in range(30)]
    assert [f.result().send_ts_us for f in futures] == list(range(30))
    ch.close()


def test_curl_http2_portal_and_json_rpc(server):
    health = subprocess.run(
        ["curl", "-sS", "--http2-prior-knowledge",
         f"http://127.0.0.1:{server}/health"],
        capture_output=True, text=True, timeout=30, check=True,
    )
    assert health.stdout == "OK\n"
    echo = subprocess.run(
        ["curl", "-sS", "--http2-prior-knowledge", "-d",
         '{"send_ts_us": 4242}',
         f"http://127.0.0.1:{server}/EchoService/Echo"],
        capture_output=True, text=True, timeout=30, check=True,
    )
    assert "4242" in echo.stdout


def test_grpcio_large_payload_flow_control(server, echo_pb):
    """A 300KB response exceeds the 65535-byte initial h2 windows: the
    server's DATA path must chunk frames and park on the client's
    WINDOW_UPDATEs (the WriteResponse flow-control loop)."""
    grpc = pytest.importorskip("grpc")
    ch = grpc.insecure_channel(f"127.0.0.1:{server}")
    stub = ch.unary_unary(
        "/benchpb.EchoService/Echo",
        request_serializer=echo_pb.EchoRequest.SerializeToString,
        response_deserializer=echo_pb.EchoResponse.FromString,
    )
    blob = bytes(range(256)) * 1200  # 300KB, non-trivial content
    res = stub(echo_pb.EchoRequest(send_ts_us=7, payload=blob), timeout=30)
    assert res.payload == blob
    ch.close()


def test_curl_http2_large_json_response(server):
    """Large json body over h2c exercises DATA chunking with curl's
    flow control."""
    import base64
    import json as jsonlib
    import tempfile
    blob = b"x" * 200000
    req = jsonlib.dumps(
        {"send_ts_us": 1, "payload": base64.b64encode(blob).decode()})
    with tempfile.NamedTemporaryFile("w", suffix=".json") as f:
        f.write(req)
        f.flush()
        out = subprocess.run(
            ["curl", "-sS", "--http2-prior-knowledge", "-d", f"@{f.name}",
             f"http://127.0.0.1:{server}/EchoService/Echo"],
            capture_output=True, text=True, timeout=60, check=True,
        )
    assert len(out.stdout) > 200000
