"""Front-door router restart soak (ISSUE 16 capstone).

Six `mesh_node` backends (pure servers: traffic fibers parked) sit
behind ONE `tpu_router`. A single mixed rpc_press load — two tenants at
two priorities, four sticky sessions plus sessionless callers — drives
the router for the whole run while EVERY backend is SIGTERM-restarted
in sequence (graceful drain: GOAWAY -> serve the window -> exit 0).
One backend also gets a "delay 80 0" handler sleep so the router's
30ms hedge floor deterministically fires backup requests to a faster
peer.

Asserted invariants — the stream-preserving contract:
  * ZERO failed completions at the press (the client saw nothing), and
    ZERO forward failures at the router;
  * ZERO lost sticky sessions: at every /router?format=json poll taken
    during the restarts, every session maps to exactly one backend and
    that backend is in the json's own live set;
  * sessions actually MOVED (session_repins > 0) and the router
    re-issued around draining backends (hedges observed > 0, with
    hedge wins);
  * the retry budget was never exhausted at the router;
  * descriptor-lease pins drain to 0 by the router's final report;
  * the router itself drains gracefully: SIGTERM -> DRAINING -> final
    REPORT -> exit 0.
"""
import json
import signal
import subprocess
import time

from test_chaos_soak import Node, _free_ports, _http_get

NUM_BACKENDS = 6

BACKEND_FLAGS = [
    "ns_health_check_interval_ms=200",
    "graceful_quit_on_sigterm=true",
]
# Backends are pure servers: park the traffic fibers past the test
# horizon so every observed call came through the router.
BACKEND_ARGS = ("--lb_only", "--drain_ms", "800",
                "--traffic_delay_ms", "600000")

PRESS_DURATION_S = 32


def _wait_line(node, prefix, timeout):
    deadline = time.time() + timeout
    while True:
        line = node._readline(deadline)
        if line is None:
            return None
        if line.startswith(prefix):
            return line


class Router:
    def __init__(self, binary, port, backends_file):
        self.port = port
        self.proc = subprocess.Popen(
            [str(binary), "--port", str(port),
             "--backends", str(backends_file),
             "--drain_ms", "800",
             "--hedge_floor_ms", "30",
             "--probe_interval_ms", "100",
             "--flag", "graceful_quit_on_sigterm=true",
             "--flag", "ns_health_check_interval_ms=200",
             # Hedge provisioning: a front door that hedges a steady
             # slow-backend stream must budget for it — the default 10%
             # retry ratio is sized for failure retries, not planned
             # backups (README "Front door").
             "--flag", "rpc_retry_budget_ratio=0.5"],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
        )
        self._buf = b""

    # Reuse Node's buffered line reader / READY handshake verbatim.
    _readline = Node._readline
    wait_ready = Node.wait_ready

    def state(self):
        return json.loads(_http_get(self.port, "/router?format=json",
                                    timeout=2.0))


def _assert_sessions_consistent(state, when):
    """Every pinned session maps to exactly ONE backend, and that
    backend is live in the SAME snapshot (the atomic-re-pin contract)."""
    live = {b["endpoint"] for b in state["backends"] if b["live"]}
    if not live:
        return  # mid-restart gap with no live backend: nothing to pin to
    for sid, ep in state["sessions"].items():
        assert ep in live, (
            "session %s pinned to non-live backend %s at %s: %r"
            % (sid, ep, when, state))


def test_router_restart_soak(cpp_build, tmp_path):
    mesh_bin = cpp_build / "mesh_node"
    router_bin = cpp_build / "tpu_router"
    press_bin = cpp_build / "rpc_press"
    for b in (mesh_bin, router_bin, press_bin):
        assert b.exists(), "%s not built" % b

    ports = _free_ports(NUM_BACKENDS + 1)
    backend_ports, router_port = ports[:NUM_BACKENDS], ports[NUM_BACKENDS]
    backends_file = tmp_path / "router_backends"
    backends_file.write_text(
        "".join("127.0.0.1:%d\n" % p for p in backend_ports))

    def spawn_backend(i):
        return Node(mesh_bin, backend_ports[i], i, backends_file,
                    flags=BACKEND_FLAGS, extra_args=BACKEND_ARGS)

    backends = [spawn_backend(i) for i in range(NUM_BACKENDS)]
    router = None
    press = None
    try:
        for n in backends:
            assert n.wait_ready(), "backend %d never became ready" % n.idx
        router = Router(router_bin, router_port, backends_file)
        assert router.wait_ready(), "router never became ready"
        time.sleep(0.5)  # first probe pass marks the backends live

        # One backend serves slowly: with the 30ms hedge floor, every
        # sessionless call that lands on it overruns the hedge delay and
        # a backup try fires to a faster peer — deterministic hedging.
        backends[NUM_BACKENDS - 1].send("delay 80 0")

        press = subprocess.Popen(
            [str(press_bin),
             "--via=127.0.0.1:%d" % router_port,
             "--qps=250", "--duration_s=%d" % PRESS_DURATION_S,
             "--payload=512", "--callers=8", "--sessions=4",
             "--tenants=gold:1:7,bronze:1:1",
             "--timeout_ms=3000", "--max_retry=0", "--json"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL)
        time.sleep(2.0)  # sessions pin + hedge model warms under load

        # --- SIGTERM-restart every backend under load -----------------
        for i in range(NUM_BACKENDS):
            n = backends[i]
            n.proc.send_signal(signal.SIGTERM)
            assert _wait_line(n, "DRAINING", 10.0) is not None, (
                "backend %d never announced its drain" % i)
            # While it drains and dies, the sticky invariant must hold
            # at every observable instant.
            deadline = time.time() + 6.0
            exited = False
            while time.time() < deadline:
                _assert_sessions_consistent(router.state(),
                                            "restart of backend %d" % i)
                if n.proc.poll() is not None:
                    exited = True
                    break
                time.sleep(0.05)
            if not exited:
                assert n.proc.wait(timeout=20) is not None
            assert n.proc.returncode == 0, (
                "backend %d unclean graceful exit: %d"
                % (i, n.proc.returncode))
            backends[i] = spawn_backend(i)
            assert backends[i].wait_ready(), "backend %d restart failed" % i
            # Keep the slow-server phase alive across its own restart.
            if i == NUM_BACKENDS - 1:
                time.sleep(0.3)
                backends[i].send("delay 80 0")
            _assert_sessions_consistent(router.state(),
                                        "after restart of backend %d" % i)
            time.sleep(0.5)

        # --- the press finishes; the client saw a flawless service ----
        out, _ = press.communicate(timeout=PRESS_DURATION_S + 30)
        assert press.returncode == 0, "rpc_press failed"
        last = [l for l in out.decode().splitlines()
                if l.startswith("{")][-1]
        rep = json.loads(last)
        assert rep["press_failed"] == 0, (
            "client-visible failures through the router: %r" % rep)
        assert rep["press_qps"] > 0, rep
        assert rep["press_hedges"] > 0, (
            "router never hedged despite the slow backend: %r" % rep)
        assert rep["press_via_p99_us"] >= 0, rep

        # --- router's own accounting ----------------------------------
        state = router.state()
        _assert_sessions_consistent(state, "end of load")
        assert state["forward_failures"] == 0, state
        assert state["forwards"] > 200, state
        assert state["hedges"] > 0, state
        assert state["hedge_wins"] > 0, state
        assert state["session_repins"] > 0, (
            "no session ever moved across six backend restarts: %r"
            % state)
        assert state["budget_exhausted"] == 0, state
        assert len(state["sessions"]) == 4, state

        # --- the router itself drains gracefully ----------------------
        router.proc.send_signal(signal.SIGTERM)
        assert _wait_line(router, "DRAINING", 10.0) is not None, (
            "router never announced its drain")
        line = _wait_line(router, "REPORT ", 30.0)
        assert line is not None, "router produced no exit report"
        final = json.loads(line[len("REPORT "):])
        assert final["forward_failures"] == 0, final
        assert final["budget_exhausted"] == 0, final
        assert final["pool_pinned"] == 0, (
            "descriptor-lease pins leaked at router exit: %r" % final)
        assert router.proc.wait(timeout=30) == 0, "router unclean exit"

        for n in backends:
            assert n.shutdown() == 0, "backend %d unclean exit" % n.idx
    finally:
        for p in [router, press] + backends:
            if p is None:
                continue
            try:
                p.proc.kill() if hasattr(p, "proc") else p.kill()
            except OSError:
                pass
