"""Raw-socket HTTP/2 frame tests for server behaviors real clients don't
exercise: request trailers, SETTINGS advertisement, malformed padding.

Reference parity rows: /root/reference/src/brpc/policy/http2_rpc_protocol.cpp
(trailer handling, SETTINGS exchange), RFC 7540 §6.2/§8.1.
"""
import json
import socket
import struct
import subprocess
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
BUILD = REPO / "build"

PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"

H2_DATA = 0x0
H2_HEADERS = 0x1
H2_SETTINGS = 0x4
H2_GOAWAY = 0x7

END_STREAM = 0x1
END_HEADERS = 0x4
PADDED = 0x8


@pytest.fixture(scope="module")
def server():
    proc = subprocess.Popen(
        [str(BUILD / "echo_bench"), "--ici-server"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
    )
    port = int(proc.stdout.readline().split()[1])
    yield port
    proc.stdin.close()
    proc.wait(timeout=20)


def frame(ftype, flags, stream_id, payload=b""):
    return (struct.pack(">I", len(payload))[1:] +
            bytes([ftype, flags]) + struct.pack(">I", stream_id) + payload)


def hpack_literal(name: bytes, value: bytes) -> bytes:
    # Literal Header Field without Indexing — New Name (RFC 7541 §6.2.2),
    # no Huffman. Lengths stay under 127 in these tests.
    return b"\x00" + bytes([len(name)]) + name + bytes([len(value)]) + value


def read_frames(sock, until_stream_end=False, timeout=10):
    sock.settimeout(timeout)
    buf = b""
    frames = []
    while True:
        while len(buf) >= 9:
            length = struct.unpack(">I", b"\x00" + buf[:3])[0]
            if len(buf) < 9 + length:
                break
            ftype, flags = buf[3], buf[4]
            sid = struct.unpack(">I", buf[5:9])[0] & 0x7FFFFFFF
            frames.append((ftype, flags, sid, buf[9:9 + length]))
            buf = buf[9 + length:]
            if not until_stream_end:
                return frames
            if ftype in (H2_DATA, H2_HEADERS) and flags & END_STREAM:
                return frames
            if ftype == H2_GOAWAY:
                return frames
        try:
            chunk = sock.recv(65536)
        except socket.timeout:
            return frames
        if not chunk:
            return frames
        buf += chunk


def connect(port):
    s = socket.create_connection(("127.0.0.1", port), timeout=10)
    s.sendall(PREFACE + frame(H2_SETTINGS, 0, 0))
    return s


def req_headers(path=b"/EchoService/Echo"):
    return (hpack_literal(b":method", b"POST") +
            hpack_literal(b":scheme", b"http") +
            hpack_literal(b":path", path) +
            hpack_literal(b":authority", b"test") +
            hpack_literal(b"content-type", b"application/json"))


def test_server_settings_advertises_max_streams(server):
    s = connect(server)
    frames = read_frames(s)
    assert frames, "no SETTINGS from server"
    ftype, flags, sid, payload = frames[0]
    assert ftype == H2_SETTINGS and flags == 0 and sid == 0
    settings = {}
    for off in range(0, len(payload) - 5, 6):
        k, v = struct.unpack(">HI", payload[off:off + 6])
        settings[k] = v
    assert settings.get(0x3) == 256  # SETTINGS_MAX_CONCURRENT_STREAMS
    s.close()


def test_request_trailers_preserve_headers_and_body(server):
    """HEADERS (no END_STREAM) + DATA + trailer HEADERS (END_STREAM):
    the request must dispatch with the original headers AND the
    accumulated DATA body, not an empty body."""
    s = connect(server)
    read_frames(s)  # server SETTINGS
    body = json.dumps({"send_ts_us": 90125}).encode()
    s.sendall(frame(H2_HEADERS, END_HEADERS, 1, req_headers()))
    s.sendall(frame(H2_DATA, 0, 1, body))
    s.sendall(frame(H2_HEADERS, END_HEADERS | END_STREAM, 1,
                    hpack_literal(b"x-checksum", b"na")))
    frames = read_frames(s, until_stream_end=True)
    resp_body = b"".join(p for t, f, sid, p in frames
                         if t == H2_DATA and sid == 1)
    assert b"90125" in resp_body
    s.close()


def test_malformed_padding_is_connection_error(server):
    """A HEADERS frame whose pad length exceeds the fragment must kill
    the connection (RFC 7540 §6.2) — not desynchronize HPACK."""
    s = connect(server)
    read_frames(s)
    # PADDED flag, pad length byte says 200 but only 2 bytes follow.
    s.sendall(frame(H2_HEADERS, END_HEADERS | END_STREAM | PADDED, 1,
                    b"\xc8\x00\x00"))
    frames = read_frames(s, until_stream_end=True, timeout=5)
    # Connection must close (recv returns b"" => loop exits); any frames
    # seen must not include a normal response on stream 1.
    assert not any(t == H2_HEADERS and sid == 1 for t, f, sid, p in frames)
    s.close()


def test_stream_flood_gets_refused_not_connection_error(server):
    """Opening more concurrent streams than advertised must RST the
    excess stream (REFUSED_STREAM), leaving earlier streams usable."""
    s = connect(server)
    read_frames(s)
    # Open 257 streams without END_STREAM (they all await DATA).
    for i in range(257):
        sid = 1 + 2 * i
        s.sendall(frame(H2_HEADERS, END_HEADERS, sid, req_headers()))
    frames = read_frames(s, until_stream_end=True, timeout=5)
    rsts = [(sid, p) for t, f, sid, p in frames if t == 0x3]
    assert rsts, "expected RST_STREAM for the stream beyond the cap"
    sid, payload = rsts[0]
    assert struct.unpack(">I", payload)[0] == 0x7  # REFUSED_STREAM
    # The connection is still alive: finish stream 1 and get an echo.
    body = json.dumps({"send_ts_us": 777}).encode()
    s.sendall(frame(H2_DATA, END_STREAM, 1, body))
    frames = read_frames(s, until_stream_end=True)
    resp_body = b"".join(p for t, f, sid, p in frames
                         if t == H2_DATA and sid == 1)
    assert b"777" in resp_body
    s.close()


def test_closed_stream_id_reuse_is_connection_error(server):
    """After a stream completes and is erased server-side, HEADERS on the
    same id must be treated as a connection error (RFC 7540 §5.1.1), not
    dispatched as a fresh request."""
    s = connect(server)
    read_frames(s)
    body = json.dumps({"send_ts_us": 1}).encode()
    s.sendall(frame(H2_HEADERS, END_HEADERS, 5, req_headers()))
    s.sendall(frame(H2_DATA, END_STREAM, 5, body))
    frames = read_frames(s, until_stream_end=True)
    assert any(t == H2_DATA and sid == 5 for t, f, sid, p in frames)
    # Reopen the same id.
    s.sendall(frame(H2_HEADERS, END_HEADERS, 5, req_headers()))
    s.sendall(frame(H2_DATA, END_STREAM, 5, body))
    frames = read_frames(s, until_stream_end=True, timeout=5)
    assert not any(t == H2_DATA and sid == 5 for t, f, sid, p in frames)
    s.close()
