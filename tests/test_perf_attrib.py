"""Performance attribution surfaces (ISSUE 6): drives echo load through
one mesh_node and asserts the data-plane attribution layer is populated
and lint-clean:

  * /hotspots/heap and /hotspots/growth serve symbolized pprof-style
    text under load, and ?raw=1 is the offline-symbolizable dump
    (weighted stacks + /proc/self/maps);
  * /loops shows per-epoll-loop wake/dispatch telemetry and per-pool
    scheduler counters with non-zero activity;
  * /connections carries the per-socket I/O attribution columns
    (in/out Bps, write batches, queued-write high-water, EOVERCROWDED);
  * /status?format=json is the machine-readable MethodStatus;
  * the new prometheus families pass the exposition lint and feed
    /vars?series= rings.
"""
import json
import time

from test_chaos_soak import Node, _free_ports, _http_get
from test_metrics_lint import _lint_exposition


def _section_rows(text, header_token):
    """Rows of the /loops table whose header contains `header_token`."""
    lines = text.splitlines()
    rows = []
    in_section = False
    for line in lines:
        if header_token in line:
            in_section = True
            continue
        if in_section:
            if not line.strip():
                in_section = False
                continue
            parts = line.split()
            if parts and parts[0].isdigit():
                rows.append(parts)
    return rows


def test_perf_attribution_surfaces(cpp_build, tmp_path):
    binary = cpp_build / "mesh_node"
    assert binary.exists(), "mesh_node not built"
    (port,) = _free_ports(1)
    peers_file = tmp_path / "peers"
    peers_file.write_text("127.0.0.1:%d\n" % port)
    node = Node(binary, port, 0, peers_file)
    try:
        assert node.wait_ready(), "node never became ready"
        # Tighten the sampling interval so the node's own echo traffic
        # produces heap samples within the soak window.
        _http_get(port, "/flags/heap_profiler_sample_bytes?setvalue=8192")
        time.sleep(3.0)  # self-echo traffic + the 1Hz series sampler

        # ---- heap / growth profiler ----
        heap = _http_get(port, "/hotspots/heap")
        assert heap.startswith("heap profile:"), heap[:200]
        raw = _http_get(port, "/hotspots/heap?raw=1")
        assert "--- maps ---" in raw, raw[:200]
        stack_lines = [l for l in raw.splitlines() if " @ " in l]
        assert stack_lines, "no sampled stacks under load:\n" + raw[:400]
        # Weighted rows: "<bytes> <count> @ pc...", bytes >= count > 0.
        first = stack_lines[0].split()
        assert int(first[0]) >= int(first[1]) > 0, stack_lines[0]
        growth = _http_get(port, "/hotspots/growth")
        assert growth.startswith("growth profile:"), growth[:200]

        # ---- /loops: dispatcher + scheduler telemetry ----
        loops = _http_get(port, "/loops")
        disp = _section_rows(loops, "epoll_waits")
        assert disp, "no dispatcher rows:\n" + loops
        # Wakes and events summed ACROSS loops: sockets shard by fd, so
        # on a multi-loop host any single loop may legitimately be idle.
        assert sum(int(r[1]) for r in disp) > 0, loops
        assert sum(int(r[2]) for r in disp) > 0, loops
        pools = _section_rows(loops, "runq_highwater")
        assert pools, "no scheduler pool rows:\n" + loops
        assert int(pools[0][1]) > 0, loops  # workers

        # ---- /connections: per-socket I/O attribution ----
        header = _http_get(port, "/connections").splitlines()[0]
        for col in ("in_Bps", "out_Bps", "wr_batches", "avg_batch",
                    "q_hiwater", "crowded"):
            assert col in header, header
        time.sleep(1.0)
        rows = [l.split() for l in
                _http_get(port, "/connections").splitlines()[1:] if l]
        assert rows, "no connections under self-traffic"
        # Scrape-to-scrape rate: the self-echo peer connection moves
        # bytes, so some socket shows a non-zero in or out rate.
        assert any(float(r[5]) > 0 or float(r[6]) > 0 for r in rows), rows
        # ...and writev batching is attributed.
        assert any(int(r[7]) > 0 for r in rows), rows

        # ---- /status?format=json ----
        st = json.loads(_http_get(port, "/status?format=json"))
        assert st["draining"] == 0
        assert st["methods"], st
        method = next(iter(st["methods"].values()))
        for key in ("count", "qps", "concurrency", "errors", "rejected",
                    "expired", "shed", "latency_us"):
            assert key in method, method
        assert method["count"] > 0, st
        assert "p99" in method["latency_us"], method

        # ---- prometheus families + series rings ----
        text = _http_get(port, "/metrics")
        families, errors = _lint_exposition(text)
        assert not errors, "exposition lint failed:\n" + "\n".join(errors)
        assert families.get("rpc_dispatcher_epoll_waits") == "gauge", \
            sorted(families)
        assert families.get("rpc_dispatcher_events_per_wake") == "summary"
        assert families.get("rpc_scheduler_steals") == "gauge"
        assert families.get("rpc_scheduler_runqueue_highwater") == "gauge"
        assert families.get("rpc_socket_write_batch_bytes") == "summary"
        assert 'rpc_dispatcher_epoll_waits{loop="0"}' in text, text[:500]
        ring = json.loads(_http_get(
            port, "/vars?series=rpc_dispatcher_epoll_waits_loop_0"))
        assert len(ring["second"]) == 60, ring
        assert ring["second"][-1] > 0, ring

        assert node.shutdown() == 0, "unclean exit"
    finally:
        try:
            node.proc.kill()
        except OSError:
            pass
