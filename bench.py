#!/usr/bin/env python3
"""Benchmark driver: prints ONE JSON line.

Headline metric (mirrors the reference's headline echo benchmark,
docs/cn/benchmark.md:104 — 2.3 GB/s echo throughput on loopback): large-
payload echo throughput through the full stack over the ICI (registered
shared-memory) transport, with the cross-process shm link and loopback TCP
riding along for comparison.

Round-to-round variance on shared hosts exceeded real deltas in earlier
rounds, so every transport round now runs `REPS` times and reports the
MEDIAN (plus min/max spread for the record). Also included:
  - tail_*: the backup-request tail benchmark (reference benchmark.md:
    126-206 — 2% slow handlers; p99 with backups ≈ backup_ms + p50).
  - scale_*: qps vs caller fibers 1/4/16/64 (reference benchmark.md:110).
"""
import json
import sys
import statistics
import subprocess
from pathlib import Path

REPO = Path(__file__).resolve().parent
BUILD = REPO / "build"

BASELINE_MBPS = 2300.0  # reference echo throughput (BASELINE.md: 2.3 GB/s)
REPS = 3


def build():
    BUILD.mkdir(exist_ok=True)
    if not (BUILD / "build.ninja").exists():
        subprocess.run(
            ["cmake", "-G", "Ninja", "-S", str(REPO), "-B", str(BUILD)],
            check=True, capture_output=True,
        )
    subprocess.run(
        ["ninja", "-C", str(BUILD)], check=True, capture_output=True
    )


def run_tool(name, args, timeout=300):
    exe = BUILD / name
    if not exe.exists():
        return None
    try:
        proc = subprocess.run(
            [str(exe)] + args, capture_output=True, text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return None
    if proc.returncode != 0:
        return None
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def median_rounds(args, reps=REPS):
    """Run echo_bench `reps` times; median-combine the numeric fields."""
    runs = [r for r in (run_tool("echo_bench", args) for _ in range(reps))
            if r is not None]
    if not runs:
        return None, 0
    combined = {}
    for key in runs[0]:
        vals = [r[key] for r in runs if key in r]
        combined[key] = statistics.median(vals)
    return combined, len(runs)


def device_path():
    """Framed payloads host->HBM->host through the C++ wire path on the
    real chip (brpc_tpu/device_path.py). Subprocess + timeout: the first
    touch of a tunneled TPU backend can hang."""
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "brpc_tpu.device_path", "4", "5"],
            capture_output=True, text=True, timeout=300, cwd=str(REPO),
        )
    except subprocess.TimeoutExpired:
        return None
    if proc.returncode != 0:
        return None
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def main():
    try:
        build()
    except Exception:
        print(json.dumps({
            "metric": "echo_throughput", "value": 0, "unit": "MB/s",
            "vs_baseline": 0.0, "error": "build failed",
        }))
        return

    ici, ici_n = median_rounds(["--json", "--ici"])
    xproc, _ = median_rounds(["--json", "--xproc"])
    tcp, _ = median_rounds(["--json"])
    tcp_pooled, _ = median_rounds(["--json", "--pooled"])

    if ici is None or "mbps" not in ici:
        # Degraded fallback: loopback TCP only (tail still runs over TCP).
        tail = run_tool("echo_bench", ["--json", "--tail"], timeout=600)
        if tcp is not None and "mbps" in tcp:
            mbps = float(tcp["mbps"])
            out = {
                "metric": "echo_throughput_1MB_loopback",
                "value": round(mbps, 1), "unit": "MB/s",
                "vs_baseline": round(mbps / BASELINE_MBPS, 3),
            }
            if tail is not None:
                out.update(tail)
            print(json.dumps(out))
        else:
            print(json.dumps({
                "metric": "echo_throughput", "value": 0, "unit": "MB/s",
                "vs_baseline": 0.0, "error": "no bench tool built",
            }))
        return

    tail = run_tool("echo_bench", ["--json", "--tail"], timeout=600)
    scale = run_tool("echo_bench", ["--json", "--scale", "--ici"],
                     timeout=600)
    device = device_path()

    mbps = float(ici["mbps"])
    out = {
        "metric": "echo_throughput_1MB_ici",
        "value": round(mbps, 1),
        "unit": "MB/s",
        "vs_baseline": round(mbps / BASELINE_MBPS, 3),
        "reps": ici_n,
    }
    for k in ("qps_4k", "p50_us_4k", "p99_us_4k"):
        if k in ici:
            out["ici_" + k] = ici[k]
    for prefix, r in (("xproc_", xproc), ("tcp_", tcp),
                      ("tcp_pooled_", tcp_pooled)):
        if r is not None:
            for k in ("mbps", "qps_4k", "p99_us_4k"):
                if k in r:
                    out[prefix + k] = r[k]
    if tail is not None:
        out.update(tail)
    if scale is not None:
        out.update(scale)
    if device is not None:
        out.update(device)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
