#!/usr/bin/env python3
"""Benchmark driver: prints ONE JSON line.

Headline metric (mirrors the reference's headline echo benchmark,
docs/cn/benchmark.md:104 — 2.3 GB/s echo throughput on loopback): large-
payload echo throughput through the full stack over the ICI (registered
shared-memory) transport, with the cross-process shm link and loopback TCP
riding along for comparison.

Round-to-round variance on shared hosts exceeded real deltas in earlier
rounds, so every transport round now runs `REPS` times and reports the
MEDIAN (plus min/max spread for the record). Also included:
  - tail_*: the backup-request tail benchmark (reference benchmark.md:
    126-206 — 2% slow handlers; p99 with backups ≈ backup_ms + p50).
  - scale_*: qps vs caller fibers 1/4/16/64 (reference benchmark.md:110).
  - perf-attribution scrape (ISSUE 6): dispatcher/scheduler counters,
    /status?format=json method stats, and cpu+heap profile snapshots
    saved under profiles/ with their paths committed into the JSON so a
    regression links to evidence.

Regression gate:
  bench.py --compare BENCH_rPREV.json [--current BENCH_rCUR.json]
           [--strict] [--threshold 0.15]
prints per-metric deltas vs the previous round (running the bench first
unless --current names an existing JSON) and exits non-zero past the
threshold ONLY with --strict — the verify flow runs it non-fatal.
"""
import json
import os
import select
import socket
import sys
import statistics
import subprocess
import tempfile
import time
import urllib.request
from pathlib import Path

REPO = Path(__file__).resolve().parent
BUILD = REPO / "build"

BASELINE_MBPS = 2300.0  # reference echo throughput (BASELINE.md: 2.3 GB/s)
REPS = 3


def build():
    BUILD.mkdir(exist_ok=True)
    if not (BUILD / "build.ninja").exists():
        subprocess.run(
            ["cmake", "-G", "Ninja", "-S", str(REPO), "-B", str(BUILD)],
            check=True, capture_output=True,
        )
    subprocess.run(
        ["ninja", "-C", str(BUILD)], check=True, capture_output=True
    )


def run_tool(name, args, timeout=300):
    exe = BUILD / name
    if not exe.exists():
        return None
    try:
        proc = subprocess.run(
            [str(exe)] + args, capture_output=True, text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return None
    if proc.returncode != 0:
        return None
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def median_rounds(args, reps=REPS):
    """Run echo_bench `reps` times; median-combine the numeric fields."""
    runs = [r for r in (run_tool("echo_bench", args) for _ in range(reps))
            if r is not None]
    if not runs:
        return None, 0
    combined = {}
    for key in runs[0]:
        vals = [r[key] for r in runs if key in r]
        combined[key] = statistics.median(vals)
    return combined, len(runs)


def device_path():
    """Framed payloads host->HBM->host through the pipelined DMA staging
    ring (brpc_tpu/device_path.py, ISSUE 9): depth-4 ring, 1MB chunks,
    serial-vs-pipelined interleaved medians. Subprocess + timeout: the
    first touch of a tunneled TPU backend can hang."""
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "brpc_tpu.device_path",
             "8", "12", "4", "1020"],
            capture_output=True, text=True, timeout=300, cwd=str(REPO),
        )
    except subprocess.TimeoutExpired:
        return None
    if proc.returncode != 0:
        return None
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def perf_attrib_scrape(port):
    """ISSUE 6: scrape the performance-attribution surfaces of a node
    under load — dispatcher/scheduler families, machine-readable method
    status, and cpu+heap profile snapshots (paths land in the BENCH json
    so a regression links to evidence)."""
    out = {}
    # Sample aggressively for the snapshot window; restore the node's
    # OWN prior interval afterwards even if a scrape step dies (the cpu
    # profile fetch is the likeliest to time out).
    prev_interval = None
    try:
        flag = _http(port, "/flags/heap_profiler_sample_bytes")
        prev_interval = int(flag.split(" = ")[1].split()[0])
    except Exception:
        pass
    try:
        _http(port, "/flags/heap_profiler_sample_bytes?setvalue=16384")
        status = json.loads(_http(port, "/status?format=json"))
        methods = status.get("methods", {})
        if methods:
            name, st = sorted(methods.items())[0]
            out["status_json_method"] = name
            out["status_json_qps"] = st.get("qps", 0)
        metrics = _http(port, "/metrics")
        for family, key in (
            ("rpc_dispatcher_epoll_waits", "dispatcher_epoll_waits"),
            ("rpc_dispatcher_events", "dispatcher_events"),
            ("rpc_dispatcher_wakeups", "dispatcher_wakeups"),
            ("rpc_dispatcher_inline_dispatches", "inline_dispatches"),
            ("rpc_dispatcher_inline_overflows", "inline_overflows"),
            ("rpc_server_inline_handlers", "inline_handlers"),
            ("rpc_socket_coalesced_writes", "coalesced_writes"),
            ("rpc_scheduler_steals", "scheduler_steals"),
            ("rpc_socket_write_batch_bytes_count", "socket_write_batches"),
        ):
            total = 0.0
            for line in metrics.splitlines():
                if line.startswith(family + "{") or \
                        line.startswith(family + " "):
                    try:
                        total += float(line.rsplit(" ", 1)[1])
                    except ValueError:
                        pass
            out[key] = int(total)
        profdir = REPO / "profiles"
        profdir.mkdir(exist_ok=True)
        heap = _http(port, "/hotspots/heap?raw=1", timeout=20)
        if "--- maps ---" in heap:
            path = profdir / "bench_heap_latest.prof"
            path.write_text(heap)
            out["heap_profile_path"] = str(path.relative_to(REPO))
        cpu = _http(port, "/hotspots/cpu?seconds=1", timeout=30)
        if "cpu profile:" in cpu:
            path = profdir / "bench_cpu_latest.prof"
            path.write_text(cpu)
            out["cpu_profile_path"] = str(path.relative_to(REPO))
    except Exception:
        pass
    finally:
        if prev_interval is not None:
            try:
                _http(port, "/flags/heap_profiler_sample_bytes?setvalue=%d"
                      % prev_interval)
            except Exception:
                pass
    return out


def _http(port, path, timeout=5):
    url = "http://127.0.0.1:%d%s" % (port, path)
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode()


def _spawn_node_ready(node, port, peers, extra_args=(), timeout_s=20.0):
    """Boot one mesh_node and wait for its READY line. Returns
    (proc, ready): the caller always owns proc teardown (its finally
    reaps it whether or not READY ever arrived)."""
    proc = subprocess.Popen(
        [str(node), "--port", str(port), "--peers", str(peers)]
        + list(extra_args),
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
    )
    deadline = time.time() + timeout_s
    buf = b""
    while b"READY" not in buf:
        remain = deadline - time.time()
        if remain <= 0:
            return proc, False
        r, _, _ = select.select([proc.stdout], [], [], remain)
        if not r:
            return proc, False
        chunk = os.read(proc.stdout.fileno(), 4096)
        if not chunk:
            return proc, False
        buf += chunk
    return proc, True


def series_scrape():
    """Time-series trajectory for the BENCH record: boot one mesh_node,
    drive it with rpc_press --metrics_csv, then scrape the server's own
    /vars?series= ring — both the client-side per-second qps/p99 rows and
    the server-side 60s qps ring land in the JSON (trends, not just one
    number)."""
    node = BUILD / "mesh_node"
    press = BUILD / "rpc_press"
    if not node.exists() or not press.exists():
        return None
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    proc = None
    try:
        with tempfile.TemporaryDirectory() as td:
            peers = Path(td) / "peers"
            peers.write_text("127.0.0.1:%d\n" % port)
            csv = Path(td) / "press.csv"
            proc, ready = _spawn_node_ready(node, port, peers)
            if not ready:
                return None
            # Generator config mirrored into the BENCH record (ISSUE 7):
            # a qps number is only comparable round-to-round if the load
            # shape that produced it is pinned alongside it.
            press_cfg = {"press_gen_threads": 2, "press_gen_callers": 4,
                         "press_gen_qps": 500, "press_gen_payload": 128}
            subprocess.run(
                [str(press), "--server=127.0.0.1:%d" % port,
                 "--qps=%d" % press_cfg["press_gen_qps"],
                 "--duration_s=4",
                 "--payload=%d" % press_cfg["press_gen_payload"],
                 "--callers=%d" % press_cfg["press_gen_callers"],
                 "--press_threads=%d" % press_cfg["press_gen_threads"],
                 "--metrics_csv=%s" % csv],
                capture_output=True, timeout=60,
            )
            time.sleep(1.2)  # let the 1Hz series sampler tick once more
            url = ("http://127.0.0.1:%d/vars?series="
                   "benchpb_EchoService_Echo_qps" % port)
            with urllib.request.urlopen(url, timeout=5) as r:
                ring = json.loads(r.read().decode())
            out = perf_attrib_scrape(port)
            rows = [r for r in csv.read_text().splitlines()[1:] if r]
            if rows:
                cols = [r.split(",") for r in rows]
                out["press_qps_series"] = [int(float(c[1])) for c in cols]
                out["press_p99_us_series"] = [int(float(c[3])) for c in cols]
            second = ring.get("second", [])
            if second:
                out["server_qps_series_tail"] = [
                    int(v) for v in second[-10:]]
            # Attach the generator config only to a real scrape: a fully
            # failed one must still return None (record skipped), not a
            # metrics-free dict of press_gen_* constants.
            if out:
                out.update(press_cfg)
            return out or None
    except Exception:
        return None
    finally:
        if proc is not None:
            try:
                proc.stdin.close()
                proc.wait(timeout=10)
            except Exception:
                proc.kill()
                proc.wait()  # reap: no zombie holding the port


def _spawn_ready_argv(argv, timeout_s=20.0):
    """Boot a binary with an explicit argv and wait for its READY line
    (infer_server takes positional port + long flags, not the mesh_node
    --port/--peers shape _spawn_node_ready assumes)."""
    proc = subprocess.Popen(
        [str(a) for a in argv],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
    )
    deadline = time.time() + timeout_s
    buf = b""
    while b"READY" not in buf:
        remain = deadline - time.time()
        if remain <= 0:
            return proc, False
        r, _, _ = select.select([proc.stdout], [], [], remain)
        if not r:
            return proc, False
        chunk = os.read(proc.stdout.fileno(), 4096)
        if not chunk:
            return proc, False
        buf += chunk
    return proc, True


def _reap(proc):
    if proc is None:
        return
    try:
        proc.kill()
    except Exception:
        pass
    try:
        proc.wait(timeout=10)
    except Exception:
        pass


def infer_scrape():
    """Continuous micro-batching round (ISSUE 17): boot the
    examples/infer_server serve plane and drive it with rpc_press
    --stream_tokens through the resumable push-stream tier.

    Three phases on fresh servers:
      1. batched — tokens/s, TTFT p50/p99, inter-token p99 (the
         compared serving metrics);
      2. unbatched baseline (--unbatched: one sequence per device
         step) — same load, the deliberately-serial number the batched
         rate is read against;
      3. resume — SIGTERM + restart the server mid-stream; the presses'
         seq-contiguity assertion makes infer_stream_resume_loss a real
         exactly-once proof, and it MUST stay 0.
    """
    server = BUILD / "infer_server"
    press = BUILD / "rpc_press"
    if not server.exists() or not press.exists():
        return None

    def one_press(port, duration_s, tokens=32):
        r = subprocess.run(
            [str(press), "--server=127.0.0.1:%d" % port,
             "--stream_tokens=%d" % tokens, "--qps=400",
             "--duration_s=%d" % duration_s, "--callers=8",
             "--timeout_ms=3000", "--json"],
            capture_output=True, timeout=duration_s + 60)
        lines = [l for l in r.stdout.decode().splitlines()
                 if l.startswith("{")]
        return json.loads(lines[-1]) if lines else None

    def fresh_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    proc = None
    try:
        # --- batched serving --------------------------------------
        port = fresh_port()
        proc, ready = _spawn_ready_argv(
            [server, port, "--step_us", 2000, "--max_batch", 8])
        if not ready:
            return None
        dur = 5
        rep = one_press(port, dur)
        _reap(proc)
        proc = None
        if rep is None or rep.get("press_stream_tokens", 0) <= 0:
            return None
        out = {
            "infer_batched_tokens_per_s": int(
                rep["press_stream_tokens"] / dur),
            "infer_ttft_p50_us": int(rep["press_ttft_us"]["p50"]),
            "infer_ttft_p99_us": int(rep["press_ttft_us"]["p99"]),
            "infer_itl_p99_us": int(rep["press_itl_us"]["p99"]),
        }

        # --- unbatched baseline -----------------------------------
        port = fresh_port()
        proc, ready = _spawn_ready_argv(
            [server, port, "--step_us", 2000, "--max_batch", 8,
             "--unbatched"])
        if ready:
            urep = one_press(port, dur)
            if urep is not None and \
                    urep.get("press_stream_tokens", 0) > 0:
                ups = int(urep["press_stream_tokens"] / dur)
                out["infer_unbatched_tokens_per_s"] = ups
                if ups > 0:
                    out["infer_batch_ratio"] = round(
                        out["infer_batched_tokens_per_s"] / ups, 2)
        _reap(proc)
        proc = None

        # --- restart mid-stream: exactly-once across the resume ---
        port = fresh_port()
        proc, ready = _spawn_ready_argv(
            [server, port, "--step_us", 2000, "--max_batch", 8])
        if ready:
            pp = subprocess.Popen(
                [str(press), "--server=127.0.0.1:%d" % port,
                 "--stream_tokens=64", "--qps=8", "--duration_s=8",
                 "--callers=4", "--timeout_ms=3000", "--json"],
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL)
            time.sleep(3.0)  # streams in flight
            _reap(proc)
            proc, ready = _spawn_ready_argv(
                [server, port, "--step_us", 2000, "--max_batch", 8])
            pout, _ = pp.communicate(timeout=90)
            lines = [l for l in pout.decode().splitlines()
                     if l.startswith("{")]
            if ready and lines:
                rrep = json.loads(lines[-1])
                out["infer_stream_resumes"] = int(
                    rrep.get("press_stream_resumes", 0))
                # Lost/duplicated/corrupt tokens across the restart:
                # the acceptance gate — MUST stay 0.
                out["infer_stream_resume_loss"] = int(
                    rrep.get("press_stream_seq_errors", 0))
        return out
    except Exception:
        return None
    finally:
        _reap(proc)


class _CollNode:
    """One mesh_node handle for the collective round: line-buffered
    stdout reads (READY / COLL lines) + stdin commands."""

    def __init__(self, binary, port, peers, extra=()):
        self.proc = subprocess.Popen(
            [str(binary), "--port", str(port), "--peers", str(peers),
             "--collective"] + list(extra),
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
        )
        self.buf = b""

    def readline(self, deadline):
        while b"\n" not in self.buf:
            remain = deadline - time.time()
            if remain <= 0:
                return None
            r, _, _ = select.select([self.proc.stdout], [], [], remain)
            if not r:
                return None
            chunk = os.read(self.proc.stdout.fileno(), 4096)
            if not chunk:
                return None
            self.buf += chunk
        line, self.buf = self.buf.split(b"\n", 1)
        return line.decode()

    def wait_ready(self, timeout=20.0):
        deadline = time.time() + timeout
        while True:
            line = self.readline(deadline)
            if line is None:
                return False
            if line.startswith("READY"):
                return True

    def send(self, line):
        self.proc.stdin.write(line.encode() + b"\n")
        self.proc.stdin.flush()

    def coll_line(self, deadline):
        while True:
            line = self.readline(deadline)
            if line is None:
                return None
            if line.startswith("COLL "):
                return json.loads(line[5:])


def collective_scrape():
    """ISSUE 13: pod-scale collectives on the 8-process mesh. Drives
    chunked-pipelined all-reduce / all-gather / all-to-all rounds (and
    the serial unpipelined all-reduce baseline) through the mesh_node
    collective driver and records per-algorithm bus bandwidth — the
    busbw of a round is the SLOWEST node's (the collective is only done
    when everyone is), and the headline acceptance ratio is pipelined
    all-reduce vs the serial fan-in measured by the same driver."""
    node = BUILD / "mesh_node"
    if not node.exists():
        return None
    num = 8
    socks, ports = [], []
    for _ in range(num):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    nodes = []
    try:
        with tempfile.TemporaryDirectory() as td:
            peers = Path(td) / "peers"
            peers.write_text("".join("127.0.0.1:%d\n" % p for p in ports))
            # Append one at a time: a spawn failure mid-list must leave
            # the already-started nodes in `nodes` for the finally reap.
            for p in ports:
                nodes.append(_CollNode(node, p, peers))
            for n in nodes:
                if not n.wait_ready():
                    return None
            time.sleep(2.0)  # shm links + pool handshakes

            seq = [10]  # command rounds share one increasing seq space

            def round_once(alg, nbytes):
                seq[0] += 1
                for n in nodes:
                    n.send("coll %s %d %d" % (alg, nbytes, seq[0]))
                deadline = time.time() + 90.0
                reps = [n.coll_line(deadline) for n in nodes]
                if any(r is None or not r.get("ok") or
                       not r.get("verified") for r in reps):
                    return None
                return reps

            def busbw(alg, nbytes, reps=REPS):
                vals, fallbacks = [], 0
                for _ in range(reps):
                    rs = round_once(alg, nbytes)
                    if rs is None:
                        return None, fallbacks
                    vals.append(min(r["busbw_mbps"] for r in rs))
                    fallbacks += sum(
                        r.get("desc_fallback_chunks", 0) for r in rs)
                return statistics.median(vals), fallbacks

            out = {}
            ar, ar_fb = busbw("allreduce", 4 << 20)
            ag, ag_fb = busbw("allgather", 512 << 10)
            a2a, a2a_fb = busbw("alltoall", 256 << 10)
            serial, _ = busbw("allreduce_serial", 4 << 20)
            if ar is None:
                return None
            out["coll_allreduce_busbw_mbps"] = round(ar, 1)
            if ag is not None:
                out["coll_allgather_busbw_mbps"] = round(ag, 1)
            if a2a is not None:
                out["coll_alltoall_busbw_mbps"] = round(a2a, 1)
            if serial is not None and serial > 0:
                out["coll_allreduce_serial_mbps"] = round(serial, 1)
                # The acceptance gate: chunked-pipelined >= 1.5x serial.
                out["coll_allreduce_pipeline_ratio"] = round(
                    ar / serial, 2)
            out["coll_nranks"] = num
            # Zero inline payload bytes on the descriptor path (the
            # serial baseline is inline BY DESIGN and never attempts
            # descriptors, so it cannot contribute fallbacks).
            out["coll_zero_inline"] = int(
                ar_fb + ag_fb + a2a_fb == 0)
            return out
    except Exception:
        return None
    finally:
        for n in nodes:
            try:
                n.proc.stdin.close()
                n.proc.wait(timeout=10)
            except Exception:
                try:
                    n.proc.kill()
                    n.proc.wait()
                except Exception:
                    pass


def dcn_collective_scrape():
    """ISSUE 14: hierarchical vs flat all-reduce on an emulated-DCN
    two-pod topology. Two mesh groups of 3 nodes; intra-pod links are
    shm, cross-pod links dcn-tier with -dcn_emu_* WAN shaping (10 ms +
    25 MB/s per connection, both directions — a real cross-DC RTT class). The flat ring drags every
    boundary-crossing step through the emulated WAN (per-step latency x
    2(N-1) steps + the full reduced volume over the boundary edges);
    the hierarchical composition crosses it once per leader — the
    acceptance gate is hier busbw >= flat on this topology
    (coll_hier_vs_flat_ratio >= 1.0)."""
    node = BUILD / "mesh_node"
    if not node.exists():
        return None
    pod = 3
    socks, ports = [], []
    for _ in range(2 * pod):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    pod_a, pod_b = ports[:pod], ports[pod:]
    nodes = []
    try:
        with tempfile.TemporaryDirectory() as td:
            naming = Path(td) / "naming"
            naming.write_text(
                "".join("127.0.0.1:%d zone=A\n" % p for p in pod_a)
                + "".join("127.0.0.1:%d zone=B\n" % p for p in pod_b))
            dcn_a = Path(td) / "dcn_a"
            dcn_a.write_text(
                "".join("127.0.0.1:%d zone=B\n" % p for p in pod_b))
            dcn_b = Path(td) / "dcn_b"
            dcn_b.write_text(
                "".join("127.0.0.1:%d zone=A\n" % p for p in pod_a))
            shaping = ["--flag", "dcn_emu_latency_us=10000",
                       "--flag", "dcn_emu_mbps=25"]
            for i, p in enumerate(ports):
                in_a = i < pod
                nodes.append(_CollNode(
                    node, p, naming,
                    extra=["--zone", "A" if in_a else "B",
                           "--dcn_peers",
                           str(dcn_a if in_a else dcn_b)] + shaping))
            for n in nodes:
                if not n.wait_ready():
                    return None
            time.sleep(3.0)  # shm + probed dcn links

            seq = [500]

            def round_once(alg, nbytes):
                seq[0] += 1
                for n in nodes:
                    n.send("coll %s %d %d" % (alg, nbytes, seq[0]))
                deadline = time.time() + 120.0
                reps = [n.coll_line(deadline) for n in nodes]
                if any(r is None or not r.get("ok") or
                       not r.get("verified") or
                       r.get("nranks") != 2 * pod for r in reps):
                    return None
                return min(r["busbw_mbps"] for r in reps)

            def busbw(alg, nbytes, reps=3):
                vals = []
                for _ in range(reps):
                    v = round_once(alg, nbytes)
                    if v is None:
                        return None
                    vals.append(v)
                return statistics.median(vals)

            # 512 KiB: large enough that bandwidth matters, small
            # enough that the flat ring's 2(N-1) latency-synchronized
            # steps dominate over CPU noise on small containers — the
            # regime the hierarchical composition exists for.
            payload = 512 << 10
            flat = busbw("allreduce", payload)
            hier = busbw("hier_allreduce", payload)
            if flat is None or hier is None:
                return None
            out = {
                "coll_flat_dcn_allreduce_busbw_mbps": round(flat, 1),
                "coll_hier_allreduce_busbw_mbps": round(hier, 1),
                "coll_hier_vs_flat_ratio": round(hier / flat, 2)
                if flat > 0 else 0.0,
                "coll_dcn_pods": 2,
            }
            return out
    except Exception:
        return None
    finally:
        for n in nodes:
            try:
                n.proc.stdin.close()
                n.proc.wait(timeout=10)
            except Exception:
                try:
                    n.proc.kill()
                    n.proc.wait()
                except Exception:
                    pass


def verbs_scrape():
    """ISSUE 18: verbs-backed collective exchange vs per-chunk RPCs on
    the same mesh. Four --collective nodes; commanded rounds are lane-
    pinned by alg name — `allreduce_verbs` posts ONE scatter-gather
    REMOTE_WRITE per ring step into the successor's leased pool window
    (plus a sync doorbell), `allreduce_chunks` forces the per-chunk
    descriptor-RPC exchange the verbs lane replaces. The recorded
    ratio is the acceptance gate (>= 1.0: one SGL verb per step must
    not be slower than N chunk RPCs), and the verbs rounds' zero-
    fallback counter proves the lane really ran one-sided instead of
    silently degrading to the chunk path."""
    node = BUILD / "mesh_node"
    if not node.exists():
        return None
    num = 4
    socks, ports = [], []
    for _ in range(num):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    nodes = []
    try:
        with tempfile.TemporaryDirectory() as td:
            peers = Path(td) / "peers"
            peers.write_text("".join("127.0.0.1:%d\n" % p for p in ports))
            for p in ports:
                nodes.append(_CollNode(node, p, peers))
            for n in nodes:
                if not n.wait_ready():
                    return None
            time.sleep(2.0)  # shm links + pool handshakes

            seq = [400]  # distinct command-seq space from other rounds

            def round_once(alg, nbytes):
                seq[0] += 1
                for n in nodes:
                    n.send("coll %s %d %d" % (alg, nbytes, seq[0]))
                deadline = time.time() + 90.0
                reps = [n.coll_line(deadline) for n in nodes]
                if any(r is None or not r.get("ok") or
                       not r.get("verified") for r in reps):
                    return None
                return reps

            def busbw(alg, nbytes, reps=REPS):
                """Median-of-reps slowest-node busbw + the verb lane's
                step/fallback evidence summed over every round."""
                vals, steps, fallbacks = [], 0, 0
                for _ in range(reps):
                    rs = round_once(alg, nbytes)
                    if rs is None:
                        return None, steps, fallbacks
                    vals.append(min(r["busbw_mbps"] for r in rs))
                    steps += sum(r.get("verb_steps", 0) for r in rs)
                    fallbacks += sum(
                        r.get("verb_fallback_chunks", 0) for r in rs)
                return statistics.median(vals), steps, fallbacks

            verbs, vsteps, vfall = busbw("allreduce_verbs", 4 << 20)
            chunk, _, _ = busbw("allreduce_chunks", 4 << 20)
            if verbs is None or chunk is None or chunk <= 0:
                return None
            return {
                "coll_verbs_busbw_mbps": round(verbs, 1),
                "coll_chunk_busbw_mbps": round(chunk, 1),
                "coll_verbs_vs_chunk_ratio": round(verbs / chunk, 2),
                "coll_verbs_steps": vsteps,
                "coll_verbs_zero_fallback": int(vfall == 0),
                "coll_verbs_nranks": num,
            }
    except Exception:
        return None
    finally:
        for n in nodes:
            try:
                n.proc.stdin.close()
                n.proc.wait(timeout=10)
            except Exception:
                try:
                    n.proc.kill()
                    n.proc.wait()
                except Exception:
                    pass


def qos_isolation_scrape():
    """QoS isolation trajectory (ISSUE 8): boot one mesh_node with
    tenant quotas, run one mixed-tenant press where bronze floods at 8x
    its quota while gold trickles at high priority, and record gold's
    qps/p99 plus bronze's shed count — the BENCH record then tracks
    whether isolation holds round over round (gold_p99 is a real
    lower-is-better metric for --compare; bronze counters are context).
    """
    node = BUILD / "mesh_node"
    press = BUILD / "rpc_press"
    if not node.exists() or not press.exists():
        return None
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    proc = None
    try:
        with tempfile.TemporaryDirectory() as td:
            peers = Path(td) / "peers"
            peers.write_text("127.0.0.1:%d\n" % port)
            proc, ready = _spawn_node_ready(
                node, port, peers,
                ["--flag", "rpc_qos_enabled=true", "--flag",
                 "rpc_tenant_quotas=bronze:qps=250,burst=50,w=1,conc=4;"
                 "gold:w=8"])
            if not ready:
                return None
            res = subprocess.run(
                [str(press), "--server=127.0.0.1:%d" % port,
                 "--tenants=gold:1:7,bronze:10:1", "--qps=2200",
                 "--duration_s=3", "--callers=12", "--max_retry=0",
                 "--payload=128", "--json"],
                capture_output=True, timeout=60, text=True,
            )
            line = None
            for ln in reversed(res.stdout.splitlines()):
                if ln.startswith("{"):
                    line = json.loads(ln)
                    break
            if line is None or "press_tenants" not in line:
                return None
            gold = line["press_tenants"].get("gold", {})
            bronze = line["press_tenants"].get("bronze", {})
            return {
                "qos_gold_qps": int(gold.get("qps", 0)),
                "qos_gold_p99_us": int(gold.get("p99_us", 0)),
                "qos_gold_failed": int(gold.get("failed", 0)),
                "qos_bronze_qps": int(bronze.get("qps", 0)),
                "qos_bronze_shed": int(bronze.get("shed", 0)),
            }
    except Exception:
        return None
    finally:
        if proc is not None:
            try:
                proc.stdin.close()
                proc.wait(timeout=10)
            except Exception:
                proc.kill()
                proc.wait()


def qos_cost_scrape():
    """Work-priced admission round (ISSUE 15): bronze floods 64KiB
    bodies INSIDE its request-count rate (a shape a request-counting
    door admits wholesale) while gold trickles light.
    qos_cost_gold_p99_us is the compared isolation metric; bronze's
    shed volume and the server's learned cost estimate are context.
    Boots its OWN node: -rpc_tenant_quotas only applies at server
    start (cost units, no conc= — the gradient limiter owns
    concurrency), and a fresh node keeps the request-count round's
    learned state out of this measurement."""
    node = BUILD / "mesh_node"
    press = BUILD / "rpc_press"
    if not node.exists() or not press.exists():
        return None
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    proc = None
    try:
        with tempfile.TemporaryDirectory() as td:
            peers = Path(td) / "peers"
            peers.write_text("127.0.0.1:%d\n" % port)
            proc, ready = _spawn_node_ready(
                node, port, peers,
                ["--flag", "rpc_qos_enabled=true", "--flag",
                 "rpc_tenant_quotas=bronze:qps=400,burst=100,w=1;"
                 "gold:w=8"])
            if not ready:
                return None
            res = subprocess.run(
                [str(press), "--server=127.0.0.1:%d" % port,
                 "--tenants=gold:4:7:128,bronze:7:1:65536", "--qps=550",
                 "--duration_s=3", "--callers=12", "--max_retry=0",
                 "--json"],
                capture_output=True, timeout=90, text=True,
            )
            line = None
            for ln in reversed(res.stdout.splitlines()):
                if ln.startswith("{"):
                    line = json.loads(ln)
                    break
            if line is None or "press_tenants" not in line:
                return None
            gold = line["press_tenants"].get("gold", {})
            bronze = line["press_tenants"].get("bronze", {})
            tj = json.loads(
                urllib.request.urlopen(
                    "http://127.0.0.1:%d/tenants?format=json" % port,
                    timeout=5).read().decode())
            srv_bronze = tj.get("tenants", {}).get("bronze", {})
            return {
                "qos_cost_gold_p99_us": int(gold.get("p99_us", 0)),
                "qos_cost_gold_qps": int(gold.get("qps", 0)),
                "qos_cost_bronze_shed": int(bronze.get("shed", 0)),
                "qos_cost_bronze_ewma_milli": int(
                    srv_bronze.get("cost_ewma_milli", 0)),
                "qos_cost_backoff_ms_max": int(
                    line.get("press_backoff_ms_max", 0)),
            }
    except Exception:
        return None
    finally:
        if proc is not None:
            try:
                proc.stdin.close()
                proc.wait(timeout=10)
            except Exception:
                proc.kill()
                proc.wait()


def blackbox_scrape():
    """Flight-recorder overhead round (ISSUE 19): the always-on event
    rings must be effectively free on the RPC hot path. One mesh_node
    serves an unthrottled press with the recorder live-toggled OFF then
    ON per rep (the /flags/flight_recorder_enabled portal — same
    process, same sockets, so nothing but the Record gate differs) and
    blackbox_overhead_pct is the relative qps delta of the interleaved
    medians. It is ACCEPTANCE evidence (<= 5), not a compared metric:
    it re-derives from two same-process measurements whose noise floor
    on a shared container exceeds the true per-event cost, so it is
    skip-keyed along with the qps pair and the event-volume context."""
    node = BUILD / "mesh_node"
    press = BUILD / "rpc_press"
    if not node.exists() or not press.exists():
        return None
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    proc = None
    try:
        with tempfile.TemporaryDirectory() as td:
            peers = Path(td) / "peers"
            peers.write_text("127.0.0.1:%d\n" % port)
            proc, ready = _spawn_node_ready(node, port, peers)
            if not ready:
                return None

            def press_qps():
                res = subprocess.run(
                    [str(press), "--server=127.0.0.1:%d" % port,
                     "--qps=8000", "--duration_s=2", "--callers=8",
                     "--press_threads=2", "--payload=128",
                     "--max_retry=0", "--json"],
                    capture_output=True, timeout=60, text=True,
                )
                for ln in reversed(res.stdout.splitlines()):
                    if ln.startswith("{"):
                        return float(json.loads(ln)["press_qps"])
                return None

            def toggle(on):
                _http(port, "/flags/flight_recorder_enabled?setvalue="
                      + ("true" if on else "false"))

            def events():
                return int(float(_http(
                    port, "/vars/rpc_blackbox_events").split()[-1]))

            press_qps()  # warm connections + fiber pool before timing
            off_qps, on_qps, ev_delta = [], [], 0
            for _ in range(REPS):
                toggle(False)
                q = press_qps()
                if q is None:
                    return None
                off_qps.append(q)
                toggle(True)
                e0 = events()
                q = press_qps()
                if q is None:
                    return None
                on_qps.append(q)
                ev_delta += events() - e0
            toggle(True)  # leave the recorder in its always-on default
            off_m = statistics.median(off_qps)
            on_m = statistics.median(on_qps)
            if off_m <= 0:
                return None
            return {
                "blackbox_overhead_pct": round(
                    max(0.0, (off_m - on_m) / off_m * 100.0), 2),
                "blackbox_qps_on": int(on_m),
                "blackbox_qps_off": int(off_m),
                "blackbox_events_per_s": int(ev_delta / (2.0 * REPS)),
            }
    except Exception:
        return None
    finally:
        if proc is not None:
            try:
                proc.stdin.close()
                proc.wait(timeout=10)
            except Exception:
                proc.kill()
                proc.wait()


# Compare-mode metric directions: latency-ish keys regress UP, the rest
# (throughput/qps/counts) regress DOWN. Non-numeric values, series
# arrays, evidence paths, and derived ratios are skipped — as are the
# raw attribution ACTIVITY counters (epoll waits, steals, write
# batches, point-in-time qps): they are context for reading a
# regression, not quality metrics with a better-direction (write
# coalescing LOWERS socket_write_batches at identical throughput and
# must not flag as a regression).
_SKIP_KEYS = {"metric", "unit", "vs_baseline", "reps", "error",
              "status_json_method", "heap_profile_path",
              "cpu_profile_path", "dispatcher_epoll_waits",
              "dispatcher_events", "dispatcher_wakeups",
              "inline_dispatches", "inline_overflows", "inline_handlers",
              "coalesced_writes", "scheduler_steals",
              "socket_write_batches", "status_json_qps",
              "press_gen_threads", "press_gen_callers", "press_gen_qps",
              "press_gen_payload",
              # QoS context counters: bronze's achieved volumes depend on
              # the flood shape and how hard it is shed, not on code
              # quality — gold qps/p99 are the compared isolation metrics.
              "qos_bronze_shed", "qos_bronze_qps", "qos_gold_failed",
              # Work-priced round (ISSUE 15): qos_cost_gold_p99_us /
              # qos_cost_gold_qps ARE compared (isolation under a
              # mixed-COST flood); shed volume, the learned estimate,
              # and the backoff hint are flood-shape context.
              "qos_cost_bronze_shed", "qos_cost_bronze_ewma_milli",
              "qos_cost_backoff_ms_max",
              # Device ring (ISSUE 9): device_path_gbps is THE compared
              # metric. device_path_mbps is the RETIRED pre-ring key —
              # skip-keyed so the MB/s -> GB/s unit change never flags as
              # a regression against old records; ring shape/efficiency
              # numbers are run context (overlap_eff depends on host core
              # availability, not code quality), and booleans are not
              # magnitudes.
              "device_path_mbps", "device_path_serial_gbps",
              "device_path_overlap_eff", "device_path_ring_depth",
              "device_path_chunk_bytes", "device_path_inflight_highwater",
              "device_path_ok", "device_path_registered_staging",
              "device_path_cores", "pool_desc_calls", "pool_desc_bytes",
              "pool_desc_zero_copy",
              # Response-direction descriptor round (ISSUE 12):
              # pool_desc_rsp_mbps IS compared (the symmetric-zero-copy
              # rate); shape/boolean evidence keys are not magnitudes.
              "pool_desc_rsp_calls", "pool_desc_rsp_zero_copy",
              "pool_desc_rsp_inline_bytes",
              # Lease leak gauges (ISSUE 10): evidence, not a rate — a
              # healthy round records pinned_after == 0; reaped counts
              # chaos/crash reclamations, so neither is a compare metric.
              "pool_desc_pinned_after", "pool_desc_reaped",
              # Collective round (ISSUE 13): the three coll_*_busbw_mbps
              # keys ARE compared (higher better). The serial baseline
              # and the derived pipeline ratio are context — the serial
              # number measures the deliberately-unpipelined path, and
              # the ratio re-derives from two compared/contextual keys;
              # nranks is shape, zero_inline a boolean proof.
              "coll_allreduce_serial_mbps", "coll_allreduce_pipeline_ratio",
              "coll_nranks", "coll_zero_inline",
              # Emulated-DCN round (ISSUE 14): the hier busbw IS
              # compared; the flat number measures the deliberately-WAN-
              # dragged baseline on an emulated pipe, and the ratio
              # re-derives from the two (the >= 1.0 acceptance lives in
              # the verify recipe); pod count is shape.
              "coll_flat_dcn_allreduce_busbw_mbps",
              "coll_hier_vs_flat_ratio", "coll_dcn_pods",
              # One-sided verbs round (ISSUE 18): coll_verbs_busbw_mbps
              # IS compared (higher better). The chunk number measures
              # the deliberately-two-sided baseline, the ratio
              # re-derives from the two (its >= 1.0 acceptance lives in
              # the verify recipe), steps/nranks are shape, and
              # zero_fallback is a boolean proof.
              "coll_chunk_busbw_mbps", "coll_verbs_vs_chunk_ratio",
              "coll_verbs_steps", "coll_verbs_zero_fallback",
              "coll_verbs_nranks",
              # Inference-serving round (ISSUE 17): batched tokens/s and
              # the TTFT/ITL latencies ARE compared. The unbatched
              # number measures the deliberately-serial baseline, the
              # ratio re-derives from the two, resume counts are
              # restart-timing context, and resume_loss is a MUST-BE-0
              # acceptance gate (asserted in the verify recipe — a 0->1
              # flip would read as "improved" to the direction
              # heuristic, so it must not be compared).
              "infer_unbatched_tokens_per_s", "infer_batch_ratio",
              "infer_stream_resumes", "infer_stream_resume_loss",
              # Flight-recorder round (ISSUE 19): blackbox_overhead_pct
              # is the <= 5 acceptance gate (asserted in the verify
              # recipe), re-derived from the same-process on/off qps
              # pair — all four keys are evidence/context, and the qps
              # pair must not double-count as throughput metrics (the
              # series round already compares qps).
              "blackbox_overhead_pct", "blackbox_qps_on",
              "blackbox_qps_off", "blackbox_events_per_s"}


def _lower_is_better(key):
    return any(t in key for t in
               ("p50", "p90", "p99", "p999", "_us", "latency"))


def compare_benches(prev_path, cur_path, strict, threshold):
    """Per-metric delta report between two BENCH jsons. Returns the exit
    code: non-zero only when --strict and a regression beyond
    `threshold` exists."""
    def load_bench(path):
        data = json.loads(Path(path).read_text())
        # Committed BENCH_rNN.json files are driver wrappers with the
        # metrics line in "tail"; a raw bench.py line parses directly.
        if isinstance(data.get("tail"), str):
            start = data["tail"].find("{")
            if start >= 0:
                data = json.loads(data["tail"][start:])
        return data

    prev = load_bench(prev_path)
    cur = load_bench(cur_path)
    rows = []
    regressions = []
    for key in sorted(set(prev) & set(cur)):
        if key in _SKIP_KEYS or key.endswith("_series") or \
                key.endswith("_series_tail"):
            continue
        pv, cv = prev[key], cur[key]
        if not isinstance(pv, (int, float)) or \
                not isinstance(cv, (int, float)):
            continue
        if pv == 0:
            delta = 0.0 if cv == 0 else float("inf")
        else:
            delta = (cv - pv) / abs(pv)
        worse = -delta if _lower_is_better(key) else delta
        flag = ""
        if worse < -threshold:
            flag = "REGRESSION"
            regressions.append(key)
        elif worse > threshold:
            flag = "improved"
        rows.append((key, pv, cv, delta, flag))
    print("regression gate: %s -> %s  (threshold %.0f%%, %s)"
          % (prev_path, cur_path, threshold * 100,
             "strict" if strict else "report-only"))
    print("%-28s %14s %14s %9s  %s"
          % ("metric", "prev", "cur", "delta", ""))
    for key, pv, cv, delta, flag in rows:
        print("%-28s %14g %14g %8.1f%%  %s"
              % (key, pv, cv, delta * 100, flag))
    for evidence in ("cpu_profile_path", "heap_profile_path"):
        if cur.get(evidence):
            print("evidence: %s = %s" % (evidence, cur[evidence]))
    if regressions:
        print("%d regression(s): %s" % (len(regressions),
                                        ", ".join(regressions)))
        return 1 if strict else 0
    print("no regressions past threshold")
    return 0


def _arg_value(argv, name):
    if name in argv:
        i = argv.index(name)
        if i + 1 < len(argv):
            return argv[i + 1]
    return None


def main():
    argv = sys.argv[1:]
    prev_path = _arg_value(argv, "--compare")
    if prev_path is not None:
        cur_path = _arg_value(argv, "--current")
        threshold = float(_arg_value(argv, "--threshold") or 0.15)
        strict = "--strict" in argv
        if cur_path is None:
            # No current json: run the bench now, save, then gate.
            import io
            from contextlib import redirect_stdout
            buf = io.StringIO()
            with redirect_stdout(buf):
                run_bench()
            line = buf.getvalue().strip().splitlines()[-1]
            cur = Path(tempfile.gettempdir()) / "BENCH_current.json"
            cur.write_text(line + "\n")
            print(line)
            cur_path = str(cur)
        sys.exit(compare_benches(prev_path, cur_path, strict, threshold))
    run_bench()


def run_bench():
    try:
        build()
    except Exception:
        print(json.dumps({
            "metric": "echo_throughput", "value": 0, "unit": "MB/s",
            "vs_baseline": 0.0, "error": "build failed",
        }))
        return

    ici, ici_n = median_rounds(["--json", "--ici"])
    xproc, _ = median_rounds(["--json", "--xproc"])
    tcp, _ = median_rounds(["--json"])
    tcp_pooled, _ = median_rounds(["--json", "--pooled"])

    if ici is None or "mbps" not in ici:
        # Degraded fallback: loopback TCP only (tail still runs over TCP).
        tail = run_tool("echo_bench", ["--json", "--tail"], timeout=600)
        if tcp is not None and "mbps" in tcp:
            mbps = float(tcp["mbps"])
            out = {
                "metric": "echo_throughput_1MB_loopback",
                "value": round(mbps, 1), "unit": "MB/s",
                "vs_baseline": round(mbps / BASELINE_MBPS, 3),
            }
            if tail is not None:
                out.update(tail)
            print(json.dumps(out))
        else:
            print(json.dumps({
                "metric": "echo_throughput", "value": 0, "unit": "MB/s",
                "vs_baseline": 0.0, "error": "no bench tool built",
            }))
        return

    tail = run_tool("echo_bench", ["--json", "--tail"], timeout=600)
    scale = run_tool("echo_bench", ["--json", "--scale", "--ici"],
                     timeout=600)
    # One-sided descriptor round, BOTH directions (ISSUE 9/12):
    # attachments as pool references over the in-process ici link.
    # pool_desc_mbps / pool_desc_rsp_mbps are the logical rates per
    # direction (the symmetric-zero-copy gate wants rsp within 20% of
    # req); the *_zero_copy booleans are the verification proof.
    pool_desc = run_tool("echo_bench", ["--json", "--ici", "--pool_desc"],
                         timeout=300)
    device = device_path()
    series = series_scrape()
    qos = qos_isolation_scrape()
    qos_cost = qos_cost_scrape()
    coll = collective_scrape()
    dcn_coll = dcn_collective_scrape()
    verbs = verbs_scrape()
    infer = infer_scrape()
    blackbox = blackbox_scrape()

    mbps = float(ici["mbps"])
    out = {
        "metric": "echo_throughput_1MB_ici",
        "value": round(mbps, 1),
        "unit": "MB/s",
        "vs_baseline": round(mbps / BASELINE_MBPS, 3),
        "reps": ici_n,
    }
    for k in ("qps_4k", "p50_us_4k", "p99_us_4k"):
        if k in ici:
            out["ici_" + k] = ici[k]
    for prefix, r in (("xproc_", xproc), ("tcp_", tcp),
                      ("tcp_pooled_", tcp_pooled)):
        if r is not None:
            for k in ("mbps", "qps_4k", "p99_us_4k"):
                if k in r:
                    out[prefix + k] = r[k]
    if tail is not None:
        out.update(tail)
    if scale is not None:
        out.update(scale)
    if pool_desc is not None:
        out.update(pool_desc)
    if device is not None:
        out.update(device)
    if series is not None:
        out.update(series)
    if qos is not None:
        out.update(qos)
    if qos_cost is not None:
        out.update(qos_cost)
    if coll is not None:
        out.update(coll)
    if dcn_coll is not None:
        out.update(dcn_coll)
    if verbs is not None:
        out.update(verbs)
    if infer is not None:
        out.update(infer)
    if blackbox is not None:
        out.update(blackbox)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
