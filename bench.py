#!/usr/bin/env python3
"""Benchmark driver: prints ONE JSON line.

Headline metric (mirrors the reference's headline echo benchmark,
docs/cn/benchmark.md:104 — 2.3 GB/s echo throughput on loopback): large-
payload echo throughput through the full stack (client Channel -> framed
protocol -> Socket -> loopback TCP -> Server -> echo service -> response),
measured by the C++ `echo_bench` tool once the RPC slice exists.

Falls back to the IOBuf zero-copy pipeline microbench while the full slice
is under construction, and to 0 if nothing is built.
"""
import json
import subprocess
from pathlib import Path

REPO = Path(__file__).resolve().parent
BUILD = REPO / "build"

BASELINE_MBPS = 2300.0  # reference echo throughput (BASELINE.md: 2.3 GB/s)


def build():
    BUILD.mkdir(exist_ok=True)
    if not (BUILD / "build.ninja").exists():
        subprocess.run(
            ["cmake", "-G", "Ninja", "-S", str(REPO), "-B", str(BUILD)],
            check=True, capture_output=True,
        )
    subprocess.run(
        ["ninja", "-C", str(BUILD)], check=True, capture_output=True
    )


def run_tool(name, args):
    exe = BUILD / name
    if not exe.exists():
        return None
    proc = subprocess.run(
        [str(exe)] + args, capture_output=True, text=True, timeout=300
    )
    if proc.returncode != 0:
        return None
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def main():
    try:
        build()
    except Exception:
        print(json.dumps({
            "metric": "echo_throughput", "value": 0, "unit": "MB/s",
            "vs_baseline": 0.0, "error": "build failed",
        }))
        return
    def assemble(result, metric, prefix=""):
        mbps = float(result["mbps"])
        out = {
            "metric": metric,
            "value": round(mbps, 1),
            "unit": "MB/s",
            "vs_baseline": round(mbps / BASELINE_MBPS, 3),
        }
        for k in ("qps_4k", "p99_us_4k"):
            if k in result:
                out[prefix + k] = result[k]
        return out

    # Headline: echo over the ICI transport (the point of the project —
    # SURVEY §2.9 north star). The cross-process shared-memory link
    # (handshake over TCP, registered-memory data plane — the product
    # transport) and TCP loopback ride along for comparison.
    ici = run_tool("echo_bench", ["--json", "--ici"])
    xproc = run_tool("echo_bench", ["--json", "--xproc"])
    tcp = run_tool("echo_bench", ["--json"])
    if ici is not None and "mbps" in ici:
        out = assemble(ici, "echo_throughput_1MB_ici", "ici_")
        if xproc is not None and "mbps" in xproc:
            out["xproc_mbps"] = xproc["mbps"]
            for k in ("qps_4k", "p99_us_4k"):
                if k in xproc:
                    out["xproc_" + k] = xproc[k]
        if tcp is not None and "mbps" in tcp:
            out["tcp_mbps"] = tcp["mbps"]
            for k in ("qps_4k", "p99_us_4k"):
                if k in tcp:
                    out["tcp_" + k] = tcp[k]
        print(json.dumps(out))
        return
    if tcp is not None and "mbps" in tcp:
        print(json.dumps(assemble(tcp, "echo_throughput_1MB_loopback")))
        return
    result = run_tool("iobuf_bench", ["--json"])
    if result is not None and "mbps" in result:
        mbps = float(result["mbps"])
        print(json.dumps({
            "metric": "iobuf_pipeline_throughput",
            "value": round(mbps, 1),
            "unit": "MB/s",
            "vs_baseline": round(mbps / BASELINE_MBPS, 3),
        }))
        return
    print(json.dumps({
        "metric": "echo_throughput", "value": 0, "unit": "MB/s",
        "vs_baseline": 0.0, "error": "no bench tool built",
    }))


if __name__ == "__main__":
    main()
