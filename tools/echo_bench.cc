// Loopback echo benchmark through the full I/O stack: Acceptor ->
// InputMessenger -> framed protocol -> Socket wait-free write queue ->
// epoll -> fibers, client and server in one process.
//
// Mirrors the reference's headline echo benchmark setup
// (docs/cn/benchmark.md:104 — 2.3 GB/s large-payload echo on loopback;
// example/echo_c++ + example/rdma_performance drivers). Once the RPC layer
// (Channel/Server) lands this driver switches to it; the framing here is
// the same shape (magic + length + payload).
//
// Prints one JSON line with --json:
//   {"mbps": ..., "qps_4k": ..., "p99_us_4k": ...}
#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <cstring>
#include <string>

#include "tbase/time.h"
#include "tfiber/fiber_sync.h"
#include "tnet/acceptor.h"
#include "tnet/input_messenger.h"
#include "tnet/socket.h"
#include "tnet/socket_map.h"
#include "tvar/latency_recorder.h"

using namespace tpurpc;

namespace {

constexpr char kMagic[4] = {'E', 'C', 'H', '1'};

struct Msg : public InputMessageBase {
    IOBuf payload;
};

ParseResult parse(IOBuf* source, Socket*, bool, const void*) {
    if (source->size() < 8) {
        return ParseResult::make(ParseError::NOT_ENOUGH_DATA);
    }
    char header[8];
    source->copy_to(header, 8);
    if (memcmp(header, kMagic, 4) != 0) {
        return ParseResult::make(ParseError::TRY_OTHERS);
    }
    uint32_t len;
    memcpy(&len, header + 4, 4);
    if (source->size() < 8 + (size_t)len) {
        return ParseResult::make(ParseError::NOT_ENOUGH_DATA);
    }
    source->pop_front(8);
    auto* m = new Msg;
    source->cutn(&m->payload, len);
    return ParseResult::make_ok(m);
}

void frame(IOBuf* out, IOBuf&& payload) {
    char header[8];
    memcpy(header, kMagic, 4);
    const uint32_t len = (uint32_t)payload.size();
    memcpy(header + 4, &len, 4);
    out->append(header, 8);
    out->append(std::move(payload));
}

void server_process(InputMessageBase* raw) {
    Msg* m = (Msg*)raw;
    SocketUniquePtr s;
    if (Socket::AddressSocket(m->socket_id, &s) == 0) {
        IOBuf out;
        frame(&out, std::move(m->payload));
        s->Write(&out);
    }
    delete m;
}

CountdownEvent* g_pending = nullptr;
std::atomic<int64_t> g_bytes{0};
LatencyRecorder* g_lat = nullptr;

void client_process(InputMessageBase* raw) {
    Msg* m = (Msg*)raw;
    // First 8 payload bytes carry the send timestamp: exact per-message
    // latency independent of response order.
    int64_t ts = 0;
    if (m->payload.size() >= 8) {
        m->payload.copy_to(&ts, 8);
        if (g_lat != nullptr) {
            *g_lat << (monotonic_time_us() - ts);
        }
    }
    g_bytes.fetch_add((int64_t)m->payload.size(), std::memory_order_relaxed);
    g_pending->signal();
    delete m;
}

// Send `iters` messages of msg_bytes in windows of `window`; returns
// elapsed seconds.
double run_round(SocketUniquePtr& cs, size_t msg_bytes, int iters,
                 int window) {
    std::string filler(msg_bytes, 'e');
    Timer t;
    t.start();
    int sent = 0;
    while (sent < iters) {
        const int batch = std::min(window, iters - sent);
        g_pending->reset(batch);
        for (int i = 0; i < batch; ++i) {
            IOBuf payload;
            const int64_t now = monotonic_time_us();
            memcpy(&filler[0], &now, 8);
            payload.append(filler);
            IOBuf framed;
            frame(&framed, std::move(payload));
            while (cs->Write(&framed) != 0) {
                usleep(1000);  // EOVERCROWDED back-pressure: retry
                if (cs->Failed()) return -1;
            }
        }
        if (g_pending->wait() != 0) return -1;
        sent += batch;
    }
    t.stop();
    return (double)t.n_elapsed() / 1e9;
}

}  // namespace

int main(int argc, char** argv) {
    bool json = false;
    for (int i = 1; i < argc; ++i) {
        if (strcmp(argv[i], "--json") == 0) json = true;
    }
    Protocol sp;
    sp.parse = parse;
    sp.process = server_process;
    sp.name = "echo_bench_server";
    const int sidx = RegisterProtocol(sp);
    Protocol cp;
    cp.parse = parse;
    cp.process = client_process;
    cp.name = "echo_bench_client";
    const int cidx = RegisterProtocol(cp);

    InputMessenger server_m({sidx});
    Acceptor acceptor(&server_m);
    EndPoint ep;
    str2endpoint("127.0.0.1:0", &ep);
    if (acceptor.StartAccept(ep) != 0) {
        fprintf(stderr, "listen failed\n");
        return 1;
    }
    InputMessenger client_m({cidx});
    EndPoint server_ep;
    str2endpoint("127.0.0.1", acceptor.listened_port(), &server_ep);
    SocketId cid;
    if (SocketMap::singleton()->GetOrCreate(server_ep, &client_m, &cid) != 0) {
        return 1;
    }
    SocketUniquePtr cs;
    if (Socket::AddressSocket(cid, &cs) != 0) return 1;

    CountdownEvent pending(0);
    g_pending = &pending;
    LatencyRecorder lat;
    lat.expose("echo_4k_latency");

    // Warmup (connect + caches).
    run_round(cs, 4096, 200, 32);

    // 4KB round: qps + latency. Capture percentiles immediately — they're
    // computed over a 10s sliding window and would rotate out during the
    // 1MB round.
    g_lat = &lat;
    const int kSmallIters = 20000;
    const double small_secs = run_round(cs, 4096, kSmallIters, 64);
    g_lat = nullptr;
    if (small_secs < 0) return 1;
    const double qps_4k = kSmallIters / small_secs;
    const long long p99 = (long long)lat.latency_percentile(0.99);
    const long long p50 = (long long)lat.latency_percentile(0.5);

    // 1MB round: throughput.
    g_bytes.store(0);
    const int kBigIters = 300;
    const double big_secs = run_round(cs, 1 << 20, kBigIters, 4);
    if (big_secs < 0) return 1;
    const double mbps =
        (double)g_bytes.load() / (1024.0 * 1024.0) / big_secs;

    if (json) {
        printf("{\"mbps\": %.1f, \"qps_4k\": %.0f, \"p50_us_4k\": %lld, "
               "\"p99_us_4k\": %lld}\n",
               mbps, qps_4k, p50, p99);
    } else {
        printf("1MB echo throughput: %.1f MB/s (%d msgs)\n", mbps, kBigIters);
        printf("4KB echo: %.0f qps, p50 %lldus, p99 %lldus\n", qps_4k, p50,
               p99);
    }
    return 0;
}
