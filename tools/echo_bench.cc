// Loopback echo benchmark through the FULL RPC stack: protobuf stub ->
// Channel -> tpu_std protocol -> Socket -> epoll -> Server -> service ->
// response, client and server in one process. Bulk bytes ride the
// attachment (zero-copy), matching the reference's echo benchmark setup
// (docs/cn/benchmark.md:104 — 2.3 GB/s large-payload echo on loopback;
// example/echo_c++ attachment echo).
//
// Prints one JSON line with --json:
//   {"mbps": ..., "qps_4k": ..., "p50_us_4k": ..., "p99_us_4k": ...}
#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <cstring>
#include <string>

#include "bench_echo.pb.h"
#include "tbase/cpu_profiler.h"
#include "tbase/flags.h"
#include "tbase/time.h"
#include "tici/block_pool.h"
#include "tici/ici_link.h"
#include "tnet/socket.h"
#include "tfiber/fiber_sync.h"
#include "trpc/channel.h"
#include "trpc/controller.h"
#include "trpc/server.h"
#include "tvar/latency_recorder.h"

using namespace tpurpc;

DECLARE_int32(socket_send_buffer_size);
DECLARE_int32(socket_recv_buffer_size);

namespace {

class EchoServiceImpl : public benchpb::EchoService {
public:
    void Echo(google::protobuf::RpcController* cntl_base,
              const benchpb::EchoRequest* request,
              benchpb::EchoResponse* response,
              google::protobuf::Closure* done) override {
        Controller* cntl = static_cast<Controller*>(cntl_base);
        response->set_send_ts_us(request->send_ts_us());
        cntl->response_attachment().append(cntl->request_attachment());
        done->Run();
    }
};

struct CallCtx {
    Controller cntl;
    benchpb::EchoRequest req;
    benchpb::EchoResponse res;
    CountdownEvent* pending;
    LatencyRecorder* lat;
    std::atomic<int64_t>* bytes;
};

void OnEchoDone(CallCtx* ctx) {
    if (!ctx->cntl.Failed()) {
        if (ctx->lat != nullptr) {
            *ctx->lat << (monotonic_time_us() - ctx->res.send_ts_us());
        }
        if (ctx->bytes != nullptr) {
            ctx->bytes->fetch_add(
                (int64_t)ctx->cntl.response_attachment().size(),
                std::memory_order_relaxed);
        }
    } else {
        fprintf(stderr, "rpc failed: %s\n", ctx->cntl.ErrorText().c_str());
    }
    ctx->pending->signal();
    delete ctx;
}

// `iters` async echo RPCs with `window` in flight; returns elapsed secs.
double run_round(benchpb::EchoService_Stub& stub, size_t attachment_bytes,
                 int iters, int window, LatencyRecorder* lat,
                 std::atomic<int64_t>* bytes) {
    // Pre-built attachment appended by reference (zero-copy), matching the
    // reference drivers (example/multi_threaded_echo_c++ appends a global
    // butil::IOBuf g_attachment).
    IOBuf filler;
    filler.append(std::string(attachment_bytes, 'e'));
    Timer t;
    t.start();
    int sent = 0;
    CountdownEvent pending(0);
    while (sent < iters) {
        const int batch = std::min(window, iters - sent);
        pending.reset(batch);
        for (int i = 0; i < batch; ++i) {
            auto* ctx = new CallCtx;
            ctx->pending = &pending;
            ctx->lat = lat;
            ctx->bytes = bytes;
            ctx->cntl.set_timeout_ms(10000);
            ctx->req.set_send_ts_us(monotonic_time_us());
            if (attachment_bytes > 0) {
                ctx->cntl.request_attachment().append(filler);
            }
            stub.Echo(&ctx->cntl, &ctx->req, &ctx->res,
                      google::protobuf::NewCallback(OnEchoDone, ctx));
        }
        if (pending.wait() != 0) return -1;
        sent += batch;
    }
    t.stop();
    return (double)t.n_elapsed() / 1e9;
}

}  // namespace

int main(int argc, char** argv) {
    bool json = false;
    bool use_ici = false;
    const char* prof_path = nullptr;
    for (int i = 1; i < argc; ++i) {
        if (strcmp(argv[i], "--json") == 0) json = true;
        if (strcmp(argv[i], "--ici") == 0) use_ici = true;
        if (strcmp(argv[i], "--prof") == 0 && i + 1 < argc) {
            prof_path = argv[++i];
        }
    }
    // Windowed 1MB messages benefit from fixed large socket buffers on
    // loopback; production connections keep kernel autotuning (-1).
    FLAGS_socket_send_buffer_size.set(1 << 20);
    FLAGS_socket_recv_buffer_size.set(1 << 20);
    Server server;
    EchoServiceImpl service;
    if (server.AddService(&service) != 0) return 1;

    Channel channel;
    ChannelOptions copts;
    copts.timeout_ms = 10000;
    if (use_ici) {
        // ICI data plane: registered-memory pool + software queue pair
        // (the loopback stand-in for the interconnect; see
        // cpp/tici/ici_link.h). One copy per byte instead of TCP's four.
        if (IciBlockPool::Init() != 0) return 1;
        if (server.StartNoListen(nullptr) != 0) return 1;
        IciLink& link = *IciLink::Create();
        SocketOptions sopts;
        sopts.fd = link.second()->event_fd();
        sopts.transport = link.second();
        sopts.owns_transport = true;
        sopts.on_edge_triggered_events = InputMessenger::OnNewMessages;
        sopts.user = server.messenger();
        SocketId server_sid;
        if (Socket::Create(sopts, &server_sid) != 0) return 1;
        SocketOptions ccopts;
        ccopts.fd = link.first()->event_fd();
        ccopts.transport = link.first();
        ccopts.owns_transport = true;
        ccopts.on_edge_triggered_events = InputMessenger::OnNewMessages;
        ccopts.user = Channel::client_messenger();
        SocketId client_sid;
        if (Socket::Create(ccopts, &client_sid) != 0) return 1;
        if (channel.InitWithSocketId(client_sid, &copts) != 0) return 1;
    } else {
        EndPoint listen;
        str2endpoint("127.0.0.1:0", &listen);
        if (server.Start(listen, nullptr) != 0) return 1;
        EndPoint ep;
        str2endpoint("127.0.0.1", server.listened_port(), &ep);
        if (channel.Init(ep, &copts) != 0) return 1;
    }
    benchpb::EchoService_Stub stub(&channel);

    LatencyRecorder lat;
    lat.expose("rpc_echo_4k_latency");

    // Warmup.
    run_round(stub, 4096, 500, 32, nullptr, nullptr);
    if (prof_path != nullptr) StartCpuProfiler();

    // 4KB round.
    const int kSmallIters = 20000;
    const double small_secs =
        run_round(stub, 4096, kSmallIters, 64, &lat, nullptr);
    if (small_secs < 0) return 1;
    const double qps_4k = kSmallIters / small_secs;
    const long long p50 = (long long)lat.latency_percentile(0.5);
    const long long p99 = (long long)lat.latency_percentile(0.99);

    // 1MB round.
    std::atomic<int64_t> bytes{0};
    const int kBigIters = 300;
    const double big_secs =
        run_round(stub, 1 << 20, kBigIters, 4, nullptr, &bytes);
    if (big_secs < 0) return 1;
    const double mbps = (double)bytes.load() / (1024.0 * 1024.0) / big_secs;
    if (prof_path != nullptr) {
        const int n = StopCpuProfiler(prof_path);
        fprintf(stderr, "wrote %d samples to %s\n", n, prof_path);
    }

    if (json) {
        printf("{\"mbps\": %.1f, \"qps_4k\": %.0f, \"p50_us_4k\": %lld, "
               "\"p99_us_4k\": %lld}\n",
               mbps, qps_4k, p50, p99);
    } else {
        printf("RPC 1MB attachment echo: %.1f MB/s (%d calls)\n", mbps,
               kBigIters);
        printf("RPC 4KB echo: %.0f qps, p50 %lldus, p99 %lldus\n", qps_4k,
               p50, p99);
    }
    return 0;
}
