// Loopback echo benchmark through the FULL RPC stack: protobuf stub ->
// Channel -> tpu_std protocol -> Socket -> epoll -> Server -> service ->
// response, client and server in one process. Bulk bytes ride the
// attachment (zero-copy), matching the reference's echo benchmark setup
// (docs/cn/benchmark.md:104 — 2.3 GB/s large-payload echo on loopback;
// example/echo_c++ attachment echo).
//
// Prints one JSON line with --json:
//   {"mbps": ..., "qps_4k": ..., "p50_us_4k": ..., "p99_us_4k": ...}
#include <signal.h>
#include <sys/prctl.h>
#include <sys/wait.h>
#include <unistd.h>

#include <atomic>
#include <map>
#include <cstdio>
#include <cstring>
#include <string>

#include "bench_echo.pb.h"
#include "tbase/cpu_profiler.h"
#include "tbase/crc32c.h"
#include "tbase/errno.h"
#include "tbase/fast_rand.h"
#include "tbase/flags.h"
#include "tbase/time.h"
#include "tici/block_lease.h"
#include "tici/block_pool.h"
#include "tici/ici_link.h"
#include "tici/shm_link.h"
#include "tnet/socket.h"
#include "tfiber/fiber_sync.h"
#include "trpc/channel.h"
#include "trpc/controller.h"
#include "trpc/redis.h"
#include "trpc/server.h"
#include "tvar/latency_recorder.h"

using namespace tpurpc;

DECLARE_int32(socket_send_buffer_size);
DECLARE_int32(socket_recv_buffer_size);

// Long-tail injection for the backup-request benchmark (reference
// docs/cn/benchmark.md:126-206: 1% of requests made slow, latency CDF
// with/without backup requests stays flat).
DEFINE_int32(echo_slow_percent, 0, "percent of echo calls made slow");
DEFINE_int32(echo_slow_us, 10000, "injected handler delay in us");

namespace {

class EchoServiceImpl : public benchpb::EchoService {
public:
    void Echo(google::protobuf::RpcController* cntl_base,
              const benchpb::EchoRequest* request,
              benchpb::EchoResponse* response,
              google::protobuf::Closure* done) override {
        Controller* cntl = static_cast<Controller*>(cntl_base);
        const int slow_pct = FLAGS_echo_slow_percent.get();
        if (slow_pct > 0 && (int)(fast_rand() % 100) < slow_pct) {
            fiber_usleep(FLAGS_echo_slow_us.get());
        }
        response->set_send_ts_us(request->send_ts_us());
        if (request->has_payload()) {
            response->set_payload(request->payload());
        }
        // One-sided pool attachment (ISSUE 9): the bytes were never
        // copied — read them IN PLACE from the mapped sender pool and
        // answer with their checksum + placement evidence, duplicating
        // nothing. (Echoing them back as response bytes would undo the
        // zero-copy the descriptor bought.)
        const Controller::PoolAttachment& pa =
            cntl->request_pool_attachment();
        if (pa.data != nullptr) {
            // inline = attachment bytes that crossed the wire alongside
            // the descriptor (0 proves the payload rode as a reference).
            char verdict[96];
            snprintf(verdict, sizeof(verdict),
                     "crc32c=%08x len=%llu inline=%zu",
                     crc32c_extend(0, pa.data, pa.length),
                     (unsigned long long)pa.length,
                     cntl->request_attachment().size());
            response->set_payload(verdict);
        }
        // Response-direction descriptor (ISSUE 12): a "desc_rsp:N:S"
        // request asks for N bytes answered as a pool-block REFERENCE —
        // the handler fills a slab slot in its OWN pool (pattern seeded
        // by S: byte 0 = S, the rest 'a'+S%26) and pins it; the client
        // resolves it against its handshake-made mapping of this pool
        // with zero inline payload bytes.
        unsigned long long rsp_n = 0;
        unsigned rsp_seed = 0;
        if (sscanf(request->payload().c_str(), "desc_rsp:%llu:%u", &rsp_n,
                   &rsp_seed) == 2 &&
            rsp_n > 0) {
            IOBuf out;
            char* data = nullptr;
            if (IciBlockPool::AllocatePoolAttachment((size_t)rsp_n, &out,
                                                     &data)) {
                memset(data, 'a' + (int)(rsp_seed % 26), (size_t)rsp_n);
                data[0] = (char)rsp_seed;
                cntl->set_response_pool_attachment(std::move(out));
                response->set_payload("desc_rsp_ok");
            } else {
                cntl->SetFailed(TERR_RESPONSE,
                                "pool attachment alloc failed");
            }
        }
        cntl->response_attachment().append(cntl->request_attachment());
        done->Run();
    }
};

struct CallCtx {
    Controller cntl;
    benchpb::EchoRequest req;
    benchpb::EchoResponse res;
    CountdownEvent* pending;
    LatencyRecorder* lat;
    std::atomic<int64_t>* bytes;
};

void OnEchoDone(CallCtx* ctx) {
    if (!ctx->cntl.Failed()) {
        if (ctx->lat != nullptr) {
            *ctx->lat << (monotonic_time_us() - ctx->res.send_ts_us());
        }
        if (ctx->bytes != nullptr) {
            ctx->bytes->fetch_add(
                (int64_t)ctx->cntl.response_attachment().size(),
                std::memory_order_relaxed);
        }
    } else {
        fprintf(stderr, "rpc failed: %s\n", ctx->cntl.ErrorText().c_str());
    }
    ctx->pending->signal();
    delete ctx;
}

// `iters` async echo RPCs with `window` in flight; returns elapsed secs.
// backup_ms >= 0 arms a backup request per call at that delay.
double run_round(benchpb::EchoService_Stub& stub, size_t attachment_bytes,
                 int iters, int window, LatencyRecorder* lat,
                 std::atomic<int64_t>* bytes, int64_t backup_ms = -1) {
    // Pre-built attachment appended by reference (zero-copy), matching the
    // reference drivers (example/multi_threaded_echo_c++ appends a global
    // butil::IOBuf g_attachment).
    IOBuf filler;
    filler.append(std::string(attachment_bytes, 'e'));
    Timer t;
    t.start();
    int sent = 0;
    CountdownEvent pending(0);
    while (sent < iters) {
        const int batch = std::min(window, iters - sent);
        pending.reset(batch);
        for (int i = 0; i < batch; ++i) {
            auto* ctx = new CallCtx;
            ctx->pending = &pending;
            ctx->lat = lat;
            ctx->bytes = bytes;
            ctx->cntl.set_timeout_ms(10000);
            if (backup_ms >= 0) {
                ctx->cntl.set_backup_request_ms(backup_ms);
                ctx->cntl.set_max_retry(1);  // backup consumes retry budget
            }
            ctx->req.set_send_ts_us(monotonic_time_us());
            if (attachment_bytes > 0) {
                ctx->cntl.request_attachment().append(filler);
            }
            stub.Echo(&ctx->cntl, &ctx->req, &ctx->res,
                      google::protobuf::NewCallback(OnEchoDone, ctx));
        }
        if (pending.wait() != 0) return -1;
        sent += batch;
    }
    t.stop();
    return (double)t.n_elapsed() / 1e9;
}

// One-sided pool-descriptor round (ISSUE 9): attachments cross the
// ici/shm seam as (pool_id, offset, len, crc) references; the server
// reads them in place and answers with the checksum it computed there.
// Returns logical MB/s, or -1 on any verification failure.
double run_pool_desc_round(benchpb::EchoService_Stub& stub,
                           size_t attachment_bytes, int iters,
                           int* zero_copy_ok) {
    *zero_copy_ok = 1;
    Timer t;
    t.start();
    for (int i = 0; i < iters; ++i) {
        IOBuf att;
        char* data = nullptr;
        if (!IciBlockPool::AllocatePoolAttachment(attachment_bytes, &att,
                                                  &data)) {
            fprintf(stderr, "pool attachment alloc failed\n");
            return -1;
        }
        // Distinct pattern per call so a stale mapping can't pass crc.
        memset(data, 'a' + (i % 26), attachment_bytes);
        data[0] = (char)i;
        const uint32_t crc =
            crc32c_extend(0, data, attachment_bytes);
        Controller cntl;
        cntl.set_timeout_ms(10000);
        cntl.set_request_pool_attachment(std::move(att));
        benchpb::EchoRequest req;
        benchpb::EchoResponse res;
        req.set_send_ts_us(monotonic_time_us());
        stub.Echo(&cntl, &req, &res, nullptr);
        if (cntl.Failed()) {
            fprintf(stderr, "pool-desc rpc failed: %s\n",
                    cntl.ErrorText().c_str());
            return -1;
        }
        char expect[96];
        snprintf(expect, sizeof(expect), "crc32c=%08x len=%llu inline=0",
                 crc, (unsigned long long)attachment_bytes);
        if (res.payload() != expect) {
            fprintf(stderr, "pool-desc verdict mismatch: got '%s' want "
                            "'%s'\n",
                    res.payload().c_str(), expect);
            *zero_copy_ok = 0;
            return -1;
        }
    }
    t.stop();
    const double secs = (double)t.n_elapsed() / 1e9;
    return (double)attachment_bytes * iters / (1024.0 * 1024.0) / secs;
}

// Response-direction descriptor round (ISSUE 12): a tiny request asks
// the server to answer `rsp_bytes` as a pool-block reference; the
// client's resolve path crc-verifies the in-place view against the
// descriptor (the wire contract), and this round additionally
// spot-checks the server's seeded pattern and that ZERO payload bytes
// arrived inline. Returns logical MB/s, or -1 on verification failure.
// Each iteration's controller teardown sends the desc_ack that unpins
// the server's block — the pinned_after gauge proves the cycle.
double run_pool_desc_rsp_round(benchpb::EchoService_Stub& stub,
                               size_t rsp_bytes, int iters,
                               int* zero_copy_ok) {
    *zero_copy_ok = 1;
    Timer t;
    t.start();
    for (int i = 0; i < iters; ++i) {
        Controller cntl;
        cntl.set_timeout_ms(10000);
        benchpb::EchoRequest req;
        benchpb::EchoResponse res;
        char ask[64];
        snprintf(ask, sizeof(ask), "desc_rsp:%zu:%u", rsp_bytes,
                 (unsigned)i);
        req.set_payload(ask);
        req.set_send_ts_us(monotonic_time_us());
        stub.Echo(&cntl, &req, &res, nullptr);
        if (cntl.Failed()) {
            fprintf(stderr, "pool-desc rsp rpc failed: %s\n",
                    cntl.ErrorText().c_str());
            return -1;
        }
        const Controller::PoolAttachment& view =
            cntl.response_pool_attachment();
        if (view.data == nullptr || view.length != rsp_bytes ||
            cntl.response_attachment().size() != 0 ||
            view.data[0] != (char)i ||
            view.data[1] != (char)('a' + i % 26)) {
            fprintf(stderr,
                    "pool-desc rsp verdict mismatch: view=%p len=%llu "
                    "inline=%zu\n",
                    (const void*)view.data,
                    (unsigned long long)view.length,
                    cntl.response_attachment().size());
            *zero_copy_ok = 0;
            return -1;
        }
        // Controller goes out of scope here: the view release acks the
        // server's pin.
    }
    t.stop();
    const double secs = (double)t.n_elapsed() / 1e9;
    return (double)rsp_bytes * iters / (1024.0 * 1024.0) / secs;
}

// qps-vs-caller-fibers scaling sweep (reference docs/cn/benchmark.md:110
// qps_vs_threadnum): N fibers issue SYNC 4KB echoes back-to-back for a
// fixed wall-time slice; near-linear growth to 16 callers is the bar.
struct ScaleCtx {
    benchpb::EchoService_Stub* stub;
    LatencyRecorder* lat;
    std::atomic<bool>* stop;
    std::atomic<int64_t>* calls;
    IOBuf* filler;
};

void* ScaleCaller(void* arg) {
    auto* c = (ScaleCtx*)arg;
    while (!c->stop->load(std::memory_order_relaxed)) {
        Controller cntl;
        cntl.set_timeout_ms(10000);
        benchpb::EchoRequest req;
        benchpb::EchoResponse res;
        req.set_send_ts_us(monotonic_time_us());
        cntl.request_attachment().append(*c->filler);
        c->stub->Echo(&cntl, &req, &res, nullptr);
        if (!cntl.Failed()) {
            *c->lat << (monotonic_time_us() - res.send_ts_us());
            c->calls->fetch_add(1, std::memory_order_relaxed);
        }
    }
    return nullptr;
}

// Runs one sweep level; returns qps and fills *p99_us.
double RunScaleLevel(benchpb::EchoService_Stub& stub, int ncallers,
                     int duration_ms, long long* p99_us) {
    IOBuf filler;
    filler.append(std::string(4096, 'e'));
    LatencyRecorder lat;
    std::atomic<bool> stop{false};
    std::atomic<int64_t> calls{0};
    ScaleCtx ctx{&stub, &lat, &stop, &calls, &filler};
    std::vector<fiber_t> tids((size_t)ncallers);
    const int64_t t0 = monotonic_time_us();
    for (auto& tid : tids) {
        fiber_start_background(&tid, nullptr, ScaleCaller, &ctx);
    }
    usleep(duration_ms * 1000);
    stop.store(true, std::memory_order_relaxed);
    for (auto tid : tids) fiber_join(tid, nullptr);
    const double secs = (double)(monotonic_time_us() - t0) / 1e6;
    *p99_us = (long long)lat.latency_percentile(0.99);
    return (double)calls.load() / secs;
}

// Child mode for the cross-process benchmark/tests: a standalone echo
// server with the ICI handshake enabled, port announced on stdout.
// Exits when stdin reaches EOF (parent closed its pipe or died).
const char* g_tls_cert = nullptr;
const char* g_tls_key = nullptr;

int RunIciServer() {
    prctl(PR_SET_PDEATHSIG, SIGKILL);  // die with the parent
    FLAGS_socket_send_buffer_size.set(1 << 20);
    FLAGS_socket_recv_buffer_size.set(1 << 20);
    if (IciBlockPool::Init() != 0) return 1;
    static EchoServiceImpl service;
    static Server server;
    if (server.AddService(&service) != 0) return 1;
    // Echo never blocks in server mode (no tail injection here):
    // run-to-completion dispatch is safe.
    server.SetMethodInlineSafe("benchpb.EchoService", "Echo");
    static RedisService redis;
    redis.AddBasicKvCommands();
    server.set_redis_service(&redis);
    ServerOptions sopts;
    if (g_tls_cert != nullptr && g_tls_key != nullptr) {
        sopts.tls_cert_path = g_tls_cert;
        sopts.tls_key_path = g_tls_key;
    }
    EndPoint listen;
    str2endpoint("127.0.0.1:0", &listen);
    if (server.Start(listen, &sopts) != 0) return 1;
    printf("PORT %d\n", server.listened_port());
    fflush(stdout);
    char buf[16];
    while (read(0, buf, sizeof(buf)) > 0) {
    }
    // Orderly stop, then _exit: running static destructors in a process
    // whose dispatcher/timer/sampler/worker threads are still live races
    // frees against those threads (observed as an exit-time UAF under
    // ASan). Long-lived server processes skip static teardown by design;
    // Stop+Join is the real shutdown.
    server.Stop();
    server.Join();
    fflush(nullptr);
    _exit(0);
}

// Spawn this binary as --ici-server; returns the child's pid and fills
// *port. *stdin_wr keeps the child alive: closing it shuts the child down.
pid_t SpawnIciServer(int* port, int* stdin_wr) {
    int out_pipe[2], in_pipe[2];
    if (pipe(out_pipe) != 0 || pipe(in_pipe) != 0) return -1;
    const pid_t pid = fork();
    if (pid < 0) return -1;
    if (pid == 0) {
        dup2(out_pipe[1], 1);
        dup2(in_pipe[0], 0);
        close(out_pipe[0]);
        close(out_pipe[1]);
        close(in_pipe[0]);
        close(in_pipe[1]);
        execl("/proc/self/exe", "echo_bench", "--ici-server",
              (char*)nullptr);
        _exit(127);
    }
    close(out_pipe[1]);
    close(in_pipe[0]);
    *stdin_wr = in_pipe[1];
    // Read "PORT <n>\n" from the child.
    char line[64];
    size_t got = 0;
    while (got < sizeof(line) - 1) {
        const ssize_t r = read(out_pipe[0], line + got, 1);
        if (r <= 0) break;
        if (line[got] == '\n') break;
        ++got;
    }
    line[got] = '\0';
    close(out_pipe[0]);
    if (sscanf(line, "PORT %d", port) != 1) {
        kill(pid, SIGKILL);
        waitpid(pid, nullptr, 0);
        return -1;
    }
    return pid;
}

}  // namespace

int main(int argc, char** argv) {
    bool json = false;
    bool use_ici = false;
    bool xproc = false;
    bool tail = false;
    bool scale = false;
    bool pooled = false;
    bool pool_desc = false;
    const char* prof_path = nullptr;
    bool ici_server = false;
    for (int i = 1; i < argc; ++i) {
        if (strcmp(argv[i], "--json") == 0) json = true;
        if (strcmp(argv[i], "--ici") == 0) use_ici = true;
        if (strcmp(argv[i], "--xproc") == 0) xproc = true;
        if (strcmp(argv[i], "--tail") == 0) tail = true;
        if (strcmp(argv[i], "--scale") == 0) scale = true;
        if (strcmp(argv[i], "--pooled") == 0) pooled = true;
        // Canonical spelling: --pool_desc (matches rpc_press and every
        // other underscore flag); the historical --pool-desc is still
        // accepted.
        if (strcmp(argv[i], "--pool_desc") == 0 ||
            strcmp(argv[i], "--pool-desc") == 0) {
            pool_desc = true;
        }
        if (strcmp(argv[i], "--ici-server") == 0) ici_server = true;
        if (strcmp(argv[i], "--help") == 0 || strcmp(argv[i], "-h") == 0) {
            printf(
                "usage: echo_bench [--json] [--ici | --xproc] [--tail] "
                "[--scale] [--pooled]\n"
                "                  [--pool_desc] [--prof FILE] "
                "[--tls-cert F --tls-key F]\n"
                "  --pool_desc   one-sided descriptor rounds, BOTH "
                "directions (requires\n"
                "                --ici or --xproc). Canonical spelling; "
                "--pool-desc is an\n"
                "                accepted alias.\n");
            return 0;
        }
        if (strcmp(argv[i], "--tls-cert") == 0 && i + 1 < argc) {
            g_tls_cert = argv[++i];
        }
        if (strcmp(argv[i], "--tls-key") == 0 && i + 1 < argc) {
            g_tls_key = argv[++i];
        }
        if (strcmp(argv[i], "--prof") == 0 && i + 1 < argc) {
            prof_path = argv[++i];
        }
    }
    if (ici_server) return RunIciServer();
    // Spawn the cross-process server BEFORE any framework threads exist
    // (fork after the dispatcher/fiber workers start is unsafe).
    int xproc_port = 0;
    int xproc_stdin = -1;
    pid_t xproc_pid = -1;
    if (xproc) {
        xproc_pid = SpawnIciServer(&xproc_port, &xproc_stdin);
        if (xproc_pid < 0) {
            fprintf(stderr, "failed to spawn --ici-server child\n");
            return 1;
        }
    }
    // Windowed 1MB messages benefit from fixed large socket buffers on
    // loopback; production connections keep kernel autotuning (-1).
    FLAGS_socket_send_buffer_size.set(1 << 20);
    FLAGS_socket_recv_buffer_size.set(1 << 20);
    EchoServiceImpl service;
    Server server;
    if (server.AddService(&service) != 0) return 1;

    Channel channel;
    ChannelOptions copts;
    copts.timeout_ms = 10000;
    // Pooled mode: one in-flight RPC per connection (the reference's
    // multi-connection headline configuration, docs/cn/benchmark.md:104).
    if (pooled) copts.connection_type = CONNECTION_TYPE_POOLED;
    if (xproc) {
        // Cross-process data plane: TCP handshake to the child, then the
        // shared-memory queue pair (tici/shm_link.h). The server runs in
        // its own process; TCP stays as doorbell + failure detector.
        if (IciBlockPool::Init() != 0) return 1;
        EndPoint ep;
        str2endpoint("127.0.0.1", xproc_port, &ep);
        if (channel.InitIci(ep, &copts) != 0) return 1;
    } else if (use_ici) {
        // ICI data plane: registered-memory pool + software queue pair
        // (the loopback stand-in for the interconnect; see
        // cpp/tici/ici_link.h). One copy per byte instead of TCP's four.
        if (IciBlockPool::Init() != 0) return 1;
        if (server.StartNoListen(nullptr) != 0) return 1;
        IciLink& link = *IciLink::Create();
        SocketOptions sopts;
        sopts.fd = link.second()->event_fd();
        sopts.transport = link.second();
        sopts.owns_transport = true;
        sopts.on_edge_triggered_events = InputMessenger::OnNewMessages;
        sopts.user = server.messenger();
        SocketId server_sid;
        if (Socket::Create(sopts, &server_sid) != 0) return 1;
        SocketOptions ccopts;
        ccopts.fd = link.first()->event_fd();
        ccopts.transport = link.first();
        ccopts.owns_transport = true;
        ccopts.on_edge_triggered_events = InputMessenger::OnNewMessages;
        ccopts.user = Channel::client_messenger();
        SocketId client_sid;
        if (Socket::Create(ccopts, &client_sid) != 0) return 1;
        if (channel.InitWithSocketId(client_sid, &copts) != 0) return 1;
    } else {
        EndPoint listen;
        str2endpoint("127.0.0.1:0", &listen);
        if (server.Start(listen, nullptr) != 0) return 1;
        EndPoint ep;
        str2endpoint("127.0.0.1", server.listened_port(), &ep);
        if (channel.Init(ep, &copts) != 0) return 1;
    }
    benchpb::EchoService_Stub stub(&channel);

    // Run-to-completion (ISSUE 7): the echo handler is cheap and
    // non-blocking, so flag it inline-safe — small requests run on the
    // input fiber and their responses coalesce into one writev per
    // burst. NOT in tail mode: there the handler sleeps (the injected
    // long tail), which would head-of-line-block the connection and
    // defeat the backup request riding the same socket.
    if (!tail) {
        server.SetMethodInlineSafe("benchpb.EchoService", "Echo");
    }

    if (pool_desc) {
        // One-sided descriptor rounds, BOTH directions (ISSUE 12):
        // requires a pool-mapped link (--ici in-process loopback or
        // --xproc shm link) — the Transport seam degrades plain-TCP
        // tries to inline instead, which is exactly what this round must
        // NOT measure.
        if (!use_ici && !xproc) {
            fprintf(stderr, "--pool_desc requires --ici or --xproc\n");
            return 1;
        }
        // 1MB-class slot minus the block header: the largest payload a
        // single slab-class block carries without spilling a class up.
        const size_t kDescBytes = (1u << 20) - 128;
        int zero_copy_ok = 0;
        run_pool_desc_round(stub, kDescBytes, 20, &zero_copy_ok);  // warm
        const int kIters = 200;
        const double mbps =
            run_pool_desc_round(stub, kDescBytes, kIters, &zero_copy_ok);
        if (mbps < 0) return 1;
        // Response direction: the server answers with references into
        // ITS pool; the client resolves them against the
        // handshake-mapped peer pool with zero inline payload bytes.
        int rsp_zero_copy_ok = 0;
        run_pool_desc_rsp_round(stub, kDescBytes, 20,
                                &rsp_zero_copy_ok);  // warm
        const double rsp_mbps = run_pool_desc_rsp_round(
            stub, kDescBytes, kIters, &rsp_zero_copy_ok);
        if (rsp_mbps < 0) return 1;
        // Leak gauge (ISSUE 10 satellite): after the rounds every pinned
        // block must be back in the pool — a nonzero pinned_after in a
        // BENCH record is the descriptor path leaking under load. The
        // LAST response ack may still be in flight (it rides the wire
        // after the RPC completes): give it a bounded moment.
        long long pinned_after = (long long)block_lease::pinned();
        for (int w = 0; w < 100 && pinned_after != 0; ++w) {
            usleep(20 * 1000);
            pinned_after = (long long)block_lease::pinned();
        }
        const long long reaped = (long long)(
            block_lease::expired_reaped() + block_lease::peer_released());
        if (json) {
            printf("{\"pool_desc_mbps\": %.1f, \"pool_desc_calls\": %d, "
                   "\"pool_desc_bytes\": %zu, \"pool_desc_zero_copy\": "
                   "%d, \"pool_desc_rsp_mbps\": %.1f, "
                   "\"pool_desc_rsp_calls\": %d, "
                   "\"pool_desc_rsp_zero_copy\": %d, "
                   "\"pool_desc_rsp_inline_bytes\": 0, "
                   "\"pool_desc_pinned_after\": %lld, "
                   "\"pool_desc_reaped\": %lld}\n",
                   mbps, kIters, kDescBytes, zero_copy_ok, rsp_mbps,
                   kIters, rsp_zero_copy_ok, pinned_after, reaped);
        } else {
            printf("pool-descriptor echo: req %.1f MB/s, rsp %.1f MB/s "
                   "logical (%d calls x %zu bytes each way, zero-copy "
                   "req %s rsp %s, pinned-after %lld, reaped %lld)\n",
                   mbps, rsp_mbps, kIters, kDescBytes,
                   zero_copy_ok ? "verified" : "FAILED",
                   rsp_zero_copy_ok ? "verified" : "FAILED", pinned_after,
                   reaped);
        }
        if (xproc_pid > 0) {
            close(xproc_stdin);
            int status = 0;
            waitpid(xproc_pid, &status, 0);
        }
        return zero_copy_ok && rsp_zero_copy_ok ? 0 : 1;
    }

    if (tail) {
        // Backup-request tail benchmark (reference benchmark.md:126-206):
        // 2% of handler calls sleep echo_slow_us; compare the latency
        // distribution without and with backup requests armed at 2ms.
        run_round(stub, 4096, 500, 16, nullptr, nullptr);  // warmup
        FLAGS_echo_slow_percent.set(2);
        const int kTailIters = 6000;
        LatencyRecorder lat_nb, lat_b;
        lat_nb.expose("tail_echo_nobackup");
        lat_b.expose("tail_echo_backup");
        if (run_round(stub, 4096, kTailIters, 16, &lat_nb, nullptr) < 0) {
            return 1;
        }
        if (run_round(stub, 4096, kTailIters, 16, &lat_b, nullptr, 2) < 0) {
            return 1;
        }
        FLAGS_echo_slow_percent.set(0);
        if (json) {
            printf("{\"tail_p50_us\": %lld, "
                   "\"tail_p99_nobackup_us\": %lld, "
                   "\"tail_p999_nobackup_us\": %lld, "
                   "\"tail_p99_backup_us\": %lld, "
                   "\"tail_p999_backup_us\": %lld}\n",
                   (long long)lat_b.latency_percentile(0.5),
                   (long long)lat_nb.latency_percentile(0.99),
                   (long long)lat_nb.latency_percentile(0.999),
                   (long long)lat_b.latency_percentile(0.99),
                   (long long)lat_b.latency_percentile(0.999));
        } else {
            printf("tail (2%% of calls +%dus), no backup: p50 %lld p99 "
                   "%lld p999 %lld\n",
                   FLAGS_echo_slow_us.get(),
                   (long long)lat_nb.latency_percentile(0.5),
                   (long long)lat_nb.latency_percentile(0.99),
                   (long long)lat_nb.latency_percentile(0.999));
            printf("tail with backup@2ms:          p50 %lld p99 %lld "
                   "p999 %lld\n",
                   (long long)lat_b.latency_percentile(0.5),
                   (long long)lat_b.latency_percentile(0.99),
                   (long long)lat_b.latency_percentile(0.999));
        }
        return 0;
    }

    if (scale) {
        // qps vs caller fibers (reference benchmark.md:110-124).
        run_round(stub, 4096, 500, 16, nullptr, nullptr);  // warmup
        const int levels[] = {1, 4, 16, 64};
        double qps[4];
        long long p99[4];
        for (int i = 0; i < 4; ++i) {
            qps[i] = RunScaleLevel(stub, levels[i], 1500, &p99[i]);
        }
        if (json) {
            printf("{\"scale_qps_1\": %.0f, \"scale_qps_4\": %.0f, "
                   "\"scale_qps_16\": %.0f, \"scale_qps_64\": %.0f, "
                   "\"scale_p99_us_1\": %lld, \"scale_p99_us_4\": %lld, "
                   "\"scale_p99_us_16\": %lld, \"scale_p99_us_64\": "
                   "%lld}\n",
                   qps[0], qps[1], qps[2], qps[3], p99[0], p99[1], p99[2],
                   p99[3]);
        } else {
            for (int i = 0; i < 4; ++i) {
                printf("callers %2d: %8.0f qps  p99 %lldus\n", levels[i],
                       qps[i], p99[i]);
            }
        }
        return 0;
    }

    LatencyRecorder lat;
    lat.expose("rpc_echo_4k_latency");

    // Warmup.
    run_round(stub, 4096, 500, 32, nullptr, nullptr);
    if (prof_path != nullptr) StartCpuProfiler();

    // 4KB round.
    const int kSmallIters = 20000;
    const double small_secs =
        run_round(stub, 4096, kSmallIters, 64, &lat, nullptr);
    if (small_secs < 0) return 1;
    const double qps_4k = kSmallIters / small_secs;
    const long long p50 = (long long)lat.latency_percentile(0.5);
    const long long p99 = (long long)lat.latency_percentile(0.99);

    // 1MB round.
    std::atomic<int64_t> bytes{0};
    const int kBigIters = 300;
    const double big_secs =
        run_round(stub, 1 << 20, kBigIters, 4, nullptr, &bytes);
    if (big_secs < 0) return 1;
    const double mbps = (double)bytes.load() / (1024.0 * 1024.0) / big_secs;
    if (prof_path != nullptr) {
        const int n = StopCpuProfiler(prof_path);
        fprintf(stderr, "wrote %d samples to %s\n", n, prof_path);
    }

    if (json) {
        printf("{\"mbps\": %.1f, \"qps_4k\": %.0f, \"p50_us_4k\": %lld, "
               "\"p99_us_4k\": %lld}\n",
               mbps, qps_4k, p50, p99);
    } else {
        printf("RPC 1MB attachment echo: %.1f MB/s (%d calls)\n", mbps,
               kBigIters);
        printf("RPC 4KB echo: %.0f qps, p50 %lldus, p99 %lldus\n", qps_4k,
               p50, p99);
    }
    if (xproc_pid > 0) {
        close(xproc_stdin);  // child sees stdin EOF and exits
        int status = 0;
        waitpid(xproc_pid, &status, 0);
    }
    return 0;
}
