// Fiber runtime microbench: creation/join rate, yield (context switch)
// latency, butex wake-park round-trip. The reference's comparable numbers
// come from test/bthread_unittest.cpp perf cases (bthread switches are
// ~100-200ns on server cores). Prints one JSON line with --json.
#include <cstdio>
#include <cstring>

#include "tbase/time.h"
#include "tfiber/butex.h"
#include "tfiber/fiber.h"
#include "tfiber/fiber_sync.h"
#include "tvar/latency_recorder.h"

using namespace tpurpc;

static void* noop_fiber(void*) { return nullptr; }

struct YieldCtx {
    int iters;
};

static void* yield_fiber(void* arg) {
    YieldCtx* c = (YieldCtx*)arg;
    for (int i = 0; i < c->iters; ++i) fiber_yield();
    return nullptr;
}

int main(int argc, char** argv) {
    bool json = false;
    for (int i = 1; i < argc; ++i) {
        if (strcmp(argv[i], "--json") == 0) json = true;
    }

    // 1) create+join rate: a clean timed loop first (the headline number),
    // then a separate instrumented loop through the metrics stack (the same
    // LatencyRecorder that MethodStatus uses for every RPC method) so the
    // instrumentation overhead never biases the headline.
    const int kCreate = 20000;
    Timer t;
    t.start();
    for (int i = 0; i < kCreate; ++i) {
        fiber_t tid;
        fiber_start_background(&tid, nullptr, noop_fiber, nullptr);
        fiber_join(tid, nullptr);
    }
    t.stop();
    const double create_us = (double)t.u_elapsed() / kCreate;

    LatencyRecorder create_lat;
    create_lat.expose("fiber_create_join");
    for (int i = 0; i < kCreate; ++i) {
        const int64_t t0 = monotonic_time_us();
        fiber_t tid;
        fiber_start_background(&tid, nullptr, noop_fiber, nullptr);
        fiber_join(tid, nullptr);
        create_lat << (monotonic_time_us() - t0);
    }

    // 2) yield latency: 2 fibers yielding to each other.
    const int kYield = 200000;
    YieldCtx yc{kYield};
    fiber_t a, b;
    t.start();
    fiber_start_background(&a, nullptr, yield_fiber, &yc);
    fiber_start_background(&b, nullptr, yield_fiber, &yc);
    fiber_join(a, nullptr);
    fiber_join(b, nullptr);
    t.stop();
    // Each yield is fiber->main->fiber (2 raw switches).
    const double yield_ns = (double)t.n_elapsed() / (2.0 * kYield);

    if (json) {
        printf("{\"create_join_us\": %.2f, \"yield_ns\": %.0f, "
               "\"create_p99_us\": %lld}\n",
               create_us, yield_ns,
               (long long)create_lat.latency_percentile(0.99));
    } else {
        printf("fiber create+join: %.2f us/op\n", create_us);
        printf("fiber yield (sched round-trip): %.0f ns\n", yield_ns);
        std::string desc;
        Variable::describe_exposed("fiber_create_join", &desc);
        printf("fiber_create_join (via tvar registry): %s\n", desc.c_str());
    }
    return 0;
}
