// Fuzz driver for the HTTP request parser (and the tpu_std frame parser's
// header stage): deterministic seeded mutation loop, no libFuzzer
// dependency (clang is not in this image — reference test/fuzzing/
// fuzz_http.cpp uses libFuzzer; this driver covers the same entry point).
//
//   http_fuzz [iterations] [seed]
//
// Exits non-zero (or crashes under ASan) on any invariant violation:
// parser must make progress on kOk, consume nothing otherwise, and never
// abort/hang.
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>

#include "thttp/http_message.h"

using namespace tpurpc;

int main(int argc, char** argv) {
    long long iters = argc > 1 ? atoll(argv[1]) : 1000000;
    unsigned long long rng = argc > 2 ? strtoull(argv[2], nullptr, 10)
                                      : 0x9e3779b97f4a7c15ull;
    auto next = [&rng]() {
        rng ^= rng << 13;
        rng ^= rng >> 7;
        rng ^= rng << 17;
        return rng;
    };
    const char* seeds[] = {
        "GET /vars?a=b HTTP/1.1\r\nHost: h\r\nAccept: */*\r\n\r\n",
        "POST /flags/x?setvalue=1 HTTP/1.1\r\nContent-Length: 4\r\n\r\n"
        "body",
        "PUT /a/b/c HTTP/1.0\r\nX-Long: "
        "0123456789012345678901234567890123456789\r\n\r\n",
        "DELETE /x HTTP/1.1\r\nConnection: close\r\n\r\n",
        "OPTIONS * HTTP/1.1\r\n\r\n",
    };
    constexpr int nseeds = sizeof(seeds) / sizeof(seeds[0]);
    long long parsed_ok = 0;
    for (long long iter = 0; iter < iters; ++iter) {
        std::string input = seeds[next() % nseeds];
        const int nmut = 1 + (int)(next() % 10);
        for (int m = 0; m < nmut; ++m) {
            switch (next() % 5) {
                case 0:
                    input[next() % input.size()] = (char)next();
                    break;
                case 1:
                    input.resize(next() % (input.size() + 1));
                    break;
                case 2:
                    if (!input.empty()) {
                        input.insert(next() % input.size(),
                                     input.substr(0, next() % 32));
                    }
                    break;
                case 3:
                    for (int i = 0; i < (int)(next() % 16); ++i) {
                        input.push_back((char)next());
                    }
                    break;
                case 4: {  // splice two seeds
                    const char* other = seeds[next() % nseeds];
                    input.insert(next() % (input.size() + 1), other);
                    break;
                }
            }
            if (input.empty()) input = "P";
        }
        IOBuf buf;
        buf.append(input);
        const size_t before = buf.size();
        HttpRequest req;
        const HttpParseStatus st = ParseHttpRequest(&buf, &req);
        if (st == HttpParseStatus::kOk) {
            ++parsed_ok;
            if (buf.size() >= before) {
                fprintf(stderr, "NO PROGRESS on kOk at iter %lld\n", iter);
                return 1;
            }
        } else if (buf.size() != before) {
            fprintf(stderr, "CONSUMED on non-OK at iter %lld\n", iter);
            return 1;
        }
    }
    printf("{\"iters\": %lld, \"parsed_ok\": %lld}\n", iters, parsed_ok);
    return 0;
}
