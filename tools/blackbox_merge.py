#!/usr/bin/env python3
"""blackbox_merge.py [--json] [--last N] DUMP... — merge N flight-recorder
dumps (binary TFRBOX1 files from crash handlers / --blackbox exits, or JSON
documents fetched from live nodes' /blackbox?format=json) into ONE causal
timeline.

Cross-node clock normalization uses the RPC envelope technique (the same
NTP-style math the rpcz stitcher uses): for every call id seen as
RPC_ISSUE(t1)/RPC_RESP_RECV(t4) on the client and RPC_DISPATCH(t2)/
RPC_WRITE(t3) on the server, the pairwise offset estimate is
((t2-t1)+(t3-t4))/2; the median over matched cids cancels asymmetric
delay outliers. Offsets propagate from a reference node (the one with the
most events) over the pairwise graph; nodes with no RPC overlap fall back
to their absolute wall-clock anchors.

Event args echo the recording seams (cpp/tbase/flight_recorder.h):
RPC_* carry a=cid; VERB_* carry a=wr_id (POST/WIRE pack op<<32|len in b);
CHAOS_INJECT packs seed_lo32<<32|op<<8|kind in b. The text timeline
decodes these; --json emits the normalized events raw for scripting
(tests/test_blackbox_forensics.py asserts on that form).
"""
import json
import struct
import sys

FILE_HDR = struct.Struct("<8sIIqqQdqQII64s")
RING_HDR = struct.Struct("<8sIIQII16s")
EVENT = struct.Struct("<QIIQQ")

KIND_NAMES = [
    "NONE", "RPC_ISSUE", "RPC_DISPATCH", "RPC_HANDLER_IN",
    "RPC_HANDLER_OUT", "RPC_WRITE", "RPC_RESP_RECV", "VERB_POST",
    "VERB_WIRE", "VERB_COMPLETE", "VERB_REAP", "LEASE_PIN", "LEASE_ARM",
    "LEASE_RELEASE", "LEASE_EXPIRE", "LEASE_PEER_DEATH", "STREAM_CHUNK",
    "STREAM_CREDIT_STALL", "STREAM_RESUME", "COLL_STEP", "COLL_REFORM",
    "SCHED_INLINE", "SCHED_PARK", "CHAOS_INJECT", "OUTLIER_EJECT",
    "OUTLIER_REINSTATE",
]
K_RPC_ISSUE, K_RPC_DISPATCH = 1, 2
K_RPC_WRITE, K_RPC_RESP_RECV = 5, 6

CHAOS_KIND_NAMES = [
    "none", "delay", "short", "drop", "corrupt", "reset", "refuse",
    "stale_epoch", "cost_inflate", "crash", "fail",
]


def cstr(b):
    return b.split(b"\0", 1)[0].decode("ascii", "replace")


class Node:
    def __init__(self, name, pid, source):
        self.name = name
        self.pid = pid
        self.source = source
        self.wall_us = 0
        self.mono_us = 0
        self.tsc = 0
        self.ticks_per_us = 0.0
        self.dump_mono_us = 0
        self.dump_tsc = 0
        self.dropped = 0
        self.events = []  # dicts: tsc, seq, k, kind, a, b, tid, tname
        self.offset_us = 0.0  # this node's clock minus the reference's
        self.offset_how = "wall-anchor"

    def tpu(self):
        """Ticks per us: prefer the dump-time re-capture (measures THIS
        run's actual rate over the whole process lifetime) when sane."""
        reported = self.ticks_per_us if self.ticks_per_us > 0 else 1.0
        dt_us = self.dump_mono_us - self.mono_us
        dt_tsc = self.dump_tsc - self.tsc
        if dt_us > 1000 and dt_tsc > 0:
            measured = dt_tsc / dt_us
            if 0.5 * reported <= measured <= 2.0 * reported:
                return measured
        return reported

    def wall_of(self, tsc):
        return self.wall_us + (tsc - self.tsc) / self.tpu()


def parse_binary(path, data):
    if len(data) < FILE_HDR.size:
        raise ValueError("truncated header")
    (magic, version, pid, wall_us, mono_us, tsc, tpu, dump_mono_us,
     dump_tsc, nrings, _res, node_name) = FILE_HDR.unpack_from(data, 0)
    if magic != b"TFRBOX1\0":
        raise ValueError("bad magic %r" % magic)
    if version != 1:
        raise ValueError("unknown version %d" % version)
    n = Node(cstr(node_name) or path, pid, path)
    n.wall_us, n.mono_us, n.tsc, n.ticks_per_us = wall_us, mono_us, tsc, tpu
    n.dump_mono_us, n.dump_tsc = dump_mono_us, dump_tsc
    off = FILE_HDR.size
    for _ in range(nrings):
        if off + RING_HDR.size > len(data):
            break  # torn dump (crash mid-write): keep what parsed
        (rmagic, tid, cap, nxt, nvalid, _rres,
         tname) = RING_HDR.unpack_from(data, off)
        if rmagic != b"TFRRING\0":
            break
        off += RING_HDR.size
        nslots = min(nvalid, (len(data) - off) // EVENT.size)
        slots = [EVENT.unpack_from(data, off + i * EVENT.size)
                 for i in range(nslots)]
        off += nslots * EVENT.size
        tname = cstr(tname)
        # Raw slot order on disk; reconstruct [next-nvalid, next) by seq,
        # dropping slots overwritten under the dumper (seq mismatch).
        for s in range(nxt - nvalid, nxt):
            i = s & (cap - 1)
            if i >= nslots:
                continue
            etsc, ekind, eseq, ea, eb = slots[i]
            if eseq != (s & 0xFFFFFFFF):
                continue
            kname = KIND_NAMES[ekind] if ekind < len(KIND_NAMES) else "?"
            n.events.append({"tsc": etsc, "seq": s, "k": ekind,
                             "kind": kname, "a": ea, "b": eb,
                             "tid": tid, "tname": tname})
        if nslots < nvalid:
            break
    return n


def parse_json(path, data):
    doc = json.loads(data)
    n = Node(doc.get("node") or path, doc.get("pid", 0), path)
    n.wall_us = doc.get("wall_us", 0)
    n.mono_us = doc.get("mono_us", 0)
    n.tsc = doc.get("tsc", 0)
    n.ticks_per_us = doc.get("ticks_per_us", 0.0)
    n.dump_mono_us = doc.get("dump_mono_us", 0)
    n.dump_tsc = doc.get("dump_tsc", 0)
    n.dropped = doc.get("dropped", 0)
    for ring in doc.get("rings", []):
        for e in ring.get("events", []):
            n.events.append({"tsc": e["tsc"], "seq": e["seq"], "k": e["k"],
                             "kind": e.get("kind", "?"), "a": e["a"],
                             "b": e["b"], "tid": ring.get("tid", 0),
                             "tname": ring.get("name", "")})
    return n


def load(path):
    with open(path, "rb") as f:
        data = f.read()
    if data[:8] == b"TFRBOX1\0":
        return parse_binary(path, data)
    return parse_json(path, data)


def median(xs):
    xs = sorted(xs)
    m = len(xs) // 2
    if len(xs) % 2:
        return xs[m]
    return (xs[m - 1] + xs[m]) / 2.0


def pair_offset(a, b):
    """Envelope offset estimate of node b's clock minus node a's, from
    RPCs a issued to b. Returns (offset_us, nsamples) or None.

    Correlation ids are only unique within one client PROCESS lifetime:
    a restarted client (each rpc_press phase, a bounced mesh node)
    reuses the same id space, and a server ring that retains history
    then holds MULTIPLE handlings of the "same" cid. Marrying a fresh
    issue to a stale dispatch skews the estimate by the inter-run gap —
    seconds, not RTTs — and because a restarted client replays at a
    similar rate, the stale pairings form their OWN tight cluster that
    can outnumber the true one (e.g. the server was ejected early in
    the fresh run). Two defenses, in order: (1) the wall-clock anchors
    both dump headers carry window the server-side candidates to the
    client dump's own wall span (+/- 1 s slack — same-host clocks are
    identical and NTP keeps peers well inside that; the inter-run gaps
    that create collisions are seconds); (2) the densest 50 ms offset
    cluster among the survivors wins, shedding asymmetric-delay
    stragglers before the median.
    """
    t1, t4, t2, t3 = {}, {}, {}, {}
    for e in a.events:
        if e["k"] == K_RPC_ISSUE:
            t1.setdefault(e["a"], a.wall_of(e["tsc"]))
        elif e["k"] == K_RPC_RESP_RECV:
            t4.setdefault(e["a"], a.wall_of(e["tsc"]))
    if not t1:
        return None
    slack_us = 1_000_000.0
    a_lo = min(t1.values()) - slack_us
    a_hi = max(t4.values()) + slack_us if t4 else max(t1.values()) + slack_us
    for e in b.events:
        if e["k"] == K_RPC_DISPATCH:
            w = b.wall_of(e["tsc"])
            if a_lo <= w <= a_hi:
                t2.setdefault(e["a"], []).append(w)
        elif e["k"] == K_RPC_WRITE:
            w = b.wall_of(e["tsc"])
            if a_lo <= w <= a_hi:
                t3.setdefault(e["a"], []).append(w)
    samples = []
    for cid in t1:
        if cid in t2 and cid in t3 and cid in t4:
            # Chronological zip: each server-side handling of this cid
            # is a dispatch->write pair; order aligns them.
            for d_us, w_us in zip(sorted(t2[cid]), sorted(t3[cid])):
                samples.append(
                    ((d_us - t1[cid]) + (w_us - t4[cid])) / 2.0)
    if not samples:
        return None
    bin_us = 50000.0  # true samples agree well inside one bin
    bins = {}
    for s in samples:
        k = int(s // bin_us)
        bins[k] = bins.get(k, 0) + 1
    best = max(bins,
               key=lambda k: bins.get(k - 1, 0) + bins[k] +
                             bins.get(k + 1, 0))
    keep = [s for s in samples
            if best - 1 <= int(s // bin_us) <= best + 1]
    return median(keep), len(keep)


def normalize(nodes):
    """Assign every node an offset relative to the reference node by
    propagating pairwise envelope offsets breadth-first."""
    if not nodes:
        return
    ref = max(range(len(nodes)), key=lambda i: len(nodes[i].events))
    edges = {}  # (i, j) -> offset of j relative to i
    for i in range(len(nodes)):
        for j in range(len(nodes)):
            if i == j:
                continue
            po = pair_offset(nodes[i], nodes[j])
            if po is not None:
                edges[(i, j)] = po
    done = {ref}
    nodes[ref].offset_us = 0.0
    nodes[ref].offset_how = "reference"
    frontier = [ref]
    while frontier:
        nxt = []
        for i in frontier:
            for j in range(len(nodes)):
                if j in done:
                    continue
                if (i, j) in edges:
                    off, ns = edges[(i, j)]
                    nodes[j].offset_us = nodes[i].offset_us + off
                    nodes[j].offset_how = "envelope, %d samples" % ns
                elif (j, i) in edges:
                    off, ns = edges[(j, i)]
                    nodes[j].offset_us = nodes[i].offset_us - off
                    nodes[j].offset_how = "envelope, %d samples" % ns
                else:
                    continue
                done.add(j)
                nxt.append(j)
        frontier = nxt
    # Unreached nodes keep offset 0: their wall anchors stand alone.


def decode_args(e):
    k, kind, a, b = e["k"], e["kind"], e["a"], e["b"]
    if kind.startswith("RPC_"):
        return "cid=%d b=%d" % (a, b)
    if kind in ("VERB_POST", "VERB_WIRE"):
        return "wr=%d op=%d len=%d" % (a, b >> 32, b & 0xFFFFFFFF)
    if kind in ("VERB_COMPLETE", "VERB_REAP"):
        return "wr=%d status=%d" % (a, b)
    if kind.startswith("LEASE_"):
        return "lease=%d b=%d" % (a, b)
    if kind.startswith("STREAM_"):
        return "stream=%d b=%d" % (a, b)
    if kind == "COLL_STEP":
        return "seq=%d kind=%d step=%d chunk=%d" % (
            a, b >> 48, (b >> 32) & 0xFFFF, b & 0xFFFFFFFF)
    if kind == "CHAOS_INJECT":
        fk = b & 0xFF
        fkname = (CHAOS_KIND_NAMES[fk]
                  if fk < len(CHAOS_KIND_NAMES) else str(fk))
        return "decision=%d seed_lo=%d op=%d fault=%s" % (
            a, b >> 32, (b >> 8) & 0xFFFFFF, fkname)
    if kind in ("OUTLIER_EJECT", "OUTLIER_REINSTATE"):
        # a packs the backend identity ip4<<16|port; EJECT's b packs
        # reason<<56|detail (cpp/trpc/outlier.cc EjectLocked).
        ip = (a >> 16) & 0xFFFFFFFF
        backend = "%d.%d.%d.%d:%d" % (
            (ip >> 24) & 0xFF, (ip >> 16) & 0xFF, (ip >> 8) & 0xFF,
            ip & 0xFF, a & 0xFFFF)
        if kind == "OUTLIER_EJECT":
            reason = b >> 56
            rname = {1: "consecutive_errors",
                     2: "latency_outlier"}.get(reason, str(reason))
            return "backend=%s reason=%s detail=%d" % (
                backend, rname, b & 0xFFFFFFFFFFFFFF)
        return "backend=%s probe_passes=%d" % (backend, b)
    del k
    return "a=%d b=%d" % (a, b)


def main(argv):
    as_json = False
    last = 0
    paths = []
    i = 1
    while i < len(argv):
        arg = argv[i]
        if arg == "--json":
            as_json = True
        elif arg == "--last":
            i += 1
            last = int(argv[i])
        elif arg.startswith("--last="):
            last = int(arg.split("=", 1)[1])
        else:
            paths.append(arg)
        i += 1
    if not paths:
        sys.stderr.write(__doc__ + "\n")
        return 2
    nodes = []
    for p in paths:
        try:
            nodes.append(load(p))
        except (ValueError, OSError, KeyError, json.JSONDecodeError) as ex:
            sys.stderr.write("skip %s: %s\n" % (p, ex))
    if not nodes:
        sys.stderr.write("no parsable dumps\n")
        return 1
    normalize(nodes)
    merged = []
    for n in nodes:
        for e in n.events:
            merged.append({
                "t_us": n.wall_of(e["tsc"]) - n.offset_us,
                "node": n.name, "pid": n.pid, "tid": e["tid"],
                "tname": e["tname"], "seq": e["seq"], "k": e["k"],
                "kind": e["kind"], "a": e["a"], "b": e["b"],
            })
    merged.sort(key=lambda e: e["t_us"])
    if last > 0:
        merged = merged[-last:]
    if as_json:
        json.dump({
            "nodes": [{"name": n.name, "pid": n.pid, "source": n.source,
                       "events": len(n.events), "dropped": n.dropped,
                       "offset_us": n.offset_us, "offset_how": n.offset_how}
                      for n in nodes],
            "events": merged,
        }, sys.stdout)
        sys.stdout.write("\n")
        return 0
    print("blackbox merge: %d nodes, %d events" %
          (len(nodes), len(merged)))
    for n in nodes:
        print("  node %-20s pid=%-7d events=%-7d offset_us=%+.1f (%s)" %
              (n.name, n.pid, len(n.events), n.offset_us, n.offset_how))
    if merged:
        t0 = merged[0]["t_us"]
        print("timeline (us since first event, normalized):")
        for e in merged:
            print("  +%-12.1f %-20s %-16s %-20s %s" %
                  (e["t_us"] - t0, e["node"], e["tname"], e["kind"],
                   decode_args(e)))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
