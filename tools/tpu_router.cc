// tpu_router: the L7 front door (ISSUE 16). One standalone node that
// stands between clients and the mesh:
//
//  - terminates client connections exactly like any serving node (a
//    normal Server: tpu_std + gRPC/h2 + HTTP json doors, the whole
//    builtin portal) — PR-15 edge admission runs HERE when the QoS
//    flags are on (-rpc_qos_enabled, -rpc_tenant_quotas, ...), so a
//    tenant flood is priced and shed before it consumes any mesh
//    bandwidth, and shed verdicts carry backoff hints to clients;
//  - forwards each Echo call over the mesh from INSIDE the handler, so
//    deadline / tenant / priority / session / trace context and the
//    cancel cascade all inherit hop-to-hop (PR 2/3/7/16 plumbing):
//      * sessionless calls ride a SelectiveChannel wrapping the
//        zone-aware LB + deterministic-subsetting stack (PR 14) over
//        file://backends, with HEDGED (backup) requests: after a
//        per-(tenant,method) adaptive delay (p99-derived EWMA with a
//        --hedge_floor_ms floor) a second try goes to a DIFFERENT
//        backend (ExcludedServers), first answer wins, the loser is
//        wire-canceled and its descriptor leases acked (EndRPC).
//        Hedges spend retry budget, and a TERR_OVERLOAD verdict from
//        the mesh disables hedging for the suggested-backoff window —
//        hedging can never amplify an overload;
//      * sticky-session calls (x-tpu-session / request-meta session)
//        are pinned to ONE backend by rendezvous hash over the live
//        set; the pin re-assigns ATOMICALLY (one mutex, observable via
//        /router?format=json) when that backend drains or dies, and a
//        call that lands in the dead window reroutes mid-flight.
//
// Rolling restarts behind the router are client-invisible: the probe
// fiber watches each backend's shared connection for the drain GOAWAY
// (Socket::Draining) and for death, moves the pinned sessions, and the
// LB plane steers sessionless traffic away on its own. The router
// itself drains gracefully on SIGTERM (announce, serve the window,
// GracefulStop, REPORT, exit 0) like every mesh node.
//
// stdin protocol (test_router_restart_soak.py): "report\n" prints one
// "REPORT {json}" line; EOF shuts down (exit 0 after a clean quiesce).
#include <signal.h>
#include <sys/prctl.h>
#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include "bench_echo.pb.h"
#include "tbase/endpoint.h"
#include "tbase/errno.h"
#include "tbase/flags.h"
#include "tbase/flight_recorder.h"
#include "tbase/logging.h"
#include "tbase/time.h"
#include "tfiber/fiber.h"
#include "thttp/http_message.h"
#include "tici/block_lease.h"
#include "tnet/socket.h"
#include "tnet/socket_map.h"
#include "trpc/channel.h"
#include "trpc/combo_channels.h"
#include "trpc/controller.h"
#include "trpc/hedge_model.h"
#include "trpc/naming_service.h"
#include "trpc/qos.h"
#include "trpc/server.h"
#include "trpc/stream.h"
#include "tvar/latency_recorder.h"
#include "tvar/reducer.h"
#include "tvar/variable.h"

using namespace tpurpc;

namespace {

// ---- observability (satellite 3): the rpc_router_* families ----
LazyAdder g_forwards("rpc_router_forwards");
LazyAdder g_forward_failures("rpc_router_forward_failures");
LazyAdder g_hedges("rpc_router_hedges");
LazyAdder g_hedge_wins("rpc_router_hedge_wins");
// Raise-only hedge-delay refreshes from hedged completions while the
// model is starved of clean samples (ISSUE 20 bugfix).
LazyAdder g_hedge_refreshes("rpc_router_hedge_refreshes");
LazyAdder g_reroutes("rpc_router_reroutes");
LazyAdder g_session_repins("rpc_router_session_repins");
LazyAdder g_edge_sheds("rpc_router_edge_sheds");
// Push-stream relay (ISSUE 17): opened relays, backend-side resumes the
// relay performed invisibly to the client, and relayed chunks.
LazyAdder g_stream_relays("rpc_router_stream_relays");
LazyAdder g_stream_relay_resumes("rpc_router_stream_relay_resumes");
LazyAdder g_stream_relay_chunks("rpc_router_stream_relay_chunks");
// Backend-measured forwarding latency (the mesh-side time of each
// forwarded call): rpc_press --via subtracts its client-side p99 from
// this family's p99 to report the router-added latency.
LatencyRecorder g_downstream_latency;

int64_t VarInt(const char* name) {
    std::string v;
    if (!Variable::describe_exposed(name, &v)) return 0;
    return atoll(v.c_str());
}

// ---- adaptive hedge delay (per tenant+method) ----
// p99-derived EWMA (trpc/hedge_model.h): each completed un-hedged
// forward feeds the key's windowed p99 into an EWMA (alpha 1/8); the
// hedge delay is that EWMA (scaled by --hedge_mult_pct) floored at
// --hedge_floor_ms. With no samples yet the floor alone drives — a cold
// router hedges only calls that are already slower than the floor.
// Hedged completions may refresh the estimate raise-only once the model
// is starved of clean samples (ISSUE 20 bugfix: an always-hedged-around
// backend froze its own estimate forever).
int g_hedge_floor_ms = 5;
int g_hedge_mult_pct = 100;  // % of the p99 EWMA
bool g_hedge_enabled = true;

struct HedgeKeyState {
    LatencyRecorder rec;  // hidden (never exposed): windowed p99 source
    HedgeDelayModel model;
};

std::mutex g_hedge_mu;
std::unordered_map<std::string, std::unique_ptr<HedgeKeyState>> g_hedge;

// Overload backpressure: while the mesh sheds (TERR_OVERLOAD seen on a
// forward), hedging is OFF — a hedge is a re-issue, and re-issues are
// exactly what an overloaded fleet cannot absorb.
std::atomic<int64_t> g_hedge_hold_until_us{0};

HedgeKeyState* HedgeStateFor(const std::string& key) {
    std::lock_guard<std::mutex> g(g_hedge_mu);
    auto& slot = g_hedge[key];
    if (slot == nullptr) slot.reset(new HedgeKeyState);
    return slot.get();
}

int64_t HedgeDelayMs(HedgeKeyState* hs) {
    if (!g_hedge_enabled) return -1;
    if (monotonic_time_us() <
        g_hedge_hold_until_us.load(std::memory_order_relaxed)) {
        return -1;  // overload hold window: hedging disabled
    }
    return hs->model.DelayMs(g_hedge_mult_pct, g_hedge_floor_ms);
}

void FeedHedgeSample(HedgeKeyState* hs, int64_t latency_us) {
    hs->rec << latency_us;
    hs->model.FeedClean(hs->rec.latency_percentile(0.99),
                        monotonic_time_us());
}

// ---- backend table + sticky-session pinning ----

struct Backend {
    EndPoint ep;
    std::string key;  // "ip:port" — the rendezvous hash input
    std::unique_ptr<Channel> ch;  // single-server (SocketMap revives it)
    // Pinnable = last probe answered AND the shared connection has not
    // seen the drain GOAWAY. Written by the probe fiber and the sticky
    // failure path; read under g_sticky_mu for atomic re-pins.
    std::atomic<bool> live{false};
    std::atomic<bool> draining{false};
};

std::vector<std::unique_ptr<Backend>> g_backends;

// One mutex guards the session map AND every read of the live set used
// for (re-)pinning, so an observer of /router?format=json can never see
// a session pinned to zero or two live backends mid-transition.
std::mutex g_sticky_mu;
std::unordered_map<std::string, int> g_session_pin;  // session -> index

uint64_t Fnv1a64(const std::string& s) {
    uint64_t h = 1469598103934665603ULL;
    for (unsigned char c : s) {
        h ^= c;
        h *= 1099511628211ULL;
    }
    return h;
}

bool Pinnable(const Backend& b) {
    return b.live.load(std::memory_order_acquire) &&
           !b.draining.load(std::memory_order_acquire);
}

// Pick (or keep) the backend for `session`. Returns the index, or -1
// when no backend is pinnable. Runs under g_sticky_mu.
int PinLocked(const std::string& session) {
    auto it = g_session_pin.find(session);
    if (it != g_session_pin.end() && Pinnable(*g_backends[it->second])) {
        return it->second;
    }
    std::vector<std::string> keys;
    std::vector<int> idx;
    for (size_t i = 0; i < g_backends.size(); ++i) {
        if (Pinnable(*g_backends[i])) {
            keys.push_back(g_backends[i]->key);
            idx.push_back((int)i);
        }
    }
    if (keys.empty()) return -1;
    // Rendezvous (HRW) over the LIVE set: stable under churn — only the
    // sessions of the departed backend move, everyone else stays put.
    const int pick = idx[RendezvousSubset(Fnv1a64(session), keys, 1)[0]];
    if (it != g_session_pin.end()) {
        if (it->second != pick) {
            it->second = pick;
            *g_session_repins << 1;
        }
    } else {
        g_session_pin.emplace(session, pick);  // initial pin, not a repin
    }
    return pick;
}

int PinForSession(const std::string& session) {
    std::lock_guard<std::mutex> g(g_sticky_mu);
    return PinLocked(session);
}

// Flip a backend's health AND move its pinned sessions in ONE critical
// section (the whole point of the one-mutex design): a /router snapshot
// — which renders the live set and the session map under the same lock
// — can never see a session pinned to a backend that the very same
// snapshot reports dead.
void SetHealthAndRepin(int idx, bool live, bool draining) {
    std::lock_guard<std::mutex> g(g_sticky_mu);
    Backend* b = g_backends[idx].get();
    const bool was = Pinnable(*b);
    b->draining.store(draining, std::memory_order_release);
    b->live.store(live, std::memory_order_release);
    if (was && !Pinnable(*b)) {
        for (auto& kv : g_session_pin) {
            if (kv.second == idx) PinLocked(kv.first);
        }
    }
}

// ---- forwarding fabric ----

// Sessionless path: SelectiveChannel -> one zone-aware LB channel over
// file://backends. The LB skips draining/broken servers on its own;
// cross-channel hops (TERR_DRAINING budget-free) ride the Selective
// retry driver; hedges ride the inner channel's backup machinery.
SelectiveChannel g_select;
std::unique_ptr<Channel> g_lb_channel;

bool SessionRetryable(int err) {
    // Errors that prove the pinned backend is gone/refusing — the call
    // was not processed, so a re-pin + re-issue is safe. Deliberately
    // excludes timeouts (the backend may have executed the handler).
    switch (err) {
        case TERR_FAILED_SOCKET:
        case TERR_EOF:
        case TERR_DRAINING:
        case ECONNREFUSED:
        case ECONNRESET:
        case EPIPE:
        case EHOSTDOWN:
            return true;
        default:
            return false;
    }
}

void CopyEchoResponse(Controller* up, Controller* down,
                      const benchpb::EchoResponse& dres,
                      benchpb::EchoResponse* response) {
    response->set_send_ts_us(dres.send_ts_us());
    if (!dres.payload().empty()) response->set_payload(dres.payload());
    if (!down->response_attachment().empty()) {
        up->response_attachment().swap(down->response_attachment());
    }
}

void FailUpstream(Controller* up, Controller* down) {
    *g_forward_failures << 1;
    if (down->ErrorCode() == TERR_OVERLOAD) {
        // Mesh overload: hold hedging for the backoff window, and hand
        // the hint to OUR client (the response meta carries it).
        const int64_t backoff =
            down->suggested_backoff_ms() > 0 ? down->suggested_backoff_ms()
                                             : 200;
        g_hedge_hold_until_us.store(monotonic_time_us() + backoff * 1000,
                                    std::memory_order_relaxed);
        up->set_suggested_backoff_ms(backoff);
    }
    up->SetFailed(down->ErrorCode(), "router->backend: %s",
                  down->ErrorText().c_str());
}

// ---- push-stream relay (ISSUE 17) ----
//
// A streaming request is TERMINATED at the router: the client's stream
// binds to the router (its server registry + replay ring), and a pump
// fiber owns a SEPARATE downstream StreamCall against the pinned
// backend. On backend death the pump re-pins and re-opens downstream
// with resume_from = its own progress — the client never notices; its
// own resumes (router connection loss) hit the router's registry and
// replay from the router's ring.
struct StreamRelayArgs {
    push_stream::StreamWriter up;
    std::string session;
    std::string payload;       // the original "stream:N:key"
    unsigned long long total = 0;  // N (EOS when relaying seq == N)
};

void* RunStreamRelay(void* arg) {
    std::unique_ptr<StreamRelayArgs> a((StreamRelayArgs*)arg);
    push_stream::StreamCall dcall;
    dcall.SeedResume(a->up.resume_from());
    int idle_rounds = 0;
    bool first_open = true;
    while (true) {
        const int idx = PinForSession(a->session);
        if (idx < 0) {
            if (++idle_rounds > 100) {
                a->up.Abort(EHOSTDOWN);
                return nullptr;
            }
            fiber_usleep(100 * 1000);
            continue;
        }
        idle_rounds = 0;
        Backend* b = g_backends[idx].get();
        Controller dcntl;
        dcntl.set_max_retry(0);
        dcntl.set_timeout_ms(2000);
        dcntl.set_session(a->session);
        dcall.PrepareOpen(&dcntl);
        benchpb::EchoRequest dreq;
        dreq.set_payload(a->payload);
        dreq.set_send_ts_us(monotonic_time_us());
        benchpb::EchoResponse dres;
        benchpb::EchoService_Stub stub(b->ch.get());
        stub.Echo(&dcntl, &dreq, &dres, nullptr);  // sync
        if (dcntl.Failed()) {
            if (SessionRetryable(dcntl.ErrorCode())) {
                SetHealthAndRepin(
                    idx, /*live=*/false,
                    b->draining.load(std::memory_order_acquire));
                continue;
            }
            a->up.Abort(dcntl.ErrorCode());
            return nullptr;
        }
        if (!first_open) *g_stream_relay_resumes << 1;
        first_open = false;
        while (true) {
            std::string chunk;
            uint64_t seq = 0;
            const int rc = dcall.Read(&chunk, &seq, 3000);
            if (rc == 0) {
                if (a->up.Write(chunk, seq == a->total) != 0) {
                    return nullptr;  // upstream gone for good
                }
                *g_stream_relay_chunks << 1;
            } else if (rc == 1) {
                return nullptr;  // complete; EOS rode the last chunk
            } else {
                // TERR_EOF (backend died) / timeout: resume downstream.
                break;
            }
        }
    }
}

class RouterEchoService : public benchpb::EchoService {
public:
    void Echo(google::protobuf::RpcController* cntl_base,
              const benchpb::EchoRequest* request,
              benchpb::EchoResponse* response,
              google::protobuf::Closure* done) override {
        Controller* cntl = static_cast<Controller*>(cntl_base);
        *g_forwards << 1;
        // The downstream call is issued INSIDE this handler, so the
        // whole context inherits through the fiber-local server call:
        // deadline cap, tenant/priority/session, trace parenting and
        // the cancel cascade (Channel::CallMethod / combo inheritance).
        if (cntl->has_push_stream_open()) {
            ForwardStream(cntl, request, response);
        } else if (!cntl->session().empty()) {
            ForwardSticky(cntl, request, response);
        } else {
            ForwardHedged(cntl, request, response);
        }
        done->Run();
    }

private:
    static void ForwardHedged(Controller* cntl,
                              const benchpb::EchoRequest* request,
                              benchpb::EchoResponse* response) {
        HedgeKeyState* hs = HedgeStateFor(cntl->tenant() + "/Echo");
        Controller dcntl;
        dcntl.set_max_retry(2);
        dcntl.set_backup_request_ms(HedgeDelayMs(hs));  // -1 = disabled
        dcntl.request_attachment() = cntl->request_attachment();
        benchpb::EchoResponse dres;
        benchpb::EchoService_Stub stub(&g_select);
        const int64_t t0 = monotonic_time_us();
        stub.Echo(&dcntl, request, &dres, nullptr);  // sync
        const int64_t elapsed = monotonic_time_us() - t0;
        if (dcntl.backup_issued()) {
            *g_hedges << 1;
            if (dcntl.backup_won()) *g_hedge_wins << 1;
            // Normally a hedged completion teaches nothing (truncated
            // latency). But a key whose EVERY forward gets hedged never
            // sees a clean sample — its estimate would stay frozen low
            // and the router would hedge 100% of traffic forever. Once
            // starved of clean samples, let the hedged elapsed refresh
            // the estimate raise-only until un-hedged completions
            // return.
            if (!dcntl.Failed() &&
                hs->model.FeedHedged(elapsed, monotonic_time_us())) {
                *g_hedge_refreshes << 1;
            }
        } else if (!dcntl.Failed()) {
            // Only clean un-hedged completions teach the delay model —
            // a hedge-truncated latency would drag the p99 down and
            // make hedging self-amplifying.
            FeedHedgeSample(hs, elapsed);
        }
        if (dcntl.Failed()) {
            FailUpstream(cntl, &dcntl);
            return;
        }
        g_downstream_latency << elapsed;
        CopyEchoResponse(cntl, &dcntl, dres, response);
    }

    static void ForwardStream(Controller* cntl,
                              const benchpb::EchoRequest* request,
                              benchpb::EchoResponse* response) {
        push_stream::StreamWriter up = cntl->accept_stream();
        if (!up.valid()) {
            *g_forward_failures << 1;
            cntl->SetFailed(TERR_INTERNAL, "push-stream accept failed");
            return;
        }
        response->set_send_ts_us(request->send_ts_us());
        if (up.resumed_in_place()) {
            // Client-side resume of a live relay: the router's replay
            // ring + the rebound pump cover it — no second pump.
            return;
        }
        unsigned long long n = 0;
        char key[64] = {0};
        if (sscanf(request->payload().c_str(), "stream:%llu:%63s", &n,
                   key) != 2 ||
            n == 0) {
            up.Abort(TERR_REQUEST);
            cntl->SetFailed(TERR_REQUEST, "bad stream payload");
            return;
        }
        *g_stream_relays << 1;
        auto* a = new StreamRelayArgs;
        a->up = up;
        a->session = cntl->session();
        a->payload = request->payload();
        a->total = n;
        fiber_t tid;
        if (fiber_start_background(&tid, nullptr, RunStreamRelay, a) !=
            0) {
            delete a;
            up.Abort(TERR_INTERNAL);
            cntl->SetFailed(TERR_INTERNAL, "relay spawn failed");
        }
    }

    static void ForwardSticky(Controller* cntl,
                              const benchpb::EchoRequest* request,
                              benchpb::EchoResponse* response) {
        int attempts = 0;
        int last_idx = -1;
        while (true) {
            const int idx = PinForSession(cntl->session());
            if (idx < 0) {
                *g_forward_failures << 1;
                cntl->SetFailed(EHOSTDOWN, "no live backend for session %s",
                                cntl->session().c_str());
                return;
            }
            Backend* b = g_backends[idx].get();
            Controller dcntl;
            dcntl.set_max_retry(0);  // the router drives its own re-pin
            dcntl.request_attachment() = cntl->request_attachment();
            benchpb::EchoResponse dres;
            benchpb::EchoService_Stub stub(b->ch.get());
            const int64_t t0 = monotonic_time_us();
            stub.Echo(&dcntl, request, &dres, nullptr);  // sync
            if (!dcntl.Failed()) {
                if (last_idx >= 0 && last_idx != idx) *g_reroutes << 1;
                g_downstream_latency << monotonic_time_us() - t0;
                CopyEchoResponse(cntl, &dcntl, dres, response);
                return;
            }
            if (++attempts > 4 || !SessionRetryable(dcntl.ErrorCode())) {
                FailUpstream(cntl, &dcntl);
                return;
            }
            // The pinned backend is provably not serving: demote it
            // (moving its sessions atomically), then go around — the
            // next PinForSession picks the re-pinned target.
            SetHealthAndRepin(idx, /*live=*/false,
                              b->draining.load(std::memory_order_acquire));
            last_idx = idx;
        }
    }
};

// ---- backend probing + session maintenance ----

int g_probe_interval_ms = 150;
std::atomic<bool> g_stop{false};
std::atomic<bool> g_watcher_stop{false};

// Mirrored shed count: edge admission runs inside the Server/QoS tier
// (cost quotas + queue sheds); the router republishes those verdicts as
// one rpc_router_edge_sheds family. rpc_server_cost_shed counts COST
// MILLI-UNITS (qos.h kCostUnitMilli = 1000 per request-unit).
int64_t g_last_shed_mirror = 0;

int64_t EdgeShedSourceNow() {
    return VarInt("rpc_server_overload_sheds") +
           VarInt("rpc_server_cost_shed") / 1000;
}

void* ProbeFiber(void*) {
    benchpb::EchoRequest preq;
    while (!g_stop.load(std::memory_order_acquire)) {
        for (size_t i = 0; i < g_backends.size(); ++i) {
            Backend* b = g_backends[i].get();
            Controller pc;
            pc.set_timeout_ms(g_probe_interval_ms);
            pc.set_max_retry(0);
            preq.set_send_ts_us(monotonic_time_us());
            benchpb::EchoResponse pres;
            benchpb::EchoService_Stub stub(b->ch.get());
            stub.Echo(&pc, &preq, &pres, nullptr);
            const bool up = !pc.Failed();
            // Drain detection: the backend's StartDraining GOAWAY marks
            // the shared SocketMap connection (policy_tpu_std). A
            // draining backend still SERVES (in-flight sticky calls
            // finish) but must lose its pins now, not at exit.
            bool draining = false;
            SocketId sid;
            if (up && SocketMap::singleton()->GetOrCreate(
                          b->ep, Channel::client_messenger(), &sid) == 0) {
                SocketUniquePtr s;
                if (Socket::AddressSocket(sid, &s) == 0) {
                    draining = s->Draining();
                }
            }
            SetHealthAndRepin((int)i, up, draining);
        }
        const int64_t shed_now = EdgeShedSourceNow();
        if (shed_now > g_last_shed_mirror) {
            *g_edge_sheds << (shed_now - g_last_shed_mirror);
            g_last_shed_mirror = shed_now;
        }
        fiber_usleep((int64_t)g_probe_interval_ms * 1000);
    }
    return nullptr;
}

// ---- /router portal page (+json) and the REPORT line ----

void RouterStateJson(std::string* out) {
    char buf[512];
    // Live set and session map render under ONE g_sticky_mu hold, the
    // same lock every health flip + re-pin runs under: each snapshot is
    // a consistent cut — a session can never appear pinned to a backend
    // the same snapshot calls dead (the soak polls exactly this).
    std::unique_lock<std::mutex> lk(g_sticky_mu);
    out->append("{\"backends\": [");
    for (size_t i = 0; i < g_backends.size(); ++i) {
        const Backend& b = *g_backends[i];
        snprintf(buf, sizeof(buf),
                 "%s{\"endpoint\": \"%s\", \"live\": %d, \"draining\": %d}",
                 i == 0 ? "" : ", ", b.key.c_str(), Pinnable(b) ? 1 : 0,
                 b.draining.load(std::memory_order_acquire) ? 1 : 0);
        out->append(buf);
    }
    out->append("], \"sessions\": {");
    {
        bool first = true;
        for (const auto& kv : g_session_pin) {
            snprintf(buf, sizeof(buf), "%s\"%s\": \"%s\"",
                     first ? "" : ", ", kv.first.c_str(),
                     g_backends[kv.second]->key.c_str());
            out->append(buf);
            first = false;
        }
    }
    lk.unlock();
    snprintf(
        buf, sizeof(buf),
        "}, \"forwards\": %lld, \"forward_failures\": %lld, "
        "\"hedges\": %lld, \"hedge_wins\": %lld, "
        "\"hedge_refreshes\": %lld, \"reroutes\": %lld, "
        "\"session_repins\": %lld, \"edge_sheds\": %lld, "
        "\"stream_relays\": %lld, \"stream_relay_resumes\": %lld, "
        "\"stream_relay_chunks\": %lld, ",
        (long long)VarInt("rpc_router_forwards"),
        (long long)VarInt("rpc_router_forward_failures"),
        (long long)VarInt("rpc_router_hedges"),
        (long long)VarInt("rpc_router_hedge_wins"),
        (long long)VarInt("rpc_router_hedge_refreshes"),
        (long long)VarInt("rpc_router_reroutes"),
        (long long)VarInt("rpc_router_session_repins"),
        (long long)VarInt("rpc_router_edge_sheds"),
        (long long)VarInt("rpc_router_stream_relays"),
        (long long)VarInt("rpc_router_stream_relay_resumes"),
        (long long)VarInt("rpc_router_stream_relay_chunks"));
    out->append(buf);
    snprintf(buf, sizeof(buf),
             "\"backend_p99_us\": %lld, \"backend_avg_us\": %lld, "
             "\"budget_exhausted\": %lld, \"backup_requests\": %lld}",
             (long long)g_downstream_latency.latency_percentile(0.99),
             (long long)g_downstream_latency.latency(),
             (long long)VarInt("rpc_retry_budget_exhausted"),
             (long long)VarInt("rpc_client_backup_requests"));
    out->append(buf);
}

void RouterPage(Server*, const HttpRequest& req, HttpResponse* res) {
    std::string json;
    RouterStateJson(&json);
    if (req.QueryParam("format") == "json") {
        res->set_content_type("application/json");
        res->Append(json);
        res->Append("\n");
        return;
    }
    res->set_content_type("text/plain");
    res->Append("router state (append ?format=json for the raw object)\n\n");
    res->Append(json);
    res->Append("\n");
}

void PrintReport() {
    std::string json;
    RouterStateJson(&json);
    // Splice the process-level tail the soak asserts on (pins must
    // drain to 0 by exit) into the same REPORT object.
    json.pop_back();  // trailing '}'
    char buf[128];
    snprintf(buf, sizeof(buf), ", \"pool_pinned\": %lld}",
             (long long)block_lease::pinned());
    json.append(buf);
    printf("REPORT %s\n", json.c_str());
    fflush(stdout);
}

// SIGTERM watcher (the -graceful_quit_on_sigterm wiring; same shape as
// mesh_node): announce the drain, serve through the window so clients
// steer away, then stop, report, exit 0.
struct QuitWatchArgs {
    Server* server;
    int drain_ms;
};

void* GracefulQuitWatcher(void* arg) {
    std::unique_ptr<QuitWatchArgs> a((QuitWatchArgs*)arg);
    bool announced = false;
    while (!IsAskedToQuit()) {
        if (g_watcher_stop.load(std::memory_order_acquire)) return nullptr;
        if (!announced && IsAskedToDrain()) {
            a->server->StartDraining();
            announced = true;
            printf("DRAINING\n");
            fflush(stdout);
        }
        fiber_usleep(20 * 1000);
    }
    a->server->StartDraining();
    if (!announced) {
        printf("DRAINING\n");
        fflush(stdout);
    }
    fiber_usleep((int64_t)a->drain_ms * 1000);
    g_stop.store(true, std::memory_order_release);
    a->server->GracefulStop(2000);
    PrintReport();
    fflush(nullptr);
    _exit(0);
    return nullptr;
}

// Unclean-exit black box: dump the flight rings to --blackbox before
// bailing with an error (the crash handler only covers signal deaths).
int FailExit(int code) {
    flight::DumpToConfiguredPath();
    return code;
}

}  // namespace

int main(int argc, char** argv) {
    prctl(PR_SET_PDEATHSIG, SIGKILL);  // die with the driving pytest
    int port = 0;
    int drain_ms = 800;
    const char* backends_file = nullptr;
    const char* blackbox_path = nullptr;
    for (int i = 1; i < argc; ++i) {
        if (strcmp(argv[i], "--port") == 0 && i + 1 < argc) {
            port = atoi(argv[++i]);
        } else if (strcmp(argv[i], "--backends") == 0 && i + 1 < argc) {
            backends_file = argv[++i];
        } else if (strcmp(argv[i], "--drain_ms") == 0 && i + 1 < argc) {
            drain_ms = atoi(argv[++i]);
        } else if (strcmp(argv[i], "--hedge_floor_ms") == 0 && i + 1 < argc) {
            g_hedge_floor_ms = atoi(argv[++i]);
        } else if (strcmp(argv[i], "--hedge_mult_pct") == 0 && i + 1 < argc) {
            g_hedge_mult_pct = atoi(argv[++i]);
        } else if (strcmp(argv[i], "--no_hedge") == 0) {
            g_hedge_enabled = false;
        } else if (strcmp(argv[i], "--probe_interval_ms") == 0 &&
                   i + 1 < argc) {
            g_probe_interval_ms = atoi(argv[++i]);
        } else if (strcmp(argv[i], "--zone") == 0 && i + 1 < argc) {
            SetFlagValue("rpc_zone", argv[++i]);
        } else if (strcmp(argv[i], "--blackbox") == 0 && i + 1 < argc) {
            // Flight-recorder black box (ISSUE 19): fatal-signal dump
            // handler + dump-on-unclean-exit, both to this path.
            blackbox_path = argv[++i];
        } else if (strcmp(argv[i], "--flag") == 0 && i + 1 < argc) {
            std::string kv = argv[++i];
            const size_t eq = kv.find('=');
            if (eq == std::string::npos ||
                !SetFlagValue(kv.substr(0, eq), kv.substr(eq + 1))) {
                fprintf(stderr, "bad --flag %s\n", kv.c_str());
                return 2;
            }
        }
    }
    if (port <= 0 || backends_file == nullptr) {
        fprintf(stderr,
                "usage: tpu_router --port N --backends FILE [--drain_ms N] "
                "[--hedge_floor_ms N] [--hedge_mult_pct N] [--no_hedge] "
                "[--probe_interval_ms N] [--zone NAME] [--blackbox PATH] "
                "[--flag name=value]"
                "...\n"
                "  with --flag graceful_quit_on_sigterm=true: SIGTERM "
                "drains gracefully and exits 0\n");
        return 2;
    }

    {
        char nn[32];
        snprintf(nn, sizeof(nn), "router:%d", port);
        flight::SetNodeName(nn);
    }
    if (blackbox_path != nullptr) {
        flight::InstallCrashHandler(blackbox_path);
    }

    // Backend table from the naming file (same format the LB resolves).
    {
        FILE* f = fopen(backends_file, "r");
        if (f == nullptr) {
            fprintf(stderr, "cannot read %s\n", backends_file);
            return FailExit(1);
        }
        char line[128];
        while (fgets(line, sizeof(line), f) != nullptr) {
            NSNode node;
            if (ParseNamingLine(line, &node) != 0) continue;
            auto b = std::make_unique<Backend>();
            b->ep = node.ep;
            b->key = endpoint2str(node.ep);
            b->ch.reset(new Channel);
            ChannelOptions copts;
            copts.timeout_ms = 2000;  // capped at the inherited budget
            copts.max_retry = 0;
            if (b->ch->Init(b->ep, &copts) != 0) {
                fprintf(stderr, "backend channel init failed for %s\n",
                        b->key.c_str());
                fclose(f);
                return FailExit(1);
            }
            g_backends.push_back(std::move(b));
        }
        fclose(f);
    }
    if (g_backends.empty()) {
        fprintf(stderr, "no backends in %s\n", backends_file);
        return FailExit(1);
    }

    // The sessionless fabric: zone-aware LB (+ subsetting flags) over
    // the shared naming file, wrapped in the Selective retry driver.
    g_lb_channel.reset(new Channel);
    {
        ChannelOptions lopts;
        lopts.timeout_ms = 2000;
        lopts.max_retry = 2;
        const std::string url = std::string("file://") + backends_file;
        if (g_lb_channel->Init(url.c_str(), "rr", &lopts) != 0) {
            fprintf(stderr, "LB channel init failed for %s\n", url.c_str());
            return FailExit(1);
        }
    }
    if (g_select.AddChannel(g_lb_channel.get()) != 0) {
        return FailExit(1);
    }

    // Eager-expose every router family so the FIRST scrape already
    // carries 0-valued counters (metrics-lint contract).
    *g_forwards << 0;
    *g_forward_failures << 0;
    *g_hedges << 0;
    *g_hedge_wins << 0;
    *g_hedge_refreshes << 0;
    *g_reroutes << 0;
    *g_session_repins << 0;
    *g_edge_sheds << 0;
    g_downstream_latency.expose("rpc_router_backend_latency");
    g_last_shed_mirror = EdgeShedSourceNow();

    static RouterEchoService service;
    static Server server;
    if (server.AddService(&service) != 0) return FailExit(1);
    server.RegisterHttpHandler(
        "/router", [](Server* s, const HttpRequest& req, HttpResponse* res) {
            RouterPage(s, req, res);
        });
    EndPoint listen;
    str2endpoint("127.0.0.1", port, &listen);
    if (server.Start(listen, nullptr) != 0) {
        fprintf(stderr, "listen failed on port %d\n", port);
        return FailExit(1);
    }

    fiber_t probe;
    bool have_probe =
        fiber_start_background(&probe, nullptr, ProbeFiber, nullptr) == 0;

    fiber_t quit_watcher;
    bool have_quit_watcher = false;
    {
        auto* qa = new QuitWatchArgs{&server, drain_ms};
        if (fiber_start_background(&quit_watcher, nullptr,
                                   GracefulQuitWatcher, qa) == 0) {
            have_quit_watcher = true;
        } else {
            delete qa;
        }
    }

    printf("READY %d\n", port);
    fflush(stdout);

    char cmd[256];
    while (fgets(cmd, sizeof(cmd), stdin) != nullptr) {
        if (strncmp(cmd, "report", 6) == 0 || strncmp(cmd, "stop", 4) == 0) {
            PrintReport();
        }
    }
    // EOF teardown: the watcher holds a pointer to the stack server —
    // stop and join it first (a racing SIGTERM path _exits instead).
    if (have_quit_watcher) {
        g_watcher_stop.store(true, std::memory_order_release);
        fiber_join(quit_watcher, nullptr);
    }
    g_stop.store(true, std::memory_order_release);
    if (have_probe) fiber_join(probe, nullptr);
    server.Stop();
    server.Join();
    fflush(nullptr);
    _exit(0);  // skip static dtors (long-lived server discipline)
}
