#!/usr/bin/env python3
"""Symbolize a tpurpc cpu_profiler dump (see cpp/tbase/cpu_profiler.h).

Usage: symbolize_prof.py PROFILE [--tree]

Prints a flat profile (sample count per function, descending). With
--tree, also prints the top caller->callee edges from the captured
frame-pointer backtraces.
"""
import bisect
import subprocess
import sys
from collections import Counter
from pathlib import Path


def load(path):
    samples = []
    maps = []
    in_maps = False
    for line in Path(path).read_text().splitlines():
        if line.startswith("--- maps ---"):
            in_maps = True
            continue
        if in_maps:
            maps.append(line)
        elif line.strip():
            samples.append([int(x, 16) for x in line.split()])
    return samples, maps


def parse_maps(maps):
    """Returns sorted list of (start, end, file_offset, path) for x regions."""
    regions = []
    for line in maps:
        parts = line.split()
        if len(parts) < 6 or "x" not in parts[1]:
            continue
        start, end = (int(x, 16) for x in parts[0].split("-"))
        off = int(parts[2], 16)
        regions.append((start, end, off, parts[5]))
    regions.sort()
    return regions


class Symbolizer:
    def __init__(self, regions):
        self.regions = regions
        self.starts = [r[0] for r in regions]
        self.cache = {}

    def region_of(self, addr):
        i = bisect.bisect_right(self.starts, addr) - 1
        if i >= 0:
            start, end, off, path = self.regions[i]
            if addr < end:
                return start, off, path
        return None

    def resolve_batch(self, addrs):
        by_mod = {}
        for a in addrs:
            r = self.region_of(a)
            if r is None:
                self.cache[a] = "??"
                continue
            start, off, path = r
            by_mod.setdefault((start, off, path), []).append(a)
        for (start, off, path), mod_addrs in by_mod.items():
            file_addrs = [hex(a - start + off) for a in mod_addrs]
            try:
                out = subprocess.run(
                    ["addr2line", "-f", "-C", "-e", path] + file_addrs,
                    capture_output=True, text=True, timeout=120,
                ).stdout.splitlines()
            except Exception:
                out = []
            funcs = out[0::2]
            for a, fn in zip(mod_addrs, funcs):
                name = fn if fn and fn != "??" else Path(path).name + "+?"
                self.cache[a] = name
            for a in mod_addrs:
                self.cache.setdefault(a, Path(path).name + "+?")

    def name(self, addr):
        return self.cache.get(addr, "??")


def main():
    prof = sys.argv[1]
    tree = "--tree" in sys.argv
    samples, maps = load(prof)
    if not samples:
        print("no samples")
        return
    sym = Symbolizer(parse_maps(maps))
    all_addrs = {a for row in samples for a in row}
    sym.resolve_batch(sorted(all_addrs))

    flat = Counter(sym.name(row[0]) for row in samples)
    total = len(samples)
    print(f"== flat profile ({total} samples) ==")
    for name, n in flat.most_common(40):
        print(f"{n:8d} {100.0 * n / total:5.1f}%  {name}")

    if tree:
        edges = Counter()
        for row in samples:
            for i in range(len(row) - 1):
                edges[(sym.name(row[i + 1]), sym.name(row[i]))] += 1
        print("\n== top edges (caller -> callee) ==")
        for (caller, callee), n in edges.most_common(30):
            print(f"{n:8d}  {caller} -> {callee}")


if __name__ == "__main__":
    main()
