#!/usr/bin/env python3
"""Symbolize a tpurpc profiler dump (see cpp/tbase/cpu_profiler.h and
cpp/tbase/heap_profiler.h).

Usage: symbolize_prof.py PROFILE [--tree]

Accepts both dump formats:
  * cpu:   one "pc fp1 fp2 ..." hex line per sample (weight 1 each)
  * heap/growth:  "<bytes> <count> @ pc1 pc2 ..." weighted stack lines
    (the /hotspots/heap?raw=1 and /hotspots/growth?raw=1 responses)

Prints a flat profile (weight per function, descending). With --tree,
also prints the top caller->callee edges from the captured backtraces.
When addr2line yields no symbol (stripped binary, JIT region), falls
back to module+0x<offset> so every address stays attributable offline.
"""
import bisect
import subprocess
import sys
from collections import Counter
from pathlib import Path


def load(path):
    """Returns (samples, maps, weighted): samples are (weight, [pcs])."""
    samples = []
    maps = []
    in_maps = False
    weighted = False
    for line in Path(path).read_text().splitlines():
        if line.startswith("--- maps ---"):
            in_maps = True
            continue
        if in_maps:
            maps.append(line)
            continue
        if not line.strip():
            continue
        if line.startswith(("heap profile:", "growth profile:")):
            weighted = True
            continue
        if " @ " in line or " @" == line[-2:]:
            head, _, stack = line.partition("@")
            parts = head.split()
            weight = int(parts[0]) if parts else 0
            pcs = [int(x, 16) for x in stack.split()]
            # The heap dump's stack-table overflow bucket is a single
            # pc of 0 — keep its weight so totals match the header
            # (Symbolizer names addr 0 "[stack-table overflow]").
            if pcs:
                samples.append((weight, pcs))
                weighted = True
        else:
            samples.append((1, [int(x, 16) for x in line.split()]))
    return samples, maps, weighted


def parse_maps(maps):
    """Returns sorted list of (start, end, file_offset, path) for x regions."""
    regions = []
    for line in maps:
        parts = line.split()
        if len(parts) < 6 or "x" not in parts[1]:
            continue
        start, end = (int(x, 16) for x in parts[0].split("-"))
        off = int(parts[2], 16)
        regions.append((start, end, off, parts[5]))
    regions.sort()
    return regions


class Symbolizer:
    def __init__(self, regions):
        self.regions = regions
        self.starts = [r[0] for r in regions]
        self.cache = {}

    def region_of(self, addr):
        i = bisect.bisect_right(self.starts, addr) - 1
        if i >= 0:
            start, end, off, path = self.regions[i]
            if addr < end:
                return start, off, path
        return None

    def resolve_batch(self, addrs):
        by_mod = {}
        for a in addrs:
            r = self.region_of(a)
            if r is None:
                self.cache[a] = "??"
                continue
            start, off, path = r
            by_mod.setdefault((start, off, path), []).append(a)
        for (start, off, path), mod_addrs in by_mod.items():
            file_addrs = [hex(a - start + off) for a in mod_addrs]
            try:
                out = subprocess.run(
                    ["addr2line", "-f", "-C", "-e", path] + file_addrs,
                    capture_output=True, text=True, timeout=120,
                ).stdout.splitlines()
            except Exception:
                out = []
            funcs = out[0::2]
            # Offline fallback: module+0x<file offset> — stable across
            # runs of the same binary, greppable in objdump output.
            def fallback(a):
                return "%s+0x%x" % (Path(path).name, a - start + off)
            for a, fn in zip(mod_addrs, funcs):
                self.cache[a] = fn if fn and fn != "??" else fallback(a)
            for a in mod_addrs:
                self.cache.setdefault(a, fallback(a))

    def name(self, addr):
        if addr == 0:
            return "[stack-table overflow]"
        return self.cache.get(addr, "??")


def main():
    prof = sys.argv[1]
    tree = "--tree" in sys.argv
    samples, maps, weighted = load(prof)
    if not samples:
        print("no samples")
        return
    sym = Symbolizer(parse_maps(maps))
    all_addrs = {a for _, row in samples for a in row if a}
    sym.resolve_batch(sorted(all_addrs))

    unit = "bytes" if weighted else "samples"
    flat = Counter()
    for w, row in samples:
        flat[sym.name(row[0])] += w
    total = sum(flat.values())
    print(f"== flat profile ({total} {unit}) ==")
    for name, n in flat.most_common(40):
        print(f"{n:12d} {100.0 * n / max(total, 1):5.1f}%  {name}")

    if tree:
        edges = Counter()
        for w, row in samples:
            for i in range(len(row) - 1):
                edges[(sym.name(row[i + 1]), sym.name(row[i]))] += w
        print(f"\n== top edges (caller -> callee, {unit}) ==")
        for (caller, callee), n in edges.most_common(30):
            print(f"{n:12d}  {caller} -> {callee}")


if __name__ == "__main__":
    main()
