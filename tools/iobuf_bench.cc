// IOBuf zero-copy pipeline microbench: append / cut / writev-readv over a
// pipe, the data motion under every RPC. Interim stand-in until echo_bench
// (full-stack loopback echo) exists. Prints one JSON line with --json.
#include <fcntl.h>
#include <unistd.h>

#include <cerrno>
#include <cstdio>
#include <cstring>
#include <string>

#include "tbase/iobuf.h"
#include "tbase/time.h"

using namespace tpurpc;

int main(int argc, char** argv) {
    bool json = false;
    for (int i = 1; i < argc; ++i) {
        if (strcmp(argv[i], "--json") == 0) json = true;
    }
    int fds[2];
    if (pipe(fds) != 0) return 1;
    // Non-blocking both ends: a single thread plays writer and reader, and a
    // blocking writev of more than the pipe capacity would deadlock.
    fcntl(fds[0], F_SETFL, O_NONBLOCK);
    fcntl(fds[1], F_SETFL, O_NONBLOCK);

    const size_t kMsg = 1 << 20;  // 1MB messages
    const int kIters = 300;
    std::string payload(kMsg, 'x');

    Timer t;
    t.start();
    size_t total = 0;
    for (int i = 0; i < kIters; ++i) {
        IOBuf out;
        out.append(payload.data(), payload.size());
        IOBuf echoed;
        IOPortal in;
        while (!out.empty() || echoed.size() < kMsg) {
            if (!out.empty()) {
                ssize_t w = out.cut_into_file_descriptor(fds[1], 65536);
                if (w < 0 && errno != EAGAIN) return 1;
            }
            ssize_t r = in.append_from_file_descriptor(fds[0], 65536);
            if (r < 0 && errno != EAGAIN) return 1;
            in.cutn(&echoed, in.size());
        }
        total += echoed.size();
    }
    t.stop();
    const double secs = (double)t.n_elapsed() / 1e9;
    const double mbps = (double)total / (1 << 20) / secs;
    if (json) {
        printf("{\"mbps\": %.1f, \"iters\": %d, \"msg_bytes\": %zu}\n", mbps,
               kIters, kMsg);
    } else {
        printf("IOBuf pipe pipeline: %.1f MB/s over %d x %zuB messages\n",
               mbps, kIters, kMsg);
    }
    return 0;
}
