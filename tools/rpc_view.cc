// rpc_view: proxy that renders another server's builtin portal pages
// (reference tools/rpc_view — point a browser at a box that can't be
// reached directly, or aggregate a remote server's /status /vars /rpcz).
//
//   rpc_view --server=ip:port [--port=8888]
//
// GET <path> on the view port fetches http://server<path> and relays the
// body. The view server is a normal tpurpc Server, so it also serves its
// OWN portal under /view-self/*.
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdio>
#include <cstring>
#include <string>

#include "tbase/endpoint.h"
#include "thttp/http_message.h"
#include "trpc/server.h"

using namespace tpurpc;

namespace {

EndPoint g_target;

// Minimal blocking HTTP/1.1 GET (Connection: close).
bool FetchFromTarget(const std::string& path, std::string* status_line,
                     std::string* body) {
    const int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return false;
    sockaddr_in addr;
    endpoint2sockaddr(g_target, &addr);
    if (::connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
        close(fd);
        return false;
    }
    const std::string req = "GET " + path +
                            " HTTP/1.1\r\nHost: view\r\nConnection: "
                            "close\r\n\r\n";
    if (write(fd, req.data(), req.size()) != (ssize_t)req.size()) {
        close(fd);
        return false;
    }
    std::string raw;
    char buf[8192];
    ssize_t r;
    while ((r = read(fd, buf, sizeof(buf))) > 0) raw.append(buf, (size_t)r);
    close(fd);
    const size_t eol = raw.find("\r\n");
    const size_t hdr_end = raw.find("\r\n\r\n");
    if (eol == std::string::npos || hdr_end == std::string::npos) {
        return false;
    }
    *status_line = raw.substr(0, eol);
    *body = raw.substr(hdr_end + 4);
    return true;
}

void HandleProxy(Server*, const HttpRequest& req, HttpResponse* res) {
    std::string status_line, body;
    const std::string path =
        req.query.empty() ? req.path : req.path + "?" + req.query;
    if (!FetchFromTarget(path, &status_line, &body)) {
        res->status = 502;
        res->set_content_type("text/plain");
        res->Append("cannot reach " + endpoint2str(g_target) + "\n");
        return;
    }
    // "HTTP/1.1 200 OK" -> 200
    const size_t sp = status_line.find(' ');
    if (sp != std::string::npos) {
        res->status = atoi(status_line.c_str() + sp + 1);
    }
    res->set_content_type("text/plain");
    res->Append(body);
}

}  // namespace

int main(int argc, char** argv) {
    std::string server_str;
    int port = 8888;
    for (int i = 1; i < argc; ++i) {
        if (strncmp(argv[i], "--server=", 9) == 0) server_str = argv[i] + 9;
        if (strncmp(argv[i], "--port=", 7) == 0) port = atoi(argv[i] + 7);
    }
    if (server_str.empty()) {
        fprintf(stderr, "usage: rpc_view --server=ip:port [--port=N]\n");
        return 1;
    }
    if (hostname2endpoint(server_str.c_str(), &g_target) != 0) {
        fprintf(stderr, "bad server address: %s\n", server_str.c_str());
        return 1;
    }
    Server server;
    // Proxy the portal pages + everything else. User registrations are
    // first-wins, so these front-run the view server's own builtins.
    for (const char* p :
         {"/", "/health", "/status", "/vars", "/flags", "/connections",
          "/rpcz", "/fibers", "/metrics"}) {
        server.RegisterHttpHandler(p, HandleProxy);
    }
    server.RegisterHttpHandler("/*", HandleProxy);
    EndPoint listen;
    str2endpoint("0.0.0.0", port, &listen);
    if (server.Start(listen, nullptr) != 0) {
        fprintf(stderr, "cannot listen on %d\n", port);
        return 1;
    }
    printf("viewing %s on http://0.0.0.0:%d/ (e.g. /status, /vars, "
           "/rpcz)\n",
           endpoint2str(g_target).c_str(), server.listened_port());
    fflush(stdout);
    // Serve until stdin closes (same convention as echo_bench's child).
    char buf[16];
    while (read(0, buf, sizeof(buf)) > 0) {
    }
    server.Stop();
    server.Join();
    fflush(nullptr);
    _exit(0);
}
