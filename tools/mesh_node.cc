// mesh_node: one member of the multi-process full-mesh chaos soak
// (tests/test_chaos_soak.py drives 8 of these).
//
// Each node is BOTH a server and a client of every peer:
//  - a tpu_std echo Server on 127.0.0.1:--port (with the whole builtin
//    portal: /vars, /chaos, /connections, ...);
//  - an LB channel over "file://<peers>" with the rr balancer —
//    naming-service membership, circuit breaker, health-checked server
//    sockets, retries: the standard client-robustness stack;
//  - one shared-memory ICI link per peer (tici/shm_link.h) carrying the
//    mesh echo traffic, re-established by a maintenance fiber when a
//    peer dies and comes back.
//
// Invariant instrumented here and asserted by the soak: every issued
// RPC terminates (sync calls + a final outstanding==0 check), under
// peer kill, partition (fault injection via each node's /chaos page)
// and heal.
//
// stdin protocol (like echo_bench --ici-server): "stop\n" stops traffic
// and prints one "REPORT {json}" line; EOF shuts the node down
// (Stop+Join, then _exit(0) — exit code 0 only after a clean quiesce).
//
// Delay-heavy phase (the deadline/budget soak): "delay H S\n" makes the
// echo handler sleep H ms and turns on a stale-traffic fiber issuing
// budget-starved calls (1 ms and S ms deadlines) marked req.stale; the
// handler counts executed stale requests so the soak can assert the
// server SHED them (expired / budget-below-service-time) instead of
// executing work nobody will read. "--timeout_cl_ms N" enables the
// server's TimeoutConcurrencyLimiter for the budget-shed path.
#include <netinet/in.h>
#include <signal.h>
#include <sys/prctl.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

#include "bench_echo.pb.h"
#include "rpc_meta.pb.h"
#include "tbase/endpoint.h"
#include "tbase/errno.h"
#include "tbase/flags.h"
#include "tbase/flight_recorder.h"
#include "tbase/logging.h"
#include "tbase/time.h"
#include "tfiber/fiber.h"
#include "tnet/fault_injection.h"
#include "tnet/transport.h"
#include "trpc/naming_service.h"
#include "tici/block_lease.h"
#include "tici/block_pool.h"
#include "tici/shm_link.h"
#include "tici/verbs.h"
#include "trpc/channel.h"
#include "trpc/collective.h"
#include "trpc/collective_benchpb.h"
#include "trpc/controller.h"
#include "trpc/pb_compat.h"
#include "trpc/policy_tpu_std.h"
#include "trpc/server.h"
#include "trpc/stream.h"
#include "tvar/variable.h"

using namespace tpurpc;

namespace {

// Delay-phase knobs (stdin "delay H S"): handler sleep + stale-call
// budget. Stale executions are the soak's proof of (non-)shedding.
std::atomic<int> g_handler_delay_ms{0};
// Inter-token generation delay of the push-stream handler (ISSUE 17) —
// models a device decode step per token.
std::atomic<int64_t> g_stream_token_delay_us{2000};
std::atomic<int> g_stale_budget_ms{0};
std::atomic<int64_t> g_stale_executed{0};
// --traffic_delay_ms: traffic fibers idle this long after launch so a
// whole mesh can finish listening first. The rolling-restart soak needs
// it: a connect-refused burst at startup would spend retry-budget
// tokens the soak asserts are NEVER spent.
std::atomic<int> g_traffic_delay_ms{0};

struct NodeState;
void TrafficStartDelay(NodeState* st);

// Detached token generator for one accepted push stream (ISSUE 17):
// writes "tok:<key>:<i>" for i = resume_from+1 .. n with a per-token
// delay. DETERMINISTIC in (key, i) — a restarted process regenerates
// exactly the tokens the client has not seen, which is what makes the
// resume exactly-once across process death.
struct StreamGenArgs {
    push_stream::StreamWriter w;
    unsigned long long n = 0;
    std::string key;
};

void* RunStreamGen(void* arg) {
    std::unique_ptr<StreamGenArgs> a((StreamGenArgs*)arg);
    const int64_t delay =
        g_stream_token_delay_us.load(std::memory_order_relaxed);
    for (unsigned long long i = a->w.resume_from() + 1; i <= a->n; ++i) {
        char tok[128];
        snprintf(tok, sizeof(tok), "tok:%s:%llu", a->key.c_str(), i);
        if (a->w.Write(tok, i == a->n) != 0) break;
        if (delay > 0 && i < a->n) fiber_usleep(delay);
    }
    return nullptr;
}

class EchoServiceImpl : public benchpb::EchoService {
public:
    void Echo(google::protobuf::RpcController* cntl_base,
              const benchpb::EchoRequest* request,
              benchpb::EchoResponse* response,
              google::protobuf::Closure* done) override {
        Controller* cntl = static_cast<Controller*>(cntl_base);
        if (request->stale()) {
            g_stale_executed.fetch_add(1, std::memory_order_relaxed);
        }
        const int delay_ms = g_handler_delay_ms.load(std::memory_order_relaxed);
        if (delay_ms > 0) {
            fiber_usleep((int64_t)delay_ms * 1000);
        }
        // Chain forwarding (rpcz stitch soak): pop the head endpoint and
        // call it with the tail FROM INSIDE this handler — the downstream
        // call inherits the remaining deadline, registers for the cancel
        // cascade, and continues this request's trace (its client span
        // parents on this hop's server span).
        if (request->chain_size() > 0) {
            EndPoint next;
            if (str2endpoint(request->chain(0).c_str(), &next) != 0) {
                cntl->SetFailed(22, "bad chain endpoint %s",
                                request->chain(0).c_str());
            } else {
                Channel ch;
                ChannelOptions copts;
                copts.timeout_ms = 2000;  // capped at the inherited budget
                copts.max_retry = 0;
                if (ch.Init(next, &copts) != 0) {
                    cntl->SetFailed(22, "chain channel init failed");
                } else {
                    benchpb::EchoService_Stub stub(&ch);
                    Controller dcntl;
                    benchpb::EchoRequest dreq;
                    benchpb::EchoResponse dres;
                    dreq.set_send_ts_us(monotonic_time_us());
                    for (int i = 1; i < request->chain_size(); ++i) {
                        dreq.add_chain(request->chain(i));
                    }
                    stub.Echo(&dcntl, &dreq, &dres, nullptr);  // sync
                    if (dcntl.Failed()) {
                        cntl->SetFailed(dcntl.ErrorCode(),
                                        "downstream %s: %s",
                                        request->chain(0).c_str(),
                                        dcntl.ErrorText().c_str());
                    }
                }
            }
        }
        // Response-direction descriptor (ISSUE 12): a "desc_rsp:N"
        // payload asks for N bytes answered as a reference into THIS
        // node's pool — the server-side pin the pool chaos soak
        // SIGKILLs clients under (peer death must release it through
        // the socket failure observer, never strand it).
        unsigned long long rsp_n = 0;
        if (sscanf(request->payload().c_str(), "desc_rsp:%llu", &rsp_n) ==
                1 &&
            rsp_n > 0 && rsp_n <= (4u << 20)) {
            IOBuf out;
            char* data = nullptr;
            if (IciBlockPool::AllocatePoolAttachment((size_t)rsp_n, &out,
                                                     &data)) {
                memset(data, 'r', (size_t)rsp_n);
                cntl->set_response_pool_attachment(std::move(out));
            }
        }
        // Push-stream serving (ISSUE 17): a "stream:N:key" payload asks
        // for N tokens streamed after this response. An in-place resume
        // (same process, generator still live) must NOT start a second
        // generator — the replay ring + the rebound writer continue it.
        unsigned long long stream_n = 0;
        char stream_key[64] = {0};
        if (sscanf(request->payload().c_str(), "stream:%llu:%63s",
                   &stream_n, stream_key) == 2 &&
            stream_n > 0 && stream_n <= (1u << 20)) {
            push_stream::StreamWriter w = cntl->accept_stream();
            if (!w.valid()) {
                cntl->SetFailed(TERR_REQUEST,
                                "stream payload without push open");
            } else if (!w.resumed_in_place()) {
                auto* a = new StreamGenArgs;
                a->w = w;
                a->n = stream_n;
                a->key = stream_key;
                fiber_t tid;
                if (fiber_start_background(&tid, nullptr, RunStreamGen,
                                           a) != 0) {
                    delete a;
                    w.Abort(TERR_INTERNAL);
                    cntl->SetFailed(TERR_INTERNAL,
                                    "stream generator spawn failed");
                }
            }
        }
        response->set_send_ts_us(request->send_ts_us());
        cntl->response_attachment().append(cntl->request_attachment());
        done->Run();
    }
};

struct Counters {
    std::atomic<int64_t> lb_issued{0}, lb_ok{0}, lb_failed{0};
    std::atomic<int64_t> shm_issued{0}, shm_ok{0}, shm_failed{0};
    // Collective rounds driven by this node (ISSUE 13): every issued
    // round terminates (ok or failed — zero lost completions), and a
    // completed round's result is VERIFIED against the deterministic
    // inputs of the membership it completed over (verify_failed must
    // stay 0 through kills and re-forms).
    std::atomic<int64_t> coll_issued{0}, coll_ok{0}, coll_failed{0};
    std::atomic<int64_t> coll_verify_failed{0};
    std::atomic<int64_t> coll_nranks_last{0};
    std::atomic<int64_t> stale_issued{0}, stale_ok{0}, stale_failed{0};
    // One-sided descriptor traffic (ISSUE 10): every call pins a pool
    // block under a lease; desc_stale counts TERR_STALE_EPOCH fences
    // (EXPECTED retriable failures under chaos_pool stale injection).
    std::atomic<int64_t> desc_issued{0}, desc_ok{0}, desc_failed{0};
    std::atomic<int64_t> desc_stale{0};
    // One-sided verb traffic (ISSUE 18): REMOTE_WRITE + REMOTE_READ
    // round-trips against leased peer windows; verbs_stale counts
    // TERR_STALE_EPOCH fences (expected retriable failures under
    // pool_stale chaos), regrants counts window (re-)grants.
    std::atomic<int64_t> verbs_issued{0}, verbs_ok{0}, verbs_failed{0};
    std::atomic<int64_t> verbs_stale{0}, verbs_regrants{0};
    // Response-direction descriptors resolved by this node's CLIENT
    // side (ISSUE 12): desc_rsp_ok counts calls whose answer arrived as
    // a verified in-place view of the peer's pool.
    std::atomic<int64_t> desc_rsp_issued{0}, desc_rsp_ok{0};
    std::atomic<int64_t> expired_probes{0};
    std::atomic<int64_t> outstanding{0};
    std::atomic<int64_t> reconnects{0};
};

// One link to a peer; the channel is replaced on reconnect (a Channel
// pins one socket for its lifetime). Intra-pod peers ride shm-ICI
// links; cross-pod peers (--dcn_peers, ISSUE 14) ride pinned dcn-tier
// channels — plain TCP flagged dcn, so descriptors degrade to inline,
// bytes land on rpc_transport_*{transport="dcn"}, and the -dcn_emu_*
// knobs shape them.
struct PeerLink {
    EndPoint ep;
    bool dcn = false;
    std::string zone;  // the peer's zone ("" = mine)
    std::mutex mu;
    std::shared_ptr<Channel> ch;  // null until connected
};

struct NodeState {
    std::vector<std::unique_ptr<PeerLink>> links;
    std::unique_ptr<Channel> lb_channel;
    Counters counters;
    std::atomic<bool> stop{false};
    // Traffic fibers, joinable from EITHER the stdin "stop" path or the
    // SIGTERM graceful-quit watcher — the exchange guard keeps the join
    // single-shot (double fiber_join is UB).
    std::vector<fiber_t> traffic_fibers;
    std::atomic<bool> fibers_joined{false};
    // Tells the GracefulQuitWatcher fiber to exit: it holds raw pointers
    // to main()'s stack-local Server/NodeState, so the stdin-EOF
    // teardown must stop and JOIN it before those objects die.
    std::atomic<bool> watcher_stop{false};

    void StopTraffic() {
        stop.store(true, std::memory_order_relaxed);
        if (!fibers_joined.exchange(true, std::memory_order_acq_rel)) {
            for (fiber_t t : traffic_fibers) fiber_join(t, nullptr);
        }
    }
};

void TrafficStartDelay(NodeState* st) {
    const int64_t until =
        monotonic_time_us() +
        (int64_t)g_traffic_delay_ms.load(std::memory_order_relaxed) * 1000;
    while (monotonic_time_us() < until &&
           !st->stop.load(std::memory_order_relaxed)) {
        fiber_usleep(20 * 1000);
    }
}

// ---------------- collectives (ISSUE 13) ----------------

int g_my_port = 0;
std::string g_my_zone;  // --zone (also sets -rpc_zone)

// Live membership from the mesh's link table: a peer is a member while
// its shm channel is up (LinkMaintenanceFiber re-establishes dead ones,
// so a restarted node rejoins the collective automatically). Keys are
// listen ports — stable, unique, and identical in every node's view.
class MeshMembership : public CollectiveMembership {
public:
    explicit MeshMembership(NodeState* st) : st_(st) {}
    void GetMembers(std::vector<Member>* out) override {
        Member self;
        self.key = (uint64_t)g_my_port;
        self.self = true;
        self.zone = g_my_zone;
        out->push_back(self);
        for (auto& lp : st_->links) {
            std::shared_ptr<Channel> ch;
            {
                std::lock_guard<std::mutex> g(lp->mu);
                ch = lp->ch;
            }
            if (ch == nullptr) continue;
            SocketUniquePtr s = SocketUniquePtr::FromId(ch->pinned_socket());
            if (!s || s->Failed()) continue;
            Member m;
            m.key = (uint64_t)lp->ep.port;
            m.chan = ch;
            m.zone = lp->dcn ? lp->zone : g_my_zone;
            out->push_back(m);
        }
    }

private:
    NodeState* st_;
};

CollectiveEngine* g_coll_engine = nullptr;

class CollectiveServiceImpl : public benchpb::CollectiveService {
public:
    void Exchange(google::protobuf::RpcController* cntl_base,
                  const benchpb::CollChunk* req, benchpb::CollAck* res,
                  google::protobuf::Closure* done) override {
        HandleCollectiveExchange(g_coll_engine,
                                 static_cast<Controller*>(cntl_base), req,
                                 res, done);
    }
};

// Deterministic collective inputs: every node can reconstruct every
// member's contribution from (seq, key) alone, so each node VERIFIES
// each completed round bit-for-bit — the strongest possible
// lost/corrupt-chunk detector under chaos. A2A pair payloads fold both
// endpoints into the key.
uint64_t A2aKey(uint64_t src_key, uint64_t dst_key) {
    return src_key * 1000003ull + dst_key;
}

struct CollRunArgs {
    NodeState* st = nullptr;
    std::string alg;     // allreduce | allreduce_serial | allgather | alltoall
    uint64_t bytes = 0;  // per-kind meaning (payload / block)
    uint64_t seq = 0;
    bool print = false;  // stdin-commanded round: emit a COLL line
};

// Runs ONE collective round, verifies it, updates counters; returns ok.
bool RunCollectiveRound(const CollRunArgs& a) {
    CollectiveEngine* eng = g_coll_engine;
    if (eng == nullptr) return false;
    Counters& c = a.st->counters;
    c.outstanding.fetch_add(1);
    c.coll_issued.fetch_add(1);
    CollectiveEngine::Result r;
    bool ok = false;
    bool verified = true;
    uint32_t checksum = 0;
    std::vector<uint32_t> head;
    double busbw = 0.0;
    uint64_t moved_total = 0;
    const uint64_t my_key = (uint64_t)g_my_port;

    // Lane-pinned stdin variants (ISSUE 18): "allreduce_verbs" /
    // "allreduce_chunks" select the ring's transport for THIS round —
    // bench verbs_scrape drives one of each and compares the
    // allreduce_verbs vs allreduce busbw gauges. The driver serializes
    // commanded rounds, so flipping the engine flag here is safe.
    std::string alg = a.alg;
    if (alg == "allreduce_verbs" || alg == "allreduce_chunks") {
        eng->set_verbs_lane(alg == "allreduce_verbs");
        alg = "allreduce";
    }

    if (alg == "allreduce" || alg == "allreduce_serial" ||
        alg == "hier_allreduce") {
        const size_t nwords = (size_t)(a.bytes / 4 ? a.bytes / 4 : 1);
        std::vector<uint32_t> words(nwords);
        CollectiveEngine::FillDeterministic(a.seq, my_key, words.data(),
                                            nwords);
        // hier (ISSUE 14): intra-pod ring + leader exchange over dcn +
        // broadcast ring — verified exactly like the flat all-reduce,
        // against the CONTRIBUTING key set the engine reports.
        const int err =
            alg == "allreduce"
                ? eng->AllReduce(a.seq, words.data(), nwords, &r)
                : alg == "hier_allreduce"
                      ? eng->HierAllReduce(a.seq, words.data(), nwords, &r)
                      : eng->SerialAllReduce(a.seq, words.data(), nwords,
                                             &r);
        ok = err == 0;
        if (ok) {
            // expected[i] = sum of every member's deterministic word.
            std::vector<uint32_t> expect(nwords, 0);
            std::vector<uint32_t> tmp(nwords);
            for (uint64_t k : r.member_keys) {
                CollectiveEngine::FillDeterministic(a.seq, k, tmp.data(),
                                                    nwords);
                for (size_t i = 0; i < nwords; ++i) expect[i] += tmp[i];
            }
            verified = expect == words;
            checksum = CollectiveEngine::Checksum(words.data(), nwords);
            for (size_t i = 0; i < nwords && i < 4; ++i) {
                head.push_back(words[i]);
            }
            moved_total = nwords * 4;
        }
    } else if (alg == "allgather") {
        const size_t block = (size_t)(a.bytes ? a.bytes & ~3ull : 4);
        std::vector<uint32_t> mine(block / 4);
        CollectiveEngine::FillDeterministic(a.seq, my_key, mine.data(),
                                            mine.size());
        std::string out;
        ok = eng->AllGather(a.seq, mine.data(), block, &out, &r) == 0;
        if (ok) {
            std::string expect;
            std::vector<uint32_t> tmp(block / 4);
            for (uint64_t k : r.member_keys) {
                CollectiveEngine::FillDeterministic(a.seq, k, tmp.data(),
                                                    tmp.size());
                expect.append((const char*)tmp.data(), block);
            }
            verified = expect == out;
            checksum = CollectiveEngine::Checksum(
                (const uint32_t*)out.data(), out.size() / 4);
            moved_total = out.size();
        }
    } else if (alg == "alltoall") {
        const size_t block = (size_t)(a.bytes ? a.bytes & ~3ull : 4);
        // Blocks for every POSSIBLE member (self + all configured
        // peers) so a re-formed round still finds its payloads.
        std::map<uint64_t, std::string> blocks;
        std::vector<uint32_t> tmp(block / 4);
        auto fill_for = [&](uint64_t dst_key) {
            CollectiveEngine::FillDeterministic(
                a.seq, A2aKey(my_key, dst_key), tmp.data(), tmp.size());
            blocks[dst_key].assign((const char*)tmp.data(), block);
        };
        fill_for(my_key);
        for (auto& lp : a.st->links) fill_for((uint64_t)lp->ep.port);
        std::string out;
        ok = eng->AllToAll(a.seq, blocks, block, &out, &r) == 0;
        if (ok) {
            std::string expect;
            for (uint64_t k : r.member_keys) {
                CollectiveEngine::FillDeterministic(
                    a.seq, A2aKey(k, my_key), tmp.data(), tmp.size());
                expect.append((const char*)tmp.data(), block);
            }
            verified = expect == out;
            checksum = CollectiveEngine::Checksum(
                (const uint32_t*)out.data(), out.size() / 4);
            moved_total = out.size();
        }
    }

    if (ok) {
        busbw = r.busbw_mbps;  // computed once, in the engine
        c.coll_ok.fetch_add(1);
        c.coll_nranks_last.store(r.nranks, std::memory_order_relaxed);
        if (!verified) c.coll_verify_failed.fetch_add(1);
    } else {
        c.coll_failed.fetch_add(1);
    }
    c.outstanding.fetch_sub(1);

    if (a.print) {
        std::string head_s;
        char num[16];
        for (uint32_t v : head) {
            snprintf(num, sizeof(num), "%s%u", head_s.empty() ? "" : ",",
                     v);
            head_s += num;
        }
        printf(
            "COLL {\"alg\": \"%s\", \"seq\": %llu, \"ok\": %d, "
            "\"verified\": %d, \"error\": %d, \"nranks\": %u, "
            "\"bytes\": %llu, \"elapsed_us\": %lld, "
            "\"busbw_mbps\": %.1f, \"checksum\": %u, \"head\": [%s], "
            "\"reforms\": %d, \"retries\": %d, "
            "\"desc_fallback_chunks\": %llu, "
            "\"verb_steps\": %llu, \"verb_fallback_chunks\": %llu}\n",
            a.alg.c_str(), (unsigned long long)a.seq, ok ? 1 : 0,
            verified ? 1 : 0, r.error, r.nranks,
            (unsigned long long)moved_total, (long long)r.elapsed_us,
            busbw, checksum, head_s.c_str(), r.reforms, r.retries,
            (unsigned long long)r.desc_fallback_chunks,
            (unsigned long long)r.verb_steps,
            (unsigned long long)r.verb_fallback_chunks);
        fflush(stdout);
    }
    return ok && verified;
}

void* CollCommandFiber(void* arg) {
    std::unique_ptr<CollRunArgs> a((CollRunArgs*)arg);
    RunCollectiveRound(*a);
    return nullptr;
}

// Continuous collective traffic (--coll_traffic): the same program on
// every node — mostly all-reduce (the soak SIGKILLs a node mid-op),
// with all-gather and all-to-all rounds mixed in on a fixed schedule
// so all nodes stay round-aligned.
void* CollTrafficFiber(void* arg) {
    auto* st = (NodeState*)arg;
    TrafficStartDelay(st);
    uint64_t seq = 0;
    CollRunArgs a;
    a.st = st;
    while (!st->stop.load(std::memory_order_relaxed)) {
        // Adopt the mesh's current round when (re)joining: peers
        // mid-round N must not wait on a node restarting from 1.
        CollectiveEngine* eng = g_coll_engine;
        const uint64_t observed = eng != nullptr ? eng->ObservedSeq() : 0;
        seq = seq + 1 > observed ? seq + 1 : observed;
        a.seq = seq;
        // With dcn peers configured (two-pod topology, ISSUE 14) the
        // mix leans on hierarchical all-reduce — the operation the
        // whole-pod-partition soak must prove re-forms over the
        // surviving pod. Every node derives the same schedule from seq.
        const bool have_dcn = [&] {
            for (auto& lp : st->links) {
                if (lp->dcn) return true;
            }
            return false;
        }();
        if (seq % 5 == 2) {
            a.alg = "allgather";
            a.bytes = 32 << 10;  // per-rank block
        } else if (seq % 5 == 4) {
            a.alg = "alltoall";
            a.bytes = 16 << 10;  // per-pair block
        } else if (have_dcn && seq % 5 != 0) {
            a.alg = "hier_allreduce";
            a.bytes = 256 << 10;
        } else {
            a.alg = "allreduce";
            a.bytes = have_dcn ? 128 << 10 : 512 << 10;  // payload
        }
        RunCollectiveRound(a);
        fiber_usleep(50 * 1000);
    }
    return nullptr;
}

// In-process numeric tvar read (the REPORT line carries re-issue and
// drain counters so the rolling-restart soak can assert on DYING
// incarnations whose portal is gone by assertion time).
int64_t VarInt(const char* name) {
    std::string v;
    if (!Variable::describe_exposed(name, &v)) return 0;
    return atoll(v.c_str());
}

// QoS identity of this node's own traffic (--tenant/--priority): the
// mesh's background load can then be classed against foreground load in
// the overload soak (unset = the default tenant/priority class).
std::string g_tenant;
std::atomic<int> g_priority{-1};

bool DoEcho(Channel* ch, int64_t timeout_ms, const std::string& payload) {
    benchpb::EchoService_Stub stub(ch);
    Controller cntl;
    cntl.set_timeout_ms(timeout_ms);
    if (!g_tenant.empty()) cntl.set_tenant(g_tenant);
    const int prio = g_priority.load(std::memory_order_relaxed);
    if (prio >= 0) cntl.set_priority(prio);
    benchpb::EchoRequest req;
    benchpb::EchoResponse res;
    req.set_send_ts_us(monotonic_time_us());
    cntl.request_attachment().append(payload);
    stub.Echo(&cntl, &req, &res, nullptr);  // sync: termination is proven
    return !cntl.Failed();
}

void* LbTrafficFiber(void* arg) {
    auto* st = (NodeState*)arg;
    TrafficStartDelay(st);
    const std::string payload(128, 'b');
    while (!st->stop.load(std::memory_order_relaxed)) {
        st->counters.outstanding.fetch_add(1);
        st->counters.lb_issued.fetch_add(1);
        if (DoEcho(st->lb_channel.get(), 800, payload)) {
            st->counters.lb_ok.fetch_add(1);
        } else {
            st->counters.lb_failed.fetch_add(1);
        }
        st->counters.outstanding.fetch_sub(1);
        fiber_usleep(3000);
    }
    return nullptr;
}

void* ShmTrafficFiber(void* arg) {
    auto* st = (NodeState*)arg;
    TrafficStartDelay(st);
    const std::string payload(128, 's');
    size_t next = 0;
    while (!st->stop.load(std::memory_order_relaxed)) {
        if (st->links.empty()) break;
        PeerLink& link = *st->links[next++ % st->links.size()];
        std::shared_ptr<Channel> ch;
        {
            std::lock_guard<std::mutex> g(link.mu);
            ch = link.ch;
        }
        if (ch != nullptr) {
            st->counters.outstanding.fetch_add(1);
            st->counters.shm_issued.fetch_add(1);
            if (DoEcho(ch.get(), 800, payload)) {
                st->counters.shm_ok.fetch_add(1);
            } else {
                st->counters.shm_failed.fetch_add(1);
            }
            st->counters.outstanding.fetch_sub(1);
        }
        fiber_usleep(3000);
    }
    return nullptr;
}

// One-sided descriptor traffic (--desc_traffic, ISSUE 10): every call
// pins a fresh pool block under a lease and posts it as a
// (pool_id, offset, len, crc, epoch) reference over the shm links —
// the zero-copy path the pool chaos soak SIGKILLs nodes under. The
// invariants the soak asserts ride the REPORT line: every issued call
// terminates, the lease ledger returns to pinned=0 after quiesce, and
// stale-epoch fences fail ONLY the call (counted desc_stale, the node
// keeps serving).
void* DescTrafficFiber(void* arg) {
    auto* st = (NodeState*)arg;
    TrafficStartDelay(st);
    constexpr size_t kDescBytes = 48 * 1024;
    size_t next = 0;
    while (!st->stop.load(std::memory_order_relaxed)) {
        if (st->links.empty()) break;
        PeerLink& link = *st->links[next++ % st->links.size()];
        std::shared_ptr<Channel> ch;
        {
            std::lock_guard<std::mutex> g(link.mu);
            ch = link.ch;
        }
        if (ch != nullptr) {
            st->counters.outstanding.fetch_add(1);
            st->counters.desc_issued.fetch_add(1);
            IOBuf att;
            char* data = nullptr;
            bool ok = false;
            bool stale = false;
            if (IciBlockPool::AllocatePoolAttachment(kDescBytes, &att,
                                                     &data)) {
                memset(data, (int)('a' + next % 26), kDescBytes);
                benchpb::EchoService_Stub stub(ch.get());
                Controller cntl;
                cntl.set_timeout_ms(800);
                cntl.set_request_pool_attachment(std::move(att));
                benchpb::EchoRequest req;
                benchpb::EchoResponse res;
                // Symmetric round (ISSUE 12): ask the peer to answer
                // with a response-direction descriptor too, so kills
                // and chaos hit pins in BOTH directions.
                char ask[48];
                snprintf(ask, sizeof(ask), "desc_rsp:%zu", kDescBytes);
                req.set_payload(ask);
                st->counters.desc_rsp_issued.fetch_add(1);
                req.set_send_ts_us(monotonic_time_us());
                stub.Echo(&cntl, &req, &res, nullptr);  // sync
                ok = !cntl.Failed();
                stale = cntl.ErrorCode() == TERR_STALE_EPOCH;
                if (ok &&
                    cntl.response_pool_attachment().length ==
                        kDescBytes &&
                    cntl.response_pool_attachment().data != nullptr &&
                    cntl.response_pool_attachment().data[0] == 'r') {
                    st->counters.desc_rsp_ok.fetch_add(1);
                }
                // Controller teardown here acks the peer's rsp pin.
            }
            if (ok) {
                st->counters.desc_ok.fetch_add(1);
            } else {
                st->counters.desc_failed.fetch_add(1);
                if (stale) st->counters.desc_stale.fetch_add(1);
            }
            st->counters.outstanding.fetch_sub(1);
        }
        fiber_usleep(4000);
    }
    return nullptr;
}

// One-sided verb traffic (--verbs_traffic, ISSUE 18): each round leases
// a 64 KB window in a peer's pool, REMOTE_WRITEs a patterned payload
// through a 4-entry scatter-gather list, then REMOTE_READs it back and
// verifies byte-for-byte — the round-trip the verb chaos soak SIGKILLs
// nodes under. Windows are cached per link and re-granted on failure,
// near lease expiry, or after a reconnect rebinds the link's socket; a
// window dropped on the floor is reclaimed by the grantor's lease
// reaper (pinned must still drain to 0). dcn links ride the emulated
// two-sided wire path — same verbs, degraded transport.
constexpr uint64_t kMeshWrTag = 0x4D45ull << 48;  // 'ME'
std::atomic<uint64_t> g_mesh_wr{1};

// Mesh wr ids are salted with the pid (bits 32..47) so ids are unique
// ACROSS nodes, not just within one: the black-box merge pairs an
// initiator's VERB_POST with the grantor's VERB_WIRE by wr id, and a
// bare per-process counter would collide between initiators.
uint64_t NextMeshWr() {
    static const uint64_t salt = ((uint64_t)(getpid() & 0xffff)) << 32;
    return kMeshWrTag | salt | (g_mesh_wr.fetch_add(1) & 0xffffffffu);
}

// Parks until the CQ delivers wr_id (this fiber posts one verb at a
// time, so no other completion can appear). The 8 s bound is far
// beyond the verb plane's post-timeout terminal guarantee — a pending
// post can never outlive the caller's stack CQ.
bool ParkForWr(verbs::CompletionQueue* cq, uint64_t wr,
               verbs::Completion* out) {
    const int64_t give_up = monotonic_time_us() + 8 * 1000 * 1000;
    while (monotonic_time_us() < give_up) {
        if (!cq->Park(out, 500 * 1000)) continue;
        if (out->wr_id == wr) return true;
    }
    return false;
}

void* VerbsTrafficFiber(void* arg) {
    auto* st = (NodeState*)arg;
    TrafficStartDelay(st);
    constexpr size_t kVerbBytes = 64 * 1024;
    constexpr uint32_t kNsge = 4;
    verbs::CompletionQueue cq;
    std::vector<verbs::RemoteWindow> wins(st->links.size());
    std::vector<char> wr_buf(kVerbBytes), rd_buf(kVerbBytes);
    size_t next = 0;
    uint64_t round = 0;
    while (!st->stop.load(std::memory_order_relaxed)) {
        if (st->links.empty()) break;
        const size_t li = next++ % st->links.size();
        PeerLink& link = *st->links[li];
        std::shared_ptr<Channel> ch;
        {
            std::lock_guard<std::mutex> g(link.mu);
            ch = link.ch;
        }
        if (ch == nullptr) {
            fiber_usleep(5000);
            continue;
        }
        const uint64_t sid = (uint64_t)ch->pinned_socket();
        st->counters.outstanding.fetch_add(1);
        st->counters.verbs_issued.fetch_add(1);
        verbs::RemoteWindow& w = wins[li];
        bool ok = false;
        bool stale = false;
        if (w.window_id == 0 || w.peer != sid ||
            (w.deadline_us != 0 &&
             monotonic_time_us() > w.deadline_us - 500 * 1000)) {
            w = verbs::RemoteWindow();
            if (verbs::RequestWindow(sid, kVerbBytes,
                                     verbs::kWinRead | verbs::kWinWrite,
                                     800, &w) == 0) {
                st->counters.verbs_regrants.fetch_add(1);
            }
        }
        if (w.window_id != 0) {
            ++round;
            for (size_t i = 0; i < kVerbBytes; ++i) {
                wr_buf[i] = (char)('a' + (round + i) % 26);
            }
            // 4-entry SGL: the write gathers local pieces, the
            // read-back scatters into a second buffer.
            const size_t piece = kVerbBytes / kNsge;
            verbs::Sge sgl[kNsge];
            for (uint32_t i = 0; i < kNsge; ++i) {
                sgl[i].addr = wr_buf.data() + i * piece;
                sgl[i].len = piece;
            }
            const uint64_t wid = NextMeshWr();
            verbs::Completion comp;
            if (verbs::PostWrite(&cq, wid, w, 0, sgl, kNsge) == 0 &&
                ParkForWr(&cq, wid, &comp)) {
                stale = comp.status == TERR_STALE_EPOCH;
                if (comp.status == 0) {
                    memset(rd_buf.data(), 0, kVerbBytes);
                    for (uint32_t i = 0; i < kNsge; ++i) {
                        sgl[i].addr = rd_buf.data() + i * piece;
                    }
                    const uint64_t rid = NextMeshWr();
                    if (verbs::PostRead(&cq, rid, w, 0, sgl, kNsge) ==
                            0 &&
                        ParkForWr(&cq, rid, &comp)) {
                        stale = comp.status == TERR_STALE_EPOCH;
                        ok = comp.status == 0 &&
                             comp.bytes == kVerbBytes &&
                             memcmp(wr_buf.data(), rd_buf.data(),
                                    kVerbBytes) == 0;
                    }
                }
            }
            if (!ok) w = verbs::RemoteWindow();  // re-grant next visit
        }
        if (ok) {
            st->counters.verbs_ok.fetch_add(1);
        } else {
            st->counters.verbs_failed.fetch_add(1);
            if (stale) st->counters.verbs_stale.fetch_add(1);
        }
        st->counters.outstanding.fetch_sub(1);
        fiber_usleep(4000);
    }
    cq.Shutdown();
    return nullptr;
}

// Delay-phase client: issues budget-starved calls against the LB plane.
// Two flavors per round, a 1 ms deadline (the minimum the stamp floor
// produces) and a g_stale_budget_ms deadline — both positive but below
// the handler-delay-taught service time, so the
// TimeoutConcurrencyLimiter's budget check sheds them at admission.
// Both fail client-side fast; the invariant is that the server did not
// EXECUTE them (g_stale_executed stays low).
void* StaleTrafficFiber(void* arg) {
    auto* st = (NodeState*)arg;
    while (!st->stop.load(std::memory_order_relaxed)) {
        const int budget_ms = g_stale_budget_ms.load(std::memory_order_relaxed);
        if (budget_ms <= 0) {
            fiber_usleep(20 * 1000);
            continue;
        }
        const int64_t budgets[2] = {1, budget_ms};
        for (int k = 0; k < 2; ++k) {
            if (st->stop.load(std::memory_order_relaxed)) break;
            st->counters.outstanding.fetch_add(1);
            st->counters.stale_issued.fetch_add(1);
            benchpb::EchoService_Stub stub(st->lb_channel.get());
            Controller cntl;
            cntl.set_timeout_ms(budgets[k]);
            cntl.set_max_retry(0);  // a doomed call must not re-issue
            benchpb::EchoRequest req;
            benchpb::EchoResponse res;
            req.set_send_ts_us(monotonic_time_us());
            req.set_stale(true);
            stub.Echo(&cntl, &req, &res, nullptr);  // sync: terminates
            if (cntl.Failed()) {
                st->counters.stale_failed.fetch_add(1);
            } else {
                st->counters.stale_ok.fetch_add(1);
            }
            st->counters.outstanding.fetch_sub(1);
        }
        fiber_usleep(15 * 1000);
    }
    return nullptr;
}

// Delay-phase raw probe: handcrafted tpu_std frames stamped
// timeout_ms=0 — the wire shape of "the client already gave up" (a
// conforming client floors live budgets at 1 ms, so 0 only appears when
// the deadline truly passed). The server must reject these BEFORE
// admission, parse, or user code (rpc_server_expired_requests); they
// can never reach the handler, so g_stale_executed is structurally
// untouched by them.
void* ExpiredProbeFiber(void* arg) {
    auto* st = (NodeState*)arg;
    int fd = -1;
    uint64_t probe_cid = 1;
    while (!st->stop.load(std::memory_order_relaxed)) {
        if (g_stale_budget_ms.load(std::memory_order_relaxed) <= 0 ||
            st->links.empty()) {
            if (fd >= 0) {
                close(fd);
                fd = -1;
            }
            fiber_usleep(20 * 1000);
            continue;
        }
        if (fd < 0) {
            fd = ::socket(AF_INET, SOCK_STREAM, 0);
            sockaddr_in addr;
            endpoint2sockaddr(st->links[0]->ep, &addr);
            if (fd < 0 ||
                ::connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
                if (fd >= 0) {
                    close(fd);
                    fd = -1;
                }
                fiber_usleep(100 * 1000);
                continue;
            }
        }
        rpc::RpcMeta meta;
        auto* rm = meta.mutable_request();
        rm->set_service_name("benchpb.EchoService");
        rm->set_method_name("Echo");
        rm->set_timeout_ms(0);  // expired on arrival, by construction
        meta.set_correlation_id(probe_cid++);
        benchpb::EchoRequest req;
        req.set_stale(true);
        IOBuf meta_buf, payload;
        SerializePbToIOBuf(meta, &meta_buf);
        SerializePbToIOBuf(req, &payload);
        IOBuf frame;
        PackTpuStdFrame(&frame, meta_buf, payload, IOBuf());
        const std::string wire = frame.to_string();
        if (::send(fd, wire.data(), wire.size(), MSG_NOSIGNAL) !=
            (ssize_t)wire.size()) {
            close(fd);
            fd = -1;
            continue;
        }
        st->counters.expired_probes.fetch_add(1);
        // Drain the error responses without blocking the worker.
        char drain[4096];
        while (::recv(fd, drain, sizeof(drain), MSG_DONTWAIT) > 0) {
        }
        fiber_usleep(30 * 1000);
    }
    if (fd >= 0) close(fd);
    return nullptr;
}

// Keeps the mesh connected: (re-)establishes any link whose pinned
// socket died — a killed peer that comes back on the same port rejoins
// the mesh here.
void* LinkMaintenanceFiber(void* arg) {
    auto* st = (NodeState*)arg;
    while (!st->stop.load(std::memory_order_relaxed)) {
        for (auto& lp : st->links) {
            if (st->stop.load(std::memory_order_relaxed)) break;
            PeerLink& link = *lp;
            bool dead;
            {
                std::lock_guard<std::mutex> g(link.mu);
                if (link.ch == nullptr) {
                    dead = true;
                } else {
                    SocketUniquePtr s =
                        SocketUniquePtr::FromId(link.ch->pinned_socket());
                    dead = !s || s->Failed();
                }
            }
            if (!dead) continue;
            auto fresh = std::make_shared<Channel>();
            ChannelOptions copts;
            copts.timeout_ms = 800;
            copts.max_retry = 0;  // the maintenance loop IS the retry
            bool up = false;
            if (link.dcn) {
                // Cross-pod link (ISSUE 14): a pinned dcn-tier channel.
                // Plain TCP connects lazily, so prove the peer is
                // really there with one short probe echo before
                // installing — the membership view (pinned socket not
                // failed) must mean "verified reachable", exactly what
                // the shm handshake gives intra-pod links.
                copts.transport = "dcn";
                copts.pin_connection = true;
                if (fresh->Init(link.ep, &copts) == 0) {
                    benchpb::EchoService_Stub stub(fresh.get());
                    Controller probe;
                    probe.set_timeout_ms(400);
                    probe.set_max_retry(0);
                    benchpb::EchoRequest req;
                    benchpb::EchoResponse res;
                    req.set_send_ts_us(monotonic_time_us());
                    stub.Echo(&probe, &req, &res, nullptr);  // sync
                    up = !probe.Failed();
                    if (!up) {
                        // Don't leak a half-open pinned connection.
                        Socket::SetFailedById(fresh->pinned_socket());
                    }
                }
            } else {
                up = fresh->InitIci(link.ep, &copts) == 0;
            }
            if (up) {
                std::lock_guard<std::mutex> g(link.mu);
                const bool was_connected = link.ch != nullptr;
                link.ch = std::move(fresh);
                if (was_connected) st->counters.reconnects.fetch_add(1);
            }
        }
        fiber_usleep(300 * 1000);
    }
    return nullptr;
}

// One root call of the stitch soak ("chain T ep1 ep2..." on stdin): Echo
// to ep1 with chain=[ep2...] under a T-ms deadline, then print the trace
// id so the driving test can fetch /rpcz/trace/<id>. Runs on a fiber
// (sync RPC) — the stdin loop stays responsive.
struct ChainArgs {
    int64_t timeout_ms = 1000;
    std::vector<std::string> eps;
};

void* ChainCallFiber(void* arg) {
    std::unique_ptr<ChainArgs> a((ChainArgs*)arg);
    EndPoint first;
    if (a->eps.empty() || str2endpoint(a->eps[0].c_str(), &first) != 0) {
        printf("CHAIN trace=0 err=22\n");
        fflush(stdout);
        return nullptr;
    }
    Channel ch;
    ChannelOptions copts;
    copts.timeout_ms = a->timeout_ms;
    copts.max_retry = 0;
    if (ch.Init(first, &copts) != 0) {
        printf("CHAIN trace=0 err=112\n");
        fflush(stdout);
        return nullptr;
    }
    benchpb::EchoService_Stub stub(&ch);
    Controller cntl;
    cntl.set_timeout_ms(a->timeout_ms);
    benchpb::EchoRequest req;
    benchpb::EchoResponse res;
    req.set_send_ts_us(monotonic_time_us());
    for (size_t i = 1; i < a->eps.size(); ++i) {
        req.add_chain(a->eps[i]);
    }
    stub.Echo(&cntl, &req, &res, nullptr);  // sync: trace id is final
    printf("CHAIN trace=%llu err=%d\n",
           (unsigned long long)cntl.trace_id(), cntl.ErrorCode());
    fflush(stdout);
    return nullptr;
}

void PrintReport(int id, int port, const Counters& c) {
    // Client re-issue + drain counters ride the report so the soak can
    // assert "zero retry tokens spent" even for an incarnation that is
    // about to exit (its /vars portal dies with it).
    const long long reissues =
        VarInt("rpc_client_retries") + VarInt("rpc_client_backup_requests");
    printf(
        "REPORT {\"id\": %d, \"port\": %d, \"lb_issued\": %lld, "
        "\"lb_ok\": %lld, \"lb_failed\": %lld, \"shm_issued\": %lld, "
        "\"shm_ok\": %lld, \"shm_failed\": %lld, "
        "\"coll_issued\": %lld, \"coll_ok\": %lld, "
        "\"coll_failed\": %lld, \"coll_verify_failed\": %lld, "
        "\"coll_nranks\": %lld, \"coll_ops\": %lld, "
        "\"coll_steps\": %lld, \"coll_retries\": %lld, "
        "\"coll_reforms\": %lld, \"coll_desc_fallbacks\": %lld, "
        "\"stale_issued\": %lld, \"stale_ok\": %lld, "
        "\"stale_failed\": %lld, \"stale_executed\": %lld, "
        "\"expired_probes\": %lld, "
        "\"desc_issued\": %lld, \"desc_ok\": %lld, "
        "\"desc_failed\": %lld, \"desc_stale\": %lld, "
        "\"desc_rsp_issued\": %lld, \"desc_rsp_ok\": %lld, "
        "\"desc_rsp_resolves\": %lld, \"desc_rsp_sends\": %lld, "
        "\"verbs_issued\": %lld, \"verbs_ok\": %lld, "
        "\"verbs_failed\": %lld, \"verbs_stale\": %lld, "
        "\"verbs_regrants\": %lld, \"verbs_posted\": %lld, "
        "\"verbs_completed\": %lld, \"verbs_bytes\": %lld, "
        "\"verbs_stale_rejects\": %lld, \"verbs_windows\": %lld, "
        "\"verbs_pending\": %lld, \"coll_verb_steps\": %lld, "
        "\"coll_verb_fallbacks\": %lld, "
        "\"pool_pinned\": %lld, \"pool_reaped\": %lld, "
        "\"pool_peer_released\": %lld, \"epoch_rejects\": %lld, "
        "\"cost_admitted_milli\": %lld, \"cost_shed_milli\": %lld, "
        "\"overload_sheds\": %lld, "
        "\"outstanding\": %lld, \"reconnects\": %lld, "
        "\"reissues\": %lld, \"budget_exhausted\": %lld, "
        "\"drain_reroutes\": %lld, \"drain_notices\": %lld, "
        "\"goaways_sent\": %lld, "
        "\"zone\": \"%s\", \"zone_spills\": %lld, "
        "\"zone_local_picks\": %lld, \"zone_partition_cuts\": %lld, "
        "\"dcn_out_bytes\": %lld, \"dcn_in_bytes\": %lld, "
        "\"stream_open\": %lld, \"stream_resumed\": %lld, "
        "\"stream_replayed\": %lld, \"stream_credit_stalls\": %lld, "
        "\"stream_aborts\": %lld, \"stream_ring_hw\": %lld}\n",
        id, port, (long long)c.lb_issued.load(), (long long)c.lb_ok.load(),
        (long long)c.lb_failed.load(), (long long)c.shm_issued.load(),
        (long long)c.shm_ok.load(), (long long)c.shm_failed.load(),
        (long long)c.coll_issued.load(), (long long)c.coll_ok.load(),
        (long long)c.coll_failed.load(),
        (long long)c.coll_verify_failed.load(),
        (long long)c.coll_nranks_last.load(),
        (long long)VarInt("rpc_collective_ops"),
        (long long)VarInt("rpc_collective_steps"),
        (long long)VarInt("rpc_collective_retries"),
        (long long)VarInt("rpc_collective_reforms"),
        (long long)VarInt("rpc_collective_desc_fallbacks"),
        (long long)c.stale_issued.load(), (long long)c.stale_ok.load(),
        (long long)c.stale_failed.load(),
        (long long)g_stale_executed.load(),
        (long long)c.expired_probes.load(),
        (long long)c.desc_issued.load(), (long long)c.desc_ok.load(),
        (long long)c.desc_failed.load(), (long long)c.desc_stale.load(),
        (long long)c.desc_rsp_issued.load(),
        (long long)c.desc_rsp_ok.load(),
        (long long)VarInt("rpc_pool_desc_rsp_resolves"),
        (long long)VarInt("rpc_pool_desc_rsp_sends"),
        (long long)c.verbs_issued.load(), (long long)c.verbs_ok.load(),
        (long long)c.verbs_failed.load(),
        (long long)c.verbs_stale.load(),
        (long long)c.verbs_regrants.load(),
        (long long)verbs::posted(), (long long)verbs::completed(),
        (long long)verbs::bytes_moved(),
        (long long)verbs::stale_rejects(),
        (long long)verbs::window_count(),
        (long long)verbs::pending_posts(),
        (long long)VarInt("rpc_collective_verb_steps"),
        (long long)VarInt("rpc_collective_verb_fallbacks"),
        (long long)block_lease::pinned(),
        (long long)block_lease::expired_reaped(),
        (long long)block_lease::peer_released(),
        (long long)VarInt("rpc_pool_epoch_rejects"),
        (long long)VarInt("rpc_server_cost_admitted"),
        (long long)VarInt("rpc_server_cost_shed"),
        (long long)VarInt("rpc_server_overload_sheds"),
        (long long)c.outstanding.load(), (long long)c.reconnects.load(),
        reissues, (long long)VarInt("rpc_retry_budget_exhausted"),
        (long long)VarInt("rpc_client_drain_reroutes"),
        (long long)VarInt("rpc_client_drain_notices"),
        (long long)VarInt("rpc_server_drain_goaways_sent"),
        g_my_zone.c_str(), (long long)VarInt("rpc_lb_zone_spills"),
        (long long)VarInt("rpc_lb_zone_local_picks"),
        (long long)FaultInjection::zone_partition_cuts(),
        (long long)transport_stats::out_bytes(TierDcn()),
        (long long)transport_stats::in_bytes(TierDcn()),
        (long long)push_stream::Opens(), (long long)push_stream::Resumed(),
        (long long)push_stream::ReplayedChunks(),
        (long long)push_stream::CreditStalls(),
        (long long)push_stream::Aborts(),
        (long long)push_stream::RingHighwater());
    fflush(stdout);
}

// SIGTERM/SIGUSR2 watcher (the -graceful_quit_on_sigterm wiring): a
// plain fiber polling the signal flags — never shutdown work in signal
// context. SIGUSR2 = drain-only (announce + keep serving, so operators
// can watch /status flip to draining: 1); SIGTERM = the zero-downtime
// exit used by the rolling-restart soak:
//   announce -> serve through the drain window (peers steer away) ->
//   stop own client traffic -> GracefulStop -> REPORT -> _exit(0).
struct QuitWatchArgs {
    Server* server;
    NodeState* st;
    int id;
    int port;
    int drain_ms;
};

void* GracefulQuitWatcher(void* arg) {
    std::unique_ptr<QuitWatchArgs> a((QuitWatchArgs*)arg);
    bool announced = false;
    while (!IsAskedToQuit()) {
        if (a->st->watcher_stop.load(std::memory_order_acquire)) {
            return nullptr;  // main() is tearing down; our pointers die
        }
        if (!announced && IsAskedToDrain()) {
            a->server->StartDraining();
            announced = true;
            printf("DRAINING\n");
            fflush(stdout);
        }
        fiber_usleep(20 * 1000);
    }
    a->server->StartDraining();
    if (!announced) {
        printf("DRAINING\n");
        fflush(stdout);
    }
    fiber_usleep((int64_t)a->drain_ms * 1000);
    if (g_coll_engine != nullptr) g_coll_engine->Shutdown();
    a->st->StopTraffic();  // our own in-flight client calls complete
    a->server->GracefulStop(2000);
    PrintReport(a->id, a->port, a->st->counters);
    fflush(nullptr);
    _exit(0);
    return nullptr;
}

// Unclean-exit black box: dump the flight rings to --blackbox before
// bailing with an error (the crash handler only covers signal deaths).
int FailExit(int code) {
    flight::DumpToConfiguredPath();
    return code;
}

}  // namespace

int main(int argc, char** argv) {
    prctl(PR_SET_PDEATHSIG, SIGKILL);  // die with the driving pytest
    int port = 0, id = 0;
    int timeout_cl_ms = 0;
    int drain_ms = 1200;
    const char* blackbox_path = nullptr;
    bool lb_only = false;
    bool inline_echo = false;
    bool desc_traffic = false;
    bool verbs_traffic = false;
    bool collective = false;
    bool coll_traffic = false;
    bool coll_verbs = false;
    const char* peers_file = nullptr;
    const char* dcn_peers_file = nullptr;
    for (int i = 1; i < argc; ++i) {
        if (strcmp(argv[i], "--port") == 0 && i + 1 < argc) {
            port = atoi(argv[++i]);
        } else if (strcmp(argv[i], "--id") == 0 && i + 1 < argc) {
            id = atoi(argv[++i]);
        } else if (strcmp(argv[i], "--peers") == 0 && i + 1 < argc) {
            peers_file = argv[++i];
        } else if (strcmp(argv[i], "--zone") == 0 && i + 1 < argc) {
            // Pod identity (ISSUE 14): feeds -rpc_zone (zone-aware LB +
            // dcn-tier naming sockets) and the collective membership.
            g_my_zone = argv[++i];
            SetFlagValue("rpc_zone", g_my_zone);
        } else if (strcmp(argv[i], "--dcn_peers") == 0 && i + 1 < argc) {
            // Cross-pod peers (naming-line format, "ip:port zone=B"):
            // linked over pinned dcn-tier channels instead of shm.
            dcn_peers_file = argv[++i];
        } else if (strcmp(argv[i], "--timeout_cl_ms") == 0 && i + 1 < argc) {
            timeout_cl_ms = atoi(argv[++i]);
        } else if (strcmp(argv[i], "--tenant") == 0 && i + 1 < argc) {
            g_tenant = argv[++i];
        } else if (strcmp(argv[i], "--priority") == 0 && i + 1 < argc) {
            g_priority.store(atoi(argv[++i]), std::memory_order_relaxed);
        } else if (strcmp(argv[i], "--drain_ms") == 0 && i + 1 < argc) {
            // SIGTERM grace window: announce, then keep serving this long
            // before the final GracefulStop (rolling restarts observe
            // /status draining:1 during it).
            drain_ms = atoi(argv[++i]);
        } else if (strcmp(argv[i], "--stream_token_delay_us") == 0 &&
                   i + 1 < argc) {
            g_stream_token_delay_us.store(atoll(argv[++i]),
                                          std::memory_order_relaxed);
        } else if (strcmp(argv[i], "--traffic_delay_ms") == 0 &&
                   i + 1 < argc) {
            g_traffic_delay_ms.store(atoi(argv[++i]),
                                     std::memory_order_relaxed);
        } else if (strcmp(argv[i], "--inline_echo") == 0) {
            // Run-to-completion soak mode (ISSUE 7): flag the echo
            // method inline-safe so small requests run on the input
            // fiber. OFF by default — this node's handler can be told to
            // sleep ("delay") and to chain downstream calls, both of
            // which violate the inline-safe contract; the delay command
            // clears the flag for its phase.
            inline_echo = true;
        } else if (strcmp(argv[i], "--desc_traffic") == 0) {
            // Pool chaos soak mode (ISSUE 10): drive one-sided
            // descriptor traffic (pinned pool blocks) over the shm
            // links so kills/chaos hit the zero-copy data path.
            desc_traffic = true;
        } else if (strcmp(argv[i], "--verbs_traffic") == 0) {
            // Verb chaos soak mode (ISSUE 18): drive one-sided
            // REMOTE_WRITE/REMOTE_READ round-trips against leased peer
            // windows so kills/chaos hit the verb plane.
            verbs_traffic = true;
        } else if (strcmp(argv[i], "--coll_verbs") == 0) {
            // Collective rounds default to the verbs-backed step
            // exchange (one SGL verb + doorbell per ring step).
            coll_verbs = true;
        } else if (strcmp(argv[i], "--collective") == 0) {
            // Mesh collectives (ISSUE 13): serve the CollectiveService
            // + engine; rounds are driven by stdin "coll ..." commands
            // (bench.py) or the --coll_traffic fiber (the soak).
            collective = true;
        } else if (strcmp(argv[i], "--coll_traffic") == 0) {
            collective = true;
            coll_traffic = true;
        } else if (strcmp(argv[i], "--lb_only") == 0) {
            // Rolling-restart soak mode: only the naming/LB plane runs.
            // The shm-ICI links die hard when a peer exits (no drain
            // protocol on the queue pair yet) — the zero-failed-
            // completions invariant is an LB-plane contract.
            lb_only = true;
        } else if (strcmp(argv[i], "--blackbox") == 0 && i + 1 < argc) {
            // Flight-recorder black box (ISSUE 19): install the fatal-
            // signal dump handler writing to this path, and dump there
            // on unclean (non-signal) exits too.
            blackbox_path = argv[++i];
        } else if (strcmp(argv[i], "--flag") == 0 && i + 1 < argc) {
            // --flag name=value: soak-tuned knobs (breaker windows,
            // health-check cadence, ...) without bespoke plumbing.
            std::string kv = argv[++i];
            const size_t eq = kv.find('=');
            if (eq == std::string::npos ||
                !SetFlagValue(kv.substr(0, eq), kv.substr(eq + 1))) {
                fprintf(stderr, "bad --flag %s\n", kv.c_str());
                return 2;
            }
        }
    }
    if (port <= 0 || peers_file == nullptr) {
        fprintf(stderr,
                "usage: mesh_node --port N --peers FILE [--id K] "
                "[--zone NAME] [--dcn_peers FILE] "
                "[--lb_only] [--inline_echo] [--desc_traffic] "
                "[--verbs_traffic] "
                "[--collective] [--coll_traffic] [--coll_verbs] "
                "[--drain_ms N] "
                "[--timeout_cl_ms N] [--tenant NAME] [--priority 0..7] "
                "[--blackbox PATH] [--flag name=value]...\n"
                "  with --flag graceful_quit_on_sigterm=true: SIGTERM "
                "drains gracefully and exits 0; SIGUSR2 drains without "
                "quitting\n");
        return 2;
    }
    // Node identity stamps every dump (blackbox_merge.py keys timelines
    // on it); the crash handler is installed only when a path was given.
    {
        char nn[32];
        snprintf(nn, sizeof(nn), "node%d:%d", id, port);
        flight::SetNodeName(nn);
    }
    if (blackbox_path != nullptr) {
        flight::InstallCrashHandler(blackbox_path);
    }
    if (IciBlockPool::Init() != 0) {
        fprintf(stderr, "IciBlockPool::Init failed\n");
        return FailExit(1);
    }

    g_my_port = port;
    static EchoServiceImpl service;
    static CollectiveServiceImpl coll_service;
    static Server server;
    if (server.AddService(&service) != 0) return FailExit(1);
    if (collective && server.AddService(&coll_service) != 0) {
        return FailExit(1);
    }
    if (inline_echo) {
        server.SetMethodInlineSafe("benchpb.EchoService", "Echo");
    }
    EndPoint listen;
    str2endpoint("127.0.0.1", port, &listen);
    ServerOptions sopts;
    if (timeout_cl_ms > 0) {
        // Budget-aware admission: requests whose propagated remaining
        // deadline is below the observed service time are shed cheaply.
        sopts.timeout_concurrency = true;
        sopts.timeout_cl_options.timeout_ms = timeout_cl_ms;
    }
    if (server.Start(listen, timeout_cl_ms > 0 ? &sopts : nullptr) != 0) {
        fprintf(stderr, "listen failed on port %d\n", port);
        return FailExit(1);
    }

    static NodeState st;
    // Naming-service membership: the rr LB channel resolves the same
    // file every node shares; its sockets carry circuit breakers and
    // health checks (FLAGS_ns_health_check_interval_ms).
    st.lb_channel.reset(new Channel);
    ChannelOptions lopts;
    lopts.timeout_ms = 800;
    lopts.max_retry = 2;
    const std::string url = std::string("file://") + peers_file;
    if (st.lb_channel->Init(url.c_str(), "rr", &lopts) != 0) {
        fprintf(stderr, "LB channel init failed for %s\n", url.c_str());
        return FailExit(1);
    }
    // Mesh links: one shm channel per same-zone peer (self excluded;
    // cross-zone entries in the naming file belong to the OTHER pod and
    // are reached through --dcn_peers links, never shm). Peer zones are
    // registered with the fault-injection layer so one
    // chaos_partition_zone command can cut a whole pod.
    if (!lb_only) {
        FILE* f = fopen(peers_file, "r");
        if (f == nullptr) return FailExit(1);
        char line[128];
        while (fgets(line, sizeof(line), f) != nullptr) {
            NSNode node;
            if (ParseNamingLine(line, &node) != 0) continue;
            const std::string zone = ZoneFromTag(node.tag);
            if (!zone.empty()) {
                FaultInjection::SetPeerZone(node.ep, zone);
            }
            if (node.ep.port == port) continue;  // self
            if (!zone.empty() && zone != g_my_zone) continue;  // other pod
            auto link = std::make_unique<PeerLink>();
            link->ep = node.ep;
            link->zone = g_my_zone;
            st.links.push_back(std::move(link));
        }
        fclose(f);
        if (dcn_peers_file != nullptr) {
            FILE* df = fopen(dcn_peers_file, "r");
            if (df == nullptr) return FailExit(1);
            while (fgets(line, sizeof(line), df) != nullptr) {
                NSNode node;
                if (ParseNamingLine(line, &node) != 0) continue;
                if (node.ep.port == port) continue;
                auto link = std::make_unique<PeerLink>();
                link->ep = node.ep;
                link->dcn = true;
                link->zone = ZoneFromTag(node.tag);
                FaultInjection::SetPeerZone(node.ep, link->zone);
                st.links.push_back(std::move(link));
            }
            fclose(df);
        }
    }

    // Collective engine over the shm-link mesh (needs st.links).
    static std::unique_ptr<MeshMembership> coll_membership;
    static BenchpbCollCodec coll_codec;
    static std::unique_ptr<CollectiveEngine> coll_engine;
    if (collective && !lb_only) {
        coll_membership.reset(new MeshMembership(&st));
        CollectiveOptions copts;
        copts.step_timeout_ms = 1500;
        copts.attempt_timeout_ms = 4000;
        copts.verbs_lane = coll_verbs;
        // Also bounds how long a rejoin-misaligned round can stall the
        // mesh before the straggler adopts the observed seq.
        copts.op_timeout_ms = 15000;
        coll_engine.reset(new CollectiveEngine(coll_membership.get(),
                                               &coll_codec, copts));
        g_coll_engine = coll_engine.get();
    }

    std::vector<fiber_t>& fibers = st.traffic_fibers;
    fiber_t tid;
    if (!lb_only &&
        fiber_start_background(&tid, nullptr, LinkMaintenanceFiber, &st) ==
            0) {
        fibers.push_back(tid);
    }
    if (coll_traffic && g_coll_engine != nullptr &&
        fiber_start_background(&tid, nullptr, CollTrafficFiber, &st) == 0) {
        fibers.push_back(tid);
    }
    if (fiber_start_background(&tid, nullptr, LbTrafficFiber, &st) == 0) {
        fibers.push_back(tid);
    }
    if (!lb_only) {
        if (fiber_start_background(&tid, nullptr, ShmTrafficFiber, &st) ==
            0) {
            fibers.push_back(tid);
        }
        if (desc_traffic &&
            fiber_start_background(&tid, nullptr, DescTrafficFiber, &st) ==
                0) {
            fibers.push_back(tid);
        }
        if (verbs_traffic &&
            fiber_start_background(&tid, nullptr, VerbsTrafficFiber,
                                   &st) == 0) {
            fibers.push_back(tid);
        }
        if (fiber_start_background(&tid, nullptr, StaleTrafficFiber, &st) ==
            0) {
            fibers.push_back(tid);
        }
        if (fiber_start_background(&tid, nullptr, ExpiredProbeFiber, &st) ==
            0) {
            fibers.push_back(tid);
        }
    }
    // Signal-driven zero-downtime lifecycle (active when the
    // -graceful_quit_on_sigterm flag installed the handlers at Start).
    fiber_t quit_watcher;
    bool have_quit_watcher = true;
    {
        auto* qa = new QuitWatchArgs{&server, &st, id, port, drain_ms};
        if (fiber_start_background(&quit_watcher, nullptr,
                                   GracefulQuitWatcher, qa) != 0) {
            delete qa;
            have_quit_watcher = false;
        }
    }

    printf("READY %d\n", port);
    fflush(stdout);

    // Control loop: "stop" -> quiesce traffic + report; "delay H S" ->
    // delay-heavy phase (handler sleeps H ms, stale fiber issues S-ms
    // budget calls; 0 0 = back to normal); "chain T ep..." -> one chained
    // echo under a T-ms deadline (prints CHAIN trace=<id>); EOF -> exit.
    char cmd[256];
    while (fgets(cmd, sizeof(cmd), stdin) != nullptr) {
        if (strncmp(cmd, "stop", 4) == 0) {
            st.StopTraffic();
            PrintReport(id, port, st.counters);
        } else if (strncmp(cmd, "report", 6) == 0) {
            PrintReport(id, port, st.counters);
        } else if (strncmp(cmd, "coll", 4) == 0 && cmd[4] == ' ') {
            // "coll <alg> <bytes> <seq>": run ONE collective round on a
            // fiber (the driver sends the same command to every node)
            // and print a COLL result line. alg: allreduce |
            // allreduce_serial | allgather | alltoall |
            // allreduce_verbs | allreduce_chunks (lane-pinned, ISSUE 18).
            char alg[32];
            unsigned long long cbytes = 0, cseq = 0;
            if (sscanf(cmd + 5, "%31s %llu %llu", alg, &cbytes, &cseq) ==
                3) {
                auto* a = new CollRunArgs;
                a->st = &st;
                a->alg = alg;
                a->bytes = cbytes;
                a->seq = cseq;
                a->print = true;
                fiber_t ct;
                if (fiber_start_background(&ct, nullptr, CollCommandFiber,
                                           a) != 0) {
                    CollCommandFiber(a);
                } else {
                    // Track it: teardown must join commanded rounds
                    // before the stack-local NodeState goes away (and
                    // before a REPORT claims outstanding == 0).
                    st.traffic_fibers.push_back(ct);
                }
            } else {
                printf("COLL {\"ok\": 0, \"error\": 22}\n");
                fflush(stdout);
            }
        } else if (strncmp(cmd, "chain", 5) == 0) {
            auto* a = new ChainArgs;
            char* save = nullptr;
            strtok_r(cmd, " \n", &save);  // "chain"
            char* tok = strtok_r(nullptr, " \n", &save);
            if (tok != nullptr) a->timeout_ms = atoll(tok);
            while ((tok = strtok_r(nullptr, " \n", &save)) != nullptr) {
                if (*tok != '\0') a->eps.push_back(tok);
            }
            fiber_t ct;
            if (fiber_start_background(&ct, nullptr, ChainCallFiber, a) !=
                0) {
                ChainCallFiber(a);
            }
        } else if (strncmp(cmd, "delay", 5) == 0) {
            int h = 0, s_ms = 0;
            if (sscanf(cmd + 5, "%d %d", &h, &s_ms) == 2) {
                // A sleeping handler must never run on the input fiber:
                // suspend run-to-completion for the delay phase.
                if (inline_echo) {
                    server.SetMethodInlineSafe("benchpb.EchoService",
                                               "Echo", h <= 0);
                }
                g_handler_delay_ms.store(h, std::memory_order_relaxed);
                g_stale_budget_ms.store(s_ms, std::memory_order_relaxed);
                printf("DELAY_OK %d %d\n", h, s_ms);
                fflush(stdout);
            }
        }
    }
    // EOF: orderly shutdown. Stop traffic if "stop" never arrived. The
    // quit watcher holds pointers to the stack-local server/state: stop
    // and join it FIRST. (If a SIGTERM raced us, the join blocks until
    // the watcher's own GracefulStop path _exits the process — also
    // orderly.)
    if (have_quit_watcher) {
        st.watcher_stop.store(true, std::memory_order_release);
        fiber_join(quit_watcher, nullptr);
    }
    // Unpark collective drivers/handlers BEFORE joining the traffic
    // fibers (a commanded round blocked in a fan-out would otherwise
    // hold the join for its op timeout) and before Join (a handler
    // fiber parked in the engine would hold its connection open).
    if (g_coll_engine != nullptr) g_coll_engine->Shutdown();
    st.StopTraffic();
    server.Stop();
    server.Join();  // quiesces sockets: a leak would hang (pytest timeout)
    fflush(nullptr);
    _exit(0);  // skip static dtors (long-lived server discipline)
}
