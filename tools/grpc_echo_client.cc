// gRPC client driver: calls ANY gRPC server's /benchpb.EchoService/Echo
// over h2c using the framework's client stack (Channel protocol="grpc" ->
// thttp/http2_client.cc). Used by tests/test_grpc_client_interop.py
// against a real grpcio server; doubles as example/grpc_c++ client parity
// (/root/reference/example/grpc_c++/client.cpp).
//
// Usage: grpc_echo_client HOST:PORT [send_ts_us] [payload_bytes] [count]
//                         [--tls]
// Prints "OK <send_ts_us> <payload_size>" per call; exit 0 iff all
// succeed. --tls: gRPC over TLS with ALPN h2 (self-signed servers
// accepted; verification off, like the reference default).
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>

#include "bench_echo.pb.h"
#include "tbase/endpoint.h"
#include "trpc/channel.h"
#include "trpc/controller.h"

using namespace tpurpc;

int main(int argc, char** argv) {
    if (argc < 2) {
        fprintf(stderr,
                "usage: %s HOST:PORT [send_ts_us] [payload_bytes] [count]\n",
                argv[0]);
        return 2;
    }
    bool tls = false;
    for (int i = 2; i < argc; ++i) {
        if (strcmp(argv[i], "--tls") == 0) tls = true;
    }
    const int64_t ts =
        argc > 2 && strcmp(argv[2], "--tls") != 0 ? atoll(argv[2]) : 12345;
    const long payload_bytes =
        argc > 3 && strcmp(argv[3], "--tls") != 0 ? atol(argv[3]) : 0;
    const int count =
        argc > 4 && strcmp(argv[4], "--tls") != 0 ? atoi(argv[4]) : 1;

    EndPoint ep;
    if (str2endpoint(argv[1], &ep) != 0) {
        fprintf(stderr, "bad endpoint %s\n", argv[1]);
        return 2;
    }
    Channel ch;
    ChannelOptions opts;
    opts.protocol = "grpc";
    opts.timeout_ms = 15000;
    opts.tls = tls;
    if (ch.Init(ep, &opts) != 0) {
        fprintf(stderr, "channel init failed\n");
        return 1;
    }
    benchpb::EchoService_Stub stub(&ch);
    for (int i = 0; i < count; ++i) {
        Controller cntl;
        benchpb::EchoRequest req;
        req.set_send_ts_us(ts + i);
        if (payload_bytes > 0) {
            req.set_payload(std::string((size_t)payload_bytes, 'p'));
        }
        benchpb::EchoResponse res;
        stub.Echo(&cntl, &req, &res, nullptr);
        if (cntl.Failed()) {
            fprintf(stderr, "call %d failed: %d %s\n", i, cntl.ErrorCode(),
                    cntl.ErrorText().c_str());
            return 1;
        }
        if (res.send_ts_us() != ts + i ||
            (long)res.payload().size() != payload_bytes) {
            fprintf(stderr, "call %d echoed wrong values\n", i);
            return 1;
        }
        printf("OK %lld %zu\n", (long long)res.send_ts_us(),
               res.payload().size());
    }
    return 0;
}
