#!/bin/bash
# Generate the protobuf STUB headers used by toolchain-less containers
# (no cmake/protoc, only g++) to syntax-sweep the whole repo and to
# build the runtime stub libtpurpc.so — see .claude/skills/verify/
# SKILL.md "Toolchain-less container fallback". Never used by the real
# CMake build (protoc generates the real .pb.h there).
#
#   bash tools/mkpbstub.sh [DEST]    # default DEST=/tmp/pbstub
#
# Produces DEST/google/protobuf/*.h (minimal API the repo touches) and
# DEST/gen/{rpc_meta,echo,bench_echo}.pb.h. The rpc_meta stub REALLY
# encodes/decodes proto2 varint fields 3 (correlation_id),
# 5 (attachment_size) and 7 (body_checksum), so c_api framing bytes
# match the protoc build and the Python native tests run for real.
# Sweep:  g++ -std=c++17 -fsyntax-only -Icpp -Icpp/tests \
#             -isystem DEST -IDEST/gen <file.cc>
set -euo pipefail
DEST="${1:-/tmp/pbstub}"
mkdir -p "$DEST/google/protobuf/util" "$DEST/gen"

cat > "$DEST/google/protobuf/message_lite.h" << 'PBEOF'
#pragma once
#include <cstddef>
#include <cstdint>
#include <string>
namespace google {
namespace protobuf {
class MessageLite {
public:
    virtual ~MessageLite() = default;
    virtual bool SerializeToString(std::string* out) const {
        if (out) out->clear();
        return true;
    }
    virtual bool ParseFromString(const std::string&) { return true; }
    bool ParseFromArray(const void* data, int n) {
        return ParseFromString(
            std::string((const char*)data, (size_t)(n < 0 ? 0 : n)));
    }
    bool AppendToString(std::string* out) const {
        std::string s;
        if (!SerializeToString(&s)) return false;
        out->append(s);
        return true;
    }
    size_t ByteSizeLong() const {
        std::string s;
        SerializeToString(&s);
        return s.size();
    }
};
}  // namespace protobuf
}  // namespace google
PBEOF

cat > "$DEST/google/protobuf/descriptor.h" << 'PBEOF'
#pragma once
#include <string>
#include <vector>
namespace google {
namespace protobuf {
class ServiceDescriptor;
class MethodDescriptor {
public:
    MethodDescriptor(const ServiceDescriptor* s, std::string n,
                     std::string fn)
        : service_(s), name_(std::move(n)), full_name_(std::move(fn)) {}
    const std::string& name() const { return name_; }
    const std::string& full_name() const { return full_name_; }
    const ServiceDescriptor* service() const { return service_; }
private:
    const ServiceDescriptor* service_;
    std::string name_;
    std::string full_name_;
};
class ServiceDescriptor {
public:
    explicit ServiceDescriptor(std::string full_name)
        : full_name_(std::move(full_name)) {}
    void add_method(const std::string& n) {
        methods_.push_back(
            new MethodDescriptor(this, n, full_name_ + "." + n));
    }
    const std::string& full_name() const { return full_name_; }
    int method_count() const { return (int)methods_.size(); }
    const MethodDescriptor* method(int i) const { return methods_[i]; }
private:
    std::string full_name_;
    std::vector<MethodDescriptor*> methods_;
};
class Descriptor {
public:
    const std::string& full_name() const { return full_name_; }
    std::string full_name_;
};
class FieldDescriptor {};
class Message;
class Reflection {
public:
    void Swap(Message*, Message*) const {}
};
}  // namespace protobuf
}  // namespace google
PBEOF

cat > "$DEST/google/protobuf/message.h" << 'PBEOF'
#pragma once
#include <google/protobuf/descriptor.h>
#include <google/protobuf/message_lite.h>
namespace google {
namespace protobuf {
class Message : public MessageLite {
public:
    virtual Message* New() const { return nullptr; }
    virtual const Descriptor* GetDescriptor() const { return nullptr; }
    virtual const Reflection* GetReflection() const {
        static Reflection r;
        return &r;
    }
    virtual void CopyFrom(const Message&) {}
    virtual void MergeFrom(const Message&) {}
    virtual void Clear() {}
    virtual std::string DebugString() const { return ""; }
};
}  // namespace protobuf
}  // namespace google
PBEOF

cat > "$DEST/google/protobuf/service.h" << 'PBEOF'
#pragma once
#include <google/protobuf/descriptor.h>
#include <google/protobuf/message.h>
#include <string>
namespace google {
namespace protobuf {
class Closure {
public:
    virtual ~Closure() = default;
    virtual void Run() = 0;
};
namespace internal {
template <typename A1>
class FunctionClosure1 : public Closure {
public:
    FunctionClosure1(void (*f)(A1), A1 a1) : f_(f), a1_(a1) {}
    void Run() override {
        auto f = f_;
        auto a1 = a1_;
        delete this;
        f(a1);
    }
private:
    void (*f_)(A1);
    A1 a1_;
};
template <typename C, typename A1>
class MethodClosure1 : public Closure {
public:
    MethodClosure1(void (C::*m)(A1), C* o, A1 a1)
        : m_(m), o_(o), a1_(a1) {}
    void Run() override {
        auto m = m_;
        auto o = o_;
        auto a1 = a1_;
        delete this;
        (o->*m)(a1);
    }
private:
    void (C::*m_)(A1);
    C* o_;
    A1 a1_;
};
}  // namespace internal
template <typename A1>
Closure* NewCallback(void (*f)(A1), A1 a1) {
    return new internal::FunctionClosure1<A1>(f, a1);
}
// static-member-function form: NewCallback(&T::Done, arg)
template <typename A1>
Closure* NewCallback(void (*f)(A1*), A1* a1) {
    return new internal::FunctionClosure1<A1*>(f, a1);
}
template <typename A1, typename A2>
class FunctionClosure2T : public Closure {
public:
    FunctionClosure2T(void (*f)(A1, A2), A1 a1, A2 a2)
        : f_(f), a1_(a1), a2_(a2) {}
    void Run() override {
        auto f = f_;
        auto a1 = a1_;
        auto a2 = a2_;
        delete this;
        f(a1, a2);
    }
private:
    void (*f_)(A1, A2);
    A1 a1_;
    A2 a2_;
};
template <typename A1, typename A2>
Closure* NewCallback(void (*f)(A1, A2), A1 a1, A2 a2) {
    return new FunctionClosure2T<A1, A2>(f, a1, a2);
}
class RpcController {
public:
    virtual ~RpcController() = default;
    virtual void Reset() = 0;
    virtual bool Failed() const = 0;
    virtual std::string ErrorText() const = 0;
    virtual void StartCancel() = 0;
    virtual void SetFailed(const std::string& reason) = 0;
    virtual bool IsCanceled() const = 0;
    virtual void NotifyOnCancel(Closure* closure) = 0;
};
class RpcChannel {
public:
    virtual ~RpcChannel() = default;
    virtual void CallMethod(const MethodDescriptor* method,
                            RpcController* controller,
                            const Message* request, Message* response,
                            Closure* done) = 0;
};
class Service {
public:
    virtual ~Service() = default;
    virtual const ServiceDescriptor* GetDescriptor() = 0;
    virtual void CallMethod(const MethodDescriptor* method,
                            RpcController* controller,
                            const Message* request, Message* response,
                            Closure* done) = 0;
    virtual const Message& GetRequestPrototype(
        const MethodDescriptor* method) const = 0;
    virtual const Message& GetResponsePrototype(
        const MethodDescriptor* method) const = 0;
};
}  // namespace protobuf
}  // namespace google
PBEOF

cat > "$DEST/google/protobuf/util/json_util.h" << 'PBEOF'
#pragma once
#include <google/protobuf/message.h>
#include <string>
namespace google {
namespace protobuf {
namespace util {
struct Status {
    bool ok() const { return true; }
    std::string ToString() const { return "ok"; }
};
struct JsonParseOptions {
    bool ignore_unknown_fields = false;
};
struct JsonPrintOptions {
    bool add_whitespace = false;
    bool always_print_primitive_fields = false;
    bool preserve_proto_field_names = false;
};
inline Status JsonStringToMessage(const std::string&, Message*,
                                  const JsonParseOptions&) {
    return Status();
}
inline Status MessageToJsonString(const Message&, std::string*,
                                  const JsonPrintOptions&) {
    return Status();
}
}  // namespace util
}  // namespace protobuf
}  // namespace google
PBEOF

cat > "$DEST/gen/rpc_meta.pb.h" << 'PBEOF'
// STUB of protoc output for cpp/trpc/proto/rpc_meta.proto (sweep +
// runtime-stub builds only). Fields 3/5/7 (correlation_id,
// attachment_size, body_checksum) REALLY encode/decode as proto2
// varints so tpurpc_frame/unframe produce protoc-compatible bytes;
// every other field is in-memory only.
#pragma once
#include <google/protobuf/message.h>
#include <cstdint>
#include <string>
namespace tpurpc {
namespace rpc {

class PoolDescriptor : public google::protobuf::Message {
public:
    uint64_t pool_id() const { return pool_id_; }
    void set_pool_id(uint64_t v) { pool_id_ = v; }
    uint64_t offset() const { return offset_; }
    void set_offset(uint64_t v) { offset_ = v; }
    uint64_t length() const { return length_; }
    void set_length(uint64_t v) { length_ = v; }
    bool has_crc32c() const { return has_crc32c_; }
    uint32_t crc32c() const { return crc32c_; }
    void set_crc32c(uint32_t v) {
        crc32c_ = v;
        has_crc32c_ = true;
    }
    bool has_pool_epoch() const { return has_pool_epoch_; }
    uint64_t pool_epoch() const { return pool_epoch_; }
    void set_pool_epoch(uint64_t v) {
        pool_epoch_ = v;
        has_pool_epoch_ = true;
    }
    uint64_t ack_token() const { return ack_token_; }
    void set_ack_token(uint64_t v) { ack_token_ = v; }
private:
    uint64_t pool_id_ = 0, offset_ = 0, length_ = 0, pool_epoch_ = 0;
    uint64_t ack_token_ = 0;
    uint32_t crc32c_ = 0;
    bool has_crc32c_ = false, has_pool_epoch_ = false;
};

class RpcRequestMeta : public google::protobuf::Message {
public:
    const std::string& service_name() const { return service_name_; }
    void set_service_name(const std::string& v) { service_name_ = v; }
    const std::string& method_name() const { return method_name_; }
    void set_method_name(const std::string& v) { method_name_ = v; }
    bool has_timeout_ms() const { return has_timeout_ms_; }
    int64_t timeout_ms() const { return timeout_ms_; }
    void set_timeout_ms(int64_t v) {
        timeout_ms_ = v;
        has_timeout_ms_ = true;
    }
    int64_t log_id() const { return log_id_; }
    void set_log_id(int64_t v) { log_id_ = v; }
    bool has_tenant() const { return !tenant_.empty(); }
    const std::string& tenant() const { return tenant_; }
    void set_tenant(const std::string& v) { tenant_ = v; }
    bool has_priority() const { return has_priority_; }
    int priority() const { return priority_; }
    void set_priority(int v) {
        priority_ = v;
        has_priority_ = true;
    }
    bool has_trace_id() const { return has_trace_id_; }
    uint64_t trace_id() const { return trace_id_; }
    void set_trace_id(uint64_t v) {
        trace_id_ = v;
        has_trace_id_ = true;
    }
    bool has_span_id() const { return has_span_id_; }
    uint64_t span_id() const { return span_id_; }
    void set_span_id(uint64_t v) {
        span_id_ = v;
        has_span_id_ = true;
    }
    bool has_parent_span_id() const { return parent_span_id_ != 0; }
    uint64_t parent_span_id() const { return parent_span_id_; }
    void set_parent_span_id(uint64_t v) { parent_span_id_ = v; }
private:
    std::string service_name_, method_name_, tenant_;
    int64_t timeout_ms_ = 0, log_id_ = 0;
    uint64_t trace_id_ = 0, span_id_ = 0, parent_span_id_ = 0;
    int priority_ = 0;
    bool has_timeout_ms_ = false, has_priority_ = false;
    bool has_trace_id_ = false, has_span_id_ = false;
};

class RpcResponseMeta : public google::protobuf::Message {
public:
    int error_code() const { return error_code_; }
    void set_error_code(int v) { error_code_ = v; }
    const std::string& error_text() const { return error_text_; }
    void set_error_text(const std::string& v) { error_text_ = v; }
    bool has_backoff_ms() const { return backoff_ms_ != 0; }
    int64_t backoff_ms() const { return backoff_ms_; }
    void set_backoff_ms(int64_t v) { backoff_ms_ = v; }
    bool has_pool_attachment() const { return has_pool_attachment_; }
    const PoolDescriptor& pool_attachment() const {
        return pool_attachment_;
    }
    PoolDescriptor* mutable_pool_attachment() {
        has_pool_attachment_ = true;
        return &pool_attachment_;
    }
private:
    int error_code_ = 0;
    int64_t backoff_ms_ = 0;
    std::string error_text_;
    PoolDescriptor pool_attachment_;
    bool has_pool_attachment_ = false;
};

class StreamSettings : public google::protobuf::Message {
public:
    uint64_t stream_id() const { return stream_id_; }
    void set_stream_id(uint64_t v) { stream_id_ = v; }
    int64_t window_size() const { return window_size_; }
    void set_window_size(int64_t v) { window_size_ = v; }
private:
    uint64_t stream_id_ = 0;
    int64_t window_size_ = 0;
};

class RpcMeta : public google::protobuf::Message {
public:
    bool has_request() const { return has_request_; }
    const RpcRequestMeta& request() const { return request_; }
    RpcRequestMeta* mutable_request() {
        has_request_ = true;
        return &request_;
    }
    bool has_response() const { return has_response_; }
    const RpcResponseMeta& response() const { return response_; }
    RpcResponseMeta* mutable_response() {
        has_response_ = true;
        return &response_;
    }
    uint64_t correlation_id() const { return correlation_id_; }
    void set_correlation_id(uint64_t v) { correlation_id_ = v; }
    int compress_type() const { return compress_type_; }
    void set_compress_type(int v) { compress_type_ = v; }
    uint32_t attachment_size() const { return attachment_size_; }
    void set_attachment_size(uint32_t v) { attachment_size_ = v; }
    bool has_stream_settings() const { return has_stream_settings_; }
    const StreamSettings& stream_settings() const {
        return stream_settings_;
    }
    StreamSettings* mutable_stream_settings() {
        has_stream_settings_ = true;
        return &stream_settings_;
    }
    bool has_body_checksum() const { return has_body_checksum_; }
    uint32_t body_checksum() const { return body_checksum_; }
    void set_body_checksum(uint32_t v) {
        body_checksum_ = v;
        has_body_checksum_ = true;
    }
    bool has_auth_data() const { return !auth_data_.empty(); }
    const std::string& auth_data() const { return auth_data_; }
    void set_auth_data(const std::string& v) { auth_data_ = v; }
    bool cancel() const { return cancel_; }
    void set_cancel(bool v) { cancel_ = v; }
    bool goaway() const { return goaway_; }
    void set_goaway(bool v) { goaway_ = v; }
    bool desc_ack() const { return desc_ack_; }
    void set_desc_ack(bool v) { desc_ack_ = v; }
    bool has_desc_ack_token() const { return desc_ack_token_ != 0; }
    uint64_t desc_ack_token() const { return desc_ack_token_; }
    void set_desc_ack_token(uint64_t v) { desc_ack_token_ = v; }
    bool has_pool_attachment() const { return has_pool_attachment_; }
    const PoolDescriptor& pool_attachment() const {
        return pool_attachment_;
    }
    PoolDescriptor* mutable_pool_attachment() {
        has_pool_attachment_ = true;
        return &pool_attachment_;
    }

    // Real proto2 wire format for fields 3/5/7 (c_api framing).
    bool SerializeToString(std::string* out) const override {
        out->clear();
        auto varint = [&](uint64_t v) {
            while (v >= 0x80) {
                out->push_back((char)(0x80 | (v & 0x7f)));
                v >>= 7;
            }
            out->push_back((char)v);
        };
        if (correlation_id_ != 0) {
            out->push_back((char)((3 << 3) | 0));
            varint(correlation_id_);
        }
        if (attachment_size_ != 0) {
            out->push_back((char)((5 << 3) | 0));
            varint(attachment_size_);
        }
        if (has_body_checksum_) {
            out->push_back((char)((7 << 3) | 0));
            varint(body_checksum_);
        }
        return true;
    }
    bool ParseFromString(const std::string& s) override {
        size_t i = 0;
        auto varint = [&](uint64_t* v) {
            *v = 0;
            int shift = 0;
            while (i < s.size()) {
                const uint8_t b = (uint8_t)s[i++];
                *v |= (uint64_t)(b & 0x7f) << shift;
                if (!(b & 0x80)) return true;
                shift += 7;
                if (shift > 63) return false;
            }
            return false;
        };
        while (i < s.size()) {
            uint64_t key = 0;
            if (!varint(&key)) return false;
            const uint32_t field = (uint32_t)(key >> 3);
            const uint32_t wt = (uint32_t)(key & 7);
            uint64_t v = 0;
            if (wt == 0) {
                if (!varint(&v)) return false;
            } else if (wt == 2) {
                if (!varint(&v) || i + v > s.size()) return false;
                i += (size_t)v;
                continue;
            } else {
                return false;
            }
            if (field == 3) correlation_id_ = v;
            if (field == 5) attachment_size_ = (uint32_t)v;
            if (field == 7) {
                body_checksum_ = (uint32_t)v;
                has_body_checksum_ = true;
            }
        }
        return true;
    }
private:
    RpcRequestMeta request_;
    RpcResponseMeta response_;
    StreamSettings stream_settings_;
    PoolDescriptor pool_attachment_;
    std::string auth_data_;
    uint64_t correlation_id_ = 0, desc_ack_token_ = 0;
    uint32_t attachment_size_ = 0, body_checksum_ = 0;
    int compress_type_ = 0;
    bool has_request_ = false, has_response_ = false;
    bool has_stream_settings_ = false, has_body_checksum_ = false;
    bool cancel_ = false, goaway_ = false, desc_ack_ = false;
    bool has_pool_attachment_ = false;
};

}  // namespace rpc
}  // namespace tpurpc
PBEOF

# Shared scaffolding for the two generated echo services.
cat > "$DEST/gen/pbstub_service.h" << 'PBEOF'
#pragma once
#include <google/protobuf/service.h>
namespace pbstub {
// One-method echo service scaffold: descriptor + stub plumbing shared
// by the test/bench generated-code stand-ins.
template <typename Req, typename Res, typename Tag>
class EchoServiceT : public google::protobuf::Service {
public:
    static const google::protobuf::ServiceDescriptor* descriptor() {
        static google::protobuf::ServiceDescriptor* sd = [] {
            auto* d =
                new google::protobuf::ServiceDescriptor(Tag::full_name());
            d->add_method("Echo");
            return d;
        }();
        return sd;
    }
    const google::protobuf::ServiceDescriptor* GetDescriptor() override {
        return descriptor();
    }
    virtual void Echo(google::protobuf::RpcController* controller,
                      const Req* request, Res* response,
                      google::protobuf::Closure* done) = 0;
    void CallMethod(const google::protobuf::MethodDescriptor*,
                    google::protobuf::RpcController* controller,
                    const google::protobuf::Message* request,
                    google::protobuf::Message* response,
                    google::protobuf::Closure* done) override {
        Echo(controller, (const Req*)request, (Res*)response, done);
    }
    const google::protobuf::Message& GetRequestPrototype(
        const google::protobuf::MethodDescriptor*) const override {
        static Req req;
        return req;
    }
    const google::protobuf::Message& GetResponsePrototype(
        const google::protobuf::MethodDescriptor*) const override {
        static Res res;
        return res;
    }
};
template <typename Req, typename Res, typename Tag>
class EchoStubT {
public:
    explicit EchoStubT(google::protobuf::RpcChannel* channel)
        : channel_(channel) {}
    void Echo(google::protobuf::RpcController* controller, const Req* req,
              Res* res, google::protobuf::Closure* done) {
        channel_->CallMethod(
            EchoServiceT<Req, Res, Tag>::descriptor()->method(0),
            controller, req, res, done);
    }
private:
    google::protobuf::RpcChannel* channel_;
};
}  // namespace pbstub
PBEOF

cat > "$DEST/gen/echo.pb.h" << 'PBEOF'
// STUB of protoc output for cpp/tests/proto/echo.proto.
#pragma once
#include "pbstub_service.h"
#include <string>
namespace test {
class EchoRequest : public google::protobuf::Message {
public:
    const std::string& message() const { return message_; }
    void set_message(const std::string& v) { message_ = v; }
    std::string* mutable_message() { return &message_; }
    int sleep_us() const { return sleep_us_; }
    void set_sleep_us(int v) { sleep_us_ = v; }
    int fail_with() const { return fail_with_; }
    void set_fail_with(int v) { fail_with_ = v; }
    google::protobuf::Message* New() const override {
        return new EchoRequest;
    }
private:
    std::string message_;
    int sleep_us_ = 0;
    int fail_with_ = 0;
};
class EchoResponse : public google::protobuf::Message {
public:
    const std::string& message() const { return message_; }
    void set_message(const std::string& v) { message_ = v; }
    std::string* mutable_message() { return &message_; }
    google::protobuf::Message* New() const override {
        return new EchoResponse;
    }
private:
    std::string message_;
};
struct EchoTag {
    static const char* full_name() { return "test.EchoService"; }
};
using EchoService = pbstub::EchoServiceT<EchoRequest, EchoResponse,
                                         EchoTag>;
using EchoService_Stub = pbstub::EchoStubT<EchoRequest, EchoResponse,
                                           EchoTag>;
// test.UnusedService: one "Nothing" method nobody registers — the
// no-such-method test calls it against a server that only serves Echo.
struct UnusedTag {
    static const char* full_name() { return "test.UnusedService"; }
};
class UnusedService_Stub {
public:
    explicit UnusedService_Stub(google::protobuf::RpcChannel* channel)
        : channel_(channel) {}
    void Nothing(google::protobuf::RpcController* controller,
                 const EchoRequest* req, EchoResponse* res,
                 google::protobuf::Closure* done) {
        static google::protobuf::ServiceDescriptor* sd = [] {
            auto* d = new google::protobuf::ServiceDescriptor(
                UnusedTag::full_name());
            d->add_method("Nothing");
            return d;
        }();
        channel_->CallMethod(sd->method(0), controller, req, res, done);
    }
private:
    google::protobuf::RpcChannel* channel_;
};
}  // namespace test
PBEOF

cat > "$DEST/gen/bench_echo.pb.h" << 'PBEOF'
// STUB of protoc output for tools/proto/bench_echo.proto.
#pragma once
#include "pbstub_service.h"
#include <string>
#include <vector>
namespace benchpb {
class EchoRequest : public google::protobuf::Message {
public:
    int64_t send_ts_us() const { return send_ts_us_; }
    void set_send_ts_us(int64_t v) { send_ts_us_ = v; }
    bool has_payload() const { return !payload_.empty(); }
    const std::string& payload() const { return payload_; }
    void set_payload(const std::string& v) { payload_ = v; }
    bool stale() const { return stale_; }
    void set_stale(bool v) { stale_ = v; }
    int chain_size() const { return (int)chain_.size(); }
    const std::string& chain(int i) const { return chain_[i]; }
    void add_chain(const std::string& v) { chain_.push_back(v); }
    google::protobuf::Message* New() const override {
        return new EchoRequest;
    }
private:
    int64_t send_ts_us_ = 0;
    std::string payload_;
    bool stale_ = false;
    std::vector<std::string> chain_;
};
class EchoResponse : public google::protobuf::Message {
public:
    int64_t send_ts_us() const { return send_ts_us_; }
    void set_send_ts_us(int64_t v) { send_ts_us_ = v; }
    const std::string& payload() const { return payload_; }
    void set_payload(const std::string& v) { payload_ = v; }
    google::protobuf::Message* New() const override {
        return new EchoResponse;
    }
private:
    int64_t send_ts_us_ = 0;
    std::string payload_;
};
struct EchoTag {
    static const char* full_name() { return "benchpb.EchoService"; }
};
using EchoService = pbstub::EchoServiceT<EchoRequest, EchoResponse,
                                         EchoTag>;
using EchoService_Stub = pbstub::EchoStubT<EchoRequest, EchoResponse,
                                           EchoTag>;
}  // namespace benchpb
PBEOF

echo "pbstub written to $DEST"
