#!/bin/bash
# Generate the protobuf STUB headers used by toolchain-less containers
# (no cmake/protoc, only g++) to syntax-sweep the whole repo and to
# build the runtime stub libtpurpc.so — see .claude/skills/verify/
# SKILL.md "Toolchain-less container fallback". Never used by the real
# CMake build (protoc generates the real .pb.h there).
#
#   bash tools/mkpbstub.sh [DEST]    # default DEST=/tmp/pbstub
#
# Produces DEST/google/protobuf/*.h (minimal API the repo touches) and
# DEST/gen/{rpc_meta,echo,bench_echo}.pb.h. Since ISSUE 13 the stubs
# are WIRE-COMPLETE: every message field really encodes/decodes with
# the proto2 wire format (gen/pbstub_wire.h — varints, zigzag,
# length-delimited strings and submessages), so runtime-stub builds of
# the whole RPC stack speak protoc-compatible bytes over real sockets
# (request routing, response errors, descriptors, test payloads).
# Sweep:  g++ -std=c++17 -fsyntax-only -Icpp -Icpp/tests \
#             -isystem DEST -IDEST/gen <file.cc>
set -euo pipefail
DEST="${1:-/tmp/pbstub}"
mkdir -p "$DEST/google/protobuf/util" "$DEST/gen"

cat > "$DEST/google/protobuf/message_lite.h" << 'PBEOF'
#pragma once
#include <cstddef>
#include <cstdint>
#include <string>
namespace google {
namespace protobuf {
class MessageLite {
public:
    virtual ~MessageLite() = default;
    virtual bool SerializeToString(std::string* out) const {
        if (out) out->clear();
        return true;
    }
    virtual bool ParseFromString(const std::string&) { return true; }
    bool ParseFromArray(const void* data, int n) {
        return ParseFromString(
            std::string((const char*)data, (size_t)(n < 0 ? 0 : n)));
    }
    bool AppendToString(std::string* out) const {
        std::string s;
        if (!SerializeToString(&s)) return false;
        out->append(s);
        return true;
    }
    size_t ByteSizeLong() const {
        std::string s;
        SerializeToString(&s);
        return s.size();
    }
};
}  // namespace protobuf
}  // namespace google
PBEOF

cat > "$DEST/google/protobuf/descriptor.h" << 'PBEOF'
#pragma once
#include <string>
#include <vector>
namespace google {
namespace protobuf {
class ServiceDescriptor;
class MethodDescriptor {
public:
    MethodDescriptor(const ServiceDescriptor* s, std::string n,
                     std::string fn)
        : service_(s), name_(std::move(n)), full_name_(std::move(fn)) {}
    const std::string& name() const { return name_; }
    const std::string& full_name() const { return full_name_; }
    const ServiceDescriptor* service() const { return service_; }
private:
    const ServiceDescriptor* service_;
    std::string name_;
    std::string full_name_;
};
class ServiceDescriptor {
public:
    explicit ServiceDescriptor(std::string full_name)
        : full_name_(std::move(full_name)) {}
    void add_method(const std::string& n) {
        methods_.push_back(
            new MethodDescriptor(this, n, full_name_ + "." + n));
    }
    const std::string& full_name() const { return full_name_; }
    int method_count() const { return (int)methods_.size(); }
    const MethodDescriptor* method(int i) const { return methods_[i]; }
private:
    std::string full_name_;
    std::vector<MethodDescriptor*> methods_;
};
class Descriptor {
public:
    const std::string& full_name() const { return full_name_; }
    std::string full_name_;
};
class FieldDescriptor {};
class Message;
class Reflection {
public:
    // Wire-based swap; defined in message.h once Message is complete.
    void Swap(Message* a, Message* b) const;
};
}  // namespace protobuf
}  // namespace google
PBEOF

cat > "$DEST/google/protobuf/message.h" << 'PBEOF'
#pragma once
#include <google/protobuf/descriptor.h>
#include <google/protobuf/message_lite.h>
namespace google {
namespace protobuf {
class Message : public MessageLite {
public:
    virtual Message* New() const { return nullptr; }
    virtual const Descriptor* GetDescriptor() const { return nullptr; }
    virtual const Reflection* GetReflection() const {
        static Reflection r;
        return &r;
    }
    // Wire-based defaults: real enough for the merge/copy paths the
    // framework exercises (stub messages implement real Serialize/
    // Parse and a real Clear). Copy/Swap must CLEAR first — serialize
    // omits default-valued fields, and parse-without-clear would merge
    // instead of replace (stale nonzero fields surviving a "copy").
    virtual void CopyFrom(const Message& other) {
        std::string s;
        other.SerializeToString(&s);
        Clear();
        ParseFromString(s);
    }
    // proto2 merge semantics for singular fields (overwrite when set in
    // `other`) == parse without clearing.
    virtual void MergeFrom(const Message& other) {
        std::string s;
        other.SerializeToString(&s);
        ParseFromString(s);
    }
    virtual void Clear() {}
    virtual std::string DebugString() const { return ""; }
};
inline void Reflection::Swap(Message* a, Message* b) const {
    std::string sa, sb;
    a->SerializeToString(&sa);
    b->SerializeToString(&sb);
    a->Clear();
    b->Clear();
    a->ParseFromString(sb);
    b->ParseFromString(sa);
}
}  // namespace protobuf
}  // namespace google
PBEOF

cat > "$DEST/google/protobuf/service.h" << 'PBEOF'
#pragma once
#include <google/protobuf/descriptor.h>
#include <google/protobuf/message.h>
#include <string>
namespace google {
namespace protobuf {
class Closure {
public:
    virtual ~Closure() = default;
    virtual void Run() = 0;
};
namespace internal {
template <typename A1>
class FunctionClosure1 : public Closure {
public:
    FunctionClosure1(void (*f)(A1), A1 a1) : f_(f), a1_(a1) {}
    void Run() override {
        auto f = f_;
        auto a1 = a1_;
        delete this;
        f(a1);
    }
private:
    void (*f_)(A1);
    A1 a1_;
};
template <typename C, typename A1>
class MethodClosure1 : public Closure {
public:
    MethodClosure1(void (C::*m)(A1), C* o, A1 a1)
        : m_(m), o_(o), a1_(a1) {}
    void Run() override {
        auto m = m_;
        auto o = o_;
        auto a1 = a1_;
        delete this;
        (o->*m)(a1);
    }
private:
    void (C::*m_)(A1);
    C* o_;
    A1 a1_;
};
}  // namespace internal
template <typename A1>
Closure* NewCallback(void (*f)(A1), A1 a1) {
    return new internal::FunctionClosure1<A1>(f, a1);
}
// static-member-function form: NewCallback(&T::Done, arg)
template <typename A1>
Closure* NewCallback(void (*f)(A1*), A1* a1) {
    return new internal::FunctionClosure1<A1*>(f, a1);
}
template <typename A1, typename A2>
class FunctionClosure2T : public Closure {
public:
    FunctionClosure2T(void (*f)(A1, A2), A1 a1, A2 a2)
        : f_(f), a1_(a1), a2_(a2) {}
    void Run() override {
        auto f = f_;
        auto a1 = a1_;
        auto a2 = a2_;
        delete this;
        f(a1, a2);
    }
private:
    void (*f_)(A1, A2);
    A1 a1_;
    A2 a2_;
};
template <typename A1, typename A2>
Closure* NewCallback(void (*f)(A1, A2), A1 a1, A2 a2) {
    return new FunctionClosure2T<A1, A2>(f, a1, a2);
}
class RpcController {
public:
    virtual ~RpcController() = default;
    virtual void Reset() = 0;
    virtual bool Failed() const = 0;
    virtual std::string ErrorText() const = 0;
    virtual void StartCancel() = 0;
    virtual void SetFailed(const std::string& reason) = 0;
    virtual bool IsCanceled() const = 0;
    virtual void NotifyOnCancel(Closure* closure) = 0;
};
class RpcChannel {
public:
    virtual ~RpcChannel() = default;
    virtual void CallMethod(const MethodDescriptor* method,
                            RpcController* controller,
                            const Message* request, Message* response,
                            Closure* done) = 0;
};
class Service {
public:
    virtual ~Service() = default;
    virtual const ServiceDescriptor* GetDescriptor() = 0;
    virtual void CallMethod(const MethodDescriptor* method,
                            RpcController* controller,
                            const Message* request, Message* response,
                            Closure* done) = 0;
    virtual const Message& GetRequestPrototype(
        const MethodDescriptor* method) const = 0;
    virtual const Message& GetResponsePrototype(
        const MethodDescriptor* method) const = 0;
};
}  // namespace protobuf
}  // namespace google
PBEOF

cat > "$DEST/google/protobuf/util/json_util.h" << 'PBEOF'
#pragma once
#include <google/protobuf/message.h>
#include <string>
namespace google {
namespace protobuf {
namespace util {
struct Status {
    bool ok() const { return true; }
    std::string ToString() const { return "ok"; }
};
struct JsonParseOptions {
    bool ignore_unknown_fields = false;
};
struct JsonPrintOptions {
    bool add_whitespace = false;
    bool always_print_primitive_fields = false;
    bool preserve_proto_field_names = false;
};
inline Status JsonStringToMessage(const std::string&, Message*,
                                  const JsonParseOptions&) {
    return Status();
}
inline Status MessageToJsonString(const Message&, std::string*,
                                  const JsonPrintOptions&) {
    return Status();
}
}  // namespace util
}  // namespace protobuf
}  // namespace google
PBEOF

cat > "$DEST/gen/pbstub_wire.h" << 'PBEOF'
// Minimal proto2 wire helpers shared by the stub pb.h files: REAL
// varint / length-delimited encoding so runtime-stub builds move
// protoc-compatible bytes (service routing, error codes, descriptors,
// test payloads) over real sockets.
#pragma once
#include <google/protobuf/message_lite.h>
#include <cstdint>
#include <cstring>
#include <string>
namespace pbstub {
namespace wire {
inline void varint(std::string* o, uint64_t v) {
    while (v >= 0x80) {
        o->push_back((char)(0x80 | (v & 0x7f)));
        v >>= 7;
    }
    o->push_back((char)v);
}
inline void put_u(std::string* o, uint32_t f, uint64_t v) {
    varint(o, ((uint64_t)f << 3) | 0);
    varint(o, v);
}
inline void put_str(std::string* o, uint32_t f, const std::string& s) {
    varint(o, ((uint64_t)f << 3) | 2);
    varint(o, s.size());
    o->append(s);
}
inline uint64_t zig32(int32_t v) {
    return (uint32_t)(((uint32_t)v << 1) ^ (uint32_t)(v >> 31));
}
inline int32_t unzig32(uint64_t n) {
    return (int32_t)((uint32_t)(n >> 1) ^ (uint32_t)(-(int64_t)(n & 1)));
}
struct Reader {
    const char* p;
    const char* end;
    explicit Reader(const std::string& s)
        : p(s.data()), end(s.data() + s.size()) {}
    bool varint(uint64_t* v) {
        *v = 0;
        int shift = 0;
        while (p < end) {
            const uint8_t b = (uint8_t)*p++;
            *v |= (uint64_t)(b & 0x7f) << shift;
            if (!(b & 0x80)) return true;
            shift += 7;
            if (shift > 63) return false;
        }
        return false;
    }
    // One field; returns false at end (ok=true) or on malformed input
    // (ok=false). wt0 fills v; wt2 fills s; wt1/5 fill v.
    bool next(uint32_t* field, uint32_t* wt, uint64_t* v, std::string* s,
              bool* ok) {
        if (p >= end) {
            *ok = true;
            return false;
        }
        uint64_t key = 0;
        if (!varint(&key)) {
            *ok = false;
            return false;
        }
        *field = (uint32_t)(key >> 3);
        *wt = (uint32_t)(key & 7);
        if (*wt == 0) {
            if (!varint(v)) {
                *ok = false;
                return false;
            }
        } else if (*wt == 2) {
            uint64_t n = 0;
            if (!varint(&n) || (uint64_t)(end - p) < n) {
                *ok = false;
                return false;
            }
            s->assign(p, (size_t)n);
            p += n;
        } else if (*wt == 5) {
            if (end - p < 4) {
                *ok = false;
                return false;
            }
            uint32_t x;
            memcpy(&x, p, 4);
            p += 4;
            *v = x;
        } else if (*wt == 1) {
            if (end - p < 8) {
                *ok = false;
                return false;
            }
            uint64_t x;
            memcpy(&x, p, 8);
            p += 8;
            *v = x;
        } else {
            *ok = false;
            return false;
        }
        *ok = true;
        return true;
    }
};
inline void put_msg(std::string* o, uint32_t f,
                    const google::protobuf::MessageLite& m) {
    std::string sub;
    m.SerializeToString(&sub);
    put_str(o, f, sub);
}
}  // namespace wire
}  // namespace pbstub
PBEOF

cat > "$DEST/gen/rpc_meta.pb.h" << 'PBEOF'
// STUB of protoc output for cpp/trpc/proto/rpc_meta.proto (sweep +
// runtime-stub builds only). EVERY field really encodes/decodes with
// the proto2 wire format (pbstub_wire.h), so tpu_std framing, request
// routing, response errors and pool descriptors all match the protoc
// build — runtime-stub meshes speak the real protocol.
#pragma once
#include <google/protobuf/message.h>
#include "pbstub_wire.h"
#include <cstdint>
#include <string>
namespace tpurpc {
namespace rpc {

class PoolDescriptor : public google::protobuf::Message {
public:
    uint64_t pool_id() const { return pool_id_; }
    void set_pool_id(uint64_t v) { pool_id_ = v; }
    uint64_t offset() const { return offset_; }
    void set_offset(uint64_t v) { offset_ = v; }
    uint64_t length() const { return length_; }
    void set_length(uint64_t v) { length_ = v; }
    bool has_crc32c() const { return has_crc32c_; }
    uint32_t crc32c() const { return crc32c_; }
    void set_crc32c(uint32_t v) {
        crc32c_ = v;
        has_crc32c_ = true;
    }
    bool has_pool_epoch() const { return has_pool_epoch_; }
    uint64_t pool_epoch() const { return pool_epoch_; }
    void set_pool_epoch(uint64_t v) {
        pool_epoch_ = v;
        has_pool_epoch_ = true;
    }
    uint64_t ack_token() const { return ack_token_; }
    void set_ack_token(uint64_t v) { ack_token_ = v; }
    void Clear() override { *this = PoolDescriptor(); }
    bool SerializeToString(std::string* out) const override {
        out->clear();
        pbstub::wire::put_u(out, 1, pool_id_);
        pbstub::wire::put_u(out, 2, offset_);
        pbstub::wire::put_u(out, 3, length_);
        if (has_crc32c_) pbstub::wire::put_u(out, 4, crc32c_);
        if (has_pool_epoch_) pbstub::wire::put_u(out, 5, pool_epoch_);
        if (ack_token_ != 0) pbstub::wire::put_u(out, 6, ack_token_);
        return true;
    }
    bool ParseFromString(const std::string& s) override {
        pbstub::wire::Reader r(s);
        uint32_t f = 0, wt = 0;
        uint64_t v = 0;
        std::string sub;
        bool ok = true;
        while (r.next(&f, &wt, &v, &sub, &ok)) {
            switch (f) {
                case 1: pool_id_ = v; break;
                case 2: offset_ = v; break;
                case 3: length_ = v; break;
                case 4: set_crc32c((uint32_t)v); break;
                case 5: set_pool_epoch(v); break;
                case 6: ack_token_ = v; break;
                default: break;
            }
        }
        return ok;
    }
private:
    uint64_t pool_id_ = 0, offset_ = 0, length_ = 0, pool_epoch_ = 0;
    uint64_t ack_token_ = 0;
    uint32_t crc32c_ = 0;
    bool has_crc32c_ = false, has_pool_epoch_ = false;
};

class RpcRequestMeta : public google::protobuf::Message {
public:
    const std::string& service_name() const { return service_name_; }
    void set_service_name(const std::string& v) { service_name_ = v; }
    const std::string& method_name() const { return method_name_; }
    void set_method_name(const std::string& v) { method_name_ = v; }
    bool has_timeout_ms() const { return has_timeout_ms_; }
    int64_t timeout_ms() const { return timeout_ms_; }
    void set_timeout_ms(int64_t v) {
        timeout_ms_ = v;
        has_timeout_ms_ = true;
    }
    int64_t log_id() const { return log_id_; }
    void set_log_id(int64_t v) { log_id_ = v; }
    bool has_tenant() const { return !tenant_.empty(); }
    const std::string& tenant() const { return tenant_; }
    void set_tenant(const std::string& v) { tenant_ = v; }
    bool has_priority() const { return has_priority_; }
    int priority() const { return priority_; }
    void set_priority(int v) {
        priority_ = v;
        has_priority_ = true;
    }
    bool has_zone() const { return !zone_.empty(); }
    const std::string& zone() const { return zone_; }
    void set_zone(const std::string& v) { zone_ = v; }
    bool has_session() const { return !session_.empty(); }
    const std::string& session() const { return session_; }
    void set_session(const std::string& v) { session_ = v; }
    bool has_trace_id() const { return has_trace_id_; }
    uint64_t trace_id() const { return trace_id_; }
    void set_trace_id(uint64_t v) {
        trace_id_ = v;
        has_trace_id_ = true;
    }
    bool has_span_id() const { return has_span_id_; }
    uint64_t span_id() const { return span_id_; }
    void set_span_id(uint64_t v) {
        span_id_ = v;
        has_span_id_ = true;
    }
    bool has_parent_span_id() const { return parent_span_id_ != 0; }
    uint64_t parent_span_id() const { return parent_span_id_; }
    void set_parent_span_id(uint64_t v) { parent_span_id_ = v; }
    void Clear() override { *this = RpcRequestMeta(); }
    bool SerializeToString(std::string* out) const override {
        out->clear();
        if (!service_name_.empty()) {
            pbstub::wire::put_str(out, 1, service_name_);
        }
        if (!method_name_.empty()) {
            pbstub::wire::put_str(out, 2, method_name_);
        }
        if (has_timeout_ms_) {
            pbstub::wire::put_u(out, 3, (uint64_t)timeout_ms_);
        }
        if (log_id_ != 0) pbstub::wire::put_u(out, 4, (uint64_t)log_id_);
        if (has_priority_) {
            pbstub::wire::put_u(out, 5, pbstub::wire::zig32(priority_));
        }
        if (has_trace_id_) pbstub::wire::put_u(out, 6, trace_id_);
        if (has_span_id_) pbstub::wire::put_u(out, 7, span_id_);
        if (parent_span_id_ != 0) {
            pbstub::wire::put_u(out, 8, parent_span_id_);
        }
        if (!tenant_.empty()) pbstub::wire::put_str(out, 9, tenant_);
        if (!zone_.empty()) pbstub::wire::put_str(out, 10, zone_);
        if (!session_.empty()) pbstub::wire::put_str(out, 11, session_);
        return true;
    }
    bool ParseFromString(const std::string& s) override {
        pbstub::wire::Reader r(s);
        uint32_t f = 0, wt = 0;
        uint64_t v = 0;
        std::string sub;
        bool ok = true;
        while (r.next(&f, &wt, &v, &sub, &ok)) {
            switch (f) {
                case 1: service_name_ = sub; break;
                case 2: method_name_ = sub; break;
                case 3: set_timeout_ms((int64_t)v); break;
                case 4: log_id_ = (int64_t)v; break;
                case 5: set_priority(pbstub::wire::unzig32(v)); break;
                case 6: set_trace_id(v); break;
                case 7: set_span_id(v); break;
                case 8: parent_span_id_ = v; break;
                case 9: tenant_ = sub; break;
                case 10: zone_ = sub; break;
                case 11: session_ = sub; break;
                default: break;
            }
        }
        return ok;
    }
private:
    std::string service_name_, method_name_, tenant_, zone_, session_;
    int64_t timeout_ms_ = 0, log_id_ = 0;
    uint64_t trace_id_ = 0, span_id_ = 0, parent_span_id_ = 0;
    int priority_ = 0;
    bool has_timeout_ms_ = false, has_priority_ = false;
    bool has_trace_id_ = false, has_span_id_ = false;
};

class RpcResponseMeta : public google::protobuf::Message {
public:
    int error_code() const { return error_code_; }
    void set_error_code(int v) { error_code_ = v; }
    const std::string& error_text() const { return error_text_; }
    void set_error_text(const std::string& v) { error_text_ = v; }
    bool has_backoff_ms() const { return backoff_ms_ != 0; }
    int64_t backoff_ms() const { return backoff_ms_; }
    void set_backoff_ms(int64_t v) { backoff_ms_ = v; }
    bool has_pool_attachment() const { return has_pool_attachment_; }
    const PoolDescriptor& pool_attachment() const {
        return pool_attachment_;
    }
    PoolDescriptor* mutable_pool_attachment() {
        has_pool_attachment_ = true;
        return &pool_attachment_;
    }
    void Clear() override { *this = RpcResponseMeta(); }
    bool SerializeToString(std::string* out) const override {
        out->clear();
        if (error_code_ != 0) {
            pbstub::wire::put_u(out, 1, (uint64_t)(int64_t)error_code_);
        }
        if (!error_text_.empty()) {
            pbstub::wire::put_str(out, 2, error_text_);
        }
        if (backoff_ms_ != 0) {
            pbstub::wire::put_u(out, 3, (uint64_t)backoff_ms_);
        }
        if (has_pool_attachment_) {
            pbstub::wire::put_msg(out, 4, pool_attachment_);
        }
        return true;
    }
    bool ParseFromString(const std::string& s) override {
        pbstub::wire::Reader r(s);
        uint32_t f = 0, wt = 0;
        uint64_t v = 0;
        std::string sub;
        bool ok = true;
        while (r.next(&f, &wt, &v, &sub, &ok)) {
            switch (f) {
                case 1: error_code_ = (int)(int64_t)v; break;
                case 2: error_text_ = sub; break;
                case 3: backoff_ms_ = (int64_t)v; break;
                case 4:
                    if (!mutable_pool_attachment()->ParseFromString(sub)) {
                        return false;
                    }
                    break;
                default: break;
            }
        }
        return ok;
    }
private:
    int error_code_ = 0;
    int64_t backoff_ms_ = 0;
    std::string error_text_;
    PoolDescriptor pool_attachment_;
    bool has_pool_attachment_ = false;
};

class StreamSettings : public google::protobuf::Message {
public:
    uint64_t stream_id() const { return stream_id_; }
    void set_stream_id(uint64_t v) { stream_id_ = v; }
    int64_t window_size() const { return window_size_; }
    void set_window_size(int64_t v) { window_size_ = v; }
    int version() const { return version_; }
    void set_version(int v) { version_ = v; }
    int64_t rx_window() const { return rx_window_; }
    void set_rx_window(int64_t v) { rx_window_ = v; }
    uint64_t resume_from_seq() const { return resume_from_seq_; }
    void set_resume_from_seq(uint64_t v) { resume_from_seq_ = v; }
    bool push() const { return push_; }
    void set_push(bool v) { push_ = v; }
    void Clear() override { *this = StreamSettings(); }
    bool SerializeToString(std::string* out) const override {
        out->clear();
        pbstub::wire::put_u(out, 1, stream_id_);
        if (window_size_ != 0) {
            pbstub::wire::put_u(out, 2, (uint64_t)window_size_);
        }
        if (version_ != 0) pbstub::wire::put_u(out, 3, (uint64_t)version_);
        if (rx_window_ != 0) {
            pbstub::wire::put_u(out, 4, (uint64_t)rx_window_);
        }
        if (resume_from_seq_ != 0) {
            pbstub::wire::put_u(out, 5, resume_from_seq_);
        }
        if (push_) pbstub::wire::put_u(out, 6, 1);
        return true;
    }
    bool ParseFromString(const std::string& s) override {
        pbstub::wire::Reader r(s);
        uint32_t f = 0, wt = 0;
        uint64_t v = 0;
        std::string sub;
        bool ok = true;
        while (r.next(&f, &wt, &v, &sub, &ok)) {
            if (f == 1) stream_id_ = v;
            if (f == 2) window_size_ = (int64_t)v;
            if (f == 3) version_ = (int)v;
            if (f == 4) rx_window_ = (int64_t)v;
            if (f == 5) resume_from_seq_ = v;
            if (f == 6) push_ = v != 0;
        }
        return ok;
    }
private:
    uint64_t stream_id_ = 0, resume_from_seq_ = 0;
    int64_t window_size_ = 0, rx_window_ = 0;
    int version_ = 0;
    bool push_ = false;
};

class StreamFrame : public google::protobuf::Message {
public:
    uint64_t stream_id() const { return stream_id_; }
    void set_stream_id(uint64_t v) { stream_id_ = v; }
    uint64_t seq() const { return seq_; }
    void set_seq(uint64_t v) { seq_ = v; }
    int kind() const { return kind_; }
    void set_kind(int v) { kind_ = v; }
    uint32_t flags() const { return flags_; }
    void set_flags(uint32_t v) { flags_ = v; }
    uint64_t ack_seq() const { return ack_seq_; }
    void set_ack_seq(uint64_t v) { ack_seq_ = v; }
    int64_t credits() const { return credits_; }
    void set_credits(int64_t v) { credits_ = v; }
    int error_code() const { return error_code_; }
    void set_error_code(int v) { error_code_ = v; }
    bool has_pool_attachment() const { return has_pool_attachment_; }
    const PoolDescriptor& pool_attachment() const {
        return pool_attachment_;
    }
    PoolDescriptor* mutable_pool_attachment() {
        has_pool_attachment_ = true;
        return &pool_attachment_;
    }
    void Clear() override { *this = StreamFrame(); }
    bool SerializeToString(std::string* out) const override {
        out->clear();
        pbstub::wire::put_u(out, 1, stream_id_);
        if (seq_ != 0) pbstub::wire::put_u(out, 2, seq_);
        if (kind_ != 0) pbstub::wire::put_u(out, 3, (uint64_t)kind_);
        if (flags_ != 0) pbstub::wire::put_u(out, 4, flags_);
        if (ack_seq_ != 0) pbstub::wire::put_u(out, 5, ack_seq_);
        if (credits_ != 0) pbstub::wire::put_u(out, 6, (uint64_t)credits_);
        if (error_code_ != 0) {
            pbstub::wire::put_u(out, 7, (uint64_t)error_code_);
        }
        if (has_pool_attachment_) {
            pbstub::wire::put_msg(out, 8, pool_attachment_);
        }
        return true;
    }
    bool ParseFromString(const std::string& s) override {
        pbstub::wire::Reader r(s);
        uint32_t f = 0, wt = 0;
        uint64_t v = 0;
        std::string sub;
        bool ok = true;
        while (r.next(&f, &wt, &v, &sub, &ok)) {
            if (f == 1) stream_id_ = v;
            if (f == 2) seq_ = v;
            if (f == 3) kind_ = (int)v;
            if (f == 4) flags_ = (uint32_t)v;
            if (f == 5) ack_seq_ = v;
            if (f == 6) credits_ = (int64_t)v;
            if (f == 7) error_code_ = (int)v;
            if (f == 8 &&
                !mutable_pool_attachment()->ParseFromString(sub)) {
                return false;
            }
        }
        return ok;
    }
private:
    uint64_t stream_id_ = 0, seq_ = 0, ack_seq_ = 0;
    int64_t credits_ = 0;
    uint32_t flags_ = 0;
    int kind_ = 0, error_code_ = 0;
    PoolDescriptor pool_attachment_;
    bool has_pool_attachment_ = false;
};

// Verb-plane wire messages (ISSUE 18): the window grant exchange and
// the emulated two-sided verb/completion frames. All-varint fields.
class WindowGrant : public google::protobuf::Message {
public:
    uint32_t kind() const { return kind_; }
    void set_kind(uint32_t v) { kind_ = v; }
    int status() const { return status_; }
    void set_status(int v) { status_ = v; }
    uint64_t window_id() const { return window_id_; }
    void set_window_id(uint64_t v) { window_id_ = v; }
    uint64_t length() const { return length_; }
    void set_length(uint64_t v) { length_ = v; }
    uint32_t mode() const { return mode_; }
    void set_mode(uint32_t v) { mode_ = v; }
    uint64_t pool_id() const { return pool_id_; }
    void set_pool_id(uint64_t v) { pool_id_ = v; }
    uint64_t offset() const { return offset_; }
    void set_offset(uint64_t v) { offset_ = v; }
    uint64_t pool_epoch() const { return pool_epoch_; }
    void set_pool_epoch(uint64_t v) { pool_epoch_ = v; }
    bool has_lease_ms() const { return has_lease_ms_; }
    int64_t lease_ms() const { return lease_ms_; }
    void set_lease_ms(int64_t v) {
        lease_ms_ = v;
        has_lease_ms_ = true;
    }
    google::protobuf::Message* New() const override {
        return new WindowGrant;
    }
    void Clear() override { *this = WindowGrant(); }
    bool SerializeToString(std::string* out) const override {
        out->clear();
        auto field = [&](uint32_t num, uint64_t v) {
            if (v != 0) pbstub::wire::put_u(out, num, v);
        };
        field(1, kind_);
        field(2, (uint64_t)(int64_t)status_);
        field(3, window_id_);
        field(4, length_);
        field(5, mode_);
        field(6, pool_id_);
        field(7, offset_);
        field(8, pool_epoch_);
        if (has_lease_ms_) {
            pbstub::wire::put_u(out, 9, (uint64_t)lease_ms_);
        }
        return true;
    }
    bool ParseFromString(const std::string& s) override {
        pbstub::wire::Reader r(s);
        uint32_t f = 0, wt = 0;
        uint64_t v = 0;
        std::string sub;
        bool ok = true;
        while (r.next(&f, &wt, &v, &sub, &ok)) {
            switch (f) {
                case 1: kind_ = (uint32_t)v; break;
                case 2: status_ = (int)(int64_t)v; break;
                case 3: window_id_ = v; break;
                case 4: length_ = v; break;
                case 5: mode_ = (uint32_t)v; break;
                case 6: pool_id_ = v; break;
                case 7: offset_ = v; break;
                case 8: pool_epoch_ = v; break;
                case 9: set_lease_ms((int64_t)v); break;
                default: break;
            }
        }
        return ok;
    }
private:
    uint64_t window_id_ = 0, length_ = 0, pool_id_ = 0, offset_ = 0;
    uint64_t pool_epoch_ = 0;
    int64_t lease_ms_ = 0;
    uint32_t kind_ = 0, mode_ = 0;
    int status_ = 0;
    bool has_lease_ms_ = false;
};

class VerbPost : public google::protobuf::Message {
public:
    uint32_t op() const { return op_; }
    void set_op(uint32_t v) { op_ = v; }
    uint64_t wr_id() const { return wr_id_; }
    void set_wr_id(uint64_t v) { wr_id_ = v; }
    uint64_t window_id() const { return window_id_; }
    void set_window_id(uint64_t v) { window_id_ = v; }
    uint64_t offset() const { return offset_; }
    void set_offset(uint64_t v) { offset_ = v; }
    uint64_t length() const { return length_; }
    void set_length(uint64_t v) { length_ = v; }
    uint64_t pool_epoch() const { return pool_epoch_; }
    void set_pool_epoch(uint64_t v) { pool_epoch_ = v; }
    uint32_t crc32c() const { return crc32c_; }
    void set_crc32c(uint32_t v) { crc32c_ = v; }
    google::protobuf::Message* New() const override {
        return new VerbPost;
    }
    void Clear() override { *this = VerbPost(); }
    bool SerializeToString(std::string* out) const override {
        out->clear();
        auto field = [&](uint32_t num, uint64_t v) {
            if (v != 0) pbstub::wire::put_u(out, num, v);
        };
        field(1, op_);
        field(2, wr_id_);
        field(3, window_id_);
        field(4, offset_);
        field(5, length_);
        field(6, pool_epoch_);
        field(7, crc32c_);
        return true;
    }
    bool ParseFromString(const std::string& s) override {
        pbstub::wire::Reader r(s);
        uint32_t f = 0, wt = 0;
        uint64_t v = 0;
        std::string sub;
        bool ok = true;
        while (r.next(&f, &wt, &v, &sub, &ok)) {
            switch (f) {
                case 1: op_ = (uint32_t)v; break;
                case 2: wr_id_ = v; break;
                case 3: window_id_ = v; break;
                case 4: offset_ = v; break;
                case 5: length_ = v; break;
                case 6: pool_epoch_ = v; break;
                case 7: crc32c_ = (uint32_t)v; break;
                default: break;
            }
        }
        return ok;
    }
private:
    uint64_t wr_id_ = 0, window_id_ = 0, offset_ = 0, length_ = 0;
    uint64_t pool_epoch_ = 0;
    uint32_t op_ = 0, crc32c_ = 0;
};

class VerbCompletion : public google::protobuf::Message {
public:
    uint64_t wr_id() const { return wr_id_; }
    void set_wr_id(uint64_t v) { wr_id_ = v; }
    int status() const { return status_; }
    void set_status(int v) { status_ = v; }
    uint64_t bytes() const { return bytes_; }
    void set_bytes(uint64_t v) { bytes_ = v; }
    uint32_t crc32c() const { return crc32c_; }
    void set_crc32c(uint32_t v) { crc32c_ = v; }
    google::protobuf::Message* New() const override {
        return new VerbCompletion;
    }
    void Clear() override { *this = VerbCompletion(); }
    bool SerializeToString(std::string* out) const override {
        out->clear();
        if (wr_id_ != 0) pbstub::wire::put_u(out, 1, wr_id_);
        if (status_ != 0) {
            pbstub::wire::put_u(out, 2, (uint64_t)(int64_t)status_);
        }
        if (bytes_ != 0) pbstub::wire::put_u(out, 3, bytes_);
        if (crc32c_ != 0) pbstub::wire::put_u(out, 4, crc32c_);
        return true;
    }
    bool ParseFromString(const std::string& s) override {
        pbstub::wire::Reader r(s);
        uint32_t f = 0, wt = 0;
        uint64_t v = 0;
        std::string sub;
        bool ok = true;
        while (r.next(&f, &wt, &v, &sub, &ok)) {
            if (f == 1) wr_id_ = v;
            if (f == 2) status_ = (int)(int64_t)v;
            if (f == 3) bytes_ = v;
            if (f == 4) crc32c_ = (uint32_t)v;
        }
        return ok;
    }
private:
    uint64_t wr_id_ = 0, bytes_ = 0;
    uint32_t crc32c_ = 0;
    int status_ = 0;
};

class RpcMeta : public google::protobuf::Message {
public:
    bool has_request() const { return has_request_; }
    const RpcRequestMeta& request() const { return request_; }
    RpcRequestMeta* mutable_request() {
        has_request_ = true;
        return &request_;
    }
    bool has_response() const { return has_response_; }
    const RpcResponseMeta& response() const { return response_; }
    RpcResponseMeta* mutable_response() {
        has_response_ = true;
        return &response_;
    }
    uint64_t correlation_id() const { return correlation_id_; }
    void set_correlation_id(uint64_t v) { correlation_id_ = v; }
    int compress_type() const { return compress_type_; }
    void set_compress_type(int v) { compress_type_ = v; }
    uint32_t attachment_size() const { return attachment_size_; }
    void set_attachment_size(uint32_t v) { attachment_size_ = v; }
    bool has_stream_settings() const { return has_stream_settings_; }
    const StreamSettings& stream_settings() const {
        return stream_settings_;
    }
    StreamSettings* mutable_stream_settings() {
        has_stream_settings_ = true;
        return &stream_settings_;
    }
    bool has_body_checksum() const { return has_body_checksum_; }
    uint32_t body_checksum() const { return body_checksum_; }
    void set_body_checksum(uint32_t v) {
        body_checksum_ = v;
        has_body_checksum_ = true;
    }
    bool has_auth_data() const { return !auth_data_.empty(); }
    const std::string& auth_data() const { return auth_data_; }
    void set_auth_data(const std::string& v) { auth_data_ = v; }
    bool cancel() const { return cancel_; }
    void set_cancel(bool v) { cancel_ = v; }
    bool goaway() const { return goaway_; }
    void set_goaway(bool v) { goaway_ = v; }
    bool desc_ack() const { return desc_ack_; }
    void set_desc_ack(bool v) { desc_ack_ = v; }
    bool has_desc_ack_token() const { return desc_ack_token_ != 0; }
    uint64_t desc_ack_token() const { return desc_ack_token_; }
    void set_desc_ack_token(uint64_t v) { desc_ack_token_ = v; }
    bool has_pool_attachment() const { return has_pool_attachment_; }
    const PoolDescriptor& pool_attachment() const {
        return pool_attachment_;
    }
    PoolDescriptor* mutable_pool_attachment() {
        has_pool_attachment_ = true;
        return &pool_attachment_;
    }
    bool has_stream_frame() const { return has_stream_frame_; }
    const StreamFrame& stream_frame() const { return stream_frame_; }
    StreamFrame* mutable_stream_frame() {
        has_stream_frame_ = true;
        return &stream_frame_;
    }
    bool has_window_grant() const { return has_window_grant_; }
    const WindowGrant& window_grant() const { return window_grant_; }
    WindowGrant* mutable_window_grant() {
        has_window_grant_ = true;
        return &window_grant_;
    }
    bool has_verb_post() const { return has_verb_post_; }
    const VerbPost& verb_post() const { return verb_post_; }
    VerbPost* mutable_verb_post() {
        has_verb_post_ = true;
        return &verb_post_;
    }
    bool has_verb_completion() const { return has_verb_completion_; }
    const VerbCompletion& verb_completion() const {
        return verb_completion_;
    }
    VerbCompletion* mutable_verb_completion() {
        has_verb_completion_ = true;
        return &verb_completion_;
    }

    // Full real proto2 wire format (pbstub_wire.h helpers).
    void Clear() override { *this = RpcMeta(); }
    bool SerializeToString(std::string* out) const override {
        out->clear();
        if (has_request_) pbstub::wire::put_msg(out, 1, request_);
        if (has_response_) pbstub::wire::put_msg(out, 2, response_);
        if (correlation_id_ != 0) {
            pbstub::wire::put_u(out, 3, correlation_id_);
        }
        if (compress_type_ != 0) {
            pbstub::wire::put_u(out, 4, (uint64_t)compress_type_);
        }
        if (attachment_size_ != 0) {
            pbstub::wire::put_u(out, 5, attachment_size_);
        }
        if (has_stream_settings_) {
            pbstub::wire::put_msg(out, 6, stream_settings_);
        }
        if (has_body_checksum_) {
            pbstub::wire::put_u(out, 7, body_checksum_);
        }
        if (!auth_data_.empty()) pbstub::wire::put_str(out, 8, auth_data_);
        if (cancel_) pbstub::wire::put_u(out, 9, 1);
        if (goaway_) pbstub::wire::put_u(out, 10, 1);
        if (has_pool_attachment_) {
            pbstub::wire::put_msg(out, 11, pool_attachment_);
        }
        if (desc_ack_) pbstub::wire::put_u(out, 12, 1);
        if (desc_ack_token_ != 0) {
            pbstub::wire::put_u(out, 13, desc_ack_token_);
        }
        if (has_stream_frame_) {
            pbstub::wire::put_msg(out, 14, stream_frame_);
        }
        if (has_window_grant_) {
            pbstub::wire::put_msg(out, 15, window_grant_);
        }
        if (has_verb_post_) pbstub::wire::put_msg(out, 16, verb_post_);
        if (has_verb_completion_) {
            pbstub::wire::put_msg(out, 17, verb_completion_);
        }
        return true;
    }
    bool ParseFromString(const std::string& s) override {
        pbstub::wire::Reader r(s);
        uint32_t f = 0, wt = 0;
        uint64_t v = 0;
        std::string sub;
        bool ok = true;
        while (r.next(&f, &wt, &v, &sub, &ok)) {
            switch (f) {
                case 1:
                    if (!mutable_request()->ParseFromString(sub)) {
                        return false;
                    }
                    break;
                case 2:
                    if (!mutable_response()->ParseFromString(sub)) {
                        return false;
                    }
                    break;
                case 3: correlation_id_ = v; break;
                case 4: compress_type_ = (int)v; break;
                case 5: attachment_size_ = (uint32_t)v; break;
                case 6:
                    if (!mutable_stream_settings()->ParseFromString(sub)) {
                        return false;
                    }
                    break;
                case 7:
                    body_checksum_ = (uint32_t)v;
                    has_body_checksum_ = true;
                    break;
                case 8: auth_data_ = sub; break;
                case 9: cancel_ = v != 0; break;
                case 10: goaway_ = v != 0; break;
                case 11:
                    if (!mutable_pool_attachment()->ParseFromString(sub)) {
                        return false;
                    }
                    break;
                case 12: desc_ack_ = v != 0; break;
                case 13: desc_ack_token_ = v; break;
                case 14:
                    if (!mutable_stream_frame()->ParseFromString(sub)) {
                        return false;
                    }
                    break;
                case 15:
                    if (!mutable_window_grant()->ParseFromString(sub)) {
                        return false;
                    }
                    break;
                case 16:
                    if (!mutable_verb_post()->ParseFromString(sub)) {
                        return false;
                    }
                    break;
                case 17:
                    if (!mutable_verb_completion()->ParseFromString(sub)) {
                        return false;
                    }
                    break;
                default: break;
            }
        }
        return ok;
    }
private:
    RpcRequestMeta request_;
    RpcResponseMeta response_;
    StreamSettings stream_settings_;
    PoolDescriptor pool_attachment_;
    StreamFrame stream_frame_;
    WindowGrant window_grant_;
    VerbPost verb_post_;
    VerbCompletion verb_completion_;
    std::string auth_data_;
    uint64_t correlation_id_ = 0, desc_ack_token_ = 0;
    uint32_t attachment_size_ = 0, body_checksum_ = 0;
    int compress_type_ = 0;
    bool has_request_ = false, has_response_ = false;
    bool has_stream_settings_ = false, has_body_checksum_ = false;
    bool cancel_ = false, goaway_ = false, desc_ack_ = false;
    bool has_pool_attachment_ = false, has_stream_frame_ = false;
    bool has_window_grant_ = false, has_verb_post_ = false;
    bool has_verb_completion_ = false;
};

}  // namespace rpc
}  // namespace tpurpc
PBEOF

# Shared scaffolding for the two generated echo services.
cat > "$DEST/gen/pbstub_service.h" << 'PBEOF'
#pragma once
#include <google/protobuf/service.h>
namespace pbstub {
// One-method echo service scaffold: descriptor + stub plumbing shared
// by the test/bench generated-code stand-ins.
template <typename Req, typename Res, typename Tag>
class EchoServiceT : public google::protobuf::Service {
public:
    static const google::protobuf::ServiceDescriptor* descriptor() {
        static google::protobuf::ServiceDescriptor* sd = [] {
            auto* d =
                new google::protobuf::ServiceDescriptor(Tag::full_name());
            d->add_method("Echo");
            return d;
        }();
        return sd;
    }
    const google::protobuf::ServiceDescriptor* GetDescriptor() override {
        return descriptor();
    }
    virtual void Echo(google::protobuf::RpcController* controller,
                      const Req* request, Res* response,
                      google::protobuf::Closure* done) = 0;
    void CallMethod(const google::protobuf::MethodDescriptor*,
                    google::protobuf::RpcController* controller,
                    const google::protobuf::Message* request,
                    google::protobuf::Message* response,
                    google::protobuf::Closure* done) override {
        Echo(controller, (const Req*)request, (Res*)response, done);
    }
    const google::protobuf::Message& GetRequestPrototype(
        const google::protobuf::MethodDescriptor*) const override {
        static Req req;
        return req;
    }
    const google::protobuf::Message& GetResponsePrototype(
        const google::protobuf::MethodDescriptor*) const override {
        static Res res;
        return res;
    }
};
template <typename Req, typename Res, typename Tag>
class EchoStubT {
public:
    explicit EchoStubT(google::protobuf::RpcChannel* channel)
        : channel_(channel) {}
    void Echo(google::protobuf::RpcController* controller, const Req* req,
              Res* res, google::protobuf::Closure* done) {
        channel_->CallMethod(
            EchoServiceT<Req, Res, Tag>::descriptor()->method(0),
            controller, req, res, done);
    }
private:
    google::protobuf::RpcChannel* channel_;
};
}  // namespace pbstub
PBEOF

cat > "$DEST/gen/echo.pb.h" << 'PBEOF'
// STUB of protoc output for cpp/tests/proto/echo.proto. Real proto2
// wire format (pbstub_wire.h), so runtime-stub test servers echo real
// content.
#pragma once
#include "pbstub_service.h"
#include "pbstub_wire.h"
#include <string>
namespace test {
class EchoRequest : public google::protobuf::Message {
public:
    const std::string& message() const { return message_; }
    void set_message(const std::string& v) { message_ = v; }
    std::string* mutable_message() { return &message_; }
    int sleep_us() const { return sleep_us_; }
    void set_sleep_us(int v) { sleep_us_ = v; }
    int fail_with() const { return fail_with_; }
    void set_fail_with(int v) { fail_with_ = v; }
    google::protobuf::Message* New() const override {
        return new EchoRequest;
    }
    void Clear() override { *this = EchoRequest(); }
    bool SerializeToString(std::string* out) const override {
        out->clear();
        pbstub::wire::put_str(out, 1, message_);
        if (sleep_us_ != 0) {
            pbstub::wire::put_u(out, 2, (uint64_t)(int64_t)sleep_us_);
        }
        if (fail_with_ != 0) {
            pbstub::wire::put_u(out, 3, (uint64_t)(int64_t)fail_with_);
        }
        return true;
    }
    bool ParseFromString(const std::string& s) override {
        pbstub::wire::Reader r(s);
        uint32_t f = 0, wt = 0;
        uint64_t v = 0;
        std::string sub;
        bool ok = true;
        while (r.next(&f, &wt, &v, &sub, &ok)) {
            if (f == 1) message_ = sub;
            if (f == 2) sleep_us_ = (int)(int64_t)v;
            if (f == 3) fail_with_ = (int)(int64_t)v;
        }
        return ok;
    }
private:
    std::string message_;
    int sleep_us_ = 0;
    int fail_with_ = 0;
};
class EchoResponse : public google::protobuf::Message {
public:
    const std::string& message() const { return message_; }
    void set_message(const std::string& v) { message_ = v; }
    std::string* mutable_message() { return &message_; }
    google::protobuf::Message* New() const override {
        return new EchoResponse;
    }
    void Clear() override { *this = EchoResponse(); }
    bool SerializeToString(std::string* out) const override {
        out->clear();
        pbstub::wire::put_str(out, 1, message_);
        return true;
    }
    bool ParseFromString(const std::string& s) override {
        pbstub::wire::Reader r(s);
        uint32_t f = 0, wt = 0;
        uint64_t v = 0;
        std::string sub;
        bool ok = true;
        while (r.next(&f, &wt, &v, &sub, &ok)) {
            if (f == 1) message_ = sub;
        }
        return ok;
    }
private:
    std::string message_;
};
struct EchoTag {
    static const char* full_name() { return "test.EchoService"; }
};
using EchoService = pbstub::EchoServiceT<EchoRequest, EchoResponse,
                                         EchoTag>;
using EchoService_Stub = pbstub::EchoStubT<EchoRequest, EchoResponse,
                                           EchoTag>;
// test.UnusedService: one "Nothing" method nobody registers — the
// no-such-method test calls it against a server that only serves Echo.
struct UnusedTag {
    static const char* full_name() { return "test.UnusedService"; }
};
class UnusedService_Stub {
public:
    explicit UnusedService_Stub(google::protobuf::RpcChannel* channel)
        : channel_(channel) {}
    void Nothing(google::protobuf::RpcController* controller,
                 const EchoRequest* req, EchoResponse* res,
                 google::protobuf::Closure* done) {
        static google::protobuf::ServiceDescriptor* sd = [] {
            auto* d = new google::protobuf::ServiceDescriptor(
                UnusedTag::full_name());
            d->add_method("Nothing");
            return d;
        }();
        channel_->CallMethod(sd->method(0), controller, req, res, done);
    }
private:
    google::protobuf::RpcChannel* channel_;
};
}  // namespace test
PBEOF

cat > "$DEST/gen/bench_echo.pb.h" << 'PBEOF'
// STUB of protoc output for tools/proto/bench_echo.proto. Real proto2
// wire format (pbstub_wire.h).
#pragma once
#include "pbstub_service.h"
#include "pbstub_wire.h"
#include <string>
#include <vector>
namespace benchpb {
class EchoRequest : public google::protobuf::Message {
public:
    int64_t send_ts_us() const { return send_ts_us_; }
    void set_send_ts_us(int64_t v) { send_ts_us_ = v; }
    bool has_payload() const { return !payload_.empty(); }
    const std::string& payload() const { return payload_; }
    void set_payload(const std::string& v) { payload_ = v; }
    bool stale() const { return stale_; }
    void set_stale(bool v) { stale_ = v; }
    int chain_size() const { return (int)chain_.size(); }
    const std::string& chain(int i) const { return chain_[i]; }
    void add_chain(const std::string& v) { chain_.push_back(v); }
    google::protobuf::Message* New() const override {
        return new EchoRequest;
    }
    void Clear() override { *this = EchoRequest(); }
    bool SerializeToString(std::string* out) const override {
        out->clear();
        if (send_ts_us_ != 0) {
            pbstub::wire::put_u(out, 1, (uint64_t)send_ts_us_);
        }
        if (!payload_.empty()) pbstub::wire::put_str(out, 2, payload_);
        if (stale_) pbstub::wire::put_u(out, 3, 1);
        for (const std::string& c : chain_) {
            pbstub::wire::put_str(out, 4, c);
        }
        return true;
    }
    bool ParseFromString(const std::string& s) override {
        pbstub::wire::Reader r(s);
        uint32_t f = 0, wt = 0;
        uint64_t v = 0;
        std::string sub;
        bool ok = true;
        chain_.clear();
        while (r.next(&f, &wt, &v, &sub, &ok)) {
            if (f == 1) send_ts_us_ = (int64_t)v;
            if (f == 2) payload_ = sub;
            if (f == 3) stale_ = v != 0;
            if (f == 4) chain_.push_back(sub);
        }
        return ok;
    }
private:
    int64_t send_ts_us_ = 0;
    std::string payload_;
    bool stale_ = false;
    std::vector<std::string> chain_;
};
class EchoResponse : public google::protobuf::Message {
public:
    int64_t send_ts_us() const { return send_ts_us_; }
    void set_send_ts_us(int64_t v) { send_ts_us_ = v; }
    const std::string& payload() const { return payload_; }
    void set_payload(const std::string& v) { payload_ = v; }
    google::protobuf::Message* New() const override {
        return new EchoResponse;
    }
    void Clear() override { *this = EchoResponse(); }
    bool SerializeToString(std::string* out) const override {
        out->clear();
        if (send_ts_us_ != 0) {
            pbstub::wire::put_u(out, 1, (uint64_t)send_ts_us_);
        }
        if (!payload_.empty()) pbstub::wire::put_str(out, 2, payload_);
        return true;
    }
    bool ParseFromString(const std::string& s) override {
        pbstub::wire::Reader r(s);
        uint32_t f = 0, wt = 0;
        uint64_t v = 0;
        std::string sub;
        bool ok = true;
        while (r.next(&f, &wt, &v, &sub, &ok)) {
            if (f == 1) send_ts_us_ = (int64_t)v;
            if (f == 2) payload_ = sub;
        }
        return ok;
    }
private:
    int64_t send_ts_us_ = 0;
    std::string payload_;
};
struct EchoTag {
    static const char* full_name() { return "benchpb.EchoService"; }
};
using EchoService = pbstub::EchoServiceT<EchoRequest, EchoResponse,
                                         EchoTag>;
using EchoService_Stub = pbstub::EchoStubT<EchoRequest, EchoResponse,
                                           EchoTag>;

// Collective chunk messages (ISSUE 13). REAL proto2 varint wire format
// for every field (all are varints), so runtime-stub builds move
// correct collective metadata over real sockets — the standalone
// multi-rank collective drive depends on it.
class CollChunk : public google::protobuf::Message {
public:
    uint64_t coll_seq() const { return coll_seq_; }
    void set_coll_seq(uint64_t v) { coll_seq_ = v; }
    uint32_t kind() const { return kind_; }
    void set_kind(uint32_t v) { kind_ = v; }
    uint32_t step() const { return step_; }
    void set_step(uint32_t v) { step_ = v; }
    uint32_t chunk() const { return chunk_; }
    void set_chunk(uint32_t v) { chunk_ = v; }
    uint32_t src_rank() const { return src_rank_; }
    void set_src_rank(uint32_t v) { src_rank_ = v; }
    uint32_t nranks() const { return nranks_; }
    void set_nranks(uint32_t v) { nranks_ = v; }
    uint64_t member_hash() const { return member_hash_; }
    void set_member_hash(uint64_t v) { member_hash_ = v; }
    uint64_t total_bytes() const { return total_bytes_; }
    void set_total_bytes(uint64_t v) { total_bytes_ = v; }
    uint64_t offset() const { return offset_; }
    void set_offset(uint64_t v) { offset_ = v; }
    uint64_t len() const { return len_; }
    void set_len(uint64_t v) { len_ = v; }
    uint32_t scope() const { return scope_; }
    void set_scope(uint32_t v) { scope_ = v; }
    uint64_t verb_window() const { return verb_window_; }
    void set_verb_window(uint64_t v) { verb_window_ = v; }
    uint32_t verb_nchunks() const { return verb_nchunks_; }
    void set_verb_nchunks(uint32_t v) { verb_nchunks_ = v; }
    uint32_t verb_crc() const { return verb_crc_; }
    void set_verb_crc(uint32_t v) { verb_crc_ = v; }
    uint64_t verb_epoch() const { return verb_epoch_; }
    void set_verb_epoch(uint64_t v) { verb_epoch_ = v; }
    google::protobuf::Message* New() const override {
        return new CollChunk;
    }
    void Clear() override { *this = CollChunk(); }
    bool SerializeToString(std::string* out) const override {
        out->clear();
        auto field = [&](uint32_t num, uint64_t v) {
            if (v != 0) pbstub::wire::put_u(out, num, v);
        };
        field(1, coll_seq_);
        field(2, kind_);
        field(3, step_);
        field(4, chunk_);
        field(5, src_rank_);
        field(6, nranks_);
        field(7, member_hash_);
        field(8, total_bytes_);
        field(9, offset_);
        field(10, len_);
        field(11, scope_);
        field(12, verb_window_);
        field(13, verb_nchunks_);
        field(14, verb_crc_);
        field(15, verb_epoch_);
        return true;
    }
    bool ParseFromString(const std::string& s) override {
        pbstub::wire::Reader r(s);
        uint32_t f = 0, wt = 0;
        uint64_t v = 0;
        std::string sub;
        bool ok = true;
        while (r.next(&f, &wt, &v, &sub, &ok)) {
            switch (f) {
                case 1: coll_seq_ = v; break;
                case 2: kind_ = (uint32_t)v; break;
                case 3: step_ = (uint32_t)v; break;
                case 4: chunk_ = (uint32_t)v; break;
                case 5: src_rank_ = (uint32_t)v; break;
                case 6: nranks_ = (uint32_t)v; break;
                case 7: member_hash_ = v; break;
                case 8: total_bytes_ = v; break;
                case 9: offset_ = v; break;
                case 10: len_ = v; break;
                case 11: scope_ = (uint32_t)v; break;
                case 12: verb_window_ = v; break;
                case 13: verb_nchunks_ = (uint32_t)v; break;
                case 14: verb_crc_ = (uint32_t)v; break;
                case 15: verb_epoch_ = v; break;
                default: break;
            }
        }
        return ok;
    }
private:
    uint64_t coll_seq_ = 0, member_hash_ = 0, total_bytes_ = 0;
    uint64_t offset_ = 0, len_ = 0, verb_window_ = 0, verb_epoch_ = 0;
    uint32_t kind_ = 0, step_ = 0, chunk_ = 0, src_rank_ = 0, nranks_ = 0;
    uint32_t scope_ = 0, verb_nchunks_ = 0, verb_crc_ = 0;
};
class CollAck : public google::protobuf::Message {
public:
    uint32_t applied() const { return applied_; }
    void set_applied(uint32_t v) { applied_ = v; }
    google::protobuf::Message* New() const override { return new CollAck; }
    void Clear() override { *this = CollAck(); }
    bool SerializeToString(std::string* out) const override {
        out->clear();
        if (applied_ != 0) pbstub::wire::put_u(out, 1, applied_);
        return true;
    }
    bool ParseFromString(const std::string& s) override {
        pbstub::wire::Reader r(s);
        uint32_t f = 0, wt = 0;
        uint64_t v = 0;
        std::string sub;
        bool ok = true;
        while (r.next(&f, &wt, &v, &sub, &ok)) {
            if (f == 1) applied_ = (uint32_t)v;
        }
        return ok;
    }
private:
    uint32_t applied_ = 0;
};
// benchpb.CollectiveService: one "Exchange" method (mirrors the protoc
// generated_service shape the way EchoServiceT does).
class CollectiveService : public google::protobuf::Service {
public:
    static const google::protobuf::ServiceDescriptor* descriptor() {
        static google::protobuf::ServiceDescriptor* sd = [] {
            auto* d = new google::protobuf::ServiceDescriptor(
                "benchpb.CollectiveService");
            d->add_method("Exchange");
            return d;
        }();
        return sd;
    }
    const google::protobuf::ServiceDescriptor* GetDescriptor() override {
        return descriptor();
    }
    virtual void Exchange(google::protobuf::RpcController* controller,
                          const CollChunk* request, CollAck* response,
                          google::protobuf::Closure* done) = 0;
    void CallMethod(const google::protobuf::MethodDescriptor*,
                    google::protobuf::RpcController* controller,
                    const google::protobuf::Message* request,
                    google::protobuf::Message* response,
                    google::protobuf::Closure* done) override {
        Exchange(controller, (const CollChunk*)request, (CollAck*)response,
                 done);
    }
    const google::protobuf::Message& GetRequestPrototype(
        const google::protobuf::MethodDescriptor*) const override {
        static CollChunk req;
        return req;
    }
    const google::protobuf::Message& GetResponsePrototype(
        const google::protobuf::MethodDescriptor*) const override {
        static CollAck res;
        return res;
    }
};
class CollectiveService_Stub {
public:
    explicit CollectiveService_Stub(google::protobuf::RpcChannel* channel)
        : channel_(channel) {}
    void Exchange(google::protobuf::RpcController* controller,
                  const CollChunk* req, CollAck* res,
                  google::protobuf::Closure* done) {
        channel_->CallMethod(CollectiveService::descriptor()->method(0),
                             controller, req, res, done);
    }
private:
    google::protobuf::RpcChannel* channel_;
};
}  // namespace benchpb
PBEOF

echo "pbstub written to $DEST"
