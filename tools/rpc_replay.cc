// rpc_replay: re-send traffic captured by -rpc_dump against a live
// server (reference tools/rpc_replay, replaying rpc_dump recordio files).
//
//   rpc_replay --file=requests.1234.dump --server=127.0.0.1:8002
//              [--times=1]
//
// Correlation ids are rewritten per send; responses are awaited on the
// same connection; prints a one-line summary.
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>

#include "tbase/endpoint.h"
#include "tbase/time.h"
#include "trpc/rpc_dump.h"

using namespace tpurpc;

int main(int argc, char** argv) {
    std::string file, server_str;
    int times = 1;
    for (int i = 1; i < argc; ++i) {
        if (strncmp(argv[i], "--file=", 7) == 0) file = argv[i] + 7;
        if (strncmp(argv[i], "--server=", 9) == 0) server_str = argv[i] + 9;
        if (strncmp(argv[i], "--times=", 8) == 0) times = atoi(argv[i] + 8);
    }
    if (file.empty() || server_str.empty()) {
        fprintf(stderr,
                "usage: rpc_replay --file=<dump> --server=<ip:port> "
                "[--times=N]\n");
        return 1;
    }
    EndPoint server;
    if (hostname2endpoint(server_str.c_str(), &server) != 0) {
        fprintf(stderr, "bad server address: %s\n", server_str.c_str());
        return 1;
    }
    const int64_t t0 = monotonic_time_us();
    const int ok = ReplayDumpFile(file, server, times);
    if (ok < 0) {
        fprintf(stderr, "cannot open %s or connect to %s\n", file.c_str(),
                server_str.c_str());
        return 1;
    }
    const double secs = (double)(monotonic_time_us() - t0) / 1e6;
    printf("replayed %d request(s) in %.3fs (%.0f/s)\n", ok, secs,
           secs > 0 ? ok / secs : 0.0);
    return 0;
}
