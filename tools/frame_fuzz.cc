// Fuzz driver for the tpu_std frame parser and the STRM stream-frame
// parser: deterministic seeded mutation loop, no libFuzzer dependency
// (reference test/fuzzing/ keeps libFuzzer harnesses per parser; clang is
// not in this image, so the same entry points are driven by this loop).
//
//   frame_fuzz [iterations] [seed]
//
// Invariants (crash/abort under ASan counts as failure): a parser must
// consume bytes only on OK, never crash, never hang, and an OK cut must
// shrink the source.
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>

#include "tbase/iobuf.h"
#include "tnet/protocol.h"
#include "trpc/policy_tpu_std.h"
#include "trpc/stream.h"

using namespace tpurpc;

int main(int argc, char** argv) {
    long long iters = argc > 1 ? atoll(argv[1]) : 10000000;
    unsigned long long rng = argc > 2 ? strtoull(argv[2], nullptr, 10)
                                      : 0x243f6a8885a308d3ull;
    auto next = [&rng]() {
        rng ^= rng << 13;
        rng ^= rng >> 7;
        rng ^= rng << 17;
        return rng;
    };

    GlobalInitializeOrDie();
    const Protocol* parsers[2] = {
        GetProtocol(TpuStdProtocolIndex()),
        GetProtocol(stream_internal::StreamProtocolIndex()),
    };
    if (parsers[0] == nullptr || parsers[1] == nullptr) {
        fprintf(stderr, "protocol registry not initialized\n");
        return 1;
    }

    // Seeds: a valid tpu_std frame (pb-ish meta + payload) and valid STRM
    // data/feedback/close frames.
    std::string seeds[4];
    {
        IOBuf frame, meta, payload, att;
        meta.append("\x08\x01\x12\x04test");
        payload.append("hello-payload");
        att.append("attach");
        PackTpuStdFrame(&frame, meta, payload, att);
        seeds[0] = frame.to_string();
    }
    seeds[1] = std::string("STRM") + std::string("\x00\x00\x00\x05", 4) +
               std::string(8, '\x02') + std::string(1, '\x00') + "hello";
    seeds[2] = std::string("STRM") + std::string("\x00\x00\x00\x08", 4) +
               std::string(8, '\x03') + std::string(1, '\x01') +
               std::string(8, '\x10');
    seeds[3] = std::string("STRM") + std::string("\x00\x00\x00\x00", 4) +
               std::string(8, '\x04') + std::string(1, '\x02');

    long long parsed_ok = 0;
    for (long long iter = 0; iter < iters; ++iter) {
        std::string input = seeds[next() % 4];
        const int nmut = 1 + (int)(next() % 6);
        for (int m = 0; m < nmut; ++m) {
            if (input.empty()) input = "T";
            switch (next() % 5) {
                case 0:
                    input[next() % input.size()] = (char)next();
                    break;
                case 1:
                    input.resize(next() % (input.size() + 1));
                    break;
                case 2: {
                    const size_t at = next() % input.size();
                    input.insert(at, input.substr(0, next() % 32));
                    break;
                }
                case 3:
                    for (int i = 0; i < 12; ++i) {
                        input.push_back((char)next());
                    }
                    break;
                case 4:  // concatenate two seeds (pipelined frames)
                    input += seeds[next() % 4];
                    break;
            }
        }
        for (const Protocol* p : parsers) {
            IOBuf buf;
            buf.append(input);
            const size_t before = buf.size();
            ParseResult r = p->parse(&buf, nullptr, false, p->parse_arg);
            if (r.error == ParseError::OK) {
                if (buf.size() >= before) {
                    fprintf(stderr, "no progress on OK (iter %lld)\n", iter);
                    return 1;
                }
                ++parsed_ok;
                delete r.msg;
            } else if (buf.size() != before) {
                fprintf(stderr, "consumed bytes on non-OK (iter %lld)\n",
                        iter);
                return 1;
            }
        }
        if ((iter & 0xfffff) == 0xfffff) {
            fprintf(stderr, "... %lld iters, %lld ok-cuts\n", iter + 1,
                    parsed_ok);
        }
    }
    printf("frame_fuzz: %lld iterations, %lld ok-cuts, all invariants "
           "held\n",
           iters, parsed_ok);
    return 0;
}
