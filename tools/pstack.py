#!/usr/bin/env python3
"""pstack.py PID — userspace stack of every thread via ptrace + the
frame-pointer chain (the tree builds with -fno-omit-frame-pointer), and
addr2line against /proc/PID/maps. No gdb required (this image has none);
plays the role of the reference's builtin/threads pstack page."""
import ctypes, os, re, struct, subprocess, sys

libc = ctypes.CDLL("libc.so.6", use_errno=True)
PTRACE_ATTACH, PTRACE_DETACH, PTRACE_GETREGS = 16, 17, 12

class user_regs(ctypes.Structure):
    _fields_ = [(n, ctypes.c_ulonglong) for n in (
        "r15","r14","r13","r12","rbp","rbx","r11","r10","r9","r8","rax",
        "rcx","rdx","rsi","rdi","orig_rax","rip","cs","eflags","rsp","ss",
        "fs_base","gs_base","ds","es","fs","gs")]

def ptrace(req, pid, addr=0, data=0):
    libc.ptrace.restype = ctypes.c_long
    libc.ptrace.argtypes = [ctypes.c_long]*4
    return libc.ptrace(req, pid, addr, data)

def read_word(pid, addr):
    try:
        with open(f"/proc/{pid}/mem", "rb") as f:
            f.seek(addr)
            return struct.unpack("<Q", f.read(8))[0]
    except Exception:
        return None

def load_maps(pid):
    maps = []
    for line in open(f"/proc/{pid}/maps"):
        m = re.match(r"([0-9a-f]+)-([0-9a-f]+) r-x. ([0-9a-f]+) \S+ \d+\s+(\S+)", line)
        if m and m.group(4).startswith("/"):
            maps.append((int(m.group(1),16), int(m.group(2),16), int(m.group(3),16), m.group(4)))
    return maps

def symbolize(maps, pc):
    for lo, hi, off, path in maps:
        if lo <= pc < hi:
            rel = pc - lo + off
            try:
                out = subprocess.run(["addr2line","-Cfe",path,hex(rel)],
                                     capture_output=True,text=True,timeout=10).stdout.split("\n")
                fn = out[0].strip()
                if fn and fn != "??":
                    return f"{fn} [{os.path.basename(path)}]"
            except Exception:
                pass
            return f"{os.path.basename(path)}+{hex(rel)}"
    return hex(pc)

def main(pid):
    maps = load_maps(pid)
    for tid in sorted(int(t) for t in os.listdir(f"/proc/{pid}/task")):
        if ptrace(PTRACE_ATTACH, tid) != 0:
            print(f"tid {tid}: attach failed"); continue
        os.waitpid(tid, 0)
        regs = user_regs()
        ptrace(PTRACE_GETREGS, tid, 0, ctypes.addressof(regs))
        print(f"--- tid {tid}")
        pc, bp, depth = regs.rip, regs.rbp, 0
        while pc and depth < 24:
            print(f"  #{depth} {hex(pc)} {symbolize(maps, pc)}")
            if not bp or bp > 2**63: break
            new_pc = read_word(pid, bp + 8)
            new_bp = read_word(pid, bp)
            if not new_pc or new_bp is None or (new_bp and new_bp <= bp): break
            pc, bp, depth = new_pc, new_bp, depth + 1
        ptrace(PTRACE_DETACH, tid)

if __name__ == "__main__":
    main(int(sys.argv[1]))
