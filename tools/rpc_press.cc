// rpc_press: target-QPS load generator (reference tools/rpc_press — we
// drive the echo fixture service rather than dynamically-loaded protos;
// the token-bucket pacing and latency reporting match the reference's
// rdma_performance client.cpp:50-68).
//
//   rpc_press --server=ip:port [--qps=10000] [--duration_s=10]
//             [--payload=4096 | --body_bytes=4096] [--callers=8]
//             [--press_threads=1] [--pooled] [--pool_desc]
//             [--timeout_ms=5000] [--metrics_csv=path] [--tenant=name]
//             [--priority=0..7]
//             [--tenants=a:8,b:1 | a:8:7,b:1:1 | a:8:7:128,b:1:1:65536]
//             [--via=ip:port] [--sessions=N]
//
// --via=ROUTER_ADDR (ISSUE 16): drive the load THROUGH a tpu_router
// front door instead of a backend directly. At the end the tool scrapes
// the router's /router?format=json and reports the ROUTER-ADDED latency
// — the client-observed p99 minus the router's backend-measured p99 —
// plus the router's hedge count (text + `press_via_p99_us` /
// `press_hedges` in --json). --sessions=N gives the FIRST N callers a
// sticky session id each ("s0".."s<N-1>", stamped on every request) so
// one run exercises the router's pinned path AND — from the remaining
// sessionless callers — its hedged path.
//
// --pool_desc (ISSUE 10 satellite, mirrors echo_bench --pool-desc):
// connect over the shm-ICI link (IciBlockPool + Channel::InitIci) and
// send every payload as a one-sided (pool_id, offset, len, crc, epoch)
// descriptor pinned under a block lease — descriptor traffic at target
// QPS, for pool/lease/epoch soaks and bench rounds. Responses carrying
// TERR_STALE_EPOCH are counted separately (press_stale_epoch): under
// chaos_pool stale injection they are EXPECTED retriable failures, not
// generator errors.
//
// --press_threads=N drives N independent pinned channels (one connection
// each, callers spread round-robin), so the generator scales past a
// single event loop / input fiber — at high connection counts the SERVER
// must be the bottleneck, not this tool (ISSUE 7). The generator config
// rides the --json line (press_threads/press_callers/...) so BENCH
// records say how the load was made.
//
// --timeout_ms sets the per-request deadline (propagated to the server
// as the remaining-budget meta): tiny values drive the server's
// expired-shed and budget-shed paths from the load tool — watch
// rpc_server_expired_requests / rpc_server_shed_requests in its /vars.
//
// Multi-tenant QoS (ISSUE 8): --tenant/--priority stamp every request's
// identity meta; --tenants=name:weight[:priority],... runs a MIXED load
// where the target --qps splits across tenants by weight (callers too)
// — the overload-isolation soak's shape: one flooding low-priority
// tenant plus a steady high-priority one, in one process. Responses
// carrying TERR_OVERLOAD count as `shed` separately from other
// failures. With more than one tenant, --metrics_csv appends one row
// per tenant per interval (tenant column; the aggregate row says
// "all") and --json adds a per-tenant breakdown.
//
// Grey-failure soaks (ISSUE 20): --server=h:p,h:p,... runs the full
// client-side LB stack (round-robin under the outlier-ejection wrapper)
// over a list:// naming set, so the GENERATOR is the process that
// detects and ejects a degraded backend. Each completed call is
// attributed to the backend that served it (cntl.remote_side()): the
// end-of-run report gains a per-backend picks/errors/p99 table (and a
// press_backends object + rpc_outlier_* counters in --json), and
// --backend_csv=<path> appends per-interval per-backend delta rows —
// the pick-share trace an ejection/reinstatement assertion reads.
//
// While running, one stats line per second (interval qps + windowed
// p50/p99/p999); --metrics_csv=<path> appends the same row per interval
// as CSV (elapsed_s,qps,p50_us,p99_us,p999_us,failed_total,tenant) —
// the BENCH trajectory input. Prints qps achieved + latency percentiles
// at the end; --json for one JSON line.
#include <netinet/in.h>
#include <poll.h>
#include <signal.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

#include "bench_echo.pb.h"
#include "tbase/endpoint.h"
#include "tbase/errno.h"
#include "tbase/flags.h"
#include "tbase/flight_recorder.h"
#include "tbase/time.h"
#include "tfiber/fiber.h"
#include "tici/block_pool.h"
#include "tnet/transport.h"
#include "trpc/channel.h"
#include "trpc/controller.h"
#include "trpc/outlier.h"
#include "trpc/stream.h"
#include "tvar/latency_recorder.h"
#include "tvar/variable.h"

using namespace tpurpc;

namespace {

// In-process numeric tvar read (per-zone LB counters for the report).
int64_t VarInt(const char* name) {
    std::string v;
    if (!Variable::describe_exposed(name, &v)) return 0;
    return atoll(v.c_str());
}

// Minimal blocking HTTP/1.1 GET against the router portal (--via): one
// scrape at end-of-run, so a plain blocking socket with a deadline is
// plenty — no reason to drag the RPC stack into reading its own proxy.
bool PortalGet(const EndPoint& ep, const std::string& path,
               std::string* body) {
    const int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return false;
    timeval tv{2, 0};
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    sockaddr_in addr;
    endpoint2sockaddr(ep, &addr);
    if (::connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
        ::close(fd);
        return false;
    }
    const std::string req = "GET " + path +
                            " HTTP/1.1\r\nHost: router\r\n"
                            "Connection: close\r\n\r\n";
    if (::send(fd, req.data(), req.size(), 0) != (ssize_t)req.size()) {
        ::close(fd);
        return false;
    }
    std::string raw;
    char chunk[4096];
    for (;;) {
        const ssize_t n = ::recv(fd, chunk, sizeof(chunk), 0);
        if (n <= 0) break;
        raw.append(chunk, (size_t)n);
    }
    ::close(fd);
    const size_t hdr_end = raw.find("\r\n\r\n");
    if (hdr_end == std::string::npos) return false;
    body->assign(raw, hdr_end + 4, std::string::npos);
    return !body->empty();
}

// Pull `"key": <int>` out of the /router json — the two fields we read
// are flat integers, so a substring scan beats a JSON parser here.
int64_t JsonIntField(const std::string& body, const char* key) {
    const std::string needle = std::string("\"") + key + "\":";
    const size_t pos = body.find(needle);
    if (pos == std::string::npos) return -1;
    return atoll(body.c_str() + pos + needle.size());
}

// One traffic class of the generator: its own pacing bucket and stats,
// so per-tenant isolation is measurable from the CLIENT side too. A
// per-tenant payload override (the 4th --tenants spec field, ISSUE 15)
// makes one generator emit MIXED-COST load: a "heavy" tenant flooding
// big bodies inside its request-count rate while a light tenant
// trickles — the shape that proves work-priced admission.
struct TenantGen {
    std::string name;       // empty = no identity stamped
    int priority = -1;      // <0 = unset
    int weight = 1;
    int payload = -1;       // <0 = the global --body_bytes/--payload
    long long qps = 0;      // this tenant's share of the target
    LatencyRecorder lat;
    IOBuf filler;           // this class's request body
    std::atomic<int64_t> tokens{0};
    std::atomic<int64_t> sent{0};
    std::atomic<int64_t> failed{0};
    std::atomic<int64_t> shed{0};  // TERR_OVERLOAD rejections
    std::atomic<int64_t> stale{0};  // TERR_STALE_EPOCH fences (pool_desc)
    // Largest server-suggested backoff seen on a shed: the soak asserts
    // the hint is real (drain-rate-derived), not just the flag floor.
    std::atomic<int64_t> backoff_ms_max{0};
    int64_t granted = 0;
    int64_t last_sent = 0;  // interval reporting
    // --stream_tokens mode: per-class inference-serving latencies —
    // time-to-first-token from the FIRST open attempt, and the gap
    // between consecutive delivered tokens (resume pauses included:
    // both are what the end user of a token stream actually waits).
    LatencyRecorder ttft;
    LatencyRecorder itl;
    std::atomic<int64_t> stream_tokens_rx{0};
    std::atomic<int64_t> stream_resumes{0};
    std::atomic<int64_t> stream_seq_errors{0};
    std::atomic<int64_t> stream_dups{0};
};

// Per-backend client-side stats (ISSUE 20): when --server is a comma
// list the channel runs the full LB stack — outlier tier included — in
// THIS process, and every completed call says which backend served it
// (cntl.remote_side()). The table is how a grey-failure soak watches
// traffic steer off an ejected node and return after reinstatement,
// without trusting the grey node's own telemetry.
struct BackendStat {
    std::atomic<int64_t> picks{0};
    std::atomic<int64_t> errors{0};
    LatencyRecorder lat;
    int64_t last_picks = 0;  // interval deltas (--backend_csv)
    int64_t last_errors = 0;
};
std::mutex g_backend_mu;
std::map<std::string, std::unique_ptr<BackendStat>> g_backends;
std::atomic<bool> g_track_backends{false};

void RecordBackend(const Controller& cntl, int64_t latency_us) {
    if (!g_track_backends.load(std::memory_order_relaxed)) return;
    const EndPoint ep = cntl.remote_side();
    if (ep.port == 0) return;  // failed before any backend was picked
    BackendStat* bs = nullptr;
    {
        std::lock_guard<std::mutex> lock(g_backend_mu);
        auto& slot = g_backends[endpoint2str(ep)];
        if (slot == nullptr) slot.reset(new BackendStat);
        bs = slot.get();
    }
    bs->picks.fetch_add(1, std::memory_order_relaxed);
    if (cntl.Failed()) {
        bs->errors.fetch_add(1, std::memory_order_relaxed);
    } else if (latency_us > 0) {
        bs->lat << latency_us;
    }
}

struct PressCtx {
    benchpb::EchoService_Stub* stub;
    TenantGen* gen;
    std::atomic<bool>* stop;
    int64_t timeout_ms;
    bool pool_desc = false;
    std::string session;  // --sessions: sticky id stamped on every call
    long long stream_tokens = 0;   // --stream_tokens: tokens per stream
    int stream_read_delay_ms = 0;  // --stream_read_delay_ms: slow consumer
};

// Ctrl-C / SIGINT: finish the current interval cleanly — flush the final
// p50/p99/p999 line and --metrics_csv row, join the callers, print the
// summary — instead of dying mid-write with a torn CSV.
volatile sig_atomic_t g_sigint = 0;
void OnSigint(int) { g_sigint = 1; }

// One streamed inference "call" (--stream_tokens, ISSUE 17): open a
// server-push stream, consume the token stream asserting contiguous
// seqs AND deterministic content ("tok:<key>:<seq>"), and drive the
// resume funnel through the SAME StreamCall on EOF/timeout/backend
// death — the generator is the exactly-once prover. Returns true when
// the full stream (all N tokens + EOS) was delivered.
bool StreamOnce(PressCtx* c, TenantGen* g) {
    push_stream::StreamCall call;
    char key[32];
    snprintf(key, sizeof(key), "k%llx",
             (unsigned long long)call.stream_id());
    char payload[96];
    snprintf(payload, sizeof(payload), "stream:%lld:%s",
             c->stream_tokens, key);
    const int64_t t_open = monotonic_time_us();
    uint64_t expect = 0;  // last contiguous seq we verified
    int opens = 0;
    bool ttft_done = false;
    int64_t last_tok_us = 0;
    bool complete = false;
    while (!complete && !c->stop->load(std::memory_order_relaxed)) {
        Controller cntl;
        cntl.set_timeout_ms(c->timeout_ms);
        if (!g->name.empty()) cntl.set_tenant(g->name);
        if (g->priority >= 0) cntl.set_priority(g->priority);
        if (!c->session.empty()) cntl.set_session(c->session);
        call.PrepareOpen(&cntl);
        benchpb::EchoRequest req;
        benchpb::EchoResponse res;
        req.set_send_ts_us(monotonic_time_us());
        req.set_payload(payload);
        c->stub->Echo(&cntl, &req, &res, nullptr);
        if (++opens > 1) {
            g->stream_resumes.fetch_add(1, std::memory_order_relaxed);
        }
        if (cntl.Failed()) {
            // Any open failure is retriable through the funnel: the
            // router/backend that refused may be mid-restart. Bounded
            // so a misconfigured target still terminates.
            if (opens < 25) {
                fiber_usleep(100 * 1000);
                continue;
            }
            break;
        }
        bool reopen = false;
        while (!c->stop->load(std::memory_order_relaxed)) {
            std::string chunk;
            uint64_t seq = 0;
            const int rc = call.Read(
                &chunk, &seq,
                (int)std::max<int64_t>(1, c->timeout_ms));
            if (rc == 0) {
                const int64_t now = monotonic_time_us();
                if (!ttft_done) {
                    g->ttft << now - t_open;
                    ttft_done = true;
                } else {
                    g->itl << now - last_tok_us;
                }
                last_tok_us = now;
                char want[64];
                snprintf(want, sizeof(want), "tok:%s:%llu", key,
                         (unsigned long long)seq);
                if (seq != expect + 1 || chunk != want) {
                    g->stream_seq_errors.fetch_add(
                        1, std::memory_order_relaxed);
                }
                expect = seq;
                g->stream_tokens_rx.fetch_add(1,
                                              std::memory_order_relaxed);
                if (c->stream_read_delay_ms > 0) {
                    // Slow consumer: stops granting credits while
                    // sleeping — the server-side writer must park.
                    fiber_usleep((int64_t)c->stream_read_delay_ms * 1000);
                }
            } else if (rc == 1) {
                complete = expect == (uint64_t)c->stream_tokens;
                break;
            } else if (rc == TERR_EOF || rc == TERR_RPC_TIMEDOUT ||
                       rc == TERR_FAILED_SOCKET) {
                reopen = opens < 25;
                break;
            } else {
                break;  // non-retriable abort
            }
        }
        if (!reopen) break;
    }
    g->stream_dups.fetch_add((int64_t)call.duplicates(),
                             std::memory_order_relaxed);
    if (complete) g->lat << (monotonic_time_us() - t_open);
    return complete;
}

void* PressCaller(void* arg) {
    auto* c = (PressCtx*)arg;
    TenantGen* g = c->gen;
    while (!c->stop->load(std::memory_order_relaxed)) {
        // Token bucket: each call consumes one token (reference
        // rdma_performance client.cpp:68).
        if (g->tokens.fetch_sub(1, std::memory_order_relaxed) <= 0) {
            g->tokens.fetch_add(1, std::memory_order_relaxed);
            fiber_usleep(200);
            continue;
        }
        if (c->stream_tokens > 0) {
            // One paced "call" = one full token stream. A stream cut
            // short by shutdown is neither success nor failure.
            if (StreamOnce(c, g)) {
                g->sent.fetch_add(1, std::memory_order_relaxed);
            } else if (!c->stop->load(std::memory_order_relaxed)) {
                g->failed.fetch_add(1, std::memory_order_relaxed);
            }
            continue;
        }
        Controller cntl;
        cntl.set_timeout_ms(c->timeout_ms);
        if (!g->name.empty()) cntl.set_tenant(g->name);
        if (g->priority >= 0) cntl.set_priority(g->priority);
        if (!c->session.empty()) cntl.set_session(c->session);
        benchpb::EchoRequest req;
        benchpb::EchoResponse res;
        req.set_send_ts_us(monotonic_time_us());
        const size_t payload = g->filler.size();
        if (c->pool_desc) {
            // One-sided descriptor load: pin a fresh pool block per call
            // (lease-managed; EndRPC releases it) so the generator
            // drives the full pin/resolve/release cycle, not a reused
            // buffer.
            IOBuf att;
            char* data = nullptr;
            if (IciBlockPool::AllocatePoolAttachment(payload, &att,
                                                     &data)) {
                memset(data, 'p', payload);
                cntl.set_request_pool_attachment(std::move(att));
            } else {
                cntl.request_attachment().append(g->filler);
            }
        } else {
            cntl.request_attachment().append(g->filler);
        }
        c->stub->Echo(&cntl, &req, &res, nullptr);
        if (cntl.Failed()) {
            RecordBackend(cntl, 0);
            g->failed.fetch_add(1, std::memory_order_relaxed);
            if (cntl.ErrorCode() == TERR_OVERLOAD) {
                g->shed.fetch_add(1, std::memory_order_relaxed);
                const int64_t hint = cntl.suggested_backoff_ms();
                int64_t cur =
                    g->backoff_ms_max.load(std::memory_order_relaxed);
                while (hint > cur &&
                       !g->backoff_ms_max.compare_exchange_weak(
                           cur, hint, std::memory_order_relaxed)) {
                }
            } else if (cntl.ErrorCode() == TERR_STALE_EPOCH) {
                g->stale.fetch_add(1, std::memory_order_relaxed);
            }
        } else {
            const int64_t lat_us = monotonic_time_us() - res.send_ts_us();
            RecordBackend(cntl, lat_us);
            g->lat << lat_us;
            g->sent.fetch_add(1, std::memory_order_relaxed);
        }
    }
    return nullptr;
}

// "--tenants=a:8,b:1", "a:8:7,b:1:1", or "a:8:7:128,b:1:1:65536" ->
// name:weight[:priority[:payload_bytes]] specs. The 4th field gives the
// class its own body size — one generator then emits mixed-COST load.
bool ParseTenantsSpec(const char* spec, int default_priority,
                      std::vector<std::unique_ptr<TenantGen>>* gens) {
    std::string s(spec);
    size_t pos = 0;
    while (pos < s.size()) {
        size_t comma = s.find(',', pos);
        if (comma == std::string::npos) comma = s.size();
        const std::string entry = s.substr(pos, comma - pos);
        pos = comma + 1;
        if (entry.empty()) continue;
        const size_t c1 = entry.find(':');
        if (c1 == std::string::npos || c1 == 0) return false;
        auto g = std::make_unique<TenantGen>();
        g->name = entry.substr(0, c1);
        g->priority = default_priority;
        const size_t c2 = entry.find(':', c1 + 1);
        g->weight = atoi(entry.c_str() + c1 + 1);
        if (g->weight <= 0) return false;
        if (c2 != std::string::npos) {
            g->priority = atoi(entry.c_str() + c2 + 1);
            const size_t c3 = entry.find(':', c2 + 1);
            if (c3 != std::string::npos) {
                g->payload = atoi(entry.c_str() + c3 + 1);
                if (g->payload < 0) return false;
            }
        }
        gens->push_back(std::move(g));
    }
    return !gens->empty();
}

}  // namespace

int main(int argc, char** argv) {
    std::string server_str;
    long long qps = 10000;
    int duration_s = 10;
    int payload = 4096;
    int callers = 8;
    int press_threads = 1;
    long long timeout_ms = 5000;
    bool pooled = false;
    bool pool_desc = false;
    bool json = false;
    const char* metrics_csv = nullptr;
    const char* tenants_spec = nullptr;
    std::string tenant;
    std::string zone;       // --zone: this generator's pod (ISSUE 14)
    std::string dcn_peers;  // --dcn_peers=h:p[,h:p]: cross-pod servers
    std::string via_str;    // --via: a tpu_router front door (ISSUE 16)
    int sessions = 0;       // --sessions: sticky ids stamped per caller
    int priority = -1;
    int max_retry = -1;  // <0 = channel default (3)
    long long stream_tokens = 0;  // --stream_tokens: push-stream mode
    int stream_read_delay_ms = 0;
    const char* blackbox_path = nullptr;  // --blackbox=PATH (ISSUE 19)
    const char* backend_csv = nullptr;    // --backend_csv=PATH (ISSUE 20)
    for (int i = 1; i < argc; ++i) {
        if (strncmp(argv[i], "--metrics_csv=", 14) == 0) {
            metrics_csv = argv[i] + 14;
        }
        if (strncmp(argv[i], "--backend_csv=", 14) == 0) {
            backend_csv = argv[i] + 14;
        }
        if (strncmp(argv[i], "--press_threads=", 16) == 0) {
            press_threads = atoi(argv[i] + 16);
        }
        if (strncmp(argv[i], "--server=", 9) == 0) server_str = argv[i] + 9;
        if (strncmp(argv[i], "--via=", 6) == 0) {
            via_str = argv[i] + 6;
            server_str = via_str;  // the router IS the target
        }
        if (strncmp(argv[i], "--sessions=", 11) == 0) {
            sessions = atoi(argv[i] + 11);
        }
        if (strncmp(argv[i], "--qps=", 6) == 0) qps = atoll(argv[i] + 6);
        if (strncmp(argv[i], "--timeout_ms=", 13) == 0) {
            timeout_ms = atoll(argv[i] + 13);
        }
        if (strncmp(argv[i], "-timeout_ms=", 12) == 0) {
            timeout_ms = atoll(argv[i] + 12);
        }
        if (strncmp(argv[i], "--duration_s=", 13) == 0) {
            duration_s = atoi(argv[i] + 13);
        }
        if (strncmp(argv[i], "--payload=", 10) == 0) {
            payload = atoi(argv[i] + 10);
        }
        // --body_bytes: the cost-model-facing spelling of --payload
        // (ISSUE 15) — the logical bytes half of a request's price.
        if (strncmp(argv[i], "--body_bytes=", 13) == 0) {
            payload = atoi(argv[i] + 13);
        }
        if (strncmp(argv[i], "--callers=", 10) == 0) {
            callers = atoi(argv[i] + 10);
        }
        if (strncmp(argv[i], "--tenant=", 9) == 0) tenant = argv[i] + 9;
        if (strncmp(argv[i], "--zone=", 7) == 0) zone = argv[i] + 7;
        if (strncmp(argv[i], "--dcn_peers=", 12) == 0) {
            dcn_peers = argv[i] + 12;
        }
        if (strncmp(argv[i], "--priority=", 11) == 0) {
            priority = atoi(argv[i] + 11);
        }
        // --max_retry=0 makes every shed/failure a FINAL failure: the
        // generator then emits its raw offered load instead of
        // throttling itself on overload backoffs — what an overload
        // soak needs to hold a flood at Nx capacity.
        if (strncmp(argv[i], "--max_retry=", 12) == 0) {
            max_retry = atoi(argv[i] + 12);
        }
        if (strncmp(argv[i], "--tenants=", 10) == 0) {
            tenants_spec = argv[i] + 10;
        }
        if (strncmp(argv[i], "--stream_tokens=", 16) == 0) {
            stream_tokens = atoll(argv[i] + 16);
        }
        if (strncmp(argv[i], "--stream_read_delay_ms=", 23) == 0) {
            stream_read_delay_ms = atoi(argv[i] + 23);
        }
        if (strcmp(argv[i], "--pooled") == 0) pooled = true;
        if (strcmp(argv[i], "--pool_desc") == 0 ||
            strcmp(argv[i], "--pool-desc") == 0) {
            pool_desc = true;
        }
        // --blackbox=PATH: dump the CLIENT-side flight rings there at
        // exit (and on a fatal signal) — the initiator half of a merged
        // causal timeline.
        if (strncmp(argv[i], "--blackbox=", 11) == 0) {
            blackbox_path = argv[i] + 11;
        }
        // --flag=name=value: tune any registered flag in the PRESS
        // process (mesh_node's --flag twin) — the grey-failure soak
        // enlarges flight_recorder_ring so the in-press EJECT event
        // survives to the end-of-run dump.
        if (strncmp(argv[i], "--flag=", 7) == 0) {
            const std::string kv = argv[i] + 7;
            const size_t eq = kv.find('=');
            if (eq == std::string::npos ||
                !SetFlagValue(kv.substr(0, eq), kv.substr(eq + 1))) {
                fprintf(stderr, "bad --flag %s\n", kv.c_str());
                return 2;
            }
        }
        if (strcmp(argv[i], "--json") == 0) json = true;
    }
    if (server_str.empty()) {
        fprintf(stderr,
                "usage: rpc_press --server=ip:port[,ip:port...] [--qps=N] "
                "[--duration_s=N] [--payload=N] [--callers=N] "
                "[--press_threads=N] [--pooled] [--pool_desc "
                "(alias: --pool-desc)] "
                "[--timeout_ms=N] [--body_bytes=N (alias: --payload)] "
                "[--max_retry=N] [--tenant=NAME] [--priority=0..7] "
                "[--tenants=name:weight[:prio[:payload_bytes]],...] "
                "[--zone=NAME] [--dcn_peers=ip:port,...] "
                "[--via=ip:port] [--sessions=N] "
                "[--stream_tokens=N [--stream_read_delay_ms=N]] "
                "[--blackbox=PATH] [--backend_csv=PATH] "
                "[--flag=name=value] [--json]\n"
                "  --server with a comma list drives a client-side LB "
                "channel (rr + outlier ejection); per-backend picks/"
                "errors/p99 and rpc_outlier_* counters are reported, "
                "--backend_csv appends per-interval per-backend rows\n"
                "  --zone/--dcn_peers: zone-aware LB over the local "
                "server + cross-pod dcn-tier peers; per-zone picks and "
                "spills are reported\n"
                "  --stream_tokens=N: each paced call opens a resumable "
                "server-push stream of N tokens; contiguity is asserted "
                "and TTFT p50/p99 + inter-token p99 reported\n");
        return 1;
    }
    if (blackbox_path != nullptr) {
        flight::SetNodeName("rpc_press");
        flight::InstallCrashHandler(blackbox_path);
    }
    // --server=h:p,h:p (ISSUE 20): a comma list turns the generator into
    // an LB client — the channel below runs the full load-balancer stack
    // (round-robin under the outlier wrapper) over a list:// naming set,
    // so ejection and reinstatement decisions happen IN this process and
    // the per-backend table (--backend_csv / press_backends) shows
    // traffic steering around a grey node. The first entry doubles as
    // the plain EndPoint the non-LB paths keep using.
    std::string server_list;
    if (server_str.find(',') != std::string::npos) {
        server_list = server_str;
        server_str.resize(server_str.find(','));
    }
    EndPoint server;
    if (hostname2endpoint(server_str.c_str(), &server) != 0) {
        fprintf(stderr, "bad server address: %s\n", server_str.c_str());
        return 1;
    }
    // Traffic classes: one per --tenants entry, or the single
    // (possibly anonymous) --tenant/--priority class.
    std::vector<std::unique_ptr<TenantGen>> gens;
    if (tenants_spec != nullptr) {
        if (!ParseTenantsSpec(tenants_spec, priority, &gens)) {
            fprintf(stderr, "bad --tenants spec: %s\n", tenants_spec);
            return 1;
        }
    } else {
        auto g = std::make_unique<TenantGen>();
        g->name = tenant;
        g->priority = priority;
        gens.push_back(std::move(g));
    }
    // Split the target qps (and below, the callers) by weight.
    long long wsum = 0;
    for (const auto& g : gens) wsum += g->weight;
    // Every class gets at least 1 qps (the max(1,...) floors can make
    // the shares sum past --qps at tiny targets — a silent zero-rate
    // tenant would be worse than a slightly-over-target run).
    long long qps_left = qps;
    for (size_t i = 0; i < gens.size(); ++i) {
        gens[i]->qps = i + 1 == gens.size()
                           ? std::max<long long>(1, qps_left)
                           : std::max<long long>(1, qps * gens[i]->weight /
                                                        wsum);
        qps_left -= gens[i]->qps;
    }
    if (press_threads < 1) press_threads = 1;
    if (callers < press_threads) callers = press_threads;
    if (callers < (int)gens.size()) callers = (int)gens.size();
    ChannelOptions copts;
    copts.timeout_ms = timeout_ms;
    if (max_retry >= 0) copts.max_retry = max_retry;
    if (pooled) copts.connection_type = CONNECTION_TYPE_POOLED;
    // Multi-channel generator: each channel pins its own connection so
    // the N connections shard across the server's (and this tool's)
    // epoll loops; a single shared SocketMap socket would serialize all
    // callers through one input fiber. NOT in pooled mode: pooled calls
    // ride fly sockets from the shared per-endpoint pool (the pin would
    // be bypassed and just leak one idle connection per channel) and the
    // pool's FIFO rotation already spreads load across connections.
    copts.pin_connection = press_threads > 1 && !pooled;
    if (pool_desc) {
        // Descriptor traffic needs the registered pool AND an shm-ICI
        // link whose handshake maps it on the server (plain TCP would
        // fall back inline / get TERR_REQUEST).
        if (IciBlockPool::Init() != 0 ||
            IciBlockPool::shm_name()[0] == '\0') {
            fprintf(stderr,
                    "--pool_desc: IciBlockPool init failed (no /dev/shm?)\n");
            return 1;
        }
    }
    // Mixed intra/cross-pod load (ISSUE 14): with --zone/--dcn_peers the
    // generator drives a zone-aware LB channel over a list:// naming set
    // — the local --server tagged with this zone, every --dcn_peers
    // entry tagged zone=remote (reached over dcn-tier sockets). Picks
    // stay local while the local server serves; kill it and the spill
    // counters reported below fire.
    std::string lb_url;
    if (!dcn_peers.empty()) {
        const std::string my_zone = zone.empty() ? "local" : zone;
        SetFlagValue("rpc_zone", my_zone);
        lb_url = "list://" + server_str + " zone=" + my_zone;
        size_t pos = 0;
        while (pos < dcn_peers.size()) {
            size_t comma = dcn_peers.find(',', pos);
            if (comma == std::string::npos) comma = dcn_peers.size();
            const std::string ep = dcn_peers.substr(pos, comma - pos);
            pos = comma + 1;
            if (ep.empty()) continue;
            // Entries may carry their own "ip:port zone=B" tag (space
            // separated); bare addresses default to zone=remote.
            lb_url += "," + ep;
            if (ep.find("zone=") == std::string::npos) {
                lb_url += " zone=remote";
            }
        }
    } else if (!zone.empty()) {
        SetFlagValue("rpc_zone", zone);
    }
    if (lb_url.empty() && !server_list.empty()) {
        lb_url = "list://" + server_list;
    }
    if (!lb_url.empty()) {
        // Client-side outlier tier: seed the rpc_outlier_* counters read
        // below and route health-check revives of ejected sockets
        // through the reinstatement probe ramp.
        outlier::ExposeVars();
        g_track_backends.store(true, std::memory_order_relaxed);
    }
    std::vector<std::unique_ptr<Channel>> channels;
    std::vector<std::unique_ptr<benchpb::EchoService_Stub>> stubs;
    for (int i = 0; i < press_threads; ++i) {
        channels.emplace_back(new Channel);
        const int rc =
            pool_desc ? channels.back()->InitIci(server, &copts)
            : !lb_url.empty()
                ? channels.back()->Init(lb_url.c_str(), "rr", &copts)
                : channels.back()->Init(server, &copts);
        if (rc != 0) {
            if (pool_desc) {
                fprintf(stderr,
                        "--pool_desc: ICI handshake with %s failed (is "
                        "the server on this host with a shared pool?)\n",
                        server_str.c_str());
            }
            return 1;
        }
        stubs.emplace_back(
            new benchpb::EchoService_Stub(channels.back().get()));
    }

    // Per-class request bodies: the spec's payload override, else the
    // global --body_bytes/--payload.
    for (auto& g : gens) {
        const int pbytes = g->payload >= 0 ? g->payload : payload;
        g->filler.append(std::string((size_t)pbytes, 'p'));
    }
    std::atomic<bool> stop{false};
    // Caller -> tenant assignment by weight (every tenant gets at least
    // one caller), channels round-robin underneath.
    std::vector<TenantGen*> assignment;
    for (auto& g : gens) assignment.push_back(g.get());
    while ((int)assignment.size() < callers) {
        // Repeat tenants proportionally to weight until callers filled.
        long long best = -1;
        TenantGen* pick = gens[0].get();
        for (auto& g : gens) {
            long long have = 0;
            for (TenantGen* a : assignment) have += (a == g.get());
            // Deficit = desired share minus current share (scaled).
            const long long deficit =
                (long long)g->weight * (long long)assignment.size() -
                have * wsum;
            if (deficit > best) {
                best = deficit;
                pick = g.get();
            }
        }
        assignment.push_back(pick);
    }
    std::vector<PressCtx> ctxs;
    ctxs.reserve((size_t)callers);
    for (int i = 0; i < callers; ++i) {
        ctxs.push_back(PressCtx{stubs[(size_t)(i % press_threads)].get(),
                                assignment[(size_t)i], &stop,
                                timeout_ms, pool_desc,
                                i < sessions
                                    ? "s" + std::to_string(i)
                                    : std::string(),
                                stream_tokens, stream_read_delay_ms});
    }
    std::vector<fiber_t> tids((size_t)callers);
    for (size_t i = 0; i < tids.size(); ++i) {
        fiber_start_background(&tids[i], nullptr, PressCaller, &ctxs[i]);
    }

    // Per-interval scrape sink (--metrics_csv): one appended row per
    // second feeds the BENCH trajectory; mixed-tenant runs add one row
    // per tenant per interval (tenant column).
    FILE* csv = nullptr;
    if (metrics_csv != nullptr) {
        const bool fresh = access(metrics_csv, F_OK) != 0;
        csv = fopen(metrics_csv, "a");
        if (csv != nullptr && fresh) {
            // Stream columns APPENDED at the end: bench.py's
            // series_scrape indexes qps/p99 positionally (c[1], c[3]).
            fprintf(csv,
                    "elapsed_s,qps,p50_us,p99_us,p999_us,failed,tenant,"
                    "ttft_p50_us,ttft_p99_us,itl_p99_us\n");
        }
    }
    // Per-interval per-backend rows (--backend_csv): interval pick and
    // error DELTAS — the soak's pick-share-recovery assertion reads the
    // tail rows, so cumulative totals (which remember the outage) would
    // be the wrong shape.
    FILE* bcsv = nullptr;
    if (backend_csv != nullptr) {
        const bool fresh = access(backend_csv, F_OK) != 0;
        bcsv = fopen(backend_csv, "a");
        if (bcsv != nullptr && fresh) {
            fprintf(bcsv, "elapsed_s,backend,picks,errors,p99_us\n");
        }
    }

    // Refill by elapsed time (exact pacing for any target, including
    // qps below the 100Hz refill cadence), per tenant class; buckets
    // capped at one second of budget so stalls don't cause unbounded
    // bursts.
    const int64_t t0 = monotonic_time_us();
    const int64_t end = t0 + (int64_t)duration_s * 1000 * 1000;
    int64_t next_report_us = t0 + 1000 * 1000;
    int64_t agg_last_sent = 0;
    const auto report = [&](int64_t now) {
        int64_t total_sent = 0, total_failed = 0;
        for (auto& g : gens) {
            total_sent += g->sent.load(std::memory_order_relaxed);
            total_failed += g->failed.load(std::memory_order_relaxed);
        }
        const int64_t iqps = total_sent - agg_last_sent;
        agg_last_sent = total_sent;
        const long long elapsed_s = (now - t0) / 1000000;
        // Aggregate percentiles: single-class runs report that class;
        // mixed runs report the first (it also gets per-tenant rows).
        long long p50 = 0, p99 = 0, p999 = 0;
        {
            int64_t cnt = 0;
            for (auto& g : gens) {
                // Use the class with the most samples as the headline.
                if (g->lat.count() > cnt) {
                    cnt = g->lat.count();
                    p50 = g->lat.latency_percentile(0.5);
                    p99 = g->lat.latency_percentile(0.99);
                    p999 = g->lat.latency_percentile(0.999);
                }
            }
        }
        // Headline stream latencies: the class with the most tokens.
        long long ttft50 = 0, ttft99 = 0, itl99 = 0;
        {
            int64_t cnt = -1;
            for (auto& g : gens) {
                if (g->ttft.count() > cnt) {
                    cnt = g->ttft.count();
                    ttft50 = g->ttft.latency_percentile(0.5);
                    ttft99 = g->ttft.latency_percentile(0.99);
                    itl99 = g->itl.latency_percentile(0.99);
                }
            }
        }
        printf("t=%llds qps=%lld p50=%lldus p99=%lldus p999=%lldus "
               "failed=%lld\n",
               elapsed_s, (long long)iqps, p50, p99, p999,
               (long long)total_failed);
        fflush(stdout);
        if (csv != nullptr) {
            fprintf(csv,
                    "%lld,%lld,%lld,%lld,%lld,%lld,all,%lld,%lld,%lld\n",
                    elapsed_s, (long long)iqps, p50, p99, p999,
                    (long long)total_failed, ttft50, ttft99, itl99);
            if (gens.size() > 1) {
                for (auto& g : gens) {
                    const int64_t s = g->sent.load(std::memory_order_relaxed);
                    fprintf(csv,
                            "%lld,%lld,%lld,%lld,%lld,%lld,%s,"
                            "%lld,%lld,%lld\n",
                            elapsed_s, (long long)(s - g->last_sent),
                            (long long)g->lat.latency_percentile(0.5),
                            (long long)g->lat.latency_percentile(0.99),
                            (long long)g->lat.latency_percentile(0.999),
                            (long long)g->failed.load(
                                std::memory_order_relaxed),
                            g->name.empty() ? "default" : g->name.c_str(),
                            (long long)g->ttft.latency_percentile(0.5),
                            (long long)g->ttft.latency_percentile(0.99),
                            (long long)g->itl.latency_percentile(0.99));
                    g->last_sent = s;
                }
            }
            fflush(csv);
        }
        if (bcsv != nullptr) {
            std::lock_guard<std::mutex> lock(g_backend_mu);
            for (auto& kv : g_backends) {
                BackendStat* b = kv.second.get();
                const int64_t p = b->picks.load(std::memory_order_relaxed);
                const int64_t e =
                    b->errors.load(std::memory_order_relaxed);
                fprintf(bcsv, "%lld,%s,%lld,%lld,%lld\n", elapsed_s,
                        kv.first.c_str(), (long long)(p - b->last_picks),
                        (long long)(e - b->last_errors),
                        (long long)b->lat.latency_percentile(0.99));
                b->last_picks = p;
                b->last_errors = e;
            }
            fflush(bcsv);
        }
    };
    signal(SIGINT, OnSigint);  // clean early stop (full final report)
    while (monotonic_time_us() < end && !g_sigint) {
        const int64_t now = monotonic_time_us();
        for (auto& g : gens) {
            const int64_t should = (now - t0) * g->qps / 1000000;
            if (should > g->granted) {
                g->tokens.fetch_add(should - g->granted,
                                    std::memory_order_relaxed);
                g->granted = should;
            }
            int64_t cur = g->tokens.load(std::memory_order_relaxed);
            if (cur > g->qps) {
                g->tokens.fetch_sub(cur - g->qps,
                                    std::memory_order_relaxed);
            }
        }
        if (now >= next_report_us) {
            next_report_us += 1000 * 1000;
            report(now);
        }
        usleep(10 * 1000);
    }
    // The loop exits AT the deadline (or on SIGINT), so the last
    // interval would otherwise never be reported — an N-second run must
    // yield N rows, and an interrupted run must still end with a
    // complete row rather than a torn write.
    report(monotonic_time_us());
    if (csv != nullptr) fclose(csv);
    if (bcsv != nullptr) fclose(bcsv);
    stop.store(true, std::memory_order_relaxed);
    for (auto tid : tids) fiber_join(tid, nullptr);
    const double secs = (double)(monotonic_time_us() - t0) / 1e6;
    int64_t total_sent = 0, total_failed = 0, total_shed = 0;
    int64_t total_stale = 0;
    for (auto& g : gens) {
        total_sent += g->sent.load();
        total_failed += g->failed.load();
        total_shed += g->shed.load();
        total_stale += g->stale.load();
    }
    const double achieved = (double)total_sent / secs;
    int64_t backoff_max = 0;
    for (auto& g : gens) {
        backoff_max = std::max(backoff_max, g->backoff_ms_max.load());
    }
    // Headline percentiles from the largest class (see report()).
    const TenantGen* head = gens[0].get();
    for (auto& g : gens) {
        if (g->lat.count() > head->lat.count()) head = g.get();
    }
    int64_t stream_rx = 0, stream_resumes = 0, stream_seq_errors = 0;
    int64_t stream_dups = 0;
    const TenantGen* shead = gens[0].get();  // most-token stream class
    for (auto& g : gens) {
        stream_rx += g->stream_tokens_rx.load();
        stream_resumes += g->stream_resumes.load();
        stream_seq_errors += g->stream_seq_errors.load();
        stream_dups += g->stream_dups.load();
        if (g->ttft.count() > shead->ttft.count()) shead = g.get();
    }
    // --via: one scrape of the router's own view — backend-measured p99
    // and the hedge count — then the router-added latency is simply
    // client-observed p99 minus what the backends took.
    int64_t via_backend_p99 = -1, via_hedges = -1, via_added_p99 = -1;
    if (!via_str.empty()) {
        std::string rj;
        if (PortalGet(server, "/router?format=json", &rj)) {
            via_backend_p99 = JsonIntField(rj, "backend_p99_us");
            via_hedges = JsonIntField(rj, "hedges");
            const int64_t client_p99 = head->lat.latency_percentile(0.99);
            if (via_backend_p99 >= 0 && client_p99 > 0) {
                via_added_p99 =
                    std::max<int64_t>(0, client_p99 - via_backend_p99);
            }
        } else {
            fprintf(stderr, "--via: scrape of %s/router failed\n",
                    via_str.c_str());
        }
    }
    if (json) {
        // Generator config rides along so BENCH records are
        // reproducible: the same qps from 1 vs 16 connections stresses
        // completely different server paths.
        printf("{\"press_qps\": %.0f, \"press_target_qps\": %lld, "
               "\"press_failed\": %lld, \"press_shed\": %lld, "
               "\"press_backoff_ms_max\": %lld, "
               "\"press_p50_us\": %lld, "
               "\"press_p99_us\": %lld, \"press_p999_us\": %lld, "
               "\"press_threads\": %d, \"press_callers\": %d, "
               "\"press_payload\": %d, \"press_pooled\": %d, "
               "\"press_pool_desc\": %d, \"press_stale_epoch\": %lld",
               achieved, qps, (long long)total_failed,
               (long long)total_shed, (long long)backoff_max,
               (long long)head->lat.latency_percentile(0.5),
               (long long)head->lat.latency_percentile(0.99),
               (long long)head->lat.latency_percentile(0.999),
               press_threads, callers, payload, pooled ? 1 : 0,
               pool_desc ? 1 : 0, (long long)total_stale);
        if (stream_tokens > 0) {
            printf(", \"press_ttft_us\": {\"p50\": %lld, \"p99\": %lld}, "
                   "\"press_itl_us\": {\"p99\": %lld}, "
                   "\"press_stream_tokens\": %lld, "
                   "\"press_stream_resumes\": %lld, "
                   "\"press_stream_seq_errors\": %lld, "
                   "\"press_stream_dups\": %lld",
                   (long long)shead->ttft.latency_percentile(0.5),
                   (long long)shead->ttft.latency_percentile(0.99),
                   (long long)shead->itl.latency_percentile(0.99),
                   (long long)stream_rx, (long long)stream_resumes,
                   (long long)stream_seq_errors, (long long)stream_dups);
        }
        if (!via_str.empty()) {
            printf(", \"press_via_p99_us\": %lld, "
                   "\"press_via_backend_p99_us\": %lld, "
                   "\"press_hedges\": %lld, \"press_sessions\": %d",
                   (long long)via_added_p99, (long long)via_backend_p99,
                   (long long)via_hedges, sessions);
        }
        if (!dcn_peers.empty()) {
            printf(", \"press_zone\": \"%s\", "
                   "\"press_zone_local_picks\": %lld, "
                   "\"press_zone_spills\": %lld, "
                   "\"press_dcn_out_bytes\": %lld",
                   zone.empty() ? "local" : zone.c_str(),
                   (long long)VarInt("rpc_lb_zone_local_picks"),
                   (long long)VarInt("rpc_lb_zone_spills"),
                   (long long)transport_stats::out_bytes(TierDcn()));
        }
        if (g_track_backends.load(std::memory_order_relaxed)) {
            // The outlier counters are CLIENT-side: the LB channel (and
            // its ejection decisions) live in this process.
            printf(", \"press_outlier_ejections\": %lld, "
                   "\"press_outlier_reinstatements\": %lld, "
                   "\"press_outlier_ejected_now\": %lld, "
                   "\"press_retry_budget_exhausted\": %lld, "
                   "\"press_backends\": {",
                   (long long)VarInt("rpc_outlier_ejections"),
                   (long long)VarInt("rpc_outlier_reinstatements"),
                   (long long)VarInt("rpc_outlier_ejected_now"),
                   (long long)VarInt("rpc_retry_budget_exhausted"));
            std::lock_guard<std::mutex> lock(g_backend_mu);
            bool first = true;
            for (auto& kv : g_backends) {
                BackendStat* b = kv.second.get();
                printf("%s\"%s\": {\"picks\": %lld, \"errors\": %lld, "
                       "\"p50_us\": %lld, \"p99_us\": %lld}",
                       first ? "" : ", ", kv.first.c_str(),
                       (long long)b->picks.load(),
                       (long long)b->errors.load(),
                       (long long)b->lat.latency_percentile(0.5),
                       (long long)b->lat.latency_percentile(0.99));
                first = false;
            }
            printf("}");
        }
        if (gens.size() > 1 || !gens[0]->name.empty()) {
            printf(", \"press_tenants\": {");
            for (size_t i = 0; i < gens.size(); ++i) {
                const auto& g = gens[i];
                printf("%s\"%s\": {\"qps\": %.0f, \"target_qps\": %lld, "
                       "\"priority\": %d, \"payload\": %lld, "
                       "\"sent\": %lld, "
                       "\"failed\": %lld, \"shed\": %lld, "
                       "\"backoff_ms_max\": %lld, "
                       "\"p50_us\": %lld, \"p99_us\": %lld}",
                       i == 0 ? "" : ", ",
                       g->name.empty() ? "default" : g->name.c_str(),
                       (double)g->sent.load() / secs, g->qps, g->priority,
                       (long long)g->filler.size(),
                       (long long)g->sent.load(),
                       (long long)g->failed.load(),
                       (long long)g->shed.load(),
                       (long long)g->backoff_ms_max.load(),
                       (long long)g->lat.latency_percentile(0.5),
                       (long long)g->lat.latency_percentile(0.99));
            }
            printf("}");
        }
        printf("}\n");
    } else {
        printf("sent %lld ok (%lld failed, %lld shed, %lld stale-epoch) "
               "in %.1fs: %.0f qps (target %lld, %d channels x %d "
               "callers%s)\n",
               (long long)total_sent, (long long)total_failed,
               (long long)total_shed, (long long)total_stale, secs,
               achieved, qps, press_threads, callers,
               pool_desc ? ", pool-desc" : "");
        printf("latency_us: p50 %lld  p99 %lld  p999 %lld  max %lld\n",
               (long long)head->lat.latency_percentile(0.5),
               (long long)head->lat.latency_percentile(0.99),
               (long long)head->lat.latency_percentile(0.999),
               (long long)head->lat.max_latency());
        if (stream_tokens > 0) {
            printf("streams: tokens %lld  resumes %lld  seq_errors %lld "
                   " dups %lld  ttft_us p50 %lld p99 %lld  itl_us p99 "
                   "%lld\n",
                   (long long)stream_rx, (long long)stream_resumes,
                   (long long)stream_seq_errors, (long long)stream_dups,
                   (long long)shead->ttft.latency_percentile(0.5),
                   (long long)shead->ttft.latency_percentile(0.99),
                   (long long)shead->itl.latency_percentile(0.99));
        }
        if (!via_str.empty()) {
            printf("via router %s: client p99 %lldus, backend p99 "
                   "%lldus, router-added p99 %lldus, hedges %lld\n",
                   via_str.c_str(),
                   (long long)head->lat.latency_percentile(0.99),
                   (long long)via_backend_p99, (long long)via_added_p99,
                   (long long)via_hedges);
        }
        if (!dcn_peers.empty()) {
            printf("zone %s: local_picks %lld  spills %lld  "
                   "dcn_out_bytes %lld\n",
                   zone.empty() ? "local" : zone.c_str(),
                   (long long)VarInt("rpc_lb_zone_local_picks"),
                   (long long)VarInt("rpc_lb_zone_spills"),
                   (long long)transport_stats::out_bytes(TierDcn()));
        }
        if (g_track_backends.load(std::memory_order_relaxed)) {
            printf("outliers: ejections %lld  reinstatements %lld  "
                   "ejected_now %lld\n",
                   (long long)VarInt("rpc_outlier_ejections"),
                   (long long)VarInt("rpc_outlier_reinstatements"),
                   (long long)VarInt("rpc_outlier_ejected_now"));
            std::lock_guard<std::mutex> lock(g_backend_mu);
            for (auto& kv : g_backends) {
                BackendStat* b = kv.second.get();
                printf("  backend %-21s picks=%lld errors=%lld "
                       "p99=%lldus\n",
                       kv.first.c_str(), (long long)b->picks.load(),
                       (long long)b->errors.load(),
                       (long long)b->lat.latency_percentile(0.99));
            }
        }
        for (auto& g : gens) {
            if (gens.size() <= 1) break;
            printf("  tenant %-12s prio=%d target=%lld qps=%.0f "
                   "sent=%lld failed=%lld shed=%lld p99=%lldus\n",
                   g->name.empty() ? "default" : g->name.c_str(),
                   g->priority, (long long)g->qps,
                   (double)g->sent.load() / secs, (long long)g->sent.load(),
                   (long long)g->failed.load(), (long long)g->shed.load(),
                   (long long)g->lat.latency_percentile(0.99));
        }
    }
    if (blackbox_path != nullptr) {
        flight::DumpToConfiguredPath();
    }
    return 0;
}
