// rpc_press: target-QPS load generator (reference tools/rpc_press — we
// drive the echo fixture service rather than dynamically-loaded protos;
// the token-bucket pacing and latency reporting match the reference's
// rdma_performance client.cpp:50-68).
//
//   rpc_press --server=ip:port [--qps=10000] [--duration_s=10]
//             [--payload=4096] [--callers=8] [--press_threads=1]
//             [--pooled] [--timeout_ms=5000] [--metrics_csv=path]
//
// --press_threads=N drives N independent pinned channels (one connection
// each, callers spread round-robin), so the generator scales past a
// single event loop / input fiber — at high connection counts the SERVER
// must be the bottleneck, not this tool (ISSUE 7). The generator config
// rides the --json line (press_threads/press_callers/...) so BENCH
// records say how the load was made.
//
// --timeout_ms sets the per-request deadline (propagated to the server
// as the remaining-budget meta): tiny values drive the server's
// expired-shed and budget-shed paths from the load tool — watch
// rpc_server_expired_requests / rpc_server_shed_requests in its /vars.
//
// While running, one stats line per second (interval qps + windowed
// p50/p99/p999); --metrics_csv=<path> appends the same row per interval
// as CSV (elapsed_s,qps,p50_us,p99_us,p999_us,failed_total) — the BENCH
// trajectory input. Prints qps achieved + latency percentiles at the
// end; --json for one JSON line.
#include <signal.h>
#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <memory>
#include <string>
#include <vector>

#include "bench_echo.pb.h"
#include "tbase/endpoint.h"
#include "tbase/time.h"
#include "tfiber/fiber.h"
#include "trpc/channel.h"
#include "trpc/controller.h"
#include "tvar/latency_recorder.h"

using namespace tpurpc;

namespace {

struct PressCtx {
    benchpb::EchoService_Stub* stub;
    LatencyRecorder* lat;
    std::atomic<int64_t>* tokens;
    std::atomic<bool>* stop;
    std::atomic<int64_t>* sent;
    std::atomic<int64_t>* failed;
    IOBuf* filler;
    int64_t timeout_ms;
};

// Ctrl-C / SIGINT: finish the current interval cleanly — flush the final
// p50/p99/p999 line and --metrics_csv row, join the callers, print the
// summary — instead of dying mid-write with a torn CSV.
volatile sig_atomic_t g_sigint = 0;
void OnSigint(int) { g_sigint = 1; }

void* PressCaller(void* arg) {
    auto* c = (PressCtx*)arg;
    while (!c->stop->load(std::memory_order_relaxed)) {
        // Token bucket: each call consumes one token (reference
        // rdma_performance client.cpp:68).
        if (c->tokens->fetch_sub(1, std::memory_order_relaxed) <= 0) {
            c->tokens->fetch_add(1, std::memory_order_relaxed);
            fiber_usleep(200);
            continue;
        }
        Controller cntl;
        cntl.set_timeout_ms(c->timeout_ms);
        benchpb::EchoRequest req;
        benchpb::EchoResponse res;
        req.set_send_ts_us(monotonic_time_us());
        cntl.request_attachment().append(*c->filler);
        c->stub->Echo(&cntl, &req, &res, nullptr);
        if (cntl.Failed()) {
            c->failed->fetch_add(1, std::memory_order_relaxed);
        } else {
            *c->lat << (monotonic_time_us() - res.send_ts_us());
            c->sent->fetch_add(1, std::memory_order_relaxed);
        }
    }
    return nullptr;
}

}  // namespace

int main(int argc, char** argv) {
    std::string server_str;
    long long qps = 10000;
    int duration_s = 10;
    int payload = 4096;
    int callers = 8;
    int press_threads = 1;
    long long timeout_ms = 5000;
    bool pooled = false;
    bool json = false;
    const char* metrics_csv = nullptr;
    for (int i = 1; i < argc; ++i) {
        if (strncmp(argv[i], "--metrics_csv=", 14) == 0) {
            metrics_csv = argv[i] + 14;
        }
        if (strncmp(argv[i], "--press_threads=", 16) == 0) {
            press_threads = atoi(argv[i] + 16);
        }
        if (strncmp(argv[i], "--server=", 9) == 0) server_str = argv[i] + 9;
        if (strncmp(argv[i], "--qps=", 6) == 0) qps = atoll(argv[i] + 6);
        if (strncmp(argv[i], "--timeout_ms=", 13) == 0) {
            timeout_ms = atoll(argv[i] + 13);
        }
        if (strncmp(argv[i], "-timeout_ms=", 12) == 0) {
            timeout_ms = atoll(argv[i] + 12);
        }
        if (strncmp(argv[i], "--duration_s=", 13) == 0) {
            duration_s = atoi(argv[i] + 13);
        }
        if (strncmp(argv[i], "--payload=", 10) == 0) {
            payload = atoi(argv[i] + 10);
        }
        if (strncmp(argv[i], "--callers=", 10) == 0) {
            callers = atoi(argv[i] + 10);
        }
        if (strcmp(argv[i], "--pooled") == 0) pooled = true;
        if (strcmp(argv[i], "--json") == 0) json = true;
    }
    if (server_str.empty()) {
        fprintf(stderr,
                "usage: rpc_press --server=ip:port [--qps=N] "
                "[--duration_s=N] [--payload=N] [--callers=N] "
                "[--press_threads=N] [--pooled] [--timeout_ms=N] "
                "[--json]\n");
        return 1;
    }
    EndPoint server;
    if (hostname2endpoint(server_str.c_str(), &server) != 0) {
        fprintf(stderr, "bad server address: %s\n", server_str.c_str());
        return 1;
    }
    if (press_threads < 1) press_threads = 1;
    if (callers < press_threads) callers = press_threads;
    ChannelOptions copts;
    copts.timeout_ms = timeout_ms;
    if (pooled) copts.connection_type = CONNECTION_TYPE_POOLED;
    // Multi-channel generator: each channel pins its own connection so
    // the N connections shard across the server's (and this tool's)
    // epoll loops; a single shared SocketMap socket would serialize all
    // callers through one input fiber. NOT in pooled mode: pooled calls
    // ride fly sockets from the shared per-endpoint pool (the pin would
    // be bypassed and just leak one idle connection per channel) and the
    // pool's FIFO rotation already spreads load across connections.
    copts.pin_connection = press_threads > 1 && !pooled;
    std::vector<std::unique_ptr<Channel>> channels;
    std::vector<std::unique_ptr<benchpb::EchoService_Stub>> stubs;
    for (int i = 0; i < press_threads; ++i) {
        channels.emplace_back(new Channel);
        if (channels.back()->Init(server, &copts) != 0) return 1;
        stubs.emplace_back(
            new benchpb::EchoService_Stub(channels.back().get()));
    }

    IOBuf filler;
    filler.append(std::string((size_t)payload, 'p'));
    LatencyRecorder lat;
    std::atomic<int64_t> tokens{0};
    std::atomic<bool> stop{false};
    std::atomic<int64_t> sent{0};
    std::atomic<int64_t> failed{0};
    // One ctx per channel; callers spread round-robin across them.
    std::vector<PressCtx> ctxs;
    ctxs.reserve((size_t)press_threads);
    for (int i = 0; i < press_threads; ++i) {
        ctxs.push_back(PressCtx{stubs[(size_t)i].get(), &lat, &tokens,
                                &stop, &sent, &failed, &filler,
                                timeout_ms});
    }
    std::vector<fiber_t> tids((size_t)callers);
    for (size_t i = 0; i < tids.size(); ++i) {
        fiber_start_background(&tids[i], nullptr, PressCaller,
                               &ctxs[i % ctxs.size()]);
    }

    // Per-interval scrape sink (--metrics_csv): one appended row per
    // second feeds the BENCH trajectory.
    FILE* csv = nullptr;
    if (metrics_csv != nullptr) {
        const bool fresh = access(metrics_csv, F_OK) != 0;
        csv = fopen(metrics_csv, "a");
        if (csv != nullptr && fresh) {
            fprintf(csv, "elapsed_s,qps,p50_us,p99_us,p999_us,failed\n");
        }
    }

    // Refill by elapsed time (exact pacing for any target, including
    // qps below the 100Hz refill cadence), bucket capped at one second
    // of budget so stalls don't cause unbounded bursts.
    const int64_t t0 = monotonic_time_us();
    const int64_t end = t0 + (int64_t)duration_s * 1000 * 1000;
    int64_t granted = 0;
    int64_t next_report_us = t0 + 1000 * 1000;
    int64_t last_sent = 0;
    const auto report = [&](int64_t now) {
        const int64_t total_sent = sent.load(std::memory_order_relaxed);
        const int64_t iqps = total_sent - last_sent;
        last_sent = total_sent;
        const long long elapsed_s = (now - t0) / 1000000;
        const long long p50 = lat.latency_percentile(0.5);
        const long long p99 = lat.latency_percentile(0.99);
        const long long p999 = lat.latency_percentile(0.999);
        const long long nfailed = failed.load(std::memory_order_relaxed);
        printf("t=%llds qps=%lld p50=%lldus p99=%lldus p999=%lldus "
               "failed=%lld\n",
               elapsed_s, (long long)iqps, p50, p99, p999, nfailed);
        fflush(stdout);
        if (csv != nullptr) {
            fprintf(csv, "%lld,%lld,%lld,%lld,%lld,%lld\n", elapsed_s,
                    (long long)iqps, p50, p99, p999, nfailed);
            fflush(csv);
        }
    };
    signal(SIGINT, OnSigint);  // clean early stop (full final report)
    while (monotonic_time_us() < end && !g_sigint) {
        const int64_t now = monotonic_time_us();
        const int64_t should = (now - t0) * qps / 1000000;
        if (should > granted) {
            tokens.fetch_add(should - granted, std::memory_order_relaxed);
            granted = should;
        }
        int64_t cur = tokens.load(std::memory_order_relaxed);
        if (cur > qps) {
            tokens.fetch_sub(cur - qps, std::memory_order_relaxed);
        }
        if (now >= next_report_us) {
            next_report_us += 1000 * 1000;
            report(now);
        }
        usleep(10 * 1000);
    }
    // The loop exits AT the deadline (or on SIGINT), so the last
    // interval would otherwise never be reported — an N-second run must
    // yield N rows, and an interrupted run must still end with a
    // complete row rather than a torn write.
    report(monotonic_time_us());
    if (csv != nullptr) fclose(csv);
    stop.store(true, std::memory_order_relaxed);
    for (auto tid : tids) fiber_join(tid, nullptr);
    const double secs = (double)(monotonic_time_us() - t0) / 1e6;
    const double achieved = (double)sent.load() / secs;
    if (json) {
        // Generator config rides along so BENCH records are
        // reproducible: the same qps from 1 vs 16 connections stresses
        // completely different server paths.
        printf("{\"press_qps\": %.0f, \"press_target_qps\": %lld, "
               "\"press_failed\": %lld, \"press_p50_us\": %lld, "
               "\"press_p99_us\": %lld, \"press_p999_us\": %lld, "
               "\"press_threads\": %d, \"press_callers\": %d, "
               "\"press_payload\": %d, \"press_pooled\": %d}\n",
               achieved, qps, (long long)failed.load(),
               (long long)lat.latency_percentile(0.5),
               (long long)lat.latency_percentile(0.99),
               (long long)lat.latency_percentile(0.999), press_threads,
               callers, payload, pooled ? 1 : 0);
    } else {
        printf("sent %lld ok (%lld failed) in %.1fs: %.0f qps "
               "(target %lld, %d channels x %d callers)\n",
               (long long)sent.load(), (long long)failed.load(), secs,
               achieved, qps, press_threads, callers);
        printf("latency_us: p50 %lld  p99 %lld  p999 %lld  max %lld\n",
               (long long)lat.latency_percentile(0.5),
               (long long)lat.latency_percentile(0.99),
               (long long)lat.latency_percentile(0.999),
               (long long)lat.max_latency());
    }
    return 0;
}
