"""Collective data plane: combo-channel fan-out lowered to XLA collectives.

The reference's ParallelChannel (src/brpc/parallel_channel.h:94) fans one RPC
out to N sub-channels and merges responses; PartitionChannel
(src/brpc/partition_channel.h:34) shards by partition tag. On TPU the regular
cases of these patterns lower to mesh collectives (all_gather /
psum / reduce_scatter over ICI) instead of per-peer socket writes — the
BASELINE.json north star.
"""
