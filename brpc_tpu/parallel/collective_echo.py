"""The RPC data-plane computation, single-chip and mesh-parallel.

Single-chip `echo_step` models what the framework does to every payload:
frame it (length + checksum header) and echo it back. The mesh version
`make_parallel_echo_step` is the ParallelChannel fan-out lowered to XLA
collectives: every peer gathers all requests (AllGather = the fan-out of
parallel_channel.cpp:40 ParallelChannelDone), computes its response share,
and the responses are reduce-scattered back to their callers (= the
ResponseMerger of parallel_channel.h:151).

All shapes are static; control flow is compiler-friendly (no Python
branching on data), so XLA tiles the reductions onto the VPU and rides ICI
for the collectives.
"""

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

_MOD = jnp.uint32(65521)


def _adler_frame_checksum(words: jax.Array) -> jax.Array:
    """Order-dependent checksum over uint32 words (last axis), vectorized.

    Plays the role crc32c plays in the reference's baidu_std frames
    (src/brpc/policy/crc32c_checksum.*): an order-sensitive integrity word.
    Split into 16-bit halves so all arithmetic stays in uint32 without
    overflow (<= 2048 halves * 65535 < 2^32), and computed with cumulative
    sums so it maps to parallel scans on TPU instead of a sequential loop.
    """
    lo = words & jnp.uint32(0xFFFF)
    hi = words >> jnp.uint32(16)
    halves = jnp.stack([lo, hi], axis=-1).reshape(*words.shape[:-1], -1)
    s1 = jnp.cumsum(halves, axis=-1)
    a = s1[..., -1] % _MOD
    b = jnp.sum(s1 % _MOD, axis=-1) % _MOD
    return (b << jnp.uint32(16)) | a


@jax.jit
def echo_step(payloads: jax.Array) -> tuple:
    """Frame + echo a batch of payloads: returns (checksums, lengths, echoed).

    payloads: uint32[batch, words].
    """
    checksums = _adler_frame_checksum(payloads)
    # The echo "service": identity transform on the payload (the reference's
    # echo example, example/echo_c++/server.cpp), plus a framed length word.
    lengths = jnp.full(
        (payloads.shape[0],), payloads.shape[1] * 4, dtype=jnp.uint32
    )
    return checksums, lengths, payloads


def make_parallel_echo_step(mesh: Mesh):
    """ParallelChannel fan-out over a mesh: AllGather -> serve -> ReduceScatter.

    Returns a jitted step: uint32[n_peers, words] -> uint32[n_peers, words]
    where row i is peer i's merged response.
    """
    axis = mesh.axis_names[0]

    def _shard_body(local: jax.Array) -> jax.Array:
        # local: uint32[1, words] — this peer's outbound request.
        # Fan-out: every peer sees all requests (the sub-channel sends of
        # ParallelChannel, lowered to one AllGather over ICI).
        all_reqs = jax.lax.all_gather(local, axis, axis=0, tiled=True)
        # Each request i is served by its designated responder, peer
        # (i+1) mod n — a real remote hop. Non-responders contribute zeros,
        # so the ReduceScatter merge below routes exactly one response back
        # to each caller with no arithmetic on payload bits (a uint32 sum
        # of n copies would wrap for words >= 2^32/n).
        n = jax.lax.axis_size(axis)
        me = jax.lax.axis_index(axis)
        req_idx = jnp.arange(n, dtype=jnp.uint32)
        is_responder = ((req_idx + 1) % n) == me.astype(jnp.uint32)
        served = jnp.where(is_responder[:, None], all_reqs, jnp.uint32(0))
        # Merge responses back to callers (ResponseMerger): ReduceScatter
        # sums one nonzero contribution per caller row == exact echo.
        merged = jax.lax.psum_scatter(
            served, axis, scatter_dimension=0, tiled=True
        )
        return merged

    sharded = jax.shard_map(
        _shard_body,
        mesh=mesh,
        in_specs=P(axis, None),
        out_specs=P(axis, None),
    )
    return jax.jit(sharded)


def make_allreduce_step(mesh: Mesh):
    """The mesh all-reduce as XLA lowers it (ISSUE 13 cross-check).

    The C++ collective tier (cpp/trpc/collective.h) runs the same
    pattern as a chunked descriptor-pipelined ring over the RPC mesh;
    both implementations compute a uint32 WRAPAROUND sum, so their
    results must agree bit for bit on identical payloads
    (tests/test_collectives.py drives both).

    Returns a jitted step: uint32[n, words] -> uint32[n, words] where
    every row holds the elementwise sum over rows.
    """
    axis = mesh.axis_names[0]

    def _shard_body(local: jax.Array) -> jax.Array:
        # local: uint32[1, words]. psum == the ring's reduce; uint32
        # arithmetic wraps identically on every backend.
        return jax.lax.psum(local, axis)

    sharded = jax.shard_map(
        _shard_body,
        mesh=mesh,
        in_specs=P(axis, None),
        out_specs=P(axis, None),
    )
    return jax.jit(sharded)


def make_allgather_step(mesh: Mesh):
    """The mesh all-gather lowering: every row collects all rows.

    Twin of the C++ pull-based chunked all-gather. Returns a jitted
    step: uint32[n, words] -> uint32[n, n*words] (per-row concatenation
    of every peer's block, rank order).
    """
    axis = mesh.axis_names[0]

    def _shard_body(local: jax.Array) -> jax.Array:
        g = jax.lax.all_gather(local, axis, axis=0, tiled=True)
        return g.reshape(1, -1)

    sharded = jax.shard_map(
        _shard_body,
        mesh=mesh,
        in_specs=P(axis, None),
        out_specs=P(axis, None),
    )
    return jax.jit(sharded)


def make_alltoall_step(mesh: Mesh):
    """The mesh all-to-all lowering: block i of row r lands on row i.

    Twin of the C++ pairwise-exchange all-to-all (lower rank initiates,
    the reply carries the reciprocal block). Returns a jitted step:
    uint32[n, n*block] -> uint32[n, n*block] where the output row r is
    the concatenation of every rank's block-for-r.
    """
    axis = mesh.axis_names[0]

    n = mesh.shape[axis]

    def _shard_body(local: jax.Array) -> jax.Array:
        # local: uint32[1, n*block] -> [n, block] blocks by destination;
        # tiled all_to_all swaps block j of rank r with block r of rank j.
        blocks = local.reshape(n, -1)
        exchanged = jax.lax.all_to_all(
            blocks, axis, split_axis=0, concat_axis=0, tiled=True
        )
        return exchanged.reshape(1, -1)

    sharded = jax.shard_map(
        _shard_body,
        mesh=mesh,
        in_specs=P(axis, None),
        out_specs=P(axis, None),
    )
    return jax.jit(sharded)


def make_partition_echo_step(mesh: Mesh):
    """PartitionChannel sharding lowered to XLA: each peer owns one shard.

    The C++ PartitionChannel (cpp/trpc/combo_channels.h; reference
    src/brpc/partition_channel.h:34) splits one logical service across M
    partitions and fans every call out to all of them, merging the
    responses. On a mesh that IS sharded computation: requests are laid
    out with one partition per device (jax.sharding), each device serves
    its shard (frame checksum + echo), and the "merge" is the sharded
    output itself — XLA inserts the collectives only where the layout
    demands them.

    Returns a jitted step: uint32[n_parts, words] ->
    (uint32[n_parts], uint32[n_parts, words], uint32[]): per-partition
    checksums, echoed shards, and the cluster-wide merged integrity word
    (the psum that rides ICI on hardware).
    """
    axis = mesh.axis_names[0]

    def _shard_body(local: jax.Array):
        # local: uint32[parts_per_device, words] — this device's shard.
        check = _adler_frame_checksum(local)
        # Cross-partition integrity word (the fan-out's merged status):
        # one psum over ICI, the cheapest possible "ResponseMerger".
        total = jax.lax.psum(jnp.sum(check, dtype=jnp.uint32), axis)
        return check, local, total

    sharded = jax.shard_map(
        _shard_body,
        mesh=mesh,
        in_specs=P(axis, None),
        out_specs=(P(axis), P(axis, None), P()),
    )
    return jax.jit(sharded)
