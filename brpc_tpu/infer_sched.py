"""Continuous micro-batching scheduler — the pure-logic twin of
examples/infer_server.cc's BatchScheduler (ISSUE 17).

Same policy, no RPC stack: membership is recomputed BETWEEN device
steps (finished sequences leave, admitted ones join immediately — no
batch-boundary barriers), ordered priority-descending, with stalled
consumers preempted (a sequence whose sink hasn't drained its last
grant yields its slot instead of growing a queue) and an optional
per-tenant slot cap so one tenant can't own the whole batch.

Unit-tested in tests/test_infer_sched.py; `simulate()` is the analytic
side of bench.py's infer_scrape round — it predicts the batched vs
unbatched tokens/s ratio the live binary must reproduce.
"""
from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class Sequence:
    """One admitted generation request."""

    key: str
    total: int                    # tokens to produce
    tenant: str = "default"
    priority: int = 4             # 0 = most sheddable .. 7 = protected
    granted: int = 0              # tokens the scheduler has granted
    drained: int = 0              # tokens the consumer has taken
    resume_from: int = 0          # client floor at (re)open

    def __post_init__(self) -> None:
        # Post-restart resume: regenerate from the client's floor.
        self.granted = max(self.granted, self.resume_from)
        self.drained = max(self.drained, self.resume_from)

    @property
    def done(self) -> bool:
        return self.granted >= self.total

    @property
    def stalled(self) -> bool:
        """Consumer behind its grants: no new slot until it catches up."""
        return self.granted > self.drained


@dataclass
class StepReport:
    """What one device step served."""

    batch: list = field(default_factory=list)  # sequences granted a token
    preempted: int = 0                         # stalled slot losses


class MicroBatchScheduler:
    """Continuous micro-batching: one token per member per step."""

    def __init__(self, max_batch: int = 8, tenant_batch_cap: int = 0,
                 unbatched: bool = False) -> None:
        self.max_batch = max_batch
        self.tenant_batch_cap = tenant_batch_cap
        self.unbatched = unbatched
        self.pool: list[Sequence] = []
        self.steps = 0
        self.tokens = 0
        self.preempted = 0

    def admit(self, seq: Sequence) -> None:
        """Join the pool; eligible for the very next step."""
        self.pool.append(seq)

    def form_batch(self) -> StepReport:
        """Membership for the next step (examples/infer_server.cc
        FormBatch): priority-descending stable order, stalled consumers
        preempted, per-tenant seats capped."""
        rep = StepReport()
        width = 1 if self.unbatched else self.max_batch
        seats: dict[str, int] = {}
        order = sorted(self.pool, key=lambda s: -s.priority)
        for seq in order:
            if len(rep.batch) >= width:
                break
            if seq.done:
                continue
            if seq.stalled:
                rep.preempted += 1
                continue
            if self.tenant_batch_cap > 0:
                held = seats.get(seq.tenant, 0)
                if held >= self.tenant_batch_cap:
                    continue
                seats[seq.tenant] = held + 1
            rep.batch.append(seq)
        return rep

    def step(self) -> StepReport:
        """One device step: grant one token to every batch member, then
        reap finished sequences — continuous, not batch-bounded."""
        rep = self.form_batch()
        for seq in rep.batch:
            seq.granted += 1
        self.steps += 1 if rep.batch else 0
        self.tokens += len(rep.batch)
        self.preempted += rep.preempted
        self.pool = [s for s in self.pool if not s.done]
        return rep


def simulate(n_seqs: int, tokens_each: int, max_batch: int = 8,
             unbatched: bool = False, step_us: int = 2000) -> dict:
    """Closed-form-ish throughput model for bench.py's infer_scrape:
    run n_seqs identical sequences to completion with an always-ready
    consumer; report steps, tokens and tokens/s at the given step cost.
    Batched serving amortizes the step across the batch width — the
    tokens/s ratio vs unbatched approaches min(n_seqs, max_batch)."""
    sched = MicroBatchScheduler(max_batch=max_batch, unbatched=unbatched)
    for i in range(n_seqs):
        sched.admit(Sequence(key=f"k{i}", total=tokens_each))
    while sched.pool:
        rep = sched.step()
        for seq in rep.batch:      # always-ready consumer
            seq.drained = seq.granted
    secs = sched.steps * step_us / 1e6
    return {
        "steps": sched.steps,
        "tokens": sched.tokens,
        "tokens_per_s": sched.tokens / secs if secs > 0 else 0.0,
    }
