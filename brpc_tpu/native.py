"""ctypes bridge to the C++ framework (libtpurpc.so).

This is how the JAX side drives the FRAMEWORK's own code — tpu_std
framing (cpp/trpc/policy_tpu_std.cc), crc32c (cpp/tbase/crc32c.cc), and
registered-memory staging buffers (cpp/tici/block_pool.cc) — instead of a
Python re-implementation. dryrun_multichip and the device-path benchmark
both route every payload through these entry points, so a C++ framing or
checksum regression fails the multi-chip validation.

Reference parity: the RDMA build's block_pool.h hands registered memory
to the transport; here the same pool stages bytes that jax.device_put
DMAs to HBM.
"""
from __future__ import annotations

import ctypes
from pathlib import Path

import numpy as np

_REPO = Path(__file__).resolve().parent.parent
_LIB = None


def lib() -> ctypes.CDLL:
    global _LIB
    if _LIB is None:
        so = _REPO / "build" / "libtpurpc.so"
        if not so.exists():
            raise FileNotFoundError(
                f"{so} not built; run cmake/ninja first (bench.py build())"
            )
        L = ctypes.CDLL(str(so))
        L.tpurpc_global_init.restype = ctypes.c_int
        L.tpurpc_crc32c.restype = ctypes.c_uint32
        L.tpurpc_crc32c.argtypes = [ctypes.c_uint32, ctypes.c_void_p,
                                    ctypes.c_size_t]
        L.tpurpc_block_alloc.restype = ctypes.c_void_p
        L.tpurpc_block_alloc.argtypes = [ctypes.c_size_t]
        L.tpurpc_block_free.argtypes = [ctypes.c_void_p]
        L.tpurpc_block_is_registered.restype = ctypes.c_int
        L.tpurpc_block_is_registered.argtypes = [ctypes.c_void_p]
        L.tpurpc_frame.restype = ctypes.c_long
        L.tpurpc_frame.argtypes = [ctypes.c_uint64, ctypes.c_void_p,
                                   ctypes.c_size_t, ctypes.c_void_p,
                                   ctypes.c_size_t]
        L.tpurpc_unframe.restype = ctypes.c_long
        L.tpurpc_unframe.argtypes = [
            ctypes.c_void_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_size_t),
            ctypes.POINTER(ctypes.c_size_t),
        ]
        if L.tpurpc_global_init() != 0:
            raise RuntimeError("tpurpc_global_init failed")
        _LIB = L
    return _LIB


def crc32c(data: bytes | np.ndarray, init: int = 0) -> int:
    buf = np.ascontiguousarray(data).view(np.uint8) if isinstance(
        data, np.ndarray) else np.frombuffer(data, dtype=np.uint8)
    return int(lib().tpurpc_crc32c(
        init, buf.ctypes.data_as(ctypes.c_void_p), buf.nbytes))


class PoolBuffer:
    """A staging buffer carved from the registered ICI block pool,
    exposed to numpy/JAX zero-copy via the buffer protocol."""

    def __init__(self, nbytes: int):
        self._ptr = lib().tpurpc_block_alloc(nbytes)
        if not self._ptr:
            raise MemoryError(f"pool alloc of {nbytes} bytes failed")
        self.nbytes = nbytes
        self.registered = bool(
            lib().tpurpc_block_is_registered(self._ptr))
        self.array = np.ctypeslib.as_array(
            ctypes.cast(self._ptr, ctypes.POINTER(ctypes.c_uint8)),
            shape=(nbytes,),
        )

    def free(self):
        if self._ptr:
            lib().tpurpc_block_free(self._ptr)
            self._ptr = None
            self.array = None


def frame(correlation_id: int, payload: np.ndarray,
          out: np.ndarray | None = None) -> np.ndarray:
    """tpu_std-frame `payload` (any contiguous array) via the C++
    framework; returns a uint8 view of the frame (in `out` if given)."""
    pay = np.ascontiguousarray(payload).view(np.uint8).reshape(-1)
    cap = pay.nbytes + 1024
    if out is None:
        out = np.empty(cap, dtype=np.uint8)
    elif out.nbytes < cap:
        raise ValueError("out buffer too small")
    n = lib().tpurpc_frame(
        correlation_id, pay.ctypes.data_as(ctypes.c_void_p), pay.nbytes,
        out.ctypes.data_as(ctypes.c_void_p), out.nbytes)
    if n < 0:
        raise ValueError("tpurpc_frame failed")
    return out[:n]


def unframe(buf: np.ndarray) -> tuple[int, np.ndarray, int]:
    """Parse + checksum-verify ONE frame via the C++ framework.
    Returns (correlation_id, payload bytes (a view into buf), consumed)."""
    b = np.ascontiguousarray(buf).view(np.uint8).reshape(-1)
    cid = ctypes.c_uint64()
    off = ctypes.c_size_t()
    length = ctypes.c_size_t()
    n = lib().tpurpc_unframe(
        b.ctypes.data_as(ctypes.c_void_p), b.nbytes,
        ctypes.byref(cid), ctypes.byref(off), ctypes.byref(length))
    if n == -1:
        raise ValueError("incomplete frame")
    if n < 0:
        raise ValueError("corrupt frame (bad magic/meta/crc32c)")
    return int(cid.value), b[off.value:off.value + length.value], int(n)
