"""ctypes bridge to the C++ framework (libtpurpc.so).

This is how the JAX side drives the FRAMEWORK's own code — tpu_std
framing (cpp/trpc/policy_tpu_std.cc), crc32c (cpp/tbase/crc32c.cc), and
registered-memory staging buffers (cpp/tici/block_pool.cc) — instead of a
Python re-implementation. dryrun_multichip and the device-path benchmark
both route every payload through these entry points, so a C++ framing or
checksum regression fails the multi-chip validation.

Reference parity: the RDMA build's block_pool.h hands registered memory
to the transport; here the same pool stages bytes that jax.device_put
DMAs to HBM.
"""
from __future__ import annotations

import ctypes
from pathlib import Path

import numpy as np

_REPO = Path(__file__).resolve().parent.parent
_LIB = None


def lib() -> ctypes.CDLL:
    global _LIB
    if _LIB is None:
        so = _REPO / "build" / "libtpurpc.so"
        if not so.exists():
            raise FileNotFoundError(
                f"{so} not built; run cmake/ninja first (bench.py build())"
            )
        L = ctypes.CDLL(str(so))
        L.tpurpc_global_init.restype = ctypes.c_int
        L.tpurpc_crc32c.restype = ctypes.c_uint32
        L.tpurpc_crc32c.argtypes = [ctypes.c_uint32, ctypes.c_void_p,
                                    ctypes.c_size_t]
        L.tpurpc_block_alloc.restype = ctypes.c_void_p
        L.tpurpc_block_alloc.argtypes = [ctypes.c_size_t]
        L.tpurpc_block_free.argtypes = [ctypes.c_void_p]
        L.tpurpc_block_is_registered.restype = ctypes.c_int
        L.tpurpc_block_is_registered.argtypes = [ctypes.c_void_p]
        L.tpurpc_slab_allocated.restype = ctypes.c_long
        L.tpurpc_slab_recycled.restype = ctypes.c_long
        L.tpurpc_pool_id.restype = ctypes.c_uint64
        L.tpurpc_ring_create.restype = ctypes.c_void_p
        L.tpurpc_ring_create.argtypes = [ctypes.c_uint32, ctypes.c_size_t]
        L.tpurpc_ring_destroy.argtypes = [ctypes.c_void_p]
        L.tpurpc_ring_acquire.restype = ctypes.c_int
        L.tpurpc_ring_acquire.argtypes = [ctypes.c_void_p, ctypes.c_long]
        L.tpurpc_ring_complete.restype = ctypes.c_int
        L.tpurpc_ring_complete.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
        L.tpurpc_ring_abort.argtypes = [ctypes.c_void_p]
        L.tpurpc_ring_aborted.restype = ctypes.c_int
        L.tpurpc_ring_aborted.argtypes = [ctypes.c_void_p]
        L.tpurpc_lease_pinned.restype = ctypes.c_uint64
        L.tpurpc_lease_reaped.restype = ctypes.c_uint64
        L.tpurpc_pool_epoch.restype = ctypes.c_uint64
        L.tpurpc_transport_tier_count.restype = ctypes.c_int
        L.tpurpc_transport_tier_name.restype = ctypes.c_long
        L.tpurpc_transport_tier_name.argtypes = [
            ctypes.c_int, ctypes.c_char_p, ctypes.c_size_t]
        L.tpurpc_transport_tier_descriptor_capable.restype = ctypes.c_int
        L.tpurpc_transport_tier_descriptor_capable.argtypes = [ctypes.c_int]
        L.tpurpc_transport_tier_zero_copy.restype = ctypes.c_int
        L.tpurpc_transport_tier_zero_copy.argtypes = [ctypes.c_int]
        L.tpurpc_transport_tier_cross_process.restype = ctypes.c_int
        L.tpurpc_transport_tier_cross_process.argtypes = [ctypes.c_int]
        L.tpurpc_transport_tier_ops.restype = ctypes.c_long
        L.tpurpc_transport_tier_ops.argtypes = [ctypes.c_int]
        L.tpurpc_transport_tier_one_sided.restype = ctypes.c_int
        L.tpurpc_transport_tier_one_sided.argtypes = [ctypes.c_int]
        L.tpurpc_transport_tier_sgl_max.restype = ctypes.c_long
        L.tpurpc_transport_tier_sgl_max.argtypes = [ctypes.c_int]
        for fn in ("posted", "completed", "bytes", "stale_rejects",
                   "cq_parks", "windows", "pending"):
            getattr(L, f"tpurpc_verbs_{fn}").restype = ctypes.c_long
        L.tpurpc_ring_slot.restype = ctypes.c_void_p
        L.tpurpc_ring_slot.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
        L.tpurpc_ring_slot_bytes.restype = ctypes.c_size_t
        L.tpurpc_ring_slot_bytes.argtypes = [ctypes.c_void_p]
        L.tpurpc_ring_depth.restype = ctypes.c_uint32
        L.tpurpc_ring_depth.argtypes = [ctypes.c_void_p]
        L.tpurpc_ring_registered.restype = ctypes.c_int
        L.tpurpc_ring_registered.argtypes = [ctypes.c_void_p]
        L.tpurpc_ring_inflight_highwater.restype = ctypes.c_uint64
        L.tpurpc_ring_inflight_highwater.argtypes = [ctypes.c_void_p]
        L.tpurpc_frame.restype = ctypes.c_long
        L.tpurpc_frame.argtypes = [ctypes.c_uint64, ctypes.c_void_p,
                                   ctypes.c_size_t, ctypes.c_void_p,
                                   ctypes.c_size_t]
        L.tpurpc_frame_in_place.restype = ctypes.c_long
        L.tpurpc_frame_in_place.argtypes = [
            ctypes.c_uint64, ctypes.c_void_p, ctypes.c_size_t,
            ctypes.c_size_t, ctypes.POINTER(ctypes.c_size_t),
            ctypes.POINTER(ctypes.c_uint32),
        ]
        L.tpurpc_unframe.restype = ctypes.c_long
        L.tpurpc_unframe.argtypes = [
            ctypes.c_void_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_size_t),
            ctypes.POINTER(ctypes.c_size_t),
        ]
        if L.tpurpc_global_init() != 0:
            raise RuntimeError("tpurpc_global_init failed")
        _LIB = L
    return _LIB


def crc32c(data: bytes | np.ndarray, init: int = 0) -> int:
    buf = np.ascontiguousarray(data).view(np.uint8) if isinstance(
        data, np.ndarray) else np.frombuffer(data, dtype=np.uint8)
    return int(lib().tpurpc_crc32c(
        init, buf.ctypes.data_as(ctypes.c_void_p), buf.nbytes))


class PoolBuffer:
    """A staging buffer carved from the registered ICI block pool,
    exposed to numpy/JAX zero-copy via the buffer protocol."""

    def __init__(self, nbytes: int):
        self._ptr = lib().tpurpc_block_alloc(nbytes)
        if not self._ptr:
            raise MemoryError(f"pool alloc of {nbytes} bytes failed")
        self.nbytes = nbytes
        self.registered = bool(
            lib().tpurpc_block_is_registered(self._ptr))
        self.array = np.ctypeslib.as_array(
            ctypes.cast(self._ptr, ctypes.POINTER(ctypes.c_uint8)),
            shape=(nbytes,),
        )

    def free(self):
        if self._ptr:
            lib().tpurpc_block_free(self._ptr)
            self._ptr = None
            self.array = None


def pool_id() -> int:
    """Descriptor identity of this process's shared pool (0 = none)."""
    return int(lib().tpurpc_pool_id())


def pool_epoch() -> int:
    """Current generation of this process's pool mapping (epoch fence)."""
    return int(lib().tpurpc_pool_epoch())


def lease_counters() -> tuple[int, int]:
    """(live pinned blocks, reaped pins) — the leak evidence bench.py
    records after every round (a healthy round ends pinned == 0)."""
    L = lib()
    return int(L.tpurpc_lease_pinned()), int(L.tpurpc_lease_reaped())


def transport_tiers() -> list[dict]:
    """The first-class Transport registry (ISSUE 12): one dict per
    registered endpoint type with its capability bits and op count —
    the uniform tcp/ici/shm_xproc/device tier story, introspected
    straight from the C++ seam."""
    L = lib()
    tiers = []
    name = ctypes.create_string_buffer(64)
    for t in range(int(L.tpurpc_transport_tier_count())):
        if L.tpurpc_transport_tier_name(t, name, len(name)) < 0:
            continue
        tiers.append({
            "name": name.value.decode(),
            "descriptor_capable": bool(
                L.tpurpc_transport_tier_descriptor_capable(t)),
            "zero_copy": bool(L.tpurpc_transport_tier_zero_copy(t)),
            "cross_process": bool(
                L.tpurpc_transport_tier_cross_process(t)),
            "one_sided": bool(L.tpurpc_transport_tier_one_sided(t)),
            "sgl_max": int(L.tpurpc_transport_tier_sgl_max(t)),
            "ops": int(L.tpurpc_transport_tier_ops(t)),
        })
    return tiers


def verbs_counters() -> dict:
    """One-sided verb plane counters (ISSUE 18): posted/completed verbs,
    bytes moved, stale-epoch rejects, CQ parks, plus the live window and
    pending-post gauges (leak evidence: a clean shutdown ends with
    windows == 0 and pending == 0)."""
    L = lib()
    return {
        "posted": int(L.tpurpc_verbs_posted()),
        "completed": int(L.tpurpc_verbs_completed()),
        "bytes": int(L.tpurpc_verbs_bytes()),
        "stale_rejects": int(L.tpurpc_verbs_stale_rejects()),
        "cq_parks": int(L.tpurpc_verbs_cq_parks()),
        "windows": int(L.tpurpc_verbs_windows()),
        "pending": int(L.tpurpc_verbs_pending()),
    }


class RingAbortedError(RuntimeError):
    """The staging ring was poisoned (device stream error / shutdown):
    parked acquires unblock with this instead of wedging forever."""


def slab_counters() -> tuple[int, int]:
    """(live slab slots, recycled-allocation count) — the zero-copy /
    recycle evidence the device-ring tests assert on."""
    L = lib()
    return int(L.tpurpc_slab_allocated()), int(L.tpurpc_slab_recycled())


class DeviceStagingRing:
    """Depth-N ring of registered staging slots (C++ DeviceStagingRing):
    the pipelined device path stages chunk i+1 while chunk i computes
    and chunk i-1 drains. acquire() hands slots out in FIFO order and
    blocks while all are in flight; complete() releases them."""

    def __init__(self, depth: int, slot_bytes: int):
        self._ptr = lib().tpurpc_ring_create(depth, slot_bytes)
        if not self._ptr:
            raise MemoryError(
                f"ring create ({depth} x {slot_bytes}B) failed")
        self.depth = int(lib().tpurpc_ring_depth(self._ptr))
        self.slot_bytes = int(lib().tpurpc_ring_slot_bytes(self._ptr))
        self.registered = bool(lib().tpurpc_ring_registered(self._ptr))
        self.slots = []
        for i in range(self.depth):
            p = lib().tpurpc_ring_slot(self._ptr, i)
            self.slots.append(np.ctypeslib.as_array(
                ctypes.cast(p, ctypes.POINTER(ctypes.c_uint8)),
                shape=(self.slot_bytes,)))

    def acquire(self, timeout_us: int = -1) -> int:
        slot = int(lib().tpurpc_ring_acquire(self._ptr, timeout_us))
        if slot == -2:
            raise RingAbortedError("ring aborted (poisoned)")
        if slot < 0:
            raise TimeoutError("ring acquire timed out")
        return slot

    def complete(self, slot: int) -> None:
        if lib().tpurpc_ring_complete(self._ptr, slot) != 0:
            raise ValueError(f"slot {slot} not in flight")

    def abort(self) -> None:
        """Poison the ring: every parked and future acquire raises
        RingAbortedError immediately (device-error escape hatch)."""
        lib().tpurpc_ring_abort(self._ptr)

    @property
    def aborted(self) -> bool:
        return bool(lib().tpurpc_ring_aborted(self._ptr))

    @property
    def inflight_highwater(self) -> int:
        return int(lib().tpurpc_ring_inflight_highwater(self._ptr))

    def close(self) -> None:
        if self._ptr:
            lib().tpurpc_ring_destroy(self._ptr)
            self._ptr = None
            self.slots = []


def _within(buf: np.ndarray, payload: np.ndarray) -> bool:
    b0 = buf.ctypes.data
    p0 = payload.ctypes.data
    return b0 <= p0 and p0 + payload.nbytes <= b0 + buf.nbytes


def frame(correlation_id: int, payload: np.ndarray,
          out: np.ndarray | None = None) -> np.ndarray:
    """tpu_std-frame `payload` (any contiguous array) via the C++
    framework; returns a uint8 view of the frame (in `out` if given).

    Fast path (ISSUE 9 satellite): when `payload` is itself a view INTO
    `out` (already staged inside the destination pool buffer, at offset
    >= 64), the payload bytes are NOT copied — the header+meta is
    written in place right before them and the returned frame view ends
    exactly at the payload's end."""
    pay = np.ascontiguousarray(payload).view(np.uint8).reshape(-1)
    if out is not None and _within(out, pay):
        off = pay.ctypes.data - out.ctypes.data
        if off >= IN_PLACE_HEADROOM:
            frame_off, n, _ = frame_in_place(correlation_id, out, off,
                                             pay.nbytes)
            return out[frame_off:frame_off + n]
        # Payload sits too close to the buffer start for an in-place
        # header: fall through to the copy path (tpurpc_frame memmoves
        # overlapping sources safely).
    cap = pay.nbytes + 1024
    if out is None:
        out = np.empty(cap, dtype=np.uint8)
    elif out.nbytes < cap:
        raise ValueError("out buffer too small")
    n = lib().tpurpc_frame(
        correlation_id, pay.ctypes.data_as(ctypes.c_void_p), pay.nbytes,
        out.ctypes.data_as(ctypes.c_void_p), out.nbytes)
    if n < 0:
        raise ValueError("tpurpc_frame failed")
    return out[:n]


# Staging offset leaving room for header+meta of any in-place frame
# (12-byte header + ~30B meta pb, rounded way up).
IN_PLACE_HEADROOM = 64


def frame_in_place(correlation_id: int, buf: np.ndarray, payload_off: int,
                   payload_len: int) -> tuple[int, int, int]:
    """Frame a payload that already resides at buf[payload_off:...]:
    writes header+meta right-justified before it (no payload memcpy).
    Returns (frame_off, frame_len, payload_crc32c) — the crc is the one
    embedded in the frame meta, handed back so the caller can verify
    round-tripped payload bytes without re-parsing."""
    b = buf.view(np.uint8).reshape(-1)
    frame_off = ctypes.c_size_t()
    crc = ctypes.c_uint32()
    n = lib().tpurpc_frame_in_place(
        correlation_id, b.ctypes.data_as(ctypes.c_void_p), payload_off,
        payload_len, ctypes.byref(frame_off), ctypes.byref(crc))
    if n < 0:
        raise ValueError("tpurpc_frame_in_place failed (headroom < meta)")
    return int(frame_off.value), int(n), int(crc.value)


def unframe(buf: np.ndarray) -> tuple[int, np.ndarray, int]:
    """Parse + checksum-verify ONE frame via the C++ framework.
    Returns (correlation_id, payload bytes (a view into buf), consumed)."""
    b = np.ascontiguousarray(buf).view(np.uint8).reshape(-1)
    cid = ctypes.c_uint64()
    off = ctypes.c_size_t()
    length = ctypes.c_size_t()
    n = lib().tpurpc_unframe(
        b.ctypes.data_as(ctypes.c_void_p), b.nbytes,
        ctypes.byref(cid), ctypes.byref(off), ctypes.byref(length))
    if n == -1:
        raise ValueError("incomplete frame")
    if n < 0:
        raise ValueError("corrupt frame (bad magic/meta/crc32c)")
    return int(cid.value), b[off.value:off.value + length.value], int(n)
