"""brpc_tpu: a TPU-native RPC framework with the capabilities of Apache bRPC.

Layers (mirroring SURVEY.md's layer map, re-designed TPU-first):
  - C++ core (cpp/): zero-copy IOBuf, M:N fiber runtime, wait-free socket
    write path, framed protocols, client/server stacks, metrics, portal.
  - brpc_tpu.parallel: the collective data plane — ParallelChannel /
    PartitionChannel fan-out lowered to XLA collectives over a jax Mesh.
"""

__version__ = "0.1.0"
