"""Device data path: framed payloads round-trip host -> HBM -> host.

The payload is framed by the C++ framework (tpu_std wire format +
crc32c, via brpc_tpu/native.py -> libtpurpc.so) into a staging buffer
carved from the REGISTERED ICI block pool (cpp/tici/block_pool.cc), then
DMA'd to the device (jax.device_put), touched by an on-device integrity
reduction (the frame-checksum computation from collective_echo), copied
back, and re-parsed + crc32c-verified by the C++ framework. That is the
transport seam the reference's RDMA endpoint implements with
ibv_post_send out of its registered block pool
(/root/reference/src/brpc/rdma/rdma_endpoint.cpp:777 CutFromIOBufList):
device DMA reading straight from pool-registered frame bytes.

Run as a module for one JSON line (bench.py merges it):
    python -m brpc_tpu.device_path [payload_mb] [reps]
"""
import json
import sys
import time

import numpy as np


def run(payload_mb: int = 4, reps: int = 5) -> dict:
    from brpc_tpu import native

    import jax
    import jax.numpy as jnp

    from brpc_tpu.parallel.collective_echo import _adler_frame_checksum

    dev = jax.devices()[0]
    nbytes = payload_mb << 20
    payload = np.arange(nbytes // 4, dtype=np.uint32)
    staging = native.PoolBuffer(nbytes + 4096)

    # Frame ONCE into pool memory; the device reads the framed bytes.
    frame = native.frame(0xD00D, payload, out=staging.array)
    frame_len = len(frame)
    padded_words = (frame_len + 3) // 4
    # uint32 view of the (padded) frame inside the registered buffer.
    fr_u32 = staging.array[: padded_words * 4].view(np.uint32)

    @jax.jit
    def touch(x):
        # On-device integrity word over the framed bytes: proves compute
        # read them on the device, not just DMA'd through.
        return x, _adler_frame_checksum(x[None, :])[0]

    # Warmup (compile + first transfer).
    x = jax.device_put(fr_u32, dev)
    y, dev_check = touch(x)
    jax.block_until_ready((y, dev_check))

    t0 = time.monotonic()
    for _ in range(reps):
        x = jax.device_put(fr_u32, dev)
        y, dev_check = touch(x)
        jax.block_until_ready((y, dev_check))
        back = np.asarray(y)
    dt = time.monotonic() - t0

    # C++ framework parses + crc32c-verifies the bytes that came back.
    cid, pay, _ = native.unframe(back.view(np.uint8)[:frame_len])
    ok = cid == 0xD00D and np.array_equal(pay.view(np.uint32), payload)

    # Cross-check the on-device integrity word against the host.
    host_check = int(
        jax.jit(lambda x: _adler_frame_checksum(x[None, :])[0],
                backend="cpu")(fr_u32)
    ) if dev.platform != "cpu" else int(dev_check)
    ok = ok and int(dev_check) == host_check

    # Bytes cross host->device and device->host once per rep.
    mbps = (2 * frame_len * reps / dt) / 1e6
    return {
        "device_path_mbps": round(mbps, 1),
        "device_path_ok": bool(ok),
        "device_path_registered_staging": bool(staging.registered),
        "device_path_device": f"{dev.platform}:{dev.device_kind}",
    }


if __name__ == "__main__":
    mb = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    reps = int(sys.argv[2]) if len(sys.argv) > 2 else 5
    print(json.dumps(run(mb, reps)))
