"""Device data path: framed payloads stream host -> HBM -> host through
a pipelined DMA staging ring.

PR-8 (ISSUE 9) rebuilt this module around `DeviceStagingRing`
(cpp/tici/block_pool.cc, exported through cpp/trpc/c_api.cc): the
payload is cut into chunks, each chunk staged into a depth-N ring of
registered pool slots and framed IN PLACE by the C++ framework (header
+ meta written right before the payload — no payload memcpy,
brpc_tpu/native.frame_in_place), so that H2D of chunk i+1, the
on-device integrity kernel on chunk i, and D2H + crc32c verification of
chunk i-1 overlap. That is the transport seam the reference's RDMA
endpoint implements with ibv_post_send out of its registered block pool
(rdma_endpoint.cpp CutFromIOBufList): device DMA reading straight from
pool-registered frame bytes, several transfers in flight.

The serial baseline (the retired `device_path_mbps` loop: device_put ->
compute -> block -> copy-back per chunk, nothing in flight) runs over
the same chunks; `device_path_overlap_eff` = pipelined / serial
throughput is the overlap win the ring buys.

Run as a module for one JSON line (bench.py merges it):
    python -m brpc_tpu.device_path [payload_mb] [reps] [ring_depth] [chunk_kb]
"""
import json
import os
import sys
import time
from collections import deque
from functools import lru_cache

import numpy as np

# In-place frame headroom per slot (importing brpc_tpu.native does NOT
# load the shared library — that happens lazily at the first call).
from brpc_tpu.native import IN_PLACE_HEADROOM as HEADROOM


def _integrity_word(words):
    """Order-sensitive integrity word over uint32 words: a weighted
    wraparound sum (odd per-position multipliers, so swapping any two
    distinct words changes the result). Unlike the adler scan used by
    collective_echo, this is ONE fused multiply-reduce pass — it maps to
    vector units instead of a sequential cumsum, keeping the on-device
    integrity check off the pipeline's critical path."""
    import jax.numpy as jnp

    idx = jnp.arange(words.shape[-1], dtype=jnp.uint32)
    return jnp.sum(words * (idx * jnp.uint32(2) + jnp.uint32(1)),
                   dtype=jnp.uint32)


@lru_cache(maxsize=4)
def _touch_kernel(chunk_words: int, platform: str):
    """Persistent jitted integrity kernel over one chunk: returns the
    chunk (identity) and its integrity word — proves on-device compute
    READ the bytes, not just DMA'd them through. Donation lets XLA reuse
    the input buffer on real devices (no per-chunk allocation); the CPU
    backend ignores donation, so it is only requested off-cpu."""
    import jax

    def touch(x):
        return x, _integrity_word(x)

    if platform == "cpu":
        return jax.jit(touch)
    return jax.jit(touch, donate_argnums=0)


def _h2d(view: np.ndarray, dev):
    """Import one staged slot view onto the device: dlpack zero-copy on
    host-backed platforms (the registered slot IS the device buffer), a
    real H2D DMA otherwise."""
    import jax

    if dev.platform == "cpu":
        try:
            return jax.dlpack.from_dlpack(view)
        except Exception:
            pass
    return jax.device_put(view, dev)


class _ChunkPipeline:
    """Drives the staging ring at a given depth.

    copy_mode=True reproduces the RETIRED device_path_mbps loop shape
    per chunk — frame() with the payload memcpy, device_put (always a
    copy), full sync, fresh ndarray materialization, copy-back — run at
    depth 1 with nothing in flight. copy_mode=False is the ring path:
    payload staged once into the registered slot, framed IN PLACE
    (header+crc only), dlpack zero-copy import where the platform backs
    arrays with host memory, donated device buffers elsewhere, and
    depth-N chunks in flight so H2D/compute/D2H of neighboring chunks
    overlap. The gap between the two is exactly what the ISSUE-9 ring
    buys: no per-RPC copies, no per-chunk sync."""

    def __init__(self, ring, chunks, dev, touch, depth, copy_mode):
        self.ring = ring
        self.chunks = chunks          # list of uint32 chunk arrays
        self.dev = dev
        self.touch = touch
        self.depth = depth
        self.copy_mode = copy_mode
        self.chunk_bytes = chunks[0].nbytes
        self.crcs = [0] * ring.depth  # staged crc per in-flight slot
        self.ok = True
        self.dev_checks = []

    # Never park forever on the ring (ISSUE 10c): a wedged device stream
    # (lost completion, dead driver) must surface as an error, not a hung
    # Python thread. 30s >> any sane per-chunk latency; on timeout the
    # ring is poisoned so every OTHER thread parked on it unblocks too.
    ACQUIRE_TIMEOUT_US = 30_000_000

    def _launch(self, k):
        import jax
        from brpc_tpu import native
        try:
            slot = self.ring.acquire(self.ACQUIRE_TIMEOUT_US)
        except TimeoutError:
            self.ring.abort()
            raise RuntimeError(
                "staging-ring acquire timed out (lost completion or "
                "wedged device stream); ring aborted") from None
        sa = self.ring.slots[slot]
        clen = self.chunk_bytes
        if self.copy_mode:
            # Old path: frame() memcpys the external payload into the
            # staging buffer, then device_put copies it again.
            fr = native.frame(k + 1, self.chunks[k], out=sa)
            foff, flen = 0, len(fr)
            poff = flen - clen
            x = jax.device_put(sa[poff:poff + clen].view(np.uint32),
                               self.dev)
        else:
            # Ring path: stage the chunk payload once, frame in place
            # (no payload memcpy — ISSUE 9 satellite), import zero-copy.
            poff = HEADROOM
            np.copyto(sa[poff:poff + clen].view(np.uint32),
                      self.chunks[k])
            foff, flen, crc = native.frame_in_place(k + 1, sa, poff, clen)
            self.crcs[slot] = crc
            x = _h2d(sa[poff:poff + clen].view(np.uint32), self.dev)
        y, chk = self.touch(x)
        if not self.copy_mode and hasattr(y, "copy_to_host_async"):
            y.copy_to_host_async()
        return (k, slot, foff, flen, poff, y, chk)

    def _retire(self, item):
        from brpc_tpu import native
        k, slot, foff, flen, poff, y, chk = item
        sa = self.ring.slots[slot]
        if self.copy_mode:
            # Old path: block, MATERIALIZE a fresh ndarray, copy back
            # into staging, then have the framework re-parse + crc32c-
            # verify the whole frame around the returned payload.
            back = np.array(y)
            np.copyto(sa[poff:poff + self.chunk_bytes].view(np.uint32),
                      back)
            cid, _, _ = native.unframe(sa[foff:foff + flen])
            self.ok = self.ok and cid == k + 1
        else:
            # Ring path: the D2H buffer is verified DIRECTLY against the
            # crc32c the C++ framework embedded at frame time — per-chunk
            # integrity with no copy-back and no re-parse (the parse path
            # is exercised by the serial baseline and the native tests).
            back = np.asarray(y)  # blocks until the device is done
            self.ok = (self.ok and
                       native.crc32c(back) == self.crcs[slot])
        self.dev_checks.append(int(chk))
        self.ring.complete(slot)

    def run(self, reps):
        t0 = time.monotonic()
        inflight = deque()
        for _ in range(reps):
            for k in range(len(self.chunks)):
                inflight.append(self._launch(k))
                # Serial (depth=1): drain immediately — nothing overlaps.
                # Pipelined: keep `depth` chunks in flight; retiring the
                # oldest overlaps its D2H/verify with the younger chunks'
                # H2D + compute.
                while len(inflight) >= self.depth:
                    self._retire(inflight.popleft())
        while inflight:
            self._retire(inflight.popleft())
        return time.monotonic() - t0


def run(payload_mb: int = 4, reps: int = 5, ring_depth: int = 4,
        chunk_kb: int = 2044) -> dict:
    from brpc_tpu import native

    import jax

    dev = jax.devices()[0]
    chunk_bytes = (chunk_kb << 10) & ~4095
    n_chunks = max(1, (payload_mb << 20) // chunk_bytes)
    payload_bytes = n_chunks * chunk_bytes
    payload = np.arange(payload_bytes // 4, dtype=np.uint32)
    chunks = [payload[i * (chunk_bytes // 4):(i + 1) * (chunk_bytes // 4)]
              for i in range(n_chunks)]
    # Room for the in-place headroom (ring path) AND the copy-mode
    # frame() headroom contract (payload + 1024).
    slot_bytes = chunk_bytes + 1024

    touch = _touch_kernel(chunk_bytes // 4, dev.platform)

    def make_ring():
        return native.DeviceStagingRing(ring_depth, slot_bytes)

    # Warmup: compile + first transfers through a throwaway ring.
    warm = make_ring()
    _ChunkPipeline(warm, chunks, dev, touch, ring_depth, False).run(1)
    _ChunkPipeline(warm, chunks, dev, touch, 1, True).run(1)
    warm.close()

    # Serial baseline = the retired device_path_mbps loop shape (per-RPC
    # copies + full sync per chunk, nothing in flight); pipelined =
    # depth-N ring, in-place frames, zero-copy import, H2D/compute/D2H
    # of neighboring chunks overlapped. The two are INTERLEAVED rep by
    # rep and combined by median so shared-host scheduling noise hits
    # both paths alike instead of fabricating (or erasing) the gap.
    ring_s = make_ring()
    ring_p = make_ring()
    serial = _ChunkPipeline(ring_s, chunks, dev, touch, 1, True)
    pipe = _ChunkPipeline(ring_p, chunks, dev, touch, ring_depth, False)
    # Each timed sample spans `passes` full passes over the chunks so
    # the pipeline reaches steady state (fill/drain amortized); several
    # alternating samples -> median.
    passes = max(2, (4 * ring_depth + n_chunks - 1) // n_chunks)
    samples = max(3, reps // passes)
    serial_dts, pipe_dts = [], []
    for _ in range(samples):
        serial_dts.append(serial.run(passes) / passes)
        pipe_dts.append(pipe.run(passes) / passes)
    import statistics
    dt_serial = statistics.median(serial_dts) * reps
    dt_pipe = statistics.median(pipe_dts) * reps
    # Overlap efficiency from ADJACENT sample pairs: each ratio compares
    # a serial and a pipelined pass that ran back to back, so shared-host
    # cpu throttling (which swings absolute GB/s several-fold here)
    # cancels out of the ratio instead of fabricating or erasing the gap.
    overlap_eff = statistics.median(
        s / p for s, p in zip(serial_dts, pipe_dts))
    highwater = ring_p.inflight_highwater
    registered = ring_p.registered
    ring_s.close()
    ring_p.close()

    # On-device integrity words must agree between the two paths (same
    # chunks, same kernel), and off-cpu the first chunk's word is
    # cross-checked against an independent host (cpu-jit) computation.
    dev_ok = (len(pipe.dev_checks) == n_chunks * passes * samples and
              pipe.dev_checks[:n_chunks] == serial.dev_checks[:n_chunks])
    if dev.platform != "cpu":
        host_chk = int(jax.jit(_integrity_word,
                               backend="cpu")(chunks[0]))
        dev_ok = dev_ok and pipe.dev_checks[0] == host_chk
    ok = serial.ok and pipe.ok and dev_ok

    # Bytes cross host->device and device->host once per chunk per rep.
    gbps = 2.0 * payload_bytes * reps / dt_pipe / 1e9
    serial_gbps = 2.0 * payload_bytes * reps / dt_serial / 1e9
    return {
        "device_path_gbps": round(gbps, 3),
        "device_path_serial_gbps": round(serial_gbps, 3),
        "device_path_overlap_eff": round(overlap_eff, 2),
        "device_path_ring_depth": ring_depth,
        "device_path_chunk_bytes": chunk_bytes,
        "device_path_inflight_highwater": int(highwater),
        "device_path_ok": bool(ok),
        "device_path_registered_staging": bool(registered),
        "device_path_device": f"{dev.platform}:{dev.device_kind}",
        # Overlap needs a core for the device kernel next to the staging
        # thread: on single-core (or cgroup-throttled-to-one) hosts the
        # pipeline degenerates to the copy-elimination win alone.
        "device_path_cores": int(os.cpu_count() or 1),
    }


if __name__ == "__main__":
    mb = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    reps = int(sys.argv[2]) if len(sys.argv) > 2 else 5
    depth = int(sys.argv[3]) if len(sys.argv) > 3 else 4
    chunk_kb = int(sys.argv[4]) if len(sys.argv) > 4 else 1020
    print(json.dumps(run(mb, reps, depth, chunk_kb)))
