// N caller fibers issuing sync echoes back-to-back, with live QPS and
// latency percentiles (reference example/multi_threaded_echo_c++).
//   multi_threaded_echo_client HOST:PORT [fibers] [seconds] [payload_bytes]
#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <vector>

#include "bench_echo.pb.h"
#include "tbase/time.h"
#include "tfiber/fiber.h"
#include "trpc/channel.h"
#include "trpc/controller.h"
#include "tvar/latency_recorder.h"

using namespace tpurpc;

struct Ctx {
    benchpb::EchoService_Stub* stub;
    LatencyRecorder* lat;
    std::atomic<bool>* stop;
    std::atomic<int64_t>* calls;
    size_t payload;
};

static void* Caller(void* arg) {
    auto* c = (Ctx*)arg;
    IOBuf filler;
    filler.append(std::string(c->payload, 'e'));
    while (!c->stop->load(std::memory_order_relaxed)) {
        Controller cntl;
        cntl.set_timeout_ms(2000);
        benchpb::EchoRequest req;
        benchpb::EchoResponse res;
        req.set_send_ts_us(monotonic_time_us());
        cntl.request_attachment().append(filler);
        c->stub->Echo(&cntl, &req, &res, nullptr);
        if (!cntl.Failed()) {
            *c->lat << (monotonic_time_us() - res.send_ts_us());
            c->calls->fetch_add(1, std::memory_order_relaxed);
        }
    }
    return nullptr;
}

int main(int argc, char** argv) {
    if (argc < 2) {
        fprintf(stderr,
                "usage: %s HOST:PORT [fibers] [seconds] [payload_bytes]\n",
                argv[0]);
        return 2;
    }
    const int nfibers = argc > 2 ? atoi(argv[2]) : 16;
    const int seconds = argc > 3 ? atoi(argv[3]) : 5;
    const size_t payload = argc > 4 ? (size_t)atol(argv[4]) : 4096;
    Channel channel;
    ChannelOptions options;
    options.timeout_ms = 2000;
    if (channel.Init(argv[1], &options) != 0) return 1;
    benchpb::EchoService_Stub stub(&channel);
    LatencyRecorder lat;
    std::atomic<bool> stop{false};
    std::atomic<int64_t> calls{0};
    Ctx ctx{&stub, &lat, &stop, &calls, payload};
    std::vector<fiber_t> tids((size_t)nfibers);
    const int64_t t0 = monotonic_time_us();
    for (auto& tid : tids) fiber_start_background(&tid, nullptr, Caller, &ctx);
    for (int s = 0; s < seconds; ++s) {
        usleep(1000 * 1000);
        printf("t=%ds  calls=%lld  p50=%lldus  p99=%lldus\n", s + 1,
               (long long)calls.load(),
               (long long)lat.latency_percentile(0.5),
               (long long)lat.latency_percentile(0.99));
    }
    stop.store(true);
    for (auto tid : tids) fiber_join(tid, nullptr);
    const double secs = (double)(monotonic_time_us() - t0) / 1e6;
    printf("qps=%.0f  (%d fibers, %zuB payload)\n",
           (double)calls.load() / secs, nfibers, payload);
    return 0;
}
