// ParallelChannel fan-out (reference example/parallel_echo_c++): one
// call fans out to N sub-channels (here: N channels to one server; in
// production, N servers), and the responses merge.
//   parallel_echo_client HOST:PORT [nchannels]
#include <cstdio>
#include <cstdlib>
#include <vector>

#include "bench_echo.pb.h"
#include "tbase/time.h"
#include "trpc/combo_channels.h"
#include "trpc/controller.h"

using namespace tpurpc;

int main(int argc, char** argv) {
    if (argc < 2) {
        fprintf(stderr, "usage: %s HOST:PORT [nchannels]\n", argv[0]);
        return 2;
    }
    const int n = argc > 2 ? atoi(argv[2]) : 4;
    ParallelChannelOptions popts;
    popts.fail_limit = 1;  // any sub-failure fails the call
    popts.timeout_ms = 2000;
    ParallelChannel pchan(&popts);
    // Sub-channels are NOT owned by the combo (commonly shared).
    std::vector<std::unique_ptr<Channel>> subs;
    for (int i = 0; i < n; ++i) {
        subs.emplace_back(new Channel);
        ChannelOptions copts;
        copts.timeout_ms = 2000;
        if (subs.back()->Init(argv[1], &copts) != 0) return 1;
        // Default mapper/merger: same request to all, last response wins
        // (supply CallMapper/ResponseMerger for real scatter-gather).
        if (pchan.AddChannel(subs.back().get(), nullptr, nullptr) != 0) {
            return 1;
        }
    }
    benchpb::EchoService_Stub stub(&pchan);
    Controller cntl;
    benchpb::EchoRequest req;
    benchpb::EchoResponse res;
    req.set_send_ts_us(monotonic_time_us());
    stub.Echo(&cntl, &req, &res, nullptr);
    if (cntl.Failed()) {
        fprintf(stderr, "parallel call failed: %s\n",
                cntl.ErrorText().c_str());
        return 1;
    }
    printf("fan-out to %d sub-channels ok, rtt=%lldus\n", n,
           (long long)(monotonic_time_us() - res.send_ts_us()));
    return 0;
}
