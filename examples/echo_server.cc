// The canonical echo server (reference example/echo_c++/server.cpp):
// one pb service on one port, with the observability portal, gRPC/h2,
// HTTP-as-RPC json, and RESP riding the same listener. Optional flags:
//   echo_server [port] [--auto-concurrency] [--graceful]
// --graceful turns on -graceful_quit_on_sigterm: SIGTERM drains (GOAWAY
// broadcast, in-flight requests complete, then quit with code 0) and
// SIGUSR2 drains without quitting — the operator-facing zero-downtime
// path, no code required.
#include <cstdio>
#include <cstring>
#include <unistd.h>

#include "bench_echo.pb.h"
#include "tbase/flags.h"
#include "trpc/controller.h"
#include "trpc/redis.h"
#include "trpc/server.h"

using namespace tpurpc;

class EchoServiceImpl : public benchpb::EchoService {
public:
    void Echo(google::protobuf::RpcController* cntl_base,
              const benchpb::EchoRequest* request,
              benchpb::EchoResponse* response,
              google::protobuf::Closure* done) override {
        Controller* cntl = static_cast<Controller*>(cntl_base);
        response->set_send_ts_us(request->send_ts_us());
        if (request->has_payload()) response->set_payload(request->payload());
        // Bulk bytes ride the attachment, zero-copy.
        cntl->response_attachment().append(cntl->request_attachment());
        done->Run();
    }
};

int main(int argc, char** argv) {
    int port = 8002;
    ServerOptions options;
    for (int i = 1; i < argc; ++i) {
        if (strcmp(argv[i], "--auto-concurrency") == 0) {
            options.auto_concurrency = true;
        } else if (strcmp(argv[i], "--graceful") == 0) {
            SetFlagValue("graceful_quit_on_sigterm", "true");
        } else {
            port = atoi(argv[i]);
        }
    }
    EchoServiceImpl service;
    RedisService redis;  // same port also answers RESP (try redis-cli)
    redis.AddBasicKvCommands();
    Server server;
    if (server.AddService(&service) != 0) return 1;
    server.set_redis_service(&redis);
    if (server.Start(port, &options) != 0) {
        fprintf(stderr, "failed to listen on %d\n", port);
        return 1;
    }
    printf("EchoServer on :%d — try\n"
           "  examples/echo_client 127.0.0.1:%d\n"
           "  curl http://127.0.0.1:%d/          (portal)\n"
           "  curl -d '{\"send_ts_us\":1}' http://127.0.0.1:%d/EchoService/Echo\n",
           server.listened_port(), server.listened_port(),
           server.listened_port(), server.listened_port());
    // With --graceful: SIGTERM drains (in-flight requests finish, peers
    // steer away on the GOAWAY) and returns here for a code-0 exit;
    // SIGUSR2 drains without quitting. Without the flag this blocks
    // forever (Ctrl-C to exit) — same loop either way.
    server.RunUntilAskedToQuit(/*max_drain_ms=*/5000);
    return 0;
}
