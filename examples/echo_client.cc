// Synchronous echo client (reference example/echo_c++/client.cpp):
//   echo_client HOST:PORT [count]
#include <cstdio>
#include <cstdlib>

#include "bench_echo.pb.h"
#include "tbase/time.h"
#include "trpc/channel.h"
#include "trpc/controller.h"

using namespace tpurpc;

int main(int argc, char** argv) {
    if (argc < 2) {
        fprintf(stderr, "usage: %s HOST:PORT [count]\n", argv[0]);
        return 2;
    }
    const int count = argc > 2 ? atoi(argv[2]) : 4;
    Channel channel;
    ChannelOptions options;
    options.timeout_ms = 1000;
    options.max_retry = 3;
    if (channel.Init(argv[1], &options) != 0) {
        fprintf(stderr, "bad address %s\n", argv[1]);
        return 1;
    }
    benchpb::EchoService_Stub stub(&channel);
    for (int i = 0; i < count; ++i) {
        Controller cntl;
        benchpb::EchoRequest request;
        benchpb::EchoResponse response;
        request.set_send_ts_us(monotonic_time_us());
        cntl.request_attachment().append("hello tpu-rpc");
        stub.Echo(&cntl, &request, &response, nullptr);  // sync: done=null
        if (cntl.Failed()) {
            fprintf(stderr, "rpc %d failed: %s\n", i,
                    cntl.ErrorText().c_str());
            return 1;
        }
        printf("echo %d: rtt=%lldus attachment=%zuB\n", i,
               (long long)(monotonic_time_us() - response.send_ts_us()),
               cntl.response_attachment().size());
    }
    return 0;
}
