// Continuous micro-batching inference server (ISSUE 17): the serve
// plane an LLM-style token generator actually needs, built entirely
// from this framework's pieces.
//
//   * Requests arrive as ordinary RPCs whose payload "stream:N:key"
//     asks for an N-token response; admission is the server's normal
//     QoS tier (work-priced cost model + per-tenant quotas, ISSUE 15 —
//     enable with --tenant_quotas), so a flooding bronze tenant sheds
//     BEFORE it ever reaches the batch.
//   * Admitted sequences join a CONTINUOUS micro-batch: one device
//     step per tick serves one token to EVERY batch member (the step
//     cost amortizes across the batch — that is the whole win), and
//     membership is recomputed BETWEEN steps: finished sequences leave,
//     waiting ones join immediately — no batch-boundary barriers.
//     Membership is priority-ordered with an optional per-tenant slot
//     cap (--tenant_batch_cap), so gold keeps its seat while bronze
//     floods.
//   * Tokens leave through the resumable server-push stream tier
//     (trpc/stream.h): per-sequence emitter fibers park on receiver
//     credits, and a consumer that stops reading gets its SLOT
//     preempted (not its memory grown) until it catches up. Token
//     content is deterministic in (key, index), so a restarted process
//     regenerates a resumed stream exactly.
//
// Drive it with: rpc_press --stream_tokens=N [--tenants=...] and
// SIGTERM it mid-stream — clients resume, token streams stay
// seq-contiguous.
//
//   infer_server [port] [--step_us N] [--max_batch N]
//                [--tenant_batch_cap N] [--unbatched]
//                [--tenant_quotas spec] [--graceful]
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cstdio>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

#include "bench_echo.pb.h"
#include "tbase/errno.h"
#include "tbase/flags.h"
#include "tbase/time.h"
#include "tfiber/fiber.h"
#include "trpc/controller.h"
#include "trpc/server.h"
#include "trpc/stream.h"
#include "tvar/latency_recorder.h"
#include "tvar/reducer.h"

using namespace tpurpc;

namespace {

int64_t g_step_us = 2000;      // one device step (per BATCH, not token)
int g_max_batch = 8;           // micro-batch width
int g_tenant_batch_cap = 0;    // max slots one tenant holds (0 = none)
bool g_unbatched = false;      // serve one sequence per step (baseline)

// Grant run-ahead before a sequence counts as consumer-stalled. The
// emitter drains grants asynchronously (its own fiber, possibly parked
// on receiver credits) — a budget of a few tokens separates ordinary
// fiber-scheduling lag from a consumer that stopped reading. Memory
// stays bounded either way: unemitted grants are counters, and emitted
// chunks are capped by the rx window + replay ring.
constexpr uint64_t kGrantRunAhead = 4;

// One admitted generation request. The scheduler GRANTS tokens (one
// per step while the sequence holds a batch slot); the emitter fiber
// converts grants into stream Writes, parking on receiver credits —
// so a stalled consumer parks its emitter, never the scheduler.
struct Seq {
    push_stream::StreamWriter w;
    std::string key;
    std::string tenant;
    int priority = 4;
    uint64_t total = 0;
    std::atomic<uint64_t> granted{0};
    std::atomic<uint64_t> emitted{0};
    std::atomic<bool> failed{false};
    fiber_t tid = 0;
};

LazyAdder g_adm("infer_admitted");      // sequences admitted to the pool
LazyAdder g_steps("infer_steps");       // device steps executed
LazyAdder g_tokens("infer_tokens");     // tokens granted (== generated)
LazyAdder g_preempted("infer_preempted");  // slot losses to backpressure

// Batch width per step (a "latency" of N = N members). Leaked + built
// on first use: the tvar registry must not run at static-init time.
LatencyRecorder& BatchSizeVar() {
    static LatencyRecorder* r = [] {
        auto* v = new LatencyRecorder;
        v->expose("infer_batch_size");
        return v;
    }();
    return *r;
}

void* EmitterMain(void* arg) {
    auto* s = (Seq*)arg;
    while (!s->failed.load(std::memory_order_acquire)) {
        const uint64_t done = s->emitted.load(std::memory_order_relaxed);
        if (done >= s->total) break;
        if (done >= s->granted.load(std::memory_order_acquire)) {
            fiber_usleep(500);  // scheduler owns the pace
            continue;
        }
        const uint64_t i = done + 1;
        char tok[96];
        snprintf(tok, sizeof(tok), "tok:%s:%llu", s->key.c_str(),
                 (unsigned long long)i);
        // Parks on receiver credits / rebind; deterministic content
        // means a post-restart resume regenerates the same stream.
        if (s->w.Write(tok, i == s->total) != 0) {
            s->failed.store(true, std::memory_order_release);
            break;
        }
        s->emitted.store(i, std::memory_order_release);
    }
    return nullptr;
}

// The continuous micro-batching scheduler: one fiber, one step per
// tick. Between steps it re-forms the batch from the live pool —
// priority first, stalled consumers preempted, per-tenant slot cap.
class BatchScheduler {
public:
    void Admit(std::unique_ptr<Seq> s) {
        Seq* raw = s.get();
        if (fiber_start_background(&raw->tid, nullptr, EmitterMain, raw) !=
            0) {
            raw->w.Abort(TERR_INTERNAL);
            return;
        }
        std::lock_guard<std::mutex> lk(mu_);
        pool_.push_back(std::move(s));
        *g_adm << 1;
    }

    void Start() {
        fiber_start_background(&tid_, nullptr, &BatchScheduler::Main, this);
    }

    void Stop() {
        stop_.store(true, std::memory_order_release);
        if (tid_ != 0) fiber_join(tid_, nullptr);
    }

private:
    static void* Main(void* arg) {
        ((BatchScheduler*)arg)->Loop();
        return nullptr;
    }

    void Loop() {
        while (!stop_.load(std::memory_order_acquire)) {
            std::vector<Seq*> batch;
            {
                std::lock_guard<std::mutex> lk(mu_);
                Reap();
                FormBatch(&batch);
            }
            if (batch.empty()) {
                fiber_usleep(200);
                continue;
            }
            // THE device step: one fixed cost serves every member —
            // batched tokens/s scales with width, unbatched doesn't.
            fiber_usleep(g_step_us);
            *g_steps << 1;
            BatchSizeVar() << (int64_t)batch.size();
            for (Seq* s : batch) {
                s->granted.fetch_add(1, std::memory_order_release);
                *g_tokens << 1;
            }
        }
        std::lock_guard<std::mutex> lk(mu_);
        for (auto& s : pool_) {
            s->failed.store(true, std::memory_order_release);
            s->w.Abort(TERR_CLOSE);
        }
        Reap();
    }

    // Drop finished/failed sequences (join their emitters). mu_ held.
    void Reap() {
        for (size_t i = 0; i < pool_.size();) {
            Seq* s = pool_[i].get();
            const bool done =
                s->emitted.load(std::memory_order_acquire) >= s->total &&
                s->granted.load(std::memory_order_acquire) >= s->total;
            if (done || s->failed.load(std::memory_order_acquire)) {
                fiber_join(s->tid, nullptr);
                pool_.erase(pool_.begin() + (long)i);
            } else {
                ++i;
            }
        }
    }

    // Membership for the NEXT step. mu_ held. Priority-descending
    // stable order; a sequence whose grants ran kGrantRunAhead past
    // its emitter (consumer parked on credits) is skipped — preemption,
    // not buffering; a tenant past --tenant_batch_cap yields its extra
    // seats.
    void FormBatch(std::vector<Seq*>* batch) {
        std::vector<Seq*> order;
        order.reserve(pool_.size());
        for (auto& s : pool_) order.push_back(s.get());
        std::stable_sort(order.begin(), order.end(),
                         [](const Seq* a, const Seq* b) {
                             return a->priority > b->priority;
                         });
        const size_t width = g_unbatched ? 1 : (size_t)g_max_batch;
        std::vector<std::pair<std::string, int>> seats;
        for (Seq* s : order) {
            if (batch->size() >= width) break;
            if (s->granted.load(std::memory_order_acquire) >=
                s->emitted.load(std::memory_order_acquire) +
                    kGrantRunAhead) {
                *g_preempted << 1;  // consumer behind: slot goes elsewhere
                continue;
            }
            if (g_tenant_batch_cap > 0) {
                int* held = nullptr;
                for (auto& kv : seats) {
                    if (kv.first == s->tenant) held = &kv.second;
                }
                if (held == nullptr) {
                    seats.emplace_back(s->tenant, 0);
                    held = &seats.back().second;
                }
                if (*held >= g_tenant_batch_cap) continue;
                ++*held;
            }
            batch->push_back(s);
        }
    }

    std::mutex mu_;
    std::vector<std::unique_ptr<Seq>> pool_;
    std::atomic<bool> stop_{false};
    fiber_t tid_ = 0;
};

BatchScheduler g_sched;

class InferServiceImpl : public benchpb::EchoService {
public:
    void Echo(google::protobuf::RpcController* cntl_base,
              const benchpb::EchoRequest* request,
              benchpb::EchoResponse* response,
              google::protobuf::Closure* done) override {
        Controller* cntl = static_cast<Controller*>(cntl_base);
        response->set_send_ts_us(request->send_ts_us());
        unsigned long long n = 0;
        char key[64] = {0};
        if (!request->has_payload() ||
            sscanf(request->payload().c_str(), "stream:%llu:%63s", &n,
                   key) != 2 ||
            n == 0 || n > (1ull << 20)) {
            cntl->SetFailed(TERR_REQUEST,
                            "expected payload stream:<tokens>:<key>");
            done->Run();
            return;
        }
        push_stream::StreamWriter w = cntl->accept_stream();
        if (!w.valid()) {
            cntl->SetFailed(TERR_REQUEST, "not a push-stream open");
            done->Run();
            return;
        }
        // Same-process resume: the original emitter still owns the
        // stream; ring replay + the rebind cover continuation.
        if (!w.resumed_in_place()) {
            auto s = std::make_unique<Seq>();
            s->w = w;
            s->key = key;
            s->tenant = cntl->tenant();
            s->priority = cntl->priority();
            s->total = n;
            // Post-restart resume: regenerate from the client's floor.
            s->granted.store(w.resume_from(), std::memory_order_relaxed);
            s->emitted.store(w.resume_from(), std::memory_order_relaxed);
            g_sched.Admit(std::move(s));
        }
        done->Run();
    }
};

}  // namespace

int main(int argc, char** argv) {
    int port = 8020;
    for (int i = 1; i < argc; ++i) {
        if (strcmp(argv[i], "--step_us") == 0 && i + 1 < argc) {
            g_step_us = atoll(argv[++i]);
        } else if (strcmp(argv[i], "--max_batch") == 0 && i + 1 < argc) {
            g_max_batch = atoi(argv[++i]);
        } else if (strcmp(argv[i], "--tenant_batch_cap") == 0 &&
                   i + 1 < argc) {
            g_tenant_batch_cap = atoi(argv[++i]);
        } else if (strcmp(argv[i], "--unbatched") == 0) {
            g_unbatched = true;
        } else if (strcmp(argv[i], "--tenant_quotas") == 0 &&
                   i + 1 < argc) {
            // Work-priced admission (ISSUE 15) in front of the batch.
            SetFlagValue("rpc_tenant_quotas", argv[++i]);
        } else if (strcmp(argv[i], "--graceful") == 0) {
            SetFlagValue("graceful_quit_on_sigterm", "true");
        } else {
            port = atoi(argv[i]);
        }
    }
    BatchSizeVar();  // eager expose: scrapes see the var before traffic
    InferServiceImpl service;
    Server server;
    if (server.AddService(&service) != 0) return 1;
    if (server.Start(port, nullptr) != 0) {
        fprintf(stderr, "failed to listen on %d\n", port);
        return 1;
    }
    g_sched.Start();
    // Scripted-boot handshake (bench.py infer_scrape / the soaks use
    // the same contract as mesh_node).
    printf("READY %d\n", server.listened_port());
    fflush(stdout);
    printf("InferServer on :%d — step %lldus, batch %d%s; try\n"
           "  tools/rpc_press --server=127.0.0.1:%d --stream_tokens=64 "
           "--qps=4 --duration_s=5\n"
           "  curl http://127.0.0.1:%d/streams\n",
           server.listened_port(), (long long)g_step_us, g_max_batch,
           g_unbatched ? " (UNBATCHED baseline)" : "",
           server.listened_port(), server.listened_port());
    server.RunUntilAskedToQuit(/*max_drain_ms=*/5000);
    g_sched.Stop();
    return 0;
}
