// Backup requests cut tail latency (reference example/backup_request_c++
// + docs/en/backup_request.md): if no response arrives within the hedge
// delay, a second request goes out on a new call id — first answer wins.
//   backup_request_client HOST:PORT [backup_ms] [count]
#include <cstdio>
#include <cstdlib>

#include "bench_echo.pb.h"
#include "tbase/time.h"
#include "trpc/channel.h"
#include "trpc/controller.h"
#include "tvar/latency_recorder.h"

using namespace tpurpc;

int main(int argc, char** argv) {
    if (argc < 2) {
        fprintf(stderr, "usage: %s HOST:PORT [backup_ms] [count]\n",
                argv[0]);
        return 2;
    }
    const int64_t backup_ms = argc > 2 ? atoll(argv[2]) : 2;
    const int count = argc > 3 ? atoi(argv[3]) : 1000;
    Channel channel;
    ChannelOptions options;
    options.timeout_ms = 2000;
    options.backup_request_ms = backup_ms;
    options.max_retry = 1;  // the backup consumes one retry
    if (channel.Init(argv[1], &options) != 0) return 1;
    benchpb::EchoService_Stub stub(&channel);
    LatencyRecorder lat;
    for (int i = 0; i < count; ++i) {
        Controller cntl;
        benchpb::EchoRequest req;
        benchpb::EchoResponse res;
        req.set_send_ts_us(monotonic_time_us());
        stub.Echo(&cntl, &req, &res, nullptr);
        if (!cntl.Failed()) {
            lat << (monotonic_time_us() - res.send_ts_us());
        }
    }
    printf("backup@%lldms over %d calls: p50=%lldus p99=%lldus "
           "p999=%lldus\n",
           (long long)backup_ms, count,
           (long long)lat.latency_percentile(0.5),
           (long long)lat.latency_percentile(0.99),
           (long long)lat.latency_percentile(0.999));
    return 0;
}
