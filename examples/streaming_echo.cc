// Streaming RPC (reference example/streaming_echo_c++): the client
// establishes a stream on an Echo RPC, pumps N windowed messages, the
// server echoes each back on its own accepted stream. Single binary:
//   streaming_echo            (in-process server + client demo)
//   streaming_echo --server PORT / --client HOST:PORT [messages]
#include <atomic>
#include <cstdio>
#include <cstring>
#include <cstdlib>
#include <string>
#include <unistd.h>

#include "bench_echo.pb.h"
#include "tfiber/fiber.h"
#include "trpc/channel.h"
#include "trpc/controller.h"
#include "trpc/server.h"
#include "trpc/stream.h"

using namespace tpurpc;

// Server: accept the stream and echo every message back on it.
class StreamingEchoService : public benchpb::EchoService {
public:
    class EchoBack : public StreamInputHandler {
    public:
        int on_received_messages(StreamId id, IOBuf* const messages[],
                                 size_t size) override {
            for (size_t i = 0; i < size; ++i) {
                IOBuf copy;
                copy.append(*messages[i]);
                while (StreamWrite(id, &copy) != 0 && errno == EAGAIN) {
                    StreamWait(id, 0);
                }
            }
            return 0;
        }
        void on_closed(StreamId id) override { StreamClose(id); }
    };

    void Echo(google::protobuf::RpcController* cntl_base,
              const benchpb::EchoRequest*, benchpb::EchoResponse*,
              google::protobuf::Closure* done) override {
        Controller* cntl = static_cast<Controller*>(cntl_base);
        StreamId sid;
        StreamOptions opts;
        opts.handler = &handler_;
        if (StreamAccept(&sid, cntl, &opts) != 0) {
            cntl->SetFailed("stream accept failed");
        }
        done->Run();
    }

private:
    EchoBack handler_;
};

// Client: counts the echoes coming back.
class CountingHandler : public StreamInputHandler {
public:
    int on_received_messages(StreamId, IOBuf* const messages[],
                             size_t size) override {
        for (size_t i = 0; i < size; ++i) {
            bytes.fetch_add((int64_t)messages[i]->size());
        }
        received.fetch_add((int64_t)size);
        return 0;
    }
    void on_closed(StreamId) override { closed.store(true); }
    std::atomic<int64_t> received{0};
    std::atomic<int64_t> bytes{0};
    std::atomic<bool> closed{false};
};

static int RunClient(const char* addr, int nmessages) {
    Channel channel;
    ChannelOptions copts;
    copts.timeout_ms = 5000;
    if (channel.Init(addr, &copts) != 0) return 1;
    CountingHandler handler;
    Controller cntl;
    StreamId stream;
    StreamOptions sopts;
    sopts.handler = &handler;
    if (StreamCreate(&stream, &cntl, &sopts) != 0) return 1;
    benchpb::EchoService_Stub stub(&channel);
    benchpb::EchoRequest req;
    benchpb::EchoResponse res;
    stub.Echo(&cntl, &req, &res, nullptr);  // establishes the stream
    if (cntl.Failed()) {
        fprintf(stderr, "establish failed: %s\n", cntl.ErrorText().c_str());
        return 1;
    }
    const std::string payload(32 * 1024, 's');
    for (int i = 0; i < nmessages; ++i) {
        IOBuf msg;
        msg.append(payload);
        while (StreamWrite(stream, &msg) != 0 && errno == EAGAIN) {
            StreamWait(stream, 0);  // window full: wait for feedback
        }
    }
    while (handler.received.load() < nmessages) fiber_usleep(1000);
    printf("streamed %d x %zuKB and got every echo back (%lld KB)\n",
           nmessages, payload.size() / 1024,
           (long long)(handler.bytes.load() / 1024));
    StreamClose(stream);
    return 0;
}

int main(int argc, char** argv) {
    if (argc > 2 && strcmp(argv[1], "--client") == 0) {
        return RunClient(argv[2], argc > 3 ? atoi(argv[3]) : 64);
    }
    StreamingEchoService service;
    Server server;
    if (server.AddService(&service) != 0) return 1;
    if (argc > 2 && strcmp(argv[1], "--server") == 0) {
        if (server.Start(atoi(argv[2]), nullptr) != 0) return 1;
        printf("streaming echo server on :%d\n", server.listened_port());
        while (true) pause();
    }
    // Demo: server + client in one process over loopback.
    if (server.Start(0, nullptr) != 0) return 1;
    char addr[64];
    snprintf(addr, sizeof(addr), "127.0.0.1:%d", server.listened_port());
    return RunClient(addr, 64);
}
