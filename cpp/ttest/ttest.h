// ttest — a minimal gtest-shaped unit test framework.
//
// The reference uses googletest with one main per suite
// (reference: test/butil_unittest_main.cpp:19-41). gtest is not available in
// this image, so we provide a single-header framework with the same macro
// surface (TEST, EXPECT_*, ASSERT_*) so tests read identically. All tests
// link into one runner binary (cheaper on a 1-core build host).
#pragma once

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <functional>
#include <sstream>
#include <string>
#include <vector>

namespace ttest {

struct TestCase {
    const char* suite;
    const char* name;
    std::function<void()> fn;
};

inline std::vector<TestCase>& registry() {
    static std::vector<TestCase> r;
    return r;
}

struct Registrar {
    Registrar(const char* suite, const char* name, std::function<void()> fn) {
        registry().push_back({suite, name, std::move(fn)});
    }
};

// Per-test failure state.
inline int& current_failures() {
    static int f = 0;
    return f;
}
inline bool& fatal_failure() {
    static bool f = false;
    return f;
}

struct FailureReporter {
    std::ostringstream msg;
    bool fatal;
    const char* file;
    int line;
    FailureReporter(bool is_fatal, const char* f, int l)
        : fatal(is_fatal), file(f), line(l) {}
    ~FailureReporter() {
        std::fprintf(stderr, "FAILURE at %s:%d: %s\n", file, line,
                     msg.str().c_str());
        ++current_failures();
        if (fatal) fatal_failure() = true;
    }
    template <typename T>
    FailureReporter& operator<<(const T& v) {
        msg << v;
        return *this;
    }
};

inline int run_all(int argc, char** argv) {
    const char* filter = nullptr;
    for (int i = 1; i < argc; ++i) {
        if (strncmp(argv[i], "--filter=", 9) == 0) filter = argv[i] + 9;
    }
    // Comma-separated substring patterns; a test runs if any matches.
    std::vector<std::string> patterns;
    if (filter != nullptr) {
        std::string f = filter;
        size_t pos = 0;
        while (pos <= f.size()) {
            const size_t c = f.find(',', pos);
            const size_t end = c == std::string::npos ? f.size() : c;
            if (end > pos) patterns.push_back(f.substr(pos, end - pos));
            pos = end + 1;
        }
    }
    int failed = 0, ran = 0;
    for (auto& tc : registry()) {
        std::string full = std::string(tc.suite) + "." + tc.name;
        if (!patterns.empty()) {
            bool match = false;
            for (const auto& p : patterns) {
                if (full.find(p) != std::string::npos) {
                    match = true;
                    break;
                }
            }
            if (!match) continue;
        }
        ++ran;
        current_failures() = 0;
        fatal_failure() = false;
        std::fprintf(stderr, "[ RUN      ] %s\n", full.c_str());
        tc.fn();
        if (current_failures() > 0) {
            ++failed;
            std::fprintf(stderr, "[  FAILED  ] %s\n", full.c_str());
        } else {
            std::fprintf(stderr, "[       OK ] %s\n", full.c_str());
        }
    }
    std::fprintf(stderr, "%d test(s) ran, %d failed\n", ran, failed);
    return failed == 0 ? 0 : 1;
}

}  // namespace ttest

#define TTEST_CONCAT_(a, b) a##b
#define TTEST_CONCAT(a, b) TTEST_CONCAT_(a, b)

#define TEST(suite, name)                                                  \
    static void TTEST_CONCAT(ttest_##suite##_##name##_, body)();           \
    static ::ttest::Registrar TTEST_CONCAT(ttest_reg_##suite##_##name##_,  \
                                           __LINE__)(                      \
        #suite, #name, TTEST_CONCAT(ttest_##suite##_##name##_, body));     \
    static void TTEST_CONCAT(ttest_##suite##_##name##_, body)()

// Expectation macros. The `else` branch binds the streaming output.
#define TTEST_CHECK_IMPL(cond, fatal)                                  \
    if (cond) {                                                        \
    } else                                                             \
        ::ttest::FailureReporter(fatal, __FILE__, __LINE__)            \
            << "expected: " << #cond

#define EXPECT_TRUE(c) TTEST_CHECK_IMPL((c), false)
#define EXPECT_FALSE(c) TTEST_CHECK_IMPL(!(c), false)
#define EXPECT_EQ(a, b) TTEST_CHECK_IMPL((a) == (b), false)
#define EXPECT_NE(a, b) TTEST_CHECK_IMPL((a) != (b), false)
#define EXPECT_LT(a, b) TTEST_CHECK_IMPL((a) < (b), false)
#define EXPECT_LE(a, b) TTEST_CHECK_IMPL((a) <= (b), false)
#define EXPECT_GT(a, b) TTEST_CHECK_IMPL((a) > (b), false)
#define EXPECT_GE(a, b) TTEST_CHECK_IMPL((a) >= (b), false)
#define EXPECT_STREQ(a, b) TTEST_CHECK_IMPL(std::strcmp((a), (b)) == 0, false)

#define ASSERT_RET_IF_FATAL() \
    if (::ttest::fatal_failure()) return
#define ASSERT_TRUE(c)            \
    TTEST_CHECK_IMPL((c), true);  \
    ASSERT_RET_IF_FATAL()
#define ASSERT_FALSE(c)           \
    TTEST_CHECK_IMPL(!(c), true); \
    ASSERT_RET_IF_FATAL()
#define ASSERT_EQ(a, b)                  \
    TTEST_CHECK_IMPL((a) == (b), true);  \
    ASSERT_RET_IF_FATAL()
#define ASSERT_NE(a, b)                  \
    TTEST_CHECK_IMPL((a) != (b), true);  \
    ASSERT_RET_IF_FATAL()
#define ASSERT_LT(a, b)                  \
    TTEST_CHECK_IMPL((a) < (b), true);   \
    ASSERT_RET_IF_FATAL()
#define ASSERT_GT(a, b)                  \
    TTEST_CHECK_IMPL((a) > (b), true);   \
    ASSERT_RET_IF_FATAL()
#define ASSERT_GE(a, b)                  \
    TTEST_CHECK_IMPL((a) >= (b), true);  \
    ASSERT_RET_IF_FATAL()
#define ASSERT_LE(a, b)                  \
    TTEST_CHECK_IMPL((a) <= (b), true);  \
    ASSERT_RET_IF_FATAL()
