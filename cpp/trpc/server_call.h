// Server-side call context plumbing: the fiber-local "current server
// call", and the registry that maps in-flight server calls to cancelable
// handles.
//
// Two jobs, both serving the end-to-end deadline/cancellation story:
//
//  1. Hop-to-hop inheritance: while a user handler runs, a ServerCallScope
//     publishes its server-side Controller in fiber-local storage.
//     Channel::CallMethod consults CurrentServerCall() so a downstream
//     call issued inside the handler caps its deadline at the upstream
//     remaining budget and registers for cancel propagation.
//
//  2. Cancellation cascade: every dispatched server call mints a CallId
//     (tfiber/call_id.h) whose on_error handler cancels the server-side
//     Controller. The registry maps (socket, wire key) -> that CallId so
//     a tpu_std CANCEL meta, an h2 RST_STREAM, or connection death can
//     deliver the cancel; CallId versioning makes every delivery path
//     stale-safe against the response having already finished (the same
//     hazard discipline as RPC timers holding only id VALUES).
#pragma once

#include <cstdint>

#include "tfiber/call_id.h"
#include "tnet/socket.h"

namespace tpurpc {

class Controller;

// The server-side Controller of the call whose handler is running on this
// fiber (or pthread), or null outside a handler. Valid only for the
// synchronous extent of the handler body — a handler that defers work to
// another fiber must capture what it needs itself.
Controller* CurrentServerCall();

// RAII publisher for CurrentServerCall (nests: restores the previous
// value, so a handler that issues a local loopback call which dispatches
// inline keeps both contexts straight).
class ServerCallScope {
public:
    explicit ServerCallScope(Controller* cntl);
    ~ServerCallScope();
    ServerCallScope(const ServerCallScope&) = delete;
    ServerCallScope& operator=(const ServerCallScope&) = delete;

private:
    Controller* prev_;
};

namespace server_call {

// Registry of cancelable in-flight server calls. `key` is the wire
// identity of the call on its connection: the tpu_std correlation id, or
// the h2 stream id (one protocol per connection, so the spaces never
// collide on one socket).
void Register(SocketId sid, uint64_t key, CallId scid);
void Unregister(SocketId sid, uint64_t key);
// Cancel one call (stale-safe no-op when it already completed).
void Cancel(SocketId sid, uint64_t key);
// Cancel everything still in flight on a dead connection.
void CancelAllOnSocket(SocketId sid);
// Socket failure observer (installed by GlobalInitializeOrDie): hops to a
// fresh fiber before cancelling — Socket::SetFailed may run under
// arbitrary locks and cancellation runs user NotifyOnCancel closures.
void OnSocketFailed(SocketId sid);

// Shared observability counters (single LazyAdder per name; the tpu_std
// and h2 paths both feed them).
void CountExpired();   // rpc_server_expired_requests
void CountShed();      // rpc_server_shed_requests
void CountCanceled();  // rpc_server_canceled_calls

}  // namespace server_call

}  // namespace tpurpc
