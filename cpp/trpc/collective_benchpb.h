// Header-only benchpb glue for the collective engine: the codec and
// the Exchange-handler body shared by every TU that compiles the
// generated bench_echo.pb.h (tools/mesh_node.cc, the tcollective test
// mesh). Header-only on purpose — libtpurpc does not build the tools
// proto, so this cannot live in a trpc .cc; keeping it in ONE place
// keeps the wire-glue contract (which kinds answer inline vs as
// response descriptors, the backoff mapping, the attachment-view
// selection) from diverging between the tool and the test meshes.
#pragma once

#include <string>

#include "bench_echo.pb.h"
#include "tbase/errno.h"
#include "trpc/collective.h"
#include "trpc/controller.h"

namespace tpurpc {

class BenchpbCollCodec : public CollectiveCodec {
public:
    const google::protobuf::MethodDescriptor* method() const override {
        return benchpb::CollectiveService::descriptor()->method(0);
    }
    google::protobuf::Message* NewRequest(const CollWire& w) const override {
        auto* req = new benchpb::CollChunk;
        req->set_coll_seq(w.seq);
        req->set_kind(w.kind);
        req->set_step(w.step);
        req->set_chunk(w.chunk);
        req->set_src_rank(w.src_rank);
        req->set_nranks(w.nranks);
        req->set_member_hash(w.member_hash);
        req->set_total_bytes(w.total_bytes);
        req->set_offset(w.offset);
        req->set_len(w.len);
        req->set_scope(w.scope);
        if (w.verb_nchunks > 0) {
            // Verbs doorbell (ISSUE 18): window coordinates instead of
            // payload bytes.
            req->set_verb_window(w.verb_window);
            req->set_verb_nchunks(w.verb_nchunks);
            req->set_verb_crc(w.verb_crc);
            req->set_verb_epoch(w.verb_epoch);
        }
        return req;
    }
    google::protobuf::Message* NewResponse() const override {
        return new benchpb::CollAck;
    }
};

// The body of CollectiveService::Exchange: decode the wire meta, pick
// the payload view (resolved one-sided descriptor, else inline bytes),
// hand it to the engine (which may park briefly for round skew), and
// route the reply — pull/exchange payloads as response-direction
// descriptors (transparent inline fallback), the serial baseline
// inline by design. Runs done->Run() on every path.
inline void HandleCollectiveExchange(CollectiveEngine* eng,
                                     Controller* cntl,
                                     const benchpb::CollChunk* req,
                                     benchpb::CollAck* res,
                                     google::protobuf::Closure* done) {
    if (eng == nullptr) {
        cntl->SetFailed(TERR_NO_METHOD, "collectives not enabled");
        done->Run();
        return;
    }
    CollWire w;
    w.seq = req->coll_seq();
    w.kind = req->kind();
    w.step = req->step();
    w.chunk = req->chunk();
    w.src_rank = req->src_rank();
    w.nranks = req->nranks();
    w.member_hash = req->member_hash();
    w.total_bytes = req->total_bytes();
    w.offset = req->offset();
    w.len = req->len();
    w.scope = req->scope();
    w.verb_window = req->verb_window();
    w.verb_nchunks = req->verb_nchunks();
    w.verb_crc = req->verb_crc();
    w.verb_epoch = req->verb_epoch();
    const char* data = nullptr;
    size_t len = 0;
    std::string inline_copy;
    if (cntl->has_request_pool_attachment_view()) {
        data = cntl->request_pool_attachment().data;
        len = (size_t)cntl->request_pool_attachment().length;
    } else if (!cntl->request_attachment().empty()) {
        inline_copy = cntl->request_attachment().to_string();
        data = inline_copy.data();
        len = inline_copy.size();
    }
    // Park at most until shortly before the caller's budget expires;
    // an already-expired budget goes through non-positive, which the
    // engine treats as "answer immediately" (never burn a handler
    // fiber waiting on behalf of a caller that gave up).
    int64_t wait_us = cntl->remaining_server_budget_us();
    if (wait_us > 100 * 1000) wait_us -= 100 * 1000;  // reply margin
    IOBuf reply;
    int64_t backoff_ms = 0;
    int applied = 0;
    const int err = eng->HandleIncoming(w, data, len, &reply, wait_us,
                                        &backoff_ms, &applied);
    if (err != 0) {
        if (backoff_ms > 0) cntl->set_suggested_backoff_ms(backoff_ms);
        cntl->SetFailed(err, "collective chunk (kind=%u step=%u): %d",
                        w.kind, w.step, err);
        done->Run();
        return;
    }
    res->set_applied(applied);
    if (!reply.empty()) {
        if (w.kind == COLL_SERIAL_PULL) {
            cntl->response_attachment().append(std::move(reply));
        } else {
            cntl->set_response_pool_attachment(std::move(reply));
        }
    }
    done->Run();
}

}  // namespace tpurpc
