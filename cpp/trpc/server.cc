#include "trpc/server.h"

#include "tnet/tls.h"

#include <google/protobuf/descriptor.h>
#include <unistd.h>

#include <csignal>
#include <cstring>

#include <algorithm>
#include <cstdint>

#include "tbase/flags.h"
#include "tbase/logging.h"
#include "tbase/time.h"
#include "tvar/reducer.h"
#include "tfiber/butex.h"
#include "tfiber/fiber.h"
#include "thttp/builtin_services.h"
#include "thttp/http2_protocol.h"
#include "tvar/default_variables.h"
#include "tvar/series.h"
#include "tici/block_lease.h"
#include "tici/shm_link.h"
#include "trpc/policy_tpu_std.h"
#include "trpc/span.h"
#include "trpc/redis.h"
#include "trpc/stream.h"

// Reference -graceful_quit_on_SIGTERM (src/brpc/server.cpp): a SIGTERM
// triggers a graceful drain+quit instead of an abrupt death. SIGUSR2
// additionally requests a drain WITHOUT quitting (rebalance: shed
// traffic, keep answering health checks and the portal).
DEFINE_bool(graceful_quit_on_sigterm, false,
            "SIGTERM gracefully drains and quits the server; SIGUSR2 "
            "drains without quitting");
DECLARE_bool(rpc_qos_enabled);
DECLARE_string(rpc_tenant_quotas);

namespace tpurpc {

// Drain observability (the rolling-restart soak asserts on these):
// rpc_server_draining is a 0/1 gauge; goaways counts drain
// announcements broadcast to live connections; drained_inflight counts
// requests that completed inside a GracefulStop drain window.
static LazyAdder g_drain_goaways("rpc_server_drain_goaways_sent");
static LazyAdder g_drained_inflight("rpc_server_drained_inflight");
static Status<int64_t>* DrainingGauge() {
    static Status<int64_t>* g = [] {
        auto* s = new Status<int64_t>(0);
        s->expose("rpc_server_draining");
        return s;
    }();
    return g;
}

// ---- -graceful_quit_on_sigterm signal plumbing ----
// sig_atomic_t flags only; all real work happens on whoever polls.
namespace {
volatile std::sig_atomic_t g_asked_to_quit = 0;
volatile std::sig_atomic_t g_asked_to_drain = 0;
void HandleQuitSignal(int) { g_asked_to_quit = 1; }
void HandleDrainSignal(int) { g_asked_to_drain = 1; }
}  // namespace

void InstallGracefulQuitSignalsOrDie() {
    struct sigaction sa;
    memset(&sa, 0, sizeof(sa));
    sa.sa_handler = HandleQuitSignal;
    CHECK_EQ(sigaction(SIGTERM, &sa, nullptr), 0);
    sa.sa_handler = HandleDrainSignal;
    CHECK_EQ(sigaction(SIGUSR2, &sa, nullptr), 0);
}

bool IsAskedToQuit() { return g_asked_to_quit != 0; }
bool IsAskedToDrain() { return g_asked_to_drain != 0; }

Server::Server() : messenger_(), acceptor_(&messenger_) {
    join_butex_ = butex_create();
}

// Join in the destructor: a request fiber touches this server's method
// map (stats in the done-closure) until nprocessing hits zero, so
// destroying without draining is a use-after-free (the reference requires
// Stop+Join too, and its ~Server performs them).
Server::~Server() {
    Stop();
    Join();
    butex_destroy(join_butex_);
}

int Server::AddService(google::protobuf::Service* service) {
    if (started_) {
        LOG(ERROR) << "AddService after Start";
        return -1;
    }
    const auto* sd = service->GetDescriptor();
    for (int i = 0; i < sd->method_count(); ++i) {
        const auto* md = sd->method(i);
        const std::string key = sd->full_name() + "." + md->name();
        MethodProperty& mp = methods_[key];
        mp.service = service;
        mp.method = md;
        mp.status.reset(new MethodStatus);
        // Expose as service_method (dots break /vars conventions).
        std::string var_name = key;
        for (char& c : var_name) {
            if (c == '.') c = '_';
        }
        mp.status->latency.expose(var_name);
    }
    return 0;
}

int Server::Start(const EndPoint& ep, const ServerOptions* options) {
    if (StartNoListen(options) != 0) return -1;
    if (!options_.tls_cert_path.empty() || !options_.tls_key_path.empty()) {
        if (TlsServerInit(options_.tls_cert_path, options_.tls_key_path) !=
            0) {
            started_ = false;
            return -1;
        }
        acceptor_.set_tls(true);
    }
    if (acceptor_.StartAccept(ep) != 0) {
        LOG(ERROR) << "listen failed on " << endpoint2str(ep);
        started_ = false;
        return -1;
    }
    listening_ = true;
    // Host identity for cross-host trace stitching (first server wins).
    // A wildcard bind would make every node report "0.0.0.0:port" — the
    // stitcher keys clock ownership and self-exclusion on this string,
    // so substitute the machine's hostname to keep it unique per host.
    EndPoint self = ep;
    self.port = acceptor_.listened_port();
    if (self.ip.s_addr == 0) {
        char hostname[256] = "localhost";
        gethostname(hostname, sizeof(hostname) - 1);
        SetRpczHost(std::string(hostname) + ":" +
                    std::to_string(self.port));
    } else {
        SetRpczHost(endpoint2str(self));
    }
    return 0;
}

int Server::Start(int port, const ServerOptions* options) {
    EndPoint ep;
    str2endpoint("0.0.0.0", port, &ep);
    return Start(ep, options);
}

int Server::StartNoListen(const ServerOptions* options) {
    if (started_) return -1;
    GlobalInitializeOrDie();
    // Restart path: Stop() quiesces sockets but not user-code fibers —
    // drain them before mutating per-method state (resetting a limiter
    // under an in-flight done-closure would be a use-after-free).
    Join();
    if (options != nullptr) options_ = *options;
    if (options_.fiber_tag < 0 || options_.fiber_tag >= 64) {
        // Validate ONCE here: the per-request of_tag fallback would lose
        // the configured isolation silently and spam the log.
        LOG(ERROR) << "ServerOptions::fiber_tag " << options_.fiber_tag
                   << " out of range [0, 64)";
        return -1;
    }
    if (options_.fiber_tag == kUsercodeBackupTag) {
        // Tag 63 is the usercode overload-isolation backup pool
        // (policy_tpu_std.h): a user server running there would share
        // workers with every overflowing blocking handler in the
        // process — silently defeating the isolation for both.
        LOG(ERROR) << "ServerOptions::fiber_tag " << kUsercodeBackupTag
                   << " is reserved for the usercode backup pool";
        return -1;
    }
    for (auto& kv : methods_) {
        if (options_.timeout_concurrency) {
            kv.second.status->limiter.reset(
                new TimeoutConcurrencyLimiter(options_.timeout_cl_options));
        } else if (options_.auto_concurrency) {
            kv.second.status->limiter.reset(
                new AutoConcurrencyLimiter(options_.auto_cl_options));
        } else if (options_.max_concurrency > 0) {
            kv.second.status->limiter.reset(
                new ConstantConcurrencyLimiter(options_.max_concurrency));
        } else {
            kv.second.status->limiter.reset();  // restart may disable limits
        }
    }
    // Multi-tenant QoS (ISSUE 8): quotas from the flag (explicit
    // SetTenantQuota calls made before Start survive — Configure only
    // overwrites tenants the flag names), drainer for the fair queue.
    // Gradient options FIRST: tenants minted by Configure-time traffic
    // must already carry the tuned limiter (ISSUE 15).
    qos_.SetGradientOptions(options_.tenant_gradient_options);
    {
        std::map<std::string, TenantQuota> quotas;
        const std::string spec = FLAGS_rpc_tenant_quotas.get();
        if (!spec.empty() && !ParseQuotaSpec(spec, &quotas)) {
            LOG(ERROR) << "malformed entries in -rpc_tenant_quotas '"
                       << spec << "' (valid part applied)";
        }
        if (!quotas.empty() || FLAGS_rpc_qos_enabled.get()) {
            qos_.Configure(quotas, FLAGS_rpc_qos_enabled.get());
        }
    }
    if (qos_.enabled()) {
        qos_.StartDrainer();
    }
    ExposeProcessVariables();  // process_* gauges for /vars + /metrics
    ExposeFlagVariables();     // flag_* bridge: flag flips are scrapeable
    // Per-variable 60s/60min/24h rings behind /vars?series= (1Hz tick).
    SeriesCollector::singleton()->Enable();
    messenger_.add_protocol(TpuStdProtocolIndex());
    messenger_.add_protocol(stream_internal::StreamProtocolIndex());
    // Any accepted TCP connection may upgrade itself to the shared-memory
    // ICI data plane (cross-process queue pair; see tici/shm_link.h).
    messenger_.add_protocol(IciHandshakeProtocolIndex());
    // The observability portal rides the same port (reference
    // server.cpp:499 AddBuiltinServices — builtins are plain services on
    // the one acceptor). h2c must sniff BEFORE HTTP/1: the "PRI *
    // HTTP/2.0" preface looks like a request line to an HTTP/1 parser.
    messenger_.add_protocol(Http2ProtocolIndex());
    messenger_.add_protocol(HttpProtocolIndex());
    // RESP rides the same port too (leading '*' never collides with the
    // other magics).
    messenger_.add_protocol(RedisServerProtocolIndex());
    AddBuiltinHttpServices(this);
    messenger_.context = this;
    if (FLAGS_graceful_quit_on_sigterm.get()) {
        InstallGracefulQuitSignalsOrDie();
    }
    draining_.store(false, std::memory_order_release);  // restart path
    started_ = true;
    listening_ = false;
    return 0;
}

void Server::StartDraining() {
    if (!started_) return;
    if (draining_.exchange(true, std::memory_order_acq_rel)) {
        return;  // already draining
    }
    DrainingGauge()->set_value(1);
    // Broadcast the drain announcement on every live accepted
    // connection, in that connection's own protocol. Requests already
    // in flight — and ones racing the announcement — are still served;
    // peers steer NEW calls away (budget-free, breaker-free).
    int64_t sent = 0;
    for (SocketId id : acceptor_.connections()) {
        SocketUniquePtr s;
        if (Socket::AddressSocket(id, &s) != 0) continue;
        if (s->preferred_protocol_index == TpuStdProtocolIndex()) {
            SendTpuStdGoaway(s.get());
            ++sent;
        } else if (s->preferred_protocol_index == Http2ProtocolIndex()) {
            if (H2ServerSendGoaway(s.get()) == 0) ++sent;
        }
        // HTTP/1.1 has no unsolicited server frame: those connections
        // learn from the Connection: close on their next response
        // (http_protocol.cc checks server->draining()). Connections
        // that never sent a byte have no protocol yet — nothing to say.
    }
    if (sent > 0) *g_drain_goaways << sent;
    LOG(INFO) << "Server draining: " << sent
              << " GOAWAY announcements sent, nprocessing="
              << nprocessing.load(std::memory_order_acquire);
}

void Server::GracefulStop(int64_t max_drain_ms) {
    if (!started_) return;
    if (max_drain_ms < 0) max_drain_ms = 0;
    const int64_t deadline = monotonic_time_us() + max_drain_ms * 1000;
    // 1. Stop ACCEPTING without closing the listening fd: no new
    //    connections, but the port stays bound and connect-probe health
    //    checks still pass while we drain.
    if (listening_) acceptor_.PauseAccept();
    // 2. Announce the drain (GOAWAY broadcast + draining flag).
    const int64_t inflight_at_start =
        nprocessing.load(std::memory_order_acquire);
    StartDraining();
    // 3. Drain, bounded by max_drain_ms. Each in-flight request is also
    //    bounded by its own propagated deadline: expired work is shed by
    //    the deadline machinery, never executed into the void. A linger
    //    window after reaching zero catches requests that raced the
    //    GOAWAY (written by a peer before it processed the
    //    announcement) — they are served too, so a rolling restart
    //    completes every call instead of stranding the race window.
    const int64_t linger_us =
        std::min<int64_t>(200 * 1000, max_drain_ms * 1000 / 4 + 1);
    while (monotonic_time_us() < deadline) {
        JoinUntil(deadline);
        if (nprocessing.load(std::memory_order_acquire) > 0) {
            continue;  // deadline interrupted the wait; loop re-checks
        }
        const int64_t begun = nbegun_.load(std::memory_order_acquire);
        const int64_t linger_end =
            std::min(deadline, monotonic_time_us() + linger_us);
        while (monotonic_time_us() < linger_end &&
               nbegun_.load(std::memory_order_acquire) == begun) {
            fiber_usleep(10 * 1000);
        }
        if (nbegun_.load(std::memory_order_acquire) == begun &&
            nprocessing.load(std::memory_order_acquire) <= 0) {
            break;  // drained AND quiet for a full linger window
        }
    }
    const int64_t remaining = nprocessing.load(std::memory_order_acquire);
    const int64_t drained = inflight_at_start - remaining;
    if (drained > 0) *g_drained_inflight << drained;
    if (remaining > 0) {
        LOG(WARNING) << "GracefulStop: drain window (" << max_drain_ms
                     << "ms) expired with " << remaining
                     << " requests still in flight; stopping hard";
    }
    // 3b. Drain in-flight pinned descriptors (ISSUE 10c): blocks this
    //     process pinned for one-sided attachments still being read by
    //     peers. Stopping with live pins would tear the pool down under
    //     a peer's in-place resolve; bounded by the same drain deadline
    //     (plus a short floor so a zero-drain Stop still yields) — the
    //     expiry reaper is the backstop for anything left.
    {
        const int64_t pin_deadline =
            std::max(deadline, monotonic_time_us() + 100 * 1000);
        while (block_lease::pinned() > 0 &&
               monotonic_time_us() < pin_deadline) {
            fiber_usleep(5 * 1000);
        }
        const uint64_t pins = block_lease::pinned();
        if (pins > 0) {
            LOG(WARNING) << "GracefulStop: " << pins
                         << " pool block(s) still pinned at teardown "
                            "(lease reaper will reclaim)";
        }
    }
    // 4. Flush queued response bytes: a response that finished its
    //    handler but still sits in a socket's write queue would be
    //    dropped by the hard close below — the one failure mode that
    //    turns a "drained" restart into a client-visible error.
    const int64_t flush_deadline = monotonic_time_us() + 500 * 1000;
    for (SocketId id : acceptor_.connections()) {
        SocketUniquePtr s;
        if (Socket::AddressSocket(id, &s) != 0) continue;
        while (s->unwritten_bytes() > 0 && !s->Failed() &&
               monotonic_time_us() < flush_deadline) {
            fiber_usleep(2 * 1000);
        }
    }
    // 5. Hard teardown (unbounded Join: request fibers hold pointers
    //    into this Server; the drain above makes the wait short). Stop
    //    clears the draining flag and gauge.
    Stop();
    Join();
}

void Server::RunUntilAskedToQuit(int64_t max_drain_ms) {
    bool drained = false;
    while (!IsAskedToQuit()) {
        if (!drained && IsAskedToDrain()) {
            StartDraining();
            drained = true;
        }
        usleep(50 * 1000);  // plain thread sleep: callable off-fiber
    }
    GracefulStop(max_drain_ms);
}

void Server::Stop() {
    if (!started_) return;
    if (listening_) acceptor_.StopAccept();
    started_ = false;
    // Stop the fair-queue drainer and shed everything still queued:
    // each queued item holds a counted admission, so leaking one would
    // hang Join below forever.
    qos_.StopDrainer();
    // A drain-only server (StartDraining without GracefulStop) that is
    // stopped the plain way must not report rpc_server_draining=1
    // forever — the gauge is process-global, the flag per-instance.
    if (draining_.exchange(false, std::memory_order_acq_rel)) {
        DrainingGauge()->set_value(0);
    }
}

void Server::EndRequest() {
    // Teardown-safe wake protocol: bump the butex word BEFORE the
    // releasing decrement (the Server is pinned until nprocessing drops),
    // capture the butex into a local, and after the decrement do only
    // butex_wake_all on that local. A post-release word mutation could
    // corrupt a recycled slot reused by a new butex; a stray wake is
    // merely spurious (butex.cc pool contract).
    void* jb = join_butex_;
    butex_word(jb)->fetch_add(1, std::memory_order_release);
    if (nprocessing.fetch_sub(1, std::memory_order_acq_rel) == 1) {
        // `this` may be freed from here on.
        butex_wake_all(jb);
    }
}

void Server::Join() { JoinUntil(INT64_MAX); }

void Server::JoinUntil(int64_t abs_deadline_us) {
    // Drain in-flight requests (reference Server::Join semantics). Butex
    // parked, not polled; the short timeout is a backstop for the
    // wake-before-wait race, re-resolved on re-check. Returns early —
    // possibly with requests still in flight — once `abs_deadline_us`
    // passes (the bounded drain of GracefulStop).
    while (true) {
        const int seq =
            butex_word(join_butex_)->load(std::memory_order_acquire);
        if (nprocessing.load(std::memory_order_acquire) <= 0) return;
        const int64_t now = monotonic_time_us();
        if (now >= abs_deadline_us) return;
        const int64_t abst = std::min(abs_deadline_us, now + 100 * 1000);
        butex_wait(join_butex_, seq, &abst);
    }
}

Server::MethodProperty* Server::FindMethod(const std::string& service_name,
                                           const std::string& method_name) {
    auto it = methods_.find(service_name + "." + method_name);
    return it == methods_.end() ? nullptr : &it->second;
}

int Server::SetMethodInlineSafe(const std::string& service_full_name,
                                const std::string& method_name,
                                bool inline_safe) {
    MethodProperty* mp = FindMethod(service_full_name, method_name);
    if (mp == nullptr) {
        LOG(ERROR) << "SetMethodInlineSafe: no method " << service_full_name
                   << "." << method_name;
        return -1;
    }
    mp->inline_safe.store(inline_safe, std::memory_order_relaxed);
    return 0;
}

Server::MethodProperty* Server::FindMethodByHttpPath(
    const std::string& path) {
    // Expect exactly "/<service>/<method>".
    if (path.size() < 4 || path[0] != '/') return nullptr;
    const size_t slash = path.find('/', 1);
    if (slash == std::string::npos || slash + 1 >= path.size() ||
        path.find('/', slash + 1) != std::string::npos) {
        return nullptr;
    }
    const std::string svc = path.substr(1, slash - 1);
    const std::string method = path.substr(slash + 1);
    // Full name first.
    if (auto it = methods_.find(svc + "." + method); it != methods_.end()) {
        return &it->second;
    }
    // Last-component service name ("EchoService" for "pkg.EchoService").
    // Ambiguous short names (two packages sharing the component) resolve
    // to nothing — silently picking one would misroute requests (the
    // reference disables short-name access on ambiguity too).
    const std::string suffix = "." + svc + "." + method;
    MethodProperty* found = nullptr;
    for (auto& kv : methods_) {
        const std::string& key = kv.first;
        if (key.size() > suffix.size() &&
            key.compare(key.size() - suffix.size(), suffix.size(),
                        suffix) == 0) {
            if (found != nullptr) return nullptr;  // ambiguous
            found = &kv.second;
        }
    }
    return found;
}

void Server::RegisterHttpHandler(const std::string& path,
                                 HttpHandler handler) {
    if (started_) {
        // Same rule as AddService: the handler maps are read without
        // locks by request fibers once serving.
        LOG(ERROR) << "RegisterHttpHandler(" << path << ") after Start";
        return;
    }
    // First registration wins: user handlers are registered before Start,
    // builtins during Start — so users can override/front-run builtin
    // pages (rpc_view proxies them this way).
    if (path.size() >= 2 && path.compare(path.size() - 2, 2, "/*") == 0) {
        http_prefix_.emplace(path.substr(0, path.size() - 2),
                             std::move(handler));
    } else {
        http_exact_.emplace(path, std::move(handler));
    }
}

const HttpHandler* Server::FindHttpHandler(const std::string& path) const {
    auto it = http_exact_.find(path);
    if (it != http_exact_.end()) return &it->second;
    // Longest matching prefix whose registration was "<prefix>/*": the
    // request path must continue with '/' after the prefix.
    const HttpHandler* best = nullptr;
    size_t best_len = 0;
    for (const auto& kv : http_prefix_) {
        const std::string& p = kv.first;
        if (p.size() >= best_len && path.size() > p.size() &&
            path.compare(0, p.size(), p) == 0 && path[p.size()] == '/') {
            best = &kv.second;
            best_len = p.size();
        }
    }
    return best;
}

}  // namespace tpurpc
