#include "trpc/server.h"

#include "tnet/tls.h"

#include <google/protobuf/descriptor.h>
#include <unistd.h>

#include "tbase/logging.h"
#include "tbase/time.h"
#include "tfiber/butex.h"
#include "tfiber/fiber.h"
#include "thttp/builtin_services.h"
#include "thttp/http2_protocol.h"
#include "tvar/default_variables.h"
#include "tvar/series.h"
#include "tici/shm_link.h"
#include "trpc/policy_tpu_std.h"
#include "trpc/span.h"
#include "trpc/redis.h"
#include "trpc/stream.h"

namespace tpurpc {

Server::Server() : messenger_(), acceptor_(&messenger_) {
    join_butex_ = butex_create();
}

// Join in the destructor: a request fiber touches this server's method
// map (stats in the done-closure) until nprocessing hits zero, so
// destroying without draining is a use-after-free (the reference requires
// Stop+Join too, and its ~Server performs them).
Server::~Server() {
    Stop();
    Join();
    butex_destroy(join_butex_);
}

int Server::AddService(google::protobuf::Service* service) {
    if (started_) {
        LOG(ERROR) << "AddService after Start";
        return -1;
    }
    const auto* sd = service->GetDescriptor();
    for (int i = 0; i < sd->method_count(); ++i) {
        const auto* md = sd->method(i);
        const std::string key = sd->full_name() + "." + md->name();
        MethodProperty& mp = methods_[key];
        mp.service = service;
        mp.method = md;
        mp.status.reset(new MethodStatus);
        // Expose as service_method (dots break /vars conventions).
        std::string var_name = key;
        for (char& c : var_name) {
            if (c == '.') c = '_';
        }
        mp.status->latency.expose(var_name);
    }
    return 0;
}

int Server::Start(const EndPoint& ep, const ServerOptions* options) {
    if (StartNoListen(options) != 0) return -1;
    if (!options_.tls_cert_path.empty() || !options_.tls_key_path.empty()) {
        if (TlsServerInit(options_.tls_cert_path, options_.tls_key_path) !=
            0) {
            started_ = false;
            return -1;
        }
        acceptor_.set_tls(true);
    }
    if (acceptor_.StartAccept(ep) != 0) {
        LOG(ERROR) << "listen failed on " << endpoint2str(ep);
        started_ = false;
        return -1;
    }
    listening_ = true;
    // Host identity for cross-host trace stitching (first server wins).
    // A wildcard bind would make every node report "0.0.0.0:port" — the
    // stitcher keys clock ownership and self-exclusion on this string,
    // so substitute the machine's hostname to keep it unique per host.
    EndPoint self = ep;
    self.port = acceptor_.listened_port();
    if (self.ip.s_addr == 0) {
        char hostname[256] = "localhost";
        gethostname(hostname, sizeof(hostname) - 1);
        SetRpczHost(std::string(hostname) + ":" +
                    std::to_string(self.port));
    } else {
        SetRpczHost(endpoint2str(self));
    }
    return 0;
}

int Server::Start(int port, const ServerOptions* options) {
    EndPoint ep;
    str2endpoint("0.0.0.0", port, &ep);
    return Start(ep, options);
}

int Server::StartNoListen(const ServerOptions* options) {
    if (started_) return -1;
    GlobalInitializeOrDie();
    // Restart path: Stop() quiesces sockets but not user-code fibers —
    // drain them before mutating per-method state (resetting a limiter
    // under an in-flight done-closure would be a use-after-free).
    Join();
    if (options != nullptr) options_ = *options;
    if (options_.fiber_tag < 0 || options_.fiber_tag >= 64) {
        // Validate ONCE here: the per-request of_tag fallback would lose
        // the configured isolation silently and spam the log.
        LOG(ERROR) << "ServerOptions::fiber_tag " << options_.fiber_tag
                   << " out of range [0, 64)";
        return -1;
    }
    if (options_.fiber_tag == kUsercodeBackupTag) {
        // Tag 63 is the usercode overload-isolation backup pool
        // (policy_tpu_std.h): a user server running there would share
        // workers with every overflowing blocking handler in the
        // process — silently defeating the isolation for both.
        LOG(ERROR) << "ServerOptions::fiber_tag " << kUsercodeBackupTag
                   << " is reserved for the usercode backup pool";
        return -1;
    }
    for (auto& kv : methods_) {
        if (options_.timeout_concurrency) {
            kv.second.status->limiter.reset(
                new TimeoutConcurrencyLimiter(options_.timeout_cl_options));
        } else if (options_.auto_concurrency) {
            kv.second.status->limiter.reset(
                new AutoConcurrencyLimiter(options_.auto_cl_options));
        } else if (options_.max_concurrency > 0) {
            kv.second.status->limiter.reset(
                new ConstantConcurrencyLimiter(options_.max_concurrency));
        } else {
            kv.second.status->limiter.reset();  // restart may disable limits
        }
    }
    ExposeProcessVariables();  // process_* gauges for /vars + /metrics
    ExposeFlagVariables();     // flag_* bridge: flag flips are scrapeable
    // Per-variable 60s/60min/24h rings behind /vars?series= (1Hz tick).
    SeriesCollector::singleton()->Enable();
    messenger_.add_protocol(TpuStdProtocolIndex());
    messenger_.add_protocol(stream_internal::StreamProtocolIndex());
    // Any accepted TCP connection may upgrade itself to the shared-memory
    // ICI data plane (cross-process queue pair; see tici/shm_link.h).
    messenger_.add_protocol(IciHandshakeProtocolIndex());
    // The observability portal rides the same port (reference
    // server.cpp:499 AddBuiltinServices — builtins are plain services on
    // the one acceptor). h2c must sniff BEFORE HTTP/1: the "PRI *
    // HTTP/2.0" preface looks like a request line to an HTTP/1 parser.
    messenger_.add_protocol(Http2ProtocolIndex());
    messenger_.add_protocol(HttpProtocolIndex());
    // RESP rides the same port too (leading '*' never collides with the
    // other magics).
    messenger_.add_protocol(RedisServerProtocolIndex());
    AddBuiltinHttpServices(this);
    messenger_.context = this;
    started_ = true;
    listening_ = false;
    return 0;
}

void Server::Stop() {
    if (!started_) return;
    if (listening_) acceptor_.StopAccept();
    started_ = false;
}

void Server::EndRequest() {
    // Teardown-safe wake protocol: bump the butex word BEFORE the
    // releasing decrement (the Server is pinned until nprocessing drops),
    // capture the butex into a local, and after the decrement do only
    // butex_wake_all on that local. A post-release word mutation could
    // corrupt a recycled slot reused by a new butex; a stray wake is
    // merely spurious (butex.cc pool contract).
    void* jb = join_butex_;
    butex_word(jb)->fetch_add(1, std::memory_order_release);
    if (nprocessing.fetch_sub(1, std::memory_order_acq_rel) == 1) {
        // `this` may be freed from here on.
        butex_wake_all(jb);
    }
}

void Server::Join() {
    // Drain in-flight requests (reference Server::Join semantics). Butex
    // parked, not polled; the short timeout is a backstop for the
    // wake-before-wait race, re-resolved on re-check.
    while (true) {
        const int seq =
            butex_word(join_butex_)->load(std::memory_order_acquire);
        if (nprocessing.load(std::memory_order_acquire) <= 0) return;
        const int64_t abst = monotonic_time_us() + 100 * 1000;
        butex_wait(join_butex_, seq, &abst);
    }
}

Server::MethodProperty* Server::FindMethod(const std::string& service_name,
                                           const std::string& method_name) {
    auto it = methods_.find(service_name + "." + method_name);
    return it == methods_.end() ? nullptr : &it->second;
}

Server::MethodProperty* Server::FindMethodByHttpPath(
    const std::string& path) {
    // Expect exactly "/<service>/<method>".
    if (path.size() < 4 || path[0] != '/') return nullptr;
    const size_t slash = path.find('/', 1);
    if (slash == std::string::npos || slash + 1 >= path.size() ||
        path.find('/', slash + 1) != std::string::npos) {
        return nullptr;
    }
    const std::string svc = path.substr(1, slash - 1);
    const std::string method = path.substr(slash + 1);
    // Full name first.
    if (auto it = methods_.find(svc + "." + method); it != methods_.end()) {
        return &it->second;
    }
    // Last-component service name ("EchoService" for "pkg.EchoService").
    // Ambiguous short names (two packages sharing the component) resolve
    // to nothing — silently picking one would misroute requests (the
    // reference disables short-name access on ambiguity too).
    const std::string suffix = "." + svc + "." + method;
    MethodProperty* found = nullptr;
    for (auto& kv : methods_) {
        const std::string& key = kv.first;
        if (key.size() > suffix.size() &&
            key.compare(key.size() - suffix.size(), suffix.size(),
                        suffix) == 0) {
            if (found != nullptr) return nullptr;  // ambiguous
            found = &kv.second;
        }
    }
    return found;
}

void Server::RegisterHttpHandler(const std::string& path,
                                 HttpHandler handler) {
    if (started_) {
        // Same rule as AddService: the handler maps are read without
        // locks by request fibers once serving.
        LOG(ERROR) << "RegisterHttpHandler(" << path << ") after Start";
        return;
    }
    // First registration wins: user handlers are registered before Start,
    // builtins during Start — so users can override/front-run builtin
    // pages (rpc_view proxies them this way).
    if (path.size() >= 2 && path.compare(path.size() - 2, 2, "/*") == 0) {
        http_prefix_.emplace(path.substr(0, path.size() - 2),
                             std::move(handler));
    } else {
        http_exact_.emplace(path, std::move(handler));
    }
}

const HttpHandler* Server::FindHttpHandler(const std::string& path) const {
    auto it = http_exact_.find(path);
    if (it != http_exact_.end()) return &it->second;
    // Longest matching prefix whose registration was "<prefix>/*": the
    // request path must continue with '/' after the prefix.
    const HttpHandler* best = nullptr;
    size_t best_len = 0;
    for (const auto& kv : http_prefix_) {
        const std::string& p = kv.first;
        if (p.size() >= best_len && path.size() > p.size() &&
            path.compare(0, p.size(), p) == 0 && path[p.size()] == '/') {
            best = &kv.second;
            best_len = p.size();
        }
    }
    return best;
}

}  // namespace tpurpc
