#include "trpc/server.h"

#include <google/protobuf/descriptor.h>
#include <unistd.h>

#include "tbase/logging.h"
#include "tfiber/fiber.h"
#include "tici/shm_link.h"
#include "trpc/policy_tpu_std.h"
#include "trpc/stream.h"

namespace tpurpc {

// Join in the destructor: a request fiber touches this server's method
// map (stats in the done-closure) until nprocessing hits zero, so
// destroying without draining is a use-after-free (the reference requires
// Stop+Join too, and its ~Server performs them).
Server::~Server() {
    Stop();
    Join();
}

int Server::AddService(google::protobuf::Service* service) {
    if (started_) {
        LOG(ERROR) << "AddService after Start";
        return -1;
    }
    const auto* sd = service->GetDescriptor();
    for (int i = 0; i < sd->method_count(); ++i) {
        const auto* md = sd->method(i);
        const std::string key = sd->full_name() + "." + md->name();
        MethodProperty& mp = methods_[key];
        mp.service = service;
        mp.method = md;
        mp.status.reset(new MethodStatus);
        // Expose as service_method (dots break /vars conventions).
        std::string var_name = key;
        for (char& c : var_name) {
            if (c == '.') c = '_';
        }
        mp.status->latency.expose(var_name);
    }
    return 0;
}

int Server::Start(const EndPoint& ep, const ServerOptions* options) {
    if (StartNoListen(options) != 0) return -1;
    if (acceptor_.StartAccept(ep) != 0) {
        LOG(ERROR) << "listen failed on " << endpoint2str(ep);
        started_ = false;
        return -1;
    }
    listening_ = true;
    return 0;
}

int Server::Start(int port, const ServerOptions* options) {
    EndPoint ep;
    str2endpoint("0.0.0.0", port, &ep);
    return Start(ep, options);
}

int Server::StartNoListen(const ServerOptions* options) {
    if (started_) return -1;
    GlobalInitializeOrDie();
    if (options != nullptr) options_ = *options;
    for (auto& kv : methods_) {
        kv.second.status->max_concurrency = options_.max_concurrency;
    }
    messenger_.add_protocol(TpuStdProtocolIndex());
    messenger_.add_protocol(stream_internal::StreamProtocolIndex());
    // Any accepted TCP connection may upgrade itself to the shared-memory
    // ICI data plane (cross-process queue pair; see tici/shm_link.h).
    messenger_.add_protocol(IciHandshakeProtocolIndex());
    messenger_.context = this;
    started_ = true;
    listening_ = false;
    return 0;
}

void Server::Stop() {
    if (!started_) return;
    if (listening_) acceptor_.StopAccept();
    started_ = false;
}

void Server::Join() {
    // Drain in-flight requests (reference Server::Join semantics).
    while (nprocessing.load(std::memory_order_acquire) > 0) {
        usleep(10000);
    }
}

Server::MethodProperty* Server::FindMethod(const std::string& service_name,
                                           const std::string& method_name) {
    auto it = methods_.find(service_name + "." + method_name);
    return it == methods_.end() ? nullptr : &it->second;
}

}  // namespace tpurpc
