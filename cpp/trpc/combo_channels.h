// Combo channels: fan-out, sharding and policy-routing over sub-channels —
// the reference's parallelism-strategy family (SURVEY §2.6).
//
// Modeled on:
//  - ParallelChannel (reference src/brpc/parallel_channel.h:94-262): one
//    RPC fanned out to every sub-channel concurrently; CallMapper maps the
//    parent call onto each sub-channel, ResponseMerger folds sub-responses
//    into the parent response; ParallelChannelDone aggregates completions
//    with fail_limit (parallel_channel.cpp:40-172).
//  - PartitionChannel (src/brpc/partition_channel.h:34-93): shard-addressed
//    fan-out; naming tags like "2/5" (partition 2 of 5) parsed by a
//    PartitionParser route servers to per-partition sub-channels.
//  - SelectiveChannel (src/brpc/selective_channel.h): policy routing — each
//    call picks ONE sub-channel (round-robin here), retrying on another
//    when it fails.
//  - DynamicPartitionChannel (src/brpc/partition_channel.h:~130): serves
//    whichever partition scheme currently has capacity, weighted by server
//    count.
//
// In the TPU build this family is also lowered onto XLA collectives for
// regular fan-out patterns (brpc_tpu/parallel/): ParallelChannel fan-out ==
// AllGather, ResponseMerger == ReduceScatter (BASELINE north star).
#pragma once

#include <google/protobuf/service.h>

#include <atomic>
#include <memory>
#include <string>
#include <vector>

#include "tbase/iobuf.h"
#include "trpc/channel.h"

namespace tpurpc {

class Controller;

// Per-sub-call completion hook (combo-channel extension for the
// collective tier, ISSUE 13): invoked exactly once per non-skipped
// sub-call, on the sub-call's completion fiber, BEFORE the parent
// merges/completes — the only window where the sub Controller's
// response attachment / resolved response-descriptor view is readable.
// May run concurrently for different indices; implementations
// synchronize their own state. Borrowed, must outlive the parent call.
class SubCallObserver {
public:
    virtual ~SubCallObserver() = default;
    virtual void OnSubCallDone(int channel_index, Controller& sub_cntl) = 0;
};

// Maps the parent call onto sub-channel `channel_index`. Default (null
// mapper): sub-request = parent request, sub-response = fresh instance of
// the parent response type (merged back by the merger).
class CallMapper {
public:
    struct SubCall {
        // Null method = skip this sub-channel entirely
        // (reference SubCall::Skip()).
        const google::protobuf::MethodDescriptor* method = nullptr;
        const google::protobuf::Message* request = nullptr;
        google::protobuf::Message* response = nullptr;
        bool owns_request = false;   // delete after the call
        bool owns_response = false;  // delete after merging
        bool skip = false;
        // Attachment bytes for THIS sub-call (moved into the sub
        // Controller). With `pool_descriptor` the bytes go out as a
        // one-sided PoolDescriptor when the buffer/transport is
        // eligible (Controller::set_request_pool_attachment semantics:
        // ineligible shapes fall back inline transparently) — how the
        // collective tier posts slab-class chunks zero-copy through a
        // plain ParallelChannel fan-out.
        IOBuf request_attachment;
        bool pool_descriptor = false;
        SubCallObserver* observer = nullptr;  // borrowed
        static SubCall Skip() {
            SubCall s;
            s.skip = true;
            return s;
        }
    };
    virtual ~CallMapper() = default;
    virtual SubCall Map(int channel_index, int channel_count,
                        const google::protobuf::MethodDescriptor* method,
                        const google::protobuf::Message* request,
                        google::protobuf::Message* response) = 0;
};

// Folds one successful sub-response into the parent response. Default
// (null merger): protobuf MergeFrom in sub-channel index order.
class ResponseMerger {
public:
    virtual ~ResponseMerger() = default;
    // Return 0 on success, <0 to count the sub-call as failed
    // (reference ResponseMerger::Result).
    virtual int Merge(google::protobuf::Message* response,
                      const google::protobuf::Message* sub_response) = 0;
};

struct ParallelChannelOptions {
    // Parent fails once this many sub-calls failed; <=0 (unset) matches
    // the reference default: the parent fails only when ALL sub-calls
    // failed (reference parallel_channel.h:165-167).
    int fail_limit = 0;
    int64_t timeout_ms = 500;
};

// Fan-out one RPC to every sub-channel concurrently.
class ParallelChannel : public google::protobuf::RpcChannel {
public:
    explicit ParallelChannel(const ParallelChannelOptions* options = nullptr);
    ~ParallelChannel() override;

    // Does NOT take ownership of `sub` (channels are commonly shared);
    // takes ownership of mapper/merger (reference takes refcounted ptrs).
    int AddChannel(google::protobuf::RpcChannel* sub, CallMapper* mapper,
                   ResponseMerger* merger);

    int channel_count() const { return (int)subs_.size(); }

    void CallMethod(const google::protobuf::MethodDescriptor* method,
                    google::protobuf::RpcController* controller,
                    const google::protobuf::Message* request,
                    google::protobuf::Message* response,
                    google::protobuf::Closure* done) override;

    // Attach with shared mapper/merger instances (one stateless object
    // serving every sub-channel — how PartitionChannel wires its
    // partitions).
    int AddChannelShared(google::protobuf::RpcChannel* sub,
                         std::shared_ptr<CallMapper> mapper,
                         std::shared_ptr<ResponseMerger> merger);

private:
    struct Sub {
        google::protobuf::RpcChannel* chan;
        std::shared_ptr<CallMapper> mapper;
        std::shared_ptr<ResponseMerger> merger;
    };
    ParallelChannelOptions options_;
    std::vector<Sub> subs_;
};

// Parses a naming tag into (index, count). Default: "N/M".
class PartitionParser {
public:
    struct Partition {
        int index = -1;
        int count = 0;
    };
    virtual ~PartitionParser() = default;
    virtual bool ParseFromTag(const std::string& tag, Partition* out);
};

struct PartitionChannelOptions : public ParallelChannelOptions {
    int max_retry = 3;
    // Applied to every partition sub-channel; owned by the
    // PartitionChannel after Init (may be null: parent request fanned
    // out as-is, responses MergeFrom'd).
    CallMapper* call_mapper = nullptr;
    ResponseMerger* response_merger = nullptr;
};

// Shard-addressed fan-out: one sub-channel per partition, fan-out to all
// partitions per call. Partition membership comes from naming tags.
//
// Round-1 scope note: the server list is resolved once at Init (list://
// and file:// schemes); live naming updates re-partitioning the set are
// wired with the naming-thread watcher in a later milestone (reference
// PartitionChannelBase::Init hooks the shared NamingServiceThread).
class PartitionChannel : public google::protobuf::RpcChannel {
public:
    PartitionChannel();
    ~PartitionChannel() override;

    // `parser` owned; null = default "N/M" parser.
    int Init(const char* naming_url, const char* lb_name,
             PartitionParser* parser, const PartitionChannelOptions* options);

    int partition_count() const { return nparts_; }

    void CallMethod(const google::protobuf::MethodDescriptor* method,
                    google::protobuf::RpcController* controller,
                    const google::protobuf::Message* request,
                    google::protobuf::Message* response,
                    google::protobuf::Closure* done) override;

private:
    int nparts_ = 0;
    std::unique_ptr<PartitionParser> parser_;
    std::vector<std::unique_ptr<Channel>> parts_;
    std::unique_ptr<ParallelChannel> fanout_;
};

// Policy routing: each call goes to ONE sub-channel; a failed call retries
// on the next one (up to the controller's max_retry).
//
// Cross-channel re-issues run through the SAME retry funnel as a plain
// Channel's in-channel retries (ISSUE 13 satellite): each hop withdraws
// from this channel's RetryBudget (flag defaults
// -rpc_retry_budget_tokens/-rpc_retry_budget_ratio; ConfigureRetryBudget
// overrides) and is counted in rpc_client_retries /
// rpc_retry_budget_exhausted — a SelectiveChannel can no longer amplify
// a correlated failure budget-free. TERR_DRAINING hops stay budget-free
// (the server provably never processed the call, PR-4 semantics).
class SelectiveChannel : public google::protobuf::RpcChannel {
public:
    SelectiveChannel() = default;
    ~SelectiveChannel() override = default;

    // Does NOT take ownership.
    int AddChannel(google::protobuf::RpcChannel* sub);
    int channel_count() const { return (int)subs_.size(); }

    // Override the flag-default budget (tokens <= 0 disables). Setup
    // phase only — like AddChannel, call it before the first
    // CallMethod (the budget fields are not written concurrently with
    // the hot path's Withdraw/OnSuccess).
    void ConfigureRetryBudget(int64_t max_tokens, double token_ratio) {
        retry_budget_.Configure(max_tokens, token_ratio);
        budget_configured_.store(true, std::memory_order_release);
    }
    RetryBudget& retry_budget() { return retry_budget_; }

    void CallMethod(const google::protobuf::MethodDescriptor* method,
                    google::protobuf::RpcController* controller,
                    const google::protobuf::Message* request,
                    google::protobuf::Message* response,
                    google::protobuf::Closure* done) override;

private:
    friend struct SelectiveCallCtx;
    void EnsureBudget();

    std::vector<google::protobuf::RpcChannel*> subs_;
    std::atomic<uint32_t> rr_{0};
    RetryBudget retry_budget_;
    std::atomic<bool> budget_configured_{false};
};

// Serves whichever partition scheme has the most capacity right now:
// Init with several "N/M" schemes' naming urls; calls route to the scheme
// with the most servers (reference DynamicPartitionChannel migrates
// traffic between schemes by capacity — here capacity = resolved server
// count at Init; live migration follows the naming-watcher milestone).
class DynamicPartitionChannel : public google::protobuf::RpcChannel {
public:
    DynamicPartitionChannel() = default;
    ~DynamicPartitionChannel() override = default;

    int Init(const std::vector<std::string>& naming_urls, const char* lb_name,
             const PartitionChannelOptions* options);

    void CallMethod(const google::protobuf::MethodDescriptor* method,
                    google::protobuf::RpcController* controller,
                    const google::protobuf::Message* request,
                    google::protobuf::Message* response,
                    google::protobuf::Closure* done) override;

    int chosen_scheme() const { return chosen_; }

private:
    std::vector<std::unique_ptr<PartitionChannel>> schemes_;
    std::vector<int> capacities_;
    int chosen_ = -1;
};

}  // namespace tpurpc
