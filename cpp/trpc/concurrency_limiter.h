// ConcurrencyLimiter: per-method admission control.
//
// Modeled on reference src/brpc/concurrency_limiter.h:29 and
// policy/auto_concurrency_limiter.{h,cpp} (state fields .h:57-73): the
// "auto" limiter estimates the no-load latency (EMA of window minima) and
// the peak service rate (EMA of max QPS), and sets
//   max_concurrency = min_latency_us * ema_max_qps * (1 + explore_ratio)
// (Little's law with headroom). Periodically it shrinks the limit hard to
// re-measure the no-load latency, so a slowly-degrading backend can't
// ratchet the estimate upward. Failed requests punish the average latency.
#pragma once

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <mutex>

#include "tbase/time.h"

namespace tpurpc {

class ConcurrencyLimiter {
public:
    virtual ~ConcurrencyLimiter() = default;
    // current = concurrency AFTER this request was counted in. True =
    // admit.
    virtual bool OnRequested(int64_t current) = 0;
    // Deadline-aware admission, consulted IN ADDITION to OnRequested for
    // requests carrying a propagated deadline: `remaining_us` is the
    // budget the client has left. False = the request cannot plausibly
    // finish inside its budget — shed it now, before it costs a handler
    // (the caller accounts it as rpc_server_shed_requests). `priority`
    // is the request's QoS shed class (qos.h): budget-aware limiters
    // keep per-priority probe state so one class's probes can't starve
    // another's recovery.
    virtual bool AdmitWithBudget(int64_t remaining_us, int priority = 0) {
        (void)remaining_us;
        (void)priority;
        return true;
    }
    // Every admitted request reports its outcome.
    virtual void OnResponded(int error_code, int64_t latency_us) = 0;
    virtual int64_t MaxConcurrency() const = 0;
};

// "constant": fixed cap; 0 = unlimited.
class ConstantConcurrencyLimiter : public ConcurrencyLimiter {
public:
    explicit ConstantConcurrencyLimiter(int64_t max) : max_(max) {}
    bool OnRequested(int64_t current) override {
        const int64_t m = max_.load(std::memory_order_relaxed);
        return m <= 0 || current <= m;
    }
    void OnResponded(int, int64_t) override {}
    int64_t MaxConcurrency() const override {
        return max_.load(std::memory_order_relaxed);
    }

private:
    std::atomic<int64_t> max_;
};

// "timeout": admit only requests that can plausibly finish within the
// budget — with `current` requests ahead and an EMA of the per-request
// latency, a newcomer whose queue wait alone would exceed `timeout_ms`
// is rejected now instead of timing out later (reference
// policy/timeout_concurrency_limiter.{h,cpp}).
class TimeoutConcurrencyLimiter : public ConcurrencyLimiter {
public:
    struct Options {
        int64_t timeout_ms = 100;    // the latency budget to protect
        int64_t min_concurrency = 2;  // always admit up to this many
        double alpha = 0.25;          // latency EMA smoothing
        // Budget-shed escape hatch: with no fresh success sample in this
        // long, AdmitWithBudget admits one probe — a shed request never
        // executes, so without probes a stale-high EMA could latch the
        // method into shedding 100% of deadline-carrying traffic forever.
        int64_t probe_interval_ms = 1000;
    };

    TimeoutConcurrencyLimiter() : TimeoutConcurrencyLimiter(Options()) {}
    explicit TimeoutConcurrencyLimiter(const Options& opt) : opt_(opt) {}

    bool OnRequested(int64_t current) override {
        if (current <= opt_.min_concurrency) return true;
        const int64_t avg = avg_latency_us_.load(std::memory_order_relaxed);
        if (avg <= 0) return true;  // no estimate yet
        return current * avg <= opt_.timeout_ms * 1000;
    }

    // A request whose remaining client budget is below even ONE observed
    // service time is doomed: the client will have hung up before the
    // response exists. Rejecting here costs a map lookup; executing it
    // costs a full handler that nobody reads.
    bool AdmitWithBudget(int64_t remaining_us, int priority = 0) override {
        const int64_t avg = avg_latency_us_.load(std::memory_order_relaxed);
        if (avg <= 0 || remaining_us >= avg) return true;
        // Probe escape: if nothing has executed recently (e.g. every
        // request is being shed against an estimate from a past latency
        // incident), admit one request per probe interval so the EMA can
        // re-learn the CURRENT service time and un-latch. The probe
        // clock is PER PRIORITY CLASS (ISSUE 8 bugfix): with a single
        // global clock, a flooding low-priority tenant's requests kept
        // winning the probe CAS, so a latched high-priority class could
        // never re-measure while the low-priority probes were being
        // shed downstream — exactly the starvation the QoS tier exists
        // to prevent. A fresh success still un-latches every class at
        // once (the EMA is shared).
        const int64_t now = monotonic_time_us();
        const int slot = priority < 0 ? 0
                         : priority >= kProbeSlots ? kProbeSlots - 1
                                                   : priority;
        const int64_t last_success =
            last_sample_us_.load(std::memory_order_relaxed);
        int64_t last_probe =
            last_probe_us_[slot].load(std::memory_order_relaxed);
        const int64_t last = std::max(last_success, last_probe);
        if (now - last > opt_.probe_interval_ms * 1000 &&
            last_probe_us_[slot].compare_exchange_strong(
                last_probe, now, std::memory_order_relaxed)) {
            return true;
        }
        return false;
    }

    void OnResponded(int error_code, int64_t latency_us) override {
        if (error_code != 0) return;  // failures don't teach latency
        int64_t cur = avg_latency_us_.load(std::memory_order_relaxed);
        const int64_t next =
            cur <= 0 ? latency_us
                     : (int64_t)(cur * (1 - opt_.alpha) +
                                 latency_us * opt_.alpha);
        avg_latency_us_.store(next, std::memory_order_relaxed);
        last_sample_us_.store(monotonic_time_us(),
                              std::memory_order_relaxed);
    }

    int64_t MaxConcurrency() const override {
        const int64_t avg = avg_latency_us_.load(std::memory_order_relaxed);
        if (avg <= 0) return 0;  // unlimited until measured
        return std::max(opt_.min_concurrency,
                        opt_.timeout_ms * 1000 / avg);
    }

    int64_t avg_latency_us() const {
        return avg_latency_us_.load(std::memory_order_relaxed);
    }

private:
    // One probe clock per priority class (see AdmitWithBudget).
    static constexpr int kProbeSlots = 8;  // == qos.h kNumPriorities

    const Options opt_;
    std::atomic<int64_t> avg_latency_us_{0};
    // Last execution sample — shared anti-latch clock (a success means
    // the EMA is fresh for everyone).
    std::atomic<int64_t> last_sample_us_{0};
    // Last granted probe per priority class.
    std::atomic<int64_t> last_probe_us_[kProbeSlots] = {};
};

// "auto": the gradient limiter.
class AutoConcurrencyLimiter : public ConcurrencyLimiter {
public:
    struct Options {
        int64_t initial_max_concurrency = 40;
        int64_t min_max_concurrency = 4;    // never throttle below this
        int64_t sampling_interval_us = 100;  // min gap between samples
        int64_t sample_window_us = 1000 * 1000;
        int32_t min_sample_count = 100;
        int32_t max_sample_count = 200;
        double alpha_ema = 0.1;              // min-latency smoothing
        double fail_punish_ratio = 1.0;      // failed time charged to avg
        double max_explore_ratio = 0.3;
        double min_explore_ratio = 0.06;
        double explore_change_step = 0.02;
        double remeasure_reduce_ratio = 0.9;  // limit factor while probing
        int64_t remeasure_interval_us = 20 * 1000 * 1000;
    };

    AutoConcurrencyLimiter() : AutoConcurrencyLimiter(Options()) {}
    explicit AutoConcurrencyLimiter(const Options& opt)
        : opt_(opt),
          max_concurrency_(opt.initial_max_concurrency),
          remeasure_start_us_(0),
          reset_latency_us_(0),
          min_latency_us_(-1),
          ema_max_qps_(-1),
          explore_ratio_(opt.max_explore_ratio) {}

    bool OnRequested(int64_t current) override {
        return current <= max_concurrency_.load(std::memory_order_relaxed);
    }

    void OnResponded(int error_code, int64_t latency_us) override;

    int64_t MaxConcurrency() const override {
        return max_concurrency_.load(std::memory_order_relaxed);
    }

    // Exposed for tests: the smoothed no-load latency estimate.
    int64_t min_latency_us() const { return min_latency_us_; }
    double ema_max_qps() const { return ema_max_qps_; }
    // Completed limit recomputations (steady-state updates, remeasure
    // probes, and all-failed halvings). The per-tenant gradient tier
    // (ISSUE 15) exposes it so "the limit converged from measurement,
    // not a hand-set constant" is an assertable fact, not a belief.
    int64_t update_count() const {
        return nupdates_.load(std::memory_order_relaxed);
    }

private:
    // All called under sw_mu_.
    void UpdateMaxConcurrency(int64_t now_us);
    void ResetSampleWindow(int64_t now_us);

    struct SampleWindow {
        int64_t start_time_us = 0;
        int32_t succ_count = 0;
        int32_t failed_count = 0;
        int64_t total_failed_us = 0;
        int64_t total_succ_us = 0;
    };

    const Options opt_;
    std::atomic<int64_t> max_concurrency_;
    std::atomic<int64_t> nupdates_{0};
    // Window state (sampled path only).
    int64_t remeasure_start_us_;
    int64_t reset_latency_us_;
    int64_t min_latency_us_;
    double ema_max_qps_;
    double explore_ratio_;
    std::atomic<int64_t> last_sampling_time_us_{0};
    std::mutex sw_mu_;
    SampleWindow sw_;
};

}  // namespace tpurpc
