// Pluggable retry + backup-request policies.
//
// Reference parity: src/brpc/retry_policy.h:28-112 (RetryPolicy::DoRetry
// + RpcRetryPolicyWithFixedBackoff/JitteredBackoff) and
// src/brpc/backup_request_policy.h. The default behavior (connection-
// level errors retry immediately, no backoff) is DefaultRetryPolicy;
// channels override via ChannelOptions::retry_policy /
// backup_request_policy (not owned, must outlive the channel).
#pragma once

#include <cstdint>

#include "tbase/fast_rand.h"

namespace tpurpc {

class Controller;

class RetryPolicy {
public:
    virtual ~RetryPolicy() = default;
    // Called with the failed try's error set on `cntl` (ErrorCode()/
    // ErrorText()); true = retry (budget and deadline permitting).
    virtual bool DoRetry(const Controller* cntl) const = 0;
    // Delay before the retry is issued; 0 = immediate. Skipped when the
    // backoff would cross the RPC deadline (the retry then goes out
    // immediately, matching the reference's DoRetryWithBackoff guard).
    virtual int64_t BackoffMs(const Controller* cntl) const { return 0; }
};

// The framework default: connection-level failures retry, server-side
// errors / timeouts don't (reference DefaultRetryPolicy).
class DefaultRetryPolicy : public RetryPolicy {
public:
    bool DoRetry(const Controller* cntl) const override;
    static const DefaultRetryPolicy* instance();
};

class RetryPolicyWithFixedBackoff : public DefaultRetryPolicy {
public:
    explicit RetryPolicyWithFixedBackoff(int64_t backoff_ms)
        : backoff_ms_(backoff_ms) {}
    int64_t BackoffMs(const Controller*) const override {
        return backoff_ms_;
    }

private:
    int64_t backoff_ms_;
};

class RetryPolicyWithJitteredBackoff : public DefaultRetryPolicy {
public:
    RetryPolicyWithJitteredBackoff(int64_t min_ms, int64_t max_ms)
        : min_ms_(min_ms), max_ms_(max_ms < min_ms ? min_ms : max_ms) {}
    int64_t BackoffMs(const Controller*) const override {
        return min_ms_ + (int64_t)(fast_rand() %
                                   (uint64_t)(max_ms_ - min_ms_ + 1));
    }

private:
    int64_t min_ms_;
    int64_t max_ms_;
};

// Backup requests: when and whether to hedge (reference
// backup_request_policy.h). GetDelayMs < 0 disables for this call.
class BackupRequestPolicy {
public:
    virtual ~BackupRequestPolicy() = default;
    virtual int64_t GetDelayMs(const Controller* cntl) const = 0;
    // Consulted when the timer fires; false skips the backup (e.g. load
    // shedding).
    virtual bool DoBackup(const Controller* cntl) const { return true; }
};

}  // namespace tpurpc
