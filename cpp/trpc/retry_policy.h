// Pluggable retry + backup-request policies.
//
// Reference parity: src/brpc/retry_policy.h:28-112 (RetryPolicy::DoRetry
// + RpcRetryPolicyWithFixedBackoff/JitteredBackoff) and
// src/brpc/backup_request_policy.h. The default behavior (connection-
// level errors retry immediately, no backoff) is DefaultRetryPolicy;
// channels override via ChannelOptions::retry_policy /
// backup_request_policy (not owned, must outlive the channel).
#pragma once

#include <algorithm>
#include <atomic>
#include <cstdint>

#include "tbase/fast_rand.h"

namespace tpurpc {

class Controller;

class RetryPolicy {
public:
    virtual ~RetryPolicy() = default;
    // Called with the failed try's error set on `cntl` (ErrorCode()/
    // ErrorText()); true = retry (budget and deadline permitting).
    virtual bool DoRetry(const Controller* cntl) const = 0;
    // Delay before the retry is issued; 0 = immediate. Skipped when the
    // backoff would cross the RPC deadline (the retry then goes out
    // immediately, matching the reference's DoRetryWithBackoff guard).
    virtual int64_t BackoffMs(const Controller* cntl) const { return 0; }
};

// The framework default: connection-level failures retry, server-side
// errors / timeouts don't (reference DefaultRetryPolicy).
class DefaultRetryPolicy : public RetryPolicy {
public:
    bool DoRetry(const Controller* cntl) const override;
    static const DefaultRetryPolicy* instance();
};

class RetryPolicyWithFixedBackoff : public DefaultRetryPolicy {
public:
    explicit RetryPolicyWithFixedBackoff(int64_t backoff_ms)
        : backoff_ms_(backoff_ms) {}
    int64_t BackoffMs(const Controller*) const override {
        return backoff_ms_;
    }

private:
    int64_t backoff_ms_;
};

class RetryPolicyWithJitteredBackoff : public DefaultRetryPolicy {
public:
    RetryPolicyWithJitteredBackoff(int64_t min_ms, int64_t max_ms)
        : min_ms_(min_ms), max_ms_(max_ms < min_ms ? min_ms : max_ms) {}
    int64_t BackoffMs(const Controller*) const override {
        return min_ms_ + (int64_t)(fast_rand() %
                                   (uint64_t)(max_ms_ - min_ms_ + 1));
    }

private:
    int64_t min_ms_;
    int64_t max_ms_;
};

// Per-channel retry throttling (the gRPC "retry budget" / retry
// throttling shape): a token bucket holding up to `max_tokens` tokens,
// drained one token per RE-ISSUE (retry or backup request) and refilled
// by `token_ratio` tokens per success. Under a correlated failure every
// channel quickly exhausts its burst and stops re-issuing — the
// retry-storm amplification "RPC Considered Harmful" warns about is
// bounded at (burst + ratio * successes) instead of (max_retry *
// failures). Lock-free; tokens are tracked in milli-tokens so
// fractional ratios accumulate exactly.
class RetryBudget {
public:
    RetryBudget() = default;
    // max_tokens <= 0 disables throttling (Withdraw always grants).
    void Configure(int64_t max_tokens, double token_ratio) {
        max_milli_ = max_tokens * 1000;
        ratio_milli_ = (int64_t)(token_ratio * 1000.0);
        tokens_milli_.store(max_milli_ > 0 ? max_milli_ : 0,
                            std::memory_order_relaxed);
    }
    bool enabled() const { return max_milli_ > 0; }
    // Take one token for a re-issue; false = budget exhausted, do not
    // re-issue.
    bool Withdraw() {
        if (max_milli_ <= 0) return true;
        int64_t cur = tokens_milli_.load(std::memory_order_relaxed);
        while (cur >= 1000) {
            if (tokens_milli_.compare_exchange_weak(
                    cur, cur - 1000, std::memory_order_relaxed)) {
                return true;
            }
        }
        return false;
    }
    // Return a withdrawn token whose re-issue never went out (e.g. the
    // call-id version bump failed after Withdraw).
    void Refund() { DepositMilli(1000); }
    // A completed success earns `token_ratio` tokens back (capped).
    void OnSuccess() { DepositMilli(ratio_milli_); }
    int64_t tokens() const {
        return tokens_milli_.load(std::memory_order_relaxed) / 1000;
    }

private:
    void DepositMilli(int64_t amount) {
        if (max_milli_ <= 0 || amount <= 0) return;
        int64_t cur = tokens_milli_.load(std::memory_order_relaxed);
        while (cur < max_milli_) {
            const int64_t next = std::min(max_milli_, cur + amount);
            if (tokens_milli_.compare_exchange_weak(
                    cur, next, std::memory_order_relaxed)) {
                return;
            }
        }
    }

    int64_t max_milli_ = 0;
    int64_t ratio_milli_ = 0;
    std::atomic<int64_t> tokens_milli_{0};
};

// Backup requests: when and whether to hedge (reference
// backup_request_policy.h). GetDelayMs < 0 disables for this call.
class BackupRequestPolicy {
public:
    virtual ~BackupRequestPolicy() = default;
    virtual int64_t GetDelayMs(const Controller* cntl) const = 0;
    // Consulted when the timer fires; false skips the backup (e.g. load
    // shedding).
    virtual bool DoBackup(const Controller* cntl) const { return true; }
};

}  // namespace tpurpc
