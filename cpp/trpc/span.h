// rpcz spans: per-RPC phase timelines, sampled via the Collector and
// browsable at /rpcz.
//
// Modeled on reference src/brpc/span.h:47-120 (Span with client/server
// phase timestamps, trace/span/parent ids propagated through RpcMeta,
// SpanDB storage, rendered by builtin/rpcz_service.cpp). Enabled by the
// live flag -enable_rpcz (settable through /flags like the reference's
// gflag).
#pragma once

#include <cstdint>
#include <deque>
#include <mutex>
#include <string>
#include <vector>

#include "tbase/endpoint.h"
#include "tvar/collector.h"

namespace tpurpc {

struct Span : public Collected {
    enum Kind { CLIENT = 0, SERVER = 1 };

    Kind kind = CLIENT;
    uint64_t trace_id = 0;
    uint64_t span_id = 0;
    uint64_t parent_span_id = 0;
    std::string method;
    EndPoint remote_side;
    int error_code = 0;
    int64_t request_bytes = 0;
    int64_t response_bytes = 0;
    int retries = 0;  // client: re-issues (retry or backup) after the first

    // Phase timestamps (monotonic us). Client: start -> sent ->
    // response_received -> end. Server: received -> process_start ->
    // process_end(=response send begins) -> end(=response queued).
    int64_t start_us = 0;
    int64_t sent_us = 0;
    int64_t received_us = 0;
    int64_t process_start_us = 0;
    int64_t process_end_us = 0;
    int64_t end_us = 0;

    // Free-form annotations with timestamps (reference Span::Annotate).
    struct Note {
        int64_t at_us;
        std::string text;
    };
    std::vector<Note> notes;

    void Annotate(const std::string& text);

    void dispatch() override;  // moves *this into the SpanDB
};

// Fixed-capacity store of recently completed spans (the reference keeps a
// time-indexed SpanDB; a bounded ring is enough for a live portal).
// Capacity sized so a trace survives several seconds of full-rate
// background sampling before the stitcher scrapes it.
class SpanDB {
public:
    static SpanDB* singleton();

    void Add(Span&& s);
    // Newest-first snapshot; trace_id == 0 means all.
    std::vector<Span> Recent(size_t limit, uint64_t trace_id = 0) const;

private:
    static constexpr size_t kCapacity = 4096;
    mutable std::mutex mu_;
    std::deque<Span> spans_;
};

// True when this RPC should carry a span (flag on + sampling gate open).
bool IsRpczSampled();
// Flag alone (for continuing an upstream-sampled trace: the remote's
// sampling decision is honored, but only while rpcz is locally enabled —
// peers must not be able to force span allocation on a disabled server).
bool IsRpczEnabled();
// Render the /rpcz page (newest-first; trace filter optional).
std::string RenderRpcz(uint64_t trace_id_filter);
// Machine-readable spans for the cross-host stitcher:
// {"host":"ip:port","spans":[{...}]} — consumed by
// /rpcz?format=json&trace_id=N and parsed back by trpc/rpcz_stitch.cc.
std::string RenderRpczJson(uint64_t trace_id_filter);

// This process's identity in stitched traces ("ip:port" of the serving
// portal). Set once by the first Server::Start; defaults to "pid:<n>".
void SetRpczHost(const std::string& host);
const std::string& RpczHost();

}  // namespace tpurpc
