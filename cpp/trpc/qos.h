// Multi-tenant QoS: per-tenant quotas, weighted-fair dispatch, and
// priority-aware overload shedding (ISSUE 8, ROADMAP item 3).
//
// The reference's admission tier (auto_concurrency_limiter) bounds TOTAL
// concurrency but is tenant-blind: one flooding tenant drives the
// limiter into shedding everyone. This tier sits in front of handler
// spawn and makes graceful degradation mean "low priority sheds first,
// high-priority p99 stays flat":
//
//  * TokenBucket — per-tenant QPS quota (milli-token precision, refilled
//    by elapsed monotonic time, bounded burst).
//  * QosDispatcher — per-server: tenant registry (quota + inflight +
//    labelled tvars), a weighted-fair dispatch queue (strict priority
//    levels, deficit-round-robin across tenants within a level), and
//    priority-aware shedding when the queue crosses its high-water or
//    the concurrency limiter rejects (evict lowest-priority-first, never
//    first-come-first-served collapse). Shed responses carry
//    TERR_OVERLOAD plus a server-suggested backoff the client honors
//    with jitter while SPENDING retry budget (no free re-issue storms).
//  * RendezvousSubset — deterministic client-side subsetting (HRW hash)
//    so huge client fleets don't full-mesh every server; stable under
//    node churn (removing one member only pulls in the next-highest
//    scorer). Used by LoadBalancerWithNaming under every LB policy.
//
// Everything here is protobuf-free by design: the whole tier links into
// the standalone (toolchain-less) tnet/tvar test harness and is unit-
// tested in cpp/tests/tqos_test.cc.
#pragma once

#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <deque>
#include <map>
#include <mutex>
#include <shared_mutex>
#include <string>
#include <vector>

#include "tfiber/fiber.h"
#include "tvar/latency_recorder.h"
#include "tvar/multi_dimension.h"
#include "tvar/reducer.h"

namespace tpurpc {

// Priority classes carried on the wire (tpu_std RpcRequestMeta.priority /
// the x-tpu-priority header): 0 = most sheddable, 7 = most protected.
// Out-of-range wire values are clamped, absent ones default to the
// middle so "no priority set" is neither privileged nor doomed.
constexpr int kMinPriority = 0;
constexpr int kMaxPriority = 7;
constexpr int kNumPriorities = kMaxPriority - kMinPriority + 1;
constexpr int kDefaultPriority = 4;

inline int ClampPriority(int64_t p) {
    if (p < kMinPriority) return kMinPriority;
    if (p > kMaxPriority) return kMaxPriority;
    return (int)p;
}

// The x-tpu-priority header, strictly parsed: absent or non-numeric
// values get the DEFAULT class, not 0 — garbage in a header must not
// silently make a request maximally sheddable.
inline int PriorityFromHeader(const std::string* v) {
    if (v == nullptr || v->empty()) return kDefaultPriority;
    char* end = nullptr;
    const long p = strtol(v->c_str(), &end, 10);
    if (end == v->c_str() || *end != '\0') return kDefaultPriority;
    return ClampPriority(p);
}

// Per-tenant quota. qps <= 0 means "no rate cap"; max_concurrency <= 0
// means "no concurrency share cap"; weight is the DRR share of dispatch
// slots under contention (relative to other tenants at the same
// priority level).
struct TenantQuota {
    double qps = 0;            // admitted requests/second (0 = unlimited)
    int64_t burst = 0;         // bucket depth; 0 = max(qps/10, 8)
    int weight = 1;            // weighted-fair dispatch share
    int64_t max_concurrency = 0;  // concurrent handlers (0 = unlimited)
};

// "tenant:qps=300,burst=64,w=1,conc=8;other:w=8" -> quotas. Unknown keys
// and malformed entries are skipped (returns false if ANYTHING was
// skipped, so flag validation can complain while still applying the
// valid part).
bool ParseQuotaSpec(const std::string& spec,
                    std::map<std::string, TenantQuota>* out);

// Monotonic-time token bucket (milli-token precision so fractional
// refill accumulates exactly). Thread-safe; one CAS per admit.
// Configure may be called at runtime under traffic (re-quota): the rate
// and burst are atomics read relaxed by concurrent admitters.
class TokenBucket {
public:
    TokenBucket() = default;
    // rate_per_s <= 0 disables (TryWithdraw always grants).
    void Configure(double rate_per_s, int64_t burst);
    bool enabled() const {
        return rate_milli_per_s_.load(std::memory_order_relaxed) > 0;
    }
    // Take one token at `now_us`; false = dry. On false, *wait_ms is the
    // suggested wait until a token accrues (>= 1).
    bool TryWithdraw(int64_t now_us, int64_t* wait_ms);
    int64_t tokens() const {
        return tokens_milli_.load(std::memory_order_relaxed) / 1000;
    }

private:
    void RefillLocked(int64_t now_us);

    std::atomic<int64_t> rate_milli_per_s_{0};  // milli-tokens/second
    std::atomic<int64_t> burst_milli_{0};
    std::atomic<int64_t> tokens_milli_{0};
    std::atomic<int64_t> last_refill_us_{0};
    std::mutex refill_mu_;  // refill is rare (>= 1ms granularity)
};

// Rendezvous (highest-random-weight) subsetting: pick k of `keys`
// deterministically for this `seed`. Stable under churn: each member's
// score depends only on (seed, key), so removing one chosen member pulls
// in exactly the next-highest scorer and every other choice stays put.
// Returns indexes into `keys` (unordered).
std::vector<size_t> RendezvousSubset(uint64_t seed,
                                     const std::vector<std::string>& keys,
                                     size_t k);

// The per-server multi-tenant dispatch tier. All entry points are
// thread-safe; the drainer is one fiber parked on a butex.
class QosDispatcher {
public:
    // One queued dispatch unit. `run` dispatches the handler (ownership
    // of arg passes to it); `shed` answers TERR_OVERLOAD with the given
    // suggested backoff and releases arg. Exactly one of the two is
    // invoked for every enqueued item, always outside the queue lock.
    struct Item {
        void (*run)(void* arg) = nullptr;
        void (*shed)(void* arg, int64_t backoff_ms) = nullptr;
        void* arg = nullptr;
    };

    struct TenantState {
        std::string name;
        // Display copy of the configured quota (written under the
        // registry lock; /tenants reads under it too). The fields the
        // DISPATCH paths read are the atomics below, so a runtime
        // re-quota never races the hot path.
        TenantQuota quota;
        TokenBucket bucket;
        std::atomic<int> weight{1};
        std::atomic<int64_t> max_concurrency{0};
        std::atomic<int64_t> inflight{0};
        // Labelled tvar cells (family instances owned process-wide).
        IntCell* admitted = nullptr;
        IntCell* shed = nullptr;
        IntCell* queued = nullptr;
        LatencyRecorder* latency = nullptr;

        // ---- DRR state, all guarded by QosDispatcher::mu_ ----
        std::deque<Item> q[kNumPriorities];
        bool in_active[kNumPriorities] = {};
        int deficit[kNumPriorities] = {};
    };

    QosDispatcher();
    ~QosDispatcher();

    // (Re)configure from parsed quotas; force_enable turns the tier on
    // even with no quotas (every tenant then gets the default weight-1
    // unlimited quota — fairness and priority shedding still apply).
    void Configure(const std::map<std::string, TenantQuota>& quotas,
                   bool force_enable);
    // Set/replace one tenant's quota (Server::SetTenantQuota; callable
    // at runtime). Enables the tier.
    void SetTenantQuota(const std::string& tenant, const TenantQuota& q);

    bool enabled() const { return enabled_.load(std::memory_order_acquire); }

    // Tenant handle for one request ("" maps to "default"; past
    // -rpc_max_tenants distinct names, the overflow tenant "other"
    // absorbs newcomers so a cardinality attack can't flood the metric
    // registry). The pointer lives as long as the dispatcher.
    TenantState* Acquire(const std::string& tenant);

    // Stage 1 — rate quota: one token at `now`; false = shed NOW with
    // TERR_OVERLOAD and the returned suggested backoff (also counted on
    // the tenant's shed tvar).
    bool AdmitQps(TenantState* t, int64_t now_us, int64_t* backoff_ms);

    // Stage 3a — uncontended fast path: true when the fair queue is
    // empty AND `t` is under its concurrency share; the request is
    // accounted (inflight + admitted) and the caller dispatches directly
    // (the PR-6 inline path stays legal exactly here).
    bool TryDirectDispatch(TenantState* t);
    // Same accounting without the queue-empty gate — protocols that
    // don't ride the fair queue (h2/HTTP) still get per-tenant
    // accounting and concurrency visibility.
    void BeginServed(TenantState* t);

    // Stage 3b — fair queue: enqueue under (priority, tenant-DRR). Past
    // the high-water the LOWEST-priority queued item below `priority` is
    // evicted (its shed callback runs) to make room; with nothing lower,
    // the newcomer itself is shed. Returns false when the newcomer was
    // shed synchronously.
    bool Enqueue(TenantState* t, int priority, const Item& item);

    // Priority-aware relief for concurrency-limiter rejections: evict
    // ONE queued item of priority strictly below `priority` (its shed
    // callback runs). True = evicted (the caller may force-admit the
    // higher-priority request in its place).
    bool EvictOneBelow(int priority);

    // Handler completion for every admitted (direct or popped) request:
    // inflight decrement, latency feed, drainer wake (a freed
    // concurrency share may unblock a queued tenant).
    void OnDone(TenantState* t, int64_t latency_us);

    // Count a shed that happened outside the queue (qps quota, limiter
    // reject without eviction relief).
    void CountShed(TenantState* t);

    // Suggested backoff for queue/limiter sheds (-rpc_overload_backoff_ms).
    int64_t SuggestedBackoffMs() const;

    // Drainer lifecycle (Server::StartNoListen / Server::Stop). Stop
    // sheds everything still queued so admission accounting drains.
    void StartDrainer();
    void StopDrainer();

    int64_t queue_depth() const {
        return depth_.load(std::memory_order_relaxed);
    }

    // Pop one item in strict-priority + DRR order. Returns false when
    // the queue is empty or every queued tenant is over its concurrency
    // share. On success the item is accounted like a direct dispatch.
    // Public for tests; the drainer is the production caller.
    bool Pop(Item* out, TenantState** owner, int* priority);

    // /tenants portal renderings.
    std::string DescribeText() const;
    std::string DescribeJson() const;

private:
    struct Level {
        std::deque<TenantState*> active;  // tenants with queued items
    };

    bool PopLocked(Item* out, TenantState** owner, int* priority);
    // Evict one item from the lowest non-empty level strictly below
    // `limit_prio`, from the tenant with the deepest queue there (the
    // flooder sheds first). Appends the item to *out_shed.
    bool EvictLowestLocked(int limit_prio, std::vector<Item>* out_shed,
                           std::vector<TenantState*>* out_owners);
    void WakeDrainer();
    static void* DrainerThunk(void* arg);
    void DrainerLoop();

    std::atomic<bool> enabled_{false};

    // Reader-heavy registry: every request resolves its tenant here, so
    // lookups take the lock shared; only tenant creation / re-quota /
    // the /tenants page take it exclusive.
    mutable std::shared_mutex tenants_mu_;
    std::map<std::string, std::unique_ptr<TenantState>> tenants_;
    // Quota templates applied to tenants on first Acquire. configured_
    // is the merged view (flag ∪ explicit, explicit wins); explicit_
    // remembers SetTenantQuota calls so a later Configure (flag apply
    // at Start / restart) can never silently drop them.
    std::map<std::string, TenantQuota> configured_;
    std::map<std::string, TenantQuota> explicit_;

    mutable std::mutex mu_;  // queue + DRR state
    Level levels_[kNumPriorities];
    std::atomic<int64_t> depth_{0};

    void* wake_butex_ = nullptr;
    fiber_t drainer_ = 0;
    bool drainer_running_ = false;  // guarded by drainer_mu_
    std::mutex drainer_mu_;
    std::atomic<bool> stop_{false};
};

}  // namespace tpurpc
