// Multi-tenant QoS: work-priced admission, per-tenant gradient
// concurrency, weighted-fair dispatch, and queue-delay-driven overload
// shedding (ISSUE 8 + ISSUE 15, ROADMAP items 3/4).
//
// The reference's admission tier (auto_concurrency_limiter) bounds TOTAL
// concurrency but is tenant-blind: one flooding tenant drives the
// limiter into shedding everyone. This tier sits in front of handler
// spawn and makes graceful degradation mean "low priority sheds first,
// high-priority p99 stays flat":
//
//  * Cost model (ISSUE 15) — admission PRICES WORK instead of counting
//    requests: each completion folds its measured service time and
//    logical bytes (inline + descriptor-exempt) into milli-cost units
//    (1000 = one baseline request; ComputeCostMilli), tracked as a
//    per-(tenant, method) EWMA. A request is charged its tenant's
//    current estimate at admission, so a tenant inside its request-rate
//    quota can no longer sink the server with few-but-heavy calls.
//    Cross-zone spill arrivals (a partitioned pod's overflow) pay
//    -rpc_spill_cost_multiplier on top, and shed first within a
//    priority level.
//  * TokenBucket — per-tenant quota in COST units/second (milli-token
//    precision, refilled by elapsed monotonic time, bounded burst; a
//    call costing more than the burst admits only at a full bucket and
//    leaves the bucket in debt).
//  * Per-tenant gradient concurrency (ISSUE 15) — tenants without an
//    explicit conc= share get their own AutoConcurrencyLimiter, so each
//    tenant's limit CONVERGES from observed latency gradients with no
//    manual -max_concurrency tuning (-rpc_tenant_gradient_limit;
//    cardinality-bounded exactly like the tenant registry itself).
//  * QosDispatcher — per-server: tenant registry (quota + inflight +
//    labelled tvars), a weighted-fair dispatch queue (strict priority
//    levels, deficit-round-robin across tenants within a level — each
//    dequeue charges the item's estimated COST against the tenant's
//    deficit, so a heavy call burns proportionally more of its turn),
//    and priority-aware shedding (evict lowest-priority-first, spills
//    before local work, never first-come-first-served collapse). Shed
//    decisions derive from the MEASURED fair-queue sojourn time
//    (CoDel-style -rpc_queue_delay_target_ms/-rpc_queue_delay_
//    interval_ms) with -rpc_fair_queue_highwater as the absolute
//    backstop; the TERR_OVERLOAD backoff hint derives from the queue's
//    cost backlog over its measured drain rate. The client honors the
//    hint with jitter while SPENDING retry budget (no free re-issue
//    storms).
//  * RendezvousSubset — deterministic client-side subsetting (HRW hash)
//    so huge client fleets don't full-mesh every server; stable under
//    node churn (removing one member only pulls in the next-highest
//    scorer). Used by LoadBalancerWithNaming under every LB policy.
//
// Everything here is protobuf-free by design: the whole tier links into
// the standalone (toolchain-less) tnet/tvar test harness and is unit-
// tested in cpp/tests/tqos_test.cc.
#pragma once

#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <shared_mutex>
#include <string>
#include <vector>

#include "tbase/endpoint.h"
#include "tfiber/fiber.h"
#include "trpc/concurrency_limiter.h"
#include "tvar/latency_recorder.h"
#include "tvar/multi_dimension.h"
#include "tvar/reducer.h"

namespace tpurpc {

// Priority classes carried on the wire (tpu_std RpcRequestMeta.priority /
// the x-tpu-priority header): 0 = most sheddable, 7 = most protected.
// Out-of-range wire values are clamped, absent ones default to the
// middle so "no priority set" is neither privileged nor doomed.
constexpr int kMinPriority = 0;
constexpr int kMaxPriority = 7;
constexpr int kNumPriorities = kMaxPriority - kMinPriority + 1;
constexpr int kDefaultPriority = 4;

inline int ClampPriority(int64_t p) {
    if (p < kMinPriority) return kMinPriority;
    if (p > kMaxPriority) return kMaxPriority;
    return (int)p;
}

// The x-tpu-priority header, strictly parsed: absent or non-numeric
// values get the DEFAULT class, not 0 — garbage in a header must not
// silently make a request maximally sheddable.
inline int PriorityFromHeader(const std::string* v) {
    if (v == nullptr || v->empty()) return kDefaultPriority;
    char* end = nullptr;
    const long p = strtol(v->c_str(), &end, 10);
    if (end == v->c_str() || *end != '\0') return kDefaultPriority;
    return ClampPriority(p);
}

// Per-tenant quota. qps <= 0 means "no rate cap"; max_concurrency <= 0
// means "no EXPLICIT concurrency share cap" (the tenant then gets its
// own self-tuning gradient limiter — see TenantState::gradient); weight
// is the DRR share of dispatch COST under contention (relative to other
// tenants at the same priority level).
struct TenantQuota {
    // Admitted COST UNITS/second (0 = unlimited). One baseline request
    // (light payload, ~-rpc_cost_ref_us of service time) costs one
    // unit, so for ordinary traffic this keeps its request-per-second
    // reading; heavy calls are priced by their measured cost.
    double qps = 0;
    int64_t burst = 0;         // bucket depth in units; 0 = max(qps/10, 8)
    int weight = 1;            // weighted-fair dispatch share
    int64_t max_concurrency = 0;  // concurrent handlers (0 = gradient)
};

// ---- cost model (ISSUE 15) ----

// Milli-cost units: 1000 = one baseline request.
constexpr int64_t kCostUnitMilli = 1000;

// Fold measured service time + logical payload bytes (inline AND
// descriptor-exempt — the referenced bytes never ride the message path
// but they ARE the work) into milli-cost: svc_us/-rpc_cost_ref_us plus
// bytes/-rpc_cost_ref_kb KiB, floored at one unit and capped so one
// pathological sample cannot park a tenant's bucket in unbounded debt.
int64_t ComputeCostMilli(int64_t svc_us, int64_t logical_bytes);

// True when `peer_zone` names a zone and it differs from this node's
// -rpc_zone (both set): the request is a cross-pod spill arrival.
bool SpillArrival(const std::string& peer_zone);

// The -rpc_spill_cost_multiplier applied to a spill arrival's charge: a
// partitioned pod's overflow must not starve local gold traffic.
int64_t SpillAdjustedCostMilli(int64_t cost_milli);

// Default tuning for per-TENANT gradient limiters: a tenant is a whole
// traffic class, not one method, so its floor/initial sit well above
// the per-method limiter's — a briefly-congested light tenant must
// never be pinched below the handful of concurrent handlers its steady
// trickle needs (ServerOptions::tenant_gradient_options overrides).
inline AutoConcurrencyLimiter::Options DefaultTenantGradientOptions() {
    AutoConcurrencyLimiter::Options o;
    o.initial_max_concurrency = 64;
    o.min_max_concurrency = 16;
    return o;
}

// "tenant:qps=300,burst=64,w=1,conc=8;other:w=8" -> quotas. Unknown keys
// and malformed entries are skipped (returns false if ANYTHING was
// skipped, so flag validation can complain while still applying the
// valid part).
bool ParseQuotaSpec(const std::string& spec,
                    std::map<std::string, TenantQuota>* out);

// Monotonic-time token bucket (milli-token precision so fractional
// refill accumulates exactly). Thread-safe; one CAS per admit.
// Configure may be called at runtime under traffic (re-quota): the rate
// and burst are atomics read relaxed by concurrent admitters.
class TokenBucket {
public:
    TokenBucket() = default;
    // rate_per_s <= 0 disables (TryWithdraw always grants). Rate/burst
    // are in COST units (see kCostUnitMilli).
    void Configure(double rate_per_s, int64_t burst);
    bool enabled() const {
        return rate_milli_per_s_.load(std::memory_order_relaxed) > 0;
    }
    // Take one baseline unit at `now_us`; false = dry. On false,
    // *wait_ms is the suggested wait until it accrues (>= 1).
    bool TryWithdraw(int64_t now_us, int64_t* wait_ms) {
        return TryWithdrawCost(now_us, kCostUnitMilli, wait_ms);
    }
    // Work-priced withdrawal (ISSUE 15): take `cost_milli` milli-units.
    // A cost above the burst depth admits only at a FULL bucket and
    // leaves the bucket in debt — heavy calls are rate-priced exactly,
    // never permanently starved by their own size. On false, *wait_ms
    // is the wait until the required tokens accrue at the configured
    // rate (clamped to something a client can reasonably sleep).
    bool TryWithdrawCost(int64_t now_us, int64_t cost_milli,
                         int64_t* wait_ms);
    int64_t tokens() const {
        return tokens_milli_.load(std::memory_order_relaxed) / 1000;
    }

private:
    void RefillLocked(int64_t now_us);

    std::atomic<int64_t> rate_milli_per_s_{0};  // milli-tokens/second
    std::atomic<int64_t> burst_milli_{0};
    std::atomic<int64_t> tokens_milli_{0};
    std::atomic<int64_t> last_refill_us_{0};
    std::mutex refill_mu_;  // refill is rare (>= 1ms granularity)
};

// Rendezvous (highest-random-weight) subsetting: pick k of `keys`
// deterministically for this `seed`. Stable under churn: each member's
// score depends only on (seed, key), so removing one chosen member pulls
// in exactly the next-highest scorer and every other choice stays put.
// Returns indexes into `keys` (unordered).
std::vector<size_t> RendezvousSubset(uint64_t seed,
                                     const std::vector<std::string>& keys,
                                     size_t k);

// The per-server multi-tenant dispatch tier. All entry points are
// thread-safe; the drainer is one fiber parked on a butex.
class QosDispatcher {
public:
    // One queued dispatch unit. `run` dispatches the handler (ownership
    // of arg passes to it); `shed` answers TERR_OVERLOAD with the given
    // suggested backoff and releases arg. Exactly one of the two is
    // invoked for every enqueued item, always outside the queue lock.
    struct Item {
        void (*run)(void* arg) = nullptr;
        void (*shed)(void* arg, int64_t backoff_ms) = nullptr;
        void* arg = nullptr;
        // Estimated charge (spill-adjusted): burned against the
        // tenant's DRR deficit at dequeue and against the queue's cost
        // backlog for the drain-rate/backoff math.
        int64_t cost_milli = kCostUnitMilli;
        // Enqueue stamp for the sojourn measurement. 0 = Enqueue stamps
        // `now` (tests may pre-stamp to simulate a stale queue).
        int64_t enqueue_us = 0;
        // Cross-zone spill arrival: shed FIRST within its priority
        // level — a partitioned pod's overflow never evicts local work
        // of the same class.
        bool spill = false;
    };

    // Completion context for OnDone (ISSUE 15): everything the cost
    // model and the gradient limiter learn from. A default-constructed
    // info (method == nullptr) feeds latency/inflight only.
    struct CompletionInfo {
        int error_code = 0;
        const std::string* method = nullptr;  // cost-model key
        int64_t logical_bytes = 0;  // inline + descriptor-exempt payload
        EndPoint peer;              // chaos cost_inflate scoping
    };

    struct TenantState {
        std::string name;
        // Display copy of the configured quota (written under the
        // registry lock; /tenants reads under it too). The fields the
        // DISPATCH paths read are the atomics below, so a runtime
        // re-quota never races the hot path.
        TenantQuota quota;
        TokenBucket bucket;
        std::atomic<int> weight{1};
        std::atomic<int64_t> max_concurrency{0};
        std::atomic<int64_t> inflight{0};
        // Labelled tvar cells (family instances owned process-wide).
        IntCell* admitted = nullptr;
        IntCell* shed = nullptr;
        IntCell* queued = nullptr;
        LatencyRecorder* latency = nullptr;
        // Cost accounting (ISSUE 15): estimated milli-cost admitted /
        // shed, the measured per-request cost distribution, and the
        // gradient limiter's current limit.
        IntCell* cost_admitted = nullptr;
        IntCell* cost_shed = nullptr;
        LatencyRecorder* cost_units = nullptr;
        IntCell* gradient_limit_cell = nullptr;
        // Self-tuning concurrency (ISSUE 15): consulted whenever no
        // explicit conc= share is configured (max_concurrency <= 0) and
        // -rpc_tenant_gradient_limit is on. Created with the tenant, so
        // dispatch paths read it without the registry lock.
        std::unique_ptr<AutoConcurrencyLimiter> gradient;
        // Per-method measured-cost EWMAs (milli-units). Bounded by
        // -rpc_cost_max_methods; strangers fold into "other" exactly
        // like the tenant registry itself.
        mutable std::shared_mutex cost_mu;
        std::map<std::string, int64_t> method_cost_milli;

        // ---- DRR state, all guarded by QosDispatcher::mu_ ----
        std::deque<Item> q[kNumPriorities];
        bool in_active[kNumPriorities] = {};
        // Cost-deficit (milli-units): a dequeue charges the item's
        // estimated cost, so one heavy call burns many turns' worth.
        int64_t deficit[kNumPriorities] = {};
        // Queued spill items per level: eviction only walks a queue's
        // items when this says a spill is actually in it, keeping the
        // common no-spill eviction O(#tenants), not O(queue depth).
        int spill_count[kNumPriorities] = {};
    };

    QosDispatcher();
    ~QosDispatcher();

    // (Re)configure from parsed quotas; force_enable turns the tier on
    // even with no quotas (every tenant then gets the default weight-1
    // unlimited quota — fairness and priority shedding still apply).
    void Configure(const std::map<std::string, TenantQuota>& quotas,
                   bool force_enable);
    // Set/replace one tenant's quota (Server::SetTenantQuota; callable
    // at runtime). Enables the tier.
    void SetTenantQuota(const std::string& tenant, const TenantQuota& q);

    bool enabled() const { return enabled_.load(std::memory_order_acquire); }

    // Tenant handle for one request ("" maps to "default"; past
    // -rpc_max_tenants distinct names, the overflow tenant "other"
    // absorbs newcomers so a cardinality attack can't flood the metric
    // registry). The pointer lives as long as the dispatcher.
    TenantState* Acquire(const std::string& tenant);

    // Per-tenant gradient limiter tuning applied to tenants created
    // AFTER this call (ServerOptions::tenant_gradient_options; tests
    // tighten the windows). Call before traffic.
    void SetGradientOptions(const AutoConcurrencyLimiter::Options& opt);

    // Cost estimate for one request of `method` from tenant `t`: the
    // measured EWMA when one exists (exact method, else the method
    // overflow bucket), else one baseline unit. Milli-units; spill
    // adjustment is the CALLER's job (SpillAdjustedCostMilli) so the
    // model itself stays zone-neutral.
    int64_t EstimateCostMilli(TenantState* t,
                              const std::string& method) const;

    // Stage 1 — rate quota, work-priced: withdraw `cost_milli` at
    // `now`; false = shed NOW with TERR_OVERLOAD and the returned
    // suggested backoff (also counted on the tenant's shed tvars).
    bool AdmitCost(TenantState* t, int64_t now_us, int64_t cost_milli,
                   int64_t* backoff_ms);

    // Stage 3a — uncontended fast path: true when the fair queue is
    // empty AND `t` is under its concurrency limit (explicit share, or
    // its gradient limiter's converged limit); the request is accounted
    // (inflight + admitted + cost) and the caller dispatches directly
    // (the PR-6 inline path stays legal exactly here).
    bool TryDirectDispatch(TenantState* t,
                           int64_t cost_milli = kCostUnitMilli);
    // Same accounting without the queue-empty gate — protocols that
    // don't ride the fair queue (h2/HTTP) still get per-tenant
    // accounting and concurrency visibility.
    void BeginServed(TenantState* t, int64_t cost_milli = kCostUnitMilli);

    // Stage 3b — fair queue: enqueue under (priority, tenant-DRR). Past
    // the high-water the LOWEST-priority queued item below `priority` is
    // evicted (its shed callback runs) to make room; with nothing lower,
    // the newcomer itself is shed. Returns false when the newcomer was
    // shed synchronously.
    bool Enqueue(TenantState* t, int priority, const Item& item);

    // Priority-aware relief for concurrency-limiter rejections: evict
    // ONE queued item of priority strictly below `priority` (its shed
    // callback runs). True = evicted (the caller may force-admit the
    // higher-priority request in its place).
    bool EvictOneBelow(int priority);

    // Handler completion for every admitted (direct or popped) request:
    // inflight decrement, latency feed, gradient-limiter feedback, cost
    // observation (with the chaos cost_inflate seam applied), drainer
    // wake (a freed concurrency share may unblock a queued tenant).
    void OnDone(TenantState* t, int64_t latency_us,
                const CompletionInfo& info);
    void OnDone(TenantState* t, int64_t latency_us) {
        OnDone(t, latency_us, CompletionInfo());
    }

    // Count a shed that happened outside the queue (rate quota, limiter
    // reject without eviction relief). `cost_milli` lands on the
    // tenant's cost_shed tvar.
    void CountShed(TenantState* t, int64_t cost_milli = kCostUnitMilli);

    // Suggested backoff for queue/limiter sheds: the queue's current
    // cost backlog over its MEASURED drain rate (time until the queue
    // empties at the observed service speed), floored at
    // -rpc_overload_backoff_ms and capped at 2s. With no drain
    // measurement yet (cold queue), the flag floor alone.
    int64_t SuggestedBackoffMs() const;

    // Observability reads for /tenants + the soaks.
    int64_t QueueDelayEwmaUs() const {
        return queue_delay_ewma_us_.load(std::memory_order_relaxed);
    }
    int64_t DrainRateCostPerS() const {
        return drain_rate_milli_per_s_.load(std::memory_order_relaxed) /
               kCostUnitMilli;
    }
    bool OverDelayTarget() const {
        return over_target_.load(std::memory_order_relaxed);
    }
    // Effective concurrency limit for one tenant: the explicit share if
    // set, else the gradient limiter's current limit, else 0
    // (unlimited). Public for tests and the portal.
    int64_t TenantConcurrencyLimit(const TenantState* t) const;

    // Drainer lifecycle (Server::StartNoListen / Server::Stop). Stop
    // sheds everything still queued so admission accounting drains.
    void StartDrainer();
    void StopDrainer();

    int64_t queue_depth() const {
        return depth_.load(std::memory_order_relaxed);
    }

    // Pop one item in strict-priority + DRR order. Returns false when
    // the queue is empty or every queued tenant is over its concurrency
    // share. On success the item is accounted like a direct dispatch.
    // Public for tests; the drainer is the production caller.
    bool Pop(Item* out, TenantState** owner, int* priority);

    // /tenants portal renderings.
    std::string DescribeText() const;
    std::string DescribeJson() const;

private:
    struct Level {
        std::deque<TenantState*> active;  // tenants with queued items
    };

    bool PopLocked(Item* out, TenantState** owner, int* priority);
    // Evict one item from the lowest non-empty level strictly below
    // `limit_prio` — a SPILL item first (newest, from the deepest
    // spill-holding queue), else the newest item of the deepest queue
    // there (the flooder sheds first). Appends the item to *out_shed.
    bool EvictLowestLocked(int limit_prio, std::vector<Item>* out_shed,
                           std::vector<TenantState*>* out_owners);
    // Sojourn + drain-rate bookkeeping for one dequeued/evicted item
    // (mu_ held). `served` items feed the CoDel window; evictions only
    // reduce the backlog.
    void AccountDequeueLocked(const Item& it, int64_t now_us, bool served);
    void WakeDrainer();
    static void* DrainerThunk(void* arg);
    void DrainerLoop();

    std::atomic<bool> enabled_{false};

    // Reader-heavy registry: every request resolves its tenant here, so
    // lookups take the lock shared; only tenant creation / re-quota /
    // the /tenants page take it exclusive.
    mutable std::shared_mutex tenants_mu_;
    std::map<std::string, std::unique_ptr<TenantState>> tenants_;
    // Quota templates applied to tenants on first Acquire. configured_
    // is the merged view (flag ∪ explicit, explicit wins); explicit_
    // remembers SetTenantQuota calls so a later Configure (flag apply
    // at Start / restart) can never silently drop them.
    std::map<std::string, TenantQuota> configured_;
    std::map<std::string, TenantQuota> explicit_;

    mutable std::mutex mu_;  // queue + DRR state
    Level levels_[kNumPriorities];
    std::atomic<int64_t> depth_{0};
    // Cost backlog of everything queued (milli-units): the numerator of
    // the drain-derived backoff hint.
    std::atomic<int64_t> backlog_cost_milli_{0};

    // ---- queue-delay shedding state (ISSUE 15; mu_ held for writes,
    // atomics for the lock-free Enqueue/portal reads) ----
    // CoDel-style window: the MINIMUM sojourn observed this interval
    // (-1 = none yet; 0 is a LEGITIMATE minimum — an instant dequeue
    // means no standing queue). Staying above the target for a whole
    // interval flips over_target_; one below-target pop (or an empty
    // queue) clears it.
    int64_t interval_start_us_ = 0;
    int64_t interval_min_sojourn_us_ = -1;
    // Drain-rate window: cost dequeued since window start.
    int64_t drain_window_start_us_ = 0;
    int64_t drain_window_cost_milli_ = 0;
    std::atomic<bool> over_target_{false};
    std::atomic<int64_t> queue_delay_ewma_us_{0};
    std::atomic<int64_t> drain_rate_milli_per_s_{0};

    // Gradient-limiter template for tenants created after the call
    // (SetGradientOptions; reads race-free because tenants are created
    // under the registry's exclusive lock).
    AutoConcurrencyLimiter::Options gradient_opts_ =
        DefaultTenantGradientOptions();

    void* wake_butex_ = nullptr;
    fiber_t drainer_ = 0;
    bool drainer_running_ = false;  // guarded by drainer_mu_
    std::mutex drainer_mu_;
    std::atomic<bool> stop_{false};
};

}  // namespace tpurpc
