// tpu_std: the native framed protocol.
//
// Wire format (modeled on the reference's default baidu_std protocol,
// src/brpc/policy/baidu_rpc_protocol.cpp — 12-byte "PRPC" header + pb meta
// + pb payload + raw attachment):
//
//   "TRPC" | u32be body_size | u32be meta_size
//   body = RpcMeta(pb, meta_size bytes) | payload(pb) | attachment(raw)
//
// parse  -> ParseTpuStdMessage   (reference ParseRpcMessage :102)
// server -> ProcessTpuStdRequest (reference ProcessRpcRequest :565)
// client -> ProcessTpuStdResponse(reference ProcessRpcResponse :907)
#pragma once

#include "tnet/protocol.h"
#include "tnet/socket.h"

namespace tpurpc {

class TpuStdMessage : public InputMessageBase {
public:
    IOBuf meta;
    IOBuf body;  // payload + attachment (split after meta parse)
};

ParseResult ParseTpuStdMessage(IOBuf* source, Socket* socket, bool read_eof,
                               const void* arg);
void ProcessTpuStdMessage(InputMessageBase* msg);

// Frame a request/response: header + serialized meta + payload + attachment.
void PackTpuStdFrame(IOBuf* out, const IOBuf& meta_pb, const IOBuf& payload,
                     const IOBuf& attachment);

// Registered index of the tpu_std protocol (valid after
// GlobalInitializeOrDie).
int TpuStdProtocolIndex();

// Best-effort CANCEL notification for the in-flight call `cid` on `sid`
// (a meta-only frame with `cancel` set; the receiver drops unknown ids).
void SendTpuStdCancel(SocketId sid, uint64_t cid);

// Response-descriptor completion ack (ISSUE 12): tells the server the
// client finished reading the response descriptor of `cid` — the
// server's pinned block releases through the lease registry
// (exactly-once; a late/duplicate ack is a no-op). `ack_token` is the
// descriptor's PoolDescriptor.ack_token (0 = none: the server falls
// back to a ledger scan). Best-effort: a dead socket drops the ack and
// the lease reaper / peer-death reclamation free the pin instead.
void SendTpuStdDescAck(SocketId sid, uint64_t cid,
                       uint64_t ack_token = 0);

// Push-stream frames (ISSUE 17, RpcMeta.stream_frame): DATA carries the
// chunk as the frame payload; ACK/CLOSE are meta-only. Return 0 on
// queued write, nonzero when the socket is dead/failed (the chunk stays
// in the sender's replay ring — resume recovers it). `try_desc`
// (ISSUE 18 satellite): on a descriptor-capable link, a first-send
// chunk >= -stream_desc_min_bytes rides as a pool REFERENCE
// (StreamFrame.pool_attachment, empty frame body) pinned through the
// lease registry; the receiver resolves it in place and desc_acks with
// correlation id = seq. Replay/retransmit sends stay inline (the pin
// was already released by the first delivery's ack or the reaper).
int SendTpuStdStreamData(SocketId sid, uint64_t stream_id, uint64_t seq,
                         uint32_t flags, const std::string& chunk,
                         bool try_desc = false);
int SendTpuStdStreamAck(SocketId sid, uint64_t stream_id, uint64_t ack_seq,
                        int64_t credits);
int SendTpuStdStreamClose(SocketId sid, uint64_t stream_id, int error_code);

// Response-direction descriptor counters (the rpc_pool_desc_rsp_*
// families; defined in policy_tpu_std.cc, shared with controller.cc —
// the send/fallback sites live on the server response path, the
// resolve/reject sites on the client response path).
namespace rsp_desc {
void CountSend(int64_t bytes);
void CountFallback();
void CountResolve(int64_t bytes);
void CountReject();
void CountAck();
}  // namespace rsp_desc

// Drain announcement (the tpu_std GOAWAY): a meta-only frame with
// `goaway` set, queued on `s`. The receiving client marks the socket
// draining — in-flight calls complete, new calls steer away. Sent by
// Server::StartDraining on every live tpu_std connection.
void SendTpuStdGoaway(Socket* s);

// Worker-pool tag reserved for usercode overload isolation (the backup
// pool that absorbs excess blocking handlers — policy_tpu_std.cc
// TooManyUserCode analog). Server::Start rejects user configurations
// naming it: a user server sharing the overflow pool would silently
// defeat the isolation.
constexpr int kUsercodeBackupTag = 63;

// One-time registration of built-in protocols (reference
// GlobalInitializeOrDie, src/brpc/global.cpp:364-626).
void GlobalInitializeOrDie();

}  // namespace tpurpc
