#include "trpc/json2pb.h"

#include <google/protobuf/util/json_util.h>

namespace tpurpc {

bool JsonToPb(const std::string& json, google::protobuf::Message* msg,
              std::string* error) {
    google::protobuf::util::JsonParseOptions opts;
    opts.ignore_unknown_fields = true;
    const auto st =
        google::protobuf::util::JsonStringToMessage(json, msg, opts);
    if (!st.ok()) {
        if (error != nullptr) *error = st.ToString();
        return false;
    }
    return true;
}

bool PbToJson(const google::protobuf::Message& msg, std::string* json,
              std::string* error) {
    google::protobuf::util::JsonPrintOptions opts;
    opts.preserve_proto_field_names = true;
    const auto st =
        google::protobuf::util::MessageToJsonString(msg, json, opts);
    if (!st.ok()) {
        if (error != nullptr) *error = st.ToString();
        return false;
    }
    return true;
}

}  // namespace tpurpc
