// Outlier detection + ejection (ISSUE 20): grey-failure immunity for the
// LB plane. A node that is slow or lossy while still answering connect
// probes defeats every binary defense — the circuit breaker needs hard
// errors, the zone layer sees it live, hedging papers over it per-call
// while the sick backend keeps absorbing picks. This tier watches the
// PASSIVE per-try feedback every RPC already produces (EndRPC ->
// Controller::FeedbackToLB -> LoadBalancer::Feedback) and ejects
// statistical outliers from the pick set the same way draining members
// are skipped: a budget-free re-route, never a breaker trip.
//
// Shape mirrors the zone layer (ISSUE 14): ONE wrapper —
// OutlierLoadBalancer, applied outermost by LoadBalancer::New — makes
// every policy (rr/wrr/random/c-hash/la) outlier-aware without
// per-policy forks. Reference point: Envoy's outlier detection
// (consecutive-5xx + success-rate ejection with max_ejection_percent)
// re-grounded on brpc-style passive feedback.
//
// Detectors (both cheap, both fed from Feed()):
//  - consecutive-error: N hard failures in a row ejects immediately.
//  - latency-outlier: a rate-limited sweep compares each backend's
//    latency EWMA against the LIVE-SET MEDIAN + k*MAD with a minimum
//    ratio and absolute-delta guard — a uniformly slow mesh moves its
//    own median and ejects NOBODY (asserted by the grey-failure soak's
//    second phase).
//
// Ejection is bounded (-outlier_max_ejection_pct, and never below a
// floor the naming layer derives from its per-zone subset minimum) and
// temporary: windows grow exponentially per relapse, expiry moves the
// backend to PROBING where rate-limited REAL RPCs (no synthetic probe
// traffic) must pass N consecutive times before a slow-start RAMP
// re-admits full weight — no cliff re-entry.
//
// Everything is first-class observable: rpc_outlier_* tvar families,
// the /outliers portal page (text + json), EJECT/REINSTATE flight-
// recorder events (blackbox_merge shows WHY routing shifted), and span
// annotations ("ejected: latency outlier 8.2x median") on re-routed
// calls. Pb-free: links into the standalone toolchain-less suites.
#pragma once

#include <atomic>
#include <cstdint>
#include <map>
#include <mutex>
#include <string>

#include "tbase/endpoint.h"
#include "trpc/load_balancer.h"

namespace tpurpc {
namespace outlier {

enum class State {
    kHealthy = 0,  // full member of the pick set
    kEjected = 1,  // skipped entirely until the window expires
    kProbing = 2,  // window expired: rate-limited real-RPC probes only
    kRamping = 3,  // probes passed: pick probability ramps to full
};

enum class Reason {
    kNone = 0,
    kConsecutiveErrors = 1,
    kLatencyOutlier = 2,
};

const char* StateName(State s);
const char* ReasonName(Reason r);

// Snapshot of one backend's detector state (tests + /outliers page).
struct BackendSnapshot {
    SocketId id = INVALID_VREF_ID;
    EndPoint ep;
    State state = State::kHealthy;
    Reason reason = Reason::kNone;
    int64_t latency_ewma_us = 0;
    int consecutive_errors = 0;
    int eject_count = 0;          // lifetime ejections (window doubling)
    int64_t ejected_for_ms = 0;   // remaining window (kEjected only)
    int probe_passes = 0;         // consecutive passes so far (kProbing)
    // ewma/median ratio x100 at ejection time (kLatencyOutlier only).
    int64_t ratio_x100 = 0;
};

// Per-channel detector registry. One instance lives inside each
// OutlierLoadBalancer; all instances self-register on a process-global
// list so /outliers and the revive observer reach every channel.
class OutlierTracker {
public:
    explicit OutlierTracker(const std::string& name);
    ~OutlierTracker();

    void AddServer(const ServerNode& node);
    void RemoveServer(SocketId id);

    // Passive per-try feedback (latency in us; error_code 0 = success).
    // Runs the consecutive-error detector inline, probe/ramp state
    // transitions, and the rate-limited latency-outlier sweep.
    void Feed(SocketId id, int64_t latency_us, int error_code);

    // Pick-time gate. kAllow: issue to this backend. kSkip: re-pick
    // (fills *note with the span-annotation reason, e.g. "ejected:
    // latency outlier 8.2x median"). A backend in kRamping is admitted
    // probabilistically (slow start); rejects come back kSkip.
    enum class Verdict { kAllow, kSkip };
    Verdict OnPick(SocketId id, std::string* note);

    // An ejected backend whose window expired and whose probe interval
    // elapsed: the wrapper diverts ONE real RPC to it. INVALID_VREF_ID
    // when nobody needs probing now.
    SocketId ProbeCandidate(int64_t now_us);

    // Health-check revive hook (ISSUE 20 satellite: revive used to
    // clear DRAINING and re-enter at full weight). A non-healthy
    // backend re-enters through the probe ramp instead.
    void OnRevive(SocketId id);

    // True when this id must not receive normal picks (kEjected or
    // kProbing — probes are diverted explicitly, never picked).
    bool IsEjected(SocketId id) const;
    State StateOf(SocketId id) const;
    bool Snapshot(SocketId id, BackendSnapshot* out) const;
    size_t size() const;
    // Backends currently withheld from the normal pick set.
    size_t ejected_now() const;

    // Floor under the ejection bound: never leave fewer than this many
    // backends un-ejected (naming layer feeds its subset floor here).
    void set_min_unejected(int n);

    // Fast-path gate: true when every backend is kHealthy (OnPick and
    // ProbeCandidate are then skipped without taking the mutex).
    bool all_healthy() const {
        return nonhealthy_.load(std::memory_order_relaxed) == 0;
    }

    void Describe(std::string* out) const;
    void DescribeJson(std::string* out) const;
    const std::string& name() const { return name_; }

private:
    struct Backend {
        EndPoint ep;
        std::string zone;
        State state = State::kHealthy;
        Reason reason = Reason::kNone;
        int64_t latency_ewma_us = 0;  // alpha 1/8
        int64_t samples = 0;          // since last state change
        int consecutive_errors = 0;
        int eject_count = 0;
        int64_t ejected_until_us = 0;
        int64_t last_probe_us = 0;
        int probe_passes = 0;
        int64_t ramp_start_us = 0;
        int64_t ratio_x100 = 0;  // at ejection (latency reason)
        std::string note;        // span-annotation text while ejected
    };

    void MaybeSweepLocked(int64_t now_us);
    bool EjectLocked(SocketId id, Backend* b, Reason reason,
                     int64_t now_us);
    void FillSnapshotLocked(SocketId id, const Backend& b, int64_t now_us,
                            BackendSnapshot* out) const;

    const std::string name_;
    mutable std::mutex mu_;
    std::map<SocketId, Backend> backends_;
    std::atomic<int> nonhealthy_{0};
    std::atomic<int64_t> last_sweep_us_{0};
    int64_t live_median_us_ = 0;  // last sweep's median (probe threshold)
    int min_unejected_ = 1;
    uint64_t ramp_seq_ = 0;  // deterministic slow-start admission draws
};

// The one wrapper (same shape as ZoneAwareLoadBalancer): applied
// outermost by LoadBalancer::New, so ejection skips compose with zone
// fallback ordering and deterministic subsetting unchanged. Never fails
// a call on its own: when every candidate is ejected, the original pick
// stands (degraded beats dead).
class OutlierLoadBalancer : public LoadBalancer {
public:
    // Takes ownership of the wrapped (zone-aware) balancer.
    explicit OutlierLoadBalancer(LoadBalancer* inner);
    ~OutlierLoadBalancer() override;

    bool AddServer(const ServerNode& server) override;
    bool RemoveServer(SocketId id) override;
    int SelectServer(const SelectIn& in, SelectOut* out) override;
    void Feedback(const CallInfo& info) override;
    void DiscardPick(SocketId id) override;
    void Describe(std::string* out) const override;
    const char* name() const override;

    OutlierTracker* tracker() { return &tracker_; }
    LoadBalancer* wrapped() { return inner_.get(); }

private:
    std::unique_ptr<LoadBalancer> inner_;
    OutlierTracker tracker_;
};

// Register the rpc_outlier_* families eagerly (idempotent) so /metrics
// and the lint see them 0-valued before the first ejection. Also
// installs the Socket revive observer that routes ejected-then-revived
// backends into the probe ramp.
void ExposeVars();

// All live trackers' state (the /outliers portal page).
std::string DescribeAll();
std::string DescribeAllJson();

// Counter reads for tests/tools.
int64_t ejections();
int64_t reinstatements();
int64_t probe_passes();
int64_t probe_fails();
int64_t ejected_now_total();

}  // namespace outlier
}  // namespace tpurpc
