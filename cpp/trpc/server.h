// Server: hosts protobuf services over the native protocol.
//
// Modeled on reference src/brpc/server.{h,cpp}: AddService builds the
// service/method maps (server.cpp:1383-1655), Start listens and wires the
// Acceptor + InputMessenger (StartInternal :845-1230), per-method
// MethodStatus records qps/latency/concurrency, a ConcurrencyLimiter
// guards admission (concurrency_limiter.h:29).
#pragma once

#include <google/protobuf/service.h>

#include <atomic>
#include <map>
#include <memory>
#include <string>

#include "tbase/endpoint.h"
#include "tbase/time.h"
#include "thttp/http_protocol.h"
#include "tnet/acceptor.h"
#include "tnet/input_messenger.h"
#include "trpc/concurrency_limiter.h"
#include "trpc/qos.h"
#include "tvar/latency_recorder.h"

namespace tpurpc {

// Per-method stats (reference src/brpc/details/method_status.h): latency
// recorder + live concurrency + admission limiter, exposed as
// <service>_<method> in /vars.
struct MethodStatus {
    LatencyRecorder latency;
    std::atomic<int64_t> concurrency{0};
    std::atomic<int64_t> nerror{0};
    std::atomic<int64_t> nrejected{0};
    // Deadline accounting (the /status expired/shed columns): requests
    // whose propagated deadline had already passed before handler
    // dispatch, and requests shed because their remaining budget was
    // below the observed service time (AdmitWithBudget).
    std::atomic<int64_t> nexpired{0};
    std::atomic<int64_t> nshed{0};
    // Null = unlimited. Constant or gradient "auto" per ServerOptions.
    std::unique_ptr<ConcurrencyLimiter> limiter;
    int64_t max_concurrency() const {
        return limiter == nullptr ? 0 : limiter->MaxConcurrency();
    }
};

// Server-side admission hook running before user code (reference
// src/brpc/interceptor.h:30): return false to reject the call with
// `error_code`/`error_text` (e.g. auth, quota, request screening).
class Interceptor {
public:
    virtual ~Interceptor() = default;
    virtual bool Accept(const class Controller* cntl, int* error_code,
                        std::string* error_text) = 0;
};

struct ServerOptions {
    // Constant per-method concurrency cap; 0 = unlimited. Ignored when
    // auto_concurrency is set.
    int max_concurrency = 0;
    // Gradient "auto" limiter (reference
    // policy/auto_concurrency_limiter.cpp): tracks no-load latency and
    // peak QPS, caps concurrency at Little's-law capacity + headroom,
    // sheds the excess under overload.
    bool auto_concurrency = false;
    // Tuning for the auto limiter (tests tighten the windows).
    AutoConcurrencyLimiter::Options auto_cl_options;
    // "timeout" limiter (reference policy/timeout_concurrency_limiter):
    // reject requests whose queue wait alone would blow the latency
    // budget. Takes precedence over auto/constant when set.
    bool timeout_concurrency = false;
    TimeoutConcurrencyLimiter::Options timeout_cl_options;
    // Per-TENANT gradient limiter tuning (ISSUE 15): QoS tenants
    // without an explicit conc= share each run their own
    // AutoConcurrencyLimiter with these options, so a tenant's
    // concurrency limit converges from its own observed latency —
    // -rpc_tenant_gradient_limit gates the whole mechanism; tests
    // tighten the windows here.
    AutoConcurrencyLimiter::Options tenant_gradient_options =
        DefaultTenantGradientOptions();
    // Run user service methods inline on the per-message fiber instead of
    // a fresh one. Default OFF: inline user code head-of-line-blocks the
    // connection's input fiber, defeating backup requests and pipelining
    // (reference never lets user code block the input path —
    // baidu_rpc_protocol.cpp:758,839-849, details/usercode_backup_pool.h).
    bool usercode_inline = false;
    // Not owned; must outlive the server. Null = accept everything.
    Interceptor* interceptor = nullptr;
    // Worker tag for user service code (reference bthread_tag server
    // option / example/bthread_tag_echo_c++): 0 = default pool; nonzero
    // isolates this server's pb handlers (tpu_std and gRPC/h2) on their
    // own worker pool so they cannot starve (or be starved by) other
    // work in the process. HTTP/1 portal/json handlers run inline on
    // their connection fiber and are NOT retagged. Must be within
    // [0, 64); Start fails otherwise.
    int fiber_tag = 0;
    // TLS: PEM cert chain + private key. When both are set, every
    // accepted connection is wrapped in a TLS transport (tnet/tls.h)
    // with ALPN (h2 preferred, http/1.1 fallback) — gRPC-over-TLS and
    // HTTPS portal ride it unchanged. Start fails if libssl is missing
    // or the files don't load. Reference: ServerOptions::ssl_options
    // (src/brpc/server.h) + details/ssl_helper.cpp.
    std::string tls_cert_path;
    std::string tls_key_path;
    // Credential verifier (trpc/auth.h). Not owned; must outlive the
    // server. tpu_std connections must authenticate on their first
    // request (bad credentials fail the connection); gRPC calls present
    // the `authorization` header and get UNAUTHENTICATED on mismatch.
    const class Authenticator* auth = nullptr;
};

class Server {
public:
    Server();
    ~Server();

    struct MethodProperty {
        google::protobuf::Service* service = nullptr;
        const google::protobuf::MethodDescriptor* method = nullptr;
        std::unique_ptr<MethodStatus> status;
        // Run-to-completion opt-in (ISSUE 7): the handler promises to be
        // cheap and to NEVER block (no sync downstream calls, no
        // fiber_usleep, no lock waits) — small requests then run it ON
        // the connection's input fiber with the response joining the
        // round's coalesced writev. A handler that parks anyway stays
        // correct (the scheduler flushes the round's batching scopes on
        // park) but head-of-line-blocks its connection. Atomic: toggled
        // at runtime (e.g. a soak's delay phase) while input fibers read
        // it; relaxed is enough — a momentarily stale read just picks
        // the other (also correct) dispatch path.
        std::atomic<bool> inline_safe{false};
    };

    // Does NOT take ownership (reference SERVER_DOESNT_OWN_SERVICE default).
    int AddService(google::protobuf::Service* service);

    // Flag "pkg.Service.Method" (AddService key format) inline-safe; see
    // MethodProperty::inline_safe for the contract. May be toggled at
    // runtime (e.g. off while a soak injects handler delays). Returns 0,
    // or -1 when the method is unknown.
    int SetMethodInlineSafe(const std::string& service_full_name,
                            const std::string& method_name,
                            bool inline_safe = true);

    // ---- multi-tenant QoS (ISSUE 8; trpc/qos.h) ----
    // Set/replace one tenant's quota (QPS rate, burst, weighted-fair
    // share, concurrency share). Enables the QoS tier for this server;
    // callable before or after Start (the dispatch-gating fields are
    // atomics, so a runtime re-quota is safe under traffic). The
    // -rpc_tenant_quotas flag configures the same thing at
    // StartNoListen; explicit calls override the flag per tenant. A
    // call that enables the tier on an already-running server also
    // starts the fair-queue drainer.
    void SetTenantQuota(const std::string& tenant, const TenantQuota& quota) {
        qos_.SetTenantQuota(tenant, quota);
        if (started_) qos_.StartDrainer();
    }
    QosDispatcher* qos() { return &qos_; }

    int Start(const EndPoint& ep, const ServerOptions* options);
    int Start(int port, const ServerOptions* options);  // 0 = ephemeral
    void Stop();
    void Join();

    // ---- zero-downtime lifecycle (reference Server::Stop/Join draining
    // + -graceful_quit_on_sigterm) ----
    // Planned shutdown, end to end: pause the acceptor (listening fd
    // stays open — connect-probe health checks keep passing), broadcast
    // a drain announcement on every live connection (tpu_std GOAWAY
    // meta; h2 GOAWAY with last-stream-id; HTTP/1.1 answers with
    // Connection: close), serve in-flight AND racing requests to
    // completion bounded by `max_drain_ms` (each request is further
    // bounded by its own propagated deadline — expired work is shed, not
    // executed), flush queued response bytes, then Stop+Join. tvars:
    // rpc_server_draining (gauge), rpc_server_drain_goaways_sent,
    // rpc_server_drained_inflight.
    void GracefulStop(int64_t max_drain_ms = 5000);
    // Drain-only (the SIGUSR2 behavior): announce the drain and mark the
    // server draining but KEEP accepting and serving — operators can
    // still scrape /status //vars, and health checks still answer, while
    // clients steer new traffic away. Idempotent.
    void StartDraining();
    bool draining() const {
        return draining_.load(std::memory_order_acquire);
    }

    // Signal-driven lifecycle for tools (-graceful_quit_on_sigterm):
    // blocks until SIGTERM, then GracefulStop(max_drain_ms) and returns.
    // A SIGUSR2 received meanwhile triggers StartDraining() without
    // quitting. Requires the flag (Start installs the handlers).
    void RunUntilAskedToQuit(int64_t max_drain_ms = 5000);

    int listened_port() const { return acceptor_.listened_port(); }
    const ServerOptions& options() const { return options_; }

    // The server's message pump — out-of-band transports (ICI endpoints)
    // bind their sockets to it so requests flow into this server's
    // services. Valid after Start (requires started protocol registry) or
    // StartNoListen.
    InputMessenger* messenger() { return &messenger_; }
    // Initialize services/registries without a TCP listener: an
    // ICI-endpoint-only server (data plane rides the interconnect; no
    // DCN port).
    int StartNoListen(const ServerOptions* options);

    // "ServiceName.MethodName" lookup (called by the protocol layer).
    MethodProperty* FindMethod(const std::string& service_name,
                               const std::string& method_name);
    // "/Service/Method" lookup for HTTP-as-RPC (reference
    // policy/http_rpc_protocol.cpp maps URLs to pb methods the same way):
    // the service component matches the full name ("pkg.EchoService") or
    // its last component ("EchoService"). Null when the path is not an
    // RPC method.
    MethodProperty* FindMethodByHttpPath(const std::string& path);

    // ---- HTTP portal (thttp/; reference src/brpc/builtin/) ----
    // Register a handler for an exact path, or a prefix when `path` ends
    // with "/*" ("/vars/*" matches /vars/anything). Builtins are added at
    // StartNoListen; user handlers may be added before Start.
    void RegisterHttpHandler(const std::string& path, HttpHandler handler);
    // Exact match first, then longest registered "/x/*" prefix; null if
    // nothing matches.
    const HttpHandler* FindHttpHandler(const std::string& path) const;

    // Portal introspection accessors.
    const std::map<std::string, MethodProperty>& methods() const {
        return methods_;
    }
    Acceptor* acceptor() { return &acceptor_; }

    // ---- redis service (trpc/redis.h; reference src/brpc/redis.h) ----
    // Serve RESP commands on the same port (sniffed by the leading '*').
    // Not owned; must outlive the server. Set before Start.
    void set_redis_service(class RedisService* rs) { redis_service_ = rs; }
    class RedisService* redis_service() const { return redis_service_; }

    std::atomic<int64_t> nprocessing{0};  // in-flight requests

    // Per-method admission + accounting shared by every protocol
    // (tpu_std, HTTP-as-RPC): one construction = one admission check; one
    // Finish = stats + limiter feedback + Join accounting. Keeps the
    // limiter/stat protocol in ONE place instead of per-protocol copies.
    class MethodCallGuard {
    public:
        // remaining_budget_us: the request's propagated remaining
        // deadline budget, or -1 when the client sent none. Budget-aware
        // limiters (TimeoutConcurrencyLimiter::AdmitWithBudget) shed
        // requests that cannot finish in time; such rejections are
        // accounted as `shed` rather than `rejected`. `priority` is the
        // request's QoS class (budget limiters probe per class);
        // `forced` skips the OnRequested concurrency check — used when
        // the QoS tier evicted a lower-priority queued request to make
        // room, so net concurrency is unchanged (budget shedding still
        // applies: eviction can't make a doomed request finish in time).
        MethodCallGuard(Server* server, MethodProperty* mp,
                        int64_t remaining_budget_us = -1,
                        int priority = 0, bool forced = false)
            : server_(server), mp_(mp) {
            const int64_t cur = mp_->status->concurrency.fetch_add(
                                    1, std::memory_order_relaxed) +
                                1;
            ConcurrencyLimiter* lim = mp_->status->limiter.get();
            if (lim != nullptr && !forced && !lim->OnRequested(cur)) {
                mp_->status->concurrency.fetch_sub(
                    1, std::memory_order_relaxed);
                mp_->status->nrejected.fetch_add(1,
                                                 std::memory_order_relaxed);
                rejected_ = true;
                return;
            }
            if (lim != nullptr && remaining_budget_us >= 0 &&
                !lim->AdmitWithBudget(remaining_budget_us, priority)) {
                mp_->status->concurrency.fetch_sub(
                    1, std::memory_order_relaxed);
                mp_->status->nshed.fetch_add(1, std::memory_order_relaxed);
                rejected_ = true;
                shed_ = true;
                return;
            }
            server_->BeginRequest();
            start_us_ = monotonic_time_us();
        }
        bool rejected() const { return rejected_; }
        // Rejection was budget-based shedding (the request could not
        // have finished inside its remaining deadline).
        bool shed() const { return shed_; }
        // Complete the call: record latency/errors, feed the limiter,
        // wake Join. error_code 0 = success. Must be called exactly once
        // unless rejected().
        void Finish(int error_code) {
            const int64_t lat_us = monotonic_time_us() - start_us_;
            mp_->status->latency << lat_us;
            mp_->status->concurrency.fetch_sub(1, std::memory_order_relaxed);
            if (error_code != 0) {
                mp_->status->nerror.fetch_add(1, std::memory_order_relaxed);
            }
            if (mp_->status->limiter != nullptr) {
                mp_->status->limiter->OnResponded(error_code, lat_us);
            }
            server_->EndRequest();  // may free the Server: last touch
        }

    private:
        Server* server_;
        MethodProperty* mp_;
        int64_t start_us_ = 0;
        bool rejected_ = false;
        bool shed_ = false;
    };
    // Admission + accounting for one request (called by protocol layers).
    void BeginRequest() {
        nprocessing.fetch_add(1, std::memory_order_relaxed);
        // Monotonic admission counter: GracefulStop's linger loop uses
        // it to tell "drained and quiet" apart from "drained but a
        // racing request just arrived".
        nbegun_.fetch_add(1, std::memory_order_relaxed);
    }
    // Last-touch of Server memory for a request fiber: wakes Join.
    void EndRequest();

private:
    InputMessenger messenger_;
    Acceptor acceptor_;
    // Multi-tenant fair dispatch + overload shedding (trpc/qos.h).
    // Disabled (and bypassed) until quotas are configured or
    // -rpc_qos_enabled is on.
    QosDispatcher qos_;
    class RedisService* redis_service_ = nullptr;
    ServerOptions options_;
    bool started_ = false;
    bool listening_ = false;
    std::map<std::string, MethodProperty> methods_;
    std::map<std::string, HttpHandler> http_exact_;
    std::map<std::string, HttpHandler> http_prefix_;  // key without "/*"
    void* join_butex_ = nullptr;  // bumped when nprocessing drains to 0
    std::atomic<bool> draining_{false};
    std::atomic<int64_t> nbegun_{0};  // total requests ever admitted
    // Join with an absolute deadline (INT64_MAX = wait forever); the
    // drain phase of GracefulStop is bounded, the final teardown is not
    // (request fibers hold pointers into this Server).
    void JoinUntil(int64_t abs_deadline_us);
};

// -graceful_quit_on_sigterm plumbing. The handlers only set flags (never
// run shutdown from signal context): poll IsAskedToQuit/IsAskedToDrain
// from a fiber/thread and call Server::GracefulStop there — or use
// Server::RunUntilAskedToQuit which does exactly that. Installed
// automatically by Server::Start when -graceful_quit_on_sigterm is on.
void InstallGracefulQuitSignalsOrDie();
bool IsAskedToQuit();   // SIGTERM seen (graceful quit requested)
bool IsAskedToDrain();  // SIGUSR2 seen (drain-only requested)

}  // namespace tpurpc
