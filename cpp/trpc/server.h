// Server: hosts protobuf services over the native protocol.
//
// Modeled on reference src/brpc/server.{h,cpp}: AddService builds the
// service/method maps (server.cpp:1383-1655), Start listens and wires the
// Acceptor + InputMessenger (StartInternal :845-1230), per-method
// MethodStatus records qps/latency/concurrency, a ConcurrencyLimiter
// guards admission (concurrency_limiter.h:29).
#pragma once

#include <google/protobuf/service.h>

#include <atomic>
#include <map>
#include <memory>
#include <string>

#include "tbase/endpoint.h"
#include "thttp/http_protocol.h"
#include "tnet/acceptor.h"
#include "tnet/input_messenger.h"
#include "tvar/latency_recorder.h"

namespace tpurpc {

// Per-method stats (reference src/brpc/details/method_status.h): latency
// recorder + live concurrency, exposed as <service>_<method> in /vars.
struct MethodStatus {
    LatencyRecorder latency;
    std::atomic<int64_t> concurrency{0};
    std::atomic<int64_t> nerror{0};
    std::atomic<int64_t> nrejected{0};
    int max_concurrency = 0;  // 0 = unlimited ("constant" limiter)
};

struct ServerOptions {
    // 0 = unlimited. The "constant" concurrency limiter; the gradient
    // "auto" limiter (reference policy/auto_concurrency_limiter.cpp) comes
    // with the robustness milestone.
    int max_concurrency = 0;
};

class Server {
public:
    Server() : messenger_(), acceptor_(&messenger_) {}
    ~Server();

    struct MethodProperty {
        google::protobuf::Service* service = nullptr;
        const google::protobuf::MethodDescriptor* method = nullptr;
        std::unique_ptr<MethodStatus> status;
    };

    // Does NOT take ownership (reference SERVER_DOESNT_OWN_SERVICE default).
    int AddService(google::protobuf::Service* service);

    int Start(const EndPoint& ep, const ServerOptions* options);
    int Start(int port, const ServerOptions* options);  // 0 = ephemeral
    void Stop();
    void Join();

    int listened_port() const { return acceptor_.listened_port(); }
    const ServerOptions& options() const { return options_; }

    // The server's message pump — out-of-band transports (ICI endpoints)
    // bind their sockets to it so requests flow into this server's
    // services. Valid after Start (requires started protocol registry) or
    // StartNoListen.
    InputMessenger* messenger() { return &messenger_; }
    // Initialize services/registries without a TCP listener: an
    // ICI-endpoint-only server (data plane rides the interconnect; no
    // DCN port).
    int StartNoListen(const ServerOptions* options);

    // "ServiceName.MethodName" lookup (called by the protocol layer).
    MethodProperty* FindMethod(const std::string& service_name,
                               const std::string& method_name);

    // ---- HTTP portal (thttp/; reference src/brpc/builtin/) ----
    // Register a handler for an exact path, or a prefix when `path` ends
    // with "/*" ("/vars/*" matches /vars/anything). Builtins are added at
    // StartNoListen; user handlers may be added before Start.
    void RegisterHttpHandler(const std::string& path, HttpHandler handler);
    // Exact match first, then longest registered "/x/*" prefix; null if
    // nothing matches.
    const HttpHandler* FindHttpHandler(const std::string& path) const;

    // Portal introspection accessors.
    const std::map<std::string, MethodProperty>& methods() const {
        return methods_;
    }
    Acceptor* acceptor() { return &acceptor_; }

    std::atomic<int64_t> nprocessing{0};  // in-flight requests

private:
    InputMessenger messenger_;
    Acceptor acceptor_;
    ServerOptions options_;
    bool started_ = false;
    bool listening_ = false;
    std::map<std::string, MethodProperty> methods_;
    std::map<std::string, HttpHandler> http_exact_;
    std::map<std::string, HttpHandler> http_prefix_;  // key without "/*"
};

}  // namespace tpurpc
