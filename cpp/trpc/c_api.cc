#include "trpc/c_api.h"

#include <cstring>
#include <string>

#include "rpc_meta.pb.h"
#include "tbase/crc32c.h"
#include "tbase/iobuf.h"
#include "tici/block_lease.h"
#include "tici/block_pool.h"
#include "tici/verbs.h"
#include "tnet/transport.h"
#include "trpc/pb_compat.h"
#include "trpc/policy_tpu_std.h"

namespace {
constexpr char kMagic[4] = {'T', 'R', 'P', 'C'};
constexpr size_t kHeaderLen = 12;  // "TRPC" + u32be body + u32be meta
}  // namespace

extern "C" {

int tpurpc_global_init() {
    tpurpc::GlobalInitializeOrDie();
    return tpurpc::IciBlockPool::Init() == 0 ? 0 : -1;
}

uint32_t tpurpc_crc32c(uint32_t init, const void* data, size_t n) {
    return tpurpc::crc32c_extend(init, (const char*)data, n);
}

void* tpurpc_block_alloc(size_t n) {
    if (tpurpc::IciBlockPool::initialized()) {
        // Slab classes first (recyclable registered slots); oversized
        // requests fall through to carve-only registered chunks inside
        // AllocateSlab.
        void* p = tpurpc::IciBlockPool::AllocateSlab(n);
        if (p != nullptr) return p;
    }
    return malloc(n);
}

void tpurpc_block_free(void* p) {
    if (tpurpc::IciBlockPool::Contains(p)) {
        // Slab slots recycle into their class freelist; carve-only
        // chunks are process-lifetime (FreeSlab ignores them).
        tpurpc::IciBlockPool::FreeSlab(p);
        return;
    }
    free(p);
}

int tpurpc_block_is_registered(const void* p) {
    return tpurpc::IciBlockPool::Contains(p) ? 1 : 0;
}

long tpurpc_slab_allocated() {
    return (long)tpurpc::IciBlockPool::slab_allocated();
}

long tpurpc_slab_recycled() {
    return (long)tpurpc::IciBlockPool::slab_recycled();
}

uint64_t tpurpc_pool_id() { return tpurpc::IciBlockPool::pool_id(); }

uint64_t tpurpc_pool_epoch() {
    return tpurpc::IciBlockPool::pool_epoch();
}

uint64_t tpurpc_lease_pinned() { return tpurpc::block_lease::pinned(); }

uint64_t tpurpc_lease_reaped() {
    return tpurpc::block_lease::expired_reaped() +
           tpurpc::block_lease::peer_released();
}

int tpurpc_transport_tier_count() {
    tpurpc::transport_stats::ExposeVars();  // built-ins registered
    return tpurpc::TransportTierCount();
}

long tpurpc_transport_tier_name(int tier, char* out, size_t cap) {
    const tpurpc::TransportTier* t = tpurpc::GetTransportTier(tier);
    if (t == nullptr || out == nullptr || cap == 0) return -1;
    const size_t n = strlen(t->name);
    const size_t ncopy = n < cap - 1 ? n : cap - 1;
    memcpy(out, t->name, ncopy);
    out[ncopy] = '\0';
    return (long)n;
}

int tpurpc_transport_tier_descriptor_capable(int tier) {
    const tpurpc::TransportTier* t = tpurpc::GetTransportTier(tier);
    return t != nullptr ? (t->descriptor_capable ? 1 : 0) : -1;
}

int tpurpc_transport_tier_zero_copy(int tier) {
    const tpurpc::TransportTier* t = tpurpc::GetTransportTier(tier);
    return t != nullptr ? (t->zero_copy ? 1 : 0) : -1;
}

int tpurpc_transport_tier_cross_process(int tier) {
    const tpurpc::TransportTier* t = tpurpc::GetTransportTier(tier);
    return t != nullptr ? (t->cross_process ? 1 : 0) : -1;
}

int tpurpc_transport_tier_one_sided(int tier) {
    const tpurpc::TransportTier* t = tpurpc::GetTransportTier(tier);
    return t != nullptr ? (t->one_sided ? 1 : 0) : -1;
}

long tpurpc_transport_tier_sgl_max(int tier) {
    const tpurpc::TransportTier* t = tpurpc::GetTransportTier(tier);
    return t != nullptr ? (long)t->sgl_max : -1;
}

long tpurpc_verbs_posted() { return (long)tpurpc::verbs::posted(); }

long tpurpc_verbs_completed() {
    return (long)tpurpc::verbs::completed();
}

long tpurpc_verbs_bytes() {
    return (long)tpurpc::verbs::bytes_moved();
}

long tpurpc_verbs_stale_rejects() {
    return (long)tpurpc::verbs::stale_rejects();
}

long tpurpc_verbs_cq_parks() { return (long)tpurpc::verbs::cq_parks(); }

long tpurpc_verbs_windows() {
    return (long)tpurpc::verbs::window_count();
}

long tpurpc_verbs_pending() {
    return (long)tpurpc::verbs::pending_posts();
}

long tpurpc_transport_tier_ops(int tier) {
    return (long)tpurpc::transport_stats::ops(tier);
}

void* tpurpc_ring_create(uint32_t depth, size_t slot_bytes) {
    return tpurpc::DeviceStagingRing::Create(depth, slot_bytes);
}

void tpurpc_ring_destroy(void* ring) {
    delete (tpurpc::DeviceStagingRing*)ring;
}

int tpurpc_ring_acquire(void* ring, long timeout_us) {
    return ((tpurpc::DeviceStagingRing*)ring)->Acquire(timeout_us);
}

int tpurpc_ring_complete(void* ring, uint32_t slot) {
    return ((tpurpc::DeviceStagingRing*)ring)->Complete(slot);
}

void tpurpc_ring_abort(void* ring) {
    ((tpurpc::DeviceStagingRing*)ring)->Abort();
}

int tpurpc_ring_aborted(void* ring) {
    return ((tpurpc::DeviceStagingRing*)ring)->aborted() ? 1 : 0;
}

void* tpurpc_ring_slot(void* ring, uint32_t slot) {
    return ((tpurpc::DeviceStagingRing*)ring)->slot(slot);
}

size_t tpurpc_ring_slot_bytes(void* ring) {
    return ((tpurpc::DeviceStagingRing*)ring)->slot_bytes();
}

uint32_t tpurpc_ring_depth(void* ring) {
    return ((tpurpc::DeviceStagingRing*)ring)->depth();
}

int tpurpc_ring_registered(void* ring) {
    return ((tpurpc::DeviceStagingRing*)ring)->registered() ? 1 : 0;
}

uint64_t tpurpc_ring_inflight_highwater(void* ring) {
    return ((tpurpc::DeviceStagingRing*)ring)->inflight_highwater();
}

namespace {

// Serialize the one-frame meta for (cid, payload crc). Returns false on
// a serialization failure (can't happen for this fixed shape).
bool frame_meta(uint64_t cid, size_t n, uint32_t crc, std::string* out) {
    tpurpc::rpc::RpcMeta meta;
    meta.set_correlation_id(cid);
    meta.set_attachment_size((uint32_t)n);
    meta.set_body_checksum(crc);
    return meta.SerializeToString(out);
}

void write_frame_header(char* dst, size_t meta_size, size_t payload_len) {
    memcpy(dst, kMagic, 4);
    const uint32_t body = __builtin_bswap32((uint32_t)(meta_size +
                                                       payload_len));
    const uint32_t msz = __builtin_bswap32((uint32_t)meta_size);
    memcpy(dst + 4, &body, 4);
    memcpy(dst + 8, &msz, 4);
}

}  // namespace

long tpurpc_frame(uint64_t correlation_id, const void* payload, size_t n,
                  void* out, size_t out_cap) {
    std::string meta_str;
    if (!frame_meta(correlation_id, n,
                    tpurpc::crc32c_extend(0, (const char*)payload, n),
                    &meta_str)) {
        return -1;
    }
    const size_t frame_len = kHeaderLen + meta_str.size() + n;
    if (frame_len > out_cap) return -1;
    char* o = (char*)out;
    char* att_pos = o + kHeaderLen + meta_str.size();
    // Payload placement FIRST (memmove: the source may overlap the
    // header/meta region about to be written). When the payload already
    // sits exactly at the frame's attachment position — staged in place
    // inside the destination pool buffer — the copy is skipped entirely:
    // the frame costs a header+meta write and the crc read only.
    if ((const char*)payload != att_pos) {
        memmove(att_pos, payload, n);
    }
    write_frame_header(o, meta_str.size(), n);
    memcpy(o + kHeaderLen, meta_str.data(), meta_str.size());
    return (long)frame_len;
}

long tpurpc_frame_in_place(uint64_t correlation_id, void* buf,
                           size_t payload_off, size_t payload_len,
                           size_t* frame_off, uint32_t* crc_out) {
    char* b = (char*)buf;
    const uint32_t crc =
        tpurpc::crc32c_extend(0, b + payload_off, payload_len);
    if (crc_out != nullptr) *crc_out = crc;
    std::string meta_str;
    if (!frame_meta(correlation_id, payload_len, crc, &meta_str)) {
        return -1;
    }
    const size_t prefix = kHeaderLen + meta_str.size();
    if (payload_off < prefix) return -1;  // not enough header room
    const size_t start = payload_off - prefix;
    write_frame_header(b + start, meta_str.size(), payload_len);
    memcpy(b + start + kHeaderLen, meta_str.data(), meta_str.size());
    if (frame_off != nullptr) *frame_off = start;
    return (long)(prefix + payload_len);
}

long tpurpc_unframe(const void* buf, size_t n, uint64_t* cid,
                    size_t* payload_off, size_t* payload_len) {
    const char* p = (const char*)buf;
    if (n < kHeaderLen) return -1;
    if (memcmp(p, kMagic, 4) != 0) return -2;
    uint32_t body_be, meta_be;
    memcpy(&body_be, p + 4, 4);
    memcpy(&meta_be, p + 8, 4);
    const uint32_t body_size = __builtin_bswap32(body_be);
    const uint32_t meta_size = __builtin_bswap32(meta_be);
    if (meta_size > body_size || body_size > (256u << 20)) return -2;
    if (n < kHeaderLen + body_size) return -1;
    tpurpc::rpc::RpcMeta meta;
    if (!meta.ParseFromArray(p + kHeaderLen, (int)meta_size)) return -2;
    const size_t off = kHeaderLen + meta_size;
    const size_t len = body_size - meta_size;
    if (meta.has_body_checksum() &&
        tpurpc::crc32c_extend(0, p + off, len) != meta.body_checksum()) {
        return -2;
    }
    if (cid != nullptr) *cid = meta.correlation_id();
    if (payload_off != nullptr) *payload_off = off;
    if (payload_len != nullptr) *payload_len = len;
    return (long)(kHeaderLen + body_size);
}

}  // extern "C"
