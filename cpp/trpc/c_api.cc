#include "trpc/c_api.h"

#include <cstring>

#include "rpc_meta.pb.h"
#include "tbase/crc32c.h"
#include "tbase/iobuf.h"
#include "tici/block_pool.h"
#include "trpc/pb_compat.h"
#include "trpc/policy_tpu_std.h"

namespace {
constexpr char kMagic[4] = {'T', 'R', 'P', 'C'};
constexpr size_t kHeaderLen = 12;  // "TRPC" + u32be body + u32be meta
}  // namespace

extern "C" {

int tpurpc_global_init() {
    tpurpc::GlobalInitializeOrDie();
    return tpurpc::IciBlockPool::Init() == 0 ? 0 : -1;
}

uint32_t tpurpc_crc32c(uint32_t init, const void* data, size_t n) {
    return tpurpc::crc32c_extend(init, (const char*)data, n);
}

void* tpurpc_block_alloc(size_t n) {
    if (tpurpc::IciBlockPool::initialized()) {
        void* p = tpurpc::IciBlockPool::AllocateRegistered(n);
        if (p != nullptr) return p;
    }
    return malloc(n);
}

void tpurpc_block_free(void* p) {
    // Registered chunks are carve-only (process-lifetime staging arenas);
    // only malloc fallbacks are freed.
    if (!tpurpc::IciBlockPool::Contains(p)) free(p);
}

int tpurpc_block_is_registered(const void* p) {
    return tpurpc::IciBlockPool::Contains(p) ? 1 : 0;
}

long tpurpc_frame(uint64_t correlation_id, const void* payload, size_t n,
                  void* out, size_t out_cap) {
    tpurpc::rpc::RpcMeta meta;
    meta.set_correlation_id(correlation_id);
    meta.set_attachment_size((uint32_t)n);
    meta.set_body_checksum(
        tpurpc::crc32c_extend(0, (const char*)payload, n));
    tpurpc::IOBuf meta_buf;
    if (!tpurpc::SerializePbToIOBuf(meta, &meta_buf)) return -1;
    tpurpc::IOBuf frame, attachment;
    attachment.append(payload, n);
    tpurpc::PackTpuStdFrame(&frame, meta_buf, tpurpc::IOBuf(), attachment);
    if (frame.size() > out_cap) return -1;
    frame.copy_to(out, frame.size());
    return (long)frame.size();
}

long tpurpc_unframe(const void* buf, size_t n, uint64_t* cid,
                    size_t* payload_off, size_t* payload_len) {
    const char* p = (const char*)buf;
    if (n < kHeaderLen) return -1;
    if (memcmp(p, kMagic, 4) != 0) return -2;
    uint32_t body_be, meta_be;
    memcpy(&body_be, p + 4, 4);
    memcpy(&meta_be, p + 8, 4);
    const uint32_t body_size = __builtin_bswap32(body_be);
    const uint32_t meta_size = __builtin_bswap32(meta_be);
    if (meta_size > body_size || body_size > (256u << 20)) return -2;
    if (n < kHeaderLen + body_size) return -1;
    tpurpc::rpc::RpcMeta meta;
    if (!meta.ParseFromArray(p + kHeaderLen, (int)meta_size)) return -2;
    const size_t off = kHeaderLen + meta_size;
    const size_t len = body_size - meta_size;
    if (meta.has_body_checksum() &&
        tpurpc::crc32c_extend(0, p + off, len) != meta.body_checksum()) {
        return -2;
    }
    if (cid != nullptr) *cid = meta.correlation_id();
    if (payload_off != nullptr) *payload_off = off;
    if (payload_len != nullptr) *payload_len = len;
    return (long)(kHeaderLen + body_size);
}

}  // extern "C"
