// Authenticator: connection-level credential exchange.
//
// Reference parity: src/brpc/authenticator.h (GenerateCredential /
// VerifyCredential / AuthContext) + the Protocol `verify` hook
// (src/brpc/protocol.h:77-172) + the Socket auth fight
// (src/brpc/socket.h:515 FightAuthentication): on a shared connection
// the FIRST request carries the credential exactly once; concurrent
// first-writers wait for its outcome instead of re-authenticating.
//
// tpu_std carries the credential in RpcMeta.auth_data (first message of
// the connection); gRPC carries it in the `authorization` header
// (per-request, the h2 idiom).
#pragma once

#include <string>

#include "tbase/endpoint.h"

namespace tpurpc {

// What a verified credential resolved to (attached to the connection).
class AuthContext {
public:
    const std::string& user() const { return user_; }
    void set_user(const std::string& u) { user_ = u; }

private:
    std::string user_;
};

class Authenticator {
public:
    virtual ~Authenticator() = default;

    // Client: fill `auth_str` with the credential to present. Return 0;
    // nonzero fails the RPC before anything is sent.
    virtual int GenerateCredential(std::string* auth_str) const = 0;

    // Server: verify a presented credential. Return 0 to accept (and
    // optionally fill `out_ctx`); nonzero rejects — the request is
    // refused and the connection is failed (tpu_std) or the call gets
    // UNAUTHENTICATED (gRPC).
    virtual int VerifyCredential(const std::string& auth_str,
                                 const EndPoint& client_addr,
                                 AuthContext* out_ctx) const = 0;
};

}  // namespace tpurpc
