#include "trpc/channel.h"

#include <cstring>

#include "tbase/errno.h"
#include "tbase/fast_rand.h"
#include "tbase/flight_recorder.h"
#include "tbase/logging.h"
#include "tbase/time.h"
#include "tfiber/call_id.h"
#include "tici/shm_link.h"
#include "tnet/tls.h"
#include "tnet/transport.h"
#include "trpc/lb_with_naming.h"
#include "trpc/controller.h"
#include "trpc/pb_compat.h"
#include "trpc/retry_policy.h"
#include "trpc/compress.h"
#include "trpc/policy_tpu_std.h"
#include "trpc/server_call.h"
#include "trpc/span.h"
#include "trpc/stream.h"

#include "tbase/flags.h"

// Default retry budget (gRPC retry-throttling shape; channel.h
// ChannelOptions::retry_budget_*): the burst bounds re-issues under a
// correlated failure, the ratio lets healthy traffic earn them back.
// tokens <= 0 disables throttling process-wide.
DEFINE_int32(rpc_retry_budget_tokens, 100,
             "per-channel retry/backup burst tokens (<=0 disables)");
DEFINE_double(rpc_retry_budget_ratio, 0.1,
              "retry budget tokens earned back per successful RPC");

namespace tpurpc {

Channel::~Channel() = default;

void Channel::ConfigureRetryBudget() {
    const int64_t tokens = options_.retry_budget_tokens >= 0
                               ? options_.retry_budget_tokens
                               : FLAGS_rpc_retry_budget_tokens.get();
    const double ratio = options_.retry_budget_ratio >= 0
                             ? options_.retry_budget_ratio
                             : FLAGS_rpc_retry_budget_ratio.get();
    retry_budget_.Configure(tokens, ratio);
}

InputMessenger* Channel::client_messenger() {
    static InputMessenger* m = [] {
        GlobalInitializeOrDie();
        return new InputMessenger(
            {TpuStdProtocolIndex(), stream_internal::StreamProtocolIndex()});
    }();
    return m;
}

int Channel::Init(const EndPoint& server, const ChannelOptions* options) {
    GlobalInitializeOrDie();
    server_ep_ = server;
    if (options != nullptr) options_ = *options;
    ConfigureRetryBudget();
    // Resolve the transport-tier name once (ISSUE 14): every connection
    // this channel draws — pinned, SocketMap-shared, pooled or short —
    // is created and keyed on this tier.
    if (!options_.transport.empty()) {
        forced_tier_ = FindTransportTier(options_.transport.c_str());
        if (forced_tier_ < 0 && options_.transport == "dcn") {
            forced_tier_ = TierDcn();  // built-in, registered on demand
        }
        if (forced_tier_ < 0) {
            LOG(ERROR) << "unknown ChannelOptions::transport '"
                       << options_.transport << "'";
            return -1;
        }
    }
    // grpc/redis and TLS channels pin their OWN connection: the
    // endpoint-keyed SocketMap/SocketPool sockets are shared with
    // tpu_std channels, and installing an h2/redis session (or a TLS
    // wrap) on a shared socket would corrupt the other protocol's
    // traffic to the same server. pin_connection opts into the same
    // ownership for plain tpu_std (per-channel connections that shard
    // across the epoll loops — load generators, ISSUE 7).
    if (options_.tls || options_.protocol == "grpc" ||
        options_.protocol == "redis" || options_.pin_connection) {
        if (options_.tls && !TlsAvailable()) {
            LOG(ERROR) << "ChannelOptions::tls set but libssl is missing";
            return -1;
        }
        if (CreateOwnedPinnedSocket(&pinned_socket_) != 0) return -1;
        owns_pinned_ = true;
    }
    return 0;
}

int Channel::CreateOwnedPinnedSocket(SocketId* sid) {
    SocketOptions sopts;
    sopts.fd = -1;  // connect-on-first-write
    sopts.remote_side = server_ep_;
    sopts.on_edge_triggered_events = &InputMessenger::OnNewMessages;
    sopts.user = client_messenger();
    if (options_.tls) {
        sopts.tls = true;
        sopts.tls_alpn = options_.protocol == "grpc" ? "h2" : "";
        sopts.tls_sni = options_.tls_sni;
    }
    sopts.forced_transport_tier = forced_tier_;
    if (Socket::Create(sopts, sid) != 0) {
        LOG(ERROR) << "pinned client socket creation failed";
        return -1;
    }
    return 0;
}

SocketId Channel::AcquirePinnedSocket() {
    const SocketId sid = pinned_socket_;
    if (sid == INVALID_VREF_ID) return sid;
    {
        SocketUniquePtr probe;
        if (Socket::AddressSocket(sid, &probe) == 0) {
            // A DRAINING pin (peer sent GOAWAY) is replaced like a dead
            // one — but only for channel-owned pins: the old connection
            // stays alive so its in-flight streams complete; it dies
            // when the drained server closes it.
            if (!owns_pinned_ || !probe->Draining()) return sid;  // live
        }
    }
    if (!owns_pinned_) return sid;  // caller's socket: its death is final
    std::lock_guard<std::mutex> g(pin_mu_);
    // Re-check: another fiber may have recreated while we waited.
    if (pinned_socket_ != sid) return pinned_socket_;
    SocketId fresh;
    if (CreateOwnedPinnedSocket(&fresh) != 0) return pinned_socket_;
    pinned_socket_ = fresh;
    return fresh;
}

int Channel::Init(const char* server_addr_and_port,
                  const ChannelOptions* options) {
    EndPoint ep;
    if (hostname2endpoint(server_addr_and_port, &ep) != 0) {
        LOG(ERROR) << "bad address: " << server_addr_and_port;
        return -1;
    }
    return Init(ep, options);
}

int Channel::InitWithSocketId(SocketId sid, const ChannelOptions* options) {
    GlobalInitializeOrDie();
    if (options != nullptr) options_ = *options;
    ConfigureRetryBudget();
    SocketUniquePtr s;
    if (Socket::AddressSocket(sid, &s) != 0) {
        LOG(ERROR) << "InitWithSocketId: dead socket id=" << sid;
        return -1;
    }
    server_ep_ = s->remote_side();
    pinned_socket_ = sid;
    return 0;
}

int Channel::InitIci(const EndPoint& server, const ChannelOptions* options) {
    GlobalInitializeOrDie();
    SocketId sid;
    if (IciConnect(server, client_messenger(), &sid) != 0) {
        LOG(ERROR) << "InitIci: handshake with " << endpoint2str(server)
                   << " failed";
        return -1;
    }
    return InitWithSocketId(sid, options);
}

int Channel::Init(const char* naming_url, const char* lb_name,
                  const ChannelOptions* options) {
    GlobalInitializeOrDie();
    if (options != nullptr) options_ = *options;
    ConfigureRetryBudget();
    // Plain "ip:port" with an LB name degenerates to single-server.
    if (strstr(naming_url, "://") == nullptr) {
        return Init(naming_url, options);
    }
    auto lb = std::make_shared<LoadBalancerWithNaming>();
    if (lb->Init(naming_url, lb_name == nullptr ? "rr" : lb_name) != 0) {
        return -1;
    }
    lb_ = std::move(lb);
    return 0;
}

// Timer callback for RPC deadlines: holds only the CallId VALUE (never a
// pointer), so a finished/destroyed RPC makes this a no-op (reference
// HandleTimeout, controller.cpp:593).
static void HandleTimeoutCb(void* arg) {
    id_error((CallId)(uintptr_t)arg, TERR_RPC_TIMEDOUT);
}

void Channel::CallMethod(const google::protobuf::MethodDescriptor* method,
                         google::protobuf::RpcController* controller,
                         const google::protobuf::Message* request,
                         google::protobuf::Message* response,
                         google::protobuf::Closure* done) {
    Controller* cntl = static_cast<Controller*>(controller);
    cntl->channel_ = this;
    cntl->method_ = method;
    cntl->response_ = response;
    cntl->done_ = done;
    cntl->start_us_ = monotonic_time_us();

    if (id_create(&cntl->correlation_id_, cntl,
                  &Controller::HandleErrorThunk) != 0) {
        cntl->SetFailed(TERR_INTERNAL, "id_create failed");
        // This path never reaches EndRPC (there is no id to destroy), so
        // release any pre-attached client stream here.
        if (cntl->request_stream() != INVALID_VREF_ID) {
            stream_internal::FailStream(cntl->request_stream());
        }
        if (done) done->Run();
        return;
    }
    cntl->current_cid_ = cntl->correlation_id_;

    // Hold the id lock through setup + IssueRPC (reference CallMethod does
    // the same, channel.cpp:467): an early timeout/error gets QUEUED on the
    // locked id and delivered at unlock, instead of destroying the
    // Controller under our feet mid-issue.
    const CallId cid = cntl->correlation_id_;
    void* unused;
    CHECK_EQ(id_lock(cid, &unused), 0);

    // rpcz: a call issued inside a sampled server handler CONTINUES the
    // upstream trace (cross-host stitching needs the parent link — the
    // downstream hop's server span points back at THIS client span);
    // outside a handler the local sampling gate may start a fresh trace.
    // Contract (same as the deadline-inheritance deref below): the
    // upstream controller — and thus its span — is valid only until the
    // handler runs done->Run(); a handler must not issue calls under
    // this scope after completing its own response.
    Controller* up = CurrentServerCall();
    Span* upspan = up != nullptr && IsRpczEnabled() ? up->span_ : nullptr;
    if (upspan != nullptr || IsRpczSampled()) {
        auto* span = new Span;
        span->kind = Span::CLIENT;
        if (upspan != nullptr) {
            span->trace_id = upspan->trace_id;
            span->parent_span_id = upspan->span_id;
        } else {
            span->trace_id = fast_rand();
        }
        span->span_id = fast_rand();
        span->method = method->full_name();
        span->start_us = cntl->start_us_;
        cntl->span_ = span;
        cntl->sampled_trace_id_ = span->trace_id;
    }

    if (!SerializePbToIOBuf(*request, &cntl->request_buf_)) {
        cntl->SetFailed(TERR_REQUEST, "serialize request failed");
        cntl->EndRPC(cid);
        return;
    }
    // gRPC framing carries its own compressed-flag + grpc-encoding
    // negotiation, which this client doesn't speak yet — sending our
    // gzip bytes with flag 0 would make the server parse gzip as raw pb.
    // Fail loudly instead of corrupting.
    if (options_.protocol == "grpc" &&
        cntl->request_compress_type() != COMPRESS_NONE) {
        cntl->SetFailed(TERR_REQUEST,
                        "request compression unsupported on grpc channels");
        cntl->EndRPC(cid);
        return;
    }
    // Compress ONCE here, not per-try: retries and backups re-send the
    // same compressed bytes (reference compresses in CallMethod too).
    if (cntl->request_compress_type() != COMPRESS_NONE) {
        IOBuf compressed;
        if (!CompressBody(cntl->request_compress_type(),
                          cntl->request_buf_, &compressed)) {
            cntl->SetFailed(TERR_REQUEST, "compress request failed");
            cntl->EndRPC(cid);
            return;
        }
        cntl->request_buf_.swap(compressed);
    }

    const int64_t timeout_ms =
        cntl->timeout_ms_ >= 0 ? cntl->timeout_ms_ : options_.timeout_ms;
    if (timeout_ms > 0) {
        cntl->deadline_us_ = cntl->start_us_ + timeout_ms * 1000;
    }
    // Hop-to-hop deadline inheritance: a call issued inside a server
    // handler never outlives its upstream caller's patience — the
    // deadline is capped at the upstream remaining budget (which IssueRPC
    // then forwards downstream as the remaining-time meta), and the call
    // registers with the server call so an upstream cancel cascades into
    // it.
    Controller* parent = CurrentServerCall();
    if (parent != nullptr && parent->has_server_deadline()) {
        const int64_t upstream = parent->server_deadline_us();
        if (cntl->deadline_us_ == 0 || upstream < cntl->deadline_us_) {
            cntl->deadline_us_ = upstream;
        }
    }
    // QoS identity inheritance (ISSUE 8): a child call issued inside a
    // handler carries its upstream's tenant + priority unless the
    // handler set its own — the whole downstream tree of a low-priority
    // request stays sheddable, and a tenant's quota follows its traffic
    // through the mesh (same shape as the deadline cap above).
    if (parent != nullptr) {
        if (cntl->tenant().empty() && !parent->tenant().empty()) {
            cntl->set_tenant(parent->tenant());
        }
        if (!cntl->has_priority() && parent->has_priority()) {
            cntl->set_priority(parent->priority());
        }
        if (cntl->session().empty() && !parent->session().empty()) {
            cntl->set_session(parent->session());
        }
    }
    if (cntl->deadline_us_ > 0) {
        cntl->timeout_timer_ = TimerThread::singleton()->schedule(
            HandleTimeoutCb, (void*)(uintptr_t)cid, cntl->deadline_us_);
    }
    if (parent != nullptr && !parent->AddChildCall(cid)) {
        // The upstream call was canceled before this one even started:
        // queue the cancel on the locked id; it is delivered at unlock.
        id_error(cid, ECANCELED);
    }
    // Backup request timer (reference controller.cpp:344-358): fires
    // before the deadline, re-issues on a second call id, first response
    // wins. Requires retry budget (a backup consumes one retry). A
    // pluggable policy (retry_policy.h) decides the delay per call.
    const int64_t backup_ms =
        options_.backup_request_policy != nullptr
            ? options_.backup_request_policy->GetDelayMs(cntl)
            : (cntl->backup_request_ms_ >= 0 ? cntl->backup_request_ms_
                                             : options_.backup_request_ms);
    // Compare against the EFFECTIVE deadline (the inherited cap may be
    // tighter than the configured timeout): hedging past — or without —
    // remaining budget is pure waste, so a deadline that leaves less
    // than the hedge delay (including one already expired) suppresses
    // the timer; only a truly deadline-less call hedges unconditionally.
    const bool has_deadline = cntl->deadline_us_ > 0;
    const int64_t effective_timeout_ms =
        has_deadline ? (cntl->deadline_us_ - cntl->start_us_) / 1000 : 0;
    if (backup_ms >= 0 &&
        (!has_deadline || backup_ms < effective_timeout_ms)) {
        cntl->backup_timer_ = TimerThread::singleton()->schedule(
            &Controller::HandleBackupThunk, (void*)(uintptr_t)cid,
            cntl->start_us_ + backup_ms * 1000);
    }

    flight::Record(flight::kRpcIssue, cid, cntl->sampled_trace_id_);
    cntl->IssueRPC();
    id_unlock(cid);  // delivers any queued early error
    // `cntl` may already be gone here (async completion).

    if (done == nullptr) {
        // Synchronous call: wait for destroy (works from fibers and plain
        // pthreads alike — butex handles both waiter kinds).
        id_join(cid);
    }
}

}  // namespace tpurpc
