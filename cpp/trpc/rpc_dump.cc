#include "trpc/rpc_dump.h"

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstring>
#include <mutex>

#include "rpc_meta.pb.h"
#include "tbase/flags.h"
#include "tbase/logging.h"
#include "tbase/recordio.h"
#include "tbase/time.h"
#include "trpc/pb_compat.h"
#include "trpc/policy_tpu_std.h"
#include "tvar/collector.h"

DEFINE_bool(rpc_dump, false,
            "sample live requests into -rpc_dump_dir (recordio)");
DEFINE_string(rpc_dump_dir, "/tmp", "directory for rpc dump files");

namespace tpurpc {

namespace {

// One writer per process, created lazily and re-opened when the live
// -rpc_dump_dir flag changes (the reference cuts multiple files; one per
// process per directory is enough here). Guarded by g_dump_mu.
std::mutex g_dump_mu;
RecordWriter* dump_writer() {
    static RecordWriter* w = nullptr;
    static std::string w_path;
    const std::string path = RpcDumpFilePath();
    if (w == nullptr || w_path != path) {
        delete w;
        w = new RecordWriter(path);
        w_path = path;
    }
    return w;
}

struct SampledRequest : public Collected {
    IOBuf payload;  // u32 meta_len + meta + body

    void dispatch() override {
        std::lock_guard<std::mutex> g(g_dump_mu);
        RecordWriter* w = dump_writer();
        if (w->valid()) {
            w->Write(payload);
            w->Flush();
        }
    }
};

}  // namespace

std::string RpcDumpFilePath() {
    return FLAGS_rpc_dump_dir.get() + "/requests." +
           std::to_string(getpid()) + ".dump";
}

bool IsRpcDumpSampled() {
    return FLAGS_rpc_dump.get() && Collector::singleton()->sample();
}

void SubmitRpcDump(const IOBuf& meta_bytes, const IOBuf& body) {
    auto* s = new SampledRequest;
    const uint32_t mlen = htonl((uint32_t)meta_bytes.size());
    s->payload.append(&mlen, sizeof(mlen));
    s->payload.append(meta_bytes);  // refcounted block refs, no copy
    s->payload.append(body);
    Collector::singleton()->submit(s);
}

int ReplayDumpFile(const std::string& path, const EndPoint& server,
                   int times) {
    RecordReader probe(path);
    if (!probe.valid()) return -1;

    const int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    sockaddr_in addr;
    endpoint2sockaddr(server, &addr);
    if (::connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
        close(fd);
        return -1;
    }
    int ok = 0;
    uint64_t next_cid = 1;
    for (int round = 0; round < times; ++round) {
        RecordReader reader(path);
        IOBuf rec;
        while (reader.Read(&rec)) {
            uint32_t mlen = 0;
            if (rec.size() < sizeof(mlen)) continue;
            rec.cutn(&mlen, sizeof(mlen));
            mlen = ntohl(mlen);
            if ((size_t)mlen > rec.size()) continue;
            IOBuf meta_bytes;
            rec.cutn(&meta_bytes, mlen);
            rpc::RpcMeta meta;
            if (!ParsePbFromIOBuf(&meta, meta_bytes)) continue;
            // Fresh correlation id per send: the recorded one belongs to
            // a dead RPC (reference rpc_replay rewrites it the same way).
            meta.set_correlation_id(next_cid++);
            IOBuf new_meta;
            SerializePbToIOBuf(meta, &new_meta);
            IOBuf frame;
            PackTpuStdFrame(&frame, new_meta, rec, IOBuf());
            const std::string wire = frame.to_string();
            if (send(fd, wire.data(), wire.size(), MSG_NOSIGNAL) !=
                (ssize_t)wire.size()) {
                close(fd);
                return ok;
            }
            // Await one full response frame (12-byte header + body) and
            // count it only when the response meta says success.
            std::string got;
            char buf[8192];
            uint32_t body_size = 0, resp_meta_size = 0;
            while (true) {
                if (got.size() >= 12) {
                    memcpy(&body_size, got.data() + 4, 4);
                    memcpy(&resp_meta_size, got.data() + 8, 4);
                    body_size = ntohl(body_size);
                    resp_meta_size = ntohl(resp_meta_size);
                    if (got.size() >= 12u + body_size) break;
                }
                const ssize_t r = recv(fd, buf, sizeof(buf), 0);
                if (r <= 0) {
                    close(fd);
                    return ok;
                }
                got.append(buf, (size_t)r);
            }
            rpc::RpcMeta resp_meta;
            if (resp_meta_size <= body_size &&
                resp_meta.ParseFromArray(got.data() + 12,
                                         (int)resp_meta_size) &&
                resp_meta.response().error_code() == 0) {
                ++ok;
            }
        }
    }
    close(fd);
    return ok;
}

}  // namespace tpurpc
