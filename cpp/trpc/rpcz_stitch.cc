#include "trpc/rpcz_stitch.h"

#include <fcntl.h>
#include <poll.h>
#include <strings.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <cctype>
#include <cerrno>
#include <cinttypes>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <set>
#include <vector>

#include "tbase/endpoint.h"
#include "tbase/flags.h"
#include "tbase/time.h"
#include "tnet/socket_map.h"
#include "trpc/span.h"

// Mesh membership for the stitcher ("ip:port,ip:port"). SocketMap remotes
// ride along automatically; this flag covers nodes this process never
// called (and is what the soaks set).
DEFINE_string(rpcz_peers, "",
              "comma-separated ip:port portals to stitch traces from");
DEFINE_int32(rpcz_stitch_timeout_ms, 1000,
             "TOTAL budget for one /rpcz/trace peer fan-out");

namespace tpurpc {

namespace {

// One span as the stitcher sees it — local spans converted, remote spans
// parsed back from RenderRpczJson output. Notes arrive pre-formatted
// ("+123us text") because cross-host at_us values are meaningless raw.
struct StitchSpan {
    std::string host;
    uint64_t trace_id = 0;
    uint64_t span_id = 0;
    uint64_t parent_span_id = 0;
    bool server = false;
    std::string method;
    std::string remote;
    int error_code = 0;
    int retries = 0;
    int64_t request_bytes = 0;
    int64_t response_bytes = 0;
    int64_t start_us = 0, sent_us = 0, received_us = 0;
    int64_t process_start_us = 0, process_end_us = 0, end_us = 0;
    std::vector<std::string> notes;
};

// ---------------- minimal HTTP/1.1 GET ----------------

// Blocking (poll-paced) GET against a portal; the whole exchange must
// finish inside `deadline_us`. Returns false on any failure. Runs on the
// handler's fiber — worst case it parks one worker pthread for the
// timeout, the same cost class as /hotspots/cpu.
bool HttpGet(const EndPoint& ep, const std::string& path,
             int64_t deadline_us, std::string* body) {
    const int fd = ::socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
    if (fd < 0) return false;
    sockaddr_in addr;
    endpoint2sockaddr(ep, &addr);
    auto remaining_ms = [deadline_us]() -> int {
        const int64_t r = (deadline_us - monotonic_time_us()) / 1000;
        return r > 0 ? (int)r : 0;
    };
    if (::connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
        if (errno != EINPROGRESS) {
            close(fd);
            return false;
        }
        pollfd p{fd, POLLOUT, 0};
        if (poll(&p, 1, remaining_ms()) != 1) {
            close(fd);
            return false;
        }
        int err = 0;
        socklen_t len = sizeof(err);
        if (getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len) != 0 ||
            err != 0) {
            close(fd);
            return false;
        }
    }
    const std::string req = "GET " + path +
                            " HTTP/1.1\r\nHost: " + endpoint2str(ep) +
                            "\r\nConnection: close\r\n\r\n";
    size_t sent = 0;
    while (sent < req.size()) {
        const ssize_t n =
            ::send(fd, req.data() + sent, req.size() - sent, MSG_NOSIGNAL);
        if (n > 0) {
            sent += (size_t)n;
            continue;
        }
        if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
            pollfd p{fd, POLLOUT, 0};
            if (poll(&p, 1, remaining_ms()) != 1) {
                close(fd);
                return false;
            }
            continue;
        }
        close(fd);
        return false;
    }
    std::string buf;
    size_t header_end = std::string::npos;
    int64_t content_length = -1;
    // Bound BOTH time and size on the read side: a misconfigured peer
    // that streams forever must cost at most the deadline, never the
    // heap (the deadline is re-checked every iteration, not only on
    // EAGAIN).
    constexpr size_t kMaxBody = 16u << 20;
    while (true) {
        if (monotonic_time_us() >= deadline_us || buf.size() > kMaxBody) {
            close(fd);
            return false;
        }
        char chunk[8192];
        const ssize_t n = ::recv(fd, chunk, sizeof(chunk), 0);
        if (n > 0) {
            buf.append(chunk, (size_t)n);
        } else if (n == 0) {
            break;  // EOF (we asked for Connection: close)
        } else if (errno == EAGAIN || errno == EWOULDBLOCK) {
            pollfd p{fd, POLLIN, 0};
            if (poll(&p, 1, remaining_ms()) != 1) {
                close(fd);
                return false;
            }
            continue;
        } else {
            close(fd);
            return false;
        }
        if (header_end == std::string::npos) {
            header_end = buf.find("\r\n\r\n");
            if (header_end != std::string::npos) {
                // Status + Content-Length (the portal always sets it).
                if (buf.compare(0, 9, "HTTP/1.1 ") != 0 ||
                    buf.compare(9, 3, "200") != 0) {
                    close(fd);
                    return false;
                }
                const char* needle = "content-length:";
                for (size_t pos = 0; pos < header_end;) {
                    size_t eol = buf.find("\r\n", pos);
                    if (eol == std::string::npos || eol > header_end) break;
                    if (eol - pos > strlen(needle) &&
                        strncasecmp(buf.c_str() + pos, needle,
                                    strlen(needle)) == 0) {
                        content_length =
                            atoll(buf.c_str() + pos + strlen(needle));
                    }
                    pos = eol + 2;
                }
            }
        }
        if (header_end != std::string::npos && content_length >= 0 &&
            buf.size() >= header_end + 4 + (size_t)content_length) {
            break;  // full body buffered
        }
    }
    close(fd);
    if (header_end == std::string::npos) return false;
    if (content_length < 0) {
        *body = buf.substr(header_end + 4);
    } else if (buf.size() >= header_end + 4 + (size_t)content_length) {
        *body = buf.substr(header_end + 4, (size_t)content_length);
    } else {
        return false;  // truncated
    }
    return true;
}

// ---------------- RenderRpczJson parser ----------------
// Parses exactly the shape span.cc emits (flat span objects with string /
// integer values and a flat notes string array) — not a general JSON
// parser, but tolerant of unknown keys so the two sides can evolve.

struct Scanner {
    const std::string& s;
    size_t p = 0;
    explicit Scanner(const std::string& str) : s(str) {}
    void ws() {
        while (p < s.size() && isspace((unsigned char)s[p])) ++p;
    }
    bool eat(char c) {
        ws();
        if (p < s.size() && s[p] == c) {
            ++p;
            return true;
        }
        return false;
    }
    bool peek(char c) {
        ws();
        return p < s.size() && s[p] == c;
    }
    bool string(std::string* out) {
        ws();
        if (p >= s.size() || s[p] != '"') return false;
        ++p;
        out->clear();
        while (p < s.size() && s[p] != '"') {
            if (s[p] == '\\' && p + 1 < s.size()) {
                const char e = s[p + 1];
                if (e == 'u' && p + 5 < s.size()) {
                    out->push_back('?');  // control chars: lossy is fine
                    p += 6;
                    continue;
                }
                out->push_back(e == 'n' ? '\n' : e == 't' ? '\t' : e);
                p += 2;
                continue;
            }
            out->push_back(s[p++]);
        }
        return eat('"');
    }
    bool number(int64_t* out) {
        ws();
        char* end = nullptr;
        const long long v = strtoll(s.c_str() + p, &end, 10);
        if (end == s.c_str() + p) return false;
        *out = v;
        p = (size_t)(end - s.c_str());
        return true;
    }
    // Skip one value of any supported shape (unknown keys).
    bool skip_value() {
        ws();
        if (peek('"')) {
            std::string tmp;
            return string(&tmp);
        }
        if (eat('[')) {
            if (eat(']')) return true;
            do {
                if (!skip_value()) return false;
            } while (eat(','));
            return eat(']');
        }
        if (eat('{')) {
            if (eat('}')) return true;
            do {
                std::string k;
                if (!string(&k) || !eat(':') || !skip_value()) return false;
            } while (eat(','));
            return eat('}');
        }
        int64_t tmp;
        return number(&tmp);
    }
};

bool ParseSpanObject(Scanner& sc, StitchSpan* out) {
    if (!sc.eat('{')) return false;
    if (sc.eat('}')) return true;
    do {
        std::string key;
        if (!sc.string(&key) || !sc.eat(':')) return false;
        if (key == "trace_id" || key == "span_id" ||
            key == "parent_span_id") {
            std::string v;
            if (!sc.string(&v)) return false;
            const uint64_t id = strtoull(v.c_str(), nullptr, 10);
            if (key == "trace_id") out->trace_id = id;
            if (key == "span_id") out->span_id = id;
            if (key == "parent_span_id") out->parent_span_id = id;
        } else if (key == "kind") {
            std::string v;
            if (!sc.string(&v)) return false;
            out->server = v == "SERVER";
        } else if (key == "method") {
            if (!sc.string(&out->method)) return false;
        } else if (key == "remote") {
            if (!sc.string(&out->remote)) return false;
        } else if (key == "notes") {
            if (!sc.eat('[')) return false;
            if (!sc.eat(']')) {
                do {
                    std::string n;
                    if (!sc.string(&n)) return false;
                    out->notes.push_back(std::move(n));
                } while (sc.eat(','));
                if (!sc.eat(']')) return false;
            }
        } else {
            int64_t v = 0;
            if (sc.peek('"') || sc.peek('[') || sc.peek('{')) {
                if (!sc.skip_value()) return false;
            } else if (sc.number(&v)) {
                if (key == "error_code") out->error_code = (int)v;
                else if (key == "retries") out->retries = (int)v;
                else if (key == "request_bytes") out->request_bytes = v;
                else if (key == "response_bytes") out->response_bytes = v;
                else if (key == "start_us") out->start_us = v;
                else if (key == "sent_us") out->sent_us = v;
                else if (key == "received_us") out->received_us = v;
                else if (key == "process_start_us") out->process_start_us = v;
                else if (key == "process_end_us") out->process_end_us = v;
                else if (key == "end_us") out->end_us = v;
            } else {
                return false;
            }
        }
    } while (sc.eat(','));
    return sc.eat('}');
}

bool ParseRpczJson(const std::string& body,
                   std::vector<StitchSpan>* spans) {
    Scanner sc(body);
    if (!sc.eat('{')) return false;
    std::string host;
    bool ok = true;
    do {
        std::string key;
        if (!sc.string(&key) || !sc.eat(':')) return false;
        if (key == "host") {
            if (!sc.string(&host)) return false;
        } else if (key == "spans") {
            if (!sc.eat('[')) return false;
            if (!sc.eat(']')) {
                do {
                    StitchSpan s;
                    if (!ParseSpanObject(sc, &s)) return false;
                    spans->push_back(std::move(s));
                } while (sc.eat(','));
                if (!sc.eat(']')) return false;
            }
        } else if (!sc.skip_value()) {
            return false;
        }
    } while (sc.eat(','));
    for (StitchSpan& s : *spans) s.host = host;
    return ok && sc.eat('}');
}

// ---------------- collection ----------------

void FormatNote(const Span::Note& n, int64_t span_start,
                std::vector<std::string>* out) {
    char buf[64];
    snprintf(buf, sizeof(buf), "%+" PRId64 "us ", n.at_us - span_start);
    out->push_back(buf + n.text);
}

void CollectLocal(uint64_t trace_id, std::vector<StitchSpan>* out) {
    for (const Span& s : SpanDB::singleton()->Recent(256, trace_id)) {
        StitchSpan t;
        t.host = RpczHost();
        t.trace_id = s.trace_id;
        t.span_id = s.span_id;
        t.parent_span_id = s.parent_span_id;
        t.server = s.kind == Span::SERVER;
        t.method = s.method;
        t.remote = endpoint2str(s.remote_side);
        t.error_code = s.error_code;
        t.retries = s.retries;
        t.request_bytes = s.request_bytes;
        t.response_bytes = s.response_bytes;
        t.start_us = s.start_us;
        t.sent_us = s.sent_us;
        t.received_us = s.received_us;
        t.process_start_us = s.process_start_us;
        t.process_end_us = s.process_end_us;
        t.end_us = s.end_us;
        for (const Span::Note& n : s.notes) {
            FormatNote(n, s.start_us, &t.notes);
        }
        out->push_back(std::move(t));
    }
}

std::vector<EndPoint> StitchPeers() {
    std::set<std::string> seen;
    std::vector<EndPoint> out;
    auto add = [&](const EndPoint& ep) {
        if (ep.port <= 0) return;  // unix / unset: no portal to query
        const std::string key = endpoint2str(ep);
        if (key == RpczHost()) return;  // self: already collected locally
        if (seen.insert(key).second) out.push_back(ep);
    };
    const std::string flag = FLAGS_rpcz_peers.get();
    size_t pos = 0;
    while (pos <= flag.size()) {
        const size_t c = flag.find(',', pos);
        const size_t end = c == std::string::npos ? flag.size() : c;
        if (end > pos) {
            EndPoint ep;
            if (str2endpoint(flag.substr(pos, end - pos).c_str(), &ep) ==
                0) {
                add(ep);
            }
        }
        pos = end + 1;
    }
    for (const EndPoint& ep : SocketMap::singleton()->endpoints()) {
        add(ep);
    }
    return out;
}

// ---------------- tree + rendering ----------------

struct RenderCtx {
    std::vector<StitchSpan> spans;
    std::multimap<uint64_t, size_t> children;  // parent_span_id -> index
    std::vector<bool> placed;
    std::string out;
};

int64_t SpanDuration(const StitchSpan& s) {
    return s.end_us > s.start_us ? s.end_us - s.start_us : 0;
}

void RenderSpan(RenderCtx& ctx, size_t idx, int64_t offset, int depth);

// Children of `idx`, displayed with clock normalization: a SERVER child
// on another host is anchored into its parent CLIENT span's sent/recv
// envelope; same-host children inherit the parent's offset.
void RenderChildren(RenderCtx& ctx, size_t idx, int64_t offset, int depth) {
    const StitchSpan& parent = ctx.spans[idx];
    std::vector<std::pair<int64_t, std::pair<size_t, int64_t>>> ordered;
    auto range = ctx.children.equal_range(parent.span_id);
    for (auto it = range.first; it != range.second; ++it) {
        const size_t ci = it->second;
        if (ctx.placed[ci]) continue;
        const StitchSpan& child = ctx.spans[ci];
        int64_t child_offset;
        if (child.host == parent.host) {
            child_offset = offset;  // same clock
        } else {
            // Anchor into the parent's wire envelope: the child's span
            // must nest inside [parent.sent, parent.received]; the RTT
            // residue splits evenly between the two wire directions.
            const int64_t psent =
                parent.sent_us > 0 ? parent.sent_us : parent.start_us;
            const int64_t precv = parent.received_us > 0
                                      ? parent.received_us
                                      : parent.end_us;
            int64_t wire = (precv - psent) - SpanDuration(child);
            if (wire < 0) wire = 0;
            child_offset = (psent + offset + wire / 2) - child.start_us;
        }
        ordered.push_back(
            {child.start_us + child_offset, {ci, child_offset}});
    }
    std::sort(ordered.begin(), ordered.end());
    for (const auto& o : ordered) {
        RenderSpan(ctx, o.second.first, o.second.second, depth + 1);
    }
}

void RenderSpan(RenderCtx& ctx, size_t idx, int64_t offset, int depth) {
    if (depth > 32) return;  // corrupt parentage: refuse to recurse forever
    ctx.placed[idx] = true;
    const StitchSpan& s = ctx.spans[idx];
    const std::string indent((size_t)depth * 4, ' ');
    char line[512];
    snprintf(line, sizeof(line),
             "%s%s%s %s @%s  start=+%" PRId64 "us total=%" PRId64
             "us err=%d req=%" PRId64 "B res=%" PRId64 "B%s\n",
             indent.c_str(), depth > 0 ? "\\_ " : "",
             s.server ? "SERVER" : "CLIENT", s.method.c_str(),
             s.host.c_str(), s.start_us + offset, SpanDuration(s),
             s.error_code, s.request_bytes, s.response_bytes,
             s.retries > 0 ? "  [re-issued]" : "");
    ctx.out += line;
    auto phase = [](int64_t from, int64_t to) -> int64_t {
        return (from > 0 && to >= from) ? to - from : 0;
    };
    if (s.server) {
        // Per-hop breakdown: queue (received -> handler fiber), process
        // (handler body), write (response serialize+send).
        snprintf(line, sizeof(line),
                 "%s      queue=%" PRId64 "us process=%" PRId64
                 "us write=%" PRId64 "us\n",
                 indent.c_str(), phase(s.start_us, s.process_start_us),
                 phase(s.process_start_us, s.process_end_us),
                 phase(s.process_end_us, s.end_us));
        ctx.out += line;
    } else {
        // Wire time of this hop: the envelope minus the (single) server
        // child's span — only meaningful when that child was stitched in.
        int64_t child_total = -1;
        auto range = ctx.children.equal_range(s.span_id);
        for (auto it = range.first; it != range.second; ++it) {
            if (ctx.spans[it->second].server) {
                child_total = SpanDuration(ctx.spans[it->second]);
                break;
            }
        }
        const int64_t psent = s.sent_us > 0 ? s.sent_us : s.start_us;
        const int64_t precv =
            s.received_us > 0 ? s.received_us : s.end_us;
        if (child_total >= 0) {
            int64_t wire = (precv - psent) - child_total;
            if (wire < 0) wire = 0;
            snprintf(line, sizeof(line),
                     "%s      issue=%" PRId64 "us wire=%" PRId64
                     "us (rtt residue) downstream=%" PRId64 "us\n",
                     indent.c_str(), phase(s.start_us, s.sent_us), wire,
                     child_total);
        } else {
            snprintf(line, sizeof(line),
                     "%s      issue=%" PRId64 "us wait=%" PRId64
                     "us done=%" PRId64 "us\n",
                     indent.c_str(), phase(s.start_us, s.sent_us),
                     phase(s.sent_us, precv), phase(precv, s.end_us));
        }
        ctx.out += line;
    }
    for (const std::string& n : s.notes) {
        ctx.out += indent + "      @" + n + "\n";
    }
    RenderChildren(ctx, idx, offset, depth);
}

}  // namespace

std::string RenderStitchedTrace(uint64_t trace_id) {
    RenderCtx ctx;
    CollectLocal(trace_id, &ctx.spans);
    const std::vector<EndPoint> peers = StitchPeers();
    int peers_ok = 0, peers_failed = 0;
    char path[128];
    snprintf(path, sizeof(path), "/rpcz?format=json&trace_id=%" PRIu64,
             trace_id);
    // ONE shared budget for the whole fan-out (a per-peer budget would
    // stack N dead peers into N timeouts), split FAIRLY as it is spent:
    // each peer gets remaining/peers_left, so one black-holed peer early
    // in the list cannot starve the healthy peers behind it of their
    // share. Healthy portals answer in microseconds and return the
    // unused share to the pool.
    const int64_t fanout_deadline =
        monotonic_time_us() +
        (int64_t)FLAGS_rpcz_stitch_timeout_ms.get() * 1000;
    for (size_t i = 0; i < peers.size(); ++i) {
        const int64_t now = monotonic_time_us();
        const int64_t remaining =
            fanout_deadline > now ? fanout_deadline - now : 0;
        const int64_t deadline =
            now + remaining / (int64_t)(peers.size() - i);
        std::string body;
        std::vector<StitchSpan> remote;
        if (HttpGet(peers[i], path, deadline, &body) &&
            ParseRpczJson(body, &remote)) {
            ++peers_ok;
            for (StitchSpan& s : remote) ctx.spans.push_back(std::move(s));
        } else {
            ++peers_failed;
        }
    }
    // Dedup (a peer may also appear in -rpcz_peers AND SocketMap; a span
    // must render once).
    {
        std::set<std::pair<std::string, uint64_t>> seen;
        std::vector<StitchSpan> uniq;
        for (StitchSpan& s : ctx.spans) {
            if (s.trace_id != trace_id) continue;
            if (seen.insert({s.host, s.span_id}).second) {
                uniq.push_back(std::move(s));
            }
        }
        ctx.spans.swap(uniq);
    }
    char head[256];
    snprintf(head, sizeof(head),
             "stitched trace %" PRIu64 ": %zu span(s), peers queried: %zu "
             "(ok %d, failed %d)\n"
             "host clocks normalized via parent-child send/recv envelopes; "
             "times relative to trace start\n\n",
             trace_id, ctx.spans.size(), peers.size(), peers_ok,
             peers_failed);
    std::string out = head;
    if (ctx.spans.empty()) {
        out += "no spans for this trace (rpcz disabled, evicted, or wrong "
               "id; check -rpcz_peers covers the mesh)\n";
        return out;
    }
    ctx.placed.assign(ctx.spans.size(), false);
    std::set<uint64_t> ids;
    for (size_t i = 0; i < ctx.spans.size(); ++i) {
        ctx.children.emplace(ctx.spans[i].parent_span_id, i);
        ids.insert(ctx.spans[i].span_id);
    }
    // Roots: no parent, or the parent span was never collected. Roots
    // render at offset -start (trace time zero); orphan subtrees fall
    // back to the same anchoring.
    std::vector<std::pair<int64_t, size_t>> roots;
    for (size_t i = 0; i < ctx.spans.size(); ++i) {
        const StitchSpan& s = ctx.spans[i];
        if (s.parent_span_id == 0 || ids.count(s.parent_span_id) == 0) {
            roots.push_back({s.start_us, i});
        }
    }
    std::sort(roots.begin(), roots.end());
    for (const auto& r : roots) {
        if (!ctx.placed[r.second]) {
            RenderSpan(ctx, r.second, -ctx.spans[r.second].start_us, 0);
            ctx.out += "\n";
        }
    }
    // Orphans with a dangling parent inside a cycle (never placed).
    for (size_t i = 0; i < ctx.spans.size(); ++i) {
        if (!ctx.placed[i]) {
            RenderSpan(ctx, i, -ctx.spans[i].start_us, 0);
        }
    }
    out += ctx.out;
    return out;
}

}  // namespace tpurpc
