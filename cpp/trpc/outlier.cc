// Outlier detection + ejection engine (ISSUE 20). See outlier.h for the
// design; this file holds the detector math, the state machine, the
// rpc_outlier_* families and the /outliers describers. Pb-free.
#include "trpc/outlier.h"

#include <algorithm>
#include <cstdio>
#include <vector>

#include "tbase/errno.h"
#include "tbase/flags.h"
#include "tbase/flight_recorder.h"
#include "tbase/logging.h"
#include "tbase/time.h"
#include "tnet/socket.h"
#include "tvar/reducer.h"

DEFINE_bool(outlier_detection_enabled, true,
            "watch passive per-RPC feedback and eject grey backends "
            "(slow/lossy but probe-alive) from the LB pick set");
DEFINE_int32(outlier_consecutive_errors, 5,
             "eject a backend after this many hard failures in a row");
DEFINE_int32(outlier_check_interval_ms, 250,
             "latency-outlier sweep cadence (median + MAD over the "
             "live set's latency EWMAs)");
DEFINE_int32(outlier_latency_ratio_pct, 300,
             "latency ejection needs ewma >= this percent of the "
             "live-set median (300 = 3x)");
DEFINE_int32(outlier_latency_mad_k, 4,
             "latency ejection needs ewma > median + k*MAD (scale-"
             "relative guard: a uniformly slow mesh ejects nobody)");
DEFINE_int32(outlier_min_delta_us, 5000,
             "latency ejection needs ewma - median >= this many us "
             "(absolute guard against microsecond-scale jitter)");
DEFINE_int32(outlier_min_samples, 8,
             "a backend needs this many feedbacks since its last state "
             "change before the latency detector may judge it");
DEFINE_int32(outlier_max_ejection_pct, 40,
             "never hold more than this percent of a tracker's "
             "backends out of the pick set at once");
DEFINE_int32(outlier_ejection_ms, 2000,
             "base ejection window; doubles per relapse");
DEFINE_int32(outlier_max_ejection_window_ms, 60000,
             "cap on the exponentially-growing ejection window");
DEFINE_int32(outlier_probe_interval_ms, 200,
             "after the window expires, divert one REAL rpc to the "
             "backend at most this often");
DEFINE_int32(outlier_probe_passes, 3,
             "consecutive probe successes required before the "
             "slow-start ramp re-admits the backend");
DEFINE_int32(outlier_rampup_ms, 3000,
             "slow-start window: pick admission probability ramps "
             "0->100% over this span after probes pass");

namespace tpurpc {
namespace outlier {

namespace {

LazyAdder g_ejections("rpc_outlier_ejections");
LazyAdder g_reinstatements("rpc_outlier_reinstatements");
LazyAdder g_probe_passes("rpc_outlier_probe_passes");
LazyAdder g_probe_fails("rpc_outlier_probe_fails");
// Ejections the bounds vetoed (max pct / subset floor): a grey MAJORITY
// stays routable even if individually eject-worthy.
LazyAdder g_eject_vetoes("rpc_outlier_eject_vetoes");

// Process-global tracker list: /outliers and the revive observer walk
// every channel's tracker.
std::mutex g_trackers_mu;
std::vector<OutlierTracker*> g_trackers;

uint64_t splitmix64(uint64_t x) {
    x += 0x9e3779b97f4a7c15ULL;
    x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
    x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
    return x ^ (x >> 31);
}

// Flight-recorder identity of a backend: routable across dumps without
// a cid (ip4 << 16 | port). blackbox_merge decodes it back.
uint64_t PackEp(const EndPoint& ep) {
    return ((uint64_t)ntohl(ep.ip.s_addr) << 16) |
           ((uint64_t)ep.port & 0xFFFF);
}

void ReviveObserver(SocketId id) {
    std::lock_guard<std::mutex> g(g_trackers_mu);
    for (OutlierTracker* t : g_trackers) t->OnRevive(id);
}

int64_t EjectionWindowUs(int eject_count) {
    const int64_t base_ms =
        std::max<int64_t>(1, FLAGS_outlier_ejection_ms.get());
    const int shift = std::min(eject_count > 0 ? eject_count - 1 : 0, 16);
    const int64_t ms = std::min<int64_t>(
        base_ms << shift,
        std::max<int64_t>(base_ms,
                          FLAGS_outlier_max_ejection_window_ms.get()));
    return ms * 1000;
}

}  // namespace

const char* StateName(State s) {
    switch (s) {
        case State::kHealthy: return "HEALTHY";
        case State::kEjected: return "EJECTED";
        case State::kProbing: return "PROBING";
        case State::kRamping: return "RAMPING";
    }
    return "?";
}

const char* ReasonName(Reason r) {
    switch (r) {
        case Reason::kNone: return "none";
        case Reason::kConsecutiveErrors: return "consecutive_errors";
        case Reason::kLatencyOutlier: return "latency_outlier";
    }
    return "?";
}

OutlierTracker::OutlierTracker(const std::string& name) : name_(name) {
    ExposeVars();  // idempotent: families + revive observer ready
    std::lock_guard<std::mutex> g(g_trackers_mu);
    g_trackers.push_back(this);
}

OutlierTracker::~OutlierTracker() {
    std::lock_guard<std::mutex> g(g_trackers_mu);
    for (size_t i = 0; i < g_trackers.size(); ++i) {
        if (g_trackers[i] == this) {
            g_trackers.erase(g_trackers.begin() + (long)i);
            break;
        }
    }
}

void OutlierTracker::AddServer(const ServerNode& node) {
    std::lock_guard<std::mutex> g(mu_);
    Backend& b = backends_[node.id];
    b.ep = node.ep;
    b.zone = node.zone;
}

void OutlierTracker::RemoveServer(SocketId id) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = backends_.find(id);
    if (it == backends_.end()) return;
    if (it->second.state != State::kHealthy) {
        nonhealthy_.fetch_sub(1, std::memory_order_relaxed);
    }
    backends_.erase(it);
}

bool OutlierTracker::EjectLocked(SocketId id, Backend* b, Reason reason,
                                 int64_t now_us) {
    // Bounds: the detectors propose, the budget disposes. Count every
    // backend currently withheld from normal picks (ejected/probing).
    int withheld = 0;
    for (const auto& kv : backends_) {
        if (kv.second.state == State::kEjected ||
            kv.second.state == State::kProbing) {
            ++withheld;
        }
    }
    const int total = (int)backends_.size();
    const int max_pct = FLAGS_outlier_max_ejection_pct.get();
    if ((withheld + 1) * 100 > max_pct * total ||
        total - (withheld + 1) < std::max(1, min_unejected_)) {
        *g_eject_vetoes << 1;
        // Still reset the trigger so a vetoed backend re-arms instead
        // of re-proposing on every feedback.
        b->consecutive_errors = 0;
        b->samples = 0;
        return false;
    }
    if (b->state == State::kHealthy) {
        nonhealthy_.fetch_add(1, std::memory_order_relaxed);
    }
    b->eject_count += 1;
    b->state = State::kEjected;
    b->reason = reason;
    b->ejected_until_us = now_us + EjectionWindowUs(b->eject_count);
    b->probe_passes = 0;
    b->samples = 0;
    b->consecutive_errors = 0;
    char note[96];
    if (reason == Reason::kLatencyOutlier) {
        snprintf(note, sizeof(note),
                 "ejected: latency outlier %lld.%llux median",
                 (long long)(b->ratio_x100 / 100),
                 (unsigned long long)((b->ratio_x100 / 10) % 10));
    } else {
        snprintf(note, sizeof(note), "ejected: %d consecutive errors",
                 FLAGS_outlier_consecutive_errors.get());
        b->ratio_x100 = 0;
    }
    b->note = note;
    *g_ejections << 1;
    // b packs reason<<56 | detail (ratio_x100 for latency, consecutive
    // error threshold for errors) — the forensic WHY of a routing shift.
    const uint64_t detail =
        reason == Reason::kLatencyOutlier
            ? (uint64_t)(b->ratio_x100 & 0xFFFFFFFFFFFFFFULL)
            : (uint64_t)FLAGS_outlier_consecutive_errors.get();
    flight::Record(flight::kOutlierEject, PackEp(b->ep),
                   ((uint64_t)reason << 56) | detail);
    LOG(WARNING) << "outlier[" << name_ << "]: " << endpoint2str(b->ep)
                 << " " << b->note << " (window "
                 << EjectionWindowUs(b->eject_count) / 1000 << "ms)";
    return true;
}

void OutlierTracker::MaybeSweepLocked(int64_t now_us) {
    const int64_t interval_us =
        (int64_t)FLAGS_outlier_check_interval_ms.get() * 1000;
    if (now_us - last_sweep_us_.load(std::memory_order_relaxed) <
        interval_us) {
        return;
    }
    last_sweep_us_.store(now_us, std::memory_order_relaxed);
    // Live set = backends currently taking normal traffic with enough
    // samples to mean something.
    std::vector<int64_t> ewmas;
    ewmas.reserve(backends_.size());
    const int64_t min_samples = FLAGS_outlier_min_samples.get();
    for (const auto& kv : backends_) {
        const Backend& b = kv.second;
        if ((b.state == State::kHealthy || b.state == State::kRamping) &&
            b.samples >= min_samples && b.latency_ewma_us > 0) {
            ewmas.push_back(b.latency_ewma_us);
        }
    }
    // Median over fewer than 3 contributors is just "the other guy":
    // no statistical ground to eject anyone.
    if (ewmas.size() < 3) return;
    std::sort(ewmas.begin(), ewmas.end());
    const size_t mid = ewmas.size() / 2;
    const int64_t median =
        ewmas.size() % 2 ? ewmas[mid]
                         : (ewmas[mid - 1] + ewmas[mid]) / 2;
    if (median <= 0) return;
    live_median_us_ = median;
    std::vector<int64_t> devs;
    devs.reserve(ewmas.size());
    for (int64_t v : ewmas) {
        devs.push_back(v > median ? v - median : median - v);
    }
    std::sort(devs.begin(), devs.end());
    const int64_t mad =
        devs.size() % 2 ? devs[mid]
                        : (devs[mid - 1] + devs[mid]) / 2;
    const int64_t ratio_pct = FLAGS_outlier_latency_ratio_pct.get();
    const int64_t k = FLAGS_outlier_latency_mad_k.get();
    const int64_t min_delta = FLAGS_outlier_min_delta_us.get();
    for (auto& kv : backends_) {
        Backend& b = kv.second;
        if (b.state != State::kHealthy && b.state != State::kRamping) {
            continue;
        }
        if (b.samples < min_samples || b.latency_ewma_us <= 0) continue;
        const int64_t ewma = b.latency_ewma_us;
        // All three guards must agree: relative ratio (grey = many
        // multiples of the median), scale-relative k*MAD (a noisy but
        // uniform mesh widens its own MAD), absolute delta (us-scale
        // jitter can't eject).
        if (ewma * 100 >= median * ratio_pct &&
            ewma > median + k * mad && ewma - median >= min_delta) {
            b.ratio_x100 = ewma * 100 / median;
            EjectLocked(kv.first, &b, Reason::kLatencyOutlier, now_us);
        }
    }
}

void OutlierTracker::Feed(SocketId id, int64_t latency_us,
                          int error_code) {
    if (!FLAGS_outlier_detection_enabled.get()) return;
    const int64_t now_us = monotonic_time_us();
    std::lock_guard<std::mutex> g(mu_);
    auto it = backends_.find(id);
    if (it == backends_.end()) return;
    Backend& b = it->second;
    if (latency_us > 0) {
        b.latency_ewma_us = b.latency_ewma_us == 0
                                ? latency_us
                                : (b.latency_ewma_us * 7 + latency_us) / 8;
        b.samples += 1;
    }
    // TERR_OVERLOAD is the server deliberately pushing back — admission
    // doing its job, not a grey failure; it must not feed the eject
    // trigger (shedding under load would then amputate healthy nodes).
    const bool hard_error = error_code != 0 && error_code != TERR_OVERLOAD;
    switch (b.state) {
        case State::kProbing: {
            // Any feedback for a PROBING backend is a probe result:
            // normal picks skip it, only the diverted probes reach it.
            const int64_t median = live_median_us_;
            const int64_t pass_ceiling =
                median > 0
                    ? std::max(median *
                                   FLAGS_outlier_latency_ratio_pct.get() /
                                   100,
                               median + FLAGS_outlier_min_delta_us.get())
                    : 0;
            const bool pass =
                !hard_error &&
                (pass_ceiling <= 0 || latency_us <= pass_ceiling);
            if (pass) {
                *g_probe_passes << 1;
                b.probe_passes += 1;
                if (b.probe_passes >= FLAGS_outlier_probe_passes.get()) {
                    b.state = State::kRamping;
                    b.ramp_start_us = now_us;
                    // The healed node is judged on FRESH evidence: the
                    // grey-era EWMA (alpha 1/8 folds out over ~25
                    // samples) would otherwise survive into the sweep
                    // and re-eject a healthy backend onto a doubled
                    // relapse window the moment it re-earns min_samples.
                    b.samples = 0;
                    b.latency_ewma_us = 0;
                    b.consecutive_errors = 0;
                    b.note = "ramping after reinstatement";
                    *g_reinstatements << 1;
                    flight::Record(flight::kOutlierReinstate, PackEp(b.ep),
                                   (uint64_t)b.probe_passes);
                    LOG(INFO) << "outlier[" << name_
                              << "]: " << endpoint2str(b.ep)
                              << " reinstated after " << b.probe_passes
                              << " probe passes; ramping";
                }
            } else {
                *g_probe_fails << 1;
                b.probe_passes = 0;
                // Relapse: back to EJECTED with a doubled window.
                b.eject_count += 1;
                b.state = State::kEjected;
                b.ejected_until_us =
                    now_us + EjectionWindowUs(b.eject_count);
            }
            break;
        }
        case State::kHealthy:
        case State::kRamping:
            if (hard_error) {
                b.consecutive_errors += 1;
                if (b.consecutive_errors >=
                    FLAGS_outlier_consecutive_errors.get()) {
                    EjectLocked(id, &b, Reason::kConsecutiveErrors,
                                now_us);
                    break;
                }
            } else if (error_code == 0) {
                b.consecutive_errors = 0;
            }
            MaybeSweepLocked(now_us);
            break;
        case State::kEjected:
            // In-flight stragglers from before the ejection: keep the
            // EWMA current (a recovered backend probes faster) but run
            // no detectors.
            break;
    }
}

OutlierTracker::Verdict OutlierTracker::OnPick(SocketId id,
                                               std::string* note) {
    if (all_healthy() || !FLAGS_outlier_detection_enabled.get()) {
        return Verdict::kAllow;
    }
    const int64_t now_us = monotonic_time_us();
    std::lock_guard<std::mutex> g(mu_);
    auto it = backends_.find(id);
    if (it == backends_.end()) return Verdict::kAllow;
    Backend& b = it->second;
    switch (b.state) {
        case State::kHealthy:
            return Verdict::kAllow;
        case State::kEjected:
        case State::kProbing:
            if (note != nullptr) *note = b.note;
            return Verdict::kSkip;
        case State::kRamping: {
            // Slow start: admission probability grows linearly over the
            // ramp window (floored at 10% so re-entry actually starts),
            // then the backend graduates to HEALTHY.
            const int64_t window_us =
                std::max<int64_t>(1, (int64_t)FLAGS_outlier_rampup_ms.get()
                                         * 1000);
            const int64_t elapsed = now_us - b.ramp_start_us;
            if (elapsed >= window_us) {
                b.state = State::kHealthy;
                b.reason = Reason::kNone;
                b.note.clear();
                b.samples = 0;
                nonhealthy_.fetch_sub(1, std::memory_order_relaxed);
                return Verdict::kAllow;
            }
            const uint64_t draw = splitmix64(ramp_seq_++) % 1000;
            const uint64_t admit =
                std::max<int64_t>(100, elapsed * 1000 / window_us);
            if (draw < admit) return Verdict::kAllow;
            if (note != nullptr) *note = b.note;
            return Verdict::kSkip;
        }
    }
    return Verdict::kAllow;
}

SocketId OutlierTracker::ProbeCandidate(int64_t now_us) {
    if (all_healthy() || !FLAGS_outlier_detection_enabled.get()) {
        return INVALID_VREF_ID;
    }
    const int64_t probe_interval_us =
        (int64_t)FLAGS_outlier_probe_interval_ms.get() * 1000;
    std::lock_guard<std::mutex> g(mu_);
    for (auto& kv : backends_) {
        Backend& b = kv.second;
        if (b.state == State::kEjected &&
            now_us >= b.ejected_until_us) {
            b.state = State::kProbing;
            b.probe_passes = 0;
            b.last_probe_us = 0;
        }
        if (b.state == State::kProbing &&
            now_us - b.last_probe_us >= probe_interval_us) {
            b.last_probe_us = now_us;
            return kv.first;
        }
    }
    return INVALID_VREF_ID;
}

void OutlierTracker::OnRevive(SocketId id) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = backends_.find(id);
    if (it == backends_.end()) return;
    Backend& b = it->second;
    // The revive bugfix (ISSUE 20 satellite): a health-check revive used
    // to clear the socket's DRAINING mark and hand the backend straight
    // back to the pick set at full weight. A backend this tracker holds
    // non-healthy re-enters through the probe ramp instead — revive
    // proves the TRANSPORT works, the probes prove the SERVICE does.
    if (b.state == State::kEjected || b.state == State::kRamping) {
        b.state = State::kProbing;
        b.probe_passes = 0;
        b.last_probe_us = 0;
        b.ejected_until_us = 0;
    }
}

bool OutlierTracker::IsEjected(SocketId id) const {
    std::lock_guard<std::mutex> g(mu_);
    auto it = backends_.find(id);
    return it != backends_.end() &&
           (it->second.state == State::kEjected ||
            it->second.state == State::kProbing);
}

State OutlierTracker::StateOf(SocketId id) const {
    std::lock_guard<std::mutex> g(mu_);
    auto it = backends_.find(id);
    return it == backends_.end() ? State::kHealthy : it->second.state;
}

void OutlierTracker::FillSnapshotLocked(SocketId id, const Backend& b,
                                        int64_t now_us,
                                        BackendSnapshot* out) const {
    out->id = id;
    out->ep = b.ep;
    out->state = b.state;
    out->reason = b.reason;
    out->latency_ewma_us = b.latency_ewma_us;
    out->consecutive_errors = b.consecutive_errors;
    out->eject_count = b.eject_count;
    out->ejected_for_ms =
        b.state == State::kEjected && b.ejected_until_us > now_us
            ? (b.ejected_until_us - now_us) / 1000
            : 0;
    out->probe_passes = b.probe_passes;
    out->ratio_x100 = b.ratio_x100;
}

bool OutlierTracker::Snapshot(SocketId id, BackendSnapshot* out) const {
    const int64_t now_us = monotonic_time_us();
    std::lock_guard<std::mutex> g(mu_);
    auto it = backends_.find(id);
    if (it == backends_.end()) return false;
    FillSnapshotLocked(id, it->second, now_us, out);
    return true;
}

size_t OutlierTracker::size() const {
    std::lock_guard<std::mutex> g(mu_);
    return backends_.size();
}

size_t OutlierTracker::ejected_now() const {
    std::lock_guard<std::mutex> g(mu_);
    size_t n = 0;
    for (const auto& kv : backends_) {
        if (kv.second.state == State::kEjected ||
            kv.second.state == State::kProbing) {
            ++n;
        }
    }
    return n;
}

void OutlierTracker::set_min_unejected(int n) {
    std::lock_guard<std::mutex> g(mu_);
    min_unejected_ = std::max(1, n);
}

void OutlierTracker::Describe(std::string* out) const {
    const int64_t now_us = monotonic_time_us();
    std::lock_guard<std::mutex> g(mu_);
    char line[256];
    snprintf(line, sizeof(line),
             "tracker %s: %zu backends, median_us=%lld\n", name_.c_str(),
             backends_.size(), (long long)live_median_us_);
    out->append(line);
    for (const auto& kv : backends_) {
        BackendSnapshot s;
        FillSnapshotLocked(kv.first, kv.second, now_us, &s);
        snprintf(line, sizeof(line),
                 "  %-21s %-8s ewma_us=%-8lld consec_err=%-3d "
                 "ejects=%-3d window_ms_left=%-6lld probe_passes=%d "
                 "reason=%s ratio_x100=%lld\n",
                 endpoint2str(s.ep).c_str(), StateName(s.state),
                 (long long)s.latency_ewma_us, s.consecutive_errors,
                 s.eject_count, (long long)s.ejected_for_ms,
                 s.probe_passes, ReasonName(s.reason),
                 (long long)s.ratio_x100);
        out->append(line);
    }
}

void OutlierTracker::DescribeJson(std::string* out) const {
    const int64_t now_us = monotonic_time_us();
    std::lock_guard<std::mutex> g(mu_);
    char buf[320];
    snprintf(buf, sizeof(buf),
             "{\"name\": \"%s\", \"backends\": [", name_.c_str());
    out->append(buf);
    bool first = true;
    for (const auto& kv : backends_) {
        BackendSnapshot s;
        FillSnapshotLocked(kv.first, kv.second, now_us, &s);
        snprintf(buf, sizeof(buf),
                 "%s{\"endpoint\": \"%s\", \"state\": \"%s\", "
                 "\"reason\": \"%s\", \"latency_ewma_us\": %lld, "
                 "\"consecutive_errors\": %d, \"eject_count\": %d, "
                 "\"window_ms_left\": %lld, \"probe_passes\": %d, "
                 "\"ratio_x100\": %lld}",
                 first ? "" : ", ", endpoint2str(s.ep).c_str(),
                 StateName(s.state), ReasonName(s.reason),
                 (long long)s.latency_ewma_us, s.consecutive_errors,
                 s.eject_count, (long long)s.ejected_for_ms,
                 s.probe_passes, (long long)s.ratio_x100);
        out->append(buf);
        first = false;
    }
    snprintf(buf, sizeof(buf), "], \"median_us\": %lld}",
             (long long)live_median_us_);
    out->append(buf);
}

// ---- the wrapper ----

OutlierLoadBalancer::OutlierLoadBalancer(LoadBalancer* inner)
    : inner_(inner), tracker_(inner->name()) {}

OutlierLoadBalancer::~OutlierLoadBalancer() = default;

bool OutlierLoadBalancer::AddServer(const ServerNode& server) {
    const bool added = inner_->AddServer(server);
    if (added) tracker_.AddServer(server);
    return added;
}

bool OutlierLoadBalancer::RemoveServer(SocketId id) {
    const bool removed = inner_->RemoveServer(id);
    if (removed) tracker_.RemoveServer(id);
    return removed;
}

int OutlierLoadBalancer::SelectServer(const SelectIn& in, SelectOut* out) {
    // Fast path: nothing ejected anywhere — one relaxed load, then the
    // wrapped stack runs exactly as before this tier existed.
    if (tracker_.all_healthy()) return inner_->SelectServer(in, out);

    // Reinstatement probes: divert ONE real rpc per interval to an
    // ejected backend whose window expired. Real traffic is the probe —
    // no synthetic load, and the probe result arrives through the same
    // passive Feedback funnel as every other call.
    const int64_t now_us = monotonic_time_us();
    const SocketId probe_id = tracker_.ProbeCandidate(now_us);
    if (probe_id != INVALID_VREF_ID &&
        (in.excluded == nullptr || !in.excluded->IsExcluded(probe_id))) {
        Socket* s = Socket::Address(probe_id);
        if (s != nullptr) {
            out->ptr = SocketUniquePtr(s);
            out->outlier_probe = true;
            return 0;
        }
    }

    // Normal pick with ejection skips: re-select with the ejected id
    // added to the exclusion set. Bounded by the ExcludedServers
    // capacity; if every candidate is ejected the LAST pick stands —
    // a degraded backend still beats failing the call (ejection must
    // never be able to fail what a breaker would have served).
    ExcludedServers ex;
    if (in.excluded != nullptr) ex = *in.excluded;
    SelectIn sub = in;
    sub.excluded = &ex;
    std::string note;
    bool skipped_ejected = false;
    std::string first_note;
    for (int attempt = 0; attempt < 8; ++attempt) {
        SelectOut candidate;
        const int rc = inner_->SelectServer(sub, &candidate);
        if (rc != 0) {
            if (skipped_ejected) break;  // fall through to last resort
            return rc;
        }
        const SocketId id = candidate.ptr->id();
        note.clear();
        if (tracker_.OnPick(id, &note) ==
            OutlierTracker::Verdict::kAllow) {
            *out = std::move(candidate);
            out->skipped_ejected = skipped_ejected;
            out->outlier_note = first_note;
            return 0;
        }
        inner_->DiscardPick(id);
        skipped_ejected = true;
        if (first_note.empty()) first_note = note;
        ex.Add(id);
    }
    // Last resort: everything pickable is ejected/ramp-rejected. Serve
    // through the wrapped stack ignoring ejection state.
    const int rc = inner_->SelectServer(in, out);
    if (rc == 0) {
        out->skipped_ejected = false;
        out->outlier_note.clear();
    }
    return rc;
}

void OutlierLoadBalancer::Feedback(const CallInfo& info) {
    // A PROBING backend's feedback is a diverted probe the wrapped
    // policies never selected (la's inflight count would underflow):
    // settle it in the tracker only.
    const bool diverted_probe =
        tracker_.StateOf(info.server_id) == State::kProbing;
    tracker_.Feed(info.server_id, info.latency_us, info.error_code);
    if (!diverted_probe) inner_->Feedback(info);
}

void OutlierLoadBalancer::DiscardPick(SocketId id) {
    inner_->DiscardPick(id);
}

void OutlierLoadBalancer::Describe(std::string* out) const {
    inner_->Describe(out);
    out->append("\n");
    tracker_.Describe(out);
}

const char* OutlierLoadBalancer::name() const { return inner_->name(); }

// ---- process-wide exposure ----

namespace {

int64_t PassiveEjectedNow(void*) { return ejected_now_total(); }

}  // namespace

void ExposeVars() {
    static std::atomic<bool> done{false};
    bool expected = false;
    if (!done.compare_exchange_strong(expected, true)) return;
    *g_ejections << 0;
    *g_reinstatements << 0;
    *g_probe_passes << 0;
    *g_probe_fails << 0;
    *g_eject_vetoes << 0;
    static PassiveStatus<int64_t> ejected(PassiveEjectedNow, nullptr);
    ejected.expose("rpc_outlier_ejected_now");
    // Health-check revives re-enter through the probe ramp, not at
    // full weight (the DRAINING-clear bug this PR fixes).
    Socket::set_revive_observer(ReviveObserver);
}

std::string DescribeAll() {
    std::string out;
    std::lock_guard<std::mutex> g(g_trackers_mu);
    if (g_trackers.empty()) {
        out = "no outlier trackers (no LB channels in this process)\n";
        return out;
    }
    for (OutlierTracker* t : g_trackers) t->Describe(&out);
    return out;
}

std::string DescribeAllJson() {
    std::string out = "{\"trackers\": [";
    {
        std::lock_guard<std::mutex> g(g_trackers_mu);
        for (size_t i = 0; i < g_trackers.size(); ++i) {
            if (i > 0) out.append(", ");
            g_trackers[i]->DescribeJson(&out);
        }
    }
    char tail[256];
    snprintf(tail, sizeof(tail),
             "], \"ejections\": %lld, \"reinstatements\": %lld, "
             "\"ejected_now\": %lld, \"probe_passes\": %lld, "
             "\"probe_fails\": %lld, \"eject_vetoes\": %lld}",
             (long long)ejections(), (long long)reinstatements(),
             (long long)ejected_now_total(), (long long)probe_passes(),
             (long long)probe_fails(),
             (long long)(*g_eject_vetoes).get_value());
    out.append(tail);
    return out;
}

int64_t ejections() { return (*g_ejections).get_value(); }
int64_t reinstatements() { return (*g_reinstatements).get_value(); }
int64_t probe_passes() { return (*g_probe_passes).get_value(); }
int64_t probe_fails() { return (*g_probe_fails).get_value(); }

int64_t ejected_now_total() {
    std::lock_guard<std::mutex> g(g_trackers_mu);
    int64_t n = 0;
    for (OutlierTracker* t : g_trackers) n += (int64_t)t->ejected_now();
    return n;
}

}  // namespace outlier
}  // namespace tpurpc
