// NamingService: resolves a service name ("list://h1:p1,h2:p2",
// "file://path", "dns://host:port") into a live server list, pushing
// updates to actions.
//
// Modeled on reference src/brpc/naming_service.h:36-61 (RunNamingService +
// NamingServiceActions::ResetServers), the periodic base
// (src/brpc/periodic_naming_service.*) and the impl set registered in
// src/brpc/global.cpp:370-381 (list/file/domain/...). The shared
// per-URL polling fiber + watcher fan-out lives in lb_with_naming.h
// (reference src/brpc/details/naming_service_thread.h:59).
#pragma once

#include <atomic>
#include <string>
#include <vector>

#include "tbase/endpoint.h"

namespace tpurpc {

// One resolved server: endpoint + optional tag ("w=N" weight for wrr/la,
// partition tags like "0/3" for PartitionChannel).
struct NSNode {
    EndPoint ep;
    std::string tag;

    bool operator==(const NSNode& o) const {
        return ep == o.ep && tag == o.tag;
    }
    bool operator<(const NSNode& o) const {
        if (ep < o.ep) return true;
        if (o.ep < ep) return false;
        return tag < o.tag;
    }
};

class NamingServiceActions {
public:
    virtual ~NamingServiceActions() = default;
    // Replace the whole list (the naming thread diffs old vs new).
    virtual void ResetServers(const std::vector<NSNode>& servers) = 0;
};

class NamingService {
public:
    virtual ~NamingService() = default;

    // Resolve `service_name` (the part after "scheme://") and push lists
    // into `actions` until Destroy() or process exit. One-shot services
    // (list/file without watching) may return after one push. Runs on a
    // dedicated fiber. Returns 0 on a clean stop.
    virtual int RunNamingService(const char* service_name,
                                 NamingServiceActions* actions) = 0;

    // Ask a running RunNamingService to stop soon.
    virtual void Destroy() {}

    virtual const char* scheme() const = 0;

    // New instance by scheme ("list", "file", "dns"); nullptr if unknown.
    static NamingService* New(const std::string& scheme);
};

// Base for poll-style services: calls GetServers every
// FLAGS_ns_refresh_interval_ms and pushes the result.
class PeriodicNamingService : public NamingService {
public:
    int RunNamingService(const char* service_name,
                         NamingServiceActions* actions) override;
    void Destroy() override;

protected:
    virtual int GetServers(const char* service_name,
                           std::vector<NSNode>* out) = 0;

private:
    std::atomic<bool> stop_{false};
};

// Parse "host:port w=2" / "ip:port tag" entries (shared by list/file).
// A tag is a space-separated token list; known tokens: "w=N" (weight),
// "zone=NAME" (locality zone / pod identity, ISSUE 14).
int ParseNamingLine(const std::string& line, NSNode* out);
// Weight from a node tag ("w=N" token anywhere in it); 1 when
// absent/invalid.
int WeightFromTag(const std::string& tag);
// Zone/pod tag ("zone=NAME" token); "" when absent. Entries whose zone
// differs from this process's -rpc_zone are cross-pod: their client
// sockets are created on the dcn transport tier and every LB policy
// prefers same-zone replicas over them (load_balancer.h).
std::string ZoneFromTag(const std::string& tag);

}  // namespace tpurpc
