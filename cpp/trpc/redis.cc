#include "trpc/redis.h"

#include <algorithm>
#include <cctype>
#include <cstring>
#include <mutex>

#include "tbase/errno.h"
#include "tbase/logging.h"
#include "tbase/time.h"
#include "tfiber/call_id.h"
#include "tfiber/fiber_sync.h"
#include "tfiber/timer_thread.h"
#include "tnet/input_messenger.h"
#include "tnet/protocol.h"
#include "tnet/socket.h"
#include "trpc/auth.h"
#include "trpc/channel.h"
#include "trpc/controller.h"
#include "trpc/server.h"

namespace tpurpc {

namespace {

// Hardening caps on untrusted RESP input.
constexpr size_t kMaxArgs = 1024;
constexpr size_t kMaxBulk = 64u << 20;
constexpr size_t kMaxArrayElems = 64u << 10;
constexpr int kMaxReplyDepth = 8;

int g_redis_server_index = -1;
int g_redis_client_index = -1;

// ---- flat-buffer RESP scanner ----
// Parsing works on a flattened copy of the buffered bytes; RESP values
// are small in practice and the copy is bounded by what the peer has
// actually sent (the caps above bound memory).

struct Scan {
    const char* p;
    size_t n;
    size_t off = 0;
    // When a scan returns need-more, the minimum ABSOLUTE byte count that
    // could complete it (0 = unknown, "more than n"). Lets the driver
    // avoid re-flattening a large buffer on every partial arrival of a
    // big bulk value.
    size_t need = 0;

    bool line(std::string* out) {  // reads to CRLF, excluding it
        const char* crlf = (const char*)memmem(p + off, n - off, "\r\n", 2);
        if (crlf == nullptr) {
            need = n + 1;
            return false;
        }
        out->assign(p + off, (size_t)(crlf - (p + off)));
        off = (size_t)(crlf - p) + 2;
        return true;
    }
    bool bytes(size_t len, std::string* out) {
        if (n - off < len + 2) {
            need = off + len + 2;
            return false;
        }
        out->assign(p + off, len);
        if (p[off + len] != '\r' || p[off + len + 1] != '\n') return false;
        off += len + 2;
        return true;
    }
};

bool parse_int(const std::string& s, int64_t* out) {
    if (s.empty()) return false;
    errno = 0;
    char* end = nullptr;
    const long long v = strtoll(s.c_str(), &end, 10);
    if (errno != 0 || end != s.c_str() + s.size()) return false;
    *out = v;
    return true;
}

// 1 = parsed, 0 = need more, -1 = corrupt.
int scan_reply(Scan* sc, RedisReply* out, int depth) {
    if (depth > kMaxReplyDepth) return -1;
    if (sc->off >= sc->n) return 0;
    const char tag = sc->p[sc->off];
    std::string l;
    const size_t start = sc->off;
    ++sc->off;
    if (!sc->line(&l)) {
        sc->off = start;
        return 0;
    }
    switch (tag) {
        case '+':
            out->type = RedisReply::STATUS;
            out->str = std::move(l);
            return 1;
        case '-':
            out->type = RedisReply::ERROR;
            out->str = std::move(l);
            return 1;
        case ':': {
            int64_t v;
            if (!parse_int(l, &v)) return -1;
            out->type = RedisReply::INTEGER;
            out->integer = v;
            return 1;
        }
        case '$': {
            int64_t len;
            if (!parse_int(l, &len)) return -1;
            if (len == -1) {
                out->type = RedisReply::NIL;
                return 1;
            }
            if (len < 0 || (size_t)len > kMaxBulk) return -1;
            if (!sc->bytes((size_t)len, &out->str)) {
                // Distinguish need-more from the missing-CRLF corruption:
                // if the buffer HAS the bytes but no CRLF terminator, the
                // bytes() false with enough data means corrupt.
                if (sc->n - sc->off >= (size_t)len + 2) return -1;
                sc->off = start;
                return 0;
            }
            out->type = RedisReply::STRING;
            return 1;
        }
        case '*': {
            int64_t cnt;
            if (!parse_int(l, &cnt)) return -1;
            if (cnt == -1) {
                out->type = RedisReply::NIL;
                return 1;
            }
            if (cnt < 0 || (size_t)cnt > kMaxArrayElems) return -1;
            out->type = RedisReply::ARRAY;
            out->elements.resize((size_t)cnt);
            for (int64_t i = 0; i < cnt; ++i) {
                const int rc =
                    scan_reply(sc, &out->elements[(size_t)i], depth + 1);
                if (rc != 1) {
                    if (rc == 0) sc->off = start;
                    out->elements.clear();
                    return rc;
                }
            }
            return 1;
        }
        default:
            return -1;
    }
}

// 1 = parsed, 0 = need more, -1 = corrupt / not RESP.
int scan_command(Scan* sc, std::vector<std::string>* args) {
    if (sc->off >= sc->n) return 0;
    if (sc->p[sc->off] != '*') return -1;  // inline commands unsupported
    const size_t start = sc->off;
    ++sc->off;
    std::string l;
    if (!sc->line(&l)) {
        sc->off = start;
        return 0;
    }
    int64_t cnt;
    if (!parse_int(l, &cnt) || cnt < 1 || (size_t)cnt > kMaxArgs) return -1;
    args->clear();
    args->reserve((size_t)cnt);
    for (int64_t i = 0; i < cnt; ++i) {
        if (sc->off >= sc->n) {
            sc->off = start;
            return 0;
        }
        if (sc->p[sc->off] != '$') return -1;
        ++sc->off;
        if (!sc->line(&l)) {
            sc->off = start;
            return 0;
        }
        int64_t len;
        if (!parse_int(l, &len) || len < 0 || (size_t)len > kMaxBulk) {
            return -1;
        }
        std::string arg;
        if (!sc->bytes((size_t)len, &arg)) {
            if (sc->n - sc->off >= (size_t)len + 2) return -1;
            sc->off = start;
            return 0;
        }
        args->push_back(std::move(arg));
    }
    return 1;
}

}  // namespace

// ---------------- public codec ----------------

void RedisSerializeCommand(const std::vector<std::string>& args,
                           IOBuf* out) {
    std::string s;
    s += "*" + std::to_string(args.size()) + "\r\n";
    for (const auto& a : args) {
        s += "$" + std::to_string(a.size()) + "\r\n";
        s += a;
        s += "\r\n";
    }
    out->append(s);
}

namespace {

// Windowed scan driver: flatten a 64KB prefix first; only when the value
// provably continues past the window AND the buffer could complete it is
// the full buffer flattened (once). Kills the quadratic re-copy a large
// bulk would otherwise cost as it arrives chunk by chunk: while
// incomplete, the `need` hint turns every retry into a cheap 64KB copy +
// size compare.
template <typename ScanFn>
int WindowedScan(IOBuf* source, ScanFn&& fn, size_t* consumed) {
    constexpr size_t kWindow = 64u << 10;
    const size_t total = source->size();
    const size_t limit = std::min(total, kWindow);
    std::string flat;
    flat.resize(limit);
    source->copy_to(&flat[0], limit);
    Scan sc{flat.data(), limit};
    int rc = fn(&sc);
    if (rc == 0 && limit < total) {
        if (sc.need > limit + 1 && sc.need > total) {
            return 0;  // a bulk that hasn't fully arrived: cheap retry
        }
        flat.resize(total);
        source->copy_to(&flat[0], total);
        Scan full{flat.data(), total};
        rc = fn(&full);
        sc = full;
    }
    if (rc == 1) *consumed = sc.off;
    return rc;
}

}  // namespace

int RedisParseReply(IOBuf* source, RedisReply* out) {
    size_t consumed = 0;
    const int rc = WindowedScan(
        source, [&](Scan* sc) { return scan_reply(sc, out, 0); },
        &consumed);
    if (rc == 1) source->pop_front(consumed);
    return rc;
}

void RedisSerializeReply(const RedisReply& r, std::string* out) {
    switch (r.type) {
        case RedisReply::NIL:
            *out += "$-1\r\n";
            return;
        case RedisReply::STATUS:
            *out += "+" + r.str + "\r\n";
            return;
        case RedisReply::ERROR:
            *out += "-" + r.str + "\r\n";
            return;
        case RedisReply::INTEGER:
            *out += ":" + std::to_string(r.integer) + "\r\n";
            return;
        case RedisReply::STRING:
            *out += "$" + std::to_string(r.str.size()) + "\r\n";
            *out += r.str;
            *out += "\r\n";
            return;
        case RedisReply::ARRAY:
            *out += "*" + std::to_string(r.elements.size()) + "\r\n";
            for (const auto& e : r.elements) RedisSerializeReply(e, out);
            return;
    }
}

// ---------------- request/service ----------------

void RedisRequest::AddCommand(const std::vector<std::string>& args) {
    RedisSerializeCommand(args, &wire_);
    ++ncommands_;
}

struct RedisService::KvState {
    FiberMutex mu;
    std::map<std::string, std::string> map;
};

namespace {

// PING/ECHO/GET/SET/DEL over a shared map (the starter command set).
class BasicKvHandler : public RedisCommandHandler {
public:
    enum Op { PING, ECHO, GET, SET, DEL };
    BasicKvHandler(Op op, RedisService::KvState* kv) : op_(op), kv_(kv) {}

    void Run(const std::vector<std::string>& args,
             RedisReply* out) override {
        switch (op_) {
            case PING:
                out->type = RedisReply::STATUS;
                out->str = "PONG";
                return;
            case ECHO:
                if (args.size() != 2) break;
                out->type = RedisReply::STRING;
                out->str = args[1];
                return;
            case SET:
                if (args.size() != 3) break;
                {
                    kv_->mu.lock();
                    kv_->map[args[1]] = args[2];
                    kv_->mu.unlock();
                }
                out->type = RedisReply::STATUS;
                out->str = "OK";
                return;
            case GET: {
                if (args.size() != 2) break;
                kv_->mu.lock();
                auto it = kv_->map.find(args[1]);
                const bool found = it != kv_->map.end();
                if (found) out->str = it->second;
                kv_->mu.unlock();
                out->type = found ? RedisReply::STRING : RedisReply::NIL;
                return;
            }
            case DEL: {
                if (args.size() != 2) break;
                kv_->mu.lock();
                const size_t n = kv_->map.erase(args[1]);
                kv_->mu.unlock();
                out->type = RedisReply::INTEGER;
                out->integer = (int64_t)n;
                return;
            }
        }
        out->type = RedisReply::ERROR;
        out->str = "ERR wrong number of arguments";
    }

private:
    Op op_;
    RedisService::KvState* kv_;
};

}  // namespace

RedisService::RedisService() = default;
RedisService::~RedisService() = default;

void RedisService::AddBasicKvCommands() {
    if (kv_ == nullptr) kv_.reset(new KvState);
    AddCommandHandler("PING", new BasicKvHandler(BasicKvHandler::PING,
                                                 kv_.get()));
    AddCommandHandler("ECHO", new BasicKvHandler(BasicKvHandler::ECHO,
                                                 kv_.get()));
    AddCommandHandler("GET",
                      new BasicKvHandler(BasicKvHandler::GET, kv_.get()));
    AddCommandHandler("SET",
                      new BasicKvHandler(BasicKvHandler::SET, kv_.get()));
    AddCommandHandler("DEL",
                      new BasicKvHandler(BasicKvHandler::DEL, kv_.get()));
}

void RedisService::AddCommandHandler(const std::string& name,
                                     RedisCommandHandler* handler) {
    std::string key = name;
    for (char& c : key) c = (char)toupper((unsigned char)c);
    handlers_[key].reset(handler);
}

RedisCommandHandler* RedisService::FindCommandHandler(
    const std::string& name) const {
    std::string key = name;
    for (char& c : key) c = (char)toupper((unsigned char)c);
    auto it = handlers_.find(key);
    return it == handlers_.end() ? nullptr : it->second.get();
}

// ---------------- server protocol ----------------

namespace {

class RedisCommandMsg : public InputMessageBase {
public:
    std::vector<std::string> args;
};

ParseResult ParseRedisCommand(IOBuf* source, Socket* socket, bool read_eof,
                              const void* arg) {
    char head;
    if (source->copy_to(&head, 1) == 1 && head != '*') {
        return ParseResult::make(ParseError::TRY_OTHERS);
    }
    auto msg = std::make_unique<RedisCommandMsg>();
    size_t consumed = 0;
    const int rc = WindowedScan(
        source, [&](Scan* sc) { return scan_command(sc, &msg->args); },
        &consumed);
    if (rc == 0) return ParseResult::make(ParseError::NOT_ENOUGH_DATA);
    if (rc < 0) return ParseResult::make(ParseError::ERROR);
    source->pop_front(consumed);
    return ParseResult::make_ok(msg.release());
}

// In-order inline processing: pipelined replies leave in command order.
void ProcessRedisCommand(InputMessageBase* raw) {
    std::unique_ptr<RedisCommandMsg> msg((RedisCommandMsg*)raw);
    SocketUniquePtr s = SocketUniquePtr::FromId(msg->socket_id);
    if (!s) return;
    auto* messenger = (InputMessenger*)s->user();
    Server* server =
        messenger != nullptr ? (Server*)messenger->context : nullptr;
    RedisService* service =
        server != nullptr ? server->redis_service() : nullptr;
    RedisReply reply;
    // ServerOptions::auth covers RESP too (the server's auth promise
    // must not have a side door): unauthenticated connections may only
    // run the standard `AUTH <credential>` command; everything else gets
    // -NOAUTH (the real redis convention).
    if (server != nullptr && server->options().auth != nullptr &&
        !s->authenticated() && service != nullptr && !msg->args.empty()) {
        std::string cmd = msg->args[0];
        for (char& c : cmd) c = (char)toupper((unsigned char)c);
        if (cmd == "AUTH" && msg->args.size() == 2) {
            AuthContext actx;
            if (server->options().auth->VerifyCredential(
                    msg->args[1], s->remote_side(), &actx) == 0) {
                s->SetAuthenticated(actx.user());
                reply.type = RedisReply::STATUS;
                reply.str = "OK";
            } else {
                reply.type = RedisReply::ERROR;
                reply.str = "ERR invalid credential";
            }
        } else {
            reply.type = RedisReply::ERROR;
            reply.str = "NOAUTH Authentication required";
        }
        std::string out;
        RedisSerializeReply(reply, &out);
        IOBuf buf;
        buf.append(out);
        s->Write(&buf);
        return;
    }
    if (service == nullptr) {
        reply.type = RedisReply::ERROR;
        reply.str = "ERR this server has no redis service";
    } else if (msg->args.empty()) {
        reply.type = RedisReply::ERROR;
        reply.str = "ERR empty command";
    } else {
        RedisCommandHandler* h = service->FindCommandHandler(msg->args[0]);
        if (h == nullptr) {
            reply.type = RedisReply::ERROR;
            reply.str = "ERR unknown command '" + msg->args[0] + "'";
        } else {
            h->Run(msg->args, &reply);
        }
    }
    std::string out;
    RedisSerializeReply(reply, &out);
    IOBuf buf;
    buf.append(out);
    // One Write per reply: the socket's wait-free queue coalesces — the
    // KeepWrite fiber gathers up to 64 queued replies into one writev —
    // so a pipelined burst still leaves in few syscalls.
    s->Write(&buf);
}

// ---------------- client protocol ----------------

struct RedisCallCtx {
    Controller* cntl;
    RedisResponse* response;
};

int RedisOnError(CallId id, void* data, int error) {
    auto* ctx = (RedisCallCtx*)data;
    ctx->cntl->SetFailed(error, "redis call failed: %s", terror(error));
    return id_unlock_and_destroy(id);
}

// Per-connection client state: the batch currently being assembled +
// a mutex ordering {PushPipelinedInfo, Write} pairs across callers.
struct RedisClientSession {
    std::mutex send_mu;
    bool cur_active = false;
    Socket::PipelinedInfo cur;
    std::vector<RedisReply> acc;
};

// Runs at socket recycle. The batch currently being ASSEMBLED was
// already popped out of the socket's pipelined queue, so the
// CloseFdAndDropQueued drain never sees it — its caller is failed here.
void DeleteRedisClientSession(void* p) {
    auto* sess = (RedisClientSession*)p;
    if (sess->cur_active && sess->cur.id_wait != 0) {
        id_error(sess->cur.id_wait, TERR_FAILED_SOCKET);
    }
    delete sess;
}

RedisClientSession* redis_session_of(Socket* s) {
    if (s->preferred_protocol_index != g_redis_client_index) return nullptr;
    return (RedisClientSession*)s->conn_data();
}

class RedisReplyMsg : public InputMessageBase {
public:
    RedisReply reply;
};

ParseResult ParseRedisReplyMsg(IOBuf* source, Socket* socket,
                               bool read_eof, const void* arg) {
    if (redis_session_of(socket) == nullptr) {
        return ParseResult::make(ParseError::TRY_OTHERS);
    }
    auto msg = std::make_unique<RedisReplyMsg>();
    const int rc = RedisParseReply(source, &msg->reply);
    if (rc == 0) return ParseResult::make(ParseError::NOT_ENOUGH_DATA);
    if (rc < 0) return ParseResult::make(ParseError::ERROR);
    return ParseResult::make_ok(msg.release());
}

void ProcessRedisReplyMsg(InputMessageBase* raw) {
    std::unique_ptr<RedisReplyMsg> msg((RedisReplyMsg*)raw);
    SocketUniquePtr s = SocketUniquePtr::FromId(msg->socket_id);
    if (!s) return;
    RedisClientSession* sess = redis_session_of(s.get());
    if (sess == nullptr) return;
    if (!sess->cur_active) {
        if (!s->PopPipelinedInfo(&sess->cur)) {
            // A reply nobody asked for: the correlation is gone; the
            // connection cannot be trusted further.
            s->SetFailedWithError(TERR_RESPONSE);
            return;
        }
        sess->cur_active = true;
        sess->acc.clear();
    }
    sess->acc.push_back(std::move(msg->reply));
    if (sess->acc.size() < sess->cur.count) return;
    // Batch complete: hand the replies to the caller.
    const CallId cid = sess->cur.id_wait;
    std::vector<RedisReply> replies;
    replies.swap(sess->acc);
    sess->cur_active = false;
    void* data = nullptr;
    if (id_lock(cid, &data) != 0) return;  // timed out meanwhile: drop
    auto* ctx = (RedisCallCtx*)data;
    ctx->response->mutable_replies()->swap(replies);
    id_unlock_and_destroy(cid);
}

void RedisTimeoutCb(void* arg) {
    id_error((CallId)(uintptr_t)arg, TERR_RPC_TIMEDOUT);
}

}  // namespace

void RedisCall(Channel* channel, Controller* cntl,
               const RedisRequest& request, RedisResponse* response) {
    response->Clear();
    if (request.command_count() == 0) {
        cntl->SetFailed(TERR_REQUEST, "empty redis request");
        return;
    }
    RedisCallCtx ctx{cntl, response};
    CallId cid;
    if (id_create(&cid, &ctx, RedisOnError) != 0) {
        cntl->SetFailed(TERR_INTERNAL, "id_create failed");
        return;
    }
    const int64_t timeout_ms = cntl->timeout_ms() >= 0
                                   ? cntl->timeout_ms()
                                   : channel->options().timeout_ms;
    TimerId tt = INVALID_TIMER_ID;
    if (timeout_ms > 0) {
        tt = TimerThread::singleton()->schedule(
            RedisTimeoutCb, (void*)(uintptr_t)cid,
            monotonic_time_us() + timeout_ms * 1000);
    }
    const SocketId sid = channel->AcquirePinnedSocket();
    SocketUniquePtr s;
    if (sid == INVALID_VREF_ID || Socket::AddressSocket(sid, &s) != 0) {
        id_error(cid, TERR_FAILED_SOCKET);
    } else {
        RedisClientSession* sess = redis_session_of(s.get());
        if (sess == nullptr) {
            static std::mutex install_mu;
            std::lock_guard<std::mutex> g(install_mu);
            sess = redis_session_of(s.get());
            if (sess == nullptr) {
                sess = new RedisClientSession;
                s->set_conn_data(sess, DeleteRedisClientSession);
                s->preferred_protocol_index = g_redis_client_index;
            }
        }
        IOBuf wire;
        wire.append(request.wire());
        int write_errno = 0;
        {
            // Info order MUST equal wire order across concurrent callers.
            std::lock_guard<std::mutex> g(sess->send_mu);
            s->PushPipelinedInfo(
                {(uint32_t)request.command_count(), cid});
            if (s->Write(&wire, cid) != 0) {
                // Write's early-return paths (failed socket,
                // EOVERCROWDED) notify NOBODY: un-push our entry so
                // later callers' correlation doesn't shift, and fail
                // the call ourselves.
                write_errno = errno != 0 ? errno : TERR_FAILED_SOCKET;
                s->RemovePipelinedInfo(cid);
            }
        }
        if (write_errno != 0) id_error(cid, write_errno);
        // Drop the socket ref BEFORE waiting: a dead connection only
        // error-notifies its pipelined waiters at recycle (nref==0) —
        // holding the ref across the wait would deadlock that path
        // (Controller::IssueRPC releases before waiting too).
        s.reset();
    }
    id_join(cid);
    if (tt != INVALID_TIMER_ID) {
        TimerThread::singleton()->unschedule(tt, false);
    }
}

void RegisterRedisProtocols() {
    if (g_redis_server_index >= 0) return;
    Protocol srv;
    srv.parse = ParseRedisCommand;
    srv.process = ProcessRedisCommand;
    srv.name = "redis-server";
    srv.process_in_order = true;  // pipelined replies leave in order
    g_redis_server_index = RegisterProtocol(srv);
    Protocol cli;
    cli.parse = ParseRedisReplyMsg;
    cli.process = ProcessRedisReplyMsg;
    cli.name = "redis-client";
    cli.process_in_order = true;  // batch assembly is per-connection state
    g_redis_client_index = RegisterProtocol(cli);
}

int RedisServerProtocolIndex() { return g_redis_server_index; }
int RedisClientProtocolIndex() { return g_redis_client_index; }

}  // namespace tpurpc
