// Channel: the client stub transport — a protobuf RpcChannel over the
// native tpu_std protocol.
//
// Modeled on reference src/brpc/channel.{h,cpp}: Init with "ip:port"
// (InitSingle channel.cpp:342) or a naming-service URL + load-balancer name
// (channel.cpp:260-430), CallMethod (:433) creating the correlation id,
// serializing, arming timers and delegating to Controller::IssueRPC.
#pragma once

#include <google/protobuf/service.h>

#include <memory>
#include <string>

#include "tbase/endpoint.h"
#include "tnet/input_messenger.h"
#include "trpc/retry_policy.h"

namespace tpurpc {

class LoadBalancerWithNaming;

// How RPCs map onto connections (reference ConnectionType,
// src/brpc/socket.cpp GetPooledSocket/GetShortSocket):
//  - SINGLE: one shared connection per remote; responses correlate by id.
//  - POOLED: one in-flight RPC per connection, pooled after its response —
//    large payloads never head-of-line-block each other (the reference's
//    2.3 GB/s headline configuration).
//  - SHORT: fresh connection per call, closed after the response.
enum ConnectionType {
    CONNECTION_TYPE_SINGLE = 0,
    CONNECTION_TYPE_POOLED = 1,
    CONNECTION_TYPE_SHORT = 2,
};

struct ChannelOptions {
    int64_t timeout_ms = 500;   // same default as the reference
    int max_retry = 3;
    int64_t backup_request_ms = -1;  // <0 disabled
    ConnectionType connection_type = CONNECTION_TYPE_SINGLE;
    // Wire protocol of this channel: "tpu_std" (native framed) or "grpc"
    // (gRPC unary over h2c — the client half of thttp/http2_client.h;
    // reference ChannelOptions::protocol, src/brpc/channel.h).
    std::string protocol = "tpu_std";
    // TLS to the server (tnet/tls.h; ALPN "h2" when protocol is "grpc").
    // The channel pins one TLS connection (single-connection semantics;
    // pooled/short don't apply). Init fails when libssl is unavailable.
    bool tls = false;
    std::string tls_sni;
    // Credential presenter (trpc/auth.h). Not owned; must outlive the
    // channel. tpu_std: first message of each connection (auth fight);
    // grpc: `authorization` header per request.
    const class Authenticator* auth = nullptr;
    // Retry/backup pluggability (trpc/retry_policy.h; not owned). Null =
    // the default policy (connection errors retry immediately) / the
    // fixed backup_request_ms above.
    const class RetryPolicy* retry_policy = nullptr;
    const class BackupRequestPolicy* backup_request_policy = nullptr;
    // Retry budget (retry_policy.h RetryBudget): burst tokens and the
    // per-success refill ratio consulted by every re-issue (retry AND
    // backup request). -1 = use the -rpc_retry_budget_tokens /
    // -rpc_retry_budget_ratio flag defaults; tokens 0 disables
    // throttling for this channel.
    int64_t retry_budget_tokens = -1;
    double retry_budget_ratio = -1.0;
    // Transport-tier name of this channel's connections (tnet/transport.h
    // registry): "" = default tcp; "dcn" marks a CROSS-POD channel whose
    // sockets are created on the dcn tier — descriptor-incapable (pinned
    // tries degrade to inline), attributed to
    // rpc_transport_*{transport="dcn"}, shaped by the -dcn_emu_* WAN
    // knobs, and never sharing a SocketMap/SocketPool connection (or its
    // health state) with a tcp channel to the same address. Single-server
    // init only; LB channels get their tiers per-member from naming zone
    // tags.
    std::string transport;
    // Give this channel its OWN connection instead of the process-wide
    // endpoint-keyed SocketMap socket (which every single-mode channel to
    // the same server shares). N channels with pin_connection then drive
    // N connections that shard across the epoll loops by fd — how a load
    // generator scales past one event loop (rpc_press --press_threads,
    // ISSUE 7). Single-server init only; ignored with an LB, and
    // pointless with POOLED/SHORT connection_type (those override the
    // pinned socket with a fly connection per call).
    bool pin_connection = false;
};

class Channel : public google::protobuf::RpcChannel {
public:
    Channel() = default;
    ~Channel() override;

    // Single-server init: "127.0.0.1:8002".
    int Init(const char* server_addr_and_port, const ChannelOptions* options);
    int Init(const EndPoint& server, const ChannelOptions* options);
    // Naming + load balancing: Init("list://h1:p1,h2:p2", "rr", &opts)
    // (naming URL schemes and LB names per SURVEY §2.6; wired in the
    // client-robustness milestone).
    int Init(const char* naming_url, const char* lb_name,
             const ChannelOptions* options);
    // Pin the channel to an existing socket (ICI transport endpoints are
    // created out-of-band by the link setup, not by the SocketMap —
    // reference Channel::Init(fd) single-socket mode is the analog).
    int InitWithSocketId(SocketId sid, const ChannelOptions* options);
    // Cross-process ICI: TCP handshake with `server`, then pin the channel
    // to the shared-memory queue pair (tici/shm_link.h). Requires
    // IciBlockPool::Init() with a shared region in this process.
    int InitIci(const EndPoint& server, const ChannelOptions* options);

    void CallMethod(const google::protobuf::MethodDescriptor* method,
                    google::protobuf::RpcController* controller,
                    const google::protobuf::Message* request,
                    google::protobuf::Message* response,
                    google::protobuf::Closure* done) override;

    const ChannelOptions& options() const { return options_; }
    const EndPoint& server() const { return server_ep_; }
    LoadBalancerWithNaming* lb() const { return lb_.get(); }

    // The process-wide client messenger for tpu_std responses.
    static InputMessenger* client_messenger();

    SocketId pinned_socket() const { return pinned_socket_; }
    // Pinned socket for the next call; when the channel CREATED its pin
    // (grpc/TLS channels) and the connection died (peer GOAWAY, network),
    // a fresh one replaces it here — the channel survives reconnects.
    SocketId AcquirePinnedSocket();

    // Per-channel re-issue throttle (configured at Init from
    // ChannelOptions / the rpc_retry_budget_* flags).
    RetryBudget& retry_budget() { return retry_budget_; }

    // Registry id resolved from ChannelOptions::transport at Init
    // (-1 = default tcp) — the tier half of the (endpoint, tier)
    // SocketMap/SocketPool key every connection of this channel uses.
    int transport_tier() const { return forced_tier_; }

private:
    int CreateOwnedPinnedSocket(SocketId* sid);
    void ConfigureRetryBudget();

    EndPoint server_ep_;
    ChannelOptions options_;
    std::shared_ptr<LoadBalancerWithNaming> lb_;
    SocketId pinned_socket_ = INVALID_VREF_ID;
    bool owns_pinned_ = false;  // created by Init (not InitWithSocketId)
    std::mutex pin_mu_;         // guards pinned_socket_ recreation
    RetryBudget retry_budget_;
    int forced_tier_ = -1;  // resolved ChannelOptions::transport
};

}  // namespace tpurpc
