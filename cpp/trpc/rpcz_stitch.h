// Cross-host rpcz trace stitching: fan out over the mesh's portals,
// collect every node's spans for one trace_id, and render a single
// parent-child timeline with per-hop queue/process/wire breakdown and
// clock-skew normalization.
//
// Peers come from two sources: the explicit -rpcz_peers flag
// ("ip:port,ip:port", the mesh membership), plus every remote this
// process holds a shared client connection to (SocketMap — those are
// serving ports, so their portals answer /rpcz). Each peer is queried
// with a plain HTTP/1.1 GET /rpcz?format=json&trace_id=N under ONE
// shared -rpcz_stitch_timeout_ms budget for the whole fan-out: however
// many peers are dead or partitioned, the page costs at most one
// timeout and renders whatever was collected.
//
// Clock-skew normalization: monotonic clocks are per-process, so a
// server span's raw timestamps are meaningless next to its parent
// client span's. The parent-child send/recv envelope fixes that: the
// server's [start..end] must nest inside the client's [sent..received];
// the wire residue ((received-sent) - (end-start)) splits evenly between
// the two directions, anchoring the child's clock to the parent's
// (children on the SAME host as their parent share its clock and just
// inherit the offset).
#pragma once

#include <cstdint>
#include <string>

namespace tpurpc {

// The /rpcz/trace/<id> page: collect (local + peers) and render. Blocks
// the calling fiber for at most -rpcz_stitch_timeout_ms total.
std::string RenderStitchedTrace(uint64_t trace_id);

}  // namespace tpurpc
