// Mesh collectives on the descriptor path (ISSUE 13): all-reduce,
// all-gather and all-to-all across the process mesh, scheduled as
// chunked descriptor pipelines per T3 (arXiv:2401.16677) and the MLPerf
// TPU-pod scaling study (arXiv:1909.09756).
//
// Shape of the subsystem:
//  - payloads are split into slab-class chunks
//    (IciBlockPool::AllocatePoolAttachmentCopy); every schedule step
//    posts its chunk as a one-sided request PoolDescriptor and — for
//    the pull-shaped exchanges — receives the peer's bytes as a
//    response descriptor, so zero payload bytes cross inline on
//    descriptor-capable links (the Transport seam degrades tcp peers
//    to inline transparently).
//  - all-reduce runs the classic chunked ring (reduce-scatter then
//    all-gather, 2(N-1) steps): in steady state the reduce-compute of
//    chunk i (in the receiving handler) overlaps the descriptor
//    transfer of chunk i+1. A serial root fan-in/fan-out baseline
//    (SerialAllReduce) is kept for the pipelined-vs-serial bench gate.
//  - all-gather and all-to-all are fan-outs and REUSE ParallelChannel
//    (combo_channels.h) — one sub-call per (peer, chunk), chunk bytes
//    riding the new SubCall attachment extension, replies applied
//    through the new SubCallObserver hook.
//  - a failed step retries through the existing funnel (the chunk RPCs
//    are plain Channel calls: retry budget, TERR_OVERLOAD backoff,
//    TERR_STALE_EPOCH, peer-death reclamation of pinned chunks all
//    already work); when a member dies the collective RE-FORMS over
//    the survivors (membership re-probed, ranks renumbered, the round
//    restarted from its kept input) instead of hanging.
//
// Concurrency contract: driver calls (AllReduce/...) block the calling
// fiber; the server-side HandleIncoming runs on handler fibers and may
// park briefly (bounded) waiting for the local round to catch up —
// answering retriable TERR_OVERLOAD (+suggested backoff) when it
// doesn't, so cross-node round skew resolves through the retry funnel
// rather than unbounded buffering.
#pragma once

#include <google/protobuf/service.h>

#include <atomic>
#include <cstdint>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "tbase/iobuf.h"
#include "tfiber/fiber_sync.h"

namespace tpurpc {

class Controller;
namespace verbs {
class CompletionQueue;
struct RemoteWindow;
}  // namespace verbs

// Wire metadata of one collective chunk RPC (mirrors
// benchpb.CollChunk; the engine is payload-proto-agnostic — the host
// tool's CollectiveCodec translates).
struct CollWire {
    uint64_t seq = 0;          // round number (per collective program)
    uint32_t kind = 0;         // CollKind
    uint32_t step = 0;         // ring step / serial phase
    uint32_t chunk = 0;        // chunk index within the step's shard
    uint32_t src_rank = 0;     // sender's rank in the round's membership
    uint32_t nranks = 0;
    uint64_t member_hash = 0;  // hash of the sorted member keys
    uint64_t total_bytes = 0;  // round payload size (per-kind meaning)
    uint64_t offset = 0;       // byte offset (per-kind: absolute / in-block)
    uint64_t len = 0;          // chunk byte length
    uint32_t scope = 0;        // CollScope (round-key namespace, ISSUE 14)
    // Verbs doorbell (ISSUE 18): when verb_nchunks > 0 (and chunk is
    // the kVerbDoorbellChunk sentinel) the step's whole shard was
    // already REMOTE_WRITTEN into the receiver's granted window
    // `verb_window` by one scatter-gather verb — this RPC carries no
    // payload and just asks for the apply. offset/len span the whole
    // shard; verb_crc covers the window bytes; verb_epoch is the
    // grant-time pool epoch (the staleness fence).
    uint64_t verb_window = 0;
    uint32_t verb_nchunks = 0;
    uint32_t verb_crc = 0;
    uint64_t verb_epoch = 0;
};

// CollWire.chunk value marking a verbs doorbell (never a real chunk
// ordinal: chunk indices are bounded far below 2^24 by slab sizing).
constexpr uint32_t kVerbDoorbellChunk = 0xFFFFFF;

// Membership scope of a round (ISSUE 14): hierarchical collectives run
// each phase over a FILTERED membership — the scope is part of the
// round key, so an intra-zone phase and a flat global round of the
// same seq never collide, and both sides of a chunk RPC filter their
// own membership view the same way.
enum CollScope : uint32_t {
    SCOPE_GLOBAL = 0,      // every live member (the flat collectives)
    SCOPE_ZONE = 1,        // members of MY zone only (hier phase 1)
    SCOPE_LEADERS = 2,     // lowest-key member of each zone (phase 2)
    SCOPE_ZONE_BCAST = 3,  // my zone again, phase-3 key namespace
};

enum CollKind : uint32_t {
    // Ring push: payload chunk reduced (uint32 wraparound sum) into the
    // receiver's round buffer at the ABSOLUTE offset; steps >= nranks-1
    // are the all-gather phase (copy, not reduce).
    COLL_ALLREDUCE = 1,
    // Pull: no payload; the reply carries bytes [offset, offset+len) of
    // the server's own input block (offset is block-relative).
    COLL_ALLGATHER = 2,
    // Pairwise exchange (lower rank initiates): payload = the caller's
    // block-for-me chunk (applied at buf[src_rank*block + offset]); the
    // reply carries my block-for-the-caller chunk from the same offsets.
    COLL_ALLTOALL = 3,
    // Serial baseline, deliberately unpipelined and inline: whole
    // payload pushed to rank 0 in one call...
    COLL_SERIAL_PUSH = 4,
    // ...and the whole reduced result pulled back in one call (the
    // reply waits for the root's reduction to complete).
    COLL_SERIAL_PULL = 5,
    // Pull-based broadcast from rank 0 (ISSUE 14, hier phase 3):
    // non-roots pull chunks [offset, offset+len) of the root's buffer;
    // the root completes once every member pulled every chunk.
    COLL_BCAST = 6,
};

// Membership probe: the host tool owns link liveness (mesh_node's peer
// links; tests use static lists). GetMembers returns every CURRENTLY
// live member including self; the engine sorts by `key` to assign
// ranks, so all nodes probing the same live set agree on numbering.
// Keys must be unique and stable per node (mesh: the listen port).
// Channels ride shared_ptr because the mesh replaces a peer's channel
// on reconnect — a round holds the channels it was formed over alive
// until its in-flight chunk calls settle.
class CollectiveMembership {
public:
    struct Member {
        uint64_t key = 0;
        std::shared_ptr<google::protobuf::RpcChannel> chan;  // null = self
        bool self = false;
        // Locality zone (pod) of the member; "" = zoneless. Drives the
        // SCOPE_ZONE/SCOPE_LEADERS membership filters of hierarchical
        // collectives (ISSUE 14). Same-zone members should be reachable
        // over the fast intra-pod tier, cross-zone ones over dcn.
        std::string zone;
    };
    virtual ~CollectiveMembership() = default;
    virtual void GetMembers(std::vector<Member>* out) = 0;
};

// Payload-proto bridge: builds/reads the host's chunk request/response
// messages (benchpb.CollChunk/CollAck in the mesh tools). Must be
// thread-safe; messages returned by New* are owned by the engine call.
class CollectiveCodec {
public:
    virtual ~CollectiveCodec() = default;
    virtual const google::protobuf::MethodDescriptor* method() const = 0;
    virtual google::protobuf::Message* NewRequest(const CollWire& w)
        const = 0;
    virtual google::protobuf::Message* NewResponse() const = 0;
};

struct CollectiveOptions {
    // Pipeline chunk size; slab-class sized so chunk buffers recycle
    // through the per-thread slab caches (ISSUE 9c).
    size_t chunk_bytes = 256 << 10;
    // Per-chunk RPC deadline and channel-funnel retries.
    int64_t step_timeout_ms = 2000;
    int max_chunk_retries = 3;
    // Whole-round attempt budget: a failed attempt re-probes membership
    // and either re-forms (membership changed) or retries (transient).
    // Deliberately generous — op_timeout_ms is the real bound; attempts
    // into a dead-but-not-yet-noticed peer fail in microseconds (the
    // peer-death lease reclamation turns them into instant
    // TERR_STALE_EPOCH), and the collective must survive that churn
    // until the membership view converges.
    int max_attempts = 100;
    int64_t attempt_timeout_ms = 6000;
    int64_t op_timeout_ms = 30000;
    // How long HandleIncoming parks for the local round to catch up
    // before answering retriable TERR_OVERLOAD (bounded additionally by
    // the caller-provided wait budget).
    int64_t handler_wait_ms = 700;
    // Post chunks as one-sided pool descriptors (ineligible buffers /
    // transports fall back inline and are counted).
    bool pool_descriptors = true;
    // Ring all-reduce steps move through the one-sided verb plane
    // (ISSUE 18): one scatter-gather REMOTE_WRITE into the successor's
    // leased window per step + one payload-free doorbell RPC, instead
    // of per-chunk descriptor RPCs. Lane setup failure (grant refused,
    // epoch bump, verb-incapable peer without the emulated seam) falls
    // back to the chunk path and counts
    // rpc_collective_verb_fallbacks.
    bool verbs_lane = false;
};

class CollectiveEngine {
public:
    // Opaque per-round state (defined in collective.cc; public only so
    // the file-local wait predicates can name it).
    struct Round;

    struct Result {
        int error = 0;
        uint32_t nranks = 0;
        uint32_t my_rank = 0;
        uint64_t moved_bytes = 0;  // payload bytes this rank pushed
        int64_t elapsed_us = 0;
        int retries = 0;           // same-membership attempt re-runs
        int reforms = 0;           // membership-changed restarts
        uint64_t desc_fallback_chunks = 0;  // chunks that went inline
        // Verbs lane accounting (ISSUE 18): ring steps that moved as
        // one SGL verb + doorbell, and chunks that fell back to the
        // per-chunk RPC path although verbs_lane was requested.
        uint64_t verb_steps = 0;
        uint64_t verb_fallback_chunks = 0;
        // NCCL-style bus bandwidth of the completed round (also set on
        // the rpc_collective_busbw_mbps{alg} gauge) — computed HERE so
        // drivers and the bench report the same number the same way.
        double busbw_mbps = 0.0;
        std::vector<uint64_t> member_keys;  // membership of the
                                            // completed round, rank order
    };

    // `membership` and `codec` are borrowed and must outlive the engine.
    CollectiveEngine(CollectiveMembership* membership,
                     CollectiveCodec* codec, const CollectiveOptions& opts);
    ~CollectiveEngine();

    // Chunked-pipelined ring all-reduce (uint32 wraparound sum),
    // in-place. Blocks the calling fiber. Returns 0 or a TERR_* code
    // (also in r->error).
    int AllReduce(uint64_t seq, uint32_t* words, size_t nwords, Result* r);

    // Hierarchical all-reduce (ISSUE 14, per the MLPerf pod study
    // arXiv:1909.09756): (1) ring all-reduce INTRA-ZONE over the fast
    // tier, (2) zone leaders (lowest key per zone) exchange their zone
    // sums — plus the zone member lists — over the cross-pod links via
    // a leaders-scoped all-gather, (3) each leader pull-broadcasts the
    // global-minus-zone delta (and the contributing-key union) back
    // through the zone (uint32 wraparound makes zsum + delta exact).
    // Bulk bytes cross the pod boundary exactly once per leader
    // instead of riding every ring step. A phase
    // failure (e.g. the OTHER pod partitions mid-round) re-probes and
    // restarts all phases over the surviving membership — on a
    // fully-partitioned topology the leader exchange degrades to a
    // no-op and the result is the surviving pod's sum. member_keys /
    // nranks of the Result are the keys that actually CONTRIBUTED
    // (union of the leaders' zone lists), so drivers can verify
    // bit-for-bit. busbw lands on rpc_collective_busbw_mbps{alg=
    // "hier_allreduce"}.
    int HierAllReduce(uint64_t seq, uint32_t* words, size_t nwords,
                      Result* r);

    // Pull-based chunked all-gather: contributes `my_bytes` bytes,
    // fills *out with nranks blocks in rank order.
    int AllGather(uint64_t seq, const void* mine, size_t my_bytes,
                  std::string* out, Result* r);

    // Pairwise-exchange all-to-all: `blocks_by_key` maps every possible
    // member key to the block (all equal `block_bytes`) destined for
    // that member; *out receives the blocks the members sent to this
    // rank, in rank order. Keyed by member key (not rank) so a re-form
    // re-selects the right blocks for the surviving membership.
    int AllToAll(uint64_t seq,
                 const std::map<uint64_t, std::string>& blocks_by_key,
                 size_t block_bytes, std::string* out, Result* r);

    // Serial unpipelined baseline (inline fan-in to rank 0 + fan-out):
    // same result contract as AllReduce, measured by the same driver —
    // the denominator of the bench's pipelined-vs-serial ratio.
    int SerialAllReduce(uint64_t seq, uint32_t* words, size_t nwords,
                        Result* r);

    // Server side: apply/serve one incoming chunk. `reply` (may be
    // null for push-only kinds) receives pull/exchange payload bytes in
    // a descriptor-eligible buffer when possible. `wait_budget_us` is
    // the caller's remaining deadline budget: parking for round skew is
    // bounded by min(it, handler_wait_ms), and a non-positive value
    // answers immediately (expired caller). Returns 0 (see *applied:
    // 1 = newly applied, 2 = duplicate) or a TERR_* code the caller
    // maps onto the response (*backoff_ms rides TERR_OVERLOAD).
    int HandleIncoming(const CollWire& w, const char* data, size_t len,
                       IOBuf* reply, int64_t wait_budget_us,
                       int64_t* backoff_ms, int* applied);

    // Unblock every parked driver and handler (server teardown).
    void Shutdown();

    // Flip the verbs lane between rounds (the mesh driver's
    // allreduce_verbs / allreduce_chunks A/B switch). NOT synchronized
    // against in-flight driver calls — call only from the (single)
    // driving fiber between ops.
    void set_verbs_lane(bool v) { opts_.verbs_lane = v; }

    // Highest round seq seen on the wire (any kind). A node that
    // (re)joins a running mesh adopts this as its next round instead of
    // restarting from 1 — the rejoin path of the continuous-traffic
    // soak (peers mid-round N would otherwise wait on a node driving
    // round 1 and vice versa).
    uint64_t ObservedSeq() const {
        return observed_seq_.load(std::memory_order_relaxed);
    }

    // Touch the rpc_collective_* counters + per-algorithm
    // rpc_collective_busbw_mbps{alg=...} family so they exist 0-valued
    // from the first /metrics scrape.
    static void ExposeVars();

    // Deterministic payload + integrity helpers shared by the drivers
    // and the cross-language validation (tests/test_collectives.py
    // re-derives both in numpy/JAX):
    //   word(i) = 0x9E3779B1*seq + 0x85EBCA77*key + 0xC2B2AE35*i  (u32)
    static void FillDeterministic(uint64_t seq, uint64_t key, uint32_t* w,
                                  size_t n);
    // Adler-style order-sensitive checksum over uint32 words, identical
    // (incl. uint32 cumsum wraparound) to
    // brpc_tpu.parallel.collective_echo._adler_frame_checksum.
    static uint32_t Checksum(const uint32_t* w, size_t n);

private:
    struct SendCtx;
    friend struct SendCtx;
    class FanMapper;
    friend class FanMapper;

    // Probe + sort the live membership filtered by `scope`; false when
    // a collective is not currently possible (self missing; for the
    // GLOBAL scope also fewer than 2 live members — scoped phases may
    // legitimately be single-member and degrade to local no-ops).
    bool ProbeMembers(uint32_t scope,
                      std::vector<CollectiveMembership::Member>* members,
                      uint32_t* my_rank, uint64_t* hash);
    std::shared_ptr<Round> GetOrCreateRound(
        uint32_t rkind, uint32_t scope, uint64_t seq,
        std::vector<CollectiveMembership::Member>&& members,
        uint32_t my_rank, uint64_t hash, const std::string& input,
        size_t base_bytes, Result* r);
    // Scoped ring all-reduce / leaders all-gather / zone broadcast: the
    // phase bodies of HierAllReduce (no busbw/op accounting of their
    // own).
    int ScopedAllReduce(uint32_t scope, uint64_t seq, uint32_t* words,
                        size_t nwords, Result* r);
    // The shared all-gather driver body: AllGather runs it
    // SCOPE_GLOBAL; hier phase 2 runs it SCOPE_LEADERS (where a
    // single-member scope degrades to out = input).
    int ScopedAllGather(uint32_t scope, uint64_t seq,
                        const std::string& input, std::string* out,
                        Result* r);
    // Chunked pull broadcast of `nbytes` within `scope`: the caller
    // that is the scope's rank 0 passes `leader` = true and the
    // payload in `bytes`; everyone else receives into `bytes`. A
    // leadership view that disagrees with the probe fails retriable.
    int ScopedBroadcast(uint32_t scope, uint64_t seq, char* bytes,
                        size_t nbytes, bool leader, Result* r);
    int RunBcastAttempt(const std::shared_ptr<Round>& round,
                        int64_t attempt_deadline_us, Result* r);
    void FinishRound(const std::shared_ptr<Round>& round, int err);
    int RunRingAttempt(const std::shared_ptr<Round>& round,
                       int64_t attempt_deadline_us, Result* r);
    // One verbs-backed ring step (ISSUE 18): wait the step's reduce
    // dependencies, post one scatter-gather REMOTE_WRITE of the whole
    // shard into the successor's leased window, park on the doorbell
    // CQ, then fire the payload-free apply RPC. Returns 0 on success,
    // a positive TERR_* that fails the attempt (stale attempt /
    // deadline), or -1 meaning "lane unusable — resend this step
    // through the per-chunk path" (the handler's key dedupe makes the
    // overlap safe). `cq` and `lane` are the attempt's stack lane; the
    // step never returns with its post still pending.
    int VerbsRingStep(const std::shared_ptr<Round>& round, uint64_t attempt,
                      uint32_t step, uint64_t w0, uint64_t wn,
                      uint32_t nchunks, uint64_t chunk_words,
                      verbs::CompletionQueue* cq,
                      const verbs::RemoteWindow& lane,
                      int64_t attempt_deadline_us, Result* r);
    int RunFanoutAttempt(const std::shared_ptr<Round>& round, uint32_t kind,
                         int64_t attempt_deadline_us, Result* r);
    int RunSerialAttempt(const std::shared_ptr<Round>& round,
                         int64_t attempt_deadline_us, Result* r);
    void SendChunkAsync(const std::shared_ptr<Round>& round,
                        uint64_t attempt, const CollWire& w, Result* r);
    static int WaitRound(Round* rd, uint64_t attempt, int64_t deadline_us,
                         bool (*pred)(Round*, void*), void* arg);

    CollectiveMembership* membership_;
    CollectiveCodec* codec_;
    CollectiveOptions opts_;

    FiberMutex mu_;  // rounds_ + watermarks + shutdown flag
    FiberCond cv_;   // signaled on round creation / shutdown
    std::map<uint64_t, std::shared_ptr<Round>> rounds_;
    // Highest completed seq per (kind, scope) round family — scoped
    // hierarchical phases never satisfy (or GC) a flat round's
    // straggler queries and vice versa.
    std::map<uint32_t, uint64_t> completed_seq_;
    std::atomic<uint64_t> observed_seq_{0};
    bool shutdown_ = false;
};

}  // namespace tpurpc
